"""OpenAI-compatible HTTP server over the continuous-batching engine.

Equivalent of the reference's FastAPI server (reference
vllm/entrypoints/openai/api_server.py:229-425: /v1/completions and
/v1/chat/completions with SSE streaming, client-disconnect abort) — built on
the stdlib ThreadingHTTPServer so it runs with zero extra dependencies
(FastAPI/uvicorn are not in the image; the engine below is framework-
agnostic regardless).

Endpoints: GET /v1/models, POST /v1/completions, POST /v1/chat/completions
(stream=true -> text/event-stream chunks, OpenAI wire format), and
POST /v1/embeddings when constructed with an embedder (BertEmbedder).

Observability endpoints (bigdl_tpu/observability/):
- GET /metrics — Prometheus text exposition of the engine's registry
- GET /v1/stats — JSON engine snapshot (slots, queues, metric
  summaries, recent request spans, jit compile table)
- GET /v1/memory — HBM memory snapshot (ledger static report, live
  device memory_stats when the backend has them, budget/headroom math
  and the engine's admission-deferral accounting)
- GET /v1/debug/dump — on-demand postmortem JSON (flight-recorder
  tail, span tail, metrics snapshot, compile table, config + env
  fingerprint); the same document the engine writes to
  $BIGDL_TPU_POSTMORTEM_DIR on step exceptions, stall-guard trips,
  and (via the CLI's signal hooks) SIGTERM/SIGINT
- GET /v1/internal/spans?trace_id= — completed distributed-trace spans
  for one trace (observability/disttrace.py), stamped with this
  replica's wall clock; the router's GET /v1/trace/{id} fan-out target
- POST /v1/profiler/start {"log_dir": ...} / POST /v1/profiler/stop —
  on-demand jax.profiler device trace against the live server
  (TensorBoard/Perfetto; wraps utils/profiling.start_profiler)
- GET /v1/profiler/status — whether a capture is running, and where
- GET /v1/slo — per-replica SLO state: resolved spec, burn rates per
  (qos, objective, window), active alerts (observability/slo.py)
- GET /v1/usage — per-tenant usage rollup: totals + current token
  burn from the usage ledger (observability/usage.py)

Tokenization: pass a HF tokenizer (transformers.AutoTokenizer) at
construction; prompts may also be raw token-id lists, in which case
completions return token ids (useful for tests and token-level clients).
"""

from __future__ import annotations

import base64
import dataclasses
import hashlib
import json
import os
import select
import socket
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, List, Optional

import numpy as np

from bigdl_tpu.observability.compile_watch import compiles_in_progress
from bigdl_tpu.observability.disttrace import (make_traceparent,
                                               new_span_id,
                                               parse_traceparent)
from bigdl_tpu.serving.engine import (EngineDraining, LLMEngine,
                                      SamplingParams)
from bigdl_tpu.serving.overload import RequestShed
from bigdl_tpu.serving.wire import (REJECT_REASONS, WireError,
                                    corrupt_frame, frame_payload,
                                    is_framed, unframe_payload)

#: engine finish reasons that map to HTTP 504 (the request ran out of
#: time: its own deadline, or the server's drain window closed on it)
_TIMEOUT_REASONS = ("deadline", "drain_timeout")

#: replica roles in the disaggregated fleet (serving/router.py,
#: serving/autoscaler.py): a ``prefill`` replica runs chunked prefill
#: and ships the prompt's quantized KV snapshot to a ``decode`` replica
#: over POST /v1/internal/kv_handoff; ``mixed`` does both locally
REPLICA_ROLES = ("mixed", "prefill", "decode")


def resolve_replica_role(value: Optional[str] = None) -> str:
    """$BIGDL_TPU_REPLICA_ROLE (default "mixed"); raises ValueError on
    an unknown role."""
    v = value if value is not None else os.environ.get(
        "BIGDL_TPU_REPLICA_ROLE", "mixed")
    v = (v or "mixed").strip().lower()
    if v not in REPLICA_ROLES:
        raise ValueError(f"replica role {v!r} not one of "
                         f"{', '.join(REPLICA_ROLES)}")
    return v


def resolve_handoff_timeout_ms(value: Optional[float] = None) -> float:
    """$BIGDL_TPU_HANDOFF_TIMEOUT_MS (default 5000): per-attempt wall
    budget for one KV-handoff POST to a decode replica."""
    if value is not None:
        v = float(value)
    else:
        v = float(os.environ.get("BIGDL_TPU_HANDOFF_TIMEOUT_MS", "5000"))
    if v <= 0:
        raise ValueError(f"handoff timeout {v} ms must be > 0")
    return v


def resolve_handoff_retries(value: Optional[int] = None) -> int:
    """$BIGDL_TPU_HANDOFF_RETRIES (default 2): transfer attempts beyond
    the first before falling back to local mixed decode."""
    if value is not None:
        v = int(value)
    else:
        v = int(os.environ.get("BIGDL_TPU_HANDOFF_RETRIES", "2"))
    if v < 0:
        raise ValueError(f"handoff retries {v} must be >= 0")
    return v


#: tristate values for $BIGDL_TPU_LIVE_MIGRATION ("auto" == enabled:
#: the knob exists so operators can hard-disable migration fleetwide,
#: and so a future build can gate "auto" on measured link bandwidth
#: without breaking explicit opt-ins)
LIVE_MIGRATION_MODES = ("auto", "on", "off")


def resolve_live_migration(value: Optional[str] = None) -> str:
    """$BIGDL_TPU_LIVE_MIGRATION (default "auto"): whether this replica
    accepts /v1/internal/migrate_in intakes and runs migrate-out on
    planned disruptions. Raises ValueError on an unknown mode."""
    v = value if value is not None else os.environ.get(
        "BIGDL_TPU_LIVE_MIGRATION", "auto")
    v = (v or "auto").strip().lower()
    if v not in LIVE_MIGRATION_MODES:
        raise ValueError(f"live migration mode {v!r} not one of "
                         f"{', '.join(LIVE_MIGRATION_MODES)}")
    return v


def resolve_migrate_timeout_ms(value: Optional[float] = None) -> float:
    """$BIGDL_TPU_MIGRATE_TIMEOUT_MS (default 5000): wall budget for
    one sequence export AND for each migrate_in POST attempt."""
    if value is not None:
        v = float(value)
    else:
        v = float(os.environ.get("BIGDL_TPU_MIGRATE_TIMEOUT_MS", "5000"))
    if v <= 0:
        raise ValueError(f"migrate timeout {v} ms must be > 0")
    return v


def resolve_migrate_max_bytes(value: Optional[int] = None) -> int:
    """$BIGDL_TPU_MIGRATE_MAX_BYTES (default 64 MiB): largest framed
    migration payload either side will move — a sender whose export
    exceeds it resumes locally, a receiver rejects oversized intakes
    with reason "too_large" before reading the body."""
    if value is not None:
        v = int(value)
    else:
        v = int(os.environ.get("BIGDL_TPU_MIGRATE_MAX_BYTES",
                               str(64 << 20)))
    if v <= 0:
        raise ValueError(f"migrate max bytes {v} must be > 0")
    return v


def _np_dtype(name: str):
    """np.dtype by name, falling back to the ml_dtypes extension types
    (bfloat16, float8_e5m2, ...) the KV planes are stored in."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def planes_to_wire(entry) -> List[dict]:
    """KV snapshot planes -> JSON-able wire form. Each plane rides as
    raw bytes (base64) + dtype/shape, so int8/int4-quantized planes
    ship at their quantized width (~1/4 of bf16 for int4+scales) —
    exactly the prefix-cache entry layout, (k, v[, k_scale, v_scale])."""
    out = []
    for p in entry:
        p = np.ascontiguousarray(p)
        out.append({"dtype": p.dtype.name, "shape": list(p.shape),
                    "data": base64.b64encode(p.tobytes()).decode("ascii")})
    return out


def planes_from_wire(objs: List[dict]):
    """Inverse of planes_to_wire; raises ValueError on a malformed or
    truncated plane."""
    if not isinstance(objs, list) or not 2 <= len(objs) <= 4:
        raise ValueError("planes must be a list of 2-4 plane objects")
    entry = []
    for o in objs:
        if not isinstance(o, dict):
            raise ValueError("each plane must be an object")
        try:
            dt = _np_dtype(str(o["dtype"]))
            shape = tuple(int(s) for s in o["shape"])
            raw = base64.b64decode(o["data"])
        except (KeyError, TypeError, ValueError, AttributeError) as e:
            raise ValueError(f"malformed KV plane: {e}") from None
        arr = np.frombuffer(raw, dtype=dt)
        if arr.size != int(np.prod(shape)):
            raise ValueError(
                f"plane byte count {arr.size} != shape {shape}")
        entry.append(arr.reshape(shape).copy())
    return tuple(entry)


def _socket_disconnected(sock) -> bool:
    """True when the client peer has closed its end (readable socket
    whose MSG_PEEK returns EOF). Used to cancel NON-streaming requests
    — the streaming path learns the same thing from its write failing."""
    try:
        r, _, _ = select.select([sock], [], [], 0)
        if not r:
            return False
        return sock.recv(1, socket.MSG_PEEK | socket.MSG_DONTWAIT) == b""
    except (BlockingIOError, InterruptedError):
        return False
    except OSError:
        return True


class _EngineLoop:
    """Background thread driving engine.step() (the reference's asyncio
    engine loop, async_llm_engine.py, minus asyncio)."""

    def __init__(self, engine: LLMEngine):
        self.engine = engine
        self._wake = threading.Event()
        self._stop = False
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop:
            try:
                did = self.engine.step()
            except Exception:   # a dead loop thread would hang every client
                import traceback

                traceback.print_exc()
                did = False
            if not did:
                self._wake.wait(timeout=0.01)
                self._wake.clear()

    def notify(self):
        self._wake.set()

    def stop(self):
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=2)


def _jsonable(obj):
    """Round-trip through JSON with repr() fallback — the postmortem
    dict may carry values json.dumps can't encode natively (the same
    default=repr the on-disk dump writer uses)."""
    return json.loads(json.dumps(obj, default=repr))


def _chat_to_prompt(messages: List[dict], tokenizer) -> Any:
    if tokenizer is not None and hasattr(tokenizer, "apply_chat_template"):
        try:
            return tokenizer.apply_chat_template(
                messages, tokenize=True, add_generation_prompt=True)
        except Exception:
            pass
    text = ""
    for m in messages:
        text += f"{m.get('role', 'user')}: {m.get('content', '')}\n"
    text += "assistant:"
    return text


class _IncrementalDetok:
    """vllm-style incremental detokenization (reference: vllm's
    Detokenizer; replaces the accumulated-decode diff flagged in r4
    advice). Each delta is computed from a sliding token window
    (`decode(ids[prefix:])` minus `decode(ids[prefix:read])`), so the
    stream is append-only BY CONSTRUCTION even when a full re-decode
    would retroactively rewrite earlier text (sentencepiece boundary
    cleanup, clean_up_tokenization_spaces), and total work is O(n) in
    generation length rather than O(n^2)."""

    def __init__(self, decode_fn):
        self._decode = decode_fn
        self.ids: list = []
        self.text = ""       # stable decoded text (what stop-scan sees)
        self._prefix = 0     # window start (token index)
        self._read = 0       # tokens already folded into .text

    def push(self, new_ids) -> str:
        self.ids.extend(new_ids)
        prefix_text = self._decode(self.ids[self._prefix:self._read])
        new_text = self._decode(self.ids[self._prefix:])
        if new_text.endswith("�"):
            return ""        # incomplete multi-byte char: hold the tail
        if len(new_text) <= len(prefix_text):
            return ""        # window shrank (cleanup): wait for more
        delta = new_text[len(prefix_text):]
        self._prefix = self._read
        self._read = len(self.ids)
        self.text += delta
        return delta

    def flush(self) -> str:
        """Final drain: emit the held-back tail even if it ends in
        U+FFFD — a completion may genuinely end mid-sequence, and the
        streamed text must equal the non-streaming response."""
        prefix_text = self._decode(self.ids[self._prefix:self._read])
        new_text = self._decode(self.ids[self._prefix:])
        delta = new_text[len(prefix_text):]
        self._prefix = self._read = len(self.ids)
        self.text += delta
        return delta


class OpenAIServer:
    def __init__(self, engine: LLMEngine, tokenizer=None,
                 model_name: str = "bigdl-tpu-model",
                 embedder=None, embedder_tokenizer=None,
                 wedge_sec: float = 10.0,
                 role: Optional[str] = None,
                 handoff_timeout_ms: Optional[float] = None,
                 handoff_retries: Optional[int] = None,
                 migrate_timeout_ms: Optional[float] = None,
                 migrate_max_bytes: Optional[int] = None,
                 live_migration: Optional[str] = None):
        self.engine = engine
        self.tokenizer = tokenizer
        self.model_name = model_name
        # disaggregated-serving role: "prefill" replicas ship each
        # non-streaming request's KV snapshot to a decode replica
        # (X-Handoff-Targets, set by the router) instead of decoding
        # locally; "decode" replicas accept those snapshots on
        # /v1/internal/kv_handoff; "mixed" (the default) does both.
        # None resolves $BIGDL_TPU_REPLICA_ROLE.
        self.role = resolve_replica_role(role)
        self._handoff_timeout_ms = resolve_handoff_timeout_ms(
            handoff_timeout_ms)
        self._handoff_retries = resolve_handoff_retries(handoff_retries)
        # handoff accounting, shared between HTTP handler threads and
        # /v1/stats readers — every touch goes through _handoff_lock
        self._handoff_lock = threading.Lock()
        self._handoff_counts = {"sends": 0, "accepted": 0, "retries": 0,
                                "fallbacks": 0, "dropped": 0}
        self._handoff_attempts = 0
        # live-migration knobs (serving/wire.py framing + engine
        # export/import): "off" disables both the migrate_in intake and
        # every migrate-out path — callers then fall back to
        # drain-and-replay, exactly the pre-migration behavior
        self.live_migration = resolve_live_migration(live_migration)
        self._migrate_timeout_ms = resolve_migrate_timeout_ms(
            migrate_timeout_ms)
        self._migrate_max_bytes = resolve_migrate_max_bytes(
            migrate_max_bytes)
        # rid -> {"resume_id", "target"} set by the migrate-out sender
        # at commit, popped by the HTTP handler when it emits the
        # client-facing resume marker (lock: _handoff_lock)
        self._migrated_info: dict = {}
        # wire-frame rejects at receive (magic/version/length/crc/json/
        # too_large), mirrored into /v1/stats for the router's deltas
        self._reject_counts = {r: 0 for r in REJECT_REASONS}
        self._m_rejects = engine.registry.counter(
            "bigdl_tpu_handoff_rejects_total",
            "internal wire payloads rejected at receive, by "
            "frame-validation reason",
            ["reason"])
        for r in REJECT_REASONS:
            self._m_rejects.labels(r)
        self._m_handoff = {
            key: engine.registry.counter(
                f"bigdl_tpu_handoff_{key}_total", desc)
            for key, desc in (
                ("sends", "KV handoffs delivered to a decode replica."),
                ("accepted", "KV handoffs accepted from a prefill "
                             "replica."),
                ("retries", "KV handoff attempts that failed and were "
                            "retried."),
                ("fallbacks", "KV handoffs abandoned after retries; "
                              "request decoded locally."),
                ("dropped", "KV handoff attempts dropped by the "
                            "handoff_drop chaos fault."),
            )}
        # a traced handoff whose decode target never echoed its child
        # span id (X-Trace-Span): the decode leg of the timeline is
        # missing — the span-propagation analog of a lost transfer
        self._m_span_orphans = engine.registry.counter(
            "bigdl_tpu_handoff_span_orphans_total",
            "traced KV handoffs whose decode target never reported "
            "its child span")
        # /health liveness: with unfinished work and no step() entered
        # for this long, the step loop is wedged (hung transfer,
        # replica_hang fault) — report 503 so a supervisor (the
        # serving router, k8s) kills and replaces this replica instead
        # of routing into a black hole
        self.wedge_sec = wedge_sec
        # client-disconnect cancellations by path: the streaming leg
        # learns about a dead client from its SSE write failing, the
        # non-streaming leg from the MSG_PEEK poll
        self._cancelled = engine.registry.counter(
            "bigdl_tpu_requests_cancelled_total",
            "requests aborted because the client disconnected",
            ["path"])
        # optional /v1/embeddings backend: a BertEmbedder (transformers/
        # embedder.py) served next to the LLM — the reference serves
        # embeddings through its langchain wrapper and FastChat worker;
        # here they ride the same OpenAI-compatible server
        self.embedder = embedder
        self.embedder_tokenizer = embedder_tokenizer
        self.loop = _EngineLoop(engine)
        self._httpd: Optional[ThreadingHTTPServer] = None

    # -- request handling ---------------------------------------------------

    def _encode(self, prompt) -> List[int]:
        if isinstance(prompt, list) and prompt and isinstance(prompt[0], int):
            return list(prompt)
        if self.tokenizer is None:
            raise ValueError("string prompts need a tokenizer; pass token "
                             "ids or construct the server with one")
        return list(self.tokenizer(prompt)["input_ids"])

    def _decode_text(self, ids: List[int]) -> str:
        if self.tokenizer is None:
            # space-joined, not JSON: streaming diffs the ACCUMULATED
            # decode, so the fallback text must be append-only as ids
            # grow (a JSON list rewrites its closing bracket)
            return " ".join(str(i) for i in ids)
        return self.tokenizer.decode(ids, skip_special_tokens=True)

    def _params(self, body: dict) -> SamplingParams:
        lp = body.get("logprobs")
        if lp is True:                      # chat-style boolean form
            lp = int(body.get("top_logprobs", 0))
        return SamplingParams(
            max_tokens=int(body.get("max_tokens", 128)),
            temperature=float(body.get("temperature", 0.0)),
            top_k=int(body.get("top_k", 0)),
            top_p=float(body.get("top_p", 1.0)),
            repetition_penalty=float(body.get("repetition_penalty", 1.0)),
            presence_penalty=float(body.get("presence_penalty", 0.0)),
            frequency_penalty=float(body.get("frequency_penalty", 0.0)),
            n=int(body.get("n", 1)),
            best_of=(int(body["best_of"]) if body.get("best_of")
                     else None),
            logprobs=(int(lp) if lp is not None and lp is not False
                      else None),
            seed=(int(body["seed"]) if body.get("seed") is not None
                  else None),
            max_time_ms=(float(body["max_time_ms"])
                         if body.get("max_time_ms") is not None
                         else None),
            ignore_eos=bool(body.get("ignore_eos", False)),
            qos=(str(body["qos"]) if body.get("qos") else None),
        )

    @staticmethod
    def _tenant_of(headers) -> str:
        """Tenant identity for fair queuing / rate limits: explicit
        X-Tenant-Id header, else a stable hash of the API key
        (Authorization header), else the shared 'default' bucket."""
        tid = headers.get("X-Tenant-Id")
        if tid:
            return str(tid)[:64]
        auth = headers.get("Authorization")
        if auth:
            return "key-" + hashlib.sha256(
                auth.encode("utf-8", "replace")).hexdigest()[:12]
        return "default"

    def _run_request(self, token_ids, params, stream_cb=None,
                     stop_strs=(), disconnect_check=None,
                     cancel_cb=None, rid=None, trace=None,
                     seed_ids=None):
        """Returns (rid, {index: ids}, {index: logprob entries},
        {index: finish_reason}, {index: final text}, {index: error}).

        stream_cb(text_delta, index) when set — deltas come from the
        ACCUMULATED decode (robust to multi-token characters), with a
        holdback of len(longest stop)-1 chars so a stop string never
        leaks into the stream. `stop_strs` are the OpenAI `stop`
        sequences (reference vllm SamplingParams.stop): output truncates
        at the first match; a single-choice request aborts early.

        `disconnect_check()` is polled while waiting (both paths); when
        it reports the client gone — or a streaming SSE write fails —
        the request is aborted: the engine frees the slot AND drops the
        prompt's prefix-cache entry, so a hung-up client stops costing
        HBM immediately. `cancel_cb()` fires exactly once on such a
        client-driven cancellation (the counter hook). When `rid` is
        given the request was already added to the engine (the HTTP
        layer admits BEFORE committing stream headers, so an admission
        shed can still be a clean 429/503); otherwise add here."""
        if rid is None:
            rid = f"cmpl-{uuid.uuid4().hex[:16]}"
            self.engine.add_request(rid, token_ids, params, trace=trace)
            self.loop.notify()
        out_ids: dict = {}
        out_lps: dict = {}
        reasons: dict = {}
        errors: dict = {}     # index -> structured engine error
        texts: dict = {}      # index -> full decoded (possibly cut) text
        emitted: dict = {}    # index -> chars already streamed
        scanned: dict = {}    # index -> chars already stop-scanned
        detoks: dict = {}     # index -> _IncrementalDetok
        stopped: set = set()
        hold = max((len(s) for s in stop_strs), default=0)
        n_choices = max(params.n, 1)
        # streaming and stop-scanning share one incremental detokenizer
        # per choice (O(n) total, append-only deltas); plain stop-free
        # requests decode once at the end
        live_decode = bool(stop_strs) or stream_cb is not None
        cancelled = [False]          # cancel_cb fired (at most once)
        if seed_ids:
            # a resumed (migrated-in) request: the engine only emits
            # tokens generated since the claim, but the client is owed
            # the WHOLE completion and decode(a + b) is not
            # decode(a) + decode(b) for real tokenizers — seed the
            # accumulated state with the pre-migration ids and mark
            # their text already emitted and already stop-scanned (the
            # source replica streamed it before handing off), so the
            # continuation's first delta carries the boundary
            # separator and the buffered response detokenizes pre +
            # post together
            out_ids[0] = list(seed_ids)
            pre_text = self._decode_text(list(seed_ids))
            emitted[0] = len(pre_text)
            scanned[0] = len(pre_text)
            if live_decode:
                det = detoks[0] = _IncrementalDetok(self._decode_text)
                det.push(list(seed_ids))
                texts[0] = det.text

        def cancel_once():
            if not cancelled[0]:
                cancelled[0] = True
                if cancel_cb is not None:
                    try:
                        cancel_cb()
                    except Exception:
                        pass         # accounting must not alter the abort

        def emit(idx, upto):
            nonlocal stream_cb
            if stream_cb is None:
                return
            full = texts[idx]
            start = emitted.get(idx, 0)
            upto = min(upto, len(full))
            if upto > start:
                try:
                    stream_cb(full[start:upto], idx)
                    emitted[idx] = upto
                except OSError:
                    # client went away mid-stream: free the slot (the
                    # abort also drops the prompt's prefix-cache
                    # entry), then keep draining until the engine
                    # emits the abort-finish (reference
                    # api_server.py:371 disconnect -> abort)
                    cancel_once()
                    self.engine.abort_request(rid)
                    self.loop.notify()
                    stream_cb = None

        def scan_stop(idx):
            """Scan the unseen tail of the stable text for the earliest
            stop string; returns the cut position or -1."""
            full = texts[idx]
            scan0 = max(0, scanned.get(idx, 0) - max(hold - 1, 0))
            cut = -1
            for s in stop_strs:
                p = full.find(s, scan0)
                if p != -1 and (cut == -1 or p < cut):
                    cut = p
            scanned[idx] = len(full)
            return cut

        def apply_stop(idx, cut, batch_len):
            texts[idx] = texts[idx][:cut]
            stopped.add(idx)
            reasons[idx] = "stop"
            emit(idx, cut)
            # drop the tokens whose text fell past the cut (usage must
            # bill the VISIBLE completion): walk back this batch's
            # tokens while the stop still matches without them
            ids = out_ids[idx]
            keep = len(ids)
            lo = keep - batch_len
            while keep > lo:
                shorter = self._decode_text(ids[:keep - 1])
                if any(s in shorter for s in stop_strs):
                    keep -= 1
                else:
                    break
            del ids[keep:]
            if idx in out_lps:
                del out_lps[idx][keep:]
            if stopped >= set(range(n_choices)):
                # every choice done: stop generating
                self.engine.abort_request(rid)
                self.loop.notify()

        done = False
        aborted = False
        next_conn_check = time.time() + 0.25
        while not done:
            if disconnect_check is not None and not aborted \
                    and time.time() >= next_conn_check:
                next_conn_check = time.time() + 0.25
                try:
                    gone = disconnect_check()
                except Exception:
                    gone = True
                if gone:
                    # client hung up mid-generation: cancel, then keep
                    # draining until the engine emits the abort-finish
                    aborted = True
                    cancel_once()
                    self.engine.abort_request(rid)
                    self.loop.notify()
            outs = self.engine.get_outputs(rid)
            if not outs:
                time.sleep(0.002)
                continue
            for o in outs:
                idx = o.index
                if idx not in stopped:
                    # stopped choices freeze: ids/logprobs past the stop
                    # would inflate usage and desync from the cut text
                    out_ids.setdefault(idx, []).extend(o.new_token_ids)
                    if o.logprobs:
                        out_lps.setdefault(idx, []).extend(o.logprobs)
                if live_decode and o.new_token_ids and idx not in stopped:
                    det = detoks.get(idx)
                    if det is None:
                        det = detoks[idx] = _IncrementalDetok(
                            self._decode_text)
                    det.push(o.new_token_ids)
                    texts[idx] = det.text
                    cut = scan_stop(idx) if stop_strs else -1
                    if cut != -1:
                        apply_stop(idx, cut, len(o.new_token_ids))
                    else:
                        emit(idx, len(det.text) - hold + 1
                             if hold else len(det.text))
                if o.finish_reason is not None:
                    reasons.setdefault(idx, o.finish_reason)
                if o.error is not None:
                    errors.setdefault(idx, o.error)
                if o.finished:
                    reasons.setdefault(idx, o.finish_reason or "stop")
                    done = True
        for idx, det in detoks.items():
            if idx in stopped:
                continue
            det.flush()                      # drain the held-back tail
            texts[idx] = det.text
            cut = scan_stop(idx) if stop_strs else -1
            if cut != -1:
                apply_stop(idx, cut, len(det.ids))
        for idx in list(texts):
            emit(idx, len(texts[idx]))       # flush the holdback
        for i in range(n_choices):
            out_ids.setdefault(i, [])
            texts.setdefault(i, self._decode_text(out_ids[i]))
            reasons.setdefault(i, reasons.get(0, "stop"))
        # the synthetic fan-out closer carries no tokens under its own
        # index; drop any empty phantom choice beyond n
        out_ids = {i: v for i, v in out_ids.items() if i < n_choices}
        texts = {i: v for i, v in texts.items() if i < n_choices}
        return rid, out_ids, out_lps, reasons, texts, errors

    # -- KV handoff (prefill side) ------------------------------------------

    def _count_handoff(self, key: str) -> None:
        with self._handoff_lock:
            self._handoff_counts[key] += 1
        self._m_handoff[key].inc()

    def _next_handoff_attempt(self) -> int:
        with self._handoff_lock:
            self._handoff_attempts += 1
            return self._handoff_attempts

    def _count_reject(self, reason: str) -> None:
        with self._handoff_lock:
            self._reject_counts[reason] = \
                self._reject_counts.get(reason, 0) + 1
        self._m_rejects.labels(reason).inc()

    def handoff_snapshot(self) -> dict:
        """The /v1/stats "handoff" block: flat counters the router's
        stats poll turns into per-replica deltas."""
        with self._handoff_lock:
            return dict(self._handoff_counts)

    def rejects_snapshot(self) -> dict:
        """The /v1/stats "wire_rejects" block: framed-payload
        rejections at receive, by reason."""
        with self._handoff_lock:
            return dict(self._reject_counts)

    def _handoff_eligible(self, body: dict, params) -> List[str]:
        """Decode targets for this request, empty when the request must
        decode locally: only a prefill-role replica hands off, only
        non-streaming single-choice requests (the decode replica owns
        the whole token stream), and only when the router named targets
        (X-Handoff-Targets is absent on direct client connections)."""
        if self.role != "prefill" or body.get("stream"):
            return []
        if max(params.n, 1) != 1 or params.best_of is not None:
            return []
        hdr = body.get("_handoff_targets")
        if not hdr:
            return []
        return [t.strip() for t in str(hdr).split(",") if t.strip()]

    def _prefill_and_handoff(self, ids, params, body: dict,
                             targets: List[str],
                             trace=None) -> Optional[dict]:
        """Run chunked prefill locally (a 1-token generation, which
        leaves the prompt's quantized KV snapshot in the prefix cache),
        then ship the snapshot + request to a decode replica and relay
        its completion JSON. Returns None when every attempt failed —
        the caller falls back to local mixed decode, reusing the same
        snapshot as its own prefix seed, so the request is NEVER lost
        to a dead decode target (and the prefill work is not wasted).

        Each attempt gets resolve_handoff_timeout_ms() of wall time;
        failures retry with bounded exponential backoff, rotating
        through `targets`, up to resolve_handoff_retries() retries.
        The handoff_drop chaos fault (robustness/faults.py) is
        consulted per attempt and makes it fail as if the wire dropped
        the transfer."""
        probe = dataclasses.replace(params, max_tokens=1, n=1,
                                    best_of=None, logprobs=None)
        _, _, _, reasons, _, _ = self._run_request(ids, probe,
                                                   trace=trace)
        if any(r in ("error",) + _TIMEOUT_REASONS
               for r in reasons.values()):
            return None          # prefill itself failed: local path decides
        entry = self.engine.export_prefix_snapshot(ids)
        if entry is None:
            return None          # snapshot evicted/disabled: decode locally
        req = {k: v for k, v in body.items()
               if k not in ("stream", "prompt", "messages",
                            "_handoff_targets", "_traceparent")}
        # the transfer claims its own (local) span, but the decode
        # target parents its spans under the span id WE were handed —
        # the router's, the nearest crash-durable ancestor — so a
        # prefill death mid-relay cannot orphan the decode leg of the
        # timeline (body, not header alone — the relay's _completions
        # re-reads it from the staged request)
        handoff_span = new_span_id() if trace is not None else None
        t_handoff0 = time.time()
        hdrs = {"Content-Type": "application/octet-stream",
                "X-Tenant-Id": params.tenant or "default"}
        if trace is not None:
            req["_traceparent"] = make_traceparent(trace[0], trace[1])
            hdrs["traceparent"] = req["_traceparent"]
        # checksummed frame (serving/wire.py): a bit-flipped base64
        # body now dies at the receiver's CRC check as a structured
        # 400 instead of deserializing into garbage KV
        payload = frame_payload({
            "prompt": [int(t) for t in ids],
            "planes": planes_to_wire(entry),
            "request": req,
        })
        import urllib.request

        attempts = self._handoff_retries + 1
        delay = 0.05
        for i in range(attempts):
            target = targets[i % len(targets)]
            step = self._next_handoff_attempt()
            if self.engine.faults.drop_point("handoff", step):
                self._count_handoff("dropped")
            else:
                data = payload
                if self.engine.faults.corrupt_point("handoff", step):
                    data = corrupt_frame(payload)
                try:
                    d = self.engine.faults.net_delay_ms("handoff", step)
                    if d:
                        time.sleep(d / 1000.0)
                    if self.engine.faults.net_dropped("handoff", step):
                        raise OSError(
                            "injected connection reset (net_drop)")
                    r = urllib.request.Request(
                        f"http://{target}/v1/internal/kv_handoff",
                        data=data, method="POST", headers=hdrs)
                    with urllib.request.urlopen(
                            r, timeout=self._handoff_timeout_ms
                            / 1000.0) as resp:
                        if resp.status == 200:
                            out = json.loads(resp.read())
                            self._count_handoff("sends")
                            if trace is not None:
                                if not resp.headers.get("X-Trace-Span"):
                                    # decode target answered but never
                                    # reported its child span: the
                                    # timeline's decode leg is missing
                                    self._m_span_orphans.inc()
                                self.engine.spans.record(
                                    "kv_handoff", trace[0],
                                    span_id=handoff_span,
                                    parent_id=trace[1],
                                    t_start=t_handoff0,
                                    t_end=time.time(),
                                    target=target, attempt=i + 1)
                            return out
                except Exception:
                    pass         # timeout, refused, 5xx, dead target
            if i + 1 < attempts:
                self._count_handoff("retries")
                if trace is not None:
                    self.engine.spans.annotate(
                        trace[0], "handoff_retry",
                        parent_id=handoff_span, attempt=i + 1,
                        target=target)
                time.sleep(delay)
                delay = min(delay * 2, 1.0)
        self._count_handoff("fallbacks")
        self.engine.flight.record(
            "handoff_fallback", targets=list(targets),
            attempts=attempts, prompt_len=len(ids),
            **({"trace_id": trace[0]} if trace is not None else {}))
        if trace is not None:
            # the abandoned transfer still claims its span (failed=True)
            # so retry/fallback annotations parented under it resolve
            self.engine.spans.record(
                "kv_handoff", trace[0], span_id=handoff_span,
                parent_id=trace[1], t_start=t_handoff0,
                t_end=time.time(), failed=True, attempts=attempts)
            self.engine.spans.annotate(
                trace[0], "handoff_fallback", parent_id=handoff_span,
                targets=list(targets), attempts=attempts)
        return None

    # -- live migration (source side) ---------------------------------------

    def _take_migrated_info(self, rid: str) -> dict:
        with self._handoff_lock:
            return self._migrated_info.pop(rid, {})

    def migrate_out(self, targets: List[str], rids=None,
                    max_sequences=None, qos=None) -> dict:
        """Migrate in-flight mid-decode sequences to healthy peers and
        report per-sequence outcomes. The planned-disruption entry
        point: the router calls it (POST /v1/admin/migrate_out) before
        a rolling-restart SIGTERM or an autoscale retirement,
        begin_drain calls it when handed migrate targets, and the
        brownout ladder's level-3 option calls it with qos="batch".
        With live migration off (or no targets) every sequence is
        skipped and callers fall back to drain-and-replay — the
        pre-migration behavior, zero-5xx but not zero-loss."""
        results: List[dict] = []
        summary = {"migrated": 0, "failed": 0, "skipped": 0,
                   "results": results}
        targets = [str(t).strip() for t in (targets or [])
                   if str(t).strip()]
        if self.live_migration == "off" or not targets:
            return summary
        todo = (list(rids) if rids
                else self.engine.active_request_ids(qos=qos))
        if max_sequences is not None:
            todo = todo[:int(max_sequences)]
        for rid in todo:
            res = self._migrate_one(rid, targets)
            results.append(res)
            o = res["outcome"]
            if o == "migrated":
                summary["migrated"] += 1
            elif o == "unexportable":
                summary["skipped"] += 1
            else:
                summary["failed"] += 1
        return summary

    def _migrate_one(self, rid: str, targets: List[str]) -> dict:
        """Export one mid-decode sequence and ship it to the first
        target that acks. Commit (engine.finish_migrated) happens ONLY
        on a 200 carrying the resume_id; every other ending resumes
        the sequence locally from its own exported planes
        (engine.resume_local) — the request is never lost, at worst it
        keeps decoding where it already was. The migration_drop /
        migration_corrupt and net_latency / net_drop chaos kinds
        (robustness/faults.py) hook every attempt."""
        state = self.engine.export_sequence(
            rid, timeout_sec=self._migrate_timeout_ms / 1000.0)
        if state is None:
            # finished, already migrating, or not mid-decode here —
            # nothing was suspended, nothing to undo
            return {"request_id": rid, "outcome": "unexportable"}
        planes = state.pop("planes")
        doc = dict(state, planes=planes_to_wire(planes))
        tr = state.get("trace")
        payload = frame_payload(doc)
        if len(payload) > self._migrate_max_bytes:
            self.engine.resume_local(rid)
            self.loop.notify()
            self.engine.flight.record(
                "migration_too_large", request_id=rid,
                bytes=len(payload), cap=self._migrate_max_bytes)
            return {"request_id": rid, "outcome": "too_large",
                    "bytes": len(payload)}
        import urllib.request

        t0 = time.time()
        span_id = new_span_id() if tr else None
        attempts = self._handoff_retries + 1
        delay = 0.05
        for i in range(attempts):
            target = targets[i % len(targets)]
            step = self._next_handoff_attempt()
            if self.engine.faults.drop_point("migrate_send", step):
                pass             # injected wire loss: no bytes moved
            else:
                data = payload
                if self.engine.faults.corrupt_point("migrate", step):
                    data = corrupt_frame(payload)
                try:
                    d = self.engine.faults.net_delay_ms("migrate", step)
                    if d:
                        time.sleep(d / 1000.0)
                    if self.engine.faults.net_dropped("migrate", step):
                        raise OSError(
                            "injected connection reset (net_drop)")
                    r = urllib.request.Request(
                        f"http://{target}/v1/internal/migrate_in",
                        data=data, method="POST",
                        headers={"Content-Type":
                                 "application/octet-stream"})
                    with urllib.request.urlopen(
                            r, timeout=self._migrate_timeout_ms
                            / 1000.0) as resp:
                        if resp.status == 200:
                            ack = json.loads(resp.read())
                            resume_id = str(ack.get("resume_id")
                                            or state["resume_id"])
                            with self._handoff_lock:
                                self._migrated_info[rid] = {
                                    "resume_id": resume_id,
                                    "target": target}
                            self.engine.finish_migrated(
                                rid, target, resume_id)
                            self.loop.notify()
                            if tr:
                                self.engine.spans.record(
                                    "migrate.out", tr[0],
                                    span_id=span_id, parent_id=tr[1],
                                    t_start=t0, t_end=time.time(),
                                    target=target, attempt=i + 1,
                                    bytes=len(payload))
                            return {"request_id": rid,
                                    "outcome": "migrated",
                                    "target": target,
                                    "resume_id": resume_id,
                                    "attempts": i + 1}
                except Exception:
                    pass         # timeout, refused, 4xx/5xx, dead target
            if i + 1 < attempts:
                time.sleep(delay)
                delay = min(delay * 2, 1.0)
        # every attempt failed: the sequence resumes HERE from its own
        # exported planes — zero tokens lost, zero recompute when the
        # local reseed lands
        self.engine.resume_local(rid)
        self.loop.notify()
        if tr:
            self.engine.spans.record(
                "migrate.out", tr[0], span_id=span_id,
                parent_id=tr[1], t_start=t0, t_end=time.time(),
                failed=True, attempts=attempts)
        return {"request_id": rid, "outcome": "failed",
                "attempts": attempts}

    # -- http ---------------------------------------------------------------

    def make_handler(server):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):   # quiet
                pass

            def _json(self, code: int, obj: dict, headers=()):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                # _trace_headers: response headers set by an outer
                # handler layer (_kv_handoff's X-Trace-Span ack rides
                # on the relayed _completions response)
                for k, v in (tuple(headers)
                             + tuple(getattr(self, "_trace_headers",
                                             ()))):
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _draining_503(self):
                # shedding during drain: tell the client when a fresh
                # replica should be up (reference: k8s preStop drain)
                retry = server.engine.drain_retry_after_sec()
                return self._json(
                    503, {"error": {
                        "message": "server is draining; retry against "
                                   "another replica",
                        "type": "unavailable", "code": 503,
                        "retry_after": retry}},
                    headers=(("Retry-After", str(retry)),))

            def _shed_response(self, e: RequestShed):
                # early load shedding: the overload controller refused
                # admission (bounded queue, rate limit, doomed-work
                # test, or brownout) — 429 for per-tenant limits, 503
                # for server-wide pressure, both with a Retry-After
                # computed from the measured drain rate and ledger
                # headroom so clients back off for the right duration
                retry = int(e.retry_after_sec)
                return self._json(
                    e.http_status, {"error": {
                        "message": f"request shed ({e.reason}): "
                                   f"{e.detail or 'server overloaded'}",
                        "type": ("rate_limited" if e.http_status == 429
                                 else "overloaded"),
                        "code": e.http_status, "reason": e.reason,
                        "qos": e.qos, "tenant": e.tenant,
                        "retry_after": retry}},
                    headers=(("Retry-After", str(retry)),))

            def do_GET(self):
                if self.path == "/v1/models":
                    self._json(200, {"object": "list", "data": [
                        {"id": server.model_name, "object": "model"}]})
                elif self.path in ("/health", "/ping"):
                    # a draining replica reports 503 so load balancers
                    # stop routing to it while in-flight work finishes;
                    # a WEDGED one (work pending, step loop frozen)
                    # reports 503 so a supervisor replaces it — the
                    # process answering HTTP proves nothing about the
                    # engine thread. A stale heartbeat during a jit
                    # compile is the compiler working (first call per
                    # shape bucket legitimately blocks step() for
                    # seconds-to-minutes), not a hang — report busy,
                    # not wedged, or every cold replica gets killed
                    # mid-compile by its supervisor.
                    age = server.engine.step_heartbeat_age()
                    if server.engine.draining:
                        self._json(503, {"status": "draining"})
                    elif server.engine.has_unfinished() \
                            and age > server.wedge_sec:
                        if compiles_in_progress():
                            self._json(200, {"status": "compiling",
                                             "heartbeat_age_sec":
                                             round(age, 3)})
                        else:
                            self._json(503, {"status": "wedged",
                                             "heartbeat_age_sec":
                                             round(age, 3)})
                    else:
                        self._json(200, {"status": "ok"})
                elif self.path == "/metrics":
                    body = server.engine.registry.render().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/v1/stats":
                    snap = server.engine.stats_snapshot()
                    snap["role"] = server.role
                    snap["handoff"] = server.handoff_snapshot()
                    snap["wire_rejects"] = server.rejects_snapshot()
                    snap["live_migration"] = server.live_migration
                    self._json(200, snap)
                elif self.path == "/v1/memory":
                    # ledger static report + live device stats +
                    # headroom math (observability/memory.py)
                    self._json(200, _jsonable(
                        server.engine.memory_snapshot()))
                elif self.path == "/v1/debug/dump":
                    # same document the engine writes to
                    # $BIGDL_TPU_POSTMORTEM_DIR, served live
                    self._json(200, _jsonable(
                        server.engine.postmortem("on_demand")))
                elif self.path == "/v1/perf":
                    # live roofline attribution + sentinel state
                    # (engine.perf_snapshot); the router's
                    # /v1/admin/profiler and /v1/router/stats aggregate
                    # this per replica
                    self._json(200, _jsonable(
                        server.engine.perf_snapshot()))
                elif self.path == "/v1/quality":
                    # quantization-error attribution + live decode
                    # quality + golden-probe NLL + QualitySentinel
                    # state (engine.quality_snapshot); the router's
                    # /v1/router/stats aggregates the compact subset
                    self._json(200, _jsonable(
                        server.engine.quality_snapshot()))
                elif self.path == "/v1/slo":
                    # per-replica SLO state: resolved spec, current
                    # burn rates per (qos, objective, window), active
                    # alerts (observability/slo.py); the router
                    # aggregates this fleet-wide in /v1/router/stats
                    self._json(200, _jsonable(
                        server.engine.slo.snapshot()))
                elif self.path == "/v1/usage":
                    # per-tenant usage rollup (observability/usage.py):
                    # totals + current token burn, reconciling exactly
                    # with bigdl_tpu_tenant_requests_total
                    self._json(200, _jsonable(
                        server.engine.usage.snapshot()))
                elif self.path == "/v1/profiler/status":
                    from bigdl_tpu.utils import profiling

                    self._json(200, profiling.profiler_status())
                elif self.path.startswith("/v1/internal/spans"):
                    # the router's /v1/trace/{id} fan-out target:
                    # completed spans for one trace, stamped with this
                    # replica's wall clock so the router can estimate
                    # and subtract clock skew
                    from urllib.parse import parse_qs, urlparse

                    q = parse_qs(urlparse(self.path).query)
                    tid = (q.get("trace_id") or [None])[0]
                    doc = {"now": time.time(),
                           "service": server.engine.spans.service}
                    if tid:
                        doc["spans"] = \
                            server.engine.spans.spans_for(tid)
                    else:
                        doc["traces"] = \
                            server.engine.spans.recent_traces()
                    self._json(200, doc)
                else:
                    self._json(404, {"error": "not found"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                internal = self.path.startswith("/v1/internal/")
                if self.path == "/v1/internal/migrate_in" \
                        and n > server._migrate_max_bytes:
                    # refuse BEFORE reading the body: an oversized
                    # export must not stall the intake thread
                    server._count_reject("too_large")
                    return self._json(413, {"error": {
                        "message": f"migration payload {n} bytes "
                                   f"exceeds BIGDL_TPU_MIGRATE_MAX_"
                                   f"BYTES={server._migrate_max_bytes}",
                        "type": "bad_wire_frame",
                        "reason": "too_large", "code": 413}})
                raw = self.rfile.read(n) if n else b"{}"
                if internal and is_framed(raw):
                    # checksummed frame (serving/wire.py): a corrupt or
                    # version-skewed payload dies here as a structured
                    # 400 the sender's retry ladder understands
                    try:
                        body = unframe_payload(raw)
                    except WireError as e:
                        server._count_reject(e.reason)
                        return self._json(400, {"error": {
                            "message": str(e),
                            "type": "bad_wire_frame",
                            "reason": e.reason, "code": 400}})
                    if not isinstance(body, dict):
                        server._count_reject("json")
                        return self._json(400, {"error": {
                            "message": "frame body must be a JSON "
                                       "object",
                            "type": "bad_wire_frame",
                            "reason": "json", "code": 400}})
                else:
                    # legacy bare-JSON internal payloads stay accepted
                    # for one version of mixed-fleet compatibility
                    try:
                        body = json.loads(raw or b"{}")
                    except json.JSONDecodeError:
                        return self._json(400, {"error": "bad json"})
                try:
                    if self.path == "/v1/completions":
                        return self._completions(body, chat=False)
                    if self.path == "/v1/chat/completions":
                        return self._completions(body, chat=True)
                    if self.path == "/v1/embeddings":
                        return self._embeddings(body)
                    if self.path == "/v1/internal/kv_handoff":
                        return self._kv_handoff(body)
                    if self.path == "/v1/internal/migrate_in":
                        return self._migrate_in(body)
                    if self.path == "/v1/admin/migrate_out":
                        return self._admin_migrate_out(body)
                    if self.path == "/v1/profiler/start":
                        return self._profiler(body, start=True)
                    if self.path == "/v1/profiler/stop":
                        return self._profiler(body, start=False)
                except EngineDraining:
                    return self._draining_503()
                except RequestShed as e:
                    return self._shed_response(e)
                except ValueError as e:
                    return self._json(400, {"error": str(e)})
                self._json(404, {"error": "not found"})

            def _profiler(self, body: dict, start: bool):
                from bigdl_tpu.utils import profiling

                try:
                    if start:
                        log_dir = body.get("log_dir")
                        if not log_dir:
                            return self._json(
                                400, {"error": "'log_dir' required"})
                        out = profiling.start_profiler(
                            log_dir,
                            max_sec=body.get("duration_sec"),
                            capture_id=body.get("capture_id"))
                    else:
                        out = profiling.stop_profiler()
                except RuntimeError as e:
                    # double-start / stop-without-start / dir over cap
                    return self._json(409, {"error": str(e)})
                self._json(200, out)

            def _kv_handoff(self, body: dict):
                """Decode side of the disaggregated prefill/decode
                split: accept a prefill replica's KV snapshot, stage it
                into the prefix cache (engine.stage_handoff — the
                engine loop drains it before the next admission), then
                run the request through the NORMAL completion path. The
                admission's prefix seeding picks the staged planes up,
                so decode skips the already-prefilled tokens while the
                output stays byte-identical to a from-scratch run.
                Shedding/draining surface as the usual 429/503 — the
                prefill side treats any non-200 as a failed attempt."""
                prompt = body.get("prompt")
                if not (isinstance(prompt, list) and prompt
                        and all(isinstance(t, int) for t in prompt)):
                    return self._json(
                        400, {"error": "'prompt' must be a non-empty "
                                       "token-id list"})
                planes = planes_from_wire(body.get("planes"))
                req = body.get("request")
                req = dict(req) if isinstance(req, dict) else {}
                req.pop("stream", None)
                req["prompt"] = prompt
                # trace propagation: claim a child span for the decode
                # leg, re-parent the staged request under it, and echo
                # its id (X-Trace-Span) so the prefill side knows the
                # decode leg reported — a missing ack counts toward
                # bigdl_tpu_handoff_span_orphans_total over there
                tp = (req.get("_traceparent")
                      or self.headers.get("traceparent"))
                trace = parse_traceparent(tp)
                t_accept0 = time.time()
                sid = None
                if trace is not None:
                    sid = new_span_id()
                    req["_traceparent"] = make_traceparent(trace[0],
                                                           sid)
                    self._trace_headers = (("X-Trace-Span", sid),)
                server.engine.stage_handoff(prompt, planes)
                server._count_handoff("accepted")
                try:
                    return self._completions(req, chat=False)
                finally:
                    if trace is not None:
                        server.engine.spans.record(
                            "kv_handoff.decode", trace[0],
                            span_id=sid, parent_id=trace[1],
                            t_start=t_accept0, t_end=time.time(),
                            prompt_len=len(prompt))

            def _migrate_in(self, body: dict):
                """Target side of live migration: accept one
                mid-decode sequence's exported state (framed and
                CRC-checked in do_POST), stage it for the resumed
                request to claim (engine.stage_migration — the engine
                loop imports the KV pages before the next admission),
                and ack with the resume_id the client must present
                (X-Resume-Id). The source treats any non-200 as a
                failed attempt and falls back (retry / local resume) —
                including the injected recv/commit drops below, which
                emulate a request lost before intake and a commit ack
                lost on the wire (state staged, source never told; the
                staging TTL reclaims it unclaimed, so no tokens ever
                reach a client twice)."""
                if server.live_migration == "off":
                    return self._json(503, {"error": {
                        "message": "live migration disabled "
                                   "(BIGDL_TPU_LIVE_MIGRATION=off)",
                        "type": "unavailable", "code": 503}})
                if server.engine.draining:
                    return self._draining_503()
                step = server._next_handoff_attempt()
                if server.engine.faults.drop_point("migrate_recv",
                                                   step):
                    return self._json(503, {"error": {
                        "message": "injected migrate_recv drop",
                        "type": "unavailable", "code": 503}})
                t0 = time.time()
                planes = planes_from_wire(body.get("planes"))
                state = dict(body)
                state["planes"] = planes
                resume_id = server.engine.stage_migration(state)
                server.loop.notify()
                tr = state.get("trace")
                if tr:
                    server.engine.spans.record(
                        "migrate.in", tr[0], span_id=new_span_id(),
                        parent_id=tr[1], t_start=t0, t_end=time.time(),
                        resume_id=resume_id,
                        kv_len=state.get("kv_len"))
                if server.engine.faults.drop_point("migrate_commit",
                                                   step):
                    # the state IS staged — only the ack dies. The
                    # source resumes locally; the staged copy expires
                    # unclaimed (engine._migration_ttl)
                    return self._json(503, {"error": {
                        "message": "injected migrate_commit drop",
                        "type": "unavailable", "code": 503}})
                return self._json(200, {"resume_id": resume_id,
                                        "staged": True})

            def _admin_migrate_out(self, body: dict):
                """Operator/router entry point for planned disruption:
                migrate in-flight sequences to the named healthy peers
                and report per-sequence outcomes. The router calls
                this before the SIGTERM of a rolling restart or an
                autoscale retirement, so the drain that follows has
                nothing left to recompute."""
                targets = body.get("targets") or []
                if isinstance(targets, str):
                    targets = targets.split(",")
                targets = [str(t).strip() for t in targets
                           if str(t).strip()]
                if not targets:
                    return self._json(
                        400, {"error": "'targets' must name at least "
                                       "one host:port peer"})
                out = server.migrate_out(
                    targets, rids=body.get("request_ids"),
                    max_sequences=body.get("max_sequences"),
                    qos=body.get("qos"))
                self._json(200, out)

            def _embeddings(self, body: dict):
                if server.embedder is None or \
                        server.embedder_tokenizer is None:
                    return self._json(
                        400, {"error": "no embedding model configured "
                              "(construct OpenAIServer with embedder= "
                              "and embedder_tokenizer=)"})
                inputs = body.get("input")
                if isinstance(inputs, str):
                    inputs = [inputs]
                if not isinstance(inputs, list) or not inputs or \
                        not all(isinstance(t, str) for t in inputs):
                    return self._json(
                        400, {"error": "'input' must be a string or a "
                              "non-empty list of strings"})
                vecs, n_tok = server.embedder.embed_texts(
                    inputs, server.embedder_tokenizer,
                    with_counts=True)
                self._json(200, {
                    "object": "list",
                    "model": body.get("model", server.model_name),
                    "data": [
                        {"object": "embedding", "index": i,
                         "embedding": [float(x) for x in v]}
                        for i, v in enumerate(vecs)],
                    "usage": {"prompt_tokens": int(n_tok),
                              "total_tokens": int(n_tok)},
                })

            def _completions(self, body: dict, chat: bool):
                if chat:
                    prompt = _chat_to_prompt(body.get("messages", []),
                                             server.tokenizer)
                else:
                    prompt = body.get("prompt", "")
                ids = server._encode(prompt)
                params = server._params(body)
                params = dataclasses.replace(
                    params, tenant=server._tenant_of(self.headers))
                stops = body.get("stop") or ()
                if isinstance(stops, str):
                    stops = (stops,)
                stops = tuple(s for s in stops if s)
                created = int(time.time())
                # trace context: router/client header, or the staged
                # _traceparent a kv_handoff relay carries in its body
                tp = (self.headers.get("traceparent")
                      or body.get("_traceparent"))
                trace = parse_traceparent(tp)
                # shed BEFORE the stream branch commits its 200 header
                # (add_request would raise EngineDraining anyway, but by
                # then a streaming response is already half-written)
                if server.engine.draining:
                    return self._draining_503()
                # disaggregated path: a prefill-role replica handed a
                # non-streaming request by the router (X-Handoff-Targets
                # names the decode candidates) prefills locally, ships
                # the KV snapshot, and relays the decode replica's
                # response verbatim. A None return means every transfer
                # attempt failed — fall through to the normal local
                # path below, which reuses the snapshot as its own
                # prefix seed (the handoff ladder's terminal fallback:
                # the request is never lost to a dead decode target).
                # a migrated sequence arriving at its new home: the
                # router re-forwards the original request with
                # X-Resume-Id, and claiming the staged state resumes
                # generation mid-decode (zero recompute). A claim miss
                # — staging TTL expired, wrong replica — falls through
                # to a fresh replay: slower, never wrong.
                resume_state = None
                rh = self.headers.get("X-Resume-Id")
                if rh:
                    resume_state = server.engine.claim_migration(rh)
                pre_ids: List[int] = []
                if resume_state is not None:
                    # tokens generated before this replica took over
                    # (any earlier hop's output rode into the exported
                    # prompt; generated_offset marks where the true
                    # prompt ends) — seeded into _run_request so the
                    # response covers the full completion
                    off = int(resume_state.get("generated_offset")
                              or 0)
                    pids = list(
                        resume_state.get("prompt_token_ids") or [])
                    pre_ids = (pids[len(pids) - off:] if off else []) \
                        + list(resume_state.get("generated") or [])
                hdr = self.headers.get("X-Handoff-Targets")
                if hdr and "_handoff_targets" not in body:
                    body = dict(body)
                    body["_handoff_targets"] = hdr
                # (chat keeps local decode: the relayed JSON is in
                # text_completion shape)
                targets = (() if chat or resume_state is not None
                           else server._handoff_eligible(body, params))
                if targets:
                    out = server._prefill_and_handoff(
                        ids, params, body, targets, trace=trace)
                    if out is not None:
                        return self._json(200, out)
                # admit BEFORE the stream branch for the same reason:
                # overload control (RequestShed -> 429/503 +
                # Retry-After, handled in do_POST) must reject doomed
                # work as a clean status line, not a broken SSE body
                rid = f"cmpl-{uuid.uuid4().hex[:16]}"
                if resume_state is not None:
                    server.engine.resume_migrated_request(
                        rid, resume_state, trace=trace)
                else:
                    server.engine.add_request(rid, ids, params,
                                              trace=trace)
                server.loop.notify()

                if body.get("stream"):
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Cache-Control", "no-cache")
                    self.end_headers()

                    def cb(text, index):
                        delta = ({"role": "assistant", "content": text}
                                 if chat else None)
                        chunk = {
                            "id": "chunk", "object":
                                ("chat.completion.chunk" if chat
                                 else "text_completion"),
                            "created": created, "model": server.model_name,
                            "choices": [{
                                "index": index,
                                **({"delta": delta} if chat
                                   else {"text": text}),
                                "finish_reason": None}],
                        }
                        self.wfile.write(
                            b"data: " + json.dumps(chunk).encode() + b"\n\n")
                        self.wfile.flush()

                    rid, out_ids, out_lps, reasons, _, _ = \
                        server._run_request(
                            ids, params, stream_cb=cb, stop_strs=stops,
                            disconnect_check=lambda:
                                _socket_disconnected(self.connection),
                            cancel_cb=lambda: server._cancelled.labels(
                                "stream").inc(),
                            rid=rid, seed_ids=pre_ids or None)
                    try:
                        if any(r == "migrated"
                               for r in reasons.values()):
                            # the sequence moved mid-stream: emit the
                            # resume marker and STOP — no [DONE], the
                            # router re-forwards to the target and the
                            # continuation rides the same client
                            # stream (serving/router.py _relay)
                            info = server._take_migrated_info(rid)
                            self.wfile.write(
                                b"data: " + json.dumps({"migrated": {
                                    "id": rid,
                                    "resume_id":
                                        info.get("resume_id"),
                                    "target": info.get("target"),
                                }}).encode() + b"\n\n")
                            self.wfile.flush()
                            return
                        self.wfile.write(b"data: [DONE]\n\n")
                        self.wfile.flush()
                    except OSError:
                        pass    # client left after the last delta
                    return

                rid, out_ids, out_lps, reasons, texts, errors = \
                    server._run_request(
                        ids, params, stop_strs=stops,
                        disconnect_check=lambda: _socket_disconnected(
                            self.connection),
                        cancel_cb=lambda: server._cancelled.labels(
                            "nonstream").inc(),
                        rid=rid, seed_ids=pre_ids or None)
                # robustness status mapping: a request that ran out of
                # time (its own deadline, or the drain window closing on
                # it) is a gateway timeout; a quarantined request is a
                # server error with the engine's structured diagnosis
                mig = [i for i, r in reasons.items()
                       if r == "migrated"]
                if mig:
                    # the sequence moved to another replica: hand the
                    # router what it needs to finish the request there
                    # (re-forward with X-Resume-Id) and stitch the
                    # partial output in front of the continuation
                    info = server._take_migrated_info(rid)
                    return self._json(200, {
                        "id": rid, "object": "migration",
                        "migrated": True,
                        "resume_id": info.get("resume_id"),
                        "target": info.get("target"),
                        "partial_text": texts.get(mig[0], ""),
                        "partial_tokens":
                            len(out_ids.get(mig[0], [])),
                    })
                timed_out = [r for r in reasons.values()
                             if r in _TIMEOUT_REASONS]
                if timed_out:
                    return self._json(504, {"error": {
                        "message": f"request timed out ({timed_out[0]})",
                        "type": "timeout", "code": 504,
                        "reason": timed_out[0], "id": rid}})
                if any(r == "error" for r in reasons.values()):
                    detail = next(iter(errors.values()), {})
                    return self._json(500, {"error": {
                        "message": "request failed in the engine",
                        "type": "engine_error", "code": 500,
                        "id": rid, **detail}})
                choices = []
                total_completion = 0
                for idx in sorted(out_ids):
                    toks = out_ids[idx]
                    total_completion += len(toks)
                    text = texts.get(idx, server._decode_text(toks))
                    choice = ({"index": idx, "message":
                               {"role": "assistant", "content": text},
                               "finish_reason": reasons.get(idx, "stop")}
                              if chat else
                              {"index": idx, "text": text,
                               "finish_reason": reasons.get(idx, "stop")})
                    lps = out_lps.get(idx)
                    if lps is not None and params.logprobs is not None:
                        # OpenAI completions logprobs block (token-id keyed
                        # when no tokenizer is attached)
                        def tname(t):
                            return (server._decode_text([t])
                                    if server.tokenizer else str(t))
                        choice["logprobs"] = {
                            "tokens": [tname(e.token_id) for e in lps],
                            "token_logprobs": [e.logprob for e in lps],
                            "top_logprobs": [
                                {tname(t): lp for t, lp in e.top}
                                for e in lps],
                        }
                    choices.append(choice)
                self._json(200, {
                    "id": rid,
                    "object": "chat.completion" if chat else "text_completion",
                    "created": created,
                    "model": server.model_name,
                    "choices": choices,
                    "usage": {
                        "prompt_tokens": len(ids),
                        "completion_tokens": total_completion,
                        "total_tokens": len(ids) + total_completion},
                })

        return Handler

    def serve(self, host: str = "127.0.0.1", port: int = 8000,
              background: bool = False) -> ThreadingHTTPServer:
        self._httpd = ThreadingHTTPServer((host, port), self.make_handler())
        if background:
            t = threading.Thread(target=self._httpd.serve_forever,
                                 daemon=True)
            t.start()
        else:
            self._httpd.serve_forever()
        return self._httpd

    def begin_drain(self, timeout_sec: Optional[float] = None,
                    migrate_targets: Optional[List[str]] = None) -> None:
        """Graceful-drain entry point (the CLI's SIGTERM handler):
        admission stops (new requests get 503 + Retry-After), in-flight
        requests run to completion, and whatever outlives the drain
        window fails with 504. Poll `engine.drained` (or `wait_drained`)
        to know when it is safe to exit.

        When `migrate_targets` names healthy peers (the router's
        rolling restart and retirement pass them; the CLI SIGTERM
        handler reads $BIGDL_TPU_MIGRATE_TARGETS), in-flight mid-decode
        sequences are live-migrated there in a background thread while
        the drain settles — zero-loss, not merely zero-5xx."""
        self.engine.begin_drain(timeout_sec)
        self.loop.notify()       # wake the step loop to run the drain
        if migrate_targets and self.live_migration != "off":
            threading.Thread(
                target=self.migrate_out,
                args=(list(migrate_targets),), daemon=True).start()

    def wait_drained(self, poll_sec: float = 0.05) -> None:
        """Block until every in-flight request has finished (or the
        drain deadline failed it). Call after begin_drain()."""
        while not self.engine.drained:
            time.sleep(poll_sec)

    def shutdown(self):
        if self._httpd is not None:
            self._httpd.shutdown()
        self.loop.stop()


def main():
    """CLI: python -m bigdl_tpu.serving.api_server --model PATH [...]

    ``--tiny-random`` swaps the checkpoint for a seeded tiny random
    llama (utils/testing.tiny_random_model) — the replica mode the
    serving router's chaos tests and CPU bench lanes spawn: identical
    seeds give byte-identical weights across replicas, so a replayed
    greedy request must reproduce a dead replica's answer exactly."""
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=None)
    ap.add_argument("--load-in-low-bit", default="sym_int4")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--max-seq", type=int, default=2048)
    ap.add_argument("--embedder", default=None,
                    help="BERT checkpoint for /v1/embeddings")
    ap.add_argument("--tiny-random", action="store_true",
                    help="serve a seeded tiny random model instead of "
                         "a checkpoint (router tests / CPU bench)")
    ap.add_argument("--tiny-seed", type=int, default=0)
    ap.add_argument("--wedge-sec", type=float, default=10.0,
                    help="/health reports wedged past this step-loop "
                         "heartbeat age with work pending")
    ap.add_argument("--role", default=None, choices=list(REPLICA_ROLES),
                    help="fleet role (default $BIGDL_TPU_REPLICA_ROLE "
                         "or 'mixed'): prefill replicas ship KV to "
                         "decode replicas after chunked prefill")
    ap.add_argument("--kv-page-size", type=int, default=None,
                    help="positions per KV page (power of two; 0 = "
                         "per-slot slab; default "
                         "$BIGDL_TPU_KV_PAGE_SIZE or slab)")
    ap.add_argument("--kv-pages", type=int, default=None,
                    help="paged-KV arena size in pages (0 = auto-size "
                         "to max_batch*max_seq; default "
                         "$BIGDL_TPU_KV_PAGES)")
    ap.add_argument("--prefix-sharing", default=None,
                    choices=["auto", "on", "off"],
                    help="radix-tree prompt-prefix page sharing for "
                         "the paged KV cache (default "
                         "$BIGDL_TPU_PREFIX_SHARING or auto)")
    args = ap.parse_args()
    role = resolve_replica_role(args.role)

    tokenizer = None
    if args.tiny_random:
        from bigdl_tpu.utils.testing import tiny_random_model

        model = tiny_random_model(seed=args.tiny_seed)
        # the synthetic config's rope table caps the usable context
        args.max_seq = min(args.max_seq,
                           model.config.max_position_embeddings)
    else:
        if not args.model:
            ap.error("--model is required (or pass --tiny-random)")
        from bigdl_tpu.transformers.model import AutoModelForCausalLM

        model = AutoModelForCausalLM.from_pretrained(
            args.model, load_in_low_bit=args.load_in_low_bit,
            max_seq=args.max_seq)
        try:
            from transformers import AutoTokenizer

            tokenizer = AutoTokenizer.from_pretrained(args.model)
        except Exception:
            pass

    from bigdl_tpu.serving.engine import EngineConfig

    # a prefill replica must keep prompt KV snapshots or it has
    # nothing to hand off; mixed/decode keep the host-DRAM-hungry
    # prefix cache off unless opted in elsewhere
    engine = LLMEngine(model, EngineConfig(
        max_batch=args.max_batch, max_seq=args.max_seq,
        prefix_cache_entries=32 if role == "prefill" else 0,
        kv_page_size=args.kv_page_size, kv_pages=args.kv_pages,
        prefix_sharing=args.prefix_sharing))
    # span timelines name this process by its listen port, so the
    # router's merged /v1/trace/{id} view tells the replicas apart
    engine.spans.service = f"replica:{args.port}"
    embedder = embedder_tok = None
    if args.embedder:
        from transformers import AutoTokenizer

        from bigdl_tpu.transformers.embedder import BertEmbedder

        embedder = BertEmbedder.from_pretrained(args.embedder)
        embedder_tok = AutoTokenizer.from_pretrained(args.embedder)
    server = OpenAIServer(engine, tokenizer, embedder=embedder,
                          embedder_tokenizer=embedder_tok,
                          wedge_sec=args.wedge_sec, role=role)

    # SIGTERM (a deploy's kill) drains instead of dying: stop admitting
    # (503 + Retry-After), finish in-flight work up to
    # $BIGDL_TPU_DRAIN_TIMEOUT_SEC, then exit cleanly. Registered FIRST
    # so install_signal_dumps (below) chains to it after its postmortem.
    import signal as _signal

    def _drain_and_exit(signum, frame):
        # $BIGDL_TPU_MIGRATE_TARGETS (comma-separated host:port peers,
        # normally injected by the router/autoscaler at spawn): when
        # set, a SIGTERM drain live-migrates in-flight sequences there
        # instead of finishing them locally
        peers = [t.strip() for t in os.environ.get(
            "BIGDL_TPU_MIGRATE_TARGETS", "").split(",") if t.strip()]
        server.begin_drain(migrate_targets=peers or None)

        def _watch():
            server.wait_drained()
            server.shutdown()

        threading.Thread(target=_watch, daemon=True).start()

    _signal.signal(_signal.SIGTERM, _drain_and_exit)

    # operator kill (SIGTERM from a deploy, ^C) leaves a postmortem in
    # $BIGDL_TPU_POSTMORTEM_DIR before drain (SIGTERM) or default
    # termination (^C) proceeds
    from bigdl_tpu.observability.flight import install_signal_dumps

    install_signal_dumps(engine.write_postmortem)
    print(f"serving on http://{args.host}:{args.port}/v1")
    server.serve(args.host, args.port)
    server.loop.stop()


if __name__ == "__main__":
    main()
