"""Overload control for the serving engine: QoS, tenants, shedding.

The engine's admission path (PRs 2-6) hardened what happens *after* a
request is admitted — fault isolation, deadlines, drain, failover. This
module is the policy tier *at* admission:

* **QoS classes** — every request carries one of ``interactive`` /
  ``standard`` / ``batch`` (``SamplingParams.qos``). Admission is
  strict-priority with aging: a queued request is promoted one class
  per ``qos_aging_sec`` waited, so batch work cannot starve forever.
* **Per-tenant accounting** — token buckets bound each tenant's
  request rate and generated-token rate (429 when exhausted), and
  admission round-robins across tenants inside a QoS class
  (deficit-round-robin with a one-request quantum), so one hot tenant
  cannot starve the rest of the queue.
* **Bounded queues + early shedding** — queue-depth and queue-bytes
  caps, plus a queue-wait test (estimated wait from the measured TPOT
  EWMA x queue depth vs. the request's deadline) reject doomed work
  with 503 + ``Retry-After`` *before* it burns a slot. Batch sheds
  first: each class only fills its fraction of the depth cap
  (batch 50%, standard 75%, interactive 100%).
* **Brownout** — a pressure signal in [0, 1] (queue-depth ratio,
  memory-ledger headroom, step-latency inflation) drives a 4-level
  ladder with hysteresis::

      level 0  healthy    full service
      level 1  warm       speculative lookahead off, max_tokens capped
      level 2  hot        + prefill chunk shrunk, tighter token cap
      level 3  melting    + batch-QoS requests shed at admission

  Escalation needs ``pressure >= brownout_high`` for
  ``BROWNOUT_ENGAGE_STEPS`` consecutive updates; recovery needs
  ``pressure <= brownout_low`` for ``BROWNOUT_RECOVER_STEPS`` — both
  the threshold gap and the dwell are hysteresis, so the ladder does
  not flap at the boundary.

Everything here is pure policy over plain Python state: no JAX, and
fully deterministic given the same sequence of (clock, event) inputs —
which is what lets the ``overload_storm`` chaos fault drive the whole
ladder reproducibly. The controller carries ONE RLock of its own:
``check_admission`` runs on HTTP handler threads (inside the engine's
``add_request``) while ``update_pressure`` / ``note_generated`` /
``select_index`` run on the engine thread, and tenant bookkeeping is
read-modify-write — determinism is per interleaving, not a substitute
for mutual exclusion.
"""

from __future__ import annotations

import dataclasses
import math
import os
import threading
from typing import Dict, Optional, Sequence

__all__ = [
    "QOS_CLASSES",
    "QOS_PRIORITY",
    "OverloadConfig",
    "OverloadController",
    "RequestShed",
    "resolve_qos_default",
    "resolve_qos_aging_sec",
    "resolve_tenant_rps",
    "resolve_tenant_tps",
    "resolve_tenant_burst",
    "resolve_brownout_high",
    "resolve_brownout_low",
    "resolve_max_queue_depth",
    "resolve_max_queue_bytes",
]

#: QoS classes in priority order (lower index admits first)
QOS_CLASSES = ("interactive", "standard", "batch")
QOS_PRIORITY = {name: i for i, name in enumerate(QOS_CLASSES)}

#: fraction of the depth cap each class may fill — batch sheds first,
#: interactive may use the whole queue
QOS_DEPTH_FRACTION = {"interactive": 1.0, "standard": 0.75, "batch": 0.5}

#: absolute max_tokens cap per brownout level (None = uncapped)
BROWNOUT_MAX_TOKENS = (None, 256, 64, 16)

#: right-shift applied to the prefill chunk per brownout level (chunk
#: stays a power of two, so bucket allocation alignment is preserved)
BROWNOUT_CHUNK_SHIFT = (0, 0, 2, 2)

BROWNOUT_LEVELS = 3            # max level
BROWNOUT_ENGAGE_STEPS = 3      # consecutive high-pressure updates to go up
BROWNOUT_RECOVER_STEPS = 10    # consecutive low-pressure updates to go down

#: rough queue footprint accounting: int32 token ids
_BYTES_PER_TOKEN = 4


# ---------------------------------------------------------------------------
# env knobs


def resolve_qos_default(raw: Optional[str] = None) -> str:
    """$BIGDL_TPU_QOS_DEFAULT — QoS class for requests that name none
    (default "standard")."""
    if raw is None:
        raw = os.environ.get("BIGDL_TPU_QOS_DEFAULT", "")
    raw = raw.strip().lower()
    if not raw:
        return "standard"
    if raw not in QOS_CLASSES:
        raise ValueError(
            f"BIGDL_TPU_QOS_DEFAULT must be one of {QOS_CLASSES}, "
            f"got {raw!r}")
    return raw


def resolve_qos_aging_sec(raw: Optional[str] = None) -> float:
    """$BIGDL_TPU_QOS_AGING_SEC — seconds of queue wait that promote a
    request one QoS class (anti-starvation; default 5.0, must be > 0)."""
    if raw is None:
        raw = os.environ.get("BIGDL_TPU_QOS_AGING_SEC", "")
    if not raw.strip():
        return 5.0
    val = float(raw)
    if val <= 0:
        raise ValueError(
            f"BIGDL_TPU_QOS_AGING_SEC must be > 0, got {val}")
    return val


def resolve_tenant_rps(raw: Optional[str] = None) -> float:
    """$BIGDL_TPU_TENANT_RPS — per-tenant request-rate limit in
    requests/sec (default 0 = unlimited, must be >= 0)."""
    if raw is None:
        raw = os.environ.get("BIGDL_TPU_TENANT_RPS", "")
    if not raw.strip():
        return 0.0
    val = float(raw)
    if val < 0:
        raise ValueError(f"BIGDL_TPU_TENANT_RPS must be >= 0, got {val}")
    return val


def resolve_tenant_tps(raw: Optional[str] = None) -> float:
    """$BIGDL_TPU_TENANT_TPS — per-tenant generated-token-rate limit in
    tokens/sec (default 0 = unlimited, must be >= 0)."""
    if raw is None:
        raw = os.environ.get("BIGDL_TPU_TENANT_TPS", "")
    if not raw.strip():
        return 0.0
    val = float(raw)
    if val < 0:
        raise ValueError(f"BIGDL_TPU_TENANT_TPS must be >= 0, got {val}")
    return val


def resolve_tenant_burst(raw: Optional[str] = None) -> float:
    """$BIGDL_TPU_TENANT_BURST — token-bucket burst multiplier: a
    tenant's bucket holds ``burst x rate`` units (default 4.0,
    must be >= 1)."""
    if raw is None:
        raw = os.environ.get("BIGDL_TPU_TENANT_BURST", "")
    if not raw.strip():
        return 4.0
    val = float(raw)
    if val < 1:
        raise ValueError(
            f"BIGDL_TPU_TENANT_BURST must be >= 1, got {val}")
    return val


def resolve_brownout_high(raw: Optional[str] = None) -> float:
    """$BIGDL_TPU_BROWNOUT_HIGH — pressure at/above which brownout
    escalates one level (default 0.85, must be in (0, 1])."""
    if raw is None:
        raw = os.environ.get("BIGDL_TPU_BROWNOUT_HIGH", "")
    if not raw.strip():
        return 0.85
    val = float(raw)
    if not 0 < val <= 1:
        raise ValueError(
            f"BIGDL_TPU_BROWNOUT_HIGH must be in (0, 1], got {val}")
    return val


def resolve_brownout_low(raw: Optional[str] = None) -> float:
    """$BIGDL_TPU_BROWNOUT_LOW — pressure at/below which brownout
    recovers one level (default 0.6, must be in [0, 1) and below the
    high threshold for real hysteresis)."""
    if raw is None:
        raw = os.environ.get("BIGDL_TPU_BROWNOUT_LOW", "")
    if not raw.strip():
        return 0.6
    val = float(raw)
    if not 0 <= val < 1:
        raise ValueError(
            f"BIGDL_TPU_BROWNOUT_LOW must be in [0, 1), got {val}")
    return val


def resolve_max_queue_depth(raw: Optional[str] = None) -> int:
    """$BIGDL_TPU_MAX_QUEUE_DEPTH — hard bound on total queued requests
    across the decode and chunked-prefill waiting queues (default 256,
    must be > 0). Enforced even when every other overload feature is
    off: an unbounded deque under a storm is an OOM."""
    if raw is None:
        raw = os.environ.get("BIGDL_TPU_MAX_QUEUE_DEPTH", "")
    if not raw.strip():
        return 256
    val = int(raw)
    if val <= 0:
        raise ValueError(
            f"BIGDL_TPU_MAX_QUEUE_DEPTH must be > 0, got {val}")
    return val


def resolve_max_queue_bytes(raw: Optional[str] = None) -> int:
    """$BIGDL_TPU_MAX_QUEUE_BYTES — cap on the summed prompt footprint
    of queued requests (int32 token ids; default 64 MiB, must be > 0)."""
    if raw is None:
        raw = os.environ.get("BIGDL_TPU_MAX_QUEUE_BYTES", "")
    if not raw.strip():
        return 64 << 20
    val = int(raw)
    if val <= 0:
        raise ValueError(
            f"BIGDL_TPU_MAX_QUEUE_BYTES must be > 0, got {val}")
    return val


# ---------------------------------------------------------------------------
# config / exception


@dataclasses.dataclass
class OverloadConfig:
    """Policy knobs; ``None`` defers to the matching env knob."""

    qos_default: Optional[str] = None
    qos_aging_sec: Optional[float] = None
    tenant_rps: Optional[float] = None       # 0 = unlimited
    tenant_tps: Optional[float] = None       # 0 = unlimited
    tenant_burst: Optional[float] = None
    brownout_high: Optional[float] = None
    brownout_low: Optional[float] = None
    max_queue_depth: Optional[int] = None
    max_queue_bytes: Optional[int] = None

    def resolve(self) -> "OverloadConfig":
        return OverloadConfig(
            qos_default=(self.qos_default if self.qos_default is not None
                         else resolve_qos_default()),
            qos_aging_sec=(self.qos_aging_sec
                           if self.qos_aging_sec is not None
                           else resolve_qos_aging_sec()),
            tenant_rps=(self.tenant_rps if self.tenant_rps is not None
                        else resolve_tenant_rps()),
            tenant_tps=(self.tenant_tps if self.tenant_tps is not None
                        else resolve_tenant_tps()),
            tenant_burst=(self.tenant_burst
                          if self.tenant_burst is not None
                          else resolve_tenant_burst()),
            brownout_high=(self.brownout_high
                           if self.brownout_high is not None
                           else resolve_brownout_high()),
            brownout_low=(self.brownout_low
                          if self.brownout_low is not None
                          else resolve_brownout_low()),
            max_queue_depth=(self.max_queue_depth
                             if self.max_queue_depth is not None
                             else resolve_max_queue_depth()),
            max_queue_bytes=(self.max_queue_bytes
                             if self.max_queue_bytes is not None
                             else resolve_max_queue_bytes()),
        )


class RequestShed(RuntimeError):
    """Raised by admission when a request is rejected by overload
    control. Maps to HTTP 429 (per-tenant rate limits) or 503
    (capacity), always with a ``Retry-After`` hint."""

    def __init__(self, reason: str, qos: str, tenant: str,
                 retry_after_sec: int, http_status: int, detail: str = ""):
        self.reason = reason
        self.qos = qos
        self.tenant = tenant
        self.retry_after_sec = max(1, int(retry_after_sec))
        self.http_status = int(http_status)
        self.detail = detail
        msg = detail or f"request shed: {reason}"
        super().__init__(
            f"{msg} (qos={qos}, tenant={tenant}, "
            f"retry_after={self.retry_after_sec}s)")


#: every shed reason x its HTTP status — pre-labelled into the shed
#: counter so all series render from the first scrape
SHED_REASONS = {
    "queue_full": 503,       # class depth cap (hard cap for interactive)
    "queue_bytes": 503,      # summed prompt footprint cap
    "rate_limit": 429,       # tenant request-rate bucket empty
    "token_rate": 429,       # tenant generated-token bucket in debt
    "doomed": 503,           # cannot finish before its own deadline
    "brownout": 503,         # level-3 brownout sheds batch QoS
}


# ---------------------------------------------------------------------------
# token bucket


class TokenBucket:
    """Classic token bucket: ``rate`` units/sec refill, ``capacity``
    max. ``rate == 0`` disables the bucket (always admits). The level
    may go negative via :meth:`charge` (post-paid debt, used for
    generated tokens whose count is only known after the fact)."""

    def __init__(self, rate: float, capacity: float):
        self.rate = float(rate)
        self.capacity = max(float(capacity), 1.0)
        self.level = self.capacity
        self._last = None  # type: Optional[float]

    def _refill(self, now: float) -> None:
        if self._last is None:
            self._last = now
            return
        dt = max(0.0, now - self._last)
        self._last = now
        self.level = min(self.capacity, self.level + dt * self.rate)

    def try_take(self, n: float, now: float) -> bool:
        """Take ``n`` units if available; False (and no change) if not
        (or if the bucket is in post-paid debt)."""
        if self.rate <= 0:
            return True
        self._refill(now)
        if self.level < n:
            return False
        self.level -= n
        return True

    def charge(self, n: float, now: float) -> None:
        """Post-paid: deduct ``n`` units, allowing the level to go
        negative. Future :meth:`try_take` calls fail until the debt
        refills."""
        if self.rate <= 0:
            return
        self._refill(now)
        self.level -= n

    def wait_sec(self, n: float, now: float) -> float:
        """Seconds until ``n`` units will be available."""
        if self.rate <= 0:
            return 0.0
        self._refill(now)
        deficit = n - self.level
        return max(0.0, deficit / self.rate)


class _Tenant:
    """Per-tenant accounting: rate buckets + fairness/served counters."""

    def __init__(self, cfg: OverloadConfig):
        self.rps = TokenBucket(cfg.tenant_rps,
                               cfg.tenant_rps * cfg.tenant_burst)
        self.tps = TokenBucket(cfg.tenant_tps,
                               cfg.tenant_tps * cfg.tenant_burst)
        self.admitted_total = 0
        self.shed_total = 0
        self.generated_total = 0
        # DRR state: requests admitted since the controller started —
        # admission picks the least-served tenant inside a QoS class,
        # which is deficit round-robin with a one-request quantum
        self.served = 0

    def snapshot(self) -> dict:
        return {
            "admitted_total": self.admitted_total,
            "shed_total": self.shed_total,
            "generated_total": self.generated_total,
            "rps_level": round(self.rps.level, 3),
            "tps_level": round(self.tps.level, 3),
        }


# ---------------------------------------------------------------------------
# controller


class OverloadController:
    """All overload policy state for one engine. The engine owns the
    clock (passes ``now`` explicitly) so tests and the
    ``overload_storm`` fault stay deterministic."""

    def __init__(self, config: Optional[OverloadConfig] = None):
        self.cfg = (config or OverloadConfig()).resolve()
        if self.cfg.brownout_low >= self.cfg.brownout_high:
            raise ValueError(
                "brownout_low must be < brownout_high for hysteresis "
                f"(got low={self.cfg.brownout_low} >= "
                f"high={self.cfg.brownout_high})")
        self.tenants: Dict[str, _Tenant] = {}
        self.level = 0
        self.pressure = 0.0
        self._hi_streak = 0
        self._lo_streak = 0
        self.shed_counts: Dict[str, int] = {r: 0 for r in SHED_REASONS}
        self.level_changes = 0
        # handler threads (check_admission via add_request) race the
        # engine thread (update_pressure / note_generated /
        # select_index); RLock because check_admission re-enters
        # through tenant()
        self._lock = threading.RLock()

    # -- tenants ----------------------------------------------------------

    def tenant(self, name: str) -> _Tenant:
        with self._lock:
            t = self.tenants.get(name)
            if t is None:
                t = self.tenants[name] = _Tenant(self.cfg)
            return t

    def note_generated(self, tenant: str, n_tokens: int,
                       now: float) -> None:
        """Charge ``n_tokens`` generated tokens to the tenant's
        token-rate bucket (post-paid: admission only checks for debt)."""
        with self._lock:
            t = self.tenant(tenant)
            t.generated_total += n_tokens
            t.tps.charge(n_tokens, now)

    # -- admission --------------------------------------------------------

    def depth_limit(self, qos: str) -> int:
        """Per-class queue-depth cap: batch sheds at 50% of the hard
        cap, standard at 75%, interactive at 100%."""
        frac = QOS_DEPTH_FRACTION.get(qos, 1.0)
        return max(1, int(self.cfg.max_queue_depth * frac))

    def check_admission(self, *, qos: str, tenant: str, n_seqs: int,
                        prompt_len: int, queue_depth: int,
                        queue_bytes: int, deadline_sec: Optional[float],
                        tpot_sec: float, retry_after_sec: int,
                        now: float) -> None:
        """Run every early-shedding test; raises :class:`RequestShed`
        on the first failure. ``retry_after_sec`` is the engine's
        drain-rate / ledger-headroom estimate for capacity sheds;
        rate-limit sheds compute their own from the bucket refill."""
        with self._lock:
            t = self.tenant(tenant)

            def shed(reason: str, retry: int, detail: str = ""):
                t.shed_total += 1
                self.shed_counts[reason] = \
                    self.shed_counts.get(reason, 0) + 1
                raise RequestShed(reason, qos, tenant, retry,
                                  SHED_REASONS[reason], detail)

            # 1. brownout level 3: shed batch work outright
            if self.level >= BROWNOUT_LEVELS and qos == "batch":
                shed("brownout", retry_after_sec,
                     "engine browned out: batch QoS is shed until "
                     "pressure recedes")

            # 2. per-class queue depth (the interactive limit IS the
            # hard cap, so the bound holds even for the highest class)
            if queue_depth + n_seqs > self.depth_limit(qos):
                shed("queue_full", retry_after_sec,
                     f"queue depth {queue_depth} at the {qos} admission "
                     f"limit {self.depth_limit(qos)}")

            # 3. queue bytes
            add_bytes = n_seqs * prompt_len * _BYTES_PER_TOKEN
            if queue_bytes + add_bytes > self.cfg.max_queue_bytes:
                shed("queue_bytes", retry_after_sec,
                     f"queued prompt footprint {queue_bytes}B + "
                     f"{add_bytes}B exceeds cap "
                     f"{self.cfg.max_queue_bytes}B")

            # 4. tenant request-rate bucket
            if not t.rps.try_take(n_seqs, now):
                shed("rate_limit",
                     int(math.ceil(t.rps.wait_sec(n_seqs, now))) or 1,
                     f"tenant {tenant!r} over its request-rate limit "
                     f"({self.cfg.tenant_rps}/s)")

            # 5. tenant generated-token bucket (post-paid: shed while
            # in debt from previously generated tokens)
            if t.tps.rate > 0:
                t.tps.wait_sec(0.0, now)  # refill to "now" pre-check
                if t.tps.level < 0:
                    shed("token_rate",
                         int(math.ceil(-t.tps.level / t.tps.rate)) or 1,
                         f"tenant {tenant!r} over its generated-token "
                         f"limit ({self.cfg.tenant_tps} tok/s)")

            # 6. queue-wait test: if the backlog alone outlasts the
            # request's deadline, it is doomed — reject now instead of
            # burning queue+slot time and failing with 504 later
            if deadline_sec is not None and tpot_sec > 0:
                est_wait = tpot_sec * queue_depth
                if est_wait > deadline_sec:
                    shed("doomed", retry_after_sec,
                         f"estimated queue wait {est_wait:.2f}s exceeds "
                         f"the request deadline {deadline_sec:.2f}s")

            t.admitted_total += n_seqs

    # -- scheduling -------------------------------------------------------

    def effective_priority(self, qos: str, waited_sec: float) -> int:
        """Strict priority with aging: one class of promotion per
        ``qos_aging_sec`` waited (floor at the top class)."""
        pr = QOS_PRIORITY.get(qos, QOS_PRIORITY["standard"])
        if self.cfg.qos_aging_sec > 0:
            pr -= int(waited_sec / self.cfg.qos_aging_sec)
        return max(0, pr)

    def select_index(self, waiting: Sequence, now: float) -> int:
        """Pick the queue index to admit next: best effective priority
        first, then the least-served tenant (DRR, quantum 1), then
        queue order. Queue POSITION is the FCFS tiebreaker — not
        arrival time — so a preempted request requeued at the back
        yields to work that has never run (arrival still drives
        aging). Pure — call :meth:`note_scheduled` only once the pick
        is actually admitted (memory deferral may put it back)."""
        with self._lock:
            best_i, best_key = 0, None
            for i, req in enumerate(waiting):
                qos = getattr(req.params, "qos", None) or "standard"
                tenant = getattr(req.params, "tenant", None) or "default"
                pr = self.effective_priority(qos, now - req.arrival)
                key = (pr, self.tenant(tenant).served, i)
                if best_key is None or key < best_key:
                    best_i, best_key = i, key
            return best_i

    def note_scheduled(self, tenant: str) -> None:
        """Advance the tenant's DRR counter after a successful pick."""
        with self._lock:
            self.tenant(tenant).served += 1

    # -- brownout ---------------------------------------------------------

    def update_pressure(self, pressure: float) -> Optional[int]:
        """Feed one pressure sample; returns the new level if it
        changed, else None. Hysteresis: both a threshold gap
        (high/low) and a dwell (consecutive samples) gate transitions."""
        with self._lock:
            self.pressure = max(0.0, min(1.0, float(pressure)))
            if self.pressure >= self.cfg.brownout_high:
                self._hi_streak += 1
                self._lo_streak = 0
            elif self.pressure <= self.cfg.brownout_low:
                self._lo_streak += 1
                self._hi_streak = 0
            else:
                self._hi_streak = 0
                self._lo_streak = 0
            if self._hi_streak >= BROWNOUT_ENGAGE_STEPS \
                    and self.level < BROWNOUT_LEVELS:
                self.level += 1
                self._hi_streak = 0
                self.level_changes += 1
                return self.level
            if self._lo_streak >= BROWNOUT_RECOVER_STEPS \
                    and self.level > 0:
                self.level -= 1
                self._lo_streak = 0
                self.level_changes += 1
                return self.level
            return None

    @property
    def speculative_allowed(self) -> bool:
        """Speculative lookahead is the first work a brownout sheds."""
        with self._lock:
            return self.level == 0

    @property
    def wants_migration(self) -> bool:
        """Level 3's fleet-relief option: a fully browned-out replica
        is shedding new batch admissions anyway, so the batch
        sequences it is ALREADY running are better finished on a
        cooler peer. Surfaced through the /v1/stats migration block;
        the router reads it and drives POST /v1/admin/migrate_out
        with qos="batch"."""
        with self._lock:
            return self.level >= BROWNOUT_LEVELS

    def max_tokens_cap(self) -> Optional[int]:
        with self._lock:
            return BROWNOUT_MAX_TOKENS[min(self.level,
                                           len(BROWNOUT_MAX_TOKENS) - 1)]

    def chunk_shift(self) -> int:
        with self._lock:
            return BROWNOUT_CHUNK_SHIFT[min(self.level,
                                            len(BROWNOUT_CHUNK_SHIFT) - 1)]

    # -- introspection ----------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "brownout_level": self.level,
                "pressure": round(self.pressure, 4),
                "speculative_allowed": self.speculative_allowed,
                "max_tokens_cap": self.max_tokens_cap(),
                "chunk_shift": self.chunk_shift(),
                "max_queue_depth": self.cfg.max_queue_depth,
                "max_queue_bytes": self.cfg.max_queue_bytes,
                "shed": {k: v for k, v in
                         sorted(self.shed_counts.items()) if v},
                "tenants": {name: t.snapshot()
                            for name, t in sorted(self.tenants.items())},
            }
