"""Load-signal autoscaler for the multi-replica serving tier.

The router (serving/router.py) already probes every replica's
``/v1/stats`` and keeps the fleet's load signals on each ``Replica``:
brownout level, queue depth, slot occupancy, decode tpot EWMA, and HBM
ledger headroom. This module closes the loop — a small supervisor that
reshapes the fleet instead of only shedding:

- **Scale up** — spawn a ``mixed`` replica when the fleet is pressured
  (any replica browned out, mean queue depth or occupancy past the
  thresholds, or ledger headroom thin) for ``up_streak`` consecutive
  ticks.
- **Scale down** — drain + retire the least-loaded ``mixed`` replica
  when the fleet has been idle for ``down_streak`` consecutive ticks.
  NEVER the last healthy replica (``Router.retire_replica`` refuses),
  never below ``$BIGDL_TPU_AUTOSCALE_MIN``. ``retire_replica`` first
  live-migrates the victim's in-flight sequences to surviving peers
  (``/v1/admin/migrate_out``), so a scale-down loses zero tokens even
  mid-decode.
- **Role reassignment** — when pressure persists at the max replica
  bound, flip a ``mixed`` replica to ``prefill`` when TTFT pressure
  dominates (deep queues, calm tpot: admission work is the bottleneck)
  or to ``decode`` when TPOT pressure dominates (hot tpot EWMA, calm
  queues: decode steps are the bottleneck).

Every decision — applied, refused, or skipped — is recorded as a
flight-recorder event and counted in
``bigdl_tpu_autoscaler_decisions_total{action, reason}``.

Discipline against the rest of the control plane:

- **Dwell + hysteresis.** Actions are gated by a dwell window
  (``$BIGDL_TPU_AUTOSCALE_DWELL_SEC`` since the previous action) and by
  consecutive-tick streaks, so a noisy load signal cannot flap the
  fleet. The ``scale_flap`` chaos fault (robustness/faults.py) forces
  alternating decisions PAST the dwell gate — the hard guards below are
  exactly what it exercises.
- **Hard guards.** Scale decisions take the router's ``_admin_lock``
  non-blocking: while a rolling restart holds it (or vice versa) the
  tick is skipped with reason ``admin_busy``. The min/max bounds and
  the last-healthy-replica refusal hold even under a forced flap.

Run it with ``Autoscaler(router).start()`` (the router CLI's
``--autoscale``), or drive ``tick()`` directly in tests.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import threading
import time
from typing import Any, Dict, List, Optional

from bigdl_tpu.robustness.faults import FaultInjector
from bigdl_tpu.serving.router import HEALTHY, QUARANTINED, RETIRED

AUTOSCALE_MIN_ENV = "BIGDL_TPU_AUTOSCALE_MIN"
AUTOSCALE_MAX_ENV = "BIGDL_TPU_AUTOSCALE_MAX"
AUTOSCALE_DWELL_ENV = "BIGDL_TPU_AUTOSCALE_DWELL_SEC"


def resolve_autoscale_min(value: Optional[str] = None) -> int:
    """Fleet floor (default 1, must be >= 1)."""
    raw = value if value is not None else os.environ.get(
        AUTOSCALE_MIN_ENV, "")
    if not raw:
        return 1
    n = int(raw)                       # ValueError propagates
    if n < 1:
        raise ValueError(
            f"{AUTOSCALE_MIN_ENV} must be >= 1, got {raw!r}")
    return n


def resolve_autoscale_max(value: Optional[str] = None) -> int:
    """Fleet ceiling (default 4, must be >= 1; clamped up to the
    resolved min by AutoscalerConfig.resolve)."""
    raw = value if value is not None else os.environ.get(
        AUTOSCALE_MAX_ENV, "")
    if not raw:
        return 4
    n = int(raw)                       # ValueError propagates
    if n < 1:
        raise ValueError(
            f"{AUTOSCALE_MAX_ENV} must be >= 1, got {raw!r}")
    return n


def resolve_autoscale_dwell_sec(value: Optional[str] = None) -> float:
    """Minimum seconds between applied scale actions (default 30,
    must be >= 0)."""
    raw = value if value is not None else os.environ.get(
        AUTOSCALE_DWELL_ENV, "")
    if not raw:
        return 30.0
    sec = float(raw)                   # ValueError propagates
    if sec < 0:
        raise ValueError(
            f"{AUTOSCALE_DWELL_ENV} must be >= 0, got {raw!r}")
    return sec


@dataclasses.dataclass
class AutoscalerConfig:
    """``None`` fields defer to their env variables (bad values fall
    back to defaults; env_check reports them)."""
    min_replicas: Optional[int] = None   # $BIGDL_TPU_AUTOSCALE_MIN
    max_replicas: Optional[int] = None   # $BIGDL_TPU_AUTOSCALE_MAX
    dwell_sec: Optional[float] = None    # $BIGDL_TPU_AUTOSCALE_DWELL_SEC
    tick_sec: float = 1.0
    # hysteresis: consecutive pressured/idle ticks before acting
    up_streak: int = 3
    down_streak: int = 6
    # pressure thresholds over the healthy fleet
    queue_high: float = 8.0        # mean queue depth -> TTFT pressure
    occupancy_high: float = 0.9    # mean active/total slots
    occupancy_low: float = 0.25    # idle bound for scale-down
    # router-side outstanding requests per replica: unlike the polled
    # signals above this is updated synchronously per forward, so a
    # burst registers as pressure immediately (no poll-cadence race)
    inflight_high: float = 8.0
    headroom_low: float = 0.1      # min ledger headroom fraction
    tpot_high_ms: float = 250.0    # max tpot EWMA -> TPOT pressure
    # only flip roles after pressure persisted this long at max scale
    flip_streak: int = 5

    def resolve(self) -> "AutoscalerConfig":
        out = dataclasses.replace(self)
        if out.min_replicas is None:
            try:
                out.min_replicas = resolve_autoscale_min()
            except ValueError:
                out.min_replicas = 1      # env_check reports it
        if out.max_replicas is None:
            try:
                out.max_replicas = resolve_autoscale_max()
            except ValueError:
                out.max_replicas = 4
        if out.dwell_sec is None:
            try:
                out.dwell_sec = resolve_autoscale_dwell_sec()
            except ValueError:
                out.dwell_sec = 30.0
        out.max_replicas = max(out.max_replicas, out.min_replicas)
        return out


class Autoscaler:
    """Dwell/hysteresis-gated fleet reshaping over a running Router.

    One decision loop thread (``start``/``stop``) — or ``tick()``
    driven directly by tests. Cross-thread state (the decision log and
    streak/dwell bookkeeping, read by HTTP handler threads via
    ``snapshot()``) is guarded by ``_lock`` on every touch; the slow
    fleet mutations (spawn, drain, respawn) run OUTSIDE it so a
    snapshot never blocks on a drain."""

    def __init__(self, router, config: Optional[AutoscalerConfig] = None,
                 faults: Optional[FaultInjector] = None):
        self.router = router
        self.cfg = (config or AutoscalerConfig()).resolve()
        if faults is None:
            try:
                faults = FaultInjector.from_env()
            except ValueError:
                faults = FaultInjector()   # env_check reports the spec
        self.faults = faults
        router.autoscaler = self
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        with self._lock:
            self._tick_no = 0
            self._up = 0                  # consecutive pressured ticks
            self._down = 0                # consecutive idle ticks
            self._pressed = 0             # pressured ticks at max scale
            # dwell measured from construction: a fresh fleet earns its
            # first action
            self._last_action_at = time.monotonic()
            self._decisions: collections.deque = collections.deque(
                maxlen=128)
        reg = router.registry
        self._c_decisions = reg.counter(
            "bigdl_tpu_autoscaler_decisions_total",
            "autoscaler decisions by action and structured reason",
            ["action", "reason"])
        self._g_healthy = reg.gauge(
            "bigdl_tpu_autoscaler_healthy_replicas",
            "healthy replicas the autoscaler observed last tick")
        self._g_active = reg.gauge(
            "bigdl_tpu_autoscaler_active_replicas",
            "non-retired, non-quarantined replicas (the scale bound)")

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        while not self._stop_evt.is_set():
            try:
                self.tick()
            except Exception:
                import traceback

                traceback.print_exc()    # the loop must survive
            self._stop_evt.wait(timeout=self.cfg.tick_sec)

    # -- signals ------------------------------------------------------------

    def _healthy(self) -> List[Any]:
        return [r for r in self.router.replicas
                if r.state == HEALTHY and not r.planned_restart]

    def _active_count(self) -> int:
        return sum(1 for r in self.router.replicas
                   if r.state not in (RETIRED, QUARANTINED))

    def signals(self) -> Dict[str, Any]:
        """Fleet-level load signals from the router's last stats poll."""
        reps = self._healthy()
        n = len(reps)
        if not n:
            return {"healthy": 0, "brownout_max": 0, "queue_mean": 0.0,
                    "occupancy_mean": 0.0, "inflight_mean": 0.0,
                    "tpot_ewma_ms_max": 0.0, "headroom_min": None}
        hrs = [r.headroom_frac for r in reps
               if r.headroom_frac is not None]
        return {
            "healthy": n,
            "brownout_max": max(r.brownout for r in reps),
            "queue_mean": sum(r.queue_depth for r in reps) / n,
            "occupancy_mean": sum(r.occupancy for r in reps) / n,
            "inflight_mean": sum(len(r.inflight) for r in reps) / n,
            "tpot_ewma_ms_max": max(r.tpot_ewma_ms for r in reps),
            "headroom_min": min(hrs) if hrs else None,
        }

    @staticmethod
    def _pressured(sig: Dict[str, Any], cfg: AutoscalerConfig) -> bool:
        hr = sig["headroom_min"]
        return (sig["brownout_max"] >= 1
                or sig["queue_mean"] >= cfg.queue_high
                or sig["occupancy_mean"] >= cfg.occupancy_high
                or sig["inflight_mean"] >= cfg.inflight_high
                or sig["tpot_ewma_ms_max"] >= cfg.tpot_high_ms
                or (hr is not None and hr < cfg.headroom_low))

    @staticmethod
    def _idle(sig: Dict[str, Any], cfg: AutoscalerConfig) -> bool:
        return (sig["brownout_max"] == 0
                and sig["queue_mean"] == 0
                and sig["inflight_mean"] == 0
                and sig["tpot_ewma_ms_max"] < cfg.tpot_high_ms
                and sig["occupancy_mean"] <= cfg.occupancy_low)

    # -- the decision loop --------------------------------------------------

    def tick(self) -> Dict[str, Any]:
        """One decision cycle; returns the recorded decision dict.
        Safe to call directly (tests) — the loop thread just calls it
        on a timer."""
        sig = self.signals()
        self._g_healthy.set(sig["healthy"])
        self._g_active.set(self._active_count())
        tick_no, action, reason = self._decide(sig)
        if action in ("up", "down", "flip_prefill", "flip_decode"):
            action, reason = self._apply(action, reason, sig)
        return self._record(tick_no, action, reason, sig)

    def _decide(self, sig: Dict[str, Any]):
        """Streak/dwell bookkeeping -> (tick_no, action, reason).
        Takes ``_lock`` itself; the slow ``_apply`` runs after it is
        released so ``snapshot()`` never blocks on a drain."""
        at_max = self._active_count() >= self.cfg.max_replicas
        with self._lock:
            self._tick_no += 1
            tick_no = self._tick_no
            forced = self.faults.flap_direction(tick_no)
            if forced is not None:
                # chaos: bypass dwell AND hysteresis — the hard guards
                # in _apply are the invariants under test
                return tick_no, forced, "fault:scale_flap"
            if sig["healthy"] == 0:
                # the router's supervisor owns crash recovery; scaling
                # a fleet with zero healthy replicas is its job
                self._up = self._down = self._pressed = 0
                return tick_no, "hold", "no_healthy_replica"
            pressured = self._pressured(sig, self.cfg)
            idle = self._idle(sig, self.cfg)
            self._up = self._up + 1 if pressured else 0
            self._down = self._down + 1 if idle else 0
            self._pressed = self._pressed + 1 \
                if (pressured and at_max) else 0
            dwell_ok = (time.monotonic() - self._last_action_at
                        >= self.cfg.dwell_sec)
            if pressured and self._up >= self.cfg.up_streak:
                if not at_max:
                    if dwell_ok:
                        return tick_no, "up", \
                            self._pressure_reason(sig)
                    return tick_no, "hold", "dwell"
                if self._pressed >= self.cfg.flip_streak and dwell_ok:
                    # at the ceiling, still pressured: reshape instead
                    if sig["queue_mean"] >= self.cfg.queue_high \
                            and sig["tpot_ewma_ms_max"] \
                            < self.cfg.tpot_high_ms:
                        return tick_no, "flip_prefill", \
                            "ttft_pressure"
                    if sig["tpot_ewma_ms_max"] \
                            >= self.cfg.tpot_high_ms \
                            and sig["queue_mean"] < self.cfg.queue_high:
                        return tick_no, "flip_decode", \
                            "tpot_pressure"
                return tick_no, "hold", "at_max"
            if idle and self._down >= self.cfg.down_streak:
                if sig["healthy"] <= max(self.cfg.min_replicas, 1):
                    return tick_no, "hold", "at_min"
                if dwell_ok:
                    return tick_no, "down", "idle"
                return tick_no, "hold", "dwell"
            return tick_no, "hold", "steady"

    @staticmethod
    def _pressure_reason(sig: Dict[str, Any]) -> str:
        if sig["brownout_max"] >= 1:
            return "brownout"
        if sig["queue_mean"] > 0:
            return "queue_depth"
        if sig["inflight_mean"] > 0:
            return "inflight"
        if sig["tpot_ewma_ms_max"] > 0:
            return "tpot_ewma"
        return "headroom"

    def _apply(self, action: str, reason: str, sig: Dict[str, Any]):
        """Execute one decision under the router's admin lock. Returns
        the (possibly downgraded) (action, reason) actually taken —
        guard refusals come back as ``refused_*``."""
        if not self.router._admin_lock.acquire(blocking=False):
            # a rolling restart (or another admin op) owns the fleet:
            # scale decisions must not fight it
            return f"skipped_{action}", "admin_busy"
        try:
            if action == "up":
                if self._active_count() >= self.cfg.max_replicas:
                    return "refused_up", "at_max"
                self.router.add_replica(role="mixed")
                self._mark_action_locked()
                return "up", reason
            healthy = self._healthy()
            if action == "down":
                if len(healthy) <= max(self.cfg.min_replicas, 1):
                    return "refused_down", "at_min"
                victim = self._victim(healthy)
                if victim is None or not self.router.retire_replica(
                        victim, reason="autoscale_down"):
                    return "refused_down", "last_healthy"
                self._mark_action_locked()
                return "down", reason
            # role flips
            mixed = [r for r in healthy if r.role == "mixed"]
            if len(mixed) < 1 or len(healthy) < 2:
                return f"refused_{action}", "no_mixed_replica"
            victim = self._victim(mixed)
            role = "prefill" if action == "flip_prefill" else "decode"
            if not self.router.reassign_role(victim, role):
                return f"refused_{action}", "flip_failed"
            self._mark_action_locked()
            return action, reason
        finally:
            self.router._admin_lock.release()

    @staticmethod
    def _victim(candidates: List[Any]):
        """Least-loaded candidate, mixed-role first: retiring or
        flipping a specialized replica costs the fleet a capability."""
        if not candidates:
            return None
        return min(candidates,
                   key=lambda r: (r.role != "mixed", r.occupancy,
                                  r.queue_depth, len(r.inflight),
                                  r.idx))

    def _mark_action_locked(self) -> None:
        with self._lock:
            self._last_action_at = time.monotonic()
            self._up = self._down = self._pressed = 0

    def _record(self, tick_no: int, action: str, reason: str,
                sig: Dict[str, Any]) -> Dict[str, Any]:
        decision = {"tick": tick_no, "action": action, "reason": reason,
                    "signals": sig}
        self._c_decisions.labels(action, reason).inc()
        if action != "hold":
            self.router._count(f"autoscale_decision_{action}")
            self.router.flight.record("autoscale_decision",
                                      tick=tick_no, action=action,
                                      reason=reason, **{
                                          k: v for k, v in sig.items()
                                          if v is not None})
            # pin the decision to the timelines of requests in flight
            # around it (tests drive stub routers without a recorder)
            rec = getattr(self.router, "spans", None)
            if rec is not None:
                rec.annotate_recent("autoscale_decision",
                                    action=action, reason=reason,
                                    tick=tick_no)
        with self._lock:
            self._decisions.append(decision)
        return decision

    # -- introspection ------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready state for ``GET /v1/router/stats`` (embedded by
        the router when attached)."""
        with self._lock:
            return {
                "tick": self._tick_no,
                "up_streak": self._up,
                "down_streak": self._down,
                "pressed_at_max": self._pressed,
                "last_action_age_sec": round(
                    time.monotonic() - self._last_action_at, 3),
                "decisions": list(self._decisions)[-16:],
                "config": {
                    "min_replicas": self.cfg.min_replicas,
                    "max_replicas": self.cfg.max_replicas,
                    "dwell_sec": self.cfg.dwell_sec,
                    "up_streak": self.cfg.up_streak,
                    "down_streak": self.cfg.down_streak,
                    "flip_streak": self.cfg.flip_streak,
                },
            }
