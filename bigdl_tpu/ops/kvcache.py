"""Static-shape KV cache with low-bit storage dtypes.

TPU-native re-design of the reference's KV caching
(`DynamicNormalCache`/`DynamicFp8Cache`, reference transformers/kv.py:28-123,
and init/append/extend helpers in transformers/models/utils.py:38-153).

The reference grows its cache in 256-token blocks (realloc + copy) because
PyTorch tolerates dynamic shapes. Under XLA everything must be static: the
cache is **pre-allocated at max_seq_len** and appends are
`lax.dynamic_update_slice` writes at the current position — no realloc ever,
the jit-compiled decode step has one shape for its whole lifetime. Validity
is tracked by a scalar `pos`; attention masks keys at positions >= the
query's position + 1 (so garbage in the unwritten tail is never read).

Storage dtypes (`kv_cache_dtype`):

==========  =============================================================
bf16        plain bfloat16 (default)
fp8_e5m2    scale-free float8_e5m2, the reference's e5m2 cache
            (models/utils.py:99-153); upcast fused into the matmul read
int8        symmetric int8 codes + per-(token, head) f32 scales
int4        symmetric jnp.int4 codes (XLA packs two per byte) + scales
==========  =============================================================

int8/int4 quantize on append: each written [D] vector gets one absmax
scale, so appends at arbitrary (unaligned) positions never re-quantize
neighbours and slot reuse can never leak a stale scale. Scales live in
separate [L, B, S, Hkv] f32 planes (`k_scale`/`v_scale`, None for the
scale-free dtypes) so the code planes keep the exact cache layout the
attention kernels already stream.

Layout: [num_layers, batch, max_seq, kv_heads, head_dim] — the whole stack is
one array per K/V so a `lax.scan` over layers can carry it and update layer
slices in place (donated buffers alias, so there is no copy in the hot loop).
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

# canonical kv_cache_dtype names -> storage dtypes
KV_CACHE_DTYPES = {
    "bf16": jnp.bfloat16,
    "fp8_e5m2": jnp.float8_e5m2,
    "int8": jnp.int8,
    "int4": jnp.int4,
}
# dtypes that carry per-(token, head) scale planes
SCALED_KV_DTYPES = ("int8", "int4")
_KV_QMAX = {"int8": 127.0, "int4": 7.0}
_DTYPE_ALIASES = {"bfloat16": "bf16", "fp8": "fp8_e5m2",
                  "float8_e5m2": "fp8_e5m2", "e5m2": "fp8_e5m2"}

_warned_quantized_alias = False


def resolve_kv_cache_dtype(spec, default: str = "bf16") -> str:
    """Normalize a kv-cache dtype spec to a canonical name.

    Accepts the canonical strings (plus common aliases), None (-> default)
    and — for backward compatibility with the old `quantize_kv_cache` /
    `kv_quantized` booleans — True (deprecated alias for "fp8_e5m2",
    warned once per process) / False (-> default)."""
    global _warned_quantized_alias
    if spec is None:
        return default
    if isinstance(spec, bool):
        if spec:
            if not _warned_quantized_alias:
                _warned_quantized_alias = True
                warnings.warn(
                    "quantize_kv_cache/kv_quantized=True is deprecated; "
                    "use kv_cache_dtype='fp8_e5m2' (or 'int8'/'int4' for "
                    "block-scaled storage)", DeprecationWarning,
                    stacklevel=3)
            return "fp8_e5m2"
        return default
    s = str(spec).strip().lower()
    s = _DTYPE_ALIASES.get(s, s)
    if s not in KV_CACHE_DTYPES:
        raise ValueError(
            f"unknown kv_cache_dtype {spec!r}; choose from "
            f"{sorted(KV_CACHE_DTYPES)}")
    return s


def reject_scaled_kv(spec, family: str) -> None:
    """Guard for model families whose forward does not thread the
    int8/int4 scale planes: fail at cache allocation with a clear
    message instead of silently attending over raw codes."""
    if resolve_kv_cache_dtype(spec) in SCALED_KV_DTYPES:
        raise NotImplementedError(
            f"kv_cache_dtype int8/int4 is not supported by the "
            f"{family} family (its forward does not carry the scale "
            f"planes); use 'bf16' or 'fp8_e5m2'")


def kv_dtype_name(storage_dtype) -> str:
    """Canonical name for a cache storage dtype (inverse of the table)."""
    dt = jnp.dtype(storage_dtype)
    for name, d in KV_CACHE_DTYPES.items():
        if jnp.dtype(d) == dt:
            return name
    return str(dt)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class KVCache:
    k: jax.Array    # [L, B, S_max, H_kv, D] storage dtype
    v: jax.Array    # [L, B, S_max, H_kv, D]
    pos: jax.Array  # scalar int32: number of valid positions
    # per-(token, head) f32 dequant scales for int8/int4 storage;
    # None for the scale-free dtypes (bf16 / fp8_e5m2)
    k_scale: Optional[jax.Array] = None   # [L, B, S_max, H_kv] f32
    v_scale: Optional[jax.Array] = None

    def tree_flatten(self):
        return (self.k, self.v, self.pos, self.k_scale, self.v_scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def max_seq(self) -> int:
        return self.k.shape[2]

    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    @property
    def kv_dtype(self) -> str:
        """Canonical kv_cache_dtype name of the storage."""
        return kv_dtype_name(self.k.dtype)

    def reset_pos(self, pos) -> "KVCache":
        """Same buffers, new validity pointer (generation pad repair /
        speculative rollback)."""
        return KVCache(self.k, self.v, pos, self.k_scale, self.v_scale)


def init_cache(
    num_layers: int,
    batch: int,
    max_seq: int,
    kv_heads: int,
    head_dim: int,
    dtype=jnp.bfloat16,
    quantized=False,
    per_slot_pos: bool = False,
    kv_cache_dtype: Optional[str] = None,
) -> KVCache:
    """Allocate an empty cache.

    `kv_cache_dtype` picks the storage ("bf16" | "fp8_e5m2" | "int8" |
    "int4"); `quantized` is the deprecated boolean alias (True ->
    "fp8_e5m2") and, for plumbing convenience, also accepts a dtype
    name string directly.

    per_slot_pos=True gives every batch row its own position counter —
    the continuous-batching layout (each serving slot decodes at its own
    depth, the capability the reference's vLLM port builds from per-seq
    KV dicts, vllm/model_executor/models/bigdl_model.py:88-139)."""
    name = resolve_kv_cache_dtype(
        kv_cache_dtype if kv_cache_dtype is not None else quantized)
    dt = dtype if name == "bf16" else KV_CACHE_DTYPES[name]
    shape = (num_layers, batch, max_seq, kv_heads, head_dim)
    scaled = name in SCALED_KV_DTYPES
    sshape = (num_layers, batch, max_seq, kv_heads)
    return KVCache(
        k=jnp.zeros(shape, dt),
        v=jnp.zeros(shape, dt),
        pos=(jnp.zeros((batch,), jnp.int32) if per_slot_pos
             else jnp.zeros((), jnp.int32)),
        k_scale=jnp.zeros(sshape, jnp.float32) if scaled else None,
        v_scale=jnp.zeros(sshape, jnp.float32) if scaled else None,
    )


def quantize_kv(x: jax.Array, storage_dtype) -> Tuple[jax.Array, jax.Array]:
    """Symmetric absmax quantization of the trailing [D] vectors.

    Returns (codes in storage_dtype, f32 scales of x.shape[:-1]).
    Zero vectors get scale 0 and all-zero codes (dequant is exact)."""
    qmax = _KV_QMAX[kv_dtype_name(storage_dtype)]
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = amax / qmax
    inv = jnp.where(scale > 0, 1.0 / jnp.maximum(scale, 1e-30), 0.0)
    codes = jnp.clip(jnp.round(xf * inv[..., None]), -qmax, qmax)
    return codes.astype(storage_dtype), scale


def dequantize_kv(codes: jax.Array, scale: jax.Array,
                  compute_dtype=jnp.bfloat16) -> jax.Array:
    """codes [.., D] * scale [..] -> compute_dtype (dequant in f32)."""
    return (codes.astype(jnp.float32)
            * scale[..., None].astype(jnp.float32)).astype(compute_dtype)


def update_layer(
    cache_k: jax.Array,
    cache_v: jax.Array,
    layer: jax.Array | int,
    k_new: jax.Array,   # [B, S_new, H_kv, D]
    v_new: jax.Array,
    pos: jax.Array,     # scalar int32 write offset, or [B] per-slot offsets
    cache_ks: Optional[jax.Array] = None,   # [L, B, S_max, H_kv] f32
    cache_vs: Optional[jax.Array] = None,
):
    """Write k_new/v_new into layer `layer` at sequence offset `pos`.

    `pos` may be a vector of per-batch offsets (continuous-batching serving:
    every slot decodes at its own depth). Returns the updated full-stack
    arrays; under jit with donated inputs this lowers to in-place updates.

    With scale planes (`cache_ks`/`cache_vs`, int8/int4 storage) the new
    values are quantized on append — one absmax scale per written [D]
    vector, so unaligned offsets never disturb neighbouring tokens — and
    a 4-tuple (ck, cv, cks, cvs) is returned instead of (ck, cv).
    """
    scaled = cache_ks is not None
    if scaled:
        k_new, ks_new = quantize_kv(k_new, cache_k.dtype)
        v_new, vs_new = quantize_kv(v_new, cache_v.dtype)
    else:
        k_new = k_new.astype(cache_k.dtype)
        v_new = v_new.astype(cache_v.dtype)
    if getattr(pos, "ndim", 0) == 1:
        def write(c_b, n_b, p):           # [S,H,D], [S_new,H,D]
            return jax.lax.dynamic_update_slice(c_b, n_b, (p, 0, 0))

        def write2(c_b, n_b, p):          # [S,H], [S_new,H] scale planes
            return jax.lax.dynamic_update_slice(c_b, n_b, (p, 0))

        ck_l = jax.lax.dynamic_index_in_dim(cache_k, layer, 0, keepdims=False)
        cv_l = jax.lax.dynamic_index_in_dim(cache_v, layer, 0, keepdims=False)
        ck_l = jax.vmap(write)(ck_l, k_new, pos)
        cv_l = jax.vmap(write)(cv_l, v_new, pos)
        ck = jax.lax.dynamic_update_index_in_dim(cache_k, ck_l, layer, 0)
        cv = jax.lax.dynamic_update_index_in_dim(cache_v, cv_l, layer, 0)
        if not scaled:
            return ck, cv
        ks_l = jax.lax.dynamic_index_in_dim(cache_ks, layer, 0,
                                            keepdims=False)
        vs_l = jax.lax.dynamic_index_in_dim(cache_vs, layer, 0,
                                            keepdims=False)
        ks_l = jax.vmap(write2)(ks_l, ks_new, pos)
        vs_l = jax.vmap(write2)(vs_l, vs_new, pos)
        return (ck, cv,
                jax.lax.dynamic_update_index_in_dim(cache_ks, ks_l, layer, 0),
                jax.lax.dynamic_update_index_in_dim(cache_vs, vs_l, layer, 0))
    idx = (layer, 0, pos, 0, 0)
    ck = jax.lax.dynamic_update_slice(cache_k, k_new[None], idx)
    cv = jax.lax.dynamic_update_slice(cache_v, v_new[None], idx)
    if not scaled:
        return ck, cv
    sidx = (layer, 0, pos, 0)
    return (ck, cv,
            jax.lax.dynamic_update_slice(cache_ks, ks_new[None], sidx),
            jax.lax.dynamic_update_slice(cache_vs, vs_new[None], sidx))


def read_layer(
    cache_k: jax.Array,
    cache_v: jax.Array,
    layer: jax.Array | int,
    compute_dtype=jnp.bfloat16,
    cache_ks: Optional[jax.Array] = None,
    cache_vs: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Full-length K/V for one layer, upcast (and dequantized when scale
    planes are given) from storage dtype — the XLA fallback path. The
    fused kernels take codes + scales directly via `read_layer_quantized`."""
    k = jax.lax.dynamic_index_in_dim(cache_k, layer, 0, keepdims=False)
    v = jax.lax.dynamic_index_in_dim(cache_v, layer, 0, keepdims=False)
    if cache_ks is not None:
        ks = jax.lax.dynamic_index_in_dim(cache_ks, layer, 0, keepdims=False)
        vs = jax.lax.dynamic_index_in_dim(cache_vs, layer, 0, keepdims=False)
        return (dequantize_kv(k, ks, compute_dtype),
                dequantize_kv(v, vs, compute_dtype))
    return k.astype(compute_dtype), v.astype(compute_dtype)


def read_layer_quantized(
    cache_k: jax.Array,
    cache_v: jax.Array,
    cache_ks: jax.Array,
    cache_vs: jax.Array,
    layer: jax.Array | int,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """One layer's raw codes + scales (no dequantization) — feed these to
    `sdp_attention(.., k_scale=, v_scale=)` so the upcast happens inside
    the fused kernels."""
    k = jax.lax.dynamic_index_in_dim(cache_k, layer, 0, keepdims=False)
    v = jax.lax.dynamic_index_in_dim(cache_v, layer, 0, keepdims=False)
    ks = jax.lax.dynamic_index_in_dim(cache_ks, layer, 0, keepdims=False)
    vs = jax.lax.dynamic_index_in_dim(cache_vs, layer, 0, keepdims=False)
    return k, v, ks, vs


def _logical_nbytes(a: jax.Array) -> int:
    """Logical storage bytes: int4 packs two codes per byte (same
    convention as QTensor.nbytes in ops/quant.py)."""
    if jnp.dtype(a.dtype) == jnp.dtype(jnp.int4):
        return -(-a.size // 2)
    return a.size * jnp.dtype(a.dtype).itemsize


def kv_cache_nbytes(num_layers: int, batch: int, max_seq: int,
                    kv_heads: int, head_dim: int,
                    kv_cache_dtype: Optional[str] = None) -> Dict[str, int]:
    """Storage footprint of a WOULD-BE cache, computed from its
    geometry without allocating anything — byte-for-byte identical to
    ``kv_cache_bytes(init_cache(...))`` (the memory ledger and the
    engine's admission-cost estimate depend on that exactness; tests
    assert it). Same components: codes planes, scale planes, total."""
    name = resolve_kv_cache_dtype(kv_cache_dtype)
    dt = jnp.dtype(KV_CACHE_DTYPES[name])
    n = num_layers * batch * max_seq * kv_heads * head_dim
    if name == "int4":
        codes = 2 * (-(-n // 2))       # k + v, two codes per byte each
    else:
        codes = 2 * n * dt.itemsize
    scales = 0
    if name in SCALED_KV_DTYPES:
        scales = 2 * num_layers * batch * max_seq * kv_heads \
            * jnp.dtype(jnp.float32).itemsize
    return {"codes": codes, "scales": scales, "total": codes + scales}


def kv_cache_bytes(cache: KVCache) -> Dict[str, int]:
    """Storage footprint of a cache: codes planes, scale planes, total."""
    codes = _logical_nbytes(cache.k) + _logical_nbytes(cache.v)
    scales = 0
    if cache.k_scale is not None:
        scales = (_logical_nbytes(cache.k_scale)
                  + _logical_nbytes(cache.v_scale))
    return {"codes": codes, "scales": scales, "total": codes + scales}


def publish_kv_cache_bytes(cache: KVCache, registry=None) -> Dict[str, int]:
    """Set the `bigdl_tpu_kv_cache_bytes` gauge (labelled by cache dtype
    and component) from a cache's storage footprint. Best-effort: metric
    export never gates cache allocation."""
    sizes = kv_cache_bytes(cache)
    try:
        if registry is None:
            from bigdl_tpu.observability import default_registry
            registry = default_registry()
        g = registry.gauge(
            "bigdl_tpu_kv_cache_bytes",
            "KV cache storage bytes by dtype and component "
            "(codes | scales | total); int4 counted at two codes per byte",
            labelnames=("dtype", "component"))
        for comp, val in sizes.items():
            g.labels(cache.kv_dtype, comp).set(float(val))
    except Exception:
        pass
    return sizes
