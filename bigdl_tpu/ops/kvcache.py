"""Static-shape KV cache with optional FP8 storage.

TPU-native re-design of the reference's KV caching
(`DynamicNormalCache`/`DynamicFp8Cache`, reference transformers/kv.py:28-123,
and init/append/extend helpers in transformers/models/utils.py:38-153).

The reference grows its cache in 256-token blocks (realloc + copy) because
PyTorch tolerates dynamic shapes. Under XLA everything must be static: the
cache is **pre-allocated at max_seq_len** and appends are
`lax.dynamic_update_slice` writes at the current position — no realloc ever,
the jit-compiled decode step has one shape for its whole lifetime. Validity
is tracked by a scalar `pos`; attention masks keys at positions >= the
query's position + 1 (so garbage in the unwritten tail is never read).

FP8 ("quantize_kv_cache"): stores K/V as float8_e5m2 exactly like the
reference's scale-free e5m2 cache (models/utils.py:99-153), halving KV HBM
traffic; values are upcast at attention time and XLA fuses the cast into the
matmul operand read.

Layout: [num_layers, batch, max_seq, kv_heads, head_dim] — the whole stack is
one array per K/V so a `lax.scan` over layers can carry it and update layer
slices in place (donated buffers alias, so there is no copy in the hot loop).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class KVCache:
    k: jax.Array    # [L, B, S_max, H_kv, D]
    v: jax.Array    # [L, B, S_max, H_kv, D]
    pos: jax.Array  # scalar int32: number of valid positions

    def tree_flatten(self):
        return (self.k, self.v, self.pos), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def max_seq(self) -> int:
        return self.k.shape[2]

    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    def reset_pos(self, pos) -> "KVCache":
        """Same buffers, new validity pointer (generation pad repair /
        speculative rollback)."""
        return KVCache(self.k, self.v, pos)


def init_cache(
    num_layers: int,
    batch: int,
    max_seq: int,
    kv_heads: int,
    head_dim: int,
    dtype=jnp.bfloat16,
    quantized: bool = False,
    per_slot_pos: bool = False,
) -> KVCache:
    """Allocate an empty cache. quantized=True stores float8_e5m2.

    per_slot_pos=True gives every batch row its own position counter —
    the continuous-batching layout (each serving slot decodes at its own
    depth, the capability the reference's vLLM port builds from per-seq
    KV dicts, vllm/model_executor/models/bigdl_model.py:88-139)."""
    dt = jnp.float8_e5m2 if quantized else dtype
    shape = (num_layers, batch, max_seq, kv_heads, head_dim)
    return KVCache(
        k=jnp.zeros(shape, dt),
        v=jnp.zeros(shape, dt),
        pos=(jnp.zeros((batch,), jnp.int32) if per_slot_pos
             else jnp.zeros((), jnp.int32)),
    )


def update_layer(
    cache_k: jax.Array,
    cache_v: jax.Array,
    layer: jax.Array | int,
    k_new: jax.Array,   # [B, S_new, H_kv, D]
    v_new: jax.Array,
    pos: jax.Array,     # scalar int32 write offset, or [B] per-slot offsets
) -> Tuple[jax.Array, jax.Array]:
    """Write k_new/v_new into layer `layer` at sequence offset `pos`.

    `pos` may be a vector of per-batch offsets (continuous-batching serving:
    every slot decodes at its own depth). Returns the updated full-stack
    arrays; under jit with donated inputs this lowers to in-place updates.
    """
    k_new = k_new.astype(cache_k.dtype)
    v_new = v_new.astype(cache_v.dtype)
    if getattr(pos, "ndim", 0) == 1:
        def write(c_b, n_b, p):           # [S,H,D], [S_new,H,D]
            return jax.lax.dynamic_update_slice(c_b, n_b, (p, 0, 0))

        ck_l = jax.lax.dynamic_index_in_dim(cache_k, layer, 0, keepdims=False)
        cv_l = jax.lax.dynamic_index_in_dim(cache_v, layer, 0, keepdims=False)
        ck_l = jax.vmap(write)(ck_l, k_new, pos)
        cv_l = jax.vmap(write)(cv_l, v_new, pos)
        return (
            jax.lax.dynamic_update_index_in_dim(cache_k, ck_l, layer, 0),
            jax.lax.dynamic_update_index_in_dim(cache_v, cv_l, layer, 0),
        )
    idx = (layer, 0, pos, 0, 0)
    return (
        jax.lax.dynamic_update_slice(cache_k, k_new[None], idx),
        jax.lax.dynamic_update_slice(cache_v, v_new[None], idx),
    )


def read_layer(
    cache_k: jax.Array,
    cache_v: jax.Array,
    layer: jax.Array | int,
    compute_dtype=jnp.bfloat16,
) -> Tuple[jax.Array, jax.Array]:
    """Full-length K/V for one layer, upcast from storage dtype."""
    k = jax.lax.dynamic_index_in_dim(cache_k, layer, 0, keepdims=False)
    v = jax.lax.dynamic_index_in_dim(cache_v, layer, 0, keepdims=False)
    return k.astype(compute_dtype), v.astype(compute_dtype)
