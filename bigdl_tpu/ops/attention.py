"""Scaled-dot-product attention over the static KV cache.

TPU-native equivalent of the reference's attention dispatch surface: the
prefill flash/native_sdp paths and the decode `sdp_fp8`/ESIMD `sdp_forward`
kernels (reference transformers/models/llama.py:1320-1349, models/utils.py:
315-355 gates, and the SYCL ops inventoried in SURVEY.md §2.3-C/D).

One function serves prefill and decode: queries carry explicit positions, so
causal masking and cache-tail masking collapse into a single comparison —
no separate mask tensors, no dynamic shapes, garbage in the unwritten cache
tail is masked because key_pos > query_pos there. GQA is computed by
reshaping queries to [.., kv_heads, group, ..] (no KV head replication, which
would multiply HBM traffic by the group size).

FP8 KV: pass e5m2 k/v straight in — the upcast happens inside and XLA fuses
it into the QK/AV matmul operand reads (the reference needs dedicated
`query_key_fp8_matmul` kernels for this; XLA gets it from fusion).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


# error signatures that mean the KERNEL cannot lower for this geometry
# (cache False forever) — anything else is presumed transient (wedged
# tunnel, RPC timeout: retried on the next call, at most once per
# _TRANSIENT_RETRIES, then treated as permanent for the process)
_COMPILE_ERROR_MARKERS = ("mosaic", "lowering", "unsupported",
                          "not implemented", "notimplemented",
                          "unimplemented", "invalid_argument")
_TRANSIENT_RETRIES = 3
_probe_cache: dict = {}
_probe_fail_counts: dict = {}


def reset_probe_cache() -> None:
    """Forget all kernel-compile probe results (e.g. after a backend
    outage, or when flipping `flags().attention_backend`).

    Also drops jit executable caches: a probe verdict is baked into any
    executable traced while it held, so clearing only the probe dict
    would leave already-compiled shapes on their old path."""
    _probe_cache.clear()
    _probe_fail_counts.clear()
    jax.clear_caches()


def _note_dequant_path(kv_dtype_name: str, path: str) -> None:
    """Count which dequant path a quantized-KV attention dispatch took
    ("fused" Pallas kernel vs "xla" fallback). Trace-time counts: each
    (shape, dtype) combination increments once per trace, not once per
    executed step — enough to tell WHICH path a deployment is on.
    Best-effort; metrics never gate dispatch."""
    try:
        from bigdl_tpu.observability import default_registry

        default_registry().counter(
            "bigdl_tpu_kv_dequant_path_total",
            "KV-cache dequantization dispatches by storage dtype and "
            "path (fused kernel vs XLA fallback); trace-time counts",
            labelnames=("dtype", "path")).labels(kv_dtype_name, path).inc()
    except Exception:
        pass


def _kernel_compiles(kind: str, h: int, hkv: int, hd: int, sq: int,
                     skv: int, kv_dtype_name: str) -> bool:
    """Eager probe, cached PER GEOMETRY: does the Pallas kernel compile
    for this attention shape? Mosaic failures can be shape-dependent, and
    a failure inside a model's outer jit is uncatchable — so the probe
    runs the geometry as a tiny concrete call OUTSIDE any trace. Auto
    mode consults this; pallas mode bypasses it so forced runs still
    raise their real error. Callers normalize `sq` to the kernel's block
    class (prefill lengths vary per request; every class needs only one
    probe compile). Genuine compile failures pin the geometry to XLA;
    transient backend failures are retried (reset_probe_cache() clears
    everything)."""
    from bigdl_tpu.config import flags as _flags

    if _flags().aot_target == "tpu":
        # AOT lowering for a topology: nothing can execute — trust the
        # dispatch and let Mosaic rejections surface at .compile()
        return True
    key = (kind, h, hkv, hd, sq, skv, kv_dtype_name)
    hit = _probe_cache.get(key)
    if hit is not None:
        return hit
    try:
        if kind == "decode":
            from bigdl_tpu.ops.pallas.decode_attention import (
                decode_attention_pallas as kernel)
        elif kind == "paged_decode":
            from bigdl_tpu.ops.pallas.paged_decode_attention import (
                paged_decode_attention_pallas as kernel)
        else:
            from bigdl_tpu.ops.pallas.prefill_attention import (
                prefill_attention_pallas as kernel)
        from bigdl_tpu.ops.probing import (probe_compile,
                                           record_probe_result)

        # The probe is usually reached while TRACING a model's outer jit;
        # compile-only AOT probing (see ops/probing.py) never executes,
        # never allocates device buffers, and never touches the ambient
        # trace — a concrete call here used to die on live TPUs with
        # "Evaluation rule for 'program_id' not implemented".
        kdt = jnp.dtype(kv_dtype_name)
        if kind == "paged_decode":
            # paged probe overloads the key slots: sq carries page_size,
            # skv carries the block-table width (logical pages)
            ps, np_ = sq, skv
            arena = jax.ShapeDtypeStruct((np_ + 1, ps, hkv, hd), kdt)
            bt = jax.ShapeDtypeStruct((1, np_), jnp.int32)
            pos = jax.ShapeDtypeStruct((1,), jnp.int32)
            qq = jax.ShapeDtypeStruct((1, 1, h, hd), jnp.bfloat16)
            if kv_dtype_name in ("int8", "int4"):
                sc = jax.ShapeDtypeStruct((np_ + 1, ps, hkv), jnp.float32)
                probe_compile(
                    lambda q_, k_, v_, b_, p_, ks, vs: kernel(
                        q_, k_, v_, b_, p_, hd ** -0.5,
                        k_scale=ks, v_scale=vs),
                    qq, arena, arena, bt, pos, sc, sc)
            else:
                probe_compile(
                    lambda q_, k_, v_, b_, p_: kernel(
                        q_, k_, v_, b_, p_, hd ** -0.5),
                    qq, arena, arena, bt, pos)
            _probe_cache[key] = True
            record_probe_result("paged_decode_attention", True)
            return True
        if kv_dtype_name in ("int8", "int4"):
            # block-scaled codes probe with their f32 scale planes — the
            # scaled kernel bodies are distinct Mosaic programs
            probe_compile(
                lambda qq, kk, vv, pp, ks, vs: kernel(
                    qq, kk, vv, pp, hd ** -0.5, k_scale=ks, v_scale=vs),
                jax.ShapeDtypeStruct((1, sq, h, hd), jnp.bfloat16),
                jax.ShapeDtypeStruct((1, skv, hkv, hd), kdt),
                jax.ShapeDtypeStruct((1, skv, hkv, hd), kdt),
                jax.ShapeDtypeStruct((), jnp.int32),
                jax.ShapeDtypeStruct((1, skv, hkv), jnp.float32),
                jax.ShapeDtypeStruct((1, skv, hkv), jnp.float32))
        else:
            probe_compile(
                lambda qq, kk, vv, pp: kernel(qq, kk, vv, pp, hd ** -0.5),
                jax.ShapeDtypeStruct((1, sq, h, hd), jnp.bfloat16),
                jax.ShapeDtypeStruct((1, skv, hkv, hd), kdt),
                jax.ShapeDtypeStruct((1, skv, hkv, hd), kdt),
                jax.ShapeDtypeStruct((), jnp.int32))
        _probe_cache[key] = True
        record_probe_result(f"{kind}_attention", True)
        return True
    except Exception as e:
        import logging

        from bigdl_tpu.ops.probing import record_probe_result

        record_probe_result(f"{kind}_attention", False)
        msg = f"{type(e).__name__}: {e}".lower()
        permanent = any(mk in msg for mk in _COMPILE_ERROR_MARKERS)
        if not permanent:
            n = _probe_fail_counts.get(key, 0) + 1
            _probe_fail_counts[key] = n
            permanent = n >= _TRANSIENT_RETRIES
        if permanent:
            _probe_cache[key] = False
        logging.getLogger(__name__).warning(
            "pallas %s-attention kernel unavailable for shape "
            "(H=%d, Hkv=%d, hd=%d, Sq=%d, Skv=%d, %s) — %s: %s; using "
            "the XLA path%s", kind, h, hkv, hd, sq, skv, kv_dtype_name,
            type(e).__name__, e,
            "" if permanent else
            " (transient — re-probed on later traces; call "
            "reset_probe_cache() after the outage to re-trace "
            "already-compiled shapes)")
        return False


def sdp_attention(
    q: jax.Array,          # [B, Sq, H, D] (post-RoPE)
    k: jax.Array,          # [B, Skv, Hkv, D] (cache slice; any storage dtype)
    v: jax.Array,          # [B, Skv, Hkv, D]
    q_pos: jax.Array,      # scalar int32: absolute position of q[..., 0, ...]
    scale: Optional[float] = None,
    logits_soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
    alibi_slopes: Optional[jax.Array] = None,   # [H] f32 (bloom families)
    backend: Optional[str] = None,   # overrides flags().attention_backend
    k_scale: Optional[jax.Array] = None,   # [B, Skv, Hkv] f32: int8/int4
    v_scale: Optional[jax.Array] = None,   # codes' per-(token, head) scales
) -> jax.Array:
    """Causal SDP against a (possibly partially-filled) KV cache.

    Query i attends keys j where j <= q_pos + i (and within the sliding
    window if set). Returns [B, Sq, H, D] in q.dtype. Softmax in f32.

    Decode (Sq=1) on TPU dispatches to the fused Pallas kernel
    (ops/pallas/decode_attention — the reference's `sdp_fp8`/ESIMD
    `sdp_forward` equivalent) unless BIGDL_TPU_ATTENTION_BACKEND=xla.

    Block-scaled KV (kv_cache_dtype int8/int4): pass the raw code planes
    as k/v plus their scale planes — the kernels dequantize in-register;
    the XLA fallback upcasts codes * scales before the einsums.
    """
    b, sq, h, d = q.shape
    skv, hkv = k.shape[1], k.shape[2]
    g = h // hkv
    if scale is None:
        scale = d ** -0.5
    quant_name = (str(k.dtype)
                  if k.dtype not in (jnp.bfloat16, jnp.float16, jnp.float32)
                  else None)

    from bigdl_tpu.config import flags, target_is_tpu, under_spmd

    be = backend or flags().attention_backend
    if be in ("auto", "pallas") and under_spmd(q, k, v):
        # GSPMD cannot auto-partition Mosaic kernels (hard compile
        # error); sharded programs take the XLA ops, which partition
        # cleanly — explicitly shard_mapped paths (parallel/sp, cp)
        # still reach the kernels with local shapes
        be = "xla" if be == "auto" else be
    if be in ("auto", "pallas"):
        from bigdl_tpu.ops.pallas.decode_attention import (
            decode_attention_pallas, decode_attention_supported)

        supported = decode_attention_supported(
            q, k, v, q_pos, scale, logits_soft_cap, sliding_window,
            alibi_slopes, k_scale)
        on_tpu = target_is_tpu()
        if supported and be == "pallas":
            if quant_name:
                _note_dequant_path(quant_name, "fused")
            return decode_attention_pallas(q, k, v, q_pos, float(scale),
                                           interpret=not on_tpu,
                                           k_scale=k_scale, v_scale=v_scale)
        if supported and on_tpu and _kernel_compiles(
                "decode", h, hkv, d, 1, skv, str(k.dtype)):
            if quant_name:
                _note_dequant_path(quant_name, "fused")
            return decode_attention_pallas(q, k, v, q_pos, float(scale),
                                           k_scale=k_scale, v_scale=v_scale)

        from bigdl_tpu.ops.pallas.prefill_attention import (
            prefill_attention_pallas, prefill_attention_supported)

        # blockwise prefill (flash): scores never touch HBM — the win
        # grows with S * S_max (the pre-allocated cache is read once);
        # scalar positions only (serving prefills per slot at Sq=1)
        pre_ok = (getattr(q_pos, "ndim", 0) == 0
                  and prefill_attention_supported(
                      q, k, v, q_pos, scale, logits_soft_cap,
                      sliding_window, alibi_slopes, k_scale))
        if pre_ok and be == "pallas":
            if quant_name:
                _note_dequant_path(quant_name, "fused")
            return prefill_attention_pallas(q, k, v, q_pos, float(scale),
                                            interpret=not on_tpu,
                                            k_scale=k_scale, v_scale=v_scale)
        # probe once per BLOCK CLASS of sq (256-aligned vs 128-aligned),
        # not per exact prompt length
        probe_sq = 256 if sq % 256 == 0 else 128
        if pre_ok and on_tpu and _kernel_compiles(
                "prefill", h, hkv, d, probe_sq, skv, str(k.dtype)):
            if quant_name:
                _note_dequant_path(quant_name, "fused")
            return prefill_attention_pallas(q, k, v, q_pos, float(scale),
                                            k_scale=k_scale, v_scale=v_scale)

    if quant_name:
        _note_dequant_path(quant_name, "xla")
    qf = q.reshape(b, sq, hkv, g, d).astype(jnp.bfloat16)
    if k_scale is not None:
        # dequant in f32 (a bf16 scale multiply would round the scales)
        kf = (k.astype(jnp.float32)
              * k_scale[..., None].astype(jnp.float32)).astype(jnp.bfloat16)
        vf = (v.astype(jnp.float32)
              * v_scale[..., None].astype(jnp.float32)).astype(jnp.bfloat16)
    else:
        kf = k.astype(jnp.bfloat16)
        vf = v.astype(jnp.bfloat16)

    # [B, Hkv, G, Sq, Skv]
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", qf, kf,
                        preferred_element_type=jnp.float32)
    scores = scores * scale
    if alibi_slopes is not None:
        # bias slopes[h] * k_pos; per-query-row constants cancel in softmax,
        # so keying on absolute key position is the standard causal form
        sl = alibi_slopes.reshape(hkv, g).astype(jnp.float32)
        kpos = jnp.arange(skv, dtype=jnp.float32)
        scores = scores + sl[None, :, :, None, None] * kpos[None, None, None, None, :]
    if logits_soft_cap is not None:
        scores = jnp.tanh(scores / logits_soft_cap) * logits_soft_cap

    k_ids = jnp.arange(skv, dtype=jnp.int32)                 # [Skv]
    if getattr(q_pos, "ndim", 0) == 1:
        # per-slot positions (continuous batching): [B, Sq, Skv] mask
        q_ids = q_pos[:, None] + jnp.arange(sq, dtype=jnp.int32)[None, :]
        mask = k_ids[None, None, :] <= q_ids[:, :, None]
        if sliding_window is not None:
            mask &= k_ids[None, None, :] > q_ids[:, :, None] - sliding_window
        # [B, Skv->k, Sq->q] -> broadcast over (Hkv, G): [B,1,1,Sq,Skv]
        scores = jnp.where(mask[:, None, None, :, :], scores, -jnp.inf)
    else:
        q_ids = q_pos + jnp.arange(sq, dtype=jnp.int32)      # [Sq]
        mask = k_ids[None, :] <= q_ids[:, None]              # [Sq, Skv]
        if sliding_window is not None:
            mask &= k_ids[None, :] > q_ids[:, None] - sliding_window
        scores = jnp.where(mask[None, None, None], scores, -jnp.inf)

    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs.astype(jnp.bfloat16), vf,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, sq, h, d).astype(q.dtype)


def sdp_attention_paged(
    q: jax.Array,             # [B, Sq, H, D] (post-RoPE)
    arena_k: jax.Array,       # [P, ps, Hkv, D] one layer's page arena
    arena_v: jax.Array,
    block_tables: jax.Array,  # [B, NP] int32 (0 = null page)
    q_pos: jax.Array,         # [B] int32 per-slot positions
    scale: Optional[float] = None,
    logits_soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
    alibi_slopes: Optional[jax.Array] = None,
    backend: Optional[str] = None,
    k_scale: Optional[jax.Array] = None,   # [P, ps, Hkv] f32 arena scales
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Causal SDP reading K/V through a block table (paged cache).

    Decode (Sq=1) on TPU dispatches to the paged Pallas kernel, whose
    BlockSpec index_maps dereference the prefetched block table — the
    gather never materializes a dense copy. Everywhere else the fallback
    the ISSUE names runs: an XLA ``take`` over the table reassembles the
    dense ``[B, NP * ps, Hkv, D]`` view (shape-identical to the slab
    read, ``NP * ps == max_seq``) and the regular `sdp_attention`
    dispatch finishes the job — so paged decode is byte-identical to
    slab decode wherever both take the XLA path, and the slab decode
    kernel still serves gathered views on TPU when the paged kernel
    cannot lower."""
    b, sq, h, d = q.shape
    ps, hkv = arena_k.shape[1], arena_k.shape[2]
    if scale is None:
        scale = d ** -0.5
    quant_name = (str(arena_k.dtype)
                  if arena_k.dtype not in (jnp.bfloat16, jnp.float16,
                                           jnp.float32)
                  else None)

    from bigdl_tpu.config import flags, target_is_tpu, under_spmd

    be = backend or flags().attention_backend
    if be in ("auto", "pallas") and under_spmd(q, arena_k, arena_v):
        be = "xla" if be == "auto" else be
    if be in ("auto", "pallas"):
        from bigdl_tpu.ops.pallas.paged_decode_attention import (
            paged_decode_attention_pallas, paged_decode_attention_supported)

        supported = paged_decode_attention_supported(
            q, arena_k, logits_soft_cap, sliding_window, alibi_slopes,
            k_scale)
        on_tpu = target_is_tpu()
        if supported and be == "pallas":
            if quant_name:
                _note_dequant_path(quant_name, "fused")
            return paged_decode_attention_pallas(
                q, arena_k, arena_v, block_tables, q_pos, float(scale),
                interpret=not on_tpu, k_scale=k_scale, v_scale=v_scale)
        if supported and on_tpu and _kernel_compiles(
                "paged_decode", h, hkv, d, ps, block_tables.shape[1],
                str(arena_k.dtype)):
            if quant_name:
                _note_dequant_path(quant_name, "fused")
            return paged_decode_attention_pallas(
                q, arena_k, arena_v, block_tables, q_pos, float(scale),
                k_scale=k_scale, v_scale=v_scale)

    from bigdl_tpu.ops.paged import _gather_dense

    kd = _gather_dense(arena_k, block_tables)
    vd = _gather_dense(arena_v, block_tables)
    ksd = vsd = None
    if k_scale is not None:
        ksd = _gather_dense(k_scale, block_tables)
        vsd = _gather_dense(v_scale, block_tables)
    return sdp_attention(q, kd, vd, q_pos, scale=scale,
                         logits_soft_cap=logits_soft_cap,
                         sliding_window=sliding_window,
                         alibi_slopes=alibi_slopes, backend=backend,
                         k_scale=ksd, v_scale=vsd)
