"""ggml IQ-format constant grids: loading, validation, and what's derivable.

The reference accepts community GGUF checkpoints in ggml's IQ2_XXS /
IQ2_XS / IQ1_S formats (qtype names at /root/reference/python/llm/src/
ipex_llm/ggml/quantize.py:43-47; the kernels live in prebuilt binaries).
Those formats quantize groups of 8 weights to an entry of a fixed
magnitude grid plus signs. Everything about the formats EXCEPT the grids
is closed-form and implemented bit-exactly in bigdl_tpu.gguf:

- block layouts (66 / 74 / 50 bytes per 256 values),
- the sign table: ksigns[i] = i | (parity(i) << 7) — the 8th sign bit is
  the parity of the 7 stored ones (derived, tested),
- scale packing: d * (0.5 + nibble) * 0.25 (iq2), d * (2*s+1) (iq1_s),
- the IQ1_S delta (+-0.125 shift applied to every value in a group).

The grids themselves — iq2xxs_grid[256], iq2xs_grid[512] (uint64, one
byte per element, magnitudes in {8, 25, 43, 62}) and iq1s_grid[2048]
(signed ternary) — are NOT derivable: they are the output of an offline
clustering run over calibration data in upstream llama.cpp. The E8
lattice constrains the CANDIDATE set (for iq2: 8 odd-half-integer
coordinates with even k-sum -> 4^8/2 = 32768 valid patterns; see
`e8_candidate_count`), but which 256/512/2048 of those made the table is
calibration output, not mathematics. Full analysis in PARITY.md.

So the grids are pluggable: point BIGDL_TPU_IQ_GRID_SOURCE at
 - a llama.cpp checkout (or its `ggml-common.h`): the tables are parsed
   straight out of the source, or
 - an .npz with arrays iq2xxs_grid/iq2xs_grid/iq1s_grid.
`save_grids_npz` re-exports parsed tables for dependency-free reuse.
Without a source, importing an IQ GGUF raises with these instructions
(a wrong grid would silently decode a different model — refusing is the
only honest default).
"""

from __future__ import annotations

import os
import re
from functools import lru_cache
from typing import Dict, Optional

import numpy as np

ENV_VAR = "BIGDL_TPU_IQ_GRID_SOURCE"

# expected table sizes (entries of 8 grouped values each)
GRID_SPECS = {
    "iq2xxs_grid": 256,
    "iq2xs_grid": 512,
    "iq1s_grid": 2048,
}

# iq2 grid bytes take one of these four magnitudes
IQ2_MAGNITUDES = frozenset({8, 25, 43, 62})


def ksigns() -> np.ndarray:
    """ggml's ksigns_iq2xs[128], derived: low 7 bits = index, bit 7 =
    parity of those bits (total sign popcount is always even)."""
    i = np.arange(128, dtype=np.uint16)
    par = i.copy()
    par ^= par >> 4
    par ^= par >> 2
    par ^= par >> 1
    return (i | ((par & 1) << 7)).astype(np.uint8)


def signs_from_index(idx: np.ndarray) -> np.ndarray:
    """[..., 8] array of +-1 from 7-bit sign indices (8th bit = parity)."""
    full = ksigns()[np.asarray(idx, np.int64)]
    bits = (full[..., None] >> np.arange(8, dtype=np.uint8)) & 1
    return np.where(bits.astype(bool), -1.0, 1.0).astype(np.float32)


def e8_candidate_count() -> int:
    """Size of the E8-constrained candidate set the iq2 grids were chosen
    from: 8 coordinates, each an odd half-integer (2k+1)/2 with k in
    0..3, restricted to even sum(k) (the all-half-integer E8 coset).
    4^8 / 2 — documented evidence the table is a strict, data-chosen
    subset, not the whole lattice shell."""
    return 4 ** 8 // 2


# ------------------------------------------------------------------ loading

# legacy form: `static const uint64_t iq2xxs_grid[256] = { ... };`
_C_TABLE = re.compile(
    r"(iq2xxs_grid|iq2xs_grid|iq1s_grid)\s*\[\s*\d*\s*\]\s*=\s*\{(.*?)\}",
    re.DOTALL)
# modern ggml-common.h form:
# `GGML_TABLE_BEGIN(uint64_t, iq2xxs_grid, 256) ... GGML_TABLE_END()`
_C_TABLE_MACRO = re.compile(
    r"GGML_TABLE_BEGIN\s*\(\s*\w+\s*,\s*"
    r"(iq2xxs_grid|iq2xs_grid|iq1s_grid)\s*,\s*\d+\s*\)"
    r"(.*?)GGML_TABLE_END\s*\(\s*\)",
    re.DOTALL)
_HEX = re.compile(r"0x[0-9a-fA-F]+|\d+")


def parse_c_tables(text: str) -> Dict[str, np.ndarray]:
    """Extract the grid tables from llama.cpp C source (ggml-common.h,
    both the GGML_TABLE_BEGIN macro form and the legacy `= { ... }`
    form). Returns {name: uint64 [N]} for each table found with the
    full expected entry count."""
    out: Dict[str, np.ndarray] = {}
    for pat in (_C_TABLE, _C_TABLE_MACRO):
        for m in pat.finditer(text):
            name, body = m.group(1), m.group(2)
            vals = [int(tok, 0) for tok in _HEX.findall(body)]
            if len(vals) == GRID_SPECS[name]:
                out[name] = np.asarray(vals, np.uint64)
    return out


def _find_source_file(path: str) -> Optional[str]:
    if os.path.isfile(path):
        return path
    if os.path.isdir(path):
        for root, _dirs, files in os.walk(path):
            for f in ("ggml-common.h", "ggml-quants.c"):
                if f in files:
                    return os.path.join(root, f)
    return None


def unpack_iq2_grid(packed: np.ndarray) -> np.ndarray:
    """uint64 [N] -> float32 [N, 8] magnitudes (little-endian bytes)."""
    b = np.asarray(packed, np.uint64)[:, None] >> (
        np.arange(8, dtype=np.uint64) * np.uint64(8))
    return (b & np.uint64(0xFF)).astype(np.float32)


def unpack_iq1_grid(packed: np.ndarray) -> np.ndarray:
    """uint64 [N] -> float32 [N, 8] in {-1, 0, +1}.

    ggml packs iq1s_grid entries as 8 bytes of {0x00, 0x01, 0xff}
    (int8 -1/0/+1)."""
    b = np.asarray(packed, np.uint64)[:, None] >> (
        np.arange(8, dtype=np.uint64) * np.uint64(8))
    raw = (b & np.uint64(0xFF)).astype(np.uint8).astype(np.int8)
    return raw.astype(np.float32)


def validate_grids(grids: Dict[str, np.ndarray]) -> None:
    for name, packed in grids.items():
        n = GRID_SPECS[name]
        if packed.shape != (n,):
            raise ValueError(f"{name}: expected [{n}] uint64, "
                             f"got {packed.shape}")
        if name.startswith("iq2"):
            mags = unpack_iq2_grid(packed)
            bad = set(np.unique(mags).astype(int)) - set(IQ2_MAGNITUDES)
            if bad:
                raise ValueError(
                    f"{name}: magnitudes {sorted(bad)} outside the ggml "
                    f"set {sorted(IQ2_MAGNITUDES)} — not a ggml iq2 grid")
        else:
            vals = unpack_iq1_grid(packed)
            bad = set(np.unique(vals).astype(int)) - {-1, 0, 1}
            if bad:
                raise ValueError(
                    f"{name}: values {sorted(bad)} not ternary — not a "
                    "ggml iq1s grid")


@lru_cache(maxsize=1)
def load_grids() -> Optional[Dict[str, np.ndarray]]:
    """The ggml IQ grids from BIGDL_TPU_IQ_GRID_SOURCE, or None.

    Accepts a .npz (arrays named per GRID_SPECS), a C source file, or a
    directory to search (e.g. a llama.cpp checkout)."""
    src = os.environ.get(ENV_VAR)
    if not src:
        return None
    if src.endswith(".npz"):
        with np.load(src) as z:
            grids = {k: np.asarray(z[k], np.uint64) for k in z.files
                     if k in GRID_SPECS}
    else:
        f = _find_source_file(src)
        if f is None:
            raise FileNotFoundError(
                f"{ENV_VAR}={src!r}: no ggml-common.h/ggml-quants.c found")
        with open(f, errors="replace") as fh:
            grids = parse_c_tables(fh.read())
    if not grids:
        raise ValueError(f"{ENV_VAR}={src!r}: no IQ grid tables found")
    validate_grids(grids)
    return grids


def save_grids_npz(path: str) -> None:
    grids = load_grids()
    if grids is None:
        raise RuntimeError(f"set {ENV_VAR} first")
    np.savez(path, **grids)


def require_grid(name: str) -> np.ndarray:
    """[N, 8] float32 decode table for one grid, or a clear error."""
    grids = load_grids()
    if grids is None or name not in grids:
        raise RuntimeError(
            f"importing this GGUF needs ggml's {name} constant table, "
            "which is calibration output that cannot be derived offline "
            f"(see bigdl_tpu/ops/iq_grids.py). Set {ENV_VAR} to a "
            "llama.cpp checkout, its ggml-common.h, or an .npz dump; "
            "save_grids_npz() can re-export it for reuse.")
    packed = grids[name]
    if name.startswith("iq2"):
        return unpack_iq2_grid(packed)
    return unpack_iq1_grid(packed)
