"""Ring attention: exact causal attention over a sequence-parallel mesh axis.

The reference has NO sequence/context parallelism (SURVEY.md §2.2: its
long-context story is FP8 KV + per-model 32k variants, all single-device).
This is the planned superset capability: shard the sequence over the `sp`
mesh axis, keep Q local, and rotate K/V chunks around the ring with
`lax.ppermute` while accumulating flash-style online softmax — peak memory
per chip is O(S/sp), communication rides ICI and overlaps with the chunk
matmuls (XLA schedules the ppermute DMA concurrently with compute).

Two layers:
- `ring_attention(q, k, v, axis_name)` — call INSIDE `shard_map` over a
  mesh with `axis_name`; q/k/v are the local sequence chunks.
- `sp_attention(q, k, v, mesh, axis)` — convenience wrapper that shard_maps
  over full arrays.

Math: online softmax accumulation in f32 (m: running row max, l: running
normalizer, o: unnormalized output), causal mask computed from *global*
positions (chunk index x chunk length + local offset). Matches
`sdp_attention` to float tolerance, verified in tests on the CPU mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:                                    # jax >= 0.5
    from jax import shard_map as _shard_map
    _REP_KW = {"check_vma": False}
except ImportError:                     # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map
    _REP_KW = {"check_rep": False}


def _chunk_scores(q, k, scale, logits_soft_cap):
    # q [B, Sq, Hkv, G, D], k [B, Sk, Hkv, D] -> [B, Hkv, G, Sq, Sk] f32
    s = jnp.einsum("bqhgd,bkhd->bhgqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if logits_soft_cap is not None:
        s = jnp.tanh(s / logits_soft_cap) * logits_soft_cap
    return s


def ring_attention(
    q: jax.Array,          # [B, Sq_loc, H, D] local query chunk
    k: jax.Array,          # [B, Sk_loc, Hkv, D] local key chunk
    v: jax.Array,          # [B, Sk_loc, Hkv, D]
    axis_name: str,
    scale: Optional[float] = None,
    logits_soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
    layout: str = "contiguous",
) -> jax.Array:
    """Exact causal attention with K/V rotating around `axis_name`.

    Sequence layouts across the axis (n devices, chunk length C):
    - "contiguous": device i holds global positions [i*C, (i+1)*C) —
      the training sp layout.
    - "cyclic": device i holds positions i, i+n, i+2n, ... — the
      context-parallel INFERENCE layout (parallel/cp.py), where decode
      tokens keep landing on rotating owners so the sharded KV cache
      stays balanced at any prompt length.
    Returns [B, Sq_loc, H, D].
    """
    b, sq, h, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    g = h // hkv
    if scale is None:
        scale = d ** -0.5
    if layout not in ("contiguous", "cyclic"):
        raise ValueError(f"unknown ring layout {layout!r}")

    p = lax.axis_index(axis_name)
    n = lax.psum(1, axis_name)

    def global_ids(dev, length):
        if layout == "contiguous":
            return dev * length + jnp.arange(length, dtype=jnp.int32)
        return dev + jnp.arange(length, dtype=jnp.int32) * n

    qf = q.reshape(b, sq, hkv, g, d).astype(jnp.bfloat16)
    q_ids = global_ids(p, sq)                               # global q pos

    # Q-blocking inside each ring step: the per-step scores are
    # [B, Hkv, G, bq, Sk] — unblocked (bq = Sq) a 32k/sp=4 llama-7B
    # prefill materialized an 8.6 GB f32 score tensor per step and blew
    # past one v5e's HBM. Long local chunks process Q in sub-blocks
    # under lax.map (sequential; buffers reuse), bounding the working
    # set at ~bq x Sk while keeping the math identical (each q row's
    # online-softmax state is independent of other rows). The carry and
    # loop-invariant q blocks live in block-major layout for the whole
    # ring loop — ONE transpose in, one out.
    bq = sq
    if sq > 1024:
        # largest divisor of sq <= 1024 (not just powers of two: a
        # non-128-multiple local chunk must still block, or the OOM
        # this exists to prevent comes back for exactly those shapes)
        for cand in range(1024, 1, -1):
            if sq % cand == 0:
                bq = cand
                break
    nb = sq // bq

    # block-major: [nb, B, ...(bq)...]
    qf_bk = jnp.moveaxis(qf.reshape(b, nb, bq, hkv, g, d), 1, 0)
    ids_bk = q_ids.reshape(nb, bq)
    o0 = jnp.zeros((nb, b, hkv, g, bq, d), jnp.float32)
    m0 = jnp.full((nb, b, hkv, g, bq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((nb, b, hkv, g, bq), jnp.float32)
    # the loop body makes these device-varying (they depend on axis_index);
    # mark the initial values accordingly for shard_map's vma tracking.
    # jax < 0.5 has no lax.pcast and no vma tracking (its shard_map runs
    # with check_rep=False, see parallel/cp.py _REP_KW): skip the cast.
    _pcast = getattr(lax, "pcast", None)
    if _pcast is not None:
        o0, m0, l0 = (_pcast(x, (axis_name,), to="varying")
                      for x in (o0, m0, l0))

    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(i, carry):
        o, m, l, k_cur, v_cur = carry
        src = (p - i) % n                                   # chunk we hold
        k_ids = global_ids(src, sk)
        kb = k_cur.astype(jnp.bfloat16)
        vb = v_cur.astype(jnp.bfloat16)

        def one_block(xs):
            qf_b, o_b, m_b, l_b, qid_b = xs
            s = _chunk_scores(qf_b, kb, scale,
                              logits_soft_cap)          # [B,Hkv,G,bq,Sk]
            mask = k_ids[None, :] <= qid_b[:, None]     # [bq, Sk]
            if sliding_window is not None:
                mask &= k_ids[None, :] > qid_b[:, None] - sliding_window
            s = jnp.where(mask[None, None, None], s, -jnp.inf)

            m_new = jnp.maximum(m_b, jnp.max(s, axis=-1))
            # fully-masked rows keep m == -inf; guard exp against NaN
            alpha = jnp.where(jnp.isfinite(m_b), jnp.exp(m_b - m_new), 0.0)
            pexp = jnp.exp(s - m_new[..., None])
            pexp = jnp.where(jnp.isfinite(s), pexp, 0.0)
            l_new = l_b * alpha + jnp.sum(pexp, axis=-1)
            o_new = o_b * alpha[..., None] + jnp.einsum(
                "bhgqk,bkhd->bhgqd", pexp.astype(jnp.bfloat16), vb,
                preferred_element_type=jnp.float32)
            return o_new, m_new, l_new

        if nb == 1:
            o1, m1, l1 = one_block((qf_bk[0], o[0], m[0], l[0], ids_bk[0]))
            o, m, l = o1[None], m1[None], l1[None]
        else:
            o, m, l = lax.map(one_block, (qf_bk, o, m, l, ids_bk))

        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o, m, l, k_nxt, v_nxt)

    o, m, l, _, _ = lax.fori_loop(0, n, body, (o0, m0, l0, k, v))
    out = o / jnp.maximum(l, 1e-30)[..., None]      # [nb,B,Hkv,G,bq,D]
    out = jnp.moveaxis(out, 0, 3).reshape(b, hkv, g, sq, d)
    out = jnp.moveaxis(out, 3, 1).reshape(b, sq, h, d)
    return out.astype(q.dtype)


def sp_attention(
    q: jax.Array,          # [B, S, H, D] (global, sharded on S)
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "sp",
    scale: Optional[float] = None,
    logits_soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """shard_map wrapper: sequence-parallel exact causal attention."""
    fn = functools.partial(ring_attention, axis_name=axis, scale=scale,
                           logits_soft_cap=logits_soft_cap,
                           sliding_window=sliding_window)
    spec = P(None, axis, None, None)
    return _shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        **_REP_KW)(q, k, v)
