"""Quantized matmul: the hot op of the whole framework.

TPU-native equivalent of the reference's dequant-matmul kernels
(`linear_q4_0.forward_new` SYCL op, reference transformers/low_bit_linear.py:
608-631, and the CPU `ggml_compute_forward_mul_mat_q_fp32` path at
low_bit_linear.py:418-453).

Two execution paths:
- **XLA fallback** (`_q_matmul_xla`): dequantize to x.dtype then `jnp.dot`.
  Works on any backend (CPU tests, interpret mode). XLA fuses the dequant
  into the matmul's operand read on TPU reasonably well for prefill shapes.
- **Pallas kernel** (`bigdl_tpu.ops.pallas.dequant_matmul`): streams the
  *packed* int4/int8 blocks HBM->VMEM and unpacks in-kernel, so decode
  (GEMV-like, memory-bound) reads ~K*N/2 bytes instead of 2*K*N. Selected
  automatically on TPU for supported qtypes.

The public entry is `q_matmul(x, w)` where `w` is a QTensor of logical shape
[K, N] (contraction-major; see ops/quant.py) and x is [..., K].
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.ops.quant import QTensor, dequantize_impl as dequantize

# Kernel backend selection:
#   "auto"   — Pallas on TPU when supported, else XLA fallback
#   "xla"    — always dequant + dot
#   "pallas" — force Pallas (errors if unsupported)
_BACKEND_ENV = "BIGDL_TPU_MATMUL_BACKEND"

# qtypes the Pallas dequant-matmul kernel supports today.
_PALLAS_QTYPES = frozenset({"sym_int4", "asym_int4", "nf4", "fp4", "nf3", "sym_int8"})


def _backend() -> str:
    # flags() folds BIGDL_TPU_MATMUL_BACKEND in at init; set_flags() wins
    from bigdl_tpu.config import flags

    return flags().matmul_backend




# formats whose XLA dequant materializes several full-size f32
# intermediates (codebook gathers, sign planes, sub-scale expansions):
# left unchunked, ONE 7B-class weight costs gigabytes of temp — a
# 32-layer mixtral-8x7B in iq2_xxs compiled to 9 GB of temp and OOM'd a
# 16 GB v5e despite only 12.8 GB of packed weights
_HEAVY_DECODE_QTYPES = frozenset(
    ("q2_k", "iq2_xxs", "iq2_xs", "iq1_s", "iq1_m"))


def _chunk_count(n: int, target_cols: int = 1024) -> int:
    """Smallest chunk count >= n/target that divides n (<= 64); when N is
    so large that every such count exceeds 64 (huge vocab heads), the
    LARGEST divisor <= 64 — giving up entirely would leave exactly the
    worst weights on the unchunked OOM path. 0 only when n is prime."""
    lo = max(1, -(-n // target_cols))
    for c in range(lo, 65):
        if n % c == 0:
            return c
    for c in range(64, 1, -1):
        if n % c == 0:
            return c
    return 0


def _chunk_planes(w: QTensor, min_elems: int, target_cols: int):
    """Shared chunk prep for the forward and backward chunked paths:
    (chunk_count, stacked planes tuple, per-chunk shape), or None when
    chunking is not applicable/worthwhile."""
    from bigdl_tpu.ops.quant import split_qtensor_n

    k, n = w.shape
    if k * n < min_elems:          # small weights: temp is already small
        return None
    c = _chunk_count(n, target_cols)
    if c <= 1:
        return None
    chunks = split_qtensor_n(w, [n // c] * c)
    stacked = []
    for f in ("data", "scale", "zero", "aux"):
        planes = [getattr(ch, f) for ch in chunks]
        stacked.append(None if planes[0] is None else jnp.stack(planes))
    return c, tuple(stacked), chunks[0].shape


def _q_matmul_xla_chunked(x: jax.Array, w: QTensor,
                          min_elems: int = 1 << 24,
                          target_cols: int = 1024):
    """Dequantize+dot in N-chunks under lax.map so XLA reuses one
    chunk's decode buffers instead of materializing them all at once.
    Returns None when chunking is not applicable/worthwhile."""
    prep = _chunk_planes(w, min_elems, target_cols)
    if prep is None:
        return None
    _, stacked, cshape = prep
    n = w.shape[1]
    xb = x.astype(jnp.bfloat16)

    def one(planes):
        d, s, z, a = planes
        wq = QTensor(d, s, z, w.qtype, cshape, a)
        return jnp.dot(xb, dequantize(wq, dtype=jnp.bfloat16),
                       preferred_element_type=jnp.float32)

    ys = jax.lax.map(one, stacked)                            # [C, M, n/C]
    # downcast BEFORE the transpose: the cast commutes with moveaxis and
    # halves the transpose buffer (the whole point here is bounding temp)
    y = jnp.moveaxis(ys.astype(x.dtype), 0, -2)
    return y.reshape(*x.shape[:-1], n)


def _rows(x: jax.Array) -> int:
    m = 1
    for dim in x.shape[:-1]:
        m *= dim
    return m


# one 7B-class weight (4096 x 11008 and up); decode-shaped calls against
# anything this large get the bounded-temp chunked plan
_DECODE_CHUNK_ELEMS = 1 << 25


def _q_matmul_xla(x: jax.Array, w: QTensor) -> jax.Array:
    if w.qtype in _HEAVY_DECODE_QTYPES:
        y = _q_matmul_xla_chunked(x, w)
        if y is not None:
            return y
    elif _rows(x) <= 16 and w.shape[0] * w.shape[1] >= _DECODE_CHUNK_ELEMS:
        # decode against a 7B-class weight: the dense plan materializes
        # the FULL bf16 dequant (2*K*N bytes of temp) per layer — across
        # a scanned 32-layer decode XLA kept several alive at once and
        # the forced-XLA bench lane died in RESOURCE_EXHAUSTED before
        # producing a number. Chunking over N bounds the live temp to
        # one chunk; over-N splits leave every dot column's K-reduction
        # untouched, so the result is bitwise identical to the dense
        # plan (prefill M is unaffected either way).
        y = _q_matmul_xla_chunked(x, w, min_elems=_DECODE_CHUNK_ELEMS)
        if y is not None:
            return y
    dense = dequantize(w, dtype=jnp.bfloat16)
    y = jnp.dot(
        x.astype(jnp.bfloat16), dense, preferred_element_type=jnp.float32
    )
    return y.astype(x.dtype)


# formats with exact (or single-LUT) codes whose dequant factors as
# code * blockscale (+ blockzero): these fuse into the contraction
_FUSED_XLA_QTYPES = frozenset({"sym_int4", "asym_int4", "nf4", "sym_int8"})


def _q_matmul_xla_fused(x: jax.Array, w: QTensor) -> jax.Array:
    """Decode-shaped XLA path with the dequant fused INTO the dot.

    The plain fallback computes dequantize(W) -> [K, N] bf16 -> dot: the
    scale multiply touches all K*N weights and the scale-expanded bf16
    weight is a full-size temp. Scales factor out of the contraction
    (same algebra as the Pallas `_gemv_kernel_fold`):

        y[m, n] = sum_r s[r, n] * sum_{j in block r} x[m, r, j] c[r, j, n]
                  (+ sum_r z[r, n] * sum_j x[m, r, j]   for asym)

    so this runs ONE batched `lax.dot_general` over the raw codes (int4
    codes are exact in bf16; nf4 is one LUT take) and applies scales to
    the [K/B, M, N] block partials in f32 — per-weight work drops to the
    unpack+convert, and at decode M the partial stack is megabytes, not
    the 2*K*N of a dense dequant. Used on TPU for decode-shaped calls
    when the Pallas kernel is unavailable (unprobed geometry, SPMD
    tracing), or forced via backend="xla_fused"."""
    from bigdl_tpu.ops.quant import _unpack4, get_qtype
    from bigdl_tpu.ops.codebooks import CODEBOOKS

    qt = get_qtype(w.qtype)
    if w.qtype not in _FUSED_XLA_QTYPES:
        raise NotImplementedError(
            f"fused XLA matmul does not support {w.qtype}")
    b = qt.block_size
    k, n = w.shape
    kp = w.scale.shape[0] * b
    batch_shape = x.shape[:-1]
    x2 = x.reshape(-1, k).astype(jnp.bfloat16)
    if kp != k:
        x2 = jax.lax.pad(x2, jnp.zeros((), x2.dtype),
                         ((0, 0, 0), (0, kp - k, 0)))
    m = x2.shape[0]
    rows = kp // b
    x3 = x2.reshape(m, rows, b).transpose(1, 0, 2)            # [r, M, B]

    data = w.data
    if data.dtype == jnp.int4:                # MXU layout: codes direct
        cb = data.astype(jnp.bfloat16)
    elif qt.storage_bits == 8:
        cb = data.astype(jnp.bfloat16)
    else:
        codes = _unpack4(data, b)                             # [kp, N] u8
        if qt.kind == "codebook":
            lut = jnp.asarray(CODEBOOKS[qt.codebook], jnp.bfloat16)
            cb = jnp.take(lut, codes.astype(jnp.int32), axis=0)
        elif qt.kind == "sym":
            cb = codes.astype(jnp.bfloat16) - 8.0
        else:                                                 # asym
            cb = codes.astype(jnp.bfloat16)
    cb3 = cb.reshape(rows, b, n)                              # [r, B, N]

    part = jax.lax.dot_general(                               # [r, M, N]
        x3, cb3, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
    s = w.scale.astype(jnp.float32)                           # [r, N]
    y = jnp.sum(part * s[:, None, :], axis=0)                 # [M, N]
    if qt.kind == "asym":
        xsum = jnp.sum(x3.astype(jnp.float32), axis=2).T      # [M, r]
        y = y + jnp.dot(xsum, w.zero.astype(jnp.float32),
                        preferred_element_type=jnp.float32)
    return y.astype(x.dtype).reshape(*batch_shape, n)


def _q_matmul_dispatch(x: jax.Array, w: QTensor, be: str) -> jax.Array:
    if be == "xla":
        return _q_matmul_xla(x, w)
    if be == "xla_fused":
        if w.qtype in _FUSED_XLA_QTYPES:
            return _q_matmul_xla_fused(x, w)
        return _q_matmul_xla(x, w)
    if be in ("auto", "pallas"):
        from bigdl_tpu.config import flags, target_is_tpu, under_spmd

        on_tpu = target_is_tpu()
        use_pallas = (w.qtype in _PALLAS_QTYPES and on_tpu
                      and not under_spmd(x, *jax.tree_util.tree_leaves(w)))
        if be == "auto" and use_pallas:
            # prefill-class M: the dequant kernel is VPU-bound while the
            # XLA dequantize-then-matmul plan rides the MXU (on-chip A/B
            # in RuntimeFlags.matmul_pallas_max_m's docstring)
            m = _rows(x)
            use_pallas = m <= flags().matmul_pallas_max_m
            if use_pallas:
                from bigdl_tpu.ops.pallas.dequant_matmul import (
                    GEMV_MAX_M, matmul_kernel_compiles)

                if m > GEMV_MAX_M:
                    # the generic tiles were the ONE unprobed Pallas
                    # path — a Mosaic rejection there crashed the whole
                    # forced-all-M bench lane instead of degrading
                    from bigdl_tpu.ops.quant import get_qtype

                    kp = w.scale.shape[0] * get_qtype(w.qtype).block_size
                    use_pallas = matmul_kernel_compiles(
                        w.qtype, m, kp, w.shape[1],
                        mxu=w.data.dtype == jnp.int4)
        if be == "pallas" or use_pallas:
            try:
                from bigdl_tpu.ops.pallas.dequant_matmul import (
                    q_matmul_pallas_impl)

                return q_matmul_pallas_impl(x, w)
            except NotImplementedError:
                if be == "pallas":
                    raise
        if on_tpu and w.qtype in _FUSED_XLA_QTYPES and _rows(x) <= 32:
            # decode-shaped call that could not take the Pallas kernel
            # (SPMD tracing, failed probe): fuse the dequant into the
            # dot rather than materializing the full bf16 weight
            return _q_matmul_xla_fused(x, w)
        return _q_matmul_xla(x, w)
    raise ValueError(f"unknown matmul backend {be!r}")


_VMAPPED_PALLAS: dict = {}


def vmapped_pallas_ok(qtype: str, k: int = 256, n: int = 256) -> bool:
    """Eager probe PER (qtype, K, N-tile): does a vmapped, dynamically-
    indexed q_matmul_pallas compile on this backend for this format at
    this geometry? Gates the MoE decode gather path's use of the fused
    kernel (models/llama.py `_moe_mlp`): pallas_call's batching rule,
    dynamic expert indexing, the qtype's dequant branch, and the REAL
    tile classes are what that path runs (Mosaic rejections are
    geometry-dependent). The stand-in keeps the full K (the GEMV x/scale
    residency depends on it) but only ONE N tile — probing the full
    [K, N] would allocate hundreds of MB next to a resident model."""
    from bigdl_tpu.config import flags as _flags, target_is_tpu

    if not (target_is_tpu() and qtype in _PALLAS_QTYPES):
        return False
    from bigdl_tpu.ops.pallas.dequant_matmul import (_gemv_tiles,
                                                     q_matmul_pallas)
    from bigdl_tpu.ops.quant import get_qtype, quantize

    if _flags().aot_target == "tpu":   # AOT lowering: trust the dispatch
        return True
    tiles = _gemv_tiles(get_qtype(qtype), k, n)
    if tiles is not None:
        n = tiles[1]
    key = (qtype, k, n)
    hit = _VMAPPED_PALLAS.get(key)
    if hit is not None:
        return hit
    try:
        from bigdl_tpu.ops.probing import (probe_compile, quant_struct,
                                           stacked_struct)

        # compile-only AOT probe (see ops/probing.py) — safe inside the
        # caller's jit trace, allocates nothing on device
        stack = stacked_struct(quant_struct(k, n, qtype), 2)

        def probe_fn(idx, x, ws):
            def per(i, row):
                wi = jax.tree.map(lambda a: a[i], ws)
                return q_matmul_pallas(row[None], wi)[0]

            return jax.vmap(per)(idx, x)

        probe_compile(probe_fn,
                      jax.ShapeDtypeStruct((2,), jnp.int32),
                      jax.ShapeDtypeStruct((2, k), jnp.bfloat16), stack)
        ok = True
    except Exception as e:
        import logging

        logging.getLogger(__name__).warning(
            "vmapped pallas_call unavailable for %s at (K=%d, N=%d) "
            "(%s: %s); MoE decode gather uses the XLA matmul", qtype,
            k, n, type(e).__name__, e)
        ok = False
    from bigdl_tpu.ops.probing import record_probe_result

    record_probe_result("vmapped_gemm", ok)
    _VMAPPED_PALLAS[key] = ok
    return ok


def _zero_cotangent(leaf):
    # int-packed leaves take float0 cotangents under AD
    import numpy as _np

    if jnp.issubdtype(leaf.dtype, jnp.inexact):
        return jnp.zeros_like(leaf)
    return _np.zeros(leaf.shape, jax.dtypes.float0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _q_matmul_vjp(x: jax.Array, w: QTensor, be: str) -> jax.Array:
    return _q_matmul_dispatch(x, w, be)


def _q_matmul_fwd(x, w, be):
    return _q_matmul_dispatch(x, w, be), w


def _q_matmul_bwd(be, w, dy):
    # MatMulLowBit.backward equivalent (reference low_bit_linear.py:470-486):
    # dx = dy @ dequantize(W)^T; the quantized weight is never trainable, so
    # its cotangent is zero. This also makes the non-differentiable Pallas
    # forward transparently trainable-through.
    dw = jax.tree.map(_zero_cotangent, w)
    if w.qtype in _HEAVY_DECODE_QTYPES:
        dx = _q_matmul_bwd_chunked(dy, w)
        if dx is not None:
            return dx.astype(dy.dtype), dw
    wd = dequantize(w, dtype=jnp.bfloat16)
    dx = jnp.dot(dy.astype(jnp.bfloat16), wd.T,
                 preferred_element_type=jnp.float32)
    return dx.astype(dy.dtype), dw


def _q_matmul_bwd_chunked(dy: jax.Array, w: QTensor,
                          min_elems: int = 1 << 24,
                          target_cols: int = 1024):
    """dx = dy @ W^T accumulated over the same N-chunks as the forward,
    so heavy-decode formats keep their bounded-temp guarantee under AD
    (QLoRA over iq/k-quant bases). Returns None when not applicable."""
    prep = _chunk_planes(w, min_elems, target_cols)
    if prep is None:
        return None
    c, stacked, cshape = prep
    k, n = w.shape
    nc = n // c
    dyb = dy.astype(jnp.bfloat16).reshape(-1, n)

    def step(acc, xs):
        i, planes = xs
        d, s, z, a = planes
        wq = QTensor(d, s, z, w.qtype, cshape, a)
        dy_c = jax.lax.dynamic_slice_in_dim(dyb, i * nc, nc, axis=1)
        return acc + jnp.dot(dy_c,
                             dequantize(wq, dtype=jnp.bfloat16).T,
                             preferred_element_type=jnp.float32), None

    acc0 = jnp.zeros((dyb.shape[0], k), jnp.float32)
    dx, _ = jax.lax.scan(step, acc0, (jnp.arange(c), stacked))
    return dx.reshape(*dy.shape[:-1], k)


_q_matmul_vjp.defvjp(_q_matmul_fwd, _q_matmul_bwd)


def q_matmul(x: jax.Array, w: QTensor, *, backend: Optional[str] = None) -> jax.Array:
    """Compute x @ W for a quantized W of logical shape [K, N].

    x: [..., K] float array. Returns [..., N] in x.dtype. Differentiable
    w.r.t. x (dequant-matmul backward); the weight gets zero cotangent.
    """
    return _q_matmul_vjp(x, w, backend or _backend())


def linear(
    x: jax.Array,
    w,
    bias: Optional[jax.Array] = None,
    *,
    backend: Optional[str] = None,
) -> jax.Array:
    """Linear over either a QTensor or a dense [K, N] array.

    Model code calls this uniformly; float-qtype models (fp16/bf16 paths of
    the reference's BF16Linear/FP16Linear, low_bit_linear.py:671-827) carry
    dense leaves, quantized models carry QTensors. Adapter-wrapped weights
    (bigdl_tpu.qlora.LoraWeight — or any leaf exposing `apply_linear`)
    dispatch to themselves, which is how LoRA reaches every model family
    with no model-code changes.
    """
    if hasattr(w, "apply_linear"):
        return w.apply_linear(x, bias, backend=backend)
    if isinstance(w, QTensor):
        return q_linear(x, w, bias, backend=backend)
    y = jnp.dot(x, w.astype(x.dtype), preferred_element_type=jnp.float32)
    y = y.astype(x.dtype)
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y


def q_linear(
    x: jax.Array,
    w: QTensor,
    bias: Optional[jax.Array] = None,
    *,
    backend: Optional[str] = None,
) -> jax.Array:
    """LowBitLinear.forward equivalent: y = x @ W + b.

    (reference transformers/low_bit_linear.py:546-668; the tensor-parallel
    all-reduce the reference issues here — dist.inference_all_reduce at
    low_bit_linear.py:635-637 — is unnecessary in this design: sharded
    QTensors under pjit make XLA insert the collective.)
    """
    y = q_matmul(x, w, backend=backend)
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y
