"""Quantized matmul: the hot op of the whole framework.

TPU-native equivalent of the reference's dequant-matmul kernels
(`linear_q4_0.forward_new` SYCL op, reference transformers/low_bit_linear.py:
608-631, and the CPU `ggml_compute_forward_mul_mat_q_fp32` path at
low_bit_linear.py:418-453).

Two execution paths:
- **XLA fallback** (`_q_matmul_xla`): dequantize to x.dtype then `jnp.dot`.
  Works on any backend (CPU tests, interpret mode). XLA fuses the dequant
  into the matmul's operand read on TPU reasonably well for prefill shapes.
- **Pallas kernel** (`bigdl_tpu.ops.pallas.dequant_matmul`): streams the
  *packed* int4/int8 blocks HBM->VMEM and unpacks in-kernel, so decode
  (GEMV-like, memory-bound) reads ~K*N/2 bytes instead of 2*K*N. Selected
  automatically on TPU for supported qtypes.

The public entry is `q_matmul(x, w)` where `w` is a QTensor of logical shape
[K, N] (contraction-major; see ops/quant.py) and x is [..., K].
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.ops.quant import QTensor, dequantize

# Kernel backend selection:
#   "auto"   — Pallas on TPU when supported, else XLA fallback
#   "xla"    — always dequant + dot
#   "pallas" — force Pallas (errors if unsupported)
_BACKEND_ENV = "BIGDL_TPU_MATMUL_BACKEND"

# qtypes the Pallas dequant-matmul kernel supports today.
_PALLAS_QTYPES = frozenset({"sym_int4", "asym_int4", "nf4", "fp4", "nf3", "sym_int8"})


def _backend() -> str:
    return os.environ.get(_BACKEND_ENV, "auto")


def _on_tpu(x: jax.Array) -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _q_matmul_xla(x: jax.Array, w: QTensor) -> jax.Array:
    dense = dequantize(w, dtype=jnp.bfloat16)
    y = jnp.dot(
        x.astype(jnp.bfloat16), dense, preferred_element_type=jnp.float32
    )
    return y.astype(x.dtype)


def q_matmul(x: jax.Array, w: QTensor, *, backend: Optional[str] = None) -> jax.Array:
    """Compute x @ W for a quantized W of logical shape [K, N].

    x: [..., K] float array. Returns [..., N] in x.dtype.
    """
    be = backend or _backend()
    if be == "xla":
        return _q_matmul_xla(x, w)
    if be in ("auto", "pallas"):
        use_pallas = w.qtype in _PALLAS_QTYPES and _on_tpu(x)
        if be == "pallas" or use_pallas:
            try:
                from bigdl_tpu.ops.pallas.dequant_matmul import q_matmul_pallas

                return q_matmul_pallas(x, w)
            except NotImplementedError:
                if be == "pallas":
                    raise
        return _q_matmul_xla(x, w)
    raise ValueError(f"unknown matmul backend {be!r}")


def linear(
    x: jax.Array,
    w,
    bias: Optional[jax.Array] = None,
    *,
    backend: Optional[str] = None,
) -> jax.Array:
    """Linear over either a QTensor or a dense [K, N] array.

    Model code calls this uniformly; float-qtype models (fp16/bf16 paths of
    the reference's BF16Linear/FP16Linear, low_bit_linear.py:671-827) carry
    dense leaves, quantized models carry QTensors.
    """
    if isinstance(w, QTensor):
        return q_linear(x, w, bias, backend=backend)
    y = jnp.dot(x, w.astype(x.dtype), preferred_element_type=jnp.float32)
    y = y.astype(x.dtype)
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y


def q_linear(
    x: jax.Array,
    w: QTensor,
    bias: Optional[jax.Array] = None,
    *,
    backend: Optional[str] = None,
) -> jax.Array:
    """LowBitLinear.forward equivalent: y = x @ W + b.

    (reference transformers/low_bit_linear.py:546-668; the tensor-parallel
    all-reduce the reference issues here — dist.inference_all_reduce at
    low_bit_linear.py:635-637 — is unnecessary in this design: sharded
    QTensors under pjit make XLA insert the collective.)
    """
    y = q_matmul(x, w, backend=backend)
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y
