"""Codebook tables for lookup-based quantization formats.

The reference (ipex-llm) supports NF4/NF3/FP4 via ggml codebook kernels
(see /root/reference SURVEY: ggml/quantize.py:28-47 qtype registry and the
native `ggml_quantize_tensor` per-format paths). Here the codebooks are plain
JAX constants; quantization is an argmin over the codebook and dequantization
is a gather — both of which XLA vectorizes onto the VPU.

Values:
- NF4: the 16 "NormalFloat" levels from the QLoRA paper (quantiles of a
  standard normal, normalized to [-1, 1]).
- NF3: 8-level variant used by the reference's nf3 qtype.
- FP4: e2m1 mini-float values (sign x {0, .5, 1, 1.5, 2, 3, 4, 6} / 6 scaled),
  matching bitsandbytes' fp4 table.
"""

from functools import lru_cache

import numpy as np

# QLoRA NF4 levels (exact values from the QLoRA paper / bitsandbytes).
NF4_CODE = np.array(
    [
        -1.0,
        -0.6961928009986877,
        -0.5250730514526367,
        -0.39491748809814453,
        -0.28444138169288635,
        -0.18477343022823334,
        -0.09105003625154495,
        0.0,
        0.07958029955625534,
        0.16093020141124725,
        0.24611230194568634,
        0.33791524171829224,
        0.44070982933044434,
        0.5626170039176941,
        0.7229568362236023,
        1.0,
    ],
    dtype=np.float32,
)

# 8-level NormalFloat (nf3): signed quantiles of N(0,1) normalized to [-1, 1].
NF3_CODE = np.array(
    [-1.0, -0.5350227355957031, -0.2469314038753510, 0.0,
     0.1833375245332718, 0.3819939494132996, 0.6229856610298157, 1.0],
    dtype=np.float32,
)

# FP4 (e2m1): bitsandbytes table, normalized so max |v| == 1.
FP4_CODE = np.array(
    [0.0, 0.0052, 0.6667, 1.0, 0.3333, 0.5, 0.1667, 0.25,
     -0.0, -0.0052, -0.6667, -1.0, -0.3333, -0.5, -0.1667, -0.25],
    dtype=np.float32,
)

CODEBOOKS = {
    "nf4": NF4_CODE,
    "nf3": NF3_CODE,
    "fp4": FP4_CODE,
}


# ---------------------------------------------------------------------------
# Group (vector) codebooks for the ultra-low-bit iq formats.
#
# The reference's IQ2_XXS/IQ1_S formats (ggml_quantize_tensor_with_weights,
# SURVEY.md §2.3-B) quantize GROUPS of 8 values to an entry of a fixed
# E8-lattice grid + signs. These are TPU-native re-designs of the same idea
# rather than bit-copies of ggml's grids: the codebook is the top-N most
# probable magnitude patterns under an iid half-Gaussian model — a
# deterministic construction (no trained tables), so encode/decode stay
# reproducible across machines.
# ---------------------------------------------------------------------------

_GROUP = 8


def _top_patterns(levels, level_logp, count: int) -> np.ndarray:
    """All len(levels)^8 patterns ranked by iid log-probability (then
    lexicographically for a deterministic tie-break); top `count` rows."""
    nl = len(levels)
    idx = np.indices((nl,) * _GROUP).reshape(_GROUP, -1).T  # [nl^8, 8]
    logp = np.asarray(level_logp)[idx].sum(axis=1)
    order = np.lexsort(tuple(idx.T[::-1]) + (-logp,))
    chosen = idx[order[:count]]
    return np.asarray(levels, np.float32)[chosen]            # [count, 8]


@lru_cache(maxsize=None)
def group_codebook(name: str) -> np.ndarray:
    """[n_entries, 8] float32 group codebook.

    - "iq2_xxs": magnitudes {1,3,5,7} (signs stored separately), 256
      entries; probabilities from half-normal bin masses at the working
      scale (amax -> 7).
    - "iq1_s": signed ternary {-1,0,+1}, 256 entries; p(0)=1/2,
      p(+-1)=1/4.
    """
    if name == "iq2_xxs":
        return _top_patterns(
            [1.0, 3.0, 5.0, 7.0],
            np.log([0.55, 0.25, 0.13, 0.07]), 256)
    if name == "iq2_xs":
        # same magnitude alphabet, twice the patterns: the 9-bit index +
        # 7-bit parity-sign packing frees the extra bit (ggml's XXS->XS
        # move, reference ggml/quantize.py:43-47) at identical storage
        return _top_patterns(
            [1.0, 3.0, 5.0, 7.0],
            np.log([0.55, 0.25, 0.13, 0.07]), 512)
    if name == "iq1_s":
        return _top_patterns(
            [0.0, 1.0, -1.0],
            np.log([0.5, 0.25, 0.25]), 256)
    raise ValueError(f"unknown group codebook {name!r}")
