"""Quantized embedding table: low-bit storage + gather-dequantize lookup.

Equivalent of the reference's `LowBitEmbedding` (reference transformers/
embedding.py:77-114: quantized table + native `dequantize_rows` gather; the
CPU-pinned `LLMEmbedding` at :57 exists for Windows iGPU memory pressure
and has no TPU analog — HBM is the only tier).

Storage layout: the [V, D] table is kept as a QTensor of logical shape
[D, V] (blocks along D, vocab on the N axis), so a lookup is a gather of
PACKED columns followed by block dequantization of just the gathered ids —
HBM traffic is ids x D/2 bytes, and a TIED lm_head is exactly
`q_matmul(x, table)` with no extra transform.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.ops.quant import QTensor, dequantize, quantize


def quantize_embedding(table_vd: jax.Array, qtype: str) -> QTensor:
    """[V, D] float table -> QTensor [D, V] (blocks along D)."""
    return quantize(jnp.asarray(table_vd).T, qtype)


def embedding_lookup(table, ids: jax.Array, dtype=jnp.bfloat16) -> jax.Array:
    """ids [...] -> embeddings [..., D]; table is dense [V, D] or QTensor."""
    if not isinstance(table, QTensor):
        return table[ids].astype(dtype)
    flat = ids.reshape(-1)                       # [n]
    gathered = QTensor(
        table.data[:, flat],
        table.scale[:, flat],
        None if table.zero is None else table.zero[:, flat],
        table.qtype,
        (table.k, flat.shape[0]),
        aux=None if table.aux is None else table.aux[:, flat],
    )
    dense = dequantize(gathered, dtype=dtype)    # [D, n]
    return dense.T.reshape(*ids.shape, table.k)
