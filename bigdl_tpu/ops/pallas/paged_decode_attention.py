"""Pallas TPU kernel: fused decode attention over a PAGED KV arena.

The slab decode kernel (`decode_attention.py`) streams each sequence's
K/V rows contiguously. Under the paged layout (`ops/paged.py`) a
sequence's rows live scattered across the ``[P, page_size, Hkv, hd]``
arena wherever its block table points — materializing a dense copy first
would double the memory traffic of an already bandwidth-bound op.

This kernel keeps the gather INSIDE the launch: the block table rides in
as a scalar-prefetch operand, and each grid step's K/V BlockSpec
*index_map* dereferences it — ``(bt[b, j], 0, head)`` — so Mosaic's
pipeline DMAs page ``bt[b, j]`` straight from the arena into VMEM while
step ``j-1`` computes. One S-block == one page; the online-softmax state
machine is the blocked slab kernel's, with the position mask doing double
duty: padded table entries point at the null page (physical 0), whose
positions are all ``> pos`` and therefore contribute nothing.

Shapes: q ``[B, 1, H, hd]``; arena k/v ``[P, ps, Hkv, hd]`` (one layer);
block_tables ``[B, NP]`` int32; pos ``[B]`` int32. int8/int4 arenas ride
with their ``[P, ps, Hkv]`` scale planes and dequantize in-register, rows
scaled exactly like the slab kernels (`_head_scales`/`_dequant_rows`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bigdl_tpu.ops.pallas.decode_attention import (
    _NEG_INF,
    _dequant_rows,
    _head_scales,
)


def _paged_kernel(pos_ref, bt_ref, q_ref, k_ref, v_ref, out_ref,
                  m_ref, l_ref, acc_ref, *, scale, ps, np_, gp):
    b = pl.program_id(0)
    sj = pl.program_id(2)
    pos = pos_ref[b]

    @pl.when(sj == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0, 0].astype(jnp.bfloat16)              # [Gp, hd]
    k = k_ref[0].astype(jnp.bfloat16)                 # [ps, hd] (one page)
    v = v_ref[0].astype(jnp.bfloat16)

    s_ = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale    # [Gp, ps]
    # logical position of this page's rows; null-page rows always mask
    # (their logical ids exceed pos by construction of the allocator)
    ids = sj * ps + jax.lax.broadcasted_iota(jnp.int32, (gp, ps), 1)
    s_ = jnp.where(ids <= pos, s_, _NEG_INF)

    m_prev = m_ref[:, :1]
    m_cur = jnp.max(s_, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    corr = jnp.exp(m_prev - m_new)
    p = jnp.exp(s_ - m_new)
    l_ref[:] = jnp.broadcast_to(
        l_ref[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True),
        l_ref.shape)
    pv = jax.lax.dot_general(
        p.astype(jnp.bfloat16), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    acc_ref[:] = acc_ref[:] * corr + pv
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(sj == np_ - 1)
    def _():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        out_ref[0, 0] = (acc_ref[:] / l).astype(out_ref.dtype)


def _paged_kernel_scaled(pos_ref, bt_ref, q_ref, k_ref, v_ref,
                         ks_ref, vs_ref, out_ref, m_ref, l_ref, acc_ref,
                         *, scale, ps, np_, gp, hkv):
    b = pl.program_id(0)
    hi = pl.program_id(1)
    sj = pl.program_id(2)
    pos = pos_ref[b]

    @pl.when(sj == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0, 0].astype(jnp.bfloat16)              # [Gp, hd]
    k = _dequant_rows(k_ref, _head_scales(ks_ref, hi, ps, hkv))  # [ps, hd]
    v = _dequant_rows(v_ref, _head_scales(vs_ref, hi, ps, hkv))

    s_ = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale    # [Gp, ps]
    ids = sj * ps + jax.lax.broadcasted_iota(jnp.int32, (gp, ps), 1)
    s_ = jnp.where(ids <= pos, s_, _NEG_INF)

    m_prev = m_ref[:, :1]
    m_cur = jnp.max(s_, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    corr = jnp.exp(m_prev - m_new)
    p = jnp.exp(s_ - m_new)
    l_ref[:] = jnp.broadcast_to(
        l_ref[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True),
        l_ref.shape)
    pv = jax.lax.dot_general(
        p.astype(jnp.bfloat16), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    acc_ref[:] = acc_ref[:] * corr + pv
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(sj == np_ - 1)
    def _():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        out_ref[0, 0] = (acc_ref[:] / l).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def paged_decode_attention_pallas(
    q: jax.Array,             # [B, 1, H, hd]
    arena_k: jax.Array,       # [P, ps, Hkv, hd] one layer's arena
    arena_v: jax.Array,
    block_tables: jax.Array,  # [B, NP] int32 (0 = null page)
    q_pos: jax.Array,         # [B] int32
    scale: float,
    interpret: bool = False,
    k_scale=None,             # [P, ps, Hkv] f32 for int8/int4 codes
    v_scale=None,
) -> jax.Array:
    """Fused paged decode SDP. Returns [B, 1, H, hd] in q.dtype."""
    b, sq, h, hd = q.shape
    p_, ps, hkv = arena_k.shape[0], arena_k.shape[1], arena_k.shape[2]
    np_ = block_tables.shape[1]
    if sq != 1:
        raise NotImplementedError("paged decode kernel handles Sq == 1")
    scaled = k_scale is not None
    g = h // hkv
    gp = max(16, -(-g // 8) * 8)   # pad query group to clean sublane run

    qr = q.reshape(b, hkv, g, hd)
    if gp != g:
        qr = jnp.pad(qr, ((0, 0), (0, 0), (0, gp - g), (0, 0)))
    # heads into the lane axis so a per-head block is (1, ps, hd); free
    # reshape on the contiguous [P, ps, Hkv, hd] arena layout
    k2 = arena_k.reshape(p_, ps, hkv * hd)
    v2 = arena_v.reshape(p_, ps, hkv * hd)

    pos = jnp.broadcast_to(jnp.asarray(q_pos, jnp.int32).reshape(-1), (b,))
    bt = block_tables.astype(jnp.int32)

    # the whole point: K/V index_maps dereference the prefetched block
    # table, so grid step (b, hi, sj) DMAs physical page bt[b, sj] —
    # the gather never materializes a dense copy in HBM
    q_spec = pl.BlockSpec((1, 1, gp, hd),
                          lambda bi, hi, sj, pos_ref, bt_ref: (bi, hi, 0, 0))
    kv_spec = pl.BlockSpec(
        (1, ps, hd),
        lambda bi, hi, sj, pos_ref, bt_ref: (bt_ref[bi, sj], 0, hi))
    in_specs = [q_spec, kv_spec, kv_spec]
    if scaled:
        # scale planes ride full-Hkv in the lanes (see _head_scales)
        sc_spec = pl.BlockSpec(
            (1, ps, hkv),
            lambda bi, hi, sj, pos_ref, bt_ref: (bt_ref[bi, sj], 0, 0))
        in_specs += [sc_spec, sc_spec]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv, np_),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, gp, hd),
            lambda bi, hi, sj, pos_ref, bt_ref: (bi, hi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((gp, 128), jnp.float32),
            pltpu.VMEM((gp, 128), jnp.float32),
            pltpu.VMEM((gp, hd), jnp.float32),
        ],
    )
    kernel = (functools.partial(_paged_kernel_scaled, scale=scale, ps=ps,
                                np_=np_, gp=gp, hkv=hkv)
              if scaled else
              functools.partial(_paged_kernel, scale=scale, ps=ps,
                                np_=np_, gp=gp))
    operands = (pos, bt, qr, k2, v2)
    if scaled:
        operands += (k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32))
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, gp, hd), q.dtype),
        interpret=interpret,
    )(*operands)

    return out[:, :, :g, :].reshape(b, 1, h, hd)


def paged_attention_geometry_ok(q, arena_k, logits_soft_cap,
                                sliding_window, alibi_slopes,
                                k_scale=None) -> bool:
    """Feature/geometry gate: plain softmax attention, MXU-aligned
    shapes, page_size a lane-tile multiple (one page == one S-block)."""
    if alibi_slopes is not None:
        return False
    if logits_soft_cap is not None or sliding_window is not None:
        return False
    h, hd = q.shape[2], q.shape[3]
    ps, hkv = arena_k.shape[1], arena_k.shape[2]
    if h % hkv != 0 or hd % 64 != 0 or ps % 128 != 0:
        return False
    if arena_k.dtype in (jnp.bfloat16, jnp.float8_e5m2):
        return k_scale is None
    if arena_k.dtype in (jnp.int8, jnp.int4):
        return k_scale is not None
    return False


def paged_decode_attention_supported(q, arena_k, logits_soft_cap,
                                     sliding_window, alibi_slopes,
                                     k_scale=None) -> bool:
    """Gate for the sdp_attention_paged dispatch (bigdl_tpu.ops.attention)."""
    return q.shape[1] == 1 and paged_attention_geometry_ok(
        q, arena_k, logits_soft_cap, sliding_window, alibi_slopes, k_scale)
