"""Pallas TPU kernel: blockwise (flash) causal attention for prefill.

TPU-native replacement for the reference's prefill flash-attention path
(`use_flash_attention` gating ipex's F.scaled_dot_product_attention,
reference transformers/models/utils.py:33-120 and the native_sdp python
fallback at models/llama.py:1320-1349).

Why: prefill attention against the pre-allocated cache computes scores
[B, H, S, S_max]; at S=1024, S_max=2048 that is a quarter-gigabyte f32
intermediate per 32-head batch that XLA writes to HBM between the QK
matmul and the softmax. This kernel runs the classic online-softmax
sweep: for each query tile, stream key/value tiles through VMEM keeping
only [bq, hd] accumulators — scores never exist in HBM, and the KV cache
is read exactly once.

Grid: (B*H, S/bq, S_max/bk), kv innermost; m/l/acc live in VMEM scratch
and persist across the kv sweep (TPU grid order guarantees sequential
iteration of the last axis per outer step). Causality and the unwritten
cache tail share one mask: k_pos <= q_pos + q_idx.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _kernel(pos_ref, q_ref, k_ref, v_ref, out_ref, m_ref, l_ref, acc_ref,
            *, scale, bq, bk, nk):
    kj = pl.program_id(2)
    qi = pl.program_id(1)
    b = pl.program_id(0)
    pos = pos_ref[b]

    @pl.when(kj == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0].astype(jnp.bfloat16)                  # [bq, hd]
    # K/V arrive as [B, S_max, Hkv*hd] views blocked (1, bk, hd) per kv
    # head (a [.., bk, 1, hd] per-head block violates Mosaic's (8,128)
    # block-tiling rule — the 1 sits second-to-last)
    k = k_ref[0].astype(jnp.bfloat16)                  # [bk, hd]
    v = v_ref[0].astype(jnp.bfloat16)

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    q_ids = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_ids = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    s = jnp.where(k_ids <= pos + q_ids, s, _NEG_INF)

    m_prev = m_ref[:, :1]                              # [bq, 1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    corr = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)                             # [bq, bk]
    l_new = l_ref[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
    pv = jax.lax.dot_general(p.astype(jnp.bfloat16), v,
                             (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    acc_ref[:] = acc_ref[:] * corr + pv
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kj == nk - 1)
    def _():
        # fully masked rows (query beyond pos with an empty cache) keep
        # l == 0; guard the division — their output is garbage that the
        # caller's position masking never reads
        l = jnp.where(l_ref[:, :1] == 0.0, 1.0, l_ref[:, :1])
        out_ref[0] = (acc_ref[:] / l).astype(out_ref.dtype)


def _kernel_scaled(pos_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, out_ref,
                   m_ref, l_ref, acc_ref, *, scale, bq, bk, nk, h, g, hkv):
    """Flash sweep over int8/int4 codes: per-(token, head) scales fold
    into the K/V rows in-register before the dots (see decode_attention.
    _dequant_rows — a rank-1 scale vector would trip Mosaic layout
    inference, so the column select keeps dims)."""
    kj = pl.program_id(2)
    qi = pl.program_id(1)
    bh = pl.program_id(0)
    pos = pos_ref[bh]
    hi = (bh % h) // g      # kv head of this b*h grid row

    @pl.when(kj == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    from bigdl_tpu.ops.pallas.decode_attention import (_dequant_rows,
                                                       _head_scales)

    q = q_ref[0].astype(jnp.bfloat16)                  # [bq, hd]
    k = _dequant_rows(k_ref, _head_scales(ks_ref, hi, bk, hkv))  # [bk, hd]
    v = _dequant_rows(v_ref, _head_scales(vs_ref, hi, bk, hkv))

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    q_ids = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_ids = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    s = jnp.where(k_ids <= pos + q_ids, s, _NEG_INF)

    m_prev = m_ref[:, :1]                              # [bq, 1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    corr = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)                             # [bq, bk]
    l_new = l_ref[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
    pv = jax.lax.dot_general(p.astype(jnp.bfloat16), v,
                             (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    acc_ref[:] = acc_ref[:] * corr + pv
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kj == nk - 1)
    def _():
        l = jnp.where(l_ref[:, :1] == 0.0, 1.0, l_ref[:, :1])
        out_ref[0] = (acc_ref[:] / l).astype(out_ref.dtype)


def prefill_attention_pallas(
    q: jax.Array,          # [B, S, H, hd]
    k: jax.Array,          # [B, S_max, Hkv, hd] bf16 | e5m2 | int8 | int4
    v: jax.Array,
    q_pos: jax.Array,      # scalar int32 or [B]
    scale: float,
    interpret: bool = False,
    k_scale=None,          # [B, S_max, Hkv] f32 (int8/int4 codes)
    v_scale=None,
) -> jax.Array:
    """Blockwise causal SDP. Returns [B, S, H, hd] in q.dtype.

    Differentiable: the forward runs the Pallas sweep; the backward is
    standard XLA softmax-attention gradients (pallas_call itself has no
    VJP — without this, dispatching prefill to the kernel would break
    every training path that reaches sdp_attention with Sq > 1).
    Block-scaled codes (k_scale given) are inference-only — gradients
    through rounded int codes are meaningless, so that path skips the
    custom-vjp wrapper."""
    if k_scale is not None:
        return _pfa_impl(q, k, v, q_pos, float(scale), bool(interpret),
                         k_scale, v_scale)
    return _pfa_vjp(q, k, v, q_pos, float(scale), bool(interpret))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _pfa_vjp(q, k, v, q_pos, scale, interpret):
    return _pfa_impl(q, k, v, q_pos, scale, interpret)


def _pfa_fwd(q, k, v, q_pos, scale, interpret):
    return _pfa_impl(q, k, v, q_pos, scale, interpret), (q, k, v, q_pos)


def _pfa_bwd(scale, interpret, res, dy):
    """Backward via jax.vjp over the XLA reference attention — ONE source
    of truth for the mask/GQA semantics (ops/attention.sdp_attention)
    instead of a hand-derived gradient to keep in sync. Gradient
    precision therefore equals differentiating the XLA path itself
    (bf16 matmul operands, f32 softmax/accumulation) — exactly what
    non-kernel training runs get."""
    import numpy as _np

    q, k, v, q_pos = res

    def ref(q_, k_, v_):
        from bigdl_tpu.ops.attention import sdp_attention

        return sdp_attention(q_, k_, v_, q_pos, scale=scale,
                             backend="xla")

    _, vjp = jax.vjp(ref, q, k, v)
    dq, dk, dv = vjp(dy.astype(q.dtype))
    pos_ct = _np.zeros(jnp.shape(q_pos), jax.dtypes.float0)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            pos_ct)


_pfa_vjp.defvjp(_pfa_fwd, _pfa_bwd)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def _pfa_impl(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_pos: jax.Array,
    scale: float,
    interpret: bool = False,
    k_scale=None,
    v_scale=None,
) -> jax.Array:
    b, s, h, hd = q.shape
    smax, hkv = k.shape[1], k.shape[2]
    g = h // hkv
    scaled = k_scale is not None

    bq = 256 if s % 256 == 0 else 128
    bk = 512 if smax % 512 == 0 else 128
    nq, nk = s // bq, smax // bk

    qr = q.transpose(0, 2, 1, 3).reshape(b * h, s, hd)
    # flatten kv heads into the lane axis (see kernel comment)
    k2 = k.reshape(b, smax, hkv * hd)
    v2 = v.reshape(b, smax, hkv * hd)
    pos = jnp.broadcast_to(jnp.asarray(q_pos, jnp.int32).reshape(-1), (b,))
    # per-(b*h) pos lookup: repeat to [B*H]
    pos_bh = jnp.repeat(pos, h)

    in_specs = [
        pl.BlockSpec((1, bq, hd),
                     lambda bh, qi, kj, pos_ref: (bh, qi, 0)),
        pl.BlockSpec((1, bk, hd),
                     lambda bh, qi, kj, pos_ref:
                     (bh // h, kj, (bh % h) // g)),
        pl.BlockSpec((1, bk, hd),
                     lambda bh, qi, kj, pos_ref:
                     (bh // h, kj, (bh % h) // g)),
    ]
    operands = (pos_bh, qr, k2, v2)
    if scaled:
        # scale planes ride full-Hkv in the lanes (decode_attention.
        # _head_scales explains the in-kernel column select)
        sc_spec = pl.BlockSpec((1, bk, hkv),
                               lambda bh, qi, kj, pos_ref:
                               (bh // h, kj, 0))
        in_specs += [sc_spec, sc_spec]
        operands += (k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32))
        kernel = functools.partial(_kernel_scaled, scale=scale, bq=bq,
                                   bk=bk, nk=nk, h=h, g=g, hkv=hkv)
    else:
        kernel = functools.partial(_kernel, scale=scale, bq=bq, bk=bk,
                                   nk=nk)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * h, nq, nk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bq, hd),
                               lambda bh, qi, kj, pos_ref: (bh, qi, 0)),
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, s, hd), q.dtype),
        interpret=interpret,
    )(*operands)

    return out.reshape(b, h, s, hd).transpose(0, 2, 1, 3)


def prefill_attention_supported(q, k, v, q_pos, scale, logits_soft_cap,
                                sliding_window, alibi_slopes,
                                k_scale=None) -> bool:
    """Gate for the sdp_attention prefill dispatch (query-length
    alignment on top of the shared geometry gate)."""
    from bigdl_tpu.ops.pallas.decode_attention import attention_geometry_ok

    return (q.shape[1] >= 2 and q.shape[1] % 128 == 0
            and attention_geometry_ok(q, k, logits_soft_cap,
                                      sliding_window, alibi_slopes,
                                      k_scale))
