"""Pallas TPU kernel: fused dequantize + matmul over packed low-bit weights.

TPU-native replacement for the reference's SYCL `linear_q4_0.forward_new`
(reference transformers/low_bit_linear.py:608-631) and the CPU
`ggml_compute_forward_mul_mat_q_fp32` (low_bit_linear.py:418-453).

Why a kernel at all: decode (M≈1) is HBM-bandwidth-bound. The XLA fallback
materializes the dequantized bf16 weight (2*K*N bytes of HBM traffic); this
kernel streams the *packed* data (K*N/2 bytes for int4 + scales) into VMEM
and unpacks on the VPU right before feeding the MXU — a ~4x cut in bytes
moved, which is a ~4x cut in decode latency at the roofline.

Layout contract (see ops/quant.py):
  data  uint8 [Kp/2, N]  — split-block nibbles: within a block of B rows,
                           byte j holds value j (lo) and value j+B/2 (hi)
  scale f16   [Kp/B, N]
  zero  f16   [Kp/B, N]  — asym only
  int8: data int8 [Kp, N]

Grid: (M/bm, N/bn, K/bk), K innermost, f32 accumulation in VMEM scratch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bigdl_tpu.ops.quant import QTensor, get_qtype
from bigdl_tpu.ops.codebooks import CODEBOOKS


# renamed TPUCompilerParams -> CompilerParams across jax versions
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

# generic grid is (M/bm, N/bn, K/bk): M and N tiles are independent,
# only the K sweep carries the accumulator
_GENERIC_SEMANTICS = _CompilerParams(
    dimension_semantics=("parallel", "parallel", "arbitrary"))


def _pick_tile(dim: int, candidates) -> int:
    for c in candidates:
        if dim % c == 0:
            return c
    return 0


def _unpack_tile(data, block: int, bk: int, bn: int):
    """uint8 [bk//2, bn] split-block packed -> int32 codes [bk//B, B, bn].

    Mosaic has no 8-bit shift lowering; widen to i32 before the bit ops.
    """
    b2 = block // 2
    v = data.reshape(bk // block, b2, bn).astype(jnp.int32)
    lo = v & 0x0F
    hi = (v >> 4) & 0x0F
    return jnp.concatenate([lo, hi], axis=1)  # [bk//block, block, bn]


def _dequant_tile(codes_blk, scale, zero, kind: str, codebook, bk: int, bn: int):
    """codes [bk//B, B, bn] uint8 + scale/zero [bk//B, bn] -> bf16 [bk, bn]."""
    s = scale.astype(jnp.float32)[:, None, :]
    # Mosaic can't cast unsigned->float directly; hop through int32.
    codes_f = codes_blk.astype(jnp.int32).astype(jnp.float32)
    if kind == "sym":
        vals = (codes_f - 8.0) * s
    elif kind == "asym":
        z = zero.astype(jnp.float32)[:, None, :]
        vals = codes_f * s + z
    elif kind == "codebook":
        # LUT via a sequential compare/select chain (avoids gather, which
        # Mosaic lowers poorly). A binary select TREE is fewer selects but
        # keeps ~15 full-tile f32 temps live at once — 48MB of scoped VMEM
        # at generic tiles, a real Mosaic OOM (caught by tests/test_aot_
        # tpu.py); the chain keeps the live set at 2 buffers. Tables
        # smaller than 16 (nf3 has 8 entries) are zero-padded — those
        # codes never occur.
        c = codes_blk
        tbl = list(codebook) + [0.0] * (16 - len(codebook))
        vals = jnp.full(c.shape, tbl[0], jnp.float32)
        for i in range(1, 16):
            vals = jnp.where(c == i, tbl[i], vals)
        vals = vals * s
    else:
        raise NotImplementedError(kind)
    return vals.reshape(bk, bn).astype(jnp.bfloat16)


def _accumulate(x_tile, w, out_ref, acc_ref, nk, k_axis: int = 2):
    """Shared K-loop zero/accumulate/writeback. `k_axis` is the grid
    dimension that sweeps K (innermost); x_tile/w are VALUES."""
    k = pl.program_id(k_axis)

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jax.lax.dot_general(
        x_tile, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(k == nk - 1)
    def _():
        out_ref[:] = acc_ref[:].astype(out_ref.dtype)


def _kernel_4bit(x_ref, data_ref, scale_ref, *rest, block, kind, codebook,
                 bk, bn, nk):
    if kind == "asym":
        zero_ref, out_ref, acc_ref = rest
        zero = zero_ref[:]
    else:
        (out_ref, acc_ref), zero = rest, None
    codes = _unpack_tile(data_ref[:], block, bk, bn)
    w = _dequant_tile(codes, scale_ref[:], zero, kind, codebook, bk, bn)
    _accumulate(x_ref[:], w, out_ref, acc_ref, nk)


def _kernel_int8(x_ref, data_ref, scale_ref, out_ref, acc_ref, *,
                 block, bk, bn, nk):
    s = scale_ref[:].astype(jnp.float32)[:, None, :]
    vals = data_ref[:].astype(jnp.float32).reshape(bk // block, block, bn) * s
    w = vals.reshape(bk, bn).astype(jnp.bfloat16)
    _accumulate(x_ref[:], w, out_ref, acc_ref, nk)


def _kernel_i4(x_ref, data_ref, scale_ref, out_ref, acc_ref, *,
               block, bk, bn, nk):
    """Generic-tile body for the MXU (int4-dtype) layout: native int4
    load, one convert, per-weight scale — no nibble unpack chain."""
    s = scale_ref[:].astype(jnp.float32)[:, None, :]
    codes = data_ref[:].astype(jnp.int8).astype(jnp.float32)
    w = (codes.reshape(bk // block, block, bn) * s) \
        .reshape(bk, bn).astype(jnp.bfloat16)
    _accumulate(x_ref[:], w, out_ref, acc_ref, nk)


def _gemv_kernel(x_ref, data_ref, scale_ref, *rest, block, kind, codebook,
                 bk, bn, nk, bits):
    """Decode-GEMV body: grid (N/bn, K/bk), K innermost. x stays
    resident in VMEM across the K sweep; the packed data AND the
    per-step scale (zero) blocks stream via their BlockSpecs — an
    in-kernel dynamic slice of a resident scale buffer needs sublane-
    aligned offsets Mosaic cannot prove for K/block % 16 != 0 (caught
    by the AOT suite at down-proj-shaped K)."""
    if kind == "asym":
        zero_ref, out_ref, acc_ref = rest
    else:
        (out_ref, acc_ref), zero_ref = rest, None
    k = pl.program_id(1)
    rows = bk // block
    scale = scale_ref[:]
    zero = zero_ref[:] if zero_ref is not None else None
    if bits == 4:
        codes = _unpack_tile(data_ref[:], block, bk, bn)
        w = _dequant_tile(codes, scale, zero, kind, codebook, bk, bn)
    else:
        s = scale.astype(jnp.float32)[:, None, :]
        vals = data_ref[:].astype(jnp.float32).reshape(rows, block, bn) * s
        w = vals.reshape(bk, bn).astype(jnp.bfloat16)

    _accumulate(x_ref[:, pl.ds(k * bk, bk)], w, out_ref, acc_ref, nk,
                k_axis=1)


def _gemv_kernel_fold(x3_ref, data_ref, scale_ref, out_ref, acc_ref, *,
                      block, kind, codebook, bk, bn, nk, bits):
    """Scale-FOLDED decode-GEMV body (sym/codebook formats).

    The standard kernel multiplies every weight by its block scale before
    the matmul — a per-weight VPU multiply plus a bf16 rounding of each
    dequantized weight. Scales factor out of the contraction:

        y[m, n] = sum_r scale[r, n] * sum_{k in block r} x[m, k] c[k, n]

    so this variant feeds the MXU the RAW (shifted/LUT) codes as one
    batched-over-blocks dot_general and applies scales to the [rows, M,
    bn] partials in f32 — per-weight work drops to unpack+shift+convert,
    and the scale multiply touches M/block as many elements. For INTEGER
    codes the numerics are strictly better than the standard path (codes
    exact in bf16, scale applied once in f32: ~0.4% vs ~14% max-rel
    against the exact-f32 dequant at 7B K); codebook formats still round
    the LUT values to bf16 for the MXU, so their accuracy merely ties
    the standard body. Asym formats keep the standard kernel (the
    zero-point adds a rank-1 correction term not worth the fuss).

    x arrives PRE-SPLIT as [K/block, M, block] (host-side reshape +
    transpose): splitting x's lane dimension inside the kernel is a
    Mosaic "unsupported shape cast" (caught by the AOT suite), and the
    batch (scale-block) axis must sit at the SAME position in both dot
    operands — the chip-side Mosaic rejects lhs-batch-at-1/rhs-batch-
    at-0 with "batch dims must be equal" (seen live 2026-08-02; the
    offline Mosaic accepted it, a version skew the AOT gate can't
    see)."""
    k = pl.program_id(1)
    rows = bk // block

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    if bits == 4:
        codes = _unpack_tile(data_ref[:], block, bk, bn)  # [rows, B, bn]
        if kind == "codebook":
            c = codes
            tbl = list(codebook) + [0.0] * (16 - len(codebook))
            vals = jnp.full(c.shape, tbl[0], jnp.float32)
            for i in range(1, 16):
                vals = jnp.where(c == i, tbl[i], vals)
            cb = vals.astype(jnp.bfloat16)
        else:                                    # sym int4
            cb = (codes.astype(jnp.float32) - 8.0).astype(jnp.bfloat16)
    else:                                        # sym int8
        cb = data_ref[:].reshape(rows, block, bn).astype(jnp.bfloat16)

    # batched over scale blocks: [rows, M, B] x [rows, B, bn]
    part = jax.lax.dot_general(
        x3_ref[:], cb, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)      # [rows, M, bn]
    s = scale_ref[:].astype(jnp.float32)         # [rows, bn]
    acc_ref[:] += jnp.sum(part * s[:, None, :], axis=0)

    @pl.when(k == nk - 1)
    def _():
        out_ref[:] = acc_ref[:].astype(out_ref.dtype)


def _gemv_kernel_mxu(x3_ref, data_ref, scale_ref, out_ref, acc_ref, *,
                     block, bk, bn, nk):
    """MXU-layout decode GEMV (int4/int8-dtype weights, scale-folded).

    The canonical split-block layout costs ~6 i32 VPU ops per weight to
    unpack (widen/mask/shift/concat) — at 7B decode that chain, not HBM,
    set the 30 ms/token floor (BENCH_r04: 18% of roofline). jnp.int4
    arrays are bit-packed by XLA (same HBM bytes) and loaded natively by
    Mosaic, so per-weight work drops to ONE convert feeding the batched
    dot; scales fold onto the [rows, M, bn] partials exactly like
    `_gemv_kernel_fold` (same numerics class: integer codes exact in
    bf16, scale applied once in f32)."""
    k = pl.program_id(1)
    rows = bk // block

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    cb = data_ref[:].astype(jnp.bfloat16).reshape(rows, block, bn)
    part = jax.lax.dot_general(
        x3_ref[:], cb, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)          # [rows, M, bn]
    s = scale_ref[:].astype(jnp.float32)             # [rows, bn]
    acc_ref[:] += jnp.sum(part * s[:, None, :], axis=0)

    @pl.when(k == nk - 1)
    def _():
        out_ref[:] = acc_ref[:].astype(out_ref.dtype)


def _gemv_kernel_mxuflat(x_ref, data_ref, scale_ref, out_ref, acc_ref, *,
                         block, bk, bn, nk):
    """Flat-dot MXU-layout body: int4 native load, per-weight scale
    (2-3 VPU ops/weight vs the canonical unpack chain's ~8), then ONE
    [mp, bk] x [bk, bn] bf16 dot at full K contraction — maximum MXU
    shape efficiency. The A/B discriminator vs `_gemv_kernel_mxu`:
    r4 on-chip numbers showed fold (batched dot, fewer VPU ops) TYING
    std (flat dot, more VPU ops) at 30 ms, so which resource binds —
    VPU convert throughput or the batched-dot's short-K MXU passes —
    is an open question only silicon can answer."""
    k = pl.program_id(1)
    s = scale_ref[:].astype(jnp.float32)[:, None, :]
    codes = data_ref[:].astype(jnp.int8).astype(jnp.float32)
    w = (codes.reshape(bk // block, block, bn) * s) \
        .reshape(bk, bn).astype(jnp.bfloat16)
    _accumulate(x_ref[:, pl.ds(k * bk, bk)], w, out_ref, acc_ref, nk,
                k_axis=1)


def _gemv_kernel_mxu8(x3_ref, sxt_ref, data_ref, scale_ref, out_ref,
                      acc_ref, *, block, bk, bn, nk):
    """int8-activation variant: per-block q8 activations against the
    int4/int8 weights on the MXU's int8 path (2x the bf16 throughput),
    llama.cpp's q4_0 x q8_0 structure on TPU. The int32 block partials
    are exact; both scales (weight s[r, n], activation sx[m, r]) apply
    in f32 on the partials."""
    k = pl.program_id(1)
    rows = bk // block

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    cb = data_ref[:].astype(jnp.int8).reshape(rows, block, bn)
    part = jax.lax.dot_general(
        x3_ref[:], cb, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.int32)            # [rows, M, bn]
    s = scale_ref[:].astype(jnp.float32)             # [rows, bn]
    sxt = sxt_ref[:].astype(jnp.float32)             # [rows, M]
    scaled = part.astype(jnp.float32) * s[:, None, :]
    acc_ref[:] += jnp.sum(scaled * sxt[:, :, None], axis=0)

    @pl.when(k == nk - 1)
    def _():
        out_ref[:] = acc_ref[:].astype(out_ref.dtype)


def _scale_rows_ok(bk: int, b: int, kp: int) -> bool:
    """The streamed scale block [bk//b, bn] must satisfy Mosaic's block
    tiling: second-to-last dim divisible by 8, or equal to the full
    array dim (kp//b). Violating K values (e.g. tensor-parallel local
    shards of 11008) fall back to the XLA matmul."""
    rows = bk // b
    return rows % 8 == 0 or bk == kp


def _matmul_tiles(qt, kp: int, n: int, bk_cands,
                  budget: int = 4 * 1024 * 1024, bm: int = 16):
    """Largest eligible (bk, bn) streaming tile under the VMEM budget.

    Eligibility couples bk to the quant block (bk % block == 0) and to
    Mosaic's scale-plane tiling (`_scale_rows_ok`); naively halving bk to
    fit VMEM can break it — e.g. the full-K tile for a tp=4 shard of
    ff=11008 (K=2752, an 86-row scale plane, legal only as ONE block)
    halves to 43 rows and falls off the kernel entirely (VERDICT r3 #4).
    So search the whole (bk, bn) grid, shrinking bn before bk, and keep
    the largest legal product (ties favor the earlier = wider bn).

    The budget accounts the M-dependent terms too (x tile bm*bk bf16 +
    f32 accumulator bm*bn): at decode bm=16 they are noise, but at
    prefill-class bm=256 they rival the streamed weight tile — ignoring
    them let a forced all-M run (bench lane `pallas-all-m`) pick tiles
    whose working set overflowed VMEM at 7B geometry."""
    b = qt.block_size
    best = None
    for bn in (512, 256, 128):
        if n % bn:
            continue
        for bk in bk_cands:
            if not bk or kp % bk or bk % b \
                    or not _scale_rows_ok(bk, b, kp):
                continue
            if bk * bn * 3 + bm * (2 * bk + 4 * bn) > budget:
                continue
            if best is None or bk * bn > best[0] * best[1]:
                best = (bk, bn)
    return best


def _gemv_tiles(qt, kp: int, n: int, mp: int = 16):
    # kp itself is always legal (block dims == array dims), VMEM permitting
    return _matmul_tiles(qt, kp, n,
                         [4096, 2048, 1024, 512, 256, 128, 64, 32, kp],
                         bm=mp)


_gemv_probe_cache: dict = {}

# decode-GEMV M ceiling: the serving engine's decode batch. One padded
# sublane tile (mp=16) covers bs<=16; bs 17-32 pads to TWO sublane tiles
# (mp=32) — the x tile and accumulator double but stay VMEM-noise, and
# decode remains HBM-bound so the pad FLOPs are free.
GEMV_MAX_M = 32


def _gemv_mp(m: int) -> int:
    return 16 if m <= 16 else 32


def gemv_kernel_compiles(qtype: str, kp: int, n: int,
                         variant: str = "std", m: int = 1) -> bool:
    """Eager per-geometry probe for the decode-GEMV variant (same
    contract as ops/attention._kernel_compiles): compiles the REAL tile
    classes on a stand-in sized (kp, bn) so a Mosaic rejection degrades
    to the generic tiling instead of crashing a jitted decode.
    `variant`: "std" | "fold" | "mxu" | "mxu8" (see the kernel bodies).
    `m` only selects the padded row class (16 vs 32)."""
    qt = get_qtype(qtype)
    mp = _gemv_mp(m)
    tiles = _gemv_tiles(qt, kp, n, mp)
    if tiles is None:
        return False
    from bigdl_tpu.config import flags as _flags

    if _flags().aot_target == "tpu":   # AOT lowering: trust the dispatch
        return True
    bk, bn = tiles
    key = (qtype, kp, bn, bk, variant, mp)
    hit = _gemv_probe_cache.get(key)
    if hit is not None:
        return hit
    try:
        from bigdl_tpu.ops.probing import probe_compile, quant_struct

        mxu = variant in ("mxu", "mxu8")
        # compile-only AOT probe (see ops/probing.py) — safe inside the
        # caller's jit trace, allocates nothing on device
        probe_compile(
            lambda xx, ww: _q_gemv_pallas(xx, ww, qt, mp, kp, bn, False,
                                          jnp.bfloat16, variant=variant),
            jax.ShapeDtypeStruct((mp, kp), jnp.bfloat16),
            quant_struct(kp, bn, qtype, mxu=mxu))
        ok = True
    except Exception as e:
        import logging

        logging.getLogger(__name__).warning(
            "pallas decode-GEMV variant %s unavailable for (K=%d, N=%d, "
            "%s) — %s: %s; using the generic tiles", variant, kp, n,
            qtype, type(e).__name__, e)
        ok = False
    from bigdl_tpu.ops.probing import record_probe_result

    record_probe_result(f"gemv_{variant}", ok)
    _gemv_probe_cache[key] = ok
    return ok


_matmul_probe_cache: dict = {}


def matmul_kernel_compiles(qtype: str, m: int, kp: int, n: int,
                           mxu: bool = False) -> bool:
    """Eager per-geometry probe for the GENERIC tiled kernel. The bench
    lane `pallas-all-m` (matmul_pallas_max_m=4096) crashed the whole
    lane when a prefill-class tile hit a Mosaic rejection — the generic
    path had no probe, unlike the GEMV variants and attention. Auto
    dispatch now consults this so an unhappy geometry degrades to the
    XLA matmul instead of dying inside a jitted forward. Keyed by the
    padded bm class, not the raw M."""
    qt = get_qtype(qtype)
    bm, mp = _generic_bm(m)
    tiles = _matmul_tiles(qt, kp, n,
                          [2048, 1024, 512, 256, 128, 64, 32, kp], bm=bm)
    if tiles is None:
        return False
    from bigdl_tpu.config import flags as _flags

    if _flags().aot_target == "tpu":   # AOT lowering: trust the dispatch
        return True
    key = (qtype, bm, kp, n, bool(mxu))
    hit = _matmul_probe_cache.get(key)
    if hit is not None:
        return hit
    try:
        from bigdl_tpu.ops.probing import probe_compile, quant_struct

        probe_compile(
            lambda xx, ww: _q_matmul_generic(xx, ww, qt, bm, kp, n, False,
                                             jnp.bfloat16),
            jax.ShapeDtypeStruct((bm, kp), jnp.bfloat16),
            quant_struct(kp, n, qtype, mxu=mxu))
        ok = True
    except Exception as e:
        import logging

        logging.getLogger(__name__).warning(
            "pallas generic matmul unavailable for (M=%d, K=%d, N=%d, %s)"
            " — %s: %s; using the XLA matmul", m, kp, n, qtype,
            type(e).__name__, e)
        ok = False
    from bigdl_tpu.ops.probing import record_probe_result

    record_probe_result("matmul_generic", ok)
    _matmul_probe_cache[key] = ok
    return ok


def _q_gemv_pallas(x2: jax.Array, w: QTensor, qt, m: int, kp: int, n: int,
                   interpret: bool, out_dtype=None, variant: str = "std"):
    """bs<=GEMV_MAX_M decode GEMV (the reference's `linear_fp16_esimd`
    decode GEMV role, low_bit_linear.py:744-745). M pads to one 16-row
    sublane tile (two for bs 17-32); x [mp, K] and the scale column
    block are VMEM-resident for the whole K sweep, the grid drops the M
    axis, and bn/bk maximize the streaming tile. FLOP overhead of the
    pad is irrelevant — decode is HBM-bound.
    `variant`: "std" (unpack + per-weight scale), "fold" (scale-folded
    batched dot over the packed layout), "mxu"/"mxu8" (int4-dtype
    weights; see `_gemv_kernel_mxu`/`_gemv_kernel_mxu8`)."""
    mp = _gemv_mp(m)
    if x2.shape[0] != mp:
        x2 = jax.lax.pad(x2, jnp.zeros((), x2.dtype),
                         ((0, mp - x2.shape[0], 0), (0, 0, 0)))
    b = qt.block_size
    tiles = _gemv_tiles(qt, kp, n, mp)
    if tiles is None:
        raise NotImplementedError(f"shapes not tileable: K={kp} N={n}")
    bk, bn = tiles
    nk = kp // bk
    grid = (n // bn, nk)

    x_spec = pl.BlockSpec((mp, kp), lambda j, k: (0, 0))      # resident
    scale_spec = pl.BlockSpec((bk // b, bn), lambda j, k: (k, j))
    out_spec = pl.BlockSpec((mp, bn), lambda j, k: (0, j))
    out_shape = jax.ShapeDtypeStruct((mp, n), out_dtype or x2.dtype)
    scratch = [pltpu.VMEM((mp, bn), jnp.float32)]

    codebook = None
    if qt.kind == "codebook":
        codebook = [float(v) for v in CODEBOOKS[qt.codebook]]
    bits = qt.storage_bits

    if variant in ("mxu", "mxuflat", "mxu8"):
        if w.data.dtype not in (jnp.int4, jnp.int8):
            raise NotImplementedError(
                f"{variant} GEMV needs int4/int8-dtype weights "
                f"(got {w.data.dtype}); apply quant.to_mxu_layout")
        data_spec = pl.BlockSpec((bk, bn), lambda j, k: (k, j))
        # x pre-split per scale block OUTSIDE the kernel (lane-dim
        # reshapes inside are a Mosaic unsupported shape cast), blocks
        # leading so the batched dot's batch dims align (see
        # _gemv_kernel_fold docstring)
        x3 = x2.reshape(mp, kp // b, b).transpose(1, 0, 2)
        x3_spec = pl.BlockSpec((bk // b, mp, b), lambda j, k: (k, 0, 0))
        if variant == "mxuflat":
            kernel = functools.partial(
                _gemv_kernel_mxuflat, block=b, bk=bk, bn=bn, nk=nk)
            operands = [x2, w.data, w.scale]
            in_specs = [x_spec, data_spec, scale_spec]
        elif variant == "mxu":
            kernel = functools.partial(
                _gemv_kernel_mxu, block=b, bk=bk, bn=bn, nk=nk)
            operands = [x3, w.data, w.scale]
            in_specs = [x3_spec, data_spec, scale_spec]
        else:
            # per-block q8 activation quantization (VPU work over just
            # M x K elements, fused into the surrounding jit by XLA)
            xf = x3.astype(jnp.float32)
            amax = jnp.max(jnp.abs(xf), axis=-1)              # [K/b, mp]
            sxt = amax * (1.0 / 127.0)
            inv = jnp.where(sxt == 0, 0.0,
                            1.0 / jnp.where(sxt == 0, 1.0, sxt))
            xq = jnp.round(xf * inv[..., None]).astype(jnp.int8)
            sxt_spec = pl.BlockSpec((bk // b, mp), lambda j, k: (k, 0))
            kernel = functools.partial(
                _gemv_kernel_mxu8, block=b, bk=bk, bn=bn, nk=nk)
            operands = [xq, sxt, w.data, w.scale]
            in_specs = [x3_spec, sxt_spec, data_spec, scale_spec]
    elif variant == "fold" and qt.kind != "asym":
        kernel = functools.partial(
            _gemv_kernel_fold, block=b, kind=qt.kind, codebook=codebook,
            bk=bk, bn=bn, nk=nk, bits=bits)
        data_spec = pl.BlockSpec((bk // 2 if bits == 4 else bk, bn),
                                 lambda j, k: (k, j))
        operands = [x2.reshape(mp, kp // b, b).transpose(1, 0, 2),
                    w.data, w.scale]
        in_specs = [pl.BlockSpec((bk // b, mp, b), lambda j, k: (k, 0, 0)),
                    data_spec, scale_spec]
    else:
        kernel = functools.partial(
            _gemv_kernel, block=b, kind=qt.kind, codebook=codebook,
            bk=bk, bn=bn, nk=nk, bits=bits)
        data_spec = pl.BlockSpec((bk // 2 if bits == 4 else bk, bn),
                                 lambda j, k: (k, j))
        operands = [x2, w.data, w.scale]
        in_specs = [x_spec, data_spec, scale_spec]
        if qt.kind == "asym":
            operands.append(w.zero)
            in_specs.append(scale_spec)
    y = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_spec,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
        # N tiles are independent; only the K sweep carries the
        # accumulator — telling Mosaic lets it software-pipeline the
        # packed-data stream across j boundaries
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
    )(*operands)
    return y[:m]


def q_matmul_pallas_impl(x: jax.Array, w: QTensor, *,
                         interpret: bool = False) -> jax.Array:
    """x [..., K] @ quantized W [K, N] -> [..., N] via a fused Pallas
    kernel. Unjitted body: model forwards call this inside their own
    jit (a nested jit's closed_call fails to lower inside shard_map's
    Manual-mesh trace — caught by the explicit-TP AOT test)."""
    qt = get_qtype(w.qtype)
    if qt.kind not in ("sym", "asym", "codebook") or qt.storage_bits not in (4, 8):
        raise NotImplementedError(f"pallas kernel does not support {w.qtype}")
    if qt.storage_bits == 8 and qt.kind != "sym":
        raise NotImplementedError(f"pallas kernel does not support {w.qtype}")

    batch_shape = x.shape[:-1]
    klog, n = w.shape
    kp = w.scale.shape[0] * qt.block_size
    m = 1
    for d in batch_shape:
        m *= d
    x2 = x.reshape(m, klog).astype(jnp.bfloat16)
    if kp != klog:
        x2 = jax.lax.pad(x2, jnp.zeros((), x2.dtype),
                         ((0, 0, 0), (0, kp - klog, 0)))

    from bigdl_tpu.config import flags

    gv = flags().matmul_gemv
    if gv == "mxu8" and w.data.dtype in (jnp.int4, jnp.int8) \
            and qt.kind == "sym":
        variant = "mxu8"
    elif gv == "mxuflat" and w.data.dtype == jnp.int4:
        variant = "mxuflat"
    elif gv in ("auto", "mxu", "fold") and w.data.dtype == jnp.int4:
        variant = "mxu"          # int4-dtype layout: always the MXU body
    elif gv == "fold" and qt.kind != "asym":
        variant = "fold"
    else:
        variant = "std"
    if m <= GEMV_MAX_M and gv != "off" and (
            interpret or gemv_kernel_compiles(w.qtype, kp, n,
                                              variant=variant, m=m)):
        try:
            y = _q_gemv_pallas(x2, w, qt, m, kp, n, interpret,
                               out_dtype=x.dtype, variant=variant)
            return y.reshape(*batch_shape, n)
        except NotImplementedError:
            pass      # fall through to the generic tiling

    y = _q_matmul_generic(x2, w, qt, m, kp, n, interpret, x.dtype)
    return y.reshape(*batch_shape, n)


def _generic_bm(m: int):
    """Generic-path row tile class: (bm, mp) with mp the padded M."""
    bm = _pick_tile(m, [256, 128, 64, 32, 16])
    if bm:
        return bm, m
    mp = m + ((-m) % 16)
    return (_pick_tile(mp, [256, 128, 64, 32, 16]) or mp), mp


def _q_matmul_generic(x2: jax.Array, w: QTensor, qt, m: int, kp: int,
                      n: int, interpret: bool, out_dtype) -> jax.Array:
    """Generic-tile kernel dispatch: x2 [m, kp] bf16 (already K-padded)
    against quantized W — grid (M/bm, N/bn, K/bk). Probed per geometry
    by `matmul_kernel_compiles`."""
    # pad M up to a bf16-tileable multiple (min sublane 16)
    bm, mp = _generic_bm(m)
    if mp != m:
        x2 = jax.lax.pad(x2, jnp.zeros((), x2.dtype),
                         ((0, mp - m, 0), (0, 0, 0)))
    # joint (bk, bn) search keeps the working set (data tile + unpacked
    # w tile + x tile + accumulator) in VMEM without sacrificing
    # scale-plane legality
    tiles = _matmul_tiles(qt, kp, n,
                          [2048, 1024, 512, 256, 128, 64, 32, kp], bm=bm)
    if tiles is None:
        raise NotImplementedError(f"shapes not tileable: K={kp} N={n}")
    bk, bn = tiles

    nk = kp // bk
    grid = (mp // bm, n // bn, nk)
    b = qt.block_size

    x_spec = pl.BlockSpec((bm, bk), lambda i, j, k: (i, k))
    scale_spec = pl.BlockSpec((bk // b, bn), lambda i, j, k: (k, j))
    out_spec = pl.BlockSpec((bm, bn), lambda i, j, k: (i, j))
    out_shape = jax.ShapeDtypeStruct((mp, n), out_dtype)
    scratch = [pltpu.VMEM((bm, bn), jnp.float32)]

    if w.data.dtype == jnp.int4:
        data_spec = pl.BlockSpec((bk, bn), lambda i, j, k: (k, j))
        kernel = functools.partial(_kernel_i4, block=b, bk=bk, bn=bn, nk=nk)
        y = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[x_spec, data_spec, scale_spec],
            out_specs=out_spec,
            out_shape=out_shape,
            scratch_shapes=scratch,
            interpret=interpret,
            compiler_params=_GENERIC_SEMANTICS,
        )(x2, w.data, w.scale)
    elif qt.storage_bits == 4:
        data_spec = pl.BlockSpec((bk // 2, bn), lambda i, j, k: (k, j))
        codebook = None
        if qt.kind == "codebook":
            codebook = [float(v) for v in CODEBOOKS[qt.codebook]]
        if qt.kind == "asym":
            kernel = functools.partial(
                _kernel_4bit, block=b, kind="asym", codebook=None,
                bk=bk, bn=bn, nk=nk)
            y = pl.pallas_call(
                kernel,
                grid=grid,
                in_specs=[x_spec, data_spec, scale_spec, scale_spec],
                out_specs=out_spec,
                out_shape=out_shape,
                scratch_shapes=scratch,
                interpret=interpret,
                compiler_params=_GENERIC_SEMANTICS,
            )(x2, w.data, w.scale, w.zero)
        else:
            kernel = functools.partial(
                _kernel_4bit, block=b, kind=qt.kind, codebook=codebook,
                bk=bk, bn=bn, nk=nk)
            y = pl.pallas_call(
                kernel,
                grid=grid,
                in_specs=[x_spec, data_spec, scale_spec],
                out_specs=out_spec,
                out_shape=out_shape,
                scratch_shapes=scratch,
                interpret=interpret,
                compiler_params=_GENERIC_SEMANTICS,
            )(x2, w.data, w.scale)
    else:  # int8 sym
        data_spec = pl.BlockSpec((bk, bn), lambda i, j, k: (k, j))
        kernel = functools.partial(_kernel_int8, block=b, bk=bk, bn=bn, nk=nk)
        y = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[x_spec, data_spec, scale_spec],
            out_specs=out_spec,
            out_shape=out_shape,
            scratch_shapes=scratch,
            interpret=interpret,
            compiler_params=_GENERIC_SEMANTICS,
        )(x2, w.data, w.scale)

    if mp != m:
        y = y[:m]
    return y


# public jitted entry (standalone callers, probes, benchmarks); model
# dispatch uses the unjitted impl — see its docstring
q_matmul_pallas = functools.partial(
    jax.jit, static_argnames=("interpret",))(q_matmul_pallas_impl)
