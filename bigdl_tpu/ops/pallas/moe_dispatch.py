"""Ragged MoE dispatch: sorted token groups x per-expert weights.

The reference's Mixtral prefill runs every token through every selected
expert via a host-side Python loop (reference transformers/models/
mixtral.py:79-138); the in-repo dense fallback (models/llama.py
`_moe_mlp`) instead runs EVERY expert over EVERY token — E/k times the
needed FLOPs (4x for Mixtral 8x top-2), acceptable only because it keeps
shapes static. This module removes that waste while staying jit-static:

1. Token-choice pairs are argsorted by expert and scattered into a
   block-padded buffer: each expert's group is padded up to the token
   tile T, so every tile belongs to exactly ONE expert. The buffer size
   N*k + E*T is a static worst case; padding rows are zeros.
2. `ragged_expert_matmul` — a Pallas kernel whose weight BlockSpec
   selects the expert via a scalar-prefetched per-tile expert id
   (pltpu.PrefetchScalarGridSpec): tile i streams expert e_ids[i]'s
   packed weight block. Same dequant tile math as
   ops/pallas/dequant_matmul; dense bf16 expert stacks use a dense
   branch of the same kernel.
3. Outputs gather back through the same permutation with the routing
   weights applied in a scatter-add combine.

Exact (no capacity drops, unlike the classic fixed-capacity dispatch):
every token-choice is computed; only tile padding is wasted.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bigdl_tpu.ops.codebooks import CODEBOOKS
from bigdl_tpu.ops.quant import QTensor, get_qtype
from bigdl_tpu.ops.pallas.dequant_matmul import (_accumulate, _dequant_tile,
                                                 _pick_tile, _unpack_tile)

TOKEN_TILE = 128


def _ragged_tiles(qtype, kp: int, n: int):
    """Tile classes the kernel would pick; None when untileable."""
    b = 1
    if qtype is not None:
        qt = get_qtype(qtype)
        b = qt.block_size
        kp = -(-kp // b) * b
    bkc = [2048, 1024, 512, 256, 128, 64, 32]
    bk = _pick_tile(kp, [c for c in bkc if c % b == 0])
    bn = _pick_tile(n, [512, 256, 128])
    if not bk or not bn:
        return None
    while bk * bn * 3 > 4 * 1024 * 1024 and bk > max(b, 32):
        bk //= 2
    if kp % bk or (qtype is not None and bk % b):
        return None
    return bk, bn




def _ragged_kernel_q(e_ref, x_ref, data_ref, scale_ref, *rest, block,
                     kind, codebook, bk, bn, nk, bits):
    if kind == "asym":
        zero_ref, out_ref, acc_ref = rest
    else:
        (out_ref, acc_ref), zero_ref = rest, None
    if bits == 4:
        codes = _unpack_tile(data_ref[0], block, bk, bn)
        zero = zero_ref[0] if zero_ref is not None else None
        w = _dequant_tile(codes, scale_ref[0], zero, kind, codebook, bk, bn)
    else:
        s = scale_ref[0].astype(jnp.float32)[:, None, :]
        vals = data_ref[0].astype(jnp.float32).reshape(
            bk // block, block, bn) * s
        w = vals.reshape(bk, bn).astype(jnp.bfloat16)
    _accumulate(x_ref[:], w, out_ref, acc_ref, nk)


def _ragged_kernel_dense(e_ref, x_ref, w_ref, out_ref, acc_ref, *, nk):
    _accumulate(x_ref[:], w_ref[0].astype(jnp.bfloat16), out_ref, acc_ref,
                nk)


@functools.partial(jax.jit, static_argnames=("interpret",))
def ragged_expert_matmul(x: jax.Array,          # [Np, K] (tile-padded)
                         w,                     # QTensor/dense, leading E
                         tile_expert: jax.Array,  # [Np // T] int32
                         *, interpret: bool = False) -> jax.Array:
    """x tile i @ W[tile_expert[i]] -> [Np, N]. Np % TOKEN_TILE == 0."""
    np_, klog = x.shape
    t = TOKEN_TILE
    if np_ % t:
        raise NotImplementedError(f"Np={np_} not a multiple of {t}")
    x2 = x.astype(jnp.bfloat16)

    quantized = isinstance(w, QTensor)
    if quantized:
        qt = get_qtype(w.qtype)
        if qt.kind not in ("sym", "asym", "codebook") \
                or qt.storage_bits not in (4, 8) \
                or (qt.storage_bits == 8 and qt.kind != "sym"):
            raise NotImplementedError(
                f"ragged kernel does not support {w.qtype}")
        kp = w.scale.shape[1] * qt.block_size
        n = w.data.shape[-1]
        b = qt.block_size
    else:
        kp, n = w.shape[1], w.shape[2]
        b = 1
    if kp != klog:
        x2 = jnp.pad(x2, ((0, 0), (0, kp - klog)))

    tiles = _ragged_tiles(w.qtype if quantized else None, kp, n)
    if tiles is None:
        raise NotImplementedError(f"shapes not tileable: K={kp} N={n}")
    bk, bn = tiles
    nk = kp // bk
    grid = (np_ // t, n // bn, nk)

    x_spec = pl.BlockSpec((t, bk), lambda i, j, k, e: (i, k))
    out_spec = pl.BlockSpec((t, bn), lambda i, j, k, e: (i, j))
    out_shape = jax.ShapeDtypeStruct((np_, n), x.dtype)
    scratch = [pltpu.VMEM((t, bn), jnp.float32)]

    if quantized:
        rows = bk // 2 if qt.storage_bits == 4 else bk
        data_spec = pl.BlockSpec((1, rows, bn),
                                 lambda i, j, k, e: (e[i], k, j))
        scale_spec = pl.BlockSpec((1, bk // b, bn),
                                  lambda i, j, k, e: (e[i], k, j))
        codebook = None
        if qt.kind == "codebook":
            codebook = [float(v) for v in CODEBOOKS[qt.codebook]]
        kernel = functools.partial(
            _ragged_kernel_q, block=b, kind=qt.kind, codebook=codebook,
            bk=bk, bn=bn, nk=nk, bits=qt.storage_bits)
        operands = [w.data, w.scale]
        in_specs = [x_spec, data_spec, scale_spec]
        if qt.kind == "asym":
            operands.append(w.zero)
            in_specs.append(scale_spec)
    else:
        data_spec = pl.BlockSpec((1, bk, bn),
                                 lambda i, j, k, e: (e[i], k, j))
        kernel = functools.partial(_ragged_kernel_dense, nk=nk)
        operands = [w]
        in_specs = [x_spec, data_spec]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_spec,
        scratch_shapes=scratch,
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec, out_shape=out_shape,
        interpret=interpret,
    )(tile_expert, x2, *operands)


_probe_cache: dict = {}


def ragged_kernel_compiles(qtype: Optional[str], k: int, n: int) -> bool:
    """Eager per-geometry compile probe (same pattern as
    ops/attention._kernel_compiles): verifies tileability of the REAL
    (K, N) first, then compiles the kernel with the real tile classes on
    a small stand-in (K = 2 tiles, N = 1 tile, E = 2) so a Mosaic
    rejection degrades to the dense combine instead of crashing a jitted
    forward."""
    tiles = _ragged_tiles(qtype, k, n)
    if tiles is None:
        return False
    from bigdl_tpu.config import flags as _flags

    if _flags().aot_target == "tpu":   # AOT lowering: trust the dispatch
        return True
    bk, bn = tiles
    key = (qtype, bk, bn)
    hit = _probe_cache.get(key)
    if hit is not None:
        return hit
    try:
        from bigdl_tpu.ops.probing import (probe_compile, quant_struct,
                                           stacked_struct)

        # compile-only AOT probe (see ops/probing.py) — safe inside the
        # caller's jit trace, allocates nothing on device
        t = TOKEN_TILE
        kd = min(2 * bk, k if qtype is None else -(-k // bk) * bk)
        kd = kd - kd % bk or bk
        if qtype is None:
            w = jax.ShapeDtypeStruct((2, kd, bn), jnp.bfloat16)
        else:
            w = stacked_struct(quant_struct(kd, bn, qtype), 2)
        probe_compile(ragged_expert_matmul,
                      jax.ShapeDtypeStruct((t, kd), jnp.bfloat16), w,
                      jax.ShapeDtypeStruct((1,), jnp.int32))
        ok = True
    except Exception as e:
        import logging

        logging.getLogger(__name__).warning(
            "ragged MoE dispatch kernel unavailable for (K=%d, N=%d, %s) "
            "(%s: %s); using the dense combine path", k, n, qtype,
            type(e).__name__, e)
        ok = False
    from bigdl_tpu.ops.probing import record_probe_result

    record_probe_result("moe_ragged", ok)
    _probe_cache[key] = ok
    return ok


def moe_mlp_ragged(
    xf: jax.Array,            # [N, D]
    topi: jax.Array,          # [N, k] int32 expert choices
    topw: jax.Array,          # [N, k] f32 routing weights
    gate_w,                   # [E, D, F] stack (QTensor or dense) or None
    up_w,
    down_w,                   # [E, F, D]
    act,
    num_experts: int,
    *, interpret: bool = False,
) -> jax.Array:
    """Exact sorted-dispatch MoE MLP -> [N, D] (see module docstring)."""
    n, k = topi.shape
    t = TOKEN_TILE
    nk_tot = n * k
    # static worst case: every expert's group padded up to the tile
    np_ = -(-(nk_tot + num_experts * (t - 1)) // t) * t

    flat_e = topi.reshape(-1)
    flat_tok = jnp.repeat(jnp.arange(n, dtype=jnp.int32), k)
    flat_w = topw.reshape(-1)

    order = jnp.argsort(flat_e, stable=True)
    sorted_e = flat_e[order]
    counts = jnp.bincount(flat_e, length=num_experts)
    padded = -(-counts // t) * t                       # per-expert region
    starts = jnp.cumsum(padded) - padded               # region starts
    group_start = jnp.cumsum(counts) - counts          # in sorted order
    ranks = jnp.arange(nk_tot) - group_start[sorted_e]
    dest = starts[sorted_e] + ranks                    # [N*k] -> buffer row

    xbuf = jnp.zeros((np_, xf.shape[1]), xf.dtype)
    xbuf = xbuf.at[dest].set(xf[flat_tok[order]])

    # expert of each tile: which padded region contains its first row
    tile_first = jnp.arange(np_ // t, dtype=jnp.int32) * t
    region_end = jnp.cumsum(padded)
    tile_expert = jnp.searchsorted(region_end, tile_first,
                                   side="right").astype(jnp.int32)
    tile_expert = jnp.minimum(tile_expert, num_experts - 1)

    if gate_w is not None:
        h = act(ragged_expert_matmul(xbuf, gate_w, tile_expert,
                                     interpret=interpret)) \
            * ragged_expert_matmul(xbuf, up_w, tile_expert,
                                   interpret=interpret)
    else:
        h = act(ragged_expert_matmul(xbuf, up_w, tile_expert,
                                     interpret=interpret))
    y = ragged_expert_matmul(h.astype(xf.dtype), down_w, tile_expert,
                             interpret=interpret)      # [Np, D]

    contrib = y[dest] * flat_w[order][:, None].astype(y.dtype)
    out = jnp.zeros_like(xf).at[flat_tok[order]].add(contrib)
    return out
