"""Pallas TPU kernel: fused single-token (decode) attention over the cache.

TPU-native replacement for the reference's decode-attention kernels —
`linear_q4_0.sdp_fp8` (FP8-KV decode SDP, reference transformers/models/
llama.py:435) and ESIMD `sdp_forward` (low_bit_linear.py:744-745 gates at
models/utils.py:315-355).

Decode attention is memory-bound: the whole KV cache is read to produce one
token. The XLA fallback computes scores/softmax/values as separate fusions
with an [B,H,1,S] intermediate round-trip; this kernel walks each (batch,
kv-head) pair once — K and V stream HBM->VMEM exactly one time, the
scores/softmax/combine never leave VMEM, and FP8 caches upcast in-register
(the reference needs dedicated fp8 GEMM kernels for the same effect).

Shapes: q [B, 1, H, hd]; cache k/v [B, S, Hkv, hd] (bf16 or float8_e5m2);
pos int32 scalar or per-slot [B] (continuous batching). GQA queries ride
the sublane axis: each grid step computes the whole G = H/Hkv query group
against its kv head with one [G, hd] x [hd, S] MXU pass.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


# above this cache length the whole-S tiles exceed VMEM (k+v bf16 at
# 8k x 128 is 4MB; 16MB/core) — switch to the S-blocked online-softmax
# sweep (same state machine as the prefill flash kernel, one query row)
_RESIDENT_MAX = 4096
_NEG_INF = -1e30


def _kernel_blocked(pos_ref, q_ref, k_ref, v_ref, out_ref,
                    m_ref, l_ref, acc_ref, *, scale, sb, ns, gp):
    b = pl.program_id(0)
    sj = pl.program_id(2)
    pos = pos_ref[b]

    @pl.when(sj == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0, 0].astype(jnp.bfloat16)              # [Gp, hd]
    k = k_ref[0].astype(jnp.bfloat16)                 # [sb, hd]
    v = v_ref[0].astype(jnp.bfloat16)

    s_ = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale    # [Gp, sb]
    ids = sj * sb + jax.lax.broadcasted_iota(jnp.int32, (gp, sb), 1)
    s_ = jnp.where(ids <= pos, s_, _NEG_INF)

    m_prev = m_ref[:, :1]
    m_cur = jnp.max(s_, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    corr = jnp.exp(m_prev - m_new)
    p = jnp.exp(s_ - m_new)
    l_ref[:] = jnp.broadcast_to(
        l_ref[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True),
        l_ref.shape)
    pv = jax.lax.dot_general(
        p.astype(jnp.bfloat16), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    acc_ref[:] = acc_ref[:] * corr + pv
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(sj == ns - 1)
    def _():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        out_ref[0, 0] = (acc_ref[:] / l).astype(out_ref.dtype)


def _kernel(pos_ref, q_ref, k_ref, v_ref, out_ref, *, scale, s, gp):
    b = pl.program_id(0)
    pos = pos_ref[b]

    q = q_ref[0, 0].astype(jnp.bfloat16)              # [Gp, hd]
    # K/V arrive as [B, S, Hkv*hd] views blocked (1, S, hd) per kv head —
    # Mosaic requires the last two BLOCK dims be (8,128)-tileable, which a
    # [.., S, 1, hd] per-head block is not (the 1 sits second-to-last)
    k = k_ref[0].astype(jnp.bfloat16)                 # [S, hd]
    v = v_ref[0].astype(jnp.bfloat16)                 # [S, hd]

    scores = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale    # [Gp, S]
    ids = jax.lax.broadcasted_iota(jnp.int32, (gp, s), 1)
    scores = jnp.where(ids <= pos, scores, -jnp.inf)

    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jax.lax.dot_general(
        p.astype(jnp.bfloat16), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) / l        # [Gp, hd]
    out_ref[0, 0] = out.astype(out_ref.dtype)


def _head_scales(sc_ref, hi, n, hkv):
    """Extract one kv head's scale column [n, 1] from a [1, n, Hkv] block.

    Scale planes ride full-Hkv in the lane axis (an [.., n, 1] per-head
    block would put 1 in the lanes); the column select is a one-hot
    mask + keepdims lane reduction. The [n, 1] result broadcasts over
    the K/V rows — a rank-1 [n] vector here trips Mosaic's layout
    inference ("unsupported implicit dim change"), so keep it 2D."""
    sel = jax.lax.broadcasted_iota(jnp.int32, (n, hkv), 1) == hi
    return jnp.sum(jnp.where(sel, sc_ref[0], 0.0), axis=1, keepdims=True)


def _dequant_rows(codes_ref, sc, dt=jnp.bfloat16):
    """[S, hd] codes x [S, 1] scales -> bf16 rows, matching the XLA
    fallback's `(codes * scale).astype(bf16)` bit for bit. The int->f32
    hop goes via bf16 (codes <= 127 are exact there; Mosaic has no
    direct low-bit-int -> f32 cast)."""
    return (codes_ref[0].astype(jnp.bfloat16).astype(jnp.float32)
            * sc).astype(dt)


def _kernel_scaled(pos_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, out_ref,
                   *, scale, s, gp, hkv):
    """Resident kernel over int8/int4 codes: per-(token, head) scales
    fold into the K/V ROWS in-register (one [S, 1] broadcast each) before
    the two dots — codes only ever upcast in-register, the f32 scale
    planes stream once, and no dequantized copy touches HBM."""
    b = pl.program_id(0)
    hi = pl.program_id(1)
    pos = pos_ref[b]

    q = q_ref[0, 0].astype(jnp.bfloat16)              # [Gp, hd]
    k = _dequant_rows(k_ref, _head_scales(ks_ref, hi, s, hkv))  # [S, hd]
    v = _dequant_rows(v_ref, _head_scales(vs_ref, hi, s, hkv))

    scores = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale    # [Gp, S]
    ids = jax.lax.broadcasted_iota(jnp.int32, (gp, s), 1)
    scores = jnp.where(ids <= pos, scores, -jnp.inf)

    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jax.lax.dot_general(
        p.astype(jnp.bfloat16), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) / l        # [Gp, hd]
    out_ref[0, 0] = out.astype(out_ref.dtype)


def _kernel_blocked_scaled(pos_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
                           out_ref, m_ref, l_ref, acc_ref,
                           *, scale, sb, ns, gp, hkv):
    b = pl.program_id(0)
    hi = pl.program_id(1)
    sj = pl.program_id(2)
    pos = pos_ref[b]

    @pl.when(sj == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0, 0].astype(jnp.bfloat16)              # [Gp, hd]
    k = _dequant_rows(k_ref, _head_scales(ks_ref, hi, sb, hkv))  # [sb, hd]
    v = _dequant_rows(v_ref, _head_scales(vs_ref, hi, sb, hkv))

    s_ = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale    # [Gp, sb]
    ids = sj * sb + jax.lax.broadcasted_iota(jnp.int32, (gp, sb), 1)
    s_ = jnp.where(ids <= pos, s_, _NEG_INF)

    m_prev = m_ref[:, :1]
    m_cur = jnp.max(s_, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    corr = jnp.exp(m_prev - m_new)
    p = jnp.exp(s_ - m_new)
    l_ref[:] = jnp.broadcast_to(
        l_ref[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True),
        l_ref.shape)
    pv = jax.lax.dot_general(
        p.astype(jnp.bfloat16), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    acc_ref[:] = acc_ref[:] * corr + pv
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(sj == ns - 1)
    def _():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        out_ref[0, 0] = (acc_ref[:] / l).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def decode_attention_pallas(
    q: jax.Array,          # [B, 1, H, hd]
    k: jax.Array,          # [B, S, Hkv, hd] bf16 | float8_e5m2 | int8 | int4
    v: jax.Array,
    q_pos: jax.Array,      # scalar int32 or [B] int32
    scale: float,
    interpret: bool = False,
    k_scale=None,          # [B, S, Hkv] f32 (int8/int4 codes), else None
    v_scale=None,
) -> jax.Array:
    """Fused decode SDP. Returns [B, 1, H, hd] in q.dtype."""
    b, sq, h, hd = q.shape
    s, hkv = k.shape[1], k.shape[2]
    if sq != 1:
        raise NotImplementedError("decode kernel handles Sq == 1 only")
    scaled = k_scale is not None
    g = h // hkv
    gp = max(16, -(-g // 8) * 8)      # pad query group to a clean sublane run

    qr = q.reshape(b, hkv, g, hd)
    if gp != g:
        qr = jnp.pad(qr, ((0, 0), (0, 0), (0, gp - g), (0, 0)))
    # flatten heads into the lane axis so the per-head block is
    # (1, S, hd) — see the kernel comment; the reshape is free on the
    # contiguous [B, S, Hkv, hd] cache layout
    k2 = k.reshape(b, s, hkv * hd)
    v2 = v.reshape(b, s, hkv * hd)

    pos = jnp.broadcast_to(jnp.asarray(q_pos, jnp.int32).reshape(-1), (b,))

    q_spec = pl.BlockSpec((1, 1, gp, hd),
                          lambda bi, hi, *r: (bi, hi, 0, 0))
    if s > _RESIDENT_MAX:
        sb = 512 if s % 512 == 0 else 128
        ns = s // sb
        in_specs = [
            q_spec,
            pl.BlockSpec((1, sb, hd),
                         lambda bi, hi, sj, pos_ref: (bi, sj, hi)),
            pl.BlockSpec((1, sb, hd),
                         lambda bi, hi, sj, pos_ref: (bi, sj, hi)),
        ]
        if scaled:
            # scale planes ride full-Hkv in the lanes (see _head_scales)
            sc_spec = pl.BlockSpec((1, sb, hkv),
                                   lambda bi, hi, sj, pos_ref: (bi, sj, 0))
            in_specs += [sc_spec, sc_spec]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, hkv, ns),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, 1, gp, hd), lambda bi, hi, sj, pos_ref: (bi, hi, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((gp, 128), jnp.float32),
                pltpu.VMEM((gp, 128), jnp.float32),
                pltpu.VMEM((gp, hd), jnp.float32),
            ],
        )
        kernel = (functools.partial(_kernel_blocked_scaled, scale=scale,
                                    sb=sb, ns=ns, gp=gp, hkv=hkv)
                  if scaled else
                  functools.partial(_kernel_blocked, scale=scale, sb=sb,
                                    ns=ns, gp=gp))
    else:
        in_specs = [
            q_spec,
            pl.BlockSpec((1, s, hd), lambda bi, hi, pos_ref: (bi, 0, hi)),
            pl.BlockSpec((1, s, hd), lambda bi, hi, pos_ref: (bi, 0, hi)),
        ]
        if scaled:
            sc_spec = pl.BlockSpec((1, s, hkv),
                                   lambda bi, hi, pos_ref: (bi, 0, 0))
            in_specs += [sc_spec, sc_spec]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, hkv),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, 1, gp, hd),
                                   lambda bi, hi, pos_ref: (bi, hi, 0, 0)),
        )
        kernel = (functools.partial(_kernel_scaled, scale=scale, s=s,
                                    gp=gp, hkv=hkv)
                  if scaled else
                  functools.partial(_kernel, scale=scale, s=s, gp=gp))
    operands = (pos, qr, k2, v2)
    if scaled:
        operands += (k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32))
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, gp, hd), q.dtype),
        interpret=interpret,
    )(*operands)

    return out[:, :, :g, :].reshape(b, 1, h, hd)


def attention_geometry_ok(q, k, logits_soft_cap, sliding_window,
                          alibi_slopes, k_scale=None) -> bool:
    """Shared feature/geometry gate for BOTH Pallas attention kernels
    (decode + blockwise prefill): plain softmax attention only, aligned
    shapes, KV dtypes the kernels upcast (or dequantize) in-register."""
    if alibi_slopes is not None:
        return False
    if logits_soft_cap is not None or sliding_window is not None:
        return False
    h, hd = q.shape[2], q.shape[3]
    s, hkv = k.shape[1], k.shape[2]
    if h % hkv != 0 or hd % 64 != 0 or s % 128 != 0:
        return False
    if k.dtype in (jnp.bfloat16, jnp.float8_e5m2):
        return k_scale is None
    if k.dtype in (jnp.int8, jnp.int4):
        # block-scaled codes need their scale planes for in-kernel dequant
        return k_scale is not None
    return False


def decode_attention_supported(q, k, v, q_pos, scale, logits_soft_cap,
                               sliding_window, alibi_slopes,
                               k_scale=None) -> bool:
    """Gate for the sdp_attention dispatch (bigdl_tpu.ops.attention)."""
    return q.shape[1] == 1 and attention_geometry_ok(
        q, k, logits_soft_cap, sliding_window, alibi_slopes, k_scale)
