"""Paged KV cache: one page arena per layer + per-sequence block tables.

The slab cache (`ops/kvcache.py`) reserves `[L, max_batch, max_seq, H, D]`
up front — every slot pays worst-case `max_seq` whether it holds a 30-token
chat turn or a book. This module replaces the per-slot axis with a pooled
one: a single ``[L, num_pages, page_size, H, D]`` arena per K/V plane and an
int32 **block table** per sequence mapping logical page -> physical page
(the vLLM PagedAttention layout, re-done for XLA's static shapes). Memory
now scales with *live tokens*, so concurrency is bounded by real KV
footprint instead of ``max_batch * max_seq`` worst case, and refcounted
pages can be shared copy-on-write across requests that start with the same
prompt prefix (the radix tree in ``serving/pagepool.py``).

Static-shape rules (everything the slab layout promised still holds):

- The arena never reallocates; appends are advanced-index scatters
  ``arena.at[layer, phys, off].set(...)`` where ``phys``/``off`` come from
  the block table — one shape for the jit-compiled step's whole lifetime.
- Block tables are dense ``[B, NP]`` with ``NP = max_seq // page_size``;
  unallocated logical pages map to **page 0**, the reserved null/trash
  page. Out-of-range or padded writes land there and out-of-range reads
  gather it — both only ever touch positions attention masks out
  (``k_ids > pos``), so the garbage is never observable.
- Validity is still a per-slot ``pos``; the dense gather
  ``arena[block_tables]`` reshapes to exactly the ``[B, max_seq, H, D]``
  view the slab path reads, which is what makes paged decode byte-identical
  to slab decode (tests assert it for bf16/int8/int4).

int8/int4 storage carries the same per-(token, head) scale planes as the
slab cache — quantization happens in `paged_update_layer` with the exact
`quantize_kv` call `update_layer` uses, so codes and scales match the slab
bit for bit and pages stay in the tile-wise low-bit layout the fused
kernels stream (BitDecoding's packing argument, PAPERS.md).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from bigdl_tpu.ops.kvcache import (
    KV_CACHE_DTYPES,
    SCALED_KV_DTYPES,
    _logical_nbytes,
    kv_cache_nbytes,
    kv_dtype_name,
    quantize_kv,
    resolve_kv_cache_dtype,
)

#: physical page 0 is never handed out: it is the write sink for padded /
#: out-of-range positions and the gather source for unallocated logical
#: pages. Its contents are garbage by design — attention masks every
#: position that could read it.
NULL_PAGE = 0


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class PagedKVCache:
    """Page-arena KV storage. Block tables are NOT part of the pytree —
    they are host-owned scheduling state (numpy, mutated per admission/
    finish) and ride into the jit as a separate ``[B, NP]`` operand, so
    donating the cache never aliases the table."""

    k: jax.Array    # [L, P, page_size, H_kv, D] storage dtype
    v: jax.Array    # [L, P, page_size, H_kv, D]
    pos: jax.Array  # [B] int32: per-slot number of valid positions
    # per-(token, head) f32 dequant scales for int8/int4 storage;
    # None for the scale-free dtypes (bf16 / fp8_e5m2)
    k_scale: Optional[jax.Array] = None   # [L, P, page_size, H_kv] f32
    v_scale: Optional[jax.Array] = None

    def tree_flatten(self):
        return (self.k, self.v, self.pos, self.k_scale, self.v_scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def num_pages(self) -> int:
        return self.k.shape[1]

    @property
    def page_size(self) -> int:
        return self.k.shape[2]

    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    @property
    def batch(self) -> int:
        return self.pos.shape[0]

    @property
    def kv_dtype(self) -> str:
        """Canonical kv_cache_dtype name of the storage."""
        return kv_dtype_name(self.k.dtype)


def init_paged_cache(
    num_layers: int,
    num_pages: int,
    page_size: int,
    kv_heads: int,
    head_dim: int,
    batch: int,
    dtype=jnp.bfloat16,
    kv_cache_dtype: Optional[str] = None,
) -> PagedKVCache:
    """Allocate an empty page arena (page 0 included — the null page is
    a real physical page so every block-table entry stays a valid
    index)."""
    name = resolve_kv_cache_dtype(kv_cache_dtype)
    dt = dtype if name == "bf16" else KV_CACHE_DTYPES[name]
    shape = (num_layers, num_pages, page_size, kv_heads, head_dim)
    scaled = name in SCALED_KV_DTYPES
    sshape = (num_layers, num_pages, page_size, kv_heads)
    return PagedKVCache(
        k=jnp.zeros(shape, dt),
        v=jnp.zeros(shape, dt),
        pos=jnp.zeros((batch,), jnp.int32),
        k_scale=jnp.zeros(sshape, jnp.float32) if scaled else None,
        v_scale=jnp.zeros(sshape, jnp.float32) if scaled else None,
    )


def _page_offsets(pos: jax.Array, s_new: int, page_size: int,
                  block_tables: jax.Array
                  ) -> Tuple[jax.Array, jax.Array]:
    """(phys, off) write coordinates for ``s_new`` tokens appended at
    per-slot ``pos``. Positions whose logical page is past the table
    width redirect to the null page (their offsets stay in range, so the
    scatter is always well-formed)."""
    npp = block_tables.shape[1]
    abs_pos = pos.reshape(-1, 1) + jnp.arange(s_new, dtype=jnp.int32)
    lp = abs_pos // page_size                                 # [B, Sn]
    off = abs_pos % page_size
    phys = jnp.take_along_axis(
        block_tables, jnp.clip(lp, 0, npp - 1), axis=1)
    phys = jnp.where(lp < npp, phys, NULL_PAGE)
    return phys, off


def paged_update_layer(
    cache_k: jax.Array,
    cache_v: jax.Array,
    layer: jax.Array | int,
    k_new: jax.Array,   # [B, S_new, H_kv, D]
    v_new: jax.Array,
    pos: jax.Array,     # [B] int32 per-slot append offsets
    block_tables: jax.Array,   # [B, NP] int32
    cache_ks: Optional[jax.Array] = None,
    cache_vs: Optional[jax.Array] = None,
):
    """Append k_new/v_new through the block table (the paged analog of
    `update_layer` with per-slot pos). Quantization is the same
    `quantize_kv` call the slab path makes, so stored codes/scales are
    bit-identical to a slab cache written at the same positions. Returns
    (ck, cv) or, with scale planes, (ck, cv, cks, cvs)."""
    scaled = cache_ks is not None
    if scaled:
        k_new, ks_new = quantize_kv(k_new, cache_k.dtype)
        v_new, vs_new = quantize_kv(v_new, cache_v.dtype)
    else:
        k_new = k_new.astype(cache_k.dtype)
        v_new = v_new.astype(cache_v.dtype)
    ps = cache_k.shape[2]
    phys, off = _page_offsets(pos, k_new.shape[1], ps, block_tables)

    ck_l = jax.lax.dynamic_index_in_dim(cache_k, layer, 0, keepdims=False)
    cv_l = jax.lax.dynamic_index_in_dim(cache_v, layer, 0, keepdims=False)
    ck_l = ck_l.at[phys, off].set(k_new)
    cv_l = cv_l.at[phys, off].set(v_new)
    ck = jax.lax.dynamic_update_index_in_dim(cache_k, ck_l, layer, 0)
    cv = jax.lax.dynamic_update_index_in_dim(cache_v, cv_l, layer, 0)
    if not scaled:
        return ck, cv
    ks_l = jax.lax.dynamic_index_in_dim(cache_ks, layer, 0, keepdims=False)
    vs_l = jax.lax.dynamic_index_in_dim(cache_vs, layer, 0, keepdims=False)
    ks_l = ks_l.at[phys, off].set(ks_new)
    vs_l = vs_l.at[phys, off].set(vs_new)
    return (ck, cv,
            jax.lax.dynamic_update_index_in_dim(cache_ks, ks_l, layer, 0),
            jax.lax.dynamic_update_index_in_dim(cache_vs, vs_l, layer, 0))


def _gather_dense(plane_l: jax.Array, block_tables: jax.Array) -> jax.Array:
    """``[P, ps, ...]`` layer plane -> dense ``[B, NP * ps, ...]`` via an
    XLA `take` over the table — the fallback read the ISSUE names. With
    ``NP * ps == max_seq`` the result is shape-identical to the slab
    layout's per-layer read."""
    g = jnp.take(plane_l, block_tables, axis=0)   # [B, NP, ps, ...]
    return g.reshape((g.shape[0], g.shape[1] * g.shape[2]) + g.shape[3:])


def paged_read_layer(
    cache_k: jax.Array,
    cache_v: jax.Array,
    layer: jax.Array | int,
    block_tables: jax.Array,
    compute_dtype=jnp.bfloat16,
    cache_ks: Optional[jax.Array] = None,
    cache_vs: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Dense full-length K/V for one layer, gathered through the block
    table and upcast (dequantized when scale planes are given)."""
    from bigdl_tpu.ops.kvcache import dequantize_kv

    k = _gather_dense(jax.lax.dynamic_index_in_dim(
        cache_k, layer, 0, keepdims=False), block_tables)
    v = _gather_dense(jax.lax.dynamic_index_in_dim(
        cache_v, layer, 0, keepdims=False), block_tables)
    if cache_ks is not None:
        ks = _gather_dense(jax.lax.dynamic_index_in_dim(
            cache_ks, layer, 0, keepdims=False), block_tables)
        vs = _gather_dense(jax.lax.dynamic_index_in_dim(
            cache_vs, layer, 0, keepdims=False), block_tables)
        return (dequantize_kv(k, ks, compute_dtype),
                dequantize_kv(v, vs, compute_dtype))
    return k.astype(compute_dtype), v.astype(compute_dtype)


def paged_read_layer_quantized(
    cache_k: jax.Array,
    cache_v: jax.Array,
    cache_ks: jax.Array,
    cache_vs: jax.Array,
    layer: jax.Array | int,
    block_tables: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """One layer's raw codes + scales gathered dense (no dequant) — the
    feed for `sdp_attention(.., k_scale=, v_scale=)` so the upcast stays
    inside the fused kernels."""
    k = _gather_dense(jax.lax.dynamic_index_in_dim(
        cache_k, layer, 0, keepdims=False), block_tables)
    v = _gather_dense(jax.lax.dynamic_index_in_dim(
        cache_v, layer, 0, keepdims=False), block_tables)
    ks = _gather_dense(jax.lax.dynamic_index_in_dim(
        cache_ks, layer, 0, keepdims=False), block_tables)
    vs = _gather_dense(jax.lax.dynamic_index_in_dim(
        cache_vs, layer, 0, keepdims=False), block_tables)
    return k, v, ks, vs


def cow_copy_pages(
    cache_k: jax.Array,
    cache_v: jax.Array,
    srcs: jax.Array,    # [N] int32 physical source pages
    dsts: jax.Array,    # [N] int32 physical destination pages
    cache_ks: Optional[jax.Array] = None,
    cache_vs: Optional[jax.Array] = None,
):
    """Copy whole pages src -> dst across every layer (the copy half of
    copy-on-write). Pair lists are fixed-length per compile — the engine
    pads with (0, 0) null-page self-copies, which are harmless no-ops on
    never-read data. Sources are gathered BEFORE the scatter, so a pair
    list that read and wrote the same page would still see pre-copy
    bytes."""
    ck = cache_k.at[:, dsts].set(jnp.take(cache_k, srcs, axis=1))
    cv = cache_v.at[:, dsts].set(jnp.take(cache_v, srcs, axis=1))
    if cache_ks is None:
        return ck, cv
    cks = cache_ks.at[:, dsts].set(jnp.take(cache_ks, srcs, axis=1))
    cvs = cache_vs.at[:, dsts].set(jnp.take(cache_vs, srcs, axis=1))
    return ck, cv, cks, cvs


def gather_pages_dense(
    cache_k: jax.Array,
    cache_v: jax.Array,
    pages: jax.Array,   # [n] int32 physical pages (0-padded tail)
    cache_ks: Optional[jax.Array] = None,
    cache_vs: Optional[jax.Array] = None,
):
    """Materialize ``n`` pages as dense ``[L, 1, n * ps, H, D]`` planes —
    the slab layout a private prefill cache expects, used to seed an
    admission's cache1 from radix-shared pages. Padding pages contribute
    garbage past the seeded length, which the prefill either overwrites
    or masks (positions > pos are never attended)."""
    def dense(plane):
        g = jnp.take(plane, pages, axis=1)        # [L, n, ps, ...]
        return g.reshape(
            (g.shape[0], 1, g.shape[1] * g.shape[2]) + g.shape[3:])

    k, v = dense(cache_k), dense(cache_v)
    if cache_ks is None:
        return k, v
    return k, v, dense(cache_ks), dense(cache_vs)


def paged_cache_nbytes(num_layers: int, num_pages: int, page_size: int,
                       kv_heads: int, head_dim: int,
                       kv_cache_dtype: Optional[str] = None
                       ) -> Dict[str, int]:
    """Storage footprint of a would-be arena without allocating it.
    By substitution (batch -> num_pages, max_seq -> page_size) this is
    exactly `kv_cache_nbytes`'s math, so an arena of
    ``old_batch * (max_seq // page_size)`` pages costs byte-for-byte what
    the old slab did — the equivalence the ledger-budget acceptance test
    leans on."""
    return kv_cache_nbytes(num_layers, num_pages, page_size, kv_heads,
                           head_dim, kv_cache_dtype)


def paged_cache_bytes(cache: PagedKVCache) -> Dict[str, int]:
    """Storage footprint of a live arena: codes, scales, total."""
    codes = _logical_nbytes(cache.k) + _logical_nbytes(cache.v)
    scales = 0
    if cache.k_scale is not None:
        scales = (_logical_nbytes(cache.k_scale)
                  + _logical_nbytes(cache.v_scale))
    return {"codes": codes, "scales": scales, "total": codes + scales}


def publish_paged_cache_bytes(cache: PagedKVCache,
                              registry=None) -> Dict[str, int]:
    """Set the `bigdl_tpu_kv_cache_bytes` gauge from the arena footprint
    (same metric family as the slab cache — dashboards keep working).
    Best-effort: metric export never gates allocation."""
    sizes = paged_cache_bytes(cache)
    try:
        if registry is None:
            from bigdl_tpu.observability import default_registry
            registry = default_registry()
        g = registry.gauge(
            "bigdl_tpu_kv_cache_bytes",
            "KV cache storage bytes by dtype and component "
            "(codes | scales | total); int4 counted at two codes per byte",
            labelnames=("dtype", "component"))
        for comp, val in sizes.items():
            g.labels(cache.kv_dtype, comp).set(float(val))
    except Exception:
        pass
    return sizes
