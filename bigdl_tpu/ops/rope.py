"""Rotary position embeddings.

TPU-native equivalent of the reference's rotary kernels
(`linear_q4_0.apply_rotary_embedding_half_q_and_k`, reference
transformers/models/utils.py:203-217, and the training-mode
`FastRopeEmbedding` at transformers/layers/rope_embedding.py:40-67).
Pure-JAX: XLA fuses the mul/add chain into surrounding ops; a custom VJP is
unnecessary since the ops are natively differentiable.

Supports the "half-rotation" (llama/mistral/qwen) and "interleaved"
(gptj/gptneox-rotary, chatglm) conventions, plus linear/NTK ("dynamic")
scaling as used by the reference's long-context model variants.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def rope_freqs(
    head_dim: int,
    base: float = 10000.0,
    rotary_dim: Optional[int] = None,
    scaling_factor: float = 1.0,
) -> jax.Array:
    """Inverse frequencies [rotary_dim // 2] (f32), linear scaling only."""
    rd = rotary_dim or head_dim
    exponent = jnp.arange(0, rd, 2, dtype=jnp.float32) / rd
    inv_freq = 1.0 / (base ** exponent)
    return inv_freq / scaling_factor


def scaled_rope_freqs(
    head_dim: int,
    base: float,
    scaling: dict,
    rotary_dim: Optional[int] = None,
    max_position_embeddings: int = 4096,
):
    """(inv_freq [rd//2], attention_factor) for every HF rope_scaling type.

    Long-context rope variants the reference only reaches via per-model
    forks (chatglm2_32k etc., convert.py:862-888) are first-class here:
    linear, dynamic-NTK (static form), yarn (with the ln-scaled attention
    factor), and llama3's piecewise frequency remapping.
    """
    import math

    rd = rotary_dim or head_dim
    rtype = scaling.get("rope_type", scaling.get("type", "linear"))
    factor = float(scaling.get("factor", 1.0))
    half = jnp.arange(0, rd, 2, dtype=jnp.float32)

    if rtype in ("default", "none"):
        return rope_freqs(head_dim, base, rotary_dim), 1.0
    if rtype == "linear":
        return rope_freqs(head_dim, base, rotary_dim, factor), 1.0
    if rtype in ("dynamic", "ntk"):
        # static NTK-aware base adjustment at the scaled context length
        base = base * (factor ** (rd / (rd - 2)))
        return 1.0 / (base ** (half / rd)), 1.0
    if rtype == "llama3":
        inv = 1.0 / (base ** (half / rd))
        orig = float(scaling.get("original_max_position_embeddings", 8192))
        lo_f = float(scaling.get("low_freq_factor", 1.0))
        hi_f = float(scaling.get("high_freq_factor", 4.0))
        low_wl = orig / lo_f
        high_wl = orig / hi_f
        wavelen = 2.0 * jnp.pi / inv
        smooth = (orig / wavelen - lo_f) / (hi_f - lo_f)
        mid = (1.0 - smooth) * inv / factor + smooth * inv
        out = jnp.where(wavelen > low_wl, inv / factor, inv)
        out = jnp.where((wavelen <= low_wl) & (wavelen >= high_wl), mid, out)
        return out, 1.0
    if rtype == "yarn":
        orig = float(scaling.get("original_max_position_embeddings",
                                 max_position_embeddings))
        beta_fast = float(scaling.get("beta_fast", 32.0))
        beta_slow = float(scaling.get("beta_slow", 1.0))
        inv = 1.0 / (base ** (half / rd))

        def correction_dim(n_rot):
            return (rd * math.log(orig / (n_rot * 2 * math.pi))
                    / (2 * math.log(base)))

        low = math.floor(correction_dim(beta_fast))
        high = math.ceil(correction_dim(beta_slow))
        low, high = max(low, 0), min(high, rd - 1)
        span = max(high - low, 1e-3)
        ramp = jnp.clip((jnp.arange(rd // 2, dtype=jnp.float32) - low)
                        / span, 0.0, 1.0)
        extrap_mask = 1.0 - ramp     # 1 where NO interpolation (high freq)
        out = (inv / factor) * ramp + inv * extrap_mask
        attn = float(scaling.get(
            "attention_factor", 0.1 * math.log(factor) + 1.0))
        return out, attn
    raise NotImplementedError(f"rope_scaling type {rtype!r} not supported")


def rope_cos_sin(
    positions: jax.Array,  # [...] int positions
    inv_freq: jax.Array,   # [rd // 2]
) -> Tuple[jax.Array, jax.Array]:
    """cos/sin tables [..., rd // 2] for given positions (f32)."""
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(angles), jnp.sin(angles)


def _rotate_half(x: jax.Array) -> jax.Array:
    h = x.shape[-1] // 2
    return jnp.concatenate([-x[..., h:], x[..., :h]], axis=-1)


def apply_rope(
    x: jax.Array,           # [..., seq, heads, head_dim] or [..., seq, head_dim]
    cos: jax.Array,         # [..., seq, rd // 2]
    sin: jax.Array,
    interleaved: bool = False,
) -> jax.Array:
    """Apply rotary embedding over the last dim's first 2*(rd//2) channels.

    cos/sin are broadcast over the heads axis; pass tables built from the
    *same* positions used to index the KV cache.
    """
    dt = x.dtype
    rd2 = cos.shape[-1]
    rd = rd2 * 2
    xf = x.astype(jnp.float32)
    x_rot, x_pass = xf[..., :rd], xf[..., rd:]

    if x.ndim == cos.ndim + 1:
        # insert heads axis: [..., seq, 1, rd2]
        cos = cos[..., None, :]
        sin = sin[..., None, :]

    if interleaved:
        x1 = x_rot[..., 0::2]
        x2 = x_rot[..., 1::2]
        o1 = x1 * cos - x2 * sin
        o2 = x2 * cos + x1 * sin
        out = jnp.stack([o1, o2], axis=-1).reshape(x_rot.shape)
    else:
        cs = jnp.concatenate([cos, cos], axis=-1)
        sn = jnp.concatenate([sin, sin], axis=-1)
        out = x_rot * cs + _rotate_half(x_rot) * sn

    if x_pass.shape[-1]:
        out = jnp.concatenate([out, x_pass], axis=-1)
    return out.astype(dt)
