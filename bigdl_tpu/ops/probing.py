"""Compile-only kernel probes.

Per-geometry dispatch probes (ops/attention._kernel_compiles,
ops/pallas/dequant_matmul.gemv_kernel_compiles, ops/matmul.
vmapped_pallas_ok, ops/pallas/moe_dispatch.ragged_kernel_compiles) must
answer "does Mosaic accept this kernel at this geometry?" from INSIDE a
model's outer jit trace, without crashing it.

The round-2 probes executed a tiny concrete call under
`jax.ensure_compile_time_eval()`. On a live TPU that shortcut routes the
pallas kernel-body trace into the eager evaluator, where grid primitives
have no eval rule — every probe died with "Evaluation rule for
'program_id' not implemented" and silently pinned every geometry to XLA
(caught on-chip, round 3: the first real-hardware bench ran 0 of 4
kernel families).

AOT lower+compile from abstract `ShapeDtypeStruct`s fixes it and is
strictly better: nothing executes, no device buffers are allocated next
to a resident multi-GB model, and the fresh `jax.jit(...).lower()`
trace is independent of any ambient trace, so no tracer ever leaks in
or out.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def record_probe_result(kernel: str, ok: bool) -> None:
    """Count a probe outcome in the observability registry
    (bigdl_tpu_kernel_probe_total{kernel, outcome="compiled"|"fallback"}).
    Every dispatch-site probe calls this exactly once per new geometry,
    making the round-3 failure class — every kernel silently pinned to
    XLA — visible on /metrics."""
    try:
        from bigdl_tpu.observability.metrics import default_registry

        default_registry().counter(
            "bigdl_tpu_kernel_probe_total",
            "Kernel compile-probe outcomes "
            "(compiled vs XLA fallback) per kernel.",
            labelnames=("kernel", "outcome"),
        ).labels(kernel, "compiled" if ok else "fallback").inc()
    except Exception:
        pass  # telemetry must never break dispatch


def probe_compile(fn, *arg_structs) -> None:
    """AOT-compile `fn` for the ambient backend from abstract shapes.

    Raises whatever the lowering/compilation raises (the caller's
    probe classifies it permanent vs transient). Safe while tracing an
    outer jit: only ShapeDtypeStructs cross the boundary.
    """
    jax.jit(fn).lower(*arg_structs).compile()


def stacked_struct(tree, n: int):
    """ShapeDtypeStruct pytree of `tree` with a leading axis of `n`
    prepended to every leaf (QTensor-safe) — abstract analog of
    `jax.tree.map(lambda a: jnp.stack([a] * n), tree)`."""
    return jax.tree_util.tree_map(
        lambda s: jax.ShapeDtypeStruct((n,) + s.shape, s.dtype), tree)


def quant_struct(k: int, n: int, qtype: str, mxu: bool = False):
    """Abstract QTensor [k, n] for `qtype` — the shapes/dtypes quantize()
    would produce, computed without materializing anything (eval_shape
    stays fully abstract for the jnp-only sym/asym/codebook encoders the
    Pallas kernels support). `mxu` applies the int4-dtype MXU layout
    (quant.to_mxu_layout) to the abstract result."""
    from bigdl_tpu.ops.quant import quantize, to_mxu_layout

    def build():
        qt = quantize(jnp.zeros((k, n), jnp.float32), qtype)
        return to_mxu_layout(qt) if mxu else qt

    return jax.eval_shape(build)
