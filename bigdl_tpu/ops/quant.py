"""Quantization core: qtype registry, QTensor pytree, quantize/dequantize.

TPU-native re-design of the reference's ggml quantization layer
(reference: python/llm/src/ipex_llm/ggml/quantize.py:28-47 qtype registry;
native `ggml_quantize_tensor` / `ggml_dequantize` C API bound at
ggml/model/llama/llama_cpp.py:946-1127; `FP4Params` quantized parameter at
transformers/low_bit_linear.py:264-455).

Differences from the reference, by design:

- **Layout is contraction-major.** A quantized linear weight is stored as a
  ``[K, N]`` array (K = in_features = contraction dim, N = out_features), with
  quantization blocks running along K. HF checkpoints store ``[N, K]``; we
  transpose at quantize time. This makes the XLA fallback a plain
  ``x @ dequantize(w)`` and lets Pallas tile the packed data directly onto
  (sublane, lane) = (K-tiles, N-tiles) without transposes in the hot loop.
- **4-bit packing is "split-block"**: within each block of B values along K,
  packed byte j (j < B/2) holds value j in its low nibble and value j + B/2 in
  its high nibble (same as ggml q4_0's qs layout, ggml-common scheme). Unpack
  is then a concat of two nibble planes — no interleave — which vectorizes
  cleanly on the VPU.
- Scales are stored per (block, N) in bfloat16 (the reference's ggml blocks
  use fp16 scales, but Mosaic/TPU has no f16 compute; bf16 is native) and
  promoted to f32 in compute. GGUF/ggml checkpoint import converts f16
  scales to bf16 at load time.
- Everything is a registered JAX pytree, so QTensors live directly inside
  model parameter trees, shard with `jax.sharding`, and pass through jit.

Quantization here is vectorized JAX (it runs once, at load time). The hot
path — dequant-matmul — lives in ``bigdl_tpu/ops/matmul.py`` (XLA fallback)
and ``bigdl_tpu/ops/pallas/`` (TPU kernels).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.ops.codebooks import CODEBOOKS


# ---------------------------------------------------------------------------
# QType registry (mirrors ggml_tensor_qtype, reference ggml/quantize.py:28-47)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class QType:
    name: str
    bits: int                 # logical bits per value
    block_size: int           # values per scale block (along K)
    kind: str                 # "sym" | "asym" | "codebook" | "fp8"
    storage_bits: int         # bits actually used in the packed layout
    codebook: Optional[str] = None  # key into CODEBOOKS for kind == "codebook"

    @property
    def is_4bit(self) -> bool:
        return self.storage_bits == 4


def _q(name, bits, block, kind, storage_bits=None, codebook=None):
    return QType(name, bits, block, kind, storage_bits or bits, codebook)


# Names follow the reference's user-facing strings (load_in_low_bit=...).
QTYPES = {
    "sym_int4": _q("sym_int4", 4, 32, "sym"),
    "asym_int4": _q("asym_int4", 4, 32, "asym"),
    "sym_int5": _q("sym_int5", 5, 32, "sym"),
    "asym_int5": _q("asym_int5", 5, 32, "asym"),
    "sym_int8": _q("sym_int8", 8, 32, "sym"),
    "nf4": _q("nf4", 4, 64, "codebook", codebook="nf4"),
    "nf3": _q("nf3", 3, 64, "codebook", storage_bits=4, codebook="nf3"),
    "fp4": _q("fp4", 4, 64, "codebook", codebook="fp4"),
    "fp8_e4m3": _q("fp8_e4m3", 8, 128, "fp8"),
    "fp8_e5m2": _q("fp8_e5m2", 8, 128, "fp8"),
    # 2-bit k-quant: 256-value superblocks of 16 sub-blocks, 4-bit
    # sub-scales/mins under fp16 super scales (ggml Q2_K; the format behind
    # the reference's "Mixtral on 16 GB" claim, README.md:16)
    "q2_k": _q("q2_k", 2, 256, "q2k"),
}
# Aliases used throughout the reference API surface.
QTYPES["int4"] = QTYPES["sym_int4"]
QTYPES["q4_0"] = QTYPES["sym_int4"]
QTYPES["q4_1"] = QTYPES["asym_int4"]
QTYPES["q5_0"] = QTYPES["sym_int5"]
QTYPES["q5_1"] = QTYPES["asym_int5"]
QTYPES["int8"] = QTYPES["sym_int8"]
QTYPES["q8_0"] = QTYPES["sym_int8"]
QTYPES["fp8"] = QTYPES["fp8_e5m2"]

# float passthrough "qtypes" accepted by the convert API (no QTensor made).
FLOAT_QTYPES = ("fp16", "bf16", "fp32")

_FP8_MAX = {"fp8_e4m3": 448.0, "fp8_e5m2": 57344.0}
_FP8_DTYPE = {"fp8_e4m3": jnp.float8_e4m3fn, "fp8_e5m2": jnp.float8_e5m2}


def is_valid_qtype(name: str) -> bool:
    """True for concrete qtypes AND mixed_* policies."""
    return name in QTYPES or name in MIXED_QTYPES


def get_qtype(name: str) -> QType:
    try:
        return QTYPES[name]
    except KeyError:
        raise ValueError(
            f"unknown qtype {name!r}; known: {sorted(set(QTYPES))} + {FLOAT_QTYPES}"
        ) from None


# ---------------------------------------------------------------------------
# QTensor pytree
# ---------------------------------------------------------------------------


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QTensor:
    """A block-quantized 2-D tensor of logical shape [K, N], blocks along K.

    Fields:
      data:  packed codes. 4-bit: uint8 [K//2, N] split-block nibble packing.
             8-bit sym: int8 [K, N]. fp8: float8_* [K, N].
      scale: bf16 [K // block, N] per-block scale (q2_k: superblock d).
      zero:  bf16 [K // block, N] per-block minimum (asym kinds), the
             superblock dmin (q2_k), or None.
      aux:   uint8 extra plane or None. int5 kinds: [K // 8, N] high-bit
             plane. q2_k: [K // 16, N] packed 4-bit sub-scale (low nibble)
             and sub-min (high nibble) per 16-value sub-block.
      qtype: qtype name (static).
      shape: logical (K, N) before padding (static). K may be padded up to a
             block multiple in `data`; `shape` records the true K.
    """

    data: jax.Array
    scale: jax.Array
    zero: Optional[jax.Array]
    qtype: str
    shape: Tuple[int, int]
    aux: Optional[jax.Array] = None

    # -- pytree protocol ----------------------------------------------------
    def tree_flatten(self):
        return (self.data, self.scale, self.zero, self.aux), (self.qtype, self.shape)

    @classmethod
    def tree_unflatten(cls, aux_data, children):
        data, scale, zero, aux = children
        qtype, shape = aux_data
        return cls(data, scale, zero, qtype, shape, aux)

    # -- conveniences -------------------------------------------------------
    @property
    def qt(self) -> QType:
        return get_qtype(self.qtype)

    @property
    def k(self) -> int:
        return self.shape[0]

    @property
    def n(self) -> int:
        return self.shape[1]

    @property
    def nbytes(self) -> int:
        tot = self.data.size * self.data.dtype.itemsize
        tot += self.scale.size * self.scale.dtype.itemsize
        if self.zero is not None:
            tot += self.zero.size * self.zero.dtype.itemsize
        if self.aux is not None:
            tot += self.aux.size * self.aux.dtype.itemsize
        return tot

    def dequantize(self, dtype=jnp.bfloat16) -> jax.Array:
        return dequantize(self, dtype=dtype)

    def __repr__(self):
        return (f"QTensor({self.qtype}, shape={self.shape}, "
                f"block={self.qt.block_size})")


# ---------------------------------------------------------------------------
# Packing helpers (split-block nibble layout)
# ---------------------------------------------------------------------------


def _safe_inv(x: jax.Array) -> jax.Array:
    """1/x with 0 -> 0 (no NaNs from empty/zero blocks)."""
    return jnp.where(x == 0, 0.0, 1.0 / jnp.where(x == 0, 1.0, x))


def _pack4(codes: jax.Array, block: int) -> jax.Array:
    """[K, N] uint8 codes (0..15) -> [K//2, N] split-block packed bytes."""
    k, n = codes.shape
    b2 = block // 2
    blk = codes.reshape(k // block, block, n)
    lo = blk[:, :b2, :]
    hi = blk[:, b2:, :]
    packed = (lo | (hi << 4)).astype(jnp.uint8)
    return packed.reshape(k // 2, n)


def _unpack4(packed: jax.Array, block: int) -> jax.Array:
    """[K//2, N] packed bytes -> [K, N] uint8 codes (0..15)."""
    k2, n = packed.shape
    b2 = block // 2
    blk = packed.reshape(k2 // b2, b2, n)
    lo = blk & jnp.uint8(0x0F)
    hi = blk >> 4
    return jnp.concatenate([lo, hi], axis=1).reshape(k2 * 2, n)


def _pack_bits1(bits: jax.Array) -> jax.Array:
    """[K, N] 0/1 uint8 -> [K//8, N] bit plane (bit j = row 8*i+j)."""
    k, n = bits.shape
    b = bits.reshape(k // 8, 8, n).astype(jnp.uint8)
    shifts = jnp.arange(8, dtype=jnp.uint8).reshape(1, 8, 1)
    return jnp.sum(b << shifts, axis=1).astype(jnp.uint8)


def _unpack_bits1(plane: jax.Array) -> jax.Array:
    """[K//8, N] bit plane -> [K, N] 0/1 uint8."""
    k8, n = plane.shape
    shifts = jnp.arange(8, dtype=jnp.uint8).reshape(1, 8, 1)
    bits = (plane[:, None, :] >> shifts) & jnp.uint8(1)
    return bits.reshape(k8 * 8, n)


def _pack2(codes: jax.Array, block: int) -> jax.Array:
    """[K, N] uint8 codes (0..3) -> [K//4, N]: 4 planes of block//4 rows."""
    k, n = codes.shape
    b4 = block // 4
    blk = codes.reshape(k // block, 4, b4, n)
    packed = (blk[:, 0] | (blk[:, 1] << 2) | (blk[:, 2] << 4)
              | (blk[:, 3] << 6)).astype(jnp.uint8)
    return packed.reshape(k // 4, n)


def _unpack2(packed: jax.Array, block: int) -> jax.Array:
    """[K//4, N] -> [K, N] uint8 codes (0..3)."""
    k4, n = packed.shape
    b4 = block // 4
    blk = packed.reshape(k4 // b4, b4, n)
    planes = jnp.stack([(blk >> (2 * i)) & jnp.uint8(3) for i in range(4)],
                       axis=1)
    return planes.reshape(k4 * 4, n)


def _pad_k(x: jax.Array, block: int) -> jax.Array:
    k = x.shape[0]
    rem = (-k) % block
    if rem:
        x = jnp.pad(x, ((0, rem), (0, 0)))
    return x


def _codebook_encode(code: np.ndarray, xn: jax.Array) -> jax.Array:
    """Nearest-codebook-entry encode via searchsorted on the sorted table."""
    order = np.argsort(code)
    sorted_code = code[order]
    bounds = (sorted_code[1:] + sorted_code[:-1]) / 2.0
    idx_sorted = jnp.searchsorted(jnp.asarray(bounds), xn)
    perm = jnp.asarray(order.astype(np.uint8))
    return perm[idx_sorted]


# ---------------------------------------------------------------------------
# quantize / dequantize
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("qtype",))
def quantize(x: jax.Array, qtype: str) -> QTensor:
    """Quantize a [K, N] float array along K (blockwise) into a QTensor.

    For an HF linear weight w of shape [out, in], call
    ``quantize(w.T, qtype)`` (see `quantize_linear`).
    """
    qt = get_qtype(qtype)
    if x.ndim != 2:
        raise ValueError(
            f"quantize expects a 2-D [K, N] array, got shape {x.shape}; "
            "reshape/flatten leading dims first"
        )
    k, n = x.shape
    b = qt.block_size
    x = _pad_k(x.astype(jnp.float32), b)
    kp = x.shape[0]
    nblk = kp // b
    xb = x.reshape(nblk, b, n)

    if qt.kind == "sym":
        # ggml-style signed-absmax scale: the max-|x| element maps exactly to
        # the most negative code (reference native q4_0/q5_0/q8_0 quantizers).
        amax_i = jnp.argmax(jnp.abs(xb), axis=1, keepdims=True)
        mx = jnp.take_along_axis(xb, amax_i, axis=1)  # [nblk, 1, n], signed
        half = float(1 << (qt.bits - 1))
        d = mx / -half
        inv = _safe_inv(d)
        q = jnp.clip(jnp.round(xb * inv) + half, 0, 2 * half - 1)
        q = q.reshape(kp, n).astype(jnp.uint8)
        scale = d.reshape(nblk, n).astype(jnp.bfloat16)
        if qt.bits == 4:
            return QTensor(_pack4(q, b), scale, None, qtype, (k, n))
        if qt.bits == 5:
            lo = _pack4(q & jnp.uint8(0x0F), b)
            hi = _pack_bits1(q >> 4)
            return QTensor(lo, scale, None, qtype, (k, n), aux=hi)
        if qt.bits == 8:
            q8 = (q.astype(jnp.int16) - 128).astype(jnp.int8)  # signed codes
            return QTensor(q8, scale, None, qtype, (k, n))
        raise ValueError(f"unsupported sym bits {qt.bits}")

    if qt.kind == "asym":
        mn = jnp.min(xb, axis=1, keepdims=True)
        mxv = jnp.max(xb, axis=1, keepdims=True)
        levels = float((1 << qt.bits) - 1)
        d = (mxv - mn) / levels
        inv = _safe_inv(d)
        q = jnp.clip(jnp.round((xb - mn) * inv), 0, levels)
        q = q.reshape(kp, n).astype(jnp.uint8)
        scale = d.reshape(nblk, n).astype(jnp.bfloat16)
        zero = mn.reshape(nblk, n).astype(jnp.bfloat16)
        if qt.bits == 4:
            return QTensor(_pack4(q, b), scale, zero, qtype, (k, n))
        if qt.bits == 5:
            lo = _pack4(q & jnp.uint8(0x0F), b)
            hi = _pack_bits1(q >> 4)
            return QTensor(lo, scale, zero, qtype, (k, n), aux=hi)
        raise ValueError(f"unsupported asym bits {qt.bits}")

    if qt.kind == "codebook":
        code = CODEBOOKS[qt.codebook]
        amax = jnp.max(jnp.abs(xb), axis=1, keepdims=True)
        d = amax
        inv = _safe_inv(d)
        q = _codebook_encode(code, xb * inv).reshape(kp, n).astype(jnp.uint8)
        scale = d.reshape(nblk, n).astype(jnp.bfloat16)
        return QTensor(_pack4(q, b), scale, None, qtype, (k, n))

    if qt.kind == "q2k":
        # per 16-value sub-block: asymmetric 2-bit with 4-bit quantized
        # sub scale/min under per-superblock fp16 scales (ggml Q2_K shape)
        sub = xb.reshape(nblk, b // 16, 16, n)
        mn = jnp.minimum(jnp.min(sub, axis=2), 0.0)        # [nblk, 16, n]
        mxv = jnp.max(sub, axis=2)
        ssc = jnp.maximum(mxv - mn, 0.0) / 3.0             # sub scale
        smin = -mn                                          # sub min (>=0)
        d = jnp.max(ssc, axis=1, keepdims=True) / 15.0     # [nblk, 1, n]
        dmin = jnp.max(smin, axis=1, keepdims=True) / 15.0
        dinv = _safe_inv(d)
        minv = _safe_inv(dmin)
        sc4 = jnp.clip(jnp.round(ssc * dinv), 0, 15).astype(jnp.uint8)
        m4 = jnp.clip(jnp.round(smin * minv), 0, 15).astype(jnp.uint8)
        eff_sc = d * sc4                                    # [nblk, 16, n]
        eff_m = dmin * m4
        inv_sc = _safe_inv(eff_sc)
        q = jnp.clip(jnp.round((sub + eff_m[:, :, None, :])
                               * inv_sc[:, :, None, :]), 0, 3)
        q = q.reshape(kp, n).astype(jnp.uint8)
        aux = (sc4 | (m4 << 4)).reshape(kp // 16, n)        # [K/16, N]
        return QTensor(
            _pack2(q, b),
            d[:, 0, :].astype(jnp.bfloat16),
            dmin[:, 0, :].astype(jnp.bfloat16),
            qtype, (k, n), aux=aux)

    if qt.kind == "fp8":
        fmax = _FP8_MAX[qt.name]
        fdt = _FP8_DTYPE[qt.name]
        amax = jnp.max(jnp.abs(xb), axis=1, keepdims=True)
        d = amax / fmax
        inv = _safe_inv(d)
        q = (xb * inv).astype(fdt).reshape(kp, n)
        scale = d.reshape(nblk, n).astype(jnp.bfloat16)
        return QTensor(q, scale, None, qtype, (k, n))

    raise ValueError(f"unsupported qtype kind {qt.kind}")


def _expand_scale(scale: jax.Array, block: int, kp: int) -> jax.Array:
    """[nblk, N] -> [K, N] by repeating each block row `block` times."""
    nblk, n = scale.shape
    return jnp.broadcast_to(
        scale.astype(jnp.float32)[:, None, :], (nblk, block, n)
    ).reshape(kp, n)


@functools.partial(jax.jit, static_argnames=("dtype",))
def dequantize(qt: QTensor, dtype=jnp.bfloat16) -> jax.Array:
    """QTensor -> dense [K, N] array of `dtype` (XLA reference path)."""
    t = qt.qt
    k, n = qt.shape
    b = t.block_size

    if t.kind == "sym" and t.bits == 8:
        kp = qt.data.shape[0]
        vals = qt.data.astype(jnp.float32)  # signed codes in [-128, 127]
        out = vals * _expand_scale(qt.scale, b, kp)
        return out[:k].astype(dtype)

    if t.kind == "fp8":
        kp = qt.data.shape[0]
        vals = qt.data.astype(jnp.float32)
        out = vals * _expand_scale(qt.scale, b, kp)
        return out[:k].astype(dtype)

    if t.kind == "codebook":
        codes = _unpack4(qt.data, b)
        kp = codes.shape[0]
        code = jnp.asarray(CODEBOOKS[t.codebook])
        vals = code[codes]
        out = vals * _expand_scale(qt.scale, b, kp)
        return out[:k].astype(dtype)

    if t.kind == "sym" and t.bits == 4:
        codes = _unpack4(qt.data, b)
        kp = codes.shape[0]
        vals = codes.astype(jnp.float32) - 8.0
        out = vals * _expand_scale(qt.scale, b, kp)
        return out[:k].astype(dtype)

    if t.kind == "sym" and t.bits == 5:
        lo = _unpack4(qt.data, b)
        hi = _unpack_bits1(qt.aux)
        kp = lo.shape[0]
        codes = lo | (hi[:kp] << 4)
        vals = codes.astype(jnp.float32) - 16.0
        out = vals * _expand_scale(qt.scale, b, kp)
        return out[:k].astype(dtype)

    if t.kind == "asym" and t.bits == 4:
        codes = _unpack4(qt.data, b)
        kp = codes.shape[0]
        d = _expand_scale(qt.scale, b, kp)
        m = _expand_scale(qt.zero, b, kp)
        out = codes.astype(jnp.float32) * d + m
        return out[:k].astype(dtype)

    if t.kind == "q2k":
        codes = _unpack2(qt.data, b).astype(jnp.float32)    # [Kp, N]
        kp = codes.shape[0]
        sc4 = (qt.aux & jnp.uint8(0xF)).astype(jnp.float32)  # [Kp/16, N]
        m4 = (qt.aux >> 4).astype(jnp.float32)
        rep16 = lambda a: jnp.repeat(a, 16, axis=0)
        d = _expand_scale(qt.scale, b, kp)
        dmin = _expand_scale(qt.zero, b, kp)
        out = d * rep16(sc4) * codes - dmin * rep16(m4)
        return out[:k].astype(dtype)

    if t.kind == "asym" and t.bits == 5:
        lo = _unpack4(qt.data, b)
        hi = _unpack_bits1(qt.aux)
        kp = lo.shape[0]
        codes = lo | (hi[:kp] << 4)
        d = _expand_scale(qt.scale, b, kp)
        m = _expand_scale(qt.zero, b, kp)
        out = codes.astype(jnp.float32) * d + m
        return out[:k].astype(dtype)

    raise ValueError(f"cannot dequantize {t.name}")


# ---------------------------------------------------------------------------
# Linear-weight conveniences (HF [out, in] orientation)
# ---------------------------------------------------------------------------


# Mixed-precision policies: per-TENSOR candidate pick by dequantization MSE
# (the reference's mixed_fp4/mixed_fp8, low_bit_linear.py:302-335: each
# layer independently gets whichever 4-/8-bit format reconstructs it best).
MIXED_QTYPES = {
    "mixed_fp4": ("fp4", "nf4", "sym_int4"),
    "mixed_fp8": ("fp8_e4m3", "fp8_e5m2", "sym_int8"),
}


def quantize_auto(x: jax.Array, qtype: str) -> QTensor:
    """quantize(), plus the mixed_* policies (MSE-picked candidate)."""
    if qtype not in MIXED_QTYPES:
        return quantize(x, qtype)
    xf = jnp.asarray(x, jnp.float32)
    best_qt, best_err = None, None
    for cand in MIXED_QTYPES[qtype]:
        qt = quantize(xf, cand)
        err = float(jnp.mean(
            (dequantize(qt, jnp.float32) - xf) ** 2))
        if best_err is None or err < best_err:
            best_qt, best_err = qt, err
    return best_qt


def quantize_linear(w_out_in: jax.Array, qtype: str) -> QTensor:
    """Quantize an HF-layout linear weight [out, in] -> QTensor [in, out]."""
    return quantize_auto(jnp.asarray(w_out_in).T, qtype)


def dequantize_linear(qt: QTensor, dtype=jnp.bfloat16) -> jax.Array:
    """QTensor [in, out] -> HF-layout dense weight [out, in]."""
    return dequantize(qt, dtype=dtype).T
