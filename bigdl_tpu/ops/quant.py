"""Quantization core: qtype registry, QTensor pytree, quantize/dequantize.

TPU-native re-design of the reference's ggml quantization layer
(reference: python/llm/src/ipex_llm/ggml/quantize.py:28-47 qtype registry;
native `ggml_quantize_tensor` / `ggml_dequantize` C API bound at
ggml/model/llama/llama_cpp.py:946-1127; `FP4Params` quantized parameter at
transformers/low_bit_linear.py:264-455).

Differences from the reference, by design:

- **Layout is contraction-major.** A quantized linear weight is stored as a
  ``[K, N]`` array (K = in_features = contraction dim, N = out_features), with
  quantization blocks running along K. HF checkpoints store ``[N, K]``; we
  transpose at quantize time. This makes the XLA fallback a plain
  ``x @ dequantize(w)`` and lets Pallas tile the packed data directly onto
  (sublane, lane) = (K-tiles, N-tiles) without transposes in the hot loop.
- **4-bit packing is "split-block"**: within each block of B values along K,
  packed byte j (j < B/2) holds value j in its low nibble and value j + B/2 in
  its high nibble (same as ggml q4_0's qs layout, ggml-common scheme). Unpack
  is then a concat of two nibble planes — no interleave — which vectorizes
  cleanly on the VPU.
- Scales are stored per (block, N) in bfloat16 (the reference's ggml blocks
  use fp16 scales, but Mosaic/TPU has no f16 compute; bf16 is native) and
  promoted to f32 in compute. GGUF/ggml checkpoint import converts f16
  scales to bf16 at load time.
- Everything is a registered JAX pytree, so QTensors live directly inside
  model parameter trees, shard with `jax.sharding`, and pass through jit.

Quantization here is vectorized JAX (it runs once, at load time). The hot
path — dequant-matmul — lives in ``bigdl_tpu/ops/matmul.py`` (XLA fallback)
and ``bigdl_tpu/ops/pallas/`` (TPU kernels).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.ops.codebooks import CODEBOOKS


# ---------------------------------------------------------------------------
# QType registry (mirrors ggml_tensor_qtype, reference ggml/quantize.py:28-47)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class QType:
    name: str
    bits: int                 # logical bits per value
    block_size: int           # values per scale block (along K)
    kind: str                 # "sym" | "asym" | "codebook" | "fp8"
    storage_bits: int         # bits actually used in the packed layout
    codebook: Optional[str] = None  # key into CODEBOOKS for kind == "codebook"

    @property
    def is_4bit(self) -> bool:
        return self.storage_bits == 4


def _q(name, bits, block, kind, storage_bits=None, codebook=None):
    return QType(name, bits, block, kind, storage_bits or bits, codebook)


# Names follow the reference's user-facing strings (load_in_low_bit=...).
QTYPES = {
    "sym_int4": _q("sym_int4", 4, 32, "sym"),
    "asym_int4": _q("asym_int4", 4, 32, "asym"),
    "sym_int5": _q("sym_int5", 5, 32, "sym"),
    "asym_int5": _q("asym_int5", 5, 32, "asym"),
    "sym_int8": _q("sym_int8", 8, 32, "sym"),
    "nf4": _q("nf4", 4, 64, "codebook", codebook="nf4"),
    "nf3": _q("nf3", 3, 64, "codebook", storage_bits=4, codebook="nf3"),
    "fp4": _q("fp4", 4, 64, "codebook", codebook="fp4"),
    "fp8_e4m3": _q("fp8_e4m3", 8, 128, "fp8"),
    "fp8_e5m2": _q("fp8_e5m2", 8, 128, "fp8"),
    # 2-bit k-quant: 256-value superblocks of 16 sub-blocks, 4-bit
    # sub-scales/mins under fp16 super scales (ggml Q2_K; the format behind
    # the reference's "Mixtral on 16 GB" claim, README.md:16)
    "q2_k": _q("q2_k", 2, 256, "q2k"),
    # Ultra-low-bit group-codebook formats (TPU-native re-designs of the
    # reference's imatrix-weighted gguf_iq2_xxs / gguf_iq1_s, SURVEY.md
    # §2.3-B ggml_quantize_tensor_with_weights): groups of 8 values map to
    # one entry of a deterministic codebook (ops/codebooks.py
    # group_codebook) + per-32 4-bit sub-scales + per-256 bf16 scales.
    # iq2_xxs: 8-bit magnitude-pattern index + 8 sign bits = 2.19 bpw.
    # iq2_xs: 9-bit index + 7-bit parity-constrained signs in the SAME
    #   16 bits (double codebook at identical storage; ggml's XXS->XS).
    # iq1_s: 8-bit signed-ternary index = 1.19 bpw.
    # iq1_m: iq1_s + per-16 sub-scales + a per-group +-1/8 delta
    #   (1.44 bpw; the role of ggml's IQ1_M refinement).
    "iq2_xxs": _q("iq2_xxs", 2, 256, "iqx", codebook="iq2_xxs"),
    "iq2_xs": _q("iq2_xs", 2, 256, "iqx", codebook="iq2_xs"),
    "iq1_s": _q("iq1_s", 1, 256, "iqx", codebook="iq1_s"),
    "iq1_m": _q("iq1_m", 1, 256, "iqx", codebook="iq1_s"),
}
# Aliases used throughout the reference API surface.
QTYPES["int4"] = QTYPES["sym_int4"]
QTYPES["q4_0"] = QTYPES["sym_int4"]
QTYPES["q4_1"] = QTYPES["asym_int4"]
QTYPES["q5_0"] = QTYPES["sym_int5"]
QTYPES["q5_1"] = QTYPES["asym_int5"]
QTYPES["int8"] = QTYPES["sym_int8"]
QTYPES["q8_0"] = QTYPES["sym_int8"]
QTYPES["fp8"] = QTYPES["fp8_e5m2"]
# the reference's user-facing names for the iq formats (load_in_low_bit=...)
QTYPES["gguf_iq2_xxs"] = QTYPES["iq2_xxs"]
QTYPES["gguf_iq2_xs"] = QTYPES["iq2_xs"]
QTYPES["gguf_iq1_s"] = QTYPES["iq1_s"]
QTYPES["gguf_iq1_m"] = QTYPES["iq1_m"]

# float passthrough "qtypes" accepted by the convert API (no QTensor made).
FLOAT_QTYPES = ("fp16", "bf16", "fp32")

_FP8_MAX = {"fp8_e4m3": 448.0, "fp8_e5m2": 57344.0}
_FP8_DTYPE = {"fp8_e4m3": jnp.float8_e4m3fn, "fp8_e5m2": jnp.float8_e5m2}


def is_valid_qtype(name: str) -> bool:
    """True for concrete qtypes AND mixed_* policies."""
    return name in QTYPES or name in MIXED_QTYPES


def get_qtype(name: str) -> QType:
    try:
        return QTYPES[name]
    except KeyError:
        raise ValueError(
            f"unknown qtype {name!r}; known: {sorted(set(QTYPES))} + {FLOAT_QTYPES}"
        ) from None


# ---------------------------------------------------------------------------
# QTensor pytree
# ---------------------------------------------------------------------------


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QTensor:
    """A block-quantized 2-D tensor of logical shape [K, N], blocks along K.

    Fields:
      data:  packed codes. 4-bit: uint8 [K//2, N] split-block nibble packing.
             8-bit sym: int8 [K, N]. fp8: float8_* [K, N].
      scale: bf16 [K // block, N] per-block scale (q2_k: superblock d).
      zero:  bf16 [K // block, N] per-block minimum (asym kinds), the
             superblock dmin (q2_k), or None.
      aux:   uint8 extra plane or None. int5 kinds: [K // 8, N] high-bit
             plane. q2_k: [K // 16, N] packed 4-bit sub-scale (low nibble)
             and sub-min (high nibble) per 16-value sub-block.
      qtype: qtype name (static).
      shape: logical (K, N) before padding (static). K may be padded up to a
             block multiple in `data`; `shape` records the true K.
    """

    data: jax.Array
    scale: jax.Array
    zero: Optional[jax.Array]
    qtype: str
    shape: Tuple[int, int]
    aux: Optional[jax.Array] = None

    # -- pytree protocol ----------------------------------------------------
    def tree_flatten(self):
        return (self.data, self.scale, self.zero, self.aux), (self.qtype, self.shape)

    @classmethod
    def tree_unflatten(cls, aux_data, children):
        data, scale, zero, aux = children
        qtype, shape = aux_data
        return cls(data, scale, zero, qtype, shape, aux)

    # -- conveniences -------------------------------------------------------
    @property
    def qt(self) -> QType:
        return get_qtype(self.qtype)

    @property
    def k(self) -> int:
        return self.shape[0]

    @property
    def n(self) -> int:
        return self.shape[1]

    @property
    def nbytes(self) -> int:
        if self.data.dtype == jnp.int4:    # XLA packs int4 2-per-byte
            tot = -(-self.data.size // 2)
        else:
            tot = self.data.size * self.data.dtype.itemsize
        tot += self.scale.size * self.scale.dtype.itemsize
        if self.zero is not None:
            tot += self.zero.size * self.zero.dtype.itemsize
        if self.aux is not None:
            tot += self.aux.size * self.aux.dtype.itemsize
        return tot

    def dequantize(self, dtype=jnp.bfloat16) -> jax.Array:
        return dequantize(self, dtype=dtype)

    def __repr__(self):
        return (f"QTensor({self.qtype}, shape={self.shape}, "
                f"block={self.qt.block_size})")


# ---------------------------------------------------------------------------
# Packing helpers (split-block nibble layout)
# ---------------------------------------------------------------------------


def _safe_inv(x: jax.Array) -> jax.Array:
    """1/x with 0 -> 0 (no NaNs from empty/zero blocks)."""
    return jnp.where(x == 0, 0.0, 1.0 / jnp.where(x == 0, 1.0, x))


def _pack4(codes: jax.Array, block: int) -> jax.Array:
    """[K, N] uint8 codes (0..15) -> [K//2, N] split-block packed bytes."""
    k, n = codes.shape
    b2 = block // 2
    blk = codes.reshape(k // block, block, n)
    lo = blk[:, :b2, :]
    hi = blk[:, b2:, :]
    packed = (lo | (hi << 4)).astype(jnp.uint8)
    return packed.reshape(k // 2, n)


def _unpack4(packed: jax.Array, block: int) -> jax.Array:
    """[K//2, N] packed bytes -> [K, N] uint8 codes (0..15)."""
    k2, n = packed.shape
    b2 = block // 2
    blk = packed.reshape(k2 // b2, b2, n)
    lo = blk & jnp.uint8(0x0F)
    hi = blk >> 4
    return jnp.concatenate([lo, hi], axis=1).reshape(k2 * 2, n)


def _pack_bits1(bits: jax.Array) -> jax.Array:
    """[K, N] 0/1 uint8 -> [K//8, N] bit plane (bit j = row 8*i+j)."""
    k, n = bits.shape
    b = bits.reshape(k // 8, 8, n).astype(jnp.uint8)
    shifts = jnp.arange(8, dtype=jnp.uint8).reshape(1, 8, 1)
    return jnp.sum(b << shifts, axis=1).astype(jnp.uint8)


def _unpack_bits1(plane: jax.Array) -> jax.Array:
    """[K//8, N] bit plane -> [K, N] 0/1 uint8."""
    k8, n = plane.shape
    shifts = jnp.arange(8, dtype=jnp.uint8).reshape(1, 8, 1)
    bits = (plane[:, None, :] >> shifts) & jnp.uint8(1)
    return bits.reshape(k8 * 8, n)


def _pack2(codes: jax.Array, block: int) -> jax.Array:
    """[K, N] uint8 codes (0..3) -> [K//4, N]: 4 planes of block//4 rows."""
    k, n = codes.shape
    b4 = block // 4
    blk = codes.reshape(k // block, 4, b4, n)
    packed = (blk[:, 0] | (blk[:, 1] << 2) | (blk[:, 2] << 4)
              | (blk[:, 3] << 6)).astype(jnp.uint8)
    return packed.reshape(k // 4, n)


def _unpack2(packed: jax.Array, block: int) -> jax.Array:
    """[K//4, N] -> [K, N] uint8 codes (0..3)."""
    k4, n = packed.shape
    b4 = block // 4
    blk = packed.reshape(k4 // b4, b4, n)
    planes = jnp.stack([(blk >> (2 * i)) & jnp.uint8(3) for i in range(4)],
                       axis=1)
    return planes.reshape(k4 * 4, n)


def _pad_k(x: jax.Array, block: int) -> jax.Array:
    k = x.shape[0]
    rem = (-k) % block
    if rem:
        x = jnp.pad(x, ((0, rem), (0, 0)))
    return x


def _codebook_encode(code: np.ndarray, xn: jax.Array) -> jax.Array:
    """Nearest-codebook-entry encode via searchsorted on the sorted table."""
    order = np.argsort(code)
    sorted_code = code[order]
    bounds = (sorted_code[1:] + sorted_code[:-1]) / 2.0
    idx_sorted = jnp.searchsorted(jnp.asarray(bounds), xn)
    perm = jnp.asarray(order.astype(np.uint8))
    return perm[idx_sorted]


# ---------------------------------------------------------------------------
# quantize / dequantize
# ---------------------------------------------------------------------------


def quantize(x: jax.Array, qtype: str,
             qw: Optional[jax.Array] = None) -> QTensor:
    """Quantize a [K, N] float array along K (blockwise) into a QTensor.

    For an HF linear weight w of shape [out, in], call
    ``quantize(w.T, qtype)`` (see `quantize_linear`).

    `qw` is an optional per-row importance vector [K] (the imatrix — the
    reference's `ggml_quantize_tensor_with_weights`, SURVEY.md §2.3-B):
    sym/asym/codebook formats run a weighted scale search, and the iq
    formats weight their codebook match. Other kinds ignore it.
    """
    if x.ndim != 2:
        raise ValueError(
            f"quantize expects a 2-D [K, N] array, got shape {x.shape}; "
            "reshape/flatten leading dims first"
        )
    if qw is not None and np.shape(qw) != (x.shape[0],):
        raise ValueError(
            f"imatrix length {np.shape(qw)} does not match the "
            f"contraction dim K={x.shape[0]} (importance is per INPUT "
            "feature)")
    qt = get_qtype(qtype)
    if qt.kind == "iqx":
        return _quantize_iqx(x, qt.name, qw)
    if qw is not None and qt.kind in ("sym", "asym", "codebook"):
        return _quantize_weighted(x, jnp.asarray(qw, jnp.float32), qt.name)
    if qw is not None and qt.kind == "q2k":
        return _quantize_q2k_weighted(x, jnp.asarray(qw, jnp.float32))
    return _quantize_core(x, qt.name)


@functools.partial(jax.jit, static_argnames=("qtype",))
def _quantize_core(x: jax.Array, qtype: str) -> QTensor:
    qt = get_qtype(qtype)
    k, n = x.shape
    b = qt.block_size
    x = _pad_k(x.astype(jnp.float32), b)
    kp = x.shape[0]
    nblk = kp // b
    xb = x.reshape(nblk, b, n)

    if qt.kind == "sym":
        # ggml-style signed-absmax scale: the max-|x| element maps exactly to
        # the most negative code (reference native q4_0/q5_0/q8_0 quantizers).
        amax_i = jnp.argmax(jnp.abs(xb), axis=1, keepdims=True)
        mx = jnp.take_along_axis(xb, amax_i, axis=1)  # [nblk, 1, n], signed
        half = float(1 << (qt.bits - 1))
        d = mx / -half
        inv = _safe_inv(d)
        q = jnp.clip(jnp.round(xb * inv) + half, 0, 2 * half - 1)
        q = q.reshape(kp, n).astype(jnp.uint8)
        scale = d.reshape(nblk, n).astype(jnp.bfloat16)
        if qt.bits == 4:
            return QTensor(_pack4(q, b), scale, None, qtype, (k, n))
        if qt.bits == 5:
            lo = _pack4(q & jnp.uint8(0x0F), b)
            hi = _pack_bits1(q >> 4)
            return QTensor(lo, scale, None, qtype, (k, n), aux=hi)
        if qt.bits == 8:
            q8 = (q.astype(jnp.int16) - 128).astype(jnp.int8)  # signed codes
            return QTensor(q8, scale, None, qtype, (k, n))
        raise ValueError(f"unsupported sym bits {qt.bits}")

    if qt.kind == "asym":
        mn = jnp.min(xb, axis=1, keepdims=True)
        mxv = jnp.max(xb, axis=1, keepdims=True)
        levels = float((1 << qt.bits) - 1)
        d = (mxv - mn) / levels
        inv = _safe_inv(d)
        q = jnp.clip(jnp.round((xb - mn) * inv), 0, levels)
        q = q.reshape(kp, n).astype(jnp.uint8)
        scale = d.reshape(nblk, n).astype(jnp.bfloat16)
        zero = mn.reshape(nblk, n).astype(jnp.bfloat16)
        if qt.bits == 4:
            return QTensor(_pack4(q, b), scale, zero, qtype, (k, n))
        if qt.bits == 5:
            lo = _pack4(q & jnp.uint8(0x0F), b)
            hi = _pack_bits1(q >> 4)
            return QTensor(lo, scale, zero, qtype, (k, n), aux=hi)
        raise ValueError(f"unsupported asym bits {qt.bits}")

    if qt.kind == "codebook":
        code = CODEBOOKS[qt.codebook]
        amax = jnp.max(jnp.abs(xb), axis=1, keepdims=True)
        d = amax
        inv = _safe_inv(d)
        q = _codebook_encode(code, xb * inv).reshape(kp, n).astype(jnp.uint8)
        scale = d.reshape(nblk, n).astype(jnp.bfloat16)
        return QTensor(_pack4(q, b), scale, None, qtype, (k, n))

    if qt.kind == "q2k":
        # per 16-value sub-block: asymmetric 2-bit with 4-bit quantized
        # sub scale/min under per-superblock fp16 scales (ggml Q2_K shape)
        sub = xb.reshape(nblk, b // 16, 16, n)
        mn = jnp.minimum(jnp.min(sub, axis=2), 0.0)        # [nblk, 16, n]
        mxv = jnp.max(sub, axis=2)
        ssc = jnp.maximum(mxv - mn, 0.0) / 3.0             # sub scale
        smin = -mn                                          # sub min (>=0)
        d = jnp.max(ssc, axis=1, keepdims=True) / 15.0     # [nblk, 1, n]
        dmin = jnp.max(smin, axis=1, keepdims=True) / 15.0
        dinv = _safe_inv(d)
        minv = _safe_inv(dmin)
        sc4 = jnp.clip(jnp.round(ssc * dinv), 0, 15).astype(jnp.uint8)
        m4 = jnp.clip(jnp.round(smin * minv), 0, 15).astype(jnp.uint8)
        eff_sc = d * sc4                                    # [nblk, 16, n]
        eff_m = dmin * m4
        inv_sc = _safe_inv(eff_sc)
        q = jnp.clip(jnp.round((sub + eff_m[:, :, None, :])
                               * inv_sc[:, :, None, :]), 0, 3)
        q = q.reshape(kp, n).astype(jnp.uint8)
        aux = (sc4 | (m4 << 4)).reshape(kp // 16, n)        # [K/16, N]
        return QTensor(
            _pack2(q, b),
            d[:, 0, :].astype(jnp.bfloat16),
            dmin[:, 0, :].astype(jnp.bfloat16),
            qtype, (k, n), aux=aux)

    if qt.kind == "fp8":
        fmax = _FP8_MAX[qt.name]
        fdt = _FP8_DTYPE[qt.name]
        amax = jnp.max(jnp.abs(xb), axis=1, keepdims=True)
        d = amax / fmax
        inv = _safe_inv(d)
        q = (xb * inv).astype(fdt).reshape(kp, n)
        scale = d.reshape(nblk, n).astype(jnp.bfloat16)
        return QTensor(q, scale, None, qtype, (k, n))

    raise ValueError(f"unsupported qtype kind {qt.kind}")


# ---------------------------------------------------------------------------
# Imatrix-weighted quantization (reference: ggml_quantize_tensor_with_weights
# bound at ggml/model/llama/llama_cpp.py:946-989; used by the reference for
# IQ2/IQ1/Q2_K with an importance matrix, transformers/utils.py:187-323)
# ---------------------------------------------------------------------------

_WEIGHTED_NCAND = 17        # scale candidates searched per block
_WEIGHTED_SPAN = 0.25       # +-25% around the absmax-derived scale


@functools.partial(jax.jit, static_argnames=("qtype",))
def _quantize_weighted(x: jax.Array, qw: jax.Array, qtype: str) -> QTensor:
    """Weighted-MSE scale search: per block, try scale candidates around
    the absmax scale and keep the one minimizing sum(qw * (x - deq)^2).
    The candidate loop is a `lax.scan` so memory stays one-candidate-deep.
    """
    qt = get_qtype(qtype)
    k, n = x.shape
    b = qt.block_size
    x = _pad_k(x.astype(jnp.float32), b)
    kp = x.shape[0]
    nblk = kp // b
    xb = x.reshape(nblk, b, n)
    wb = _pad_k(qw.reshape(-1, 1).astype(jnp.float32), b)
    wb = jnp.maximum(wb, 1e-12).reshape(nblk, b, 1)

    factors = jnp.linspace(1.0 - _WEIGHTED_SPAN, 1.0 + _WEIGHTED_SPAN,
                           _WEIGHTED_NCAND)

    if qt.kind == "sym":
        amax_i = jnp.argmax(jnp.abs(xb), axis=1, keepdims=True)
        mx = jnp.take_along_axis(xb, amax_i, axis=1)
        half = float(1 << (qt.bits - 1))
        base_d = mx / -half                                   # [nblk, 1, n]
        lo, hi = 0.0, 2 * half - 1

        def encode(d):
            q = jnp.clip(jnp.round(xb * _safe_inv(d)) + half, lo, hi)
            return q, (q - half) * d
    elif qt.kind == "asym":
        mn = jnp.min(xb, axis=1, keepdims=True)
        mxv = jnp.max(xb, axis=1, keepdims=True)
        levels = float((1 << qt.bits) - 1)
        base_d = (mxv - mn) / levels

        def encode(d):
            q = jnp.clip(jnp.round((xb - mn) * _safe_inv(d)), 0, levels)
            return q, q * d + mn
    else:                                       # codebook
        code = CODEBOOKS[qt.codebook]
        base_d = jnp.max(jnp.abs(xb), axis=1, keepdims=True)
        code_j = jnp.asarray(code)

        def encode(d):
            q = _codebook_encode(code, xb * _safe_inv(d))
            return q, code_j[q] * d

    def try_factor(best, f):
        best_d, best_err = best
        d = base_d * f
        _, recon = encode(d)
        err = jnp.sum(wb * (xb - recon) ** 2, axis=1)          # [nblk, n]
        better = err < best_err
        return (jnp.where(better[:, None, :], d, best_d),
                jnp.where(better, err, best_err)), None

    init = (base_d, jnp.full((nblk, n), jnp.inf))
    (d_best, _), _ = lax.scan(try_factor, init, factors)

    q, _ = encode(d_best)
    q = q.reshape(kp, n).astype(jnp.uint8)
    scale = d_best.reshape(nblk, n).astype(jnp.bfloat16)

    if qt.kind == "asym":
        zero = mn.reshape(nblk, n).astype(jnp.bfloat16)
        if qt.bits == 4:
            return QTensor(_pack4(q, b), scale, zero, qtype, (k, n))
        lo4 = _pack4(q & jnp.uint8(0x0F), b)
        return QTensor(lo4, scale, zero, qtype, (k, n),
                       aux=_pack_bits1(q >> 4))
    if qt.kind == "codebook":
        return QTensor(_pack4(q, b), scale, None, qtype, (k, n))
    # sym
    if qt.bits == 4:
        return QTensor(_pack4(q, b), scale, None, qtype, (k, n))
    if qt.bits == 5:
        lo4 = _pack4(q & jnp.uint8(0x0F), b)
        return QTensor(lo4, scale, None, qtype, (k, n),
                       aux=_pack_bits1(q >> 4))
    q8 = (q.astype(jnp.int16) - 128).astype(jnp.int8)
    return QTensor(q8, scale, None, qtype, (k, n))


@jax.jit
def _quantize_q2k_weighted(x: jax.Array, qw: jax.Array) -> QTensor:
    """Imatrix-weighted q2_k: per sub-block, search scale candidates for
    the (ssc, smin) fit minimizing the weighted reconstruction error
    (the reference's Q2_K-with-imatrix path of
    ggml_quantize_tensor_with_weights)."""
    qt = get_qtype("q2_k")
    k, n = x.shape
    b = qt.block_size
    x = _pad_k(x.astype(jnp.float32), b)
    kp = x.shape[0]
    nblk = kp // b
    xb = x.reshape(nblk, b, n)
    wb = _pad_k(qw.reshape(-1, 1).astype(jnp.float32), b)
    wb = jnp.maximum(wb, 1e-12).reshape(nblk, b // 16, 16, 1)

    sub = xb.reshape(nblk, b // 16, 16, n)
    mn = jnp.minimum(jnp.min(sub, axis=2), 0.0)
    mxv = jnp.max(sub, axis=2)
    base_ssc = jnp.maximum(mxv - mn, 0.0) / 3.0          # [nblk, 16, n]
    smin = -mn

    factors = jnp.linspace(1.0 - _WEIGHTED_SPAN, 1.0 + _WEIGHTED_SPAN,
                           _WEIGHTED_NCAND)

    def recon_err(ssc):
        inv = _safe_inv(ssc)
        q = jnp.clip(jnp.round((sub + smin[:, :, None, :])
                               * inv[:, :, None, :]), 0, 3)
        rec = q * ssc[:, :, None, :] - smin[:, :, None, :]
        err = jnp.sum(wb * (sub - rec) ** 2, axis=2)      # [nblk, 16, n]
        return err

    def try_factor(best, f):
        best_ssc, best_err = best
        ssc = base_ssc * f
        err = recon_err(ssc)
        better = err < best_err
        return (jnp.where(better, ssc, best_ssc),
                jnp.where(better, err, best_err)), None

    init = (base_ssc, jnp.full(base_ssc.shape, jnp.inf))
    (ssc, _), _ = lax.scan(try_factor, init, factors)

    # same superblock packing as the unweighted core
    d = jnp.max(ssc, axis=1, keepdims=True) / 15.0
    dmin = jnp.max(smin, axis=1, keepdims=True) / 15.0
    dinv = _safe_inv(d)
    minv = _safe_inv(dmin)
    sc4 = jnp.clip(jnp.round(ssc * dinv), 0, 15).astype(jnp.uint8)
    m4 = jnp.clip(jnp.round(smin * minv), 0, 15).astype(jnp.uint8)
    eff_sc = d * sc4
    eff_m = dmin * m4
    inv_sc = _safe_inv(eff_sc)
    q = jnp.clip(jnp.round((sub + eff_m[:, :, None, :])
                           * inv_sc[:, :, None, :]), 0, 3)
    q = q.reshape(kp, n).astype(jnp.uint8)
    aux = (sc4 | (m4 << 4)).reshape(kp // 16, n)
    return QTensor(
        _pack2(q, b),
        d[:, 0, :].astype(jnp.bfloat16),
        dmin[:, 0, :].astype(jnp.bfloat16),
        "q2_k", (k, n), aux=aux)


# ---------------------------------------------------------------------------
# iq formats: group-of-8 codebook quantization (iq2_xxs / iq1_s)
# ---------------------------------------------------------------------------

_IQ_CHUNK = 1024          # encode N columns at a time (bounds the [G,256,Nc]
                          # score tensor to ~0.5 GB f32 for K=4096)


def _iq_scales(xc: jax.Array, gmax: float, sub: int = 32):
    """Per-`sub` sub-scale (4-bit) under per-256 bf16 superscale.

    Returns (d [K/256, Nc], s4 [K/sub, Nc] uint8, effk [K, Nc])."""
    kp, nc = xc.shape
    per = 256 // sub
    s = jnp.max(jnp.abs(xc.reshape(kp // sub, sub, nc)), axis=1) / gmax
    d = jnp.max(s.reshape(kp // 256, per, nc), axis=1) / 15.0
    drep = jnp.repeat(d, per, axis=0)
    s4 = jnp.clip(jnp.round(s * _safe_inv(drep)), 0, 15).astype(jnp.uint8)
    eff = drep * s4.astype(jnp.float32)
    return d, s4, jnp.repeat(eff, sub, axis=0)


# Native iq1_m per-group shift magnitude. DELIBERATELY 1/8 (not ggml's
# IQ1M_DELTA = 0.0625, which gguf.py uses to decode real ggml files):
# this native format pairs the delta with per-16 sub-scales, and 1/8
# measured lower RMSE here. The two formats are independent layouts.
_IQ_DELTA = 0.125


@functools.partial(jax.jit, static_argnames=("qtype", "iters"))
def _iqx_encode_chunk(xc: jax.Array, wv: jax.Array, qtype: str,
                      iters: int = 2):
    """Encode one [K, Nc] chunk. wv: [K, 1] importance (ones if no imatrix).

    Codebook match maximizes sum(w * y * c) - 0.5 * sum(w * c^2) per group
    (equivalent to weighted-MSE argmin), computed as one [G, J, Nc]
    einsum — MXU work, not a loop.

    Coordinate descent (`iters` extra rounds): the amax-derived initial
    scale is far from optimal for coarse codebooks — for ternary iq1_s it
    pins the group max to +-1, which rounds most of a Gaussian group to
    zero, and no imatrix weighting can rescue a bad scale (the r2 ppl
    numbers showed exactly that). Each round re-fits every sub-scale by
    weighted least squares against the CHOSEN patterns
    (eff* = sum(w x c) / sum(w c^2) — exact given the assignment, the
    same scale-search idea as ggml's iq quantizers), then re-assigns
    patterns under the new scale. Monotone in weighted MSE modulo the
    4-bit scale rounding.

    Format variants:
    - iq2_xxs: unsigned cb[256], free 8-bit signs.
    - iq2_xs: unsigned cb[512]; signs parity-constrained to 7 stored
      bits (the lowest-|w x c| sign flips when the parity is odd), code
      packed as uint16 = idx | sign7 << 9 in two uint8 rows.
    - iq1_s: signed ternary cb[256].
    - iq1_m: iq1_s + per-16 sub-scales + per-group delta in
      {-1/8, +1/8}: values decode as eff * (c + delta). The (pattern,
      delta) pair is chosen jointly — score(c, d) separates as
      [s1 - s2/2] + d*(Sy - Swc) with the d^2 term constant.

    Returns (data, d, aux, extra): `extra` is the packed per-group delta
    bits for iq1_m, else None."""
    from bigdl_tpu.ops.codebooks import group_codebook

    qt = get_qtype(qtype)
    cb = jnp.asarray(group_codebook(qt.codebook))             # [J, 8]
    name = qt.name
    signed_cb = name in ("iq1_s", "iq1_m")
    with_delta = name == "iq1_m"
    xs_signs = name == "iq2_xs"
    sub = 16 if with_delta else 32
    gmax = float(np.max(np.abs(group_codebook(qt.codebook))))
    kp, nc = xc.shape
    g = kp // 8
    per = 256 // sub

    d, s4, effk = _iq_scales(xc, gmax, sub=sub)
    # wv: [K, 1] (uniform across columns) or [K, Nc] (magnitude-
    # modulated imatrix weights — per-column by construction)
    w = wv.reshape(g, 8, -1)
    percol = w.shape[-1] != 1
    drep = jnp.repeat(d, per, axis=0)                         # [K/sub, Nc]
    if percol:
        s2 = jnp.einsum("gkn,jk->gjn", w, cb * cb)            # [g, J, Nc]
    else:
        s2 = jnp.einsum("gk,jk->gj", w[..., 0], cb * cb)[:, :, None]
    if with_delta:
        if percol:
            swc = jnp.einsum("gkn,jk->gjn", w, cb)
        else:
            swc = jnp.einsum("gk,jk->gj", w[..., 0], cb)[:, :, None]

    def assign(effk):
        y = xc * _safe_inv(effk)                              # [K, Nc]
        a = (y if signed_cb else jnp.abs(y)).reshape(g, 8, nc)
        s1 = jnp.einsum("gkn,jk->gjn", a * w, cb)
        base = s1 - 0.5 * s2                                  # [g, J, Nc]
        if not with_delta:
            return jnp.argmax(base, axis=1), None
        sy = jnp.sum((a * w), axis=1)                         # [g, Nc]
        dterm = _IQ_DELTA * (sy[:, None, :] - swc)
        plus, minus = base + dterm, base - dterm
        jp, jm = jnp.argmax(plus, axis=1), jnp.argmax(minus, axis=1)
        bp = jnp.take_along_axis(plus, jp[:, None, :], axis=1)[:, 0]
        bm = jnp.take_along_axis(minus, jm[:, None, :], axis=1)[:, 0]
        take_p = bp >= bm
        return jnp.where(take_p, jp, jm), take_p              # [g, Nc] x2

    def stored_neg(idx):
        """Sign bits as they will be STORED: for iq2_xs the 7-bit parity
        constraint flips the cheapest position of every odd-parity
        group, so the decode differs from the raw (x < 0) signs — the
        scale refit must see the corrected signs or it optimizes for a
        decode that never happens (r4 advice)."""
        neg = (xc < 0).astype(jnp.int32).reshape(g, 8, nc)
        if xs_signs:
            pattern = cb[idx].transpose(0, 2, 1)              # [g, 8, Nc]
            cost = jnp.abs(xc.reshape(g, 8, nc)) * pattern * w
            odd = (jnp.sum(neg, axis=1) & 1) == 1             # [g, Nc]
            flip_at = jnp.argmin(cost, axis=1)                # [g, Nc]
            onehot = (jnp.arange(8)[None, :, None]
                      == flip_at[:, None, :])
            neg = jnp.where(odd[:, None, :] & onehot, 1 - neg, neg)
        return neg

    def decoded_units(idx, dpos):
        """Chosen patterns at unit scale, signs + delta folded."""
        c = cb[idx].transpose(0, 2, 1).reshape(kp, nc)        # [K, Nc]
        if not signed_cb:
            # stored sign bit is (x < 0): x == 0 decodes as +c
            sgn = 1.0 - 2.0 * stored_neg(idx).astype(jnp.float32)
            c = c * sgn.reshape(kp, nc)
        if with_delta:
            delta = jnp.where(dpos, _IQ_DELTA, -_IQ_DELTA)    # [g, Nc]
            c = c + jnp.repeat(delta, 8, axis=0)
        return c

    idx, dpos = assign(effk)
    for _ in range(iters):
        c = decoded_units(idx, dpos)
        wk = wv                                               # [K, 1]
        num = jnp.sum((wk * xc * c).reshape(kp // sub, sub, nc), axis=1)
        den = jnp.sum((wk * c * c).reshape(kp // sub, sub, nc), axis=1)
        eff = num * _safe_inv(den)                            # [K/sub, Nc]
        s4 = jnp.clip(jnp.round(eff * _safe_inv(drep)),
                      0, 15).astype(jnp.uint8)
        effk = jnp.repeat(drep * s4.astype(jnp.float32), sub, axis=0)
        idx, dpos = assign(effk)

    # pack sub-scales: 2 nibbles per byte along K
    s4p = s4.reshape(kp // (2 * sub), 2, nc)
    aux = (s4p[:, 0] | (s4p[:, 1] << 4)).astype(jnp.uint8)

    extra = None
    if with_delta:
        bits = dpos.astype(jnp.int32).reshape(g // 8, 8, nc)
        shifts = jnp.arange(8, dtype=jnp.int32).reshape(1, 8, 1)
        extra = jnp.sum(bits << shifts, axis=1).astype(jnp.uint8)

    if signed_cb:
        data = idx.astype(jnp.uint8)                          # [K/8, Nc]
    elif xs_signs:
        # representable sign vectors have EVEN popcount (bit 7 is the
        # parity of bits 0-6); when the desired signs are odd, flip the
        # cheapest position — the one with the least |w x c| at stake
        neg = stored_neg(idx)
        shifts = jnp.arange(7, dtype=jnp.int32).reshape(1, 7, 1)
        sign7 = jnp.sum(neg[:, :7] << shifts, axis=1)         # [g, Nc]
        code = idx.astype(jnp.int32) | (sign7 << 9)           # 16 bits
        data = jnp.stack([code & 0xFF, code >> 8],
                         axis=1).reshape(2 * g, nc).astype(jnp.uint8)
    else:
        neg = stored_neg(idx)
        shifts = jnp.arange(8, dtype=jnp.int32).reshape(1, 8, 1)
        signs = jnp.sum(neg << shifts, axis=1).astype(jnp.uint8)
        data = jnp.stack([idx.astype(jnp.uint8), signs],
                         axis=1).reshape(2 * g, nc)
    return data, d.astype(jnp.bfloat16), aux, extra


def _quantize_iqx(x: jax.Array, qtype: str,
                  qw: Optional[jax.Array]) -> QTensor:
    """Host-chunked iq encode (runs once at load time; the [G,256,N]
    score tensor is why this is chunked over N rather than one jit)."""
    k, n = x.shape
    x = _pad_k(jnp.asarray(x, jnp.float32), 256)
    kp = x.shape[0]
    if qw is None:
        wv = jnp.ones((kp, 1), jnp.float32)
    else:
        wv = _pad_k(jnp.asarray(qw, jnp.float32).reshape(-1, 1), 256)
        wv = jnp.maximum(wv, 1e-12)

    datas, ds, auxs, extras = [], [], [], []
    for c0 in range(0, n, _IQ_CHUNK):
        xc = x[:, c0:c0 + _IQ_CHUNK]
        if qw is None:
            wc = wv
        else:
            # llama.cpp's iq quantizers don't use the raw imatrix as
            # the MSE weight — they modulate it by weight magnitude,
            # w = qw * sqrt(sigma2 + x^2), sigma2 = 2*mean(x^2) per
            # superblock (quantize_row_iq2_xxs_impl and friends). The
            # raw-qw objective over-protects high-importance but
            # small-magnitude coordinates and measurably HURT iq ppl
            # on the in-repo testbeds (the r4 imatrix anomaly).
            x2 = xc * xc
            sigma2 = 2.0 * jnp.mean(
                x2.reshape(kp // 256, 256, -1), axis=1, keepdims=True)
            wc = wv * jnp.sqrt(
                (sigma2 + x2.reshape(kp // 256, 256, -1))
            ).reshape(kp, -1)
        data, d, aux, extra = _iqx_encode_chunk(xc, wc, qtype)
        datas.append(data)
        ds.append(d)
        auxs.append(aux)
        if extra is not None:
            extras.append(extra)
    return QTensor(jnp.concatenate(datas, axis=1),
                   jnp.concatenate(ds, axis=1),
                   # iq1_m: packed per-group delta bits ride the (otherwise
                   # unused) zero plane
                   jnp.concatenate(extras, axis=1) if extras else None,
                   get_qtype(qtype).name, (k, n),
                   aux=jnp.concatenate(auxs, axis=1))


def _dequantize_iqx(qt_t: QTensor, dtype) -> jax.Array:
    from bigdl_tpu.ops.codebooks import group_codebook

    t = qt_t.qt
    k, n = qt_t.shape
    cb = jnp.asarray(group_codebook(t.codebook))               # [J, 8]
    name = t.name
    signed_cb = name in ("iq1_s", "iq1_m")
    sub = 16 if name == "iq1_m" else 32

    if signed_cb:
        idx = qt_t.data                                        # [Kp/8, N]
        g = idx.shape[0]
        vals = cb[idx]                                         # [g, N, 8]
        vals = vals.transpose(0, 2, 1)                         # [g, 8, N]
        if name == "iq1_m":
            shifts = jnp.arange(8, dtype=jnp.int32).reshape(1, 8, 1)
            bits = (qt_t.zero.astype(jnp.int32)[:, None, :] >> shifts) & 1
            delta = jnp.where(bits.astype(bool), _IQ_DELTA, -_IQ_DELTA)
            vals = vals + delta.reshape(g, 1, n)
    elif name == "iq2_xs":
        gi = qt_t.data.reshape(-1, 2, qt_t.data.shape[1])
        code = (gi[:, 0].astype(jnp.int32)
                | (gi[:, 1].astype(jnp.int32) << 8))           # [g, N]
        idx, sign7 = code & 0x1FF, code >> 9
        g = idx.shape[0]
        vals = cb[idx].transpose(0, 2, 1)                      # [g, 8, N]
        # bit 7 of the sign byte is the parity of bits 0-6 (the derived
        # ksigns rule, ops/iq_grids.ksigns)
        par = sign7 ^ (sign7 >> 4)
        par = par ^ (par >> 2)
        par = par ^ (par >> 1)
        full = sign7 | ((par & 1) << 7)
        shifts = jnp.arange(8, dtype=jnp.int32).reshape(1, 8, 1)
        neg = (full[:, None, :] >> shifts) & 1
        vals = vals * (1.0 - 2.0 * neg.astype(jnp.float32))
    else:
        gi = qt_t.data.reshape(-1, 2, qt_t.data.shape[1])
        idx, signs = gi[:, 0], gi[:, 1]
        g = idx.shape[0]
        vals = cb[idx].transpose(0, 2, 1)                      # [g, 8, N]
        shifts = jnp.arange(8, dtype=jnp.int32).reshape(1, 8, 1)
        neg = (signs.astype(jnp.int32)[:, None, :] >> shifts) & 1
        vals = vals * (1.0 - 2.0 * neg.astype(jnp.float32))
    kp = g * 8

    s4p = qt_t.aux
    lo = (s4p & jnp.uint8(0xF)).astype(jnp.float32)
    hi = (s4p >> 4).astype(jnp.float32)
    s4 = jnp.stack([lo, hi], axis=1).reshape(kp // sub, n)
    per = 256 // sub
    drep = jnp.repeat(qt_t.scale.astype(jnp.float32), per, axis=0)
    effk = jnp.repeat(drep * s4, sub, axis=0)                  # [Kp, N]

    out = vals.reshape(kp, n) * effk
    return out[:k].astype(dtype)


def _expand_scale(scale: jax.Array, block: int, kp: int) -> jax.Array:
    """[nblk, N] -> [K, N] by repeating each block row `block` times."""
    nblk, n = scale.shape
    return jnp.broadcast_to(
        scale.astype(jnp.float32)[:, None, :], (nblk, block, n)
    ).reshape(kp, n)


def dequantize_impl(qt: QTensor, dtype=jnp.bfloat16) -> jax.Array:
    """QTensor -> dense [K, N] array of `dtype` (XLA reference path).

    Unjitted body: model forwards reach this inside their own jit, and a
    nested jit's closed_call fails to lower inside shard_map's Manual-
    mesh AOT trace (see ops/pallas/dequant_matmul.q_matmul_pallas_impl).
    The jitted public alias `dequantize` is defined below for eager
    callers (conversion, tests)."""
    t = qt.qt
    k, n = qt.shape
    b = t.block_size

    if t.kind == "iqx":
        return _dequantize_iqx(qt, dtype)

    if t.kind == "sym" and t.bits == 8:
        kp = qt.data.shape[0]
        vals = qt.data.astype(jnp.float32)  # signed codes in [-128, 127]
        out = vals * _expand_scale(qt.scale, b, kp)
        return out[:k].astype(dtype)

    if t.kind == "fp8":
        kp = qt.data.shape[0]
        vals = qt.data.astype(jnp.float32)
        out = vals * _expand_scale(qt.scale, b, kp)
        return out[:k].astype(dtype)

    if t.kind == "codebook":
        codes = _unpack4(qt.data, b)
        kp = codes.shape[0]
        code = jnp.asarray(CODEBOOKS[t.codebook])
        vals = code[codes]
        out = vals * _expand_scale(qt.scale, b, kp)
        return out[:k].astype(dtype)

    if t.kind == "sym" and t.bits == 4:
        if qt.data.dtype == jnp.int4:      # MXU layout: signed, unpacked
            kp = qt.data.shape[0]
            vals = qt.data.astype(jnp.float32)
        else:
            codes = _unpack4(qt.data, b)
            kp = codes.shape[0]
            vals = codes.astype(jnp.float32) - 8.0
        out = vals * _expand_scale(qt.scale, b, kp)
        return out[:k].astype(dtype)

    if t.kind == "sym" and t.bits == 5:
        lo = _unpack4(qt.data, b)
        hi = _unpack_bits1(qt.aux)
        kp = lo.shape[0]
        codes = lo | (hi[:kp] << 4)
        vals = codes.astype(jnp.float32) - 16.0
        out = vals * _expand_scale(qt.scale, b, kp)
        return out[:k].astype(dtype)

    if t.kind == "asym" and t.bits == 4:
        codes = _unpack4(qt.data, b)
        kp = codes.shape[0]
        d = _expand_scale(qt.scale, b, kp)
        m = _expand_scale(qt.zero, b, kp)
        out = codes.astype(jnp.float32) * d + m
        return out[:k].astype(dtype)

    if t.kind == "q2k":
        codes = _unpack2(qt.data, b).astype(jnp.float32)    # [Kp, N]
        kp = codes.shape[0]
        sc4 = (qt.aux & jnp.uint8(0xF)).astype(jnp.float32)  # [Kp/16, N]
        m4 = (qt.aux >> 4).astype(jnp.float32)
        rep16 = lambda a: jnp.repeat(a, 16, axis=0)
        d = _expand_scale(qt.scale, b, kp)
        dmin = _expand_scale(qt.zero, b, kp)
        out = d * rep16(sc4) * codes - dmin * rep16(m4)
        return out[:k].astype(dtype)

    if t.kind == "asym" and t.bits == 5:
        lo = _unpack4(qt.data, b)
        hi = _unpack_bits1(qt.aux)
        kp = lo.shape[0]
        codes = lo | (hi[:kp] << 4)
        d = _expand_scale(qt.scale, b, kp)
        m = _expand_scale(qt.zero, b, kp)
        out = codes.astype(jnp.float32) * d + m
        return out[:k].astype(dtype)

    raise ValueError(f"cannot dequantize {t.name}")


# ---------------------------------------------------------------------------
# Linear-weight conveniences (HF [out, in] orientation)
# ---------------------------------------------------------------------------


# Mixed-precision policies: per-TENSOR candidate pick by dequantization MSE
# (the reference's mixed_fp4/mixed_fp8, low_bit_linear.py:302-335: each
# layer independently gets whichever 4-/8-bit format reconstructs it best).
MIXED_QTYPES = {
    "mixed_fp4": ("fp4", "nf4", "sym_int4"),
    "mixed_fp8": ("fp8_e4m3", "fp8_e5m2", "sym_int8"),
}


def quantize_auto(x: jax.Array, qtype: str,
                  qw: Optional[jax.Array] = None) -> QTensor:
    """quantize(), plus the mixed_* policies (MSE-picked candidate; the
    MSE is imatrix-weighted when qw is given)."""
    if qtype not in MIXED_QTYPES:
        return quantize(x, qtype, qw=qw)
    xf = jnp.asarray(x, jnp.float32)
    wcol = (None if qw is None
            else jnp.asarray(qw, jnp.float32).reshape(-1, 1))
    best_qt, best_err = None, None
    for cand in MIXED_QTYPES[qtype]:
        qt = quantize(xf, cand, qw=qw)
        sq = (dequantize(qt, jnp.float32) - xf) ** 2
        if wcol is not None:
            sq = sq * wcol
        err = float(jnp.mean(sq))
        if best_err is None or err < best_err:
            best_qt, best_err = qt, err
    return best_qt


def quantize_linear(w_out_in: jax.Array, qtype: str,
                    qw: Optional[jax.Array] = None) -> QTensor:
    """Quantize an HF-layout linear weight [out, in] -> QTensor [in, out].

    `qw` is the imatrix row for this weight: importance per INPUT feature
    (length in_features = our contraction dim K)."""
    return quantize_auto(jnp.asarray(w_out_in).T, qtype, qw=qw)


def dequantize_linear(qt: QTensor, dtype=jnp.bfloat16) -> jax.Array:
    """QTensor [in, out] -> HF-layout dense weight [out, in]."""
    return dequantize(qt, dtype=dtype).T


def concat_qtensors_n(ws) -> QTensor:
    """Concatenate QTensors along N (the output dim).

    Because blocks run along K and every column quantizes independently,
    the result is BIT-IDENTICAL to quantizing the concatenated dense
    weight — the basis for merged-QKV / merged-gate-up projections (the
    reference does the same surgery on dense weights in `_optimize_pre`,
    transformers/convert.py:529-640). Works on layer-stacked planes
    (leading L dims) since every plane is N-last."""
    import dataclasses as dc

    w0 = ws[0]
    if len({w.qtype for w in ws}) != 1:
        raise ValueError("cannot concat mixed qtypes: "
                         f"{[w.qtype for w in ws]}")
    if len({w.shape[0] for w in ws}) != 1:
        raise ValueError("cannot concat differing K: "
                         f"{[w.shape for w in ws]}")
    rep = {}
    for f in ("data", "scale", "zero", "aux"):
        planes = [getattr(w, f) for w in ws]
        if any(p is None for p in planes):
            if any(p is not None for p in planes):
                raise ValueError(f"inconsistent {f} planes across operands")
            continue
        rep[f] = jnp.concatenate(planes, axis=-1)
    n_total = sum(w.shape[1] for w in ws)
    return dc.replace(w0, shape=(w0.shape[0], n_total), **rep)


def split_qtensor_n(w: QTensor, sizes) -> list:
    """Inverse of `concat_qtensors_n`: slice along N at the given sizes."""
    import dataclasses as dc

    if sum(sizes) != w.shape[1]:
        raise ValueError(f"split sizes {sizes} != N={w.shape[1]}")
    outs, off = [], 0
    for s in sizes:
        rep = {f: getattr(w, f)[..., off:off + s]
               for f in ("data", "scale", "zero", "aux")
               if getattr(w, f) is not None}
        outs.append(dc.replace(w, shape=(w.shape[0], s), **rep))
        off += s
    return outs


# public jitted alias (eager callers: conversion utilities, tests)
dequantize = functools.partial(
    jax.jit, static_argnames=("dtype",))(dequantize_impl)


# ---------------------------------------------------------------------------
# MXU (int4-dtype) weight layout
# ---------------------------------------------------------------------------


def to_mxu_layout(qt: QTensor) -> QTensor:
    """sym_int4 canonical (split-block packed uint8) -> int4-dtype data.

    The decode GEMV's bottleneck is the VPU nibble unpack (~6 i32 vector
    ops per weight over ~4 GB of weights every token — BENCH_r04 put the
    kernel at 18% of the HBM roofline). XLA stores jnp.int4 arrays bit-
    packed (same HBM bytes) and Mosaic loads them natively, so the
    in-kernel per-weight work drops to ONE int4->int8/bf16 convert. The
    transform is applied once at load time (transformers/model.py); the
    canonical layout remains the on-disk / GGUF interchange format
    (`from_mxu_layout` restores it bit-exactly — codes are just shifted
    by 8). sym_int8 is already MXU-ready; other qtypes pass through."""
    if qt.qtype not in ("sym_int4",) or qt.data.dtype == jnp.int4:
        return qt
    if qt.data.ndim >= 4:
        # [L, E, K//2, N] MoE expert stacks: the ragged MoE prefill
        # kernel (ops/pallas/moe_dispatch.py) and the vmapped decode
        # gather probe read the canonical packing — converting them
        # would feed int4-dtype data to kernels that bit-unpack uint8
        # (code-review r5). Expert matmuls stay on the proven path.
        return qt
    packed = qt.data
    *lead, k2, n = packed.shape
    b2 = qt.qt.block_size // 2

    def unpack(blk, xp, i8, i4):
        codes = xp.concatenate([blk & 0x0F, blk >> 4], axis=-2)
        return (codes.astype(i8) - i8(8)).astype(i4) \
            .reshape(*lead, k2 * 2, n)

    if isinstance(packed, jax.core.Tracer):
        blk = packed.reshape(*lead, k2 // b2, b2, n)
        return dataclasses.replace(
            qt, data=unpack(blk, jnp, jnp.int8, jnp.int4))
    def host_convert(host):
        # numpy's ml_dtypes int4 transfers straight to a bit-packed
        # device array with the layout every consumer expects.
        import ml_dtypes

        host = host.reshape(*lead, k2 // b2, b2, n)
        return jnp.asarray(unpack(host, np, np.int8, ml_dtypes.int4))

    if isinstance(packed, np.ndarray):
        return dataclasses.replace(qt, data=host_convert(packed))
    # Concrete DEVICE weights convert on device, chunked. Two wrong
    # ways, both hit live: a host round-trip (np.asarray) dies on the
    # axon tunnel — D2H of device uint8 arrays is UNIMPLEMENTED
    # (2026-08-02 window, the shipped-default bench config failed at
    # load); an unchunked device expansion materializes ~4x the packed
    # bytes (uint8 codes + int8) as a transient next to the resident
    # model — a multi-GB load-time HBM spike for 7B stacked leaves.
    # lax.map over the superblock axis bounds the transient to one
    # [b2, n] row group. Every step is belt-and-braces guarded: an
    # experimental backend (axon) has runtime gaps we can only discover
    # live, and a failed relayout must degrade to the canonical packing
    # (28.6 ms/token on the split-block kernels) rather than kill the
    # load.
    import logging

    log = logging.getLogger(__name__)
    try:
        return dataclasses.replace(
            qt, data=_mxu_unpack_device(packed, b2))
    except Exception as e:  # noqa: BLE001 — backend gaps surface as
        #                     JaxRuntimeError/RecursionError/TypeError
        log.warning("device-side int4 relayout failed (%s: %s); "
                    "trying the host round-trip", type(e).__name__, e)
    try:
        return dataclasses.replace(
            qt, data=host_convert(np.asarray(packed)))
    except Exception as e:  # noqa: BLE001
        log.warning("host round-trip relayout also failed (%s: %s); "
                    "keeping the canonical split-block layout",
                    type(e).__name__, e)
        return qt


@functools.lru_cache(maxsize=None)
def _mxu_unpack_jit(rank: int, b2: int, device):
    """Jitted split-block packed uint8 -> int4 codes (see to_mxu_layout).

    The output layout is pinned to row-major default: left to the
    compiler, this program emits an exotic int4 layout
    ({1,2,0:T(64,128)}, seen live 2026-08-02) that differs from what a
    host->device transfer produces — and any downstream executable
    compiled against transferred weights (e.g. out of the persistent
    compile cache) then needs an implicit relayout device_put at
    dispatch, which trips JAX's "Recursively calling jit" guard."""

    def impl(packed):
        *lead, k2, n = packed.shape
        blk = packed.reshape(-1, b2, n)

        def step(rows):
            codes = jnp.concatenate([rows & 0x0F, rows >> 4], axis=-2)
            return (codes.astype(jnp.int8) - jnp.int8(8)).astype(jnp.int4)

        out = jax.lax.map(step, blk)        # [S, 2*b2, n] int4
        return out.reshape(*lead, k2 * 2, n)

    from bigdl_tpu.observability.compile_watch import tracked_jit

    try:
        from jax.experimental.layout import Format, Layout
        from jax.sharding import SingleDeviceSharding

        fmt = Format(Layout(tuple(range(rank))),
                     SingleDeviceSharding(device))
        return tracked_jit("int4_mxu_relayout", impl, out_shardings=fmt)
    except (ImportError, TypeError, ValueError) as e:
        import logging

        logging.getLogger(__name__).warning(
            "int4 relayout jit: could not pin the row-major output "
            "layout (%s: %s) — compiler-chosen layouts risk an implicit "
            "relayout at downstream dispatch", type(e).__name__, e)
        return tracked_jit("int4_mxu_relayout", impl)


@functools.lru_cache(maxsize=None)
def _mxu_ref_format(rank: int, device):
    """The Format a host->device int4 transfer produces on `device`.

    Compiled consumers (including executables revived from the
    persistent compile cache, which were built against transferred
    weights) expect exactly this layout; handing them anything else
    forces an implicit relayout device_put inside dispatch arg-prep,
    which JAX 0.9 rejects with "Recursively calling jit". major_to_minor
    alone is not enough — the live failure showed a row-major but
    differently-TILED arg ({2,1,0:T(64,128)} vs the transfer default) —
    so the reference is measured, not assumed: transfer one tile and
    read its format."""
    import ml_dtypes
    from jax.experimental.layout import Format
    from jax.sharding import SingleDeviceSharding

    probe = np.zeros((1,) * (rank - 2) + (8, 128), ml_dtypes.int4)
    arr = jax.device_put(probe, device)
    return Format(arr.format.layout, SingleDeviceSharding(device))


def _mxu_unpack_device(packed, b2: int):
    dev = next(iter(packed.devices())) if hasattr(packed, "devices") \
        else None
    out = _mxu_unpack_jit(packed.ndim, b2, dev)(packed)
    try:
        fmt = _mxu_ref_format(out.ndim, dev)
        if out.format.layout != fmt.layout:
            # eager relayout: a device_put OUTSIDE any dispatch is legal
            # and runs as one compiled on-device copy
            out = jax.device_put(out, fmt)
    except Exception as e:  # noqa: BLE001 — probe is best-effort
        import logging

        logging.getLogger(__name__).warning(
            "int4 layout normalization skipped (%s: %s)",
            type(e).__name__, e)
    return out


def from_mxu_layout(qt: QTensor) -> QTensor:
    """Inverse of `to_mxu_layout` (for save_low_bit / GGUF export)."""
    if getattr(qt.data, "dtype", None) != jnp.int4:
        return qt
    codes = (qt.data.astype(jnp.int8) + 8).astype(jnp.uint8)
    *lead, k, n = codes.shape
    b = qt.qt.block_size
    blk = codes.reshape(*lead, k // b, b, n)
    packed = (blk[..., :b // 2, :] | (blk[..., b // 2:, :] << 4)) \
        .astype(jnp.uint8).reshape(*lead, k // 2, n)
    return dataclasses.replace(qt, data=packed)


def tree_to_mxu_layout(tree):
    """Apply `to_mxu_layout` to every sym_int4 QTensor in a pytree."""
    return jax.tree_util.tree_map(
        lambda x: to_mxu_layout(x) if isinstance(x, QTensor) else x,
        tree, is_leaf=lambda x: isinstance(x, QTensor))


def tree_from_mxu_layout(tree):
    return jax.tree_util.tree_map(
        lambda x: from_mxu_layout(x) if isinstance(x, QTensor) else x,
        tree, is_leaf=lambda x: isinstance(x, QTensor))


def prepack_tree(tree, mode: Optional[str] = None):
    """One-time load-time weight prepacking: retile every QTensor's
    code/scale planes into the layout the decode kernels want (today:
    the int4-dtype MXU layout for sym_int4 — native Mosaic int4 loads
    instead of the VPU nibble-unpack chain). Applied ONCE at checkpoint
    load (transformers/model.py); `save_low_bit` always repacks to the
    canonical split-block interchange format via `tree_from_mxu_layout`.

    `mode`: "auto" (prepack when the compute target is TPU), "on",
    "off"; defaults to flags().prepack (BIGDL_TPU_PREPACK). Subsumes
    the older mxu_layout knob — either knob set to "off" disables,
    and either set to "on" forces the retile even off-TPU (the CPU
    fallbacks read both layouts, so "on" stays testable anywhere).

    Returns (tree, report): report is a plain-JSON dict (mode, applied,
    qtensor/converted counts, packed bytes) that the memory ledger and
    the bench's `prepack` block record, so a failed or skipped retile
    is visible in every perf artifact instead of silently changing
    which kernel variant the A/B numbers measured."""
    from bigdl_tpu.config import flags, resolve_prepack, target_is_tpu

    f = flags()
    mode = resolve_prepack(mode) if mode is not None else f.prepack
    report: dict = {"mode": mode, "applied": False,
                    "qtensors": 0, "converted": 0, "bytes_packed": 0}
    off = mode == "off" or f.mxu_layout == "off"
    force = mode == "on" or f.mxu_layout == "on"
    if off or (not force and not target_is_tpu()):
        return tree, report

    is_q = lambda x: isinstance(x, QTensor)  # noqa: E731

    def conv(x):
        if not is_q(x):
            return x
        report["qtensors"] += 1
        y = to_mxu_layout(x)
        if y.data.dtype != x.data.dtype:
            report["converted"] += 1
        report["bytes_packed"] += int(y.nbytes)
        return y

    tree = jax.tree_util.tree_map(conv, tree, is_leaf=is_q)
    report["applied"] = report["converted"] > 0
    return tree, report
