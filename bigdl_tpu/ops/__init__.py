from bigdl_tpu.ops.quant import (  # noqa: F401
    QTensor,
    QTYPES,
    FLOAT_QTYPES,
    get_qtype,
    quantize,
    dequantize,
    quantize_linear,
    dequantize_linear,
)
