"""Normalization ops.

TPU-native equivalents of the reference's fused norm kernels:
`linear_q4_0.rms_norm` (reference transformers/models/llama.py:134-141) and
`fused_layer_norm` (models/utils.py). On TPU these are bandwidth-trivial
elementwise+reduce patterns that XLA fuses into neighboring ops, so the
default implementation is plain jnp; a Pallas variant exists for fusing into
surrounding kernels when profiling shows a win.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMSNorm in f32 accumulation, output in x.dtype (llama-family)."""
    dt = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32)).astype(dt)


def layer_norm(
    x: jax.Array,
    weight: jax.Array,
    bias: jax.Array | None = None,
    eps: float = 1e-5,
) -> jax.Array:
    """Standard LayerNorm in f32 accumulation (gpt/bert families)."""
    dt = x.dtype
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(dt)
