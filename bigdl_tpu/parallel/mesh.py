"""Device mesh construction, single- and multi-host.

Replaces the reference's process-group bootstrap: `deepspeed.init_inference
(mp_size=N)` + oneCCL backend selection and MPI `PMI_SIZE` env sniffing
(reference transformers/training_patch.py:100-198, example/GPU/
Deepspeed-AutoTP/deepspeed_autotp.py:76-101). On TPU the equivalents are
`jax.distributed.initialize()` for multi-host and a named `Mesh` whose axes
map onto ICI (within-slice) and DCN (across-slice) links.

Axis convention used across the framework:
  dp — data parallel (batch), outermost; rides DCN across slices
  fsdp — parameter/optimizer sharding (ZeRO-equivalent), within slice
  tp — tensor parallel (the AutoTP equivalent), innermost for fastest ICI
  sp — sequence/context parallel (ring attention), shares ICI with tp
  ep — expert parallel (MoE)
Any axis of size 1 may be omitted when building specs.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical mesh shape. Axes with size 1 still exist (GSPMD ignores
    unit axes at zero cost), so one spec set serves every topology."""
    dp: int = 1
    fsdp: int = 1
    pp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return ("dp", "fsdp", "pp", "tp", "sp", "ep")

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.dp, self.fsdp, self.pp, self.tp, self.sp, self.ep)

    @property
    def size(self) -> int:
        return int(np.prod(self.shape))


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bootstrap (the `mpirun`/PMI analog, training_patch.py).

    On TPU pods the args are discovered from the environment; explicit args
    support manual (GPU/CPU) clusters. Safe to call when single-host. Must
    run before any backend-initializing JAX call (so no jax.devices() /
    process_count() probes here — the initialized-guard reads the
    distributed client state directly).
    """
    from jax._src import distributed as _dist

    if getattr(_dist.global_state, "client", None) is not None:
        return  # already initialized
    env_has_tpu = os.environ.get("TPU_WORKER_HOSTNAMES") or os.environ.get(
        "MEGASCALE_COORDINATOR_ADDRESS")
    if coordinator_address or env_has_tpu:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )


def make_mesh(
    cfg: Optional[MeshConfig] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    tp: Optional[int] = None,
    dp: Optional[int] = None,
    sp: int = 1,
    ep: int = 1,
    fsdp: int = 1,
    pp: int = 1,
) -> Mesh:
    """Build a named Mesh over the available devices.

    With no arguments: all devices on the `tp` axis (the common inference
    setup — the AutoTP equivalent). `mesh_utils.create_device_mesh` orders
    devices so the innermost axes land on the fastest ICI links.
    """
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if cfg is None:
        if tp is None and dp is None:
            tp = max(1, n // (sp * ep * fsdp * pp))
        if tp is None:
            tp = max(1, n // ((dp or 1) * sp * ep * fsdp * pp))
        dp = dp or max(1, n // (tp * sp * ep * fsdp * pp))
        cfg = MeshConfig(dp=dp, fsdp=fsdp, pp=pp, tp=tp, sp=sp, ep=ep)
    if cfg.size != n:
        raise ValueError(
            f"mesh shape {cfg.shape} needs {cfg.size} devices, have {n}")
    try:
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_device_mesh(cfg.shape, devices=devs)
    except Exception:
        arr = np.asarray(devs).reshape(cfg.shape)
    return Mesh(arr, cfg.axis_names)
