"""Parallelism: device meshes, sharding rules, collectives.

TPU-native replacement for the reference's distributed stack (SURVEY.md
§2.2): DeepSpeed-AutoTP tensor-parallel sharding + oneCCL all-reduce
(reference transformers/convert.py:102-119, low_bit_linear.py:635-637),
MPI/ccl training launch (transformers/training_patch.py), and the absent
sequence-parallel path. Here parallelism is declarative: build a
`jax.sharding.Mesh`, annotate parameter/activation shardings, and XLA
inserts the ICI/DCN collectives.
"""

from bigdl_tpu.parallel.mesh import (  # noqa: F401
    MeshConfig,
    make_mesh,
    init_distributed,
)
from bigdl_tpu.parallel.sharding import (  # noqa: F401
    llama_param_specs,
    shard_params,
    shard_batch,
    shard_moe_params,
    replicate,
)
