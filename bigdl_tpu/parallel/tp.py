"""Tensor-parallel inference under EXPLICIT shard_map — kernels on shards.

The GSPMD path (parallel/sharding.py: shard the params, let XLA insert
the collectives) is correct but cannot use Pallas kernels — Mosaic ops
are not auto-partitionable (see PARITY.md "Multi-chip kernel dispatch"),
so it runs XLA ops. This module is the kernel-capable alternative, the
analog of how the reference reaches its per-device SYCL kernels through
DeepSpeed-AutoTP's explicit sharding (reference transformers/convert.py:
102-119 + dist.inference_all_reduce at low_bit_linear.py:635-637):

- the forward runs INSIDE shard_map over a 1-axis tp mesh;
- every device holds its head/column shard (q/k/v/gate/up column-split,
  o/down row-split — the same llama_param_specs layout) and computes
  with LOCAL shapes, so `sdp_attention`/`q_matmul` dispatch to the
  Pallas kernels exactly as on a single chip;
- the two row-parallel matmuls are followed by explicit `lax.psum`
  (the `inference_all_reduce` analog), the lm_head's column shards
  `all_gather` into full logits.

Families: everything the generalized decoder serves (r4 — the local
body IS `M.forward` with collective-injecting weight wrappers, so
parallel-residual, shared-input-norm, non-gated-MLP, sliding-window and
soft-cap families all work) including (r5) MoE expert stacks and ALiBi
families (each device slices the full-model slope schedule at its head
offset). Embeddings and norms are replicated (as in the reference's
AutoTP).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.models import llama as M
from bigdl_tpu.observability.compile_watch import tracked_jit
from bigdl_tpu.ops.kvcache import KVCache
from bigdl_tpu.ops.matmul import linear
from bigdl_tpu.parallel.sharding import llama_param_specs

try:
    from jax import shard_map as _shard_map
    _REP_KW = {"check_vma": False}
except ImportError:                        # older jax
    from jax.experimental.shard_map import shard_map as _shard_map
    _REP_KW = {"check_rep": False}


def _tp_cfg(cfg, n: int, axis: str = "tp"):
    # r4: the local body is the REAL generalized decoder (M.forward with
    # collective-injecting weight wrappers), so every family knob it
    # supports — parallel residual, shared input norm, non-gated MLP,
    # layernorm biases, partial rotary, sliding windows, soft caps —
    # and (r5) MoE expert stacks work under explicit TP. One exclusion
    # remains:
    if getattr(cfg, "num_local_experts", 0) \
            and cfg.intermediate_size % n:
        raise ValueError(
            f"MoE expert ff {cfg.intermediate_size} not divisible by "
            f"tp={n}: expert stacks are not lane-padded (pad_ff_for_tp "
            "covers dense MLPs only); use a dividing tp, the ep axis "
            "(models/mixtral.py), or the GSPMD path")
    if cfg.num_attention_heads % n or cfg.num_key_value_heads % n:
        raise ValueError(
            f"heads ({cfg.num_attention_heads}/{cfg.num_key_value_heads}) "
            f"not divisible by tp={n}")
    if cfg.intermediate_size % n and _ff_padded(
            cfg.intermediate_size, n) == cfg.intermediate_size:
        # big models lane-pad their way to divisibility (_ff_padded);
        # small ones must fail HERE with a named error, not deep inside
        # device_put with a shard-count message
        raise ValueError(
            f"intermediate_size {cfg.intermediate_size} not divisible "
            f"by tp={n} (model too small for lane padding)")
    return dataclasses.replace(
        cfg,
        num_attention_heads=cfg.num_attention_heads // n,
        num_key_value_heads=cfg.num_key_value_heads // n,
        # ff may be lane-padded at shard time; runtime shapes come from
        # the weights, this field is only a bookkeeping hint
        intermediate_size=cfg.intermediate_size // n
        if cfg.intermediate_size % n == 0 else cfg.intermediate_size,
        head_dim=cfg.hd,   # pin: hd otherwise derives from FULL heads
        # ALiBi slopes are a function of the FULL head count; the local
        # trace slices the full schedule at its axis_index (llama.py
        # _model_slopes)
        alibi_total_heads=(cfg.num_attention_heads
                           if cfg.use_alibi else None),
        tp_axis=axis)


def tp_param_specs(params: Any, mesh: Mesh, axis: str = "tp") -> Any:
    """Shard specs for the explicit-TP path: the standard col/row rules,
    except embeddings are REPLICATED (a vocab-sharded gather inside
    shard_map would need masked-psum index arithmetic for no win here).

    Unlike the GSPMD path — where a quantized weight's planes may shard
    inconsistently and the partitioner just handles it — the explicit
    path computes with the LOCAL arrays, so every plane of a col/row
    weight must actually split. Validates and raises otherwise (tiny
    models: block-quantized scale planes have K/32 rows; K must satisfy
    K/32 % tp == 0 for row-parallel weights)."""
    specs = llama_param_specs(params, mesh, axis=axis)
    specs = jax.tree_util.tree_map_with_path(
        lambda path, s: P() if any(
            getattr(e, "key", None) == "embed_tokens" for e in path) else s,
        specs, is_leaf=lambda x: isinstance(x, P))

    from bigdl_tpu.parallel.sharding import LLAMA_RULES, _path_param_name

    flat_s = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, P))[0]
    for path, s in flat_s:
        name = _path_param_name(path)
        style = LLAMA_RULES.get(name)
        if name == "embed_tokens" or style is None:
            continue
        if not any(ax is not None for ax in s):
            raise ValueError(
                f"explicit TP cannot shard {name!r} over {axis}="
                f"{mesh.shape[axis]}: a plane's sharded dim does not "
                "divide (block-quantized scales need K/block % tp == 0); "
                "use the GSPMD path (parallel/sharding.py) or a smaller "
                "tp for this model")
    return specs


def _ff_padded(ff: int, n: int, block: int = 128) -> int:
    """Global intermediate size padded so each tp shard's ff slice is a
    128-lane multiple AND a quant-block multiple. An unaligned shard
    (e.g. 11008/4 = 2752, which is 21.5 x 128) can never satisfy the
    Pallas matmul's bn tiling, so the whole MLP would decode on the slow
    XLA dequant path (VERDICT r3 #4); and block-256 qtypes (k-quants,
    iqx) additionally need the down-proj's per-shard K to be a 256
    multiple, or the plane-row scaling in `_pad_ff_leaf` produces
    inconsistent shapes for odd shard counts (r4 advice). Zero-padding
    is EXACT: padded gate/up columns carry zero scales, so they
    dequantize to 0, the activation is act(0)*0 = 0, and the padded
    down-proj rows are zero too. Tiny test models stay untouched."""
    if ff < 2048 or n <= 1:
        return ff
    align = max(128, block)
    per = -(-ff // n)
    per = -(-per // align) * align
    return per * n


def _pad_axis(a, axis: int, new: int):
    pad = new - a.shape[axis]
    if pad <= 0:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, pad)
    if isinstance(a, jax.core.Tracer) or not hasattr(a, "shape"):
        return jnp.pad(a, widths)
    # concrete values pad on HOST: jnp.pad would materialize each full
    # padded weight on device 0 before the sharded device_put, a
    # transient whole-model-on-one-chip HBM spike at load time
    return np.pad(np.asarray(a), widths)


def _pad_ff_leaf(w, ff_new: int, axis_kind: str):
    """Zero-pad one (possibly layer-stacked) weight along its ff dim.
    axis_kind "n": gate/up (+biases) — last axis. "k": down-proj — the
    K axis; every QTensor plane's row count scales proportionally."""
    import dataclasses as dc

    from bigdl_tpu.ops.quant import QTensor

    if w is None:
        return None
    if isinstance(w, QTensor):
        if axis_kind == "n":
            if w.data.shape[-1] >= ff_new:
                return w
            rep = {f: _pad_axis(getattr(w, f), -1, ff_new)
                   for f in ("data", "scale", "zero", "aux")
                   if getattr(w, f) is not None}
            return dc.replace(w, shape=(w.shape[0], ff_new), **rep)
        kp = w.scale.shape[-2] * w.qt.block_size
        if kp >= ff_new:
            return w
        assert ff_new % w.qt.block_size == 0, \
            f"ff pad {ff_new} breaks block {w.qt.block_size} alignment"
        rep = {}
        for f in ("data", "scale", "zero", "aux"):
            p = getattr(w, f)
            if p is None:
                continue
            rep[f] = _pad_axis(p, -2, p.shape[-2] * ff_new // kp)
        return dc.replace(w, shape=(ff_new, w.shape[1]), **rep)
    return _pad_axis(w, -1 if axis_kind == "n" else -2, ff_new)


def pad_ff_for_tp(params: Any, n: int) -> Any:
    """Pad the per-layer MLP weights (ff dim) and the untied lm_head
    (vocab dim) so their tp shards are lane-aligned (no-op when already
    aligned). Exact — see `_ff_padded`; padded lm_head columns carry
    zero scales and the local forward slices the gathered logits back
    to the true vocab."""
    from bigdl_tpu.ops.quant import QTensor

    layers = params.get("layers")
    new_params = params
    if isinstance(layers, dict) and "down_proj" in layers:
        gate = layers.get("gate_proj", layers.get("up_proj"))
        if gate is not None:
            ff = gate.shape[1] if isinstance(gate, QTensor) \
                else gate.shape[-1]
            down = layers["down_proj"]
            blk = down.qt.block_size if isinstance(down, QTensor) else 128
            ff_new = _ff_padded(ff, n, blk)
            if ff_new != ff:
                new_layers = dict(layers)
                for name in ("gate_proj", "up_proj",
                             "gate_proj_bias", "up_proj_bias"):
                    if layers.get(name) is not None:
                        new_layers[name] = _pad_ff_leaf(
                            layers[name], ff_new, "n")
                new_layers["down_proj"] = _pad_ff_leaf(
                    layers["down_proj"], ff_new, "k")
                new_params = {**new_params, "layers": new_layers}
    head = params.get("lm_head")
    if head is not None:
        v = head.shape[1] if isinstance(head, QTensor) else head.shape[-1]
        v_new = _ff_padded(v, n)
        if v_new != v:
            new_params = {**new_params,
                          "lm_head": _pad_ff_leaf(head, v_new, "n")}
    return new_params


def shard_params_tp(params: Any, mesh: Mesh, axis: str = "tp") -> Any:
    layers = params.get("layers", {})
    if isinstance(layers, dict) and (
            "qkv_proj" in layers or "gate_up_proj" in layers):
        # a contiguous N-shard of a merged weight interleaves q/k/v
        # (gate/up) across devices — wrong math, so refuse loudly
        raise ValueError(
            "explicit TP shards the SPLIT projection layout; load the "
            "model with merge_projections=False (or run models.llama."
            "unmerge_projections) before shard_params_tp")
    params = pad_ff_for_tp(params, mesh.shape[axis])
    specs = tp_param_specs(params, mesh, axis=axis)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs)


def tp_cache_specs(axis: str = "tp") -> P:
    # [L, B, S, Hkv, hd]: heads sharded
    return P(None, None, None, axis, None)


def new_cache_tp(cfg, batch: int, max_seq: int, mesh: Mesh,
                 quantized=False, axis: str = "tp") -> KVCache:
    _tp_cfg(cfg, mesh.shape[axis], axis)  # fail fast, clear message
    from bigdl_tpu.ops.kvcache import (SCALED_KV_DTYPES,
                                       resolve_kv_cache_dtype)

    if resolve_kv_cache_dtype(quantized) in SCALED_KV_DTYPES:
        # the shard_mapped TP step carries only the k/v planes; the
        # int8/int4 scale planes are not threaded through its specs yet
        raise NotImplementedError(
            "kv_cache_dtype int8/int4 is not supported under explicit "
            "tensor parallelism; use 'bf16' or 'fp8_e5m2'")
    cache = M.new_cache(cfg, batch, max_seq, quantized=quantized)
    sh = NamedSharding(mesh, tp_cache_specs(axis))
    return KVCache(jax.device_put(cache.k, sh),
                   jax.device_put(cache.v, sh), cache.pos)


def _localize_qtensors(tree):
    """Inside shard_map a QTensor's ARRAYS are local shards but its
    static logical `shape` metadata still describes the global tensor —
    recompute it from the physical shards (valid because the sharding
    rules only split block-aligned dims)."""
    import dataclasses as dc

    from bigdl_tpu.ops.quant import QTensor, get_qtype

    def fix(w):
        if not isinstance(w, QTensor):
            return w
        qt = get_qtype(w.qtype)
        k_l = w.scale.shape[-2] * qt.block_size
        n_l = w.data.shape[-1]
        return dc.replace(w, shape=(min(w.shape[0], k_l), n_l))

    return jax.tree.map(fix, tree,
                        is_leaf=lambda x: not isinstance(x, (dict, list,
                                                             tuple)))


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class AllReduceLinear:
    """Row-parallel local weight: y = psum(x @ w_local) [+ bias].

    The collective rides the weight leaf (ops/matmul.linear dispatches
    to `apply_linear`), so the UNMODIFIED generalized decoder body runs
    per-device inside shard_map — the literal analog of DeepSpeed
    AutoTP's LinearAllreduce wrapper (`dist.inference_all_reduce`,
    reference transformers/low_bit_linear.py:635-637), expressed as a
    pytree transform instead of module surgery. The bias is replicated
    and must be added once, AFTER the reduce."""

    base: Any
    axis: str

    def apply_linear(self, x, bias, backend=None):
        y = linear(x, self.base, None, backend=backend)
        y = lax.psum(y, self.axis)
        if bias is not None:
            y = y + bias.astype(y.dtype)
        return y

    def post_reduce(self, y):
        """The reduce alone — for paths that consume `.base` directly
        (the ragged MoE kernel takes the raw expert stack) and reduce
        the partial output themselves."""
        return lax.psum(y, self.axis)

    def tree_flatten(self):
        return (self.base,), (self.axis,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], aux[0])


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class AllGatherLinear:
    """Column-parallel local weight whose FULL output is needed (the
    lm_head): y = all_gather(x @ w_local)[..., :true_n] [+ bias].
    `true_n` drops zero-scale vocab-padding logits before they can win
    an argmax."""

    base: Any
    axis: str
    true_n: int

    def apply_linear(self, x, bias, backend=None):
        y = linear(x, self.base, None, backend=backend)
        y = lax.all_gather(y, self.axis, axis=y.ndim - 1, tiled=True)
        y = y[..., :self.true_n]
        if bias is not None:
            y = y + bias.astype(y.dtype)[..., :self.true_n]
        return y

    def tree_flatten(self):
        return (self.base,), (self.axis, self.true_n)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], aux[0], aux[1])


def _wrap_collectives(p, axis: str, true_vocab: int):
    """Inject the TP collectives into the param pytree: row-parallel
    projections all-reduce, the col-sharded lm_head all-gathers."""
    layers = dict(p["layers"])
    for name in ("o_proj", "down_proj", "experts_down"):
        if name in layers:
            layers[name] = AllReduceLinear(layers[name], axis)
    out = {**p, "layers": layers}
    if "lm_head" in out:
        out["lm_head"] = AllGatherLinear(out["lm_head"], axis, true_vocab)
    return out


def _local_forward(cfg_l, axis: str, true_vocab: int):
    """Per-device forward over local head/column shards: the REAL
    generalized decoder (M.forward) — every family knob by construction
    — with collectives injected through the weight leaves."""

    def fwd(p, tokens, ck, cv, pos):
        p = _wrap_collectives(_localize_qtensors(p), axis, true_vocab)
        cache = KVCache(ck, cv, pos)
        lg, cache2 = M.forward(p, cfg_l, tokens, cache, last_only=True)
        return lg[:, -1], cache2.k, cache2.v

    return fwd


@functools.lru_cache(maxsize=32)
def _tp_fn(cfg, mesh, axis):
    n = mesh.shape[axis]
    cfg_l = _tp_cfg(cfg, n, axis)
    fwd = _local_forward(cfg_l, axis, cfg.vocab_size)

    # param specs must match how shard_params_tp laid them out; the spec
    # pytree uses the PARAM SHAPE tree, built lazily at first call
    def run(params, tokens, cache):
        pspecs = tp_param_specs(params, mesh, axis=axis)
        f = _shard_map(
            fwd, mesh=mesh,
            in_specs=(pspecs, P(), tp_cache_specs(axis),
                      tp_cache_specs(axis),
                      P()),
            out_specs=(P(), tp_cache_specs(axis), tp_cache_specs(axis)),
            **_REP_KW)
        lg, ck, cv = f(params, tokens, cache.k, cache.v, cache.pos)
        return lg, KVCache(ck, cv, cache.pos + tokens.shape[1])

    return tracked_jit("tp_forward_step", run, donate_argnums=(2,))


def tp_forward_step(
    params: Dict[str, Any],
    cfg,
    tokens: jax.Array,        # [B, Sq] int32
    cache: KVCache,
    mesh: Mesh,
    axis: str = "tp",
) -> Tuple[jax.Array, KVCache]:
    """One prefill/decode step (last-position logits [B, V], cache).
    Params/cache must be laid out by shard_params_tp/new_cache_tp."""
    fn = _tp_fn(cfg, mesh, axis)
    return fn(params, jnp.asarray(tokens, jnp.int32), cache)


def tp_generate(
    params: Dict[str, Any],
    cfg,
    input_ids,
    mesh: Mesh,
    axis: str = "tp",
    max_new_tokens: int = 32,
    max_seq: int = 2048,
    eos_token_id: Optional[int] = None,
) -> np.ndarray:
    """Greedy explicit-TP generation -> [B, S + new]."""
    ids = np.asarray(input_ids, np.int32)
    if ids.ndim == 1:
        ids = ids[None]
    b, s = ids.shape
    if s + max_new_tokens > max_seq:
        raise ValueError("prompt + max_new_tokens exceeds max_seq")
    cache = new_cache_tp(cfg, b, max_seq, mesh, axis=axis)
    lg, cache = tp_forward_step(params, cfg, jnp.asarray(ids), cache,
                                mesh, axis)
    out = [np.asarray(jnp.argmax(lg, axis=-1), np.int32)]
    for _ in range(max_new_tokens - 1):
        tok = jnp.asarray(out[-1][:, None])
        lg, cache = tp_forward_step(params, cfg, tok, cache, mesh, axis)
        nxt = np.asarray(jnp.argmax(lg, axis=-1), np.int32)
        out.append(nxt)
        if eos_token_id is not None and (nxt == eos_token_id).all():
            break
    return np.concatenate([ids, np.stack(out, axis=1)], axis=1)
