"""Tensor-parallel inference under EXPLICIT shard_map — kernels on shards.

The GSPMD path (parallel/sharding.py: shard the params, let XLA insert
the collectives) is correct but cannot use Pallas kernels — Mosaic ops
are not auto-partitionable (see PARITY.md "Multi-chip kernel dispatch"),
so it runs XLA ops. This module is the kernel-capable alternative, the
analog of how the reference reaches its per-device SYCL kernels through
DeepSpeed-AutoTP's explicit sharding (reference transformers/convert.py:
102-119 + dist.inference_all_reduce at low_bit_linear.py:635-637):

- the forward runs INSIDE shard_map over a 1-axis tp mesh;
- every device holds its head/column shard (q/k/v/gate/up column-split,
  o/down row-split — the same llama_param_specs layout) and computes
  with LOCAL shapes, so `sdp_attention`/`q_matmul` dispatch to the
  Pallas kernels exactly as on a single chip;
- the two row-parallel matmuls are followed by explicit `lax.psum`
  (the `inference_all_reduce` analog), the lm_head's column shards
  `all_gather` into full logits.

Families: standard residual path (same guard as parallel/cp.py).
Embeddings and norms are replicated (as in the reference's AutoTP).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.models import llama as M
from bigdl_tpu.ops.attention import sdp_attention
from bigdl_tpu.ops.kvcache import KVCache
from bigdl_tpu.ops.matmul import linear
from bigdl_tpu.ops.rope import apply_rope, rope_cos_sin
from bigdl_tpu.parallel.cp import _check_cfg
from bigdl_tpu.parallel.sharding import llama_param_specs

try:
    from jax import shard_map as _shard_map
    _REP_KW = {"check_vma": False}
except ImportError:                        # older jax
    from jax.experimental.shard_map import shard_map as _shard_map
    _REP_KW = {"check_rep": False}


def _tp_cfg(cfg, n: int):
    # the hand-rolled local layer body below supports the gated
    # sequential-residual block only (cp.py escapes this by reusing
    # M.ext_attn_layer; here the psum split makes that impossible)
    if (cfg.parallel_residual or getattr(cfg, "shared_input_norm", False)
            or not cfg.mlp_gated):
        raise NotImplementedError(
            "explicit TP supports the standard gated sequential-residual "
            "block; parallel-residual / non-gated families run through "
            "the GSPMD path (parallel/sharding.py)")
    if cfg.num_attention_heads % n or cfg.num_key_value_heads % n:
        raise ValueError(
            f"heads ({cfg.num_attention_heads}/{cfg.num_key_value_heads}) "
            f"not divisible by tp={n}")
    if cfg.intermediate_size % n:
        raise ValueError(f"intermediate_size {cfg.intermediate_size} not "
                         f"divisible by tp={n}")
    return dataclasses.replace(
        cfg,
        num_attention_heads=cfg.num_attention_heads // n,
        num_key_value_heads=cfg.num_key_value_heads // n,
        intermediate_size=cfg.intermediate_size // n,
        head_dim=cfg.hd)   # pin: hd otherwise derives from FULL heads


def tp_param_specs(params: Any, mesh: Mesh, axis: str = "tp") -> Any:
    """Shard specs for the explicit-TP path: the standard col/row rules,
    except embeddings are REPLICATED (a vocab-sharded gather inside
    shard_map would need masked-psum index arithmetic for no win here).

    Unlike the GSPMD path — where a quantized weight's planes may shard
    inconsistently and the partitioner just handles it — the explicit
    path computes with the LOCAL arrays, so every plane of a col/row
    weight must actually split. Validates and raises otherwise (tiny
    models: block-quantized scale planes have K/32 rows; K must satisfy
    K/32 % tp == 0 for row-parallel weights)."""
    specs = llama_param_specs(params, mesh, axis=axis)
    specs = jax.tree_util.tree_map_with_path(
        lambda path, s: P() if any(
            getattr(e, "key", None) == "embed_tokens" for e in path) else s,
        specs, is_leaf=lambda x: isinstance(x, P))

    from bigdl_tpu.parallel.sharding import LLAMA_RULES, _path_param_name

    flat_s = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, P))[0]
    for path, s in flat_s:
        name = _path_param_name(path)
        style = LLAMA_RULES.get(name)
        if name == "embed_tokens" or style is None:
            continue
        if not any(ax is not None for ax in s):
            raise ValueError(
                f"explicit TP cannot shard {name!r} over {axis}="
                f"{mesh.shape[axis]}: a plane's sharded dim does not "
                "divide (block-quantized scales need K/block % tp == 0); "
                "use the GSPMD path (parallel/sharding.py) or a smaller "
                "tp for this model")
    return specs


def _ff_padded(ff: int, n: int) -> int:
    """Global intermediate size padded so each tp shard's ff slice is a
    128-lane multiple. An unaligned shard (e.g. 11008/4 = 2752, which is
    21.5 x 128) can never satisfy the Pallas matmul's bn tiling, so the
    whole MLP would decode on the slow XLA dequant path (VERDICT r3 #4).
    Zero-padding is EXACT: padded gate/up columns carry zero scales, so
    they dequantize to 0, the activation is act(0)*0 = 0, and the padded
    down-proj rows are zero too. Tiny test models stay untouched."""
    if ff < 2048 or n <= 1:
        return ff
    per = -(-ff // n)
    per = -(-per // 128) * 128
    return per * n


def _pad_axis(a, axis: int, new: int):
    pad = new - a.shape[axis]
    if pad <= 0:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, pad)
    if isinstance(a, jax.core.Tracer) or not hasattr(a, "shape"):
        return jnp.pad(a, widths)
    # concrete values pad on HOST: jnp.pad would materialize each full
    # padded weight on device 0 before the sharded device_put, a
    # transient whole-model-on-one-chip HBM spike at load time
    return np.pad(np.asarray(a), widths)


def _pad_ff_leaf(w, ff_new: int, axis_kind: str):
    """Zero-pad one (possibly layer-stacked) weight along its ff dim.
    axis_kind "n": gate/up (+biases) — last axis. "k": down-proj — the
    K axis; every QTensor plane's row count scales proportionally."""
    import dataclasses as dc

    from bigdl_tpu.ops.quant import QTensor

    if w is None:
        return None
    if isinstance(w, QTensor):
        if axis_kind == "n":
            if w.data.shape[-1] >= ff_new:
                return w
            rep = {f: _pad_axis(getattr(w, f), -1, ff_new)
                   for f in ("data", "scale", "zero", "aux")
                   if getattr(w, f) is not None}
            return dc.replace(w, shape=(w.shape[0], ff_new), **rep)
        kp = w.scale.shape[-2] * w.qt.block_size
        if kp >= ff_new:
            return w
        rep = {}
        for f in ("data", "scale", "zero", "aux"):
            p = getattr(w, f)
            if p is None:
                continue
            rep[f] = _pad_axis(p, -2, p.shape[-2] * ff_new // kp)
        return dc.replace(w, shape=(ff_new, w.shape[1]), **rep)
    return _pad_axis(w, -1 if axis_kind == "n" else -2, ff_new)


def pad_ff_for_tp(params: Any, n: int) -> Any:
    """Pad the per-layer MLP weights (ff dim) and the untied lm_head
    (vocab dim) so their tp shards are lane-aligned (no-op when already
    aligned). Exact — see `_ff_padded`; padded lm_head columns carry
    zero scales and the local forward slices the gathered logits back
    to the true vocab."""
    from bigdl_tpu.ops.quant import QTensor

    layers = params.get("layers")
    new_params = params
    if isinstance(layers, dict) and "down_proj" in layers:
        gate = layers.get("gate_proj", layers.get("up_proj"))
        if gate is not None:
            ff = gate.shape[1] if isinstance(gate, QTensor) \
                else gate.shape[-1]
            ff_new = _ff_padded(ff, n)
            if ff_new != ff:
                new_layers = dict(layers)
                for name in ("gate_proj", "up_proj",
                             "gate_proj_bias", "up_proj_bias"):
                    if layers.get(name) is not None:
                        new_layers[name] = _pad_ff_leaf(
                            layers[name], ff_new, "n")
                new_layers["down_proj"] = _pad_ff_leaf(
                    layers["down_proj"], ff_new, "k")
                new_params = {**new_params, "layers": new_layers}
    head = params.get("lm_head")
    if head is not None:
        v = head.shape[1] if isinstance(head, QTensor) else head.shape[-1]
        v_new = _ff_padded(v, n)
        if v_new != v:
            new_params = {**new_params,
                          "lm_head": _pad_ff_leaf(head, v_new, "n")}
    return new_params


def shard_params_tp(params: Any, mesh: Mesh, axis: str = "tp") -> Any:
    layers = params.get("layers", {})
    if isinstance(layers, dict) and (
            "qkv_proj" in layers or "gate_up_proj" in layers):
        # a contiguous N-shard of a merged weight interleaves q/k/v
        # (gate/up) across devices — wrong math, so refuse loudly
        raise ValueError(
            "explicit TP shards the SPLIT projection layout; load the "
            "model with merge_projections=False (or run models.llama."
            "unmerge_projections) before shard_params_tp")
    params = pad_ff_for_tp(params, mesh.shape[axis])
    specs = tp_param_specs(params, mesh, axis=axis)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs)


def tp_cache_specs(axis: str = "tp") -> P:
    # [L, B, S, Hkv, hd]: heads sharded
    return P(None, None, None, axis, None)


def new_cache_tp(cfg, batch: int, max_seq: int, mesh: Mesh,
                 quantized: bool = False, axis: str = "tp") -> KVCache:
    _tp_cfg(cfg, mesh.shape[axis])      # fail fast with a clear message
    cache = M.new_cache(cfg, batch, max_seq, quantized=quantized)
    sh = NamedSharding(mesh, tp_cache_specs(axis))
    return KVCache(jax.device_put(cache.k, sh),
                   jax.device_put(cache.v, sh), cache.pos)


def _localize_qtensors(tree):
    """Inside shard_map a QTensor's ARRAYS are local shards but its
    static logical `shape` metadata still describes the global tensor —
    recompute it from the physical shards (valid because the sharding
    rules only split block-aligned dims)."""
    import dataclasses as dc

    from bigdl_tpu.ops.quant import QTensor, get_qtype

    def fix(w):
        if not isinstance(w, QTensor):
            return w
        qt = get_qtype(w.qtype)
        k_l = w.scale.shape[-2] * qt.block_size
        n_l = w.data.shape[-1]
        return dc.replace(w, shape=(min(w.shape[0], k_l), n_l))

    return jax.tree.map(fix, tree,
                        is_leaf=lambda x: not isinstance(x, (dict, list,
                                                             tuple)))


def _local_forward(cfg_l, axis: str):
    """Per-device forward over local head/column shards: the generalized
    decoder body, with psum after the row-parallel projections."""

    def fwd(p, tokens, ck, cv, pos):
        p = _localize_qtensors(p)
        b, sq = tokens.shape
        inv_freq, rope_mscale = M.model_rope_freqs(cfg_l)
        positions = pos + jnp.arange(sq, dtype=jnp.int32)
        x = M.embed_prologue(p, cfg_l, tokens, positions, jnp.bfloat16)
        cos, sin = rope_cos_sin(positions[None, :], inv_freq)
        if rope_mscale != 1.0:
            cos, sin = cos * rope_mscale, sin * rope_mscale
        h, hkv, hd = (cfg_l.num_attention_heads,
                      cfg_l.num_key_value_heads, cfg_l.hd)

        def layer(carry, xs):
            x, ck_l, cv_l = carry[0], xs[1], xs[2]
            lp = xs[0]
            hidden = M._norm(x, lp["input_layernorm"],
                             lp.get("input_layernorm_bias"), cfg_l)
            q = linear(hidden, lp["q_proj"], lp.get("q_proj_bias")) \
                .reshape(b, sq, h, hd)
            k = linear(hidden, lp["k_proj"], lp.get("k_proj_bias")) \
                .reshape(b, sq, hkv, hd)
            v = linear(hidden, lp["v_proj"], lp.get("v_proj_bias")) \
                .reshape(b, sq, hkv, hd)
            if cfg_l.use_rope:
                q = apply_rope(q, cos, sin,
                               interleaved=cfg_l.rope_interleaved)
                k = apply_rope(k, cos, sin,
                               interleaved=cfg_l.rope_interleaved)
            ck_l = lax.dynamic_update_slice(
                ck_l, k.astype(ck_l.dtype), (0, pos, 0, 0))
            cv_l = lax.dynamic_update_slice(
                cv_l, v.astype(cv_l.dtype), (0, pos, 0, 0))
            a = sdp_attention(q, ck_l, cv_l, pos)
            a = linear(a.reshape(b, sq, h * hd), lp["o_proj"], None)
            # row-parallel: partial results sum over the tp axis (the
            # reference's inference_all_reduce, low_bit_linear.py:635)
            a = lax.psum(a, axis)
            if lp.get("o_proj_bias") is not None:
                a = a + lp["o_proj_bias"].astype(a.dtype)
            x = x + a
            hidden2 = M._norm(x, lp["post_attention_layernorm"],
                              lp.get("post_attention_layernorm_bias"),
                              cfg_l)
            gate = linear(hidden2, lp["gate_proj"],
                          lp.get("gate_proj_bias"))
            up = linear(hidden2, lp["up_proj"], lp.get("up_proj_bias"))
            inner = M._ACTS[cfg_l.hidden_act](gate) * up
            down = lax.psum(
                linear(inner, lp["down_proj"], None), axis)
            if lp.get("down_proj_bias") is not None:
                down = down + lp["down_proj_bias"].astype(down.dtype)
            return (x + down,), (ck_l, cv_l)

        (x,), (ck2, cv2) = lax.scan(layer, (x,), (p["layers"], ck, cv))
        x = M._norm(x, p["norm"], p.get("norm_bias"), cfg_l)
        lg = M._lm_head(x[:, -1:], p, cfg_l)[:, 0]
        if "lm_head" in p:      # col-sharded head: [B, V/n] -> [B, V]
            lg = lax.all_gather(lg, axis, axis=1, tiled=True)
            # pad_ff_for_tp may have lane-padded the vocab; drop the
            # zero-scale pad logits before they can win an argmax
            lg = lg[:, :cfg_l.vocab_size]
        # tied embeddings are replicated: lg is already full-vocab
        return lg, ck2, cv2

    return fwd


@functools.lru_cache(maxsize=32)
def _tp_fn(cfg, mesh, axis):
    n = mesh.shape[axis]
    cfg_l = _tp_cfg(cfg, n)
    fwd = _local_forward(cfg_l, axis)

    # param specs must match how shard_params_tp laid them out; the spec
    # pytree uses the PARAM SHAPE tree, built lazily at first call
    def run(params, tokens, cache):
        pspecs = tp_param_specs(params, mesh, axis=axis)
        f = _shard_map(
            fwd, mesh=mesh,
            in_specs=(pspecs, P(), tp_cache_specs(axis),
                      tp_cache_specs(axis),
                      P()),
            out_specs=(P(), tp_cache_specs(axis), tp_cache_specs(axis)),
            **_REP_KW)
        lg, ck, cv = f(params, tokens, cache.k, cache.v, cache.pos)
        return lg, KVCache(ck, cv, cache.pos + tokens.shape[1])

    return jax.jit(run, donate_argnums=(2,))


def tp_forward_step(
    params: Dict[str, Any],
    cfg,
    tokens: jax.Array,        # [B, Sq] int32
    cache: KVCache,
    mesh: Mesh,
    axis: str = "tp",
) -> Tuple[jax.Array, KVCache]:
    """One prefill/decode step (last-position logits [B, V], cache).
    Params/cache must be laid out by shard_params_tp/new_cache_tp."""
    _check_cfg(cfg)
    fn = _tp_fn(cfg, mesh, axis)
    return fn(params, jnp.asarray(tokens, jnp.int32), cache)


def tp_generate(
    params: Dict[str, Any],
    cfg,
    input_ids,
    mesh: Mesh,
    axis: str = "tp",
    max_new_tokens: int = 32,
    max_seq: int = 2048,
    eos_token_id: Optional[int] = None,
) -> np.ndarray:
    """Greedy explicit-TP generation -> [B, S + new]."""
    ids = np.asarray(input_ids, np.int32)
    if ids.ndim == 1:
        ids = ids[None]
    b, s = ids.shape
    if s + max_new_tokens > max_seq:
        raise ValueError("prompt + max_new_tokens exceeds max_seq")
    cache = new_cache_tp(cfg, b, max_seq, mesh, axis=axis)
    lg, cache = tp_forward_step(params, cfg, jnp.asarray(ids), cache,
                                mesh, axis)
    out = [np.asarray(jnp.argmax(lg, axis=-1), np.int32)]
    for _ in range(max_new_tokens - 1):
        tok = jnp.asarray(out[-1][:, None])
        lg, cache = tp_forward_step(params, cfg, tok, cache, mesh, axis)
        nxt = np.asarray(jnp.argmax(lg, axis=-1), np.int32)
        out.append(nxt)
        if eos_token_id is not None and (nxt == eos_token_id).all():
            break
    return np.concatenate([ids, np.stack(out, axis=1)], axis=1)
