"""Microbatched pipeline parallelism over the `pp` mesh axis.

The reference's "pipeline parallel" is a naive 2-GPU layer split with no
microbatching — `accelerate.dispatch_model` over a device_map (reference
example/GPU/Pipeline-Parallel-Inference/generate.py:44-62): one GPU idles
while the other computes. This module is the real schedule the reference
lacks: a GPipe-style microbatched pipeline expressed the TPU way —

- The stacked layer tree [L, ...] is sharded along L over the `pp` axis
  (each stage holds L/P contiguous layers — works for dense AND quantized
  stacks, since every QTensor field is [L, ...]-leading).
- The schedule is a `lax.scan` over M + P - 1 ticks inside `shard_map`;
  activations move stage→stage with `lax.ppermute` over ICI. Stage 0
  injects a fresh microbatch each tick; the last stage's outputs fill in
  as the pipeline drains. Bubble fraction = (P-1)/(M+P-1), the GPipe
  formula — pick M >= 4*P to amortize.
- Reverse-mode AD flows through scan+ppermute (ppermute transposes to the
  reverse permutation), so the same schedule backs `make_pp_train_step` —
  1F1B-style memory scheduling is left to XLA's rematerialization
  (`jax.checkpoint` on the per-layer body).

Composes with the other axes: dp shards each microbatch's rows, tp shards
the within-layer matmuls (GSPMD), pp moves whole-layer activations.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.models import llama as M


def pp_param_specs(params: Dict[str, Any]) -> Dict[str, Any]:
    """PartitionSpec tree: layer stacks split along L over `pp`, the rest
    replicated."""
    specs = {k: jax.tree.map(lambda _: P(), v)
             for k, v in params.items() if k != "layers"}
    specs["layers"] = jax.tree.map(lambda _: P("pp"), params["layers"])
    return specs


def shard_params_pp(params: Dict[str, Any], mesh: Mesh) -> Dict[str, Any]:
    """Place the parameter tree: [L, ...] leaves split over `pp`."""
    pp = mesh.shape["pp"]
    sample = jax.tree_util.tree_leaves(params["layers"])[0]
    if sample.shape[0] % pp != 0:
        raise ValueError(
            f"num_hidden_layers {sample.shape[0]} not divisible by pp={pp}")
    specs = pp_param_specs(params)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs)


def _stage_forward(x, layers_local, cfg, cos, sin, slopes, stage, lp_count):
    """Run this stage's local layer stack on one microbatch activation."""
    lidx0 = stage * lp_count

    @jax.checkpoint
    def layer(x, xs):
        lp, li = xs
        out, _ = M._decoder_layer(x, lp, cfg, cos, sin, slopes,
                                  cache_ctx=None, lidx=li)
        return out

    lids = lidx0 + jnp.arange(lp_count, dtype=jnp.int32)
    x, _ = lax.scan(lambda c, xs: (layer(c, xs), None), x,
                    (layers_local, lids))
    return x


def pp_forward_train(
    params: Dict[str, Any],
    cfg,
    tokens: jax.Array,            # [B, S] int32
    mesh: Mesh,
    num_microbatches: int,
    compute_dtype=jnp.bfloat16,
) -> jax.Array:
    """Cacheless causal forward under the pipeline schedule.

    Returns logits [B, S, V] (valid on every device — the last stage's
    result is broadcast, so downstream loss code is placement-agnostic).
    Use `make_pp_train_step` for training (it keeps the loss scalar
    instead of broadcasting full logits).
    """
    return _pp_apply(params, cfg, tokens, mesh, num_microbatches,
                     compute_dtype, want="logits")


def _pp_apply(params, cfg, tokens, mesh, num_microbatches, compute_dtype,
              want="logits", targets=None, mask=None):
    pp = mesh.shape["pp"]
    L = cfg.num_hidden_layers
    if L % pp != 0:
        raise ValueError(f"num_hidden_layers {L} not divisible by pp={pp}")
    lp_count = L // pp
    b, s = tokens.shape
    mcount = num_microbatches
    if b % mcount != 0:
        raise ValueError(f"batch {b} not divisible by microbatches {mcount}")
    mb = b // mcount

    inv_freq, rope_mscale = M.model_rope_freqs(cfg)
    positions = jnp.arange(s, dtype=jnp.int32)
    from bigdl_tpu.ops.rope import rope_cos_sin

    cos, sin = rope_cos_sin(positions[None, :], inv_freq)
    if rope_mscale != 1.0:
        cos, sin = cos * rope_mscale, sin * rope_mscale
    slopes = (jnp.asarray(M.alibi_slopes(cfg.num_attention_heads))
              if cfg.use_alibi else None)

    top = {k: v for k, v in params.items() if k != "layers"}
    args = [top, params["layers"], tokens]
    specs = [jax.tree.map(lambda _: P(), top),
             jax.tree.map(lambda _: P("pp"), params["layers"]), P()]
    if targets is not None:
        args += [targets, mask]
        specs += [P(), P()]

    def body(top, layers_local, tokens, *rest):
        stage = lax.axis_index("pp")
        micro = tokens.reshape(mcount, mb, s)
        ticks = mcount + pp - 1
        perm = [(i, (i + 1) % pp) for i in range(pp)]

        def embed(toks):
            return M.embed_prologue(top, cfg, toks, positions,
                                    compute_dtype)

        d = cfg.hidden_size

        def tick(carry, t):
            x_recv = carry                       # from previous stage
            inj = embed(micro[jnp.minimum(t, mcount - 1)])
            x_in = jnp.where(stage == 0, inj, x_recv)
            y = _stage_forward(x_in, layers_local, cfg, cos, sin, slopes,
                               stage, lp_count)
            x_next = lax.ppermute(y, "pp", perm)
            return x_next, y

        x0 = jnp.zeros((mb, s, d), compute_dtype)
        _, ys = lax.scan(tick, x0, jnp.arange(ticks))

        # last stage's emissions at ticks P-1 .. P-2+M are microbatches
        # 0..M-1; other stages' slots are pipeline garbage
        outs = ys[pp - 1:].reshape(b, s, d)
        hidden = M._norm(outs, top["norm"], top.get("norm_bias"), cfg)
        logits = M._lm_head(hidden, top, cfg)
        is_last = (stage == pp - 1).astype(logits.dtype)

        if want == "loss":
            targets_, mask_ = rest
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(
                logp, targets_[..., None].astype(jnp.int32), axis=-1
            )[..., 0]
            m = mask_.astype(jnp.float32)
            local = jnp.sum(nll * m) / jnp.maximum(jnp.sum(m), 1.0)
            # only the final stage computed real activations
            return lax.psum(local * is_last, "pp")
        return lax.psum(logits * is_last, "pp")

    try:
        from jax import shard_map
        rep_kw = {"check_vma": False}
    except ImportError:                    # older jax
        from jax.experimental.shard_map import shard_map
        rep_kw = {"check_rep": False}

    fn = shard_map(body, mesh=mesh, in_specs=tuple(specs),
                   out_specs=P(), **rep_kw)
    return fn(*args)


def make_pp_train_step(cfg, mesh: Mesh, optimizer,
                       num_microbatches: int,
                       compute_dtype=jnp.bfloat16):
    """jit-compiled (params, opt_state, batch) -> (params, opt_state, loss)
    under the pipeline schedule. `batch` = {"tokens": [B, S+1] int32,
    "mask": [B, S+1]} (next-token loss, like training.make_train_step).
    Gradients stay stage-local (same [L,...]-split sharding as params);
    the optimizer update runs shard-wise under GSPMD.
    """

    def loss_fn(params, tokens, targets, mask):
        return _pp_apply(params, cfg, tokens, mesh, num_microbatches,
                         compute_dtype, want="loss", targets=targets,
                         mask=mask)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, batch):
        toks = batch["tokens"]
        mask = batch.get("mask")
        if mask is None:
            mask = jnp.ones_like(toks)
        tokens, targets = toks[:, :-1], toks[:, 1:]
        m = mask[:, 1:]
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets,
                                                  m)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        import optax

        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def pp_generate_forward(
    params: Dict[str, Any],
    cfg,
    tokens: jax.Array,
    mesh: Mesh,
    num_microbatches: int = 1,
    compute_dtype=jnp.bfloat16,
) -> jax.Array:
    """Inference convenience: pipeline-parallel scoring of a batch of
    prompts (the reference's Pipeline-Parallel-Inference example shape —
    layer-split forward — but microbatched instead of lock-step).
    Decode-with-KV-cache under pp is intentionally not provided: on TPU
    meshes, tensor parallelism over ICI dominates for token-by-token
    decoding (PARITY.md §2.2); pp targets whole-sequence throughput."""
    return pp_forward_train(params, cfg, tokens, mesh, num_microbatches,
                            compute_dtype)
