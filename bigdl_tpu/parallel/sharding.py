"""Sharding rules for parameter pytrees (QTensor-aware tensor parallelism).

The AutoTP equivalent, redesigned: where the reference shards nn.Linear
modules with DeepSpeed and then quantizes the shards — capturing an
`mp_group` and calling `dist.inference_all_reduce` by hand after every
row-parallel matmul (reference transformers/convert.py:102-119,
low_bit_linear.py:635-637) — here the *quantized* arrays themselves carry
shardings. A QTensor's packed data, scales, zeros and high-bit planes are
all laid out [.., K-ish, N], so one rule covers every field:

  column-parallel (q/k/v/gate/up, lm_head): shard the last axis (N)
  row-parallel  (o_proj/down_proj):         shard the second-to-last (K)

Scales shard *with* their blocks automatically (K//block rows follow K).
XLA/GSPMD then inserts the all-reduce after row-parallel matmuls — there is
no hand-written collective anywhere in the model code.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# param-name → parallel style for the llama family pytree
# (bigdl_tpu/models/llama.py layout).
LLAMA_RULES: Dict[str, str] = {
    "embed_tokens": "row",      # shard vocab; gather+psum handled by GSPMD
    "q_proj": "col",
    "k_proj": "col",
    "v_proj": "col",
    "o_proj": "row",
    "gate_proj": "col",
    "up_proj": "col",
    "down_proj": "row",
    "q_proj_bias": "col",
    "k_proj_bias": "col",
    "v_proj_bias": "col",
    "gate_proj_bias": "col",
    "up_proj_bias": "col",
    # merged layouts (the from_pretrained default): still column-parallel
    # under GSPMD — the q/k/v (gate/up) output slices cross shard
    # boundaries, which the partitioner reshard-handles; without these
    # entries the LARGEST weights would silently replicate
    "qkv_proj": "col",
    "gate_up_proj": "col",
    "qkv_proj_bias": "col",
    "gate_up_proj_bias": "col",
    "lm_head": "col",
    # MoE expert stacks [L, E, K, N]: each expert's ff dim splits
    # across tp (Megatron-style expert TP) — gate/up column-parallel,
    # down row-parallel; the router stays replicated (no rule)
    "experts_gate": "col",
    "experts_up": "col",
    "experts_down": "row",
    "experts_up_bias": "col",
    # replicated: norms, router, o/down/experts_down biases (added
    # post-reduce)
}


def _path_param_name(path) -> str:
    """Last dict key on the path = the logical parameter name."""
    name = ""
    for entry in path:
        if isinstance(entry, jax.tree_util.DictKey):
            name = str(entry.key)
    return name


def _leaf_spec(style: str, leaf: jax.Array, axis: str, axis_size: int) -> P:
    """Spec for one array leaf under a col/row rule.

    Leaves are [.., K-ish, N] (weights, scales, zeros, bit-planes, stacked
    or not) or [.., N] (biases). Falls back to replication when the sharded
    dim does not divide by the mesh axis (the reference hard-fails here;
    uneven heads are common enough to deserve a graceful path).
    """
    nd = leaf.ndim
    if style == "col":
        dim = nd - 1
    elif style == "row":
        dim = nd - 2
        if dim < 0:
            return P()
    else:
        return P()
    if leaf.shape[dim] % axis_size != 0:
        return P()
    spec = [None] * nd
    spec[dim] = axis
    return P(*spec)


def llama_param_specs(
    params: Any,
    mesh: Mesh,
    rules: Optional[Dict[str, str]] = None,
    axis: str = "tp",
) -> Any:
    """PartitionSpec pytree matching `params` (llama-family layout).

    Works for dense and quantized pytrees alike: QTensor children (packed
    data / scale / zero / aux) inherit the owning parameter's rule.
    """
    rules = rules if rules is not None else LLAMA_RULES
    axis_size = mesh.shape.get(axis, 1)

    def spec_for(path, leaf):
        style = rules.get(_path_param_name(path), "rep")
        return _leaf_spec(style, leaf, axis, axis_size)

    return jax.tree_util.tree_map_with_path(spec_for, params)


def shard_params(
    params: Any,
    mesh: Mesh,
    specs: Optional[Any] = None,
    rules: Optional[Dict[str, str]] = None,
    axis: str = "tp",
) -> Any:
    """device_put every leaf with its NamedSharding (commits the layout;
    jit then propagates it — no in_shardings needed at call sites)."""
    if specs is None:
        specs = llama_param_specs(params, mesh, rules=rules, axis=axis)
    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_s, _ = jax.tree_util.tree_flatten(
        specs, is_leaf=lambda x: isinstance(x, P))
    out = [
        jax.device_put(p, NamedSharding(mesh, s))
        for p, s in zip(flat_p, flat_s)
    ]
    return jax.tree_util.tree_unflatten(treedef, out)


def replicate(tree: Any, mesh: Mesh) -> Any:
    """Fully replicate a pytree over the mesh."""
    sh = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(x, sh), tree)


def shard_batch(batch: Any, mesh: Mesh, axis: str = "dp") -> Any:
    """Shard array leading axes over the data-parallel mesh axis."""
    def put(x):
        if getattr(x, "ndim", 0) == 0 or x.shape[0] % mesh.shape.get(axis, 1):
            return jax.device_put(x, NamedSharding(mesh, P()))
        return jax.device_put(x, NamedSharding(mesh, P(axis)))
    return jax.tree.map(put, batch)


def shard_moe_params(params: Any, mesh: Mesh, axis: str = "ep") -> Any:
    """Expert parallelism: shard the expert axis of MoE stacks [L, E, ..]
    over `axis`, replicating everything else (the reference has no
    cross-device MoE at all — models/mixtral.py:79-138 loops experts on
    one device). Every `experts_*` leaf (and its QTensor planes, which
    keep the [L, E, ...] leading axes) splits on dim 1."""
    def put(path, x):
        names = [getattr(p, "name", getattr(p, "key", None)) for p in path]
        is_exp = any(isinstance(n, str) and n.startswith("experts_")
                     for n in names)
        spec = P(None, axis) if is_exp else P()
        return jax.device_put(x, NamedSharding(mesh, spec))
    return jax.tree_util.tree_map_with_path(put, params)
