"""Context-parallel INFERENCE: ring prefill + sequence-sharded KV decode.

Long-context serving the reference cannot do at all (SURVEY.md §2.2: its
long-context story is FP8 KV + 32k model variants, single-device): here a
prompt longer than one chip's KV budget shards over the `sp` mesh axis —

- **Prefill** runs the generalized decoder once per chip on its token
  chunk with EXACT ring attention (ops/ring.py): peak activation and KV
  memory are O(S/n) per chip, K/V chunks ride the ICI ring.
- **The KV cache stays sharded for decode.** Global position g lives on
  device g mod n at local row g div n (the "cyclic" ring layout), so
  ownership stays balanced for any prompt length and every decode token
  lands on a rotating owner. Each step, every chip computes the (tiny)
  token forward, attends over ITS cache slice, and the partial softmax
  stats merge with one pmax + two psums (flash-style: m_g = pmax(m),
  l_g = psum(l*exp(m-m_g)), o_g = psum(o*exp(m-m_g))) — decode HBM
  traffic per chip is the weight read + 1/n of the KV read.

Everything runs inside ONE shard_map-per-phase jit; params are replicated
over sp (compose with tp via parallel/sharding.py for weight sharding).
Supported families: the standard residual path (same guard as
forward_train's attn_fn branch).
"""

from __future__ import annotations

import functools
import warnings
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.models import llama as M
from bigdl_tpu.observability.compile_watch import tracked_jit
from bigdl_tpu.ops.matmul import linear

_WARNED_CP_SCALED = False    # one warning per process for int8/int4 CP
from bigdl_tpu.ops.ring import ring_attention
from bigdl_tpu.ops.rope import apply_rope, rope_cos_sin

try:
    from jax import shard_map as _shard_map
    _REP_KW = {"check_vma": False}
except ImportError:                        # older jax
    from jax.experimental.shard_map import shard_map as _shard_map
    _REP_KW = {"check_rep": False}


def _check_cfg(cfg) -> None:
    if (cfg.use_alibi or cfg.attn_soft_cap is not None
            or cfg.sandwich_norms or cfg.alt_sliding_window
            or cfg.query_pre_attn_scalar is not None
            or cfg.sliding_window is not None):
        raise NotImplementedError(
            "context-parallel inference supports the standard residual "
            "path (same guard as forward_train's ring-attention branch); "
            "ALiBi/soft-cap/sliding-window families run single-device")


def to_cyclic(tokens: jax.Array, n: int) -> jax.Array:
    """[B, S] -> device-major cyclic order: sharding the result over the
    last axis hands device p the tokens p, p+n, p+2n, ..."""
    b, s = tokens.shape
    return tokens.reshape(b, s // n, n).transpose(0, 2, 1).reshape(b, s)


def cp_prefill(
    params: Dict[str, Any],
    cfg,
    tokens: jax.Array,        # [B, S] int32; S % n == 0
    mesh: Mesh,
    axis: str = "sp",
    max_seq: Optional[int] = None,
    compute_dtype=jnp.bfloat16,
) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
    """Returns (next-token logits [B, V] replicated, (ck, cv) sharded
    caches [L, B, max_seq/n, Hkv, hd] in the cyclic layout, filled for
    the prompt)."""
    _check_cfg(cfg)
    n = mesh.shape[axis]
    b, s = tokens.shape
    if s % n:
        raise ValueError(f"prompt length {s} not divisible by sp={n}")
    max_seq = max_seq or s
    if max_seq % n or max_seq < s:
        raise ValueError(f"max_seq {max_seq} must be a multiple of sp={n} "
                         f"and >= prompt {s}")
    tok_cyc = to_cyclic(tokens, n)
    fn = _prefill_fn(cfg, mesh, axis, s, max_seq, compute_dtype)
    lg, ck, cv = fn(params, tok_cyc)
    return lg, (ck, cv)


@functools.lru_cache(maxsize=32)
def _prefill_fn(cfg, mesh, axis, s, max_seq, compute_dtype):
    n = mesh.shape[axis]
    cap = max_seq // n
    inv_freq, rope_mscale = M.model_rope_freqs(cfg)

    def local(params, tok_loc):
        p = lax.axis_index(axis)
        s_loc = tok_loc.shape[1]
        positions = p + jnp.arange(s_loc, dtype=jnp.int32) * n
        x = M.embed_prologue(params, cfg, tok_loc, positions,
                             compute_dtype)
        cos, sin = rope_cos_sin(positions[None, :], inv_freq)
        if rope_mscale != 1.0:
            cos, sin = cos * rope_mscale, sin * rope_mscale

        ring = functools.partial(ring_attention, axis_name=axis,
                                 layout="cyclic")

        def step(carry, lp):
            out, kv = M.ext_attn_layer(carry, lp, cfg, cos, sin, ring)
            return out, kv

        x, (ks, vs) = lax.scan(step, x, params["layers"])
        x = M._norm(x, params["norm"], params.get("norm_bias"), cfg)

        # logits only for the LAST global token (position s-1, owned by
        # device (s-1) % n at local row (s-1) // n)
        owner = (s - 1) % n
        row = (s - 1) // n
        lg = M._lm_head(x[:, row:row + 1], params, cfg)[:, 0]   # [B, V]
        lg = lax.psum(jnp.where(p == owner, lg, 0.0), axis)

        # grow the per-layer chunks into the capacity-sized cache slice
        pad = cap - s_loc
        ck = jnp.pad(ks.astype(compute_dtype),
                     ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0)))
        cv = jnp.pad(vs.astype(compute_dtype),
                     ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0)))
        return lg, ck, cv

    spec_tok = P(None, axis)
    spec_cache = P(None, None, axis)
    return tracked_jit("cp_prefill", _shard_map(
        local, mesh=mesh, in_specs=(P(), spec_tok),
        out_specs=(P(), spec_cache, spec_cache), **_REP_KW))


def cp_decode_step(
    params: Dict[str, Any],
    cfg,
    tok: jax.Array,           # [B] int32 current token
    cache: Tuple[jax.Array, jax.Array],
    pos: jax.Array,           # scalar int32: global position of `tok`
    mesh: Mesh,
    axis: str = "sp",
    compute_dtype=jnp.bfloat16,
) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
    """One decode step over the sequence-sharded cache. `pos` is a HOST
    int (the guard below needs it concrete). Returns (logits [B, V]
    replicated, updated cache)."""
    _check_cfg(cfg)
    pos = int(pos)
    capacity = cache[0].shape[2]      # global rows (n shards of cap each)
    if pos >= capacity:
        # dynamic_update_slice would silently CLAMP the write row and
        # corrupt the last stored position
        raise ValueError(
            f"decode position {pos} exceeds the sharded cache capacity "
            f"{capacity}; allocate a larger max_seq at cp_prefill")
    fn = _decode_fn(cfg, mesh, axis, compute_dtype)
    lg, ck, cv = fn(params, tok, cache[0], cache[1],
                    jnp.asarray(pos, jnp.int32))
    return lg, (ck, cv)


@functools.lru_cache(maxsize=32)
def _decode_fn(cfg, mesh, axis, compute_dtype):
    n = mesh.shape[axis]
    inv_freq, rope_mscale = M.model_rope_freqs(cfg)
    h, hkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.hd
    g = h // hkv

    def local(params, tok, ck, cv, pos):
        p = lax.axis_index(axis)
        cap = ck.shape[2]
        positions = pos[None]                       # [1]
        x = M.embed_prologue(params, cfg, tok[:, None], positions,
                             compute_dtype)
        cos, sin = rope_cos_sin(positions[None, :], inv_freq)
        if rope_mscale != 1.0:
            cos, sin = cos * rope_mscale, sin * rope_mscale

        owner = pos % n
        row = pos // n
        gid = p + jnp.arange(cap, dtype=jnp.int32) * n      # global ids

        def step(carry, xs):
            x = carry
            lp, ck_l, cv_l = xs
            stored = {}

            def attn_fn(q, k, v):
                # the owner stores the new entry BEFORE attending, so
                # the current token attends itself through the same path
                k_new = jnp.where(p == owner,
                                  lax.dynamic_update_slice(
                                      ck_l, k.astype(ck_l.dtype),
                                      (0, row, 0, 0)), ck_l)
                v_new = jnp.where(p == owner,
                                  lax.dynamic_update_slice(
                                      cv_l, v.astype(cv_l.dtype),
                                      (0, row, 0, 0)), cv_l)
                stored["kv"] = (k_new, v_new)
                # partial attention over the local slice, flash-merged
                qf = q.reshape(-1, 1, hkv, g, hd).astype(jnp.bfloat16)
                s_ = jnp.einsum("bqhgd,bkhd->bhgqk", qf,
                                k_new.astype(jnp.bfloat16),
                                preferred_element_type=jnp.float32) \
                    * (hd ** -0.5)
                valid = gid <= pos
                s_ = jnp.where(valid[None, None, None, None, :], s_,
                               -jnp.inf)
                m_loc = jnp.max(s_, axis=-1)
                m_g = lax.pmax(m_loc, axis)
                pexp = jnp.where(jnp.isfinite(s_),
                                 jnp.exp(s_ - m_g[..., None]), 0.0)
                l_g = lax.psum(jnp.sum(pexp, axis=-1), axis)
                o = jnp.einsum("bhgqk,bkhd->bhgqd",
                               pexp.astype(jnp.bfloat16),
                               v_new.astype(jnp.bfloat16),
                               preferred_element_type=jnp.float32)
                o = lax.psum(o, axis) / jnp.maximum(l_g, 1e-30)[..., None]
                return jnp.moveaxis(o, 3, 1).reshape(
                    q.shape[0], 1, h * hd).astype(q.dtype)

            out, _ = M.ext_attn_layer(x, lp, cfg, cos, sin, attn_fn)
            return out, stored["kv"]

        x, (ck2, cv2) = lax.scan(step, x, (params["layers"], ck, cv))
        x = M._norm(x, params["norm"], params.get("norm_bias"), cfg)
        lg = M._lm_head(x, params, cfg)[:, 0]               # [B, V]
        return lg, ck2, cv2

    spec_cache = P(None, None, axis)
    return tracked_jit("cp_decode_step", _shard_map(
        local, mesh=mesh,
        in_specs=(P(), P(), spec_cache, spec_cache, P()),
        out_specs=(P(), spec_cache, spec_cache), **_REP_KW),
        donate_argnums=(2, 3))


def cp_empty_cache(cfg, batch: int, max_seq: int, mesh: Mesh,
                   axis: str = "sp", compute_dtype=jnp.bfloat16,
                   kv_cache_dtype: str = "bf16"):
    """Zero sequence-sharded (ck, cv) caches for incremental CP prefill
    (cp_prefill_chunk); max_seq % mesh size == 0.

    kv_cache_dtype selects the STORAGE dtype: "fp8_e5m2" stores e5m2
    (the einsum read sites already upcast to bf16); "int8"/"int4" need
    per-token scale planes the sharded (ck, cv) tuple does not carry, so
    the CP lane falls back to bf16 storage with a one-time warning."""
    n = mesh.shape[axis]
    if max_seq % n:
        raise ValueError(f"max_seq {max_seq} not divisible by {n}")
    if kv_cache_dtype == "fp8_e5m2":
        compute_dtype = jnp.float8_e5m2
    elif kv_cache_dtype in ("int8", "int4"):
        global _WARNED_CP_SCALED
        if not _WARNED_CP_SCALED:
            _WARNED_CP_SCALED = True
            warnings.warn(
                f"kv_cache_dtype={kv_cache_dtype!r} is not supported on "
                "the context-parallel overflow lane (no scale planes in "
                "the sequence-sharded cache); CP requests store bf16",
                stacklevel=2)
    return_dtype = compute_dtype
    shape = (cfg.num_hidden_layers, batch, max_seq,
             cfg.num_key_value_heads, cfg.hd)
    sh = NamedSharding(mesh, P(None, None, axis))
    ck = jax.device_put(jnp.zeros(shape, return_dtype), sh)
    return ck, jax.device_put(jnp.zeros(shape, return_dtype), sh)


def cp_prefill_chunk(
    params: Dict[str, Any],
    cfg,
    tokens: jax.Array,        # [B, C] int32 (pad tail with anything)
    cache: Tuple[jax.Array, jax.Array],
    p0: int,                  # global position of tokens[:, 0]
    sel_pos: int,             # global position whose logits to return
    mesh: Mesh,
    axis: str = "sp",
    compute_dtype=jnp.bfloat16,
) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
    """Append one CONTIGUOUS chunk of prompt tokens to the sequence-
    sharded cache in a single dispatch — the incremental form of
    cp_prefill that a serving engine can interleave with decode steps
    (chunked admission; one chunk per engine step). Each device writes
    the chunk rows it owns (cyclic layout; out-of-capacity pad writes
    drop), then C queries flash-merge over every local cache slice.
    Returns (logits [B, V] replicated for `sel_pos`, updated cache)."""
    _check_cfg(cfg)
    fn = _extend_fn(cfg, mesh, axis, int(tokens.shape[1]), compute_dtype)
    lg, ck, cv = fn(params, tokens, cache[0], cache[1],
                    jnp.asarray(int(p0), jnp.int32),
                    jnp.asarray(int(sel_pos), jnp.int32))
    return lg, (ck, cv)


@functools.lru_cache(maxsize=32)
def _extend_fn(cfg, mesh, axis, c, compute_dtype):
    n = mesh.shape[axis]
    inv_freq, rope_mscale = M.model_rope_freqs(cfg)
    h, hkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.hd
    g = h // hkv

    def local(params, tok, ck, cv, p0, sel_pos):
        p = lax.axis_index(axis)
        cap = ck.shape[2]
        positions = p0 + jnp.arange(c, dtype=jnp.int32)       # [C]
        x = M.embed_prologue(params, cfg, tok, positions, compute_dtype)
        cos, sin = rope_cos_sin(positions[None, :], inv_freq)
        if rope_mscale != 1.0:
            cos, sin = cos * rope_mscale, sin * rope_mscale

        mine = (positions % n) == p
        # out-of-range index -> scatter drops the write (pad tail rows
        # past capacity, and rows owned by other devices)
        lrow = jnp.where(mine, positions // n, cap)
        gid = p + jnp.arange(cap, dtype=jnp.int32) * n

        def step(carry, xs):
            x = carry
            lp, ck_l, cv_l = xs
            stored = {}

            def attn_fn(q, k, v):
                k_new = ck_l.at[:, lrow].set(
                    k.astype(ck_l.dtype), mode="drop")
                v_new = cv_l.at[:, lrow].set(
                    v.astype(cv_l.dtype), mode="drop")
                stored["kv"] = (k_new, v_new)
                qf = q.reshape(-1, c, hkv, g, hd).astype(jnp.bfloat16)
                s_ = jnp.einsum("bqhgd,bkhd->bhgqk", qf,
                                k_new.astype(jnp.bfloat16),
                                preferred_element_type=jnp.float32) \
                    * (hd ** -0.5)
                valid = gid[None, :] <= positions[:, None]    # [C, cap]
                s_ = jnp.where(valid[None, None, None], s_, -jnp.inf)
                m_loc = jnp.max(s_, axis=-1)
                m_g = lax.pmax(m_loc, axis)
                pexp = jnp.where(jnp.isfinite(s_),
                                 jnp.exp(s_ - m_g[..., None]), 0.0)
                l_g = lax.psum(jnp.sum(pexp, axis=-1), axis)
                o = jnp.einsum("bhgqk,bkhd->bhgqd",
                               pexp.astype(jnp.bfloat16),
                               v_new.astype(jnp.bfloat16),
                               preferred_element_type=jnp.float32)
                o = lax.psum(o, axis) / jnp.maximum(l_g, 1e-30)[..., None]
                return jnp.moveaxis(o, 3, 1).reshape(
                    q.shape[0], c, h * hd).astype(q.dtype)

            out, _ = M.ext_attn_layer(x, lp, cfg, cos, sin, attn_fn)
            return out, stored["kv"]

        x, (ck2, cv2) = lax.scan(step, x, (params["layers"], ck, cv))
        x = M._norm(x, params["norm"], params.get("norm_bias"), cfg)
        row = jnp.clip(sel_pos - p0, 0, c - 1)
        lg = M._lm_head(
            lax.dynamic_slice_in_dim(x, row, 1, axis=1), params, cfg)[:, 0]
        return lg, ck2, cv2

    spec_cache = P(None, None, axis)
    return tracked_jit("cp_prefill_chunk", _shard_map(
        local, mesh=mesh,
        in_specs=(P(), P(), spec_cache, spec_cache, P(), P()),
        out_specs=(P(), spec_cache, spec_cache), **_REP_KW),
        donate_argnums=(2, 3))


def cp_generate(
    params: Dict[str, Any],
    cfg,
    input_ids,                # [B, S] ints, S % n == 0
    mesh: Mesh,
    axis: str = "sp",
    max_new_tokens: int = 32,
    max_seq: Optional[int] = None,
    eos_token_id: Optional[int] = None,
) -> np.ndarray:
    """Greedy context-parallel generation -> [B, S + new]. The prompt KV
    never materializes on one chip; see module docstring."""
    ids = np.asarray(input_ids, np.int32)
    if ids.ndim == 1:
        ids = ids[None]
    b, s = ids.shape
    n = mesh.shape[axis]
    max_seq = max_seq or (-(-(s + max_new_tokens) // n) * n)
    if max_seq < s + max_new_tokens:
        raise ValueError(
            f"max_seq {max_seq} cannot hold prompt {s} + "
            f"max_new_tokens {max_new_tokens}")

    lg, cache = cp_prefill(params, cfg, jnp.asarray(ids), mesh, axis,
                           max_seq=max_seq)
    out = [np.asarray(jnp.argmax(lg, axis=-1), np.int32)]
    for t in range(max_new_tokens - 1):
        tok = jnp.asarray(out[-1])
        lg, cache = cp_decode_step(params, cfg, tok, cache, s + t, mesh,
                                   axis)
        nxt = np.asarray(jnp.argmax(lg, axis=-1), np.int32)
        out.append(nxt)
        if eos_token_id is not None and (nxt == eos_token_id).all():
            break
    return np.concatenate([ids, np.stack(out, axis=1)], axis=1)
