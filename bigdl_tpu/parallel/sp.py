"""Sequence/context-parallel training: ring attention over the sp axis.

A capability the reference does NOT have (SURVEY.md §5 "Long-context":
its options are FP8 KV caches and 32k model variants, single-device only).
Here a long sequence is sharded over the `sp` mesh axis; every layer runs
on local chunks; attention is exact ring attention (ops/ring.py) with K/V
rotating over ICI; RoPE uses global position offsets; and the next-token
loss handles the shard-boundary shift with a single ppermute of the
neighbouring first token. Peak activation memory per chip is O(S/sp).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.ops.ring import ring_attention


def sp_loss_fn(
    params: Any,
    cfg: Any,
    tokens_local: jax.Array,       # [B, S_loc] this shard's sequence chunk
    mask_local: Optional[jax.Array],
    forward_train: Callable,
    axis_name: str = "sp",
) -> jax.Array:
    """Mean next-token loss, computed collectively. Call inside shard_map."""
    b, s_loc = tokens_local.shape
    p = lax.axis_index(axis_name)
    n = lax.psum(1, axis_name)

    attn = functools.partial(ring_attention, axis_name=axis_name,
                             sliding_window=getattr(cfg, "sliding_window",
                                                    None))
    logits = forward_train(params, cfg, tokens_local,
                           attn_fn=attn, pos_offset=p * s_loc)  # [B,S_loc,V]

    # targets: local tokens shifted by one; the last position's target is
    # the NEXT shard's first token (ppermute right-to-left)
    perm = [((i + 1) % n, i) for i in range(n)]   # recv from right neighbor
    nxt_first = lax.ppermute(tokens_local[:, :1], axis_name, perm)
    targets = jnp.concatenate([tokens_local[:, 1:], nxt_first], axis=1)

    valid = jnp.ones((b, s_loc), jnp.float32)
    if mask_local is not None:
        m = mask_local.astype(jnp.float32)
        nxt_mask = lax.ppermute(m[:, :1], axis_name, perm)
        valid = jnp.concatenate([m[:, 1:], nxt_mask], axis=1)
    # global last position has no target
    is_last_shard = (p == n - 1)
    last_pos_mask = jnp.where(
        is_last_shard & (jnp.arange(s_loc) == s_loc - 1), 0.0, 1.0)
    valid = valid * last_pos_mask[None, :]

    ll = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(ll, targets[..., None], axis=-1)[..., 0]
    local_sum = jnp.sum(nll * valid)
    local_cnt = jnp.sum(valid)
    total = lax.psum(local_sum, axis_name)
    count = lax.psum(local_cnt, axis_name)
    return total / jnp.maximum(count, 1.0)


def make_sp_train_step(
    forward_train: Callable,
    cfg: Any,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    axis_name: str = "sp",
) -> Callable:
    """Build `step(params, opt_state, batch) -> (params, opt_state, loss)`
    with the sequence axis of batch["input_ids"] sharded over `axis_name`.

    Params are replicated over sp (grads come back psum'd); compose with tp
    by sharding param leaves on other axes as usual — shard_map only
    manualizes the sp axis.
    """
    def loss(params, tokens_local, mask_local):
        return sp_loss_fn(params, cfg, tokens_local, mask_local,
                          forward_train, axis_name)

    grad_fn = jax.value_and_grad(loss)

    def sharded_grads(params, tokens_local, mask_local):
        l, g = grad_fn(params, tokens_local, mask_local)
        # psum's transpose is psum, so each shard's local grad already
        # carries an n-factor from the collective loss; pmean both combines
        # the per-shard contributions and cancels it exactly
        g = jax.tree.map(lambda x: lax.pmean(x, axis_name), g)
        return l, g

    seq_spec = P(None, axis_name)
    rep = P()

    shard_grad = jax.shard_map(
        sharded_grads, mesh=mesh,
        in_specs=(rep, seq_spec, seq_spec),
        out_specs=(rep, rep),
    )

    @jax.jit
    def step(params, opt_state, batch):
        mask = batch.get("attention_mask")
        if mask is None:
            mask = jnp.ones_like(batch["input_ids"])
        l, grads = shard_grad(params, batch["input_ids"], mask)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, l

    return step


def shard_batch_sp(batch, mesh: Mesh, axis_name: str = "sp"):
    spec = NamedSharding(mesh, P(None, axis_name))
    return jax.tree.map(lambda x: jax.device_put(x, spec), batch)
