"""Importance-matrix (imatrix) support: collect, load/save, apply.

The reference loads llama.cpp imatrix files and threads per-channel
importance weights into native quantization for the ultra-low-bit formats
(`load_imatrix` + per-layer mixed-qtype policy, reference
transformers/utils.py:187-323; `ggml_quantize_tensor_with_weights`,
ggml/model/llama/llama_cpp.py:946-989; `imatrix=` kwarg of
from_pretrained, transformers/model.py:104).

This module provides all three legs, TPU-native:

- `load_imatrix` / `save_imatrix`: the llama.cpp binary imatrix format
  (entries of name / ncall / float32 sums), with llama.cpp tensor names
  ("blk.N.attn_q.weight") translated to HF names so conversion can look
  weights up by the checkpoint tensor name.
- `collect_imatrix`: computes the imatrix directly on OUR model — a
  layer-by-layer replay of the generalized decoder that accumulates the
  mean squared activation entering every linear (the same statistic
  llama.cpp's imatrix tool collects). No hooks: the functional model is
  re-run with its internals exposed.
- `low_bit_policy`: the per-layer mixed-qtype policy for ultra-low-bit
  quantization (the reference bumps sensitive tensors to higher-bit
  formats when quantizing to IQ2/Q2_K).
"""

from __future__ import annotations

import re
import struct
from typing import Any, Dict, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

# qtypes low enough that sensitive tensors get bumped (reference
# transformers/utils.py: IQ2/Q2_K loads rewrite embedding/lm_head/
# attn_v qtypes)
ULTRA_LOW_QTYPES = ("iq2_xxs", "gguf_iq2_xxs", "iq2_xs", "gguf_iq2_xs",
                    "iq1_s", "gguf_iq1_s", "iq1_m", "gguf_iq1_m", "q2_k")


# -- llama.cpp name translation ---------------------------------------------

_LCPP_LAYER = {
    "attn_q": "self_attn.q_proj",
    "attn_k": "self_attn.k_proj",
    "attn_v": "self_attn.v_proj",
    "attn_output": "self_attn.o_proj",
    "ffn_gate": "mlp.gate_proj",
    "ffn_up": "mlp.up_proj",
    "ffn_down": "mlp.down_proj",
}


# MoE: llama.cpp keeps ONE imatrix entry per expert stack (experts share
# the input activations); translated to an expert-index-free HF name that
# mixtral's conversion falls back to for every expert
_LCPP_MOE = {
    "ffn_gate_exps": "block_sparse_moe.experts.w1",
    "ffn_up_exps": "block_sparse_moe.experts.w3",
    "ffn_down_exps": "block_sparse_moe.experts.w2",
    "ffn_gate_inp": "block_sparse_moe.gate",
}

# old-style per-expert entries ("blk.N.ffn_down.E.weight", parsed by the
# reference transformers/utils.py:207-217) map to the per-expert HF name
_LCPP_MOE_PER_EXPERT = {
    "ffn_gate": "w1",
    "ffn_down": "w2",
    "ffn_up": "w3",
}


def lcpp_to_hf_name(name: str) -> Optional[str]:
    """"blk.3.attn_q.weight" -> "model.layers.3.self_attn.q_proj.weight"."""
    if name == "token_embd.weight":
        return "model.embed_tokens.weight"
    if name == "output.weight":
        return "lm_head.weight"
    m = re.match(r"blk\.(\d+)\.(\w+)\.weight$", name)
    if m and m.group(2) in _LCPP_LAYER:
        return f"model.layers.{m.group(1)}.{_LCPP_LAYER[m.group(2)]}.weight"
    if m and m.group(2) in _LCPP_MOE:
        return f"model.layers.{m.group(1)}.{_LCPP_MOE[m.group(2)]}.weight"
    m = re.match(r"blk\.(\d+)\.(\w+)\.(\d+)\.weight$", name)
    if m and m.group(2) in _LCPP_MOE_PER_EXPERT:
        return (f"model.layers.{m.group(1)}.block_sparse_moe.experts."
                f"{m.group(3)}.{_LCPP_MOE_PER_EXPERT[m.group(2)]}.weight")
    return None


def imatrix_lookup(imatrix: Optional[Dict[str, np.ndarray]],
                   name: str) -> Optional[np.ndarray]:
    """Importance vector for an HF tensor name, resolving the synthetic
    forms conversion produces:

    - "...query_key_value.weight#v_proj" (fused-QKV split): falls back to
      the fused tensor's entry — the split shares its input channels.
    - "...experts.4.w1.weight" (per-expert): falls back to the
      expert-index-free "...experts.w1.weight" entry (llama.cpp keeps one
      per stack).
    """
    if imatrix is None:
        return None
    hit = imatrix.get(name)
    if hit is not None:
        return hit
    base = name.split("#", 1)[0]
    if base != name and base in imatrix:
        return imatrix[base]
    m = re.match(r"(.*\.experts)\.\d+\.(w\d\.weight)$", base)
    if m:
        return imatrix.get(f"{m.group(1)}.{m.group(2)}")
    return None


# -- llama.cpp imatrix file format ------------------------------------------


def load_imatrix(path: str) -> Dict[str, np.ndarray]:
    """Parse a llama.cpp imatrix file -> {hf_tensor_name: importance[K]}.

    Stored values are per-channel sums of squared activations over ncall
    evaluations; they are normalized by ncall here. Names that cannot be
    translated keep their llama.cpp spelling (callers match by name)."""
    out: Dict[str, np.ndarray] = {}
    with open(path, "rb") as f:
        (n_entries,) = struct.unpack("<i", f.read(4))
        for _ in range(n_entries):
            (ln,) = struct.unpack("<i", f.read(4))
            name = f.read(ln).decode("utf-8")
            ncall, nval = struct.unpack("<ii", f.read(8))
            vals = np.frombuffer(f.read(4 * nval), dtype="<f4").copy()
            if ncall > 0:
                vals /= ncall
            out[lcpp_to_hf_name(name) or name] = vals
    return out


def save_imatrix(imatrix: Dict[str, np.ndarray], path: str,
                 ncall: int = 1) -> None:
    """Write {name: importance[K]} in the llama.cpp imatrix layout (names
    are stored as given; HF names round-trip through load_imatrix)."""
    with open(path, "wb") as f:
        f.write(struct.pack("<i", len(imatrix)))
        for name, vals in imatrix.items():
            raw = name.encode("utf-8")
            v = np.asarray(vals, np.float32) * max(ncall, 1)
            f.write(struct.pack("<i", len(raw)))
            f.write(raw)
            f.write(struct.pack("<ii", ncall, v.size))
            f.write(v.astype("<f4").tobytes())


# -- collection on our model -------------------------------------------------


_KEY_TO_HF = {
    "q_proj": "model.layers.{i}.self_attn.q_proj.weight",
    "k_proj": "model.layers.{i}.self_attn.k_proj.weight",
    "v_proj": "model.layers.{i}.self_attn.v_proj.weight",
    "o_proj": "model.layers.{i}.self_attn.o_proj.weight",
    "gate_proj": "model.layers.{i}.mlp.gate_proj.weight",
    "up_proj": "model.layers.{i}.mlp.up_proj.weight",
    "down_proj": "model.layers.{i}.mlp.down_proj.weight",
}


def collect_imatrix(params: Dict[str, Any], cfg, tokens,
                    compute_dtype=jnp.bfloat16) -> Dict[str, np.ndarray]:
    """Run calibration tokens through the generalized decoder, recording
    E[x^2] per input channel of every linear. Returns HF-named vectors
    usable as `quantize_linear(..., qw=...)` / `from_pretrained(imatrix=)`.

    Works for any family served by models/llama.py: layers are replayed
    one at a time through the REAL `_decoder_layer` with its `record`
    hook, so the statistics follow every family knob (sandwich norms,
    parallel residual, alternating sliding windows, ...) by construction.
    """
    from bigdl_tpu.models import llama as M
    from bigdl_tpu.ops.rope import rope_cos_sin

    # stats are keyed by the SPLIT projection names (they feed
    # quantize_linear at conversion time, which sees HF tensors);
    # models loaded with the default merged layout replay unmerged —
    # exact, and the per-projection activations are identical
    params = M.unmerge_projections(params, cfg)
    tokens = jnp.asarray(np.asarray(tokens, np.int32))
    if tokens.ndim == 1:
        tokens = tokens[None]
    b, s = tokens.shape

    positions = jnp.arange(s, dtype=jnp.int32)
    x = M.embed_prologue(params, cfg, tokens, positions, compute_dtype)

    inv_freq, rope_mscale = M.model_rope_freqs(cfg)
    cos, sin = rope_cos_sin(positions[None, :], inv_freq)
    if rope_mscale != 1.0:
        cos, sin = cos * rope_mscale, sin * rope_mscale
    slopes = (jnp.asarray(M.alibi_slopes(cfg.num_attention_heads))
              if cfg.use_alibi else None)

    stats: Dict[str, np.ndarray] = {}

    def accumulate(name: str, act: jax.Array):
        v = np.asarray(jnp.mean(
            jnp.square(act.astype(jnp.float32)), axis=tuple(
                range(act.ndim - 1))))
        stats[name] = stats.get(name, 0.0) + v

    # token_embd importance = token frequency (what llama.cpp records);
    # kept for file parity — our embedding quantizer blocks along D, so
    # conversion only applies qw vectors whose length matches K
    stats["model.embed_tokens.weight"] = np.bincount(
        np.asarray(tokens).ravel(), minlength=cfg.vocab_size
    ).astype(np.float32) / tokens.size

    for i in range(cfg.num_hidden_layers):
        lp = jax.tree.map(lambda a: a[i], params["layers"])

        def rec(key, act, _i=i):
            accumulate(_KEY_TO_HF[key].format(i=_i), act)

        x, _ = M._decoder_layer(x, lp, cfg, cos, sin, slopes,
                                cache_ctx=None,
                                lidx=jnp.asarray(i, jnp.int32), record=rec)

    x = M._norm(x, params["norm"], params.get("norm_bias"), cfg)
    accumulate("lm_head.weight", x)
    return stats


# -- mixed-qtype policy ------------------------------------------------------


def low_bit_policy(base_qtype: str, hf_name: str) -> str:
    """Per-tensor qtype under an ultra-low-bit load.

    Mirrors the reference's (and llama.cpp's) practice of protecting the
    most sensitive tensors when the bulk of the model drops below ~2.5
    bpw (reference transformers/utils.py:187-323): the output head keeps
    8 bits, attention V and FFN down keep 4 bits.
    """
    if base_qtype not in ULTRA_LOW_QTYPES:
        return base_qtype
    if hf_name.endswith(("lm_head.weight", "output.weight", "head.weight")):
        return "sym_int8"
    if (".v_proj." in hf_name or ".down_proj." in hf_name
            or ".w2." in hf_name       # .w2 = mixtral expert down_proj
            # fused-QKV splits carry the logical slot as a "#" suffix
            or hf_name.endswith(("#v_proj", "#down_proj"))):
        return "sym_int4"
    return base_qtype
