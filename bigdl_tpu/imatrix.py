"""Importance-matrix (imatrix) support: collect, load/save, apply.

The reference loads llama.cpp imatrix files and threads per-channel
importance weights into native quantization for the ultra-low-bit formats
(`load_imatrix` + per-layer mixed-qtype policy, reference
transformers/utils.py:187-323; `ggml_quantize_tensor_with_weights`,
ggml/model/llama/llama_cpp.py:946-989; `imatrix=` kwarg of
from_pretrained, transformers/model.py:104).

This module provides all three legs, TPU-native:

- `load_imatrix` / `save_imatrix`: the llama.cpp binary imatrix format
  (entries of name / ncall / float32 sums), with llama.cpp tensor names
  ("blk.N.attn_q.weight") translated to HF names so conversion can look
  weights up by the checkpoint tensor name.
- `collect_imatrix`: computes the imatrix directly on OUR model — a
  layer-by-layer replay of the generalized decoder that accumulates the
  mean squared activation entering every linear (the same statistic
  llama.cpp's imatrix tool collects). No hooks: the functional model is
  re-run with its internals exposed.
- `low_bit_policy`: the per-layer mixed-qtype policy for ultra-low-bit
  quantization (the reference bumps sensitive tensors to higher-bit
  formats when quantizing to IQ2/Q2_K).
"""

from __future__ import annotations

import re
import struct
from typing import Any, Dict, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

# qtypes low enough that sensitive tensors get bumped (reference
# transformers/utils.py: IQ2/Q2_K loads rewrite embedding/lm_head/
# attn_v qtypes)
ULTRA_LOW_QTYPES = ("iq2_xxs", "gguf_iq2_xxs", "iq1_s", "gguf_iq1_s",
                    "q2_k")


# -- llama.cpp name translation ---------------------------------------------

_LCPP_LAYER = {
    "attn_q": "self_attn.q_proj",
    "attn_k": "self_attn.k_proj",
    "attn_v": "self_attn.v_proj",
    "attn_output": "self_attn.o_proj",
    "ffn_gate": "mlp.gate_proj",
    "ffn_up": "mlp.up_proj",
    "ffn_down": "mlp.down_proj",
}


def lcpp_to_hf_name(name: str) -> Optional[str]:
    """"blk.3.attn_q.weight" -> "model.layers.3.self_attn.q_proj.weight"."""
    if name == "token_embd.weight":
        return "model.embed_tokens.weight"
    if name == "output.weight":
        return "lm_head.weight"
    m = re.match(r"blk\.(\d+)\.(\w+)\.weight$", name)
    if m and m.group(2) in _LCPP_LAYER:
        return f"model.layers.{m.group(1)}.{_LCPP_LAYER[m.group(2)]}.weight"
    return None


# -- llama.cpp imatrix file format ------------------------------------------


def load_imatrix(path: str) -> Dict[str, np.ndarray]:
    """Parse a llama.cpp imatrix file -> {hf_tensor_name: importance[K]}.

    Stored values are per-channel sums of squared activations over ncall
    evaluations; they are normalized by ncall here. Names that cannot be
    translated keep their llama.cpp spelling (callers match by name)."""
    out: Dict[str, np.ndarray] = {}
    with open(path, "rb") as f:
        (n_entries,) = struct.unpack("<i", f.read(4))
        for _ in range(n_entries):
            (ln,) = struct.unpack("<i", f.read(4))
            name = f.read(ln).decode("utf-8")
            ncall, nval = struct.unpack("<ii", f.read(8))
            vals = np.frombuffer(f.read(4 * nval), dtype="<f4").copy()
            if ncall > 0:
                vals /= ncall
            out[lcpp_to_hf_name(name) or name] = vals
    return out


def save_imatrix(imatrix: Dict[str, np.ndarray], path: str,
                 ncall: int = 1) -> None:
    """Write {name: importance[K]} in the llama.cpp imatrix layout (names
    are stored as given; HF names round-trip through load_imatrix)."""
    with open(path, "wb") as f:
        f.write(struct.pack("<i", len(imatrix)))
        for name, vals in imatrix.items():
            raw = name.encode("utf-8")
            v = np.asarray(vals, np.float32) * max(ncall, 1)
            f.write(struct.pack("<i", len(raw)))
            f.write(raw)
            f.write(struct.pack("<ii", ncall, v.size))
            f.write(v.astype("<f4").tobytes())


# -- collection on our model -------------------------------------------------


def collect_imatrix(params: Dict[str, Any], cfg, tokens,
                    compute_dtype=jnp.bfloat16) -> Dict[str, np.ndarray]:
    """Run calibration tokens through the generalized decoder, recording
    E[x^2] per input channel of every linear. Returns HF-named vectors
    usable as `quantize_linear(..., qw=...)` / `from_pretrained(imatrix=)`.

    Works for any family served by models/llama.py (the scan decoder);
    layer params are unstacked and replayed one layer at a time so the
    intermediate activations are observable.
    """
    from bigdl_tpu.models import llama as M

    tokens = jnp.asarray(np.asarray(tokens, np.int32))
    if tokens.ndim == 1:
        tokens = tokens[None]
    b, s = tokens.shape

    from bigdl_tpu.ops.embedding import embedding_lookup

    x = embedding_lookup(params["embed_tokens"], tokens, compute_dtype)
    if cfg.embed_scale != 1.0:
        x = x * jnp.asarray(cfg.embed_scale, compute_dtype)
    if cfg.embed_norm:
        x = M._norm(x, params["embed_norm"], params.get("embed_norm_bias"),
                    cfg)

    inv_freq, rope_mscale = M.model_rope_freqs(cfg)
    positions = jnp.arange(s, dtype=jnp.int32)
    from bigdl_tpu.ops.rope import rope_cos_sin

    cos, sin = rope_cos_sin(positions[None, :], inv_freq)
    if rope_mscale != 1.0:
        cos, sin = cos * rope_mscale, sin * rope_mscale
    slopes = (jnp.asarray(M.alibi_slopes(cfg.num_attention_heads))
              if cfg.use_alibi else None)

    stats: Dict[str, np.ndarray] = {}

    def record(name: str, act: jax.Array):
        v = np.asarray(jnp.mean(
            jnp.square(act.astype(jnp.float32)), axis=tuple(
                range(act.ndim - 1))))
        stats[name] = stats.get(name, 0.0) + v

    # token_embd importance = token frequency (what llama.cpp records);
    # kept for file parity — our embedding quantizer blocks along D, so
    # conversion only applies qw vectors whose length matches K
    stats["model.embed_tokens.weight"] = np.bincount(
        np.asarray(tokens).ravel(), minlength=cfg.vocab_size
    ).astype(np.float32) / tokens.size

    L = cfg.num_hidden_layers
    from bigdl_tpu.ops.attention import sdp_attention
    from bigdl_tpu.ops.matmul import linear
    from bigdl_tpu.ops.rope import apply_rope

    h, hkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.hd
    for i in range(L):
        lp = jax.tree.map(lambda a: a[i], params["layers"])
        pre = f"model.layers.{i}."
        hidden = M._norm(x, lp["input_layernorm"],
                         lp.get("input_layernorm_bias"), cfg)
        record(pre + "self_attn.q_proj.weight", hidden)
        record(pre + "self_attn.k_proj.weight", hidden)
        record(pre + "self_attn.v_proj.weight", hidden)
        q = linear(hidden, lp["q_proj"], lp.get("q_proj_bias")).reshape(
            b, s, h, hd)
        k = linear(hidden, lp["k_proj"], lp.get("k_proj_bias")).reshape(
            b, s, hkv, hd)
        v = linear(hidden, lp["v_proj"], lp.get("v_proj_bias")).reshape(
            b, s, hkv, hd)
        if cfg.use_rope:
            q = apply_rope(q, cos, sin, interleaved=cfg.rope_interleaved)
            k = apply_rope(k, cos, sin, interleaved=cfg.rope_interleaved)
        scale = (cfg.query_pre_attn_scalar ** -0.5
                 if cfg.query_pre_attn_scalar is not None else None)
        attn = sdp_attention(q, k, v, jnp.zeros((), jnp.int32), scale=scale,
                             sliding_window=cfg.sliding_window,
                             logits_soft_cap=cfg.attn_soft_cap,
                             alibi_slopes=slopes).reshape(b, s, h * hd)
        record(pre + "self_attn.o_proj.weight", attn)
        attn_out = linear(attn, lp["o_proj"], lp.get("o_proj_bias"))

        if cfg.parallel_residual:
            mlp_in = hidden if cfg.shared_input_norm else M._norm(
                x, lp["post_attention_layernorm"],
                lp.get("post_attention_layernorm_bias"), cfg)
            record(pre + "mlp.gate_proj.weight", mlp_in)
            record(pre + "mlp.up_proj.weight", mlp_in)
            inner = _mlp_inner(mlp_in, lp, cfg)
            record(pre + "mlp.down_proj.weight", inner)
            x = x + attn_out + linear(inner, lp["down_proj"],
                                      lp.get("down_proj_bias"))
        else:
            x = x + attn_out
            mlp_in = M._norm(x, lp["post_attention_layernorm"],
                             lp.get("post_attention_layernorm_bias"), cfg)
            record(pre + "mlp.gate_proj.weight", mlp_in)
            record(pre + "mlp.up_proj.weight", mlp_in)
            inner = _mlp_inner(mlp_in, lp, cfg)
            record(pre + "mlp.down_proj.weight", inner)
            x = x + linear(inner, lp["down_proj"], lp.get("down_proj_bias"))

    x = M._norm(x, params["norm"], params.get("norm_bias"), cfg)
    record("lm_head.weight", x)
    return stats


def _mlp_inner(hidden, lp, cfg):
    """The activation entering down_proj (gate/up already applied)."""
    from bigdl_tpu.models.llama import _ACTS
    from bigdl_tpu.ops.matmul import linear

    act = _ACTS[cfg.hidden_act]
    if cfg.mlp_gated:
        gate = linear(hidden, lp["gate_proj"], lp.get("gate_proj_bias"))
        up = linear(hidden, lp["up_proj"], lp.get("up_proj_bias"))
        return act(gate) * up
    return act(linear(hidden, lp["up_proj"], lp.get("up_proj_bias")))


# -- mixed-qtype policy ------------------------------------------------------


def low_bit_policy(base_qtype: str, hf_name: str) -> str:
    """Per-tensor qtype under an ultra-low-bit load.

    Mirrors the reference's (and llama.cpp's) practice of protecting the
    most sensitive tensors when the bulk of the model drops below ~2.5
    bpw (reference transformers/utils.py:187-323): the output head keeps
    8 bits, attention V and FFN down keep 4 bits.
    """
    if base_qtype not in ULTRA_LOW_QTYPES:
        return base_qtype
    if hf_name.endswith(("lm_head.weight", "output.weight", "head.weight")):
        return "sym_int8"
    if (".v_proj." in hf_name or ".down_proj." in hf_name
            or ".w2." in hf_name):     # .w2 = mixtral expert down_proj
        return "sym_int4"
    return base_qtype
