"""graftlint: AST static analysis for JAX hazards and lock discipline.

Three rule families guard the serving stack's riskiest Python-side bug
classes before they cost a bench run:

* **JAX hazards** (:mod:`.jax_rules`) — host-device syncs inside
  jit-traced code and on the engine step path, raw ``jax.jit`` outside
  the tracked wrapper, trace-time nondeterminism, missing buffer
  donation, recompile-prone static scalars.
* **Lock discipline** (:mod:`.locks`) — infers which attributes are
  guarded by which ``threading.Lock`` from ``with self._lock:``
  bodies, then flags unguarded access to guarded state and inverted
  nested lock orders across the engine/router/overload threads.
* **Ratcheted baseline** (:mod:`.core`) — accepted findings live in
  ``tools/graftlint_baseline.json``; the gate fails on anything new,
  and the baseline may only shrink.

Run it as ``python -m bigdl_tpu.analysis`` (or the ``graftlint``
console script / ``tools/graftlint.py``); the tier-1 test
``tests/test_graftlint.py`` runs the same entry points in-process.
The analyzer itself is pure stdlib (ast + json + pathlib) and never
executes or imports the code it inspects.
"""

from bigdl_tpu.analysis.core import (  # noqa: F401
    RULES,
    AnalysisResult,
    Finding,
    analyze,
    baseline_fingerprints,
    iter_package_files,
    load_baseline,
    new_findings,
    ratchet_violations,
    render_baseline,
)
from bigdl_tpu.analysis.jax_rules import (  # noqa: F401
    RAW_JIT_ALLOWLIST,
    RAW_JIT_MESSAGE,
)

__all__ = [
    "RULES",
    "AnalysisResult",
    "Finding",
    "analyze",
    "baseline_fingerprints",
    "iter_package_files",
    "load_baseline",
    "new_findings",
    "ratchet_violations",
    "render_baseline",
    "RAW_JIT_ALLOWLIST",
    "RAW_JIT_MESSAGE",
]
