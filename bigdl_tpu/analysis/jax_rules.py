"""JAX hazard rules: tracing, host syncs, recompiles, donation.

What counts as "jit-traced code"
--------------------------------
A function body is traced when it is

* decorated with ``@tracked_jit(...)``, ``@functools.partial(
  tracked_jit, ...)``, ``@jax.jit`` or ``@functools.partial(jax.jit,
  ...)``, or
* passed (as a ``def`` name or inline ``lambda``) to a
  ``tracked_jit(...)`` / ``jax.jit(...)`` call in the same module.

Nested ``def``s inside a traced body are traced too (``jax.vmap`` row
functions and the like). Functions referenced by attribute
(``tracked_jit("x", family.forward)``) have no visible body here and
are skipped — the rule set is deliberately intra-module.

Rules
-----
``jax-raw-jit``
    Any ``jax.jit(`` call outside the allowlist (the tracked wrapper
    itself plus the AOT compile-cost probe). Subsumes the old
    ``tests/test_no_raw_jit.py`` regex scanner.
``jax-host-sync-in-jit``
    ``.item()`` / ``.tolist()`` / ``.block_until_ready()`` /
    ``jax.device_get`` / ``np.*(...)`` / ``float()``/``int()`` on a
    TRACED expression inside a traced body: each forces the value onto
    the host (ConcretizationError at best, a silent per-call D2H sync
    at worst). Taint starts at the non-static parameters —
    ``static_argnums``/``static_argnames`` values are plain Python at
    trace time, so config math like ``float(1 << (qt.bits - 1))``
    stays silent.
``jax-nondet-in-jit``
    ``time.time()``-family or ``random``/``np.random`` calls inside a
    traced body: evaluated ONCE at trace time and baked into the
    compiled executable (``jax.random`` is fine — that is the traced
    RNG).
``jax-missing-donate``
    A traced function whose FIRST parameter is a KV cache
    (``cache``/``cache1``/``kv``/``kv_cache``/``kvcache``) — or a
    params/state pytree on a train/update step — without
    ``donate_argnums`` covering position 0. The un-donated buffer
    doubles peak HBM for the call.
``jax-scalar-signature``
    A call to a known jit-wrapped callable passing ``len(...)`` or an
    arithmetic expression into a ``static_argnums``/``static_argnames``
    position: every distinct value compiles a fresh executable (bucket
    or trace the scalar instead).
``step-host-sync``
    On the engine step path (methods reachable from
    ``LLMEngine.step``): a D2H pull (``np.asarray``/``np.array``/
    ``np.ascontiguousarray``/``jax.device_get``) inside a loop or
    comprehension, an ``.item()``/``.tolist()``/
    ``.block_until_ready()`` anywhere, or ``float()``/``int()`` of a
    subscript whose base is not provably host-resident numpy. The
    sanctioned pattern is ONE ``np.asarray`` per step, then numpy
    indexing.
``jax-dispatch-in-decode-loop``
    On the engine step path: a call to a jit-bound callable (a name or
    ``self`` attribute a ``tracked_jit``/``jax.jit`` result was
    assigned to) inside a ``for``/``while`` loop or comprehension.
    Each call is a full host->device launch — per-token dispatch
    overhead the resident decode step exists to remove. Batch the rows
    into one call, or fold the loop into the jit (``lax.scan``).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from bigdl_tpu.analysis.core import Finding, Module

#: files (path suffixes) allowed to call raw jax.jit — the wrapper
#: itself and the compile-cost probe (its throwaway fn must NOT land in
#: the compile table)
RAW_JIT_ALLOWLIST = (
    "bigdl_tpu/observability/compile_watch.py",
    "bigdl_tpu/ops/probing.py",
)

#: kept byte-compatible with the retired tests/test_no_raw_jit.py
RAW_JIT_MESSAGE = (
    "raw jax.jit( call — use "
    "bigdl_tpu.observability.compile_watch.tracked_jit instead so the "
    "compile lands in the compile table")

#: engine-step-path roots: path suffix -> (class, entry method)
DEFAULT_STEP_ENTRIES = {
    "bigdl_tpu/serving/engine.py": ("LLMEngine", "step"),
}

_CACHE_PARAMS = {"cache", "cache1", "kv", "kv_cache", "kvcache"}
_STATE_PARAMS = {"params", "state", "train", "opt_state"}
_TRAIN_HINTS = ("train", "update", "optimiz")
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_PULL_FUNCS = {"asarray", "array", "ascontiguousarray"}
_TIME_FUNCS = {"time", "monotonic", "perf_counter", "time_ns",
               "process_time"}


def _dotted(node: ast.AST) -> Optional[str]:
    """'jax.random.split' for nested Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _int_tuple(node: Optional[ast.AST]) -> Optional[Tuple[int, ...]]:
    """Literal donate/static_argnums value: int or tuple/list of ints."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    return None


def _str_tuple(node: Optional[ast.AST]) -> Tuple[str, ...]:
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    return ()


@dataclasses.dataclass
class JitSite:
    """One traced function the module can see the body of (or just the
    jit kwargs, when the body is an attribute reference)."""

    name: str                       # jit display name or fn name
    fn: Optional[ast.AST]           # FunctionDef or Lambda, if visible
    lineno: int
    donate: Tuple[int, ...] = ()
    static_nums: Tuple[int, ...] = ()
    static_names: Tuple[str, ...] = ()
    binding: Optional[Tuple[str, str]] = None   # ("self", "_decode") etc.


def _jit_kwargs(call: ast.Call) -> dict:
    kw = {k.arg: k.value for k in call.keywords if k.arg}
    return {
        "donate": _int_tuple(kw.get("donate_argnums")) or (),
        "static_nums": _int_tuple(kw.get("static_argnums")) or (),
        "static_names": _str_tuple(kw.get("static_argnames")),
    }


def _is_tracked_jit(node: ast.AST) -> bool:
    d = _dotted(node)
    return d is not None and d.split(".")[-1] == "tracked_jit"


def _is_jax_jit(node: ast.AST) -> bool:
    return _dotted(node) == "jax.jit"


class _ModuleScan(ast.NodeVisitor):
    """Collect jit sites, local def nodes, and assignments binding jit
    results to names/attributes."""

    def __init__(self):
        self.defs: Dict[str, ast.AST] = {}      # fn name -> def node
        self.sites: List[JitSite] = []
        self.raw_jit_calls: List[ast.Call] = []
        # names a jit result was bound to: ("self", attr) or ("", name)
        self.bindings: Dict[Tuple[str, str], JitSite] = {}
        self._pending_alias: Dict[str, JitSite] = {}

    # -- defs ---------------------------------------------------------------

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.defs.setdefault(node.name, node)
        site = self._site_from_decorators(node)
        if site is not None:
            self.sites.append(site)
            self._pending_alias[node.name] = site
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def _site_from_decorators(self, node) -> Optional[JitSite]:
        for dec in node.decorator_list:
            # @tracked_jit("name", ...) / @jax.jit / @tracked_jit
            if _is_tracked_jit(dec) or _is_jax_jit(dec):
                return JitSite(node.name, node, node.lineno)
            if isinstance(dec, ast.Call):
                f = dec.func
                # @functools.partial(tracked_jit|jax.jit, "name", ...)
                if (_dotted(f) or "").split(".")[-1] == "partial" \
                        and dec.args \
                        and (_is_tracked_jit(dec.args[0])
                             or _is_jax_jit(dec.args[0])):
                    return JitSite(self._display_name(dec, node.name),
                                   node, node.lineno,
                                   **_jit_kwargs(dec))
                # @tracked_jit("name", donate_argnums=...) factory form
                if _is_tracked_jit(f) or _is_jax_jit(f):
                    return JitSite(self._display_name(dec, node.name),
                                   node, node.lineno,
                                   **_jit_kwargs(dec))
        return None

    @staticmethod
    def _display_name(call: ast.Call, fallback: str) -> str:
        for a in call.args:
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                return a.value
        return fallback

    # -- calls --------------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        if _is_jax_jit(node.func):
            self.raw_jit_calls.append(node)
        if _is_tracked_jit(node.func) or _is_jax_jit(node.func):
            site = self._site_from_call(node)
            if site is not None:
                self.sites.append(site)
                node._graftlint_site = site     # for binding detection
        self.generic_visit(node)

    def _site_from_call(self, node: ast.Call) -> Optional[JitSite]:
        # tracked_jit("name", fn, ...) — fn may be args[0] (jax.jit) or
        # args[1] (tracked_jit with a leading display name)
        fn_node = None
        name = "<jit>"
        for a in node.args[:2]:
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                name = a.value
            elif isinstance(a, ast.Lambda):
                fn_node = a
            elif isinstance(a, ast.Name):
                fn_node = self.defs.get(a.id)
                name = a.id if name == "<jit>" else name
        return JitSite(name, fn_node, node.lineno, **_jit_kwargs(node))

    # -- bindings -----------------------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        site = getattr(node.value, "_graftlint_site", None)
        if site is None and isinstance(node.value, ast.Name):
            site = self._pending_alias.get(node.value.id)
        if site is None and isinstance(node.value, ast.Call):
            # assigned AFTER visit_Call ran (generic_visit order): probe
            if _is_tracked_jit(node.value.func) \
                    or _is_jax_jit(node.value.func):
                site = self._site_from_call(node.value)
                if site is not None and site not in self.sites:
                    self.sites.append(site)
        if site is not None:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    site.binding = ("", t.id)
                    self.bindings[("", t.id)] = site
                elif isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name):
                    site.binding = (t.value.id, t.attr)
                    self.bindings[(t.value.id, t.attr)] = site
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# traced-body checks


def _param_taint(fn: ast.AST, static_nums: Tuple[int, ...],
                 static_names: Tuple[str, ...]) -> Set[str]:
    """Parameters that carry TRACED values: everything except the
    static_argnums/static_argnames positions (those are plain Python
    at trace time — ``qt = get_qtype(qtype)`` off a static name is
    host config, not a tracer)."""
    a = fn.args
    statics = set(static_names)
    pos = [p.arg for p in getattr(a, "posonlyargs", [])] \
        + [p.arg for p in a.args]
    tainted: Set[str] = set()
    for i, name in enumerate(pos):
        if i not in static_nums and name not in statics:
            tainted.add(name)
    for p in a.kwonlyargs:
        if p.arg not in statics:
            tainted.add(p.arg)
    if a.vararg:
        tainted.add(a.vararg.arg)
    if a.kwarg:
        tainted.add(a.kwarg.arg)
    tainted.discard("self")
    return tainted


class _TracedBody(ast.NodeVisitor):
    """Flag host syncs and nondeterminism inside one traced body.

    Host-sync checks are taint-gated: only expressions that (may)
    derive from a traced parameter fire. ``float(1 << (qt.bits - 1))``
    off a static-argname config object is trace-time Python and stays
    silent; ``float(x[0])`` off a traced ``x`` fires. Subscripts take
    the taint of their BASE only — indexing a module-level host table
    with a trace-time key (``CODEBOOKS[qt.codebook]``) yields host
    data even when the key's provenance is murky."""

    def __init__(self, module: Module, obj: str, out: List[Finding],
                 tainted: Iterable[str] = ()):
        self.m = module
        self.obj = obj
        self.out = out
        self.tainted: Set[str] = set(tainted)

    def _emit(self, rule: str, node: ast.AST, msg: str) -> None:
        self.out.append(Finding(
            rule=rule, path=self.m.rel, line=node.lineno, obj=self.obj,
            message=msg, snippet=self.m.snippet(node.lineno)))

    # -- taint of an expression --------------------------------------------

    def _traced(self, node: Optional[ast.AST]) -> bool:
        if node is None:
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Subscript):
            return self._traced(node.value)
        if isinstance(node, (ast.Attribute, ast.Starred, ast.Await)):
            return self._traced(node.value)
        if isinstance(node, ast.Call):
            return (any(self._traced(a) for a in node.args)
                    or any(self._traced(k.value)
                           for k in node.keywords)
                    or (isinstance(node.func, ast.Attribute)
                        and self._traced(node.func.value)))
        if isinstance(node, ast.BinOp):
            return self._traced(node.left) or self._traced(node.right)
        if isinstance(node, ast.UnaryOp):
            return self._traced(node.operand)
        if isinstance(node, ast.IfExp):
            return self._traced(node.body) or self._traced(node.orelse)
        if isinstance(node, ast.Compare):
            return self._traced(node.left) or any(
                self._traced(c) for c in node.comparators)
        if isinstance(node, ast.BoolOp):
            return any(self._traced(v) for v in node.values)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self._traced(e) for e in node.elts)
        return False

    def _taint_target(self, target: ast.AST, traced: bool) -> None:
        if isinstance(target, ast.Name):
            (self.tainted.add if traced
             else self.tainted.discard)(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._taint_target(e, traced)
        elif isinstance(target, ast.Starred):
            self._taint_target(target.value, traced)

    # -- propagation --------------------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)        # check RHS with pre-assign taint
        traced = self._traced(node.value)
        for t in node.targets:
            self._taint_target(t, traced)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self.visit(node.value)
            self._taint_target(node.target, self._traced(node.value))

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.visit(node.value)
        if isinstance(node.target, ast.Name) \
                and self._traced(node.value):
            self.tainted.add(node.target.id)

    def visit_For(self, node: ast.For) -> None:
        self.visit(node.iter)
        self._taint_target(node.target, self._traced(node.iter))
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def _visit_comp(self, node) -> None:
        saved = set(self.tainted)
        for gen in node.generators:
            self.visit(gen.iter)
            self._taint_target(gen.target, self._traced(gen.iter))
            for cond in gen.ifs:
                self.visit(cond)
        if isinstance(node, ast.DictComp):
            self.visit(node.key)
            self.visit(node.value)
        else:
            self.visit(node.elt)
        self.tainted = saved            # comprehension scope

    visit_ListComp = visit_SetComp = _visit_comp
    visit_GeneratorExp = visit_DictComp = _visit_comp

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # nested defs (vmap row fns, scan bodies) are traced too: they
        # close over this body's tracers and their own params are traced
        inner = _TracedBody(
            self.m, f"{self.obj}.{node.name}", self.out,
            self.tainted | _param_taint(node, (), ()))
        for stmt in node.body:
            inner.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        inner = _TracedBody(
            self.m, self.obj, self.out,
            self.tainted | _param_taint(node, (), ()))
        inner.visit(node.body)

    # -- checks -------------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        dotted = _dotted(f) or ""
        root = dotted.split(".")[0] if dotted else ""
        # .item() / .tolist() / .block_until_ready()
        if isinstance(f, ast.Attribute) and f.attr in _SYNC_METHODS \
                and self._traced(f.value):
            self._emit("jax-host-sync-in-jit", node,
                       f".{f.attr}() forces the traced value onto the "
                       "host")
        elif dotted == "jax.device_get":
            self._emit("jax-host-sync-in-jit", node,
                       "jax.device_get inside traced code is a D2H "
                       "sync per call")
        elif root in ("np", "numpy"):
            if dotted.split(".")[1:2] == ["random"]:
                self._emit("jax-nondet-in-jit", node,
                           f"{dotted}() draws host entropy at trace "
                           "time; use jax.random with a threaded key")
            elif any(self._traced(a) for a in node.args):
                self._emit("jax-host-sync-in-jit", node,
                           f"{dotted}() concretizes its traced "
                           "argument on the host; use the jnp "
                           "equivalent")
        elif root == "random":
            self._emit("jax-nondet-in-jit", node,
                       f"{dotted}() is host RNG evaluated once at "
                       "trace time; use jax.random")
        elif root == "time" and dotted.split(".")[-1] in _TIME_FUNCS:
            self._emit("jax-nondet-in-jit", node,
                       f"{dotted}() is evaluated once at trace time "
                       "and baked into the executable")
        elif isinstance(f, ast.Name) and f.id in ("float", "int") \
                and node.args and self._traced(node.args[0]):
            self._emit("jax-host-sync-in-jit", node,
                       f"{f.id}() on a traced value raises "
                       "ConcretizationError (or silently syncs)")
        self.generic_visit(node)


def _walk_traced(site: JitSite, module: Module,
                 out: List[Finding]) -> None:
    fn = site.fn
    if fn is None:
        return
    tainted = _param_taint(fn, site.static_nums, site.static_names)
    checker = _TracedBody(module, site.name, out, tainted)
    if isinstance(fn, ast.Lambda):
        checker.visit(fn.body)
    else:
        for stmt in fn.body:
            checker.visit(stmt)


# ---------------------------------------------------------------------------
# donation


def _check_donate(site: JitSite, module: Module,
                  out: List[Finding]) -> None:
    fn = site.fn
    if fn is None or isinstance(fn, ast.Lambda):
        args = fn.args if fn is not None else None
    else:
        args = fn.args
    if args is None or not args.args:
        return
    first = args.args[0].arg
    lineno = site.lineno
    if first in _CACHE_PARAMS:
        if 0 not in site.donate:
            out.append(Finding(
                "jax-missing-donate", module.rel, lineno,
                site.name,
                f"first arg {first!r} is a KV cache: donate it "
                "(donate_argnums=(0,)) or the splice doubles peak HBM",
                module.snippet(lineno)))
    elif first in _STATE_PARAMS and any(
            h in site.name.lower() for h in _TRAIN_HINTS):
        if 0 not in site.donate:
            out.append(Finding(
                "jax-missing-donate", module.rel, lineno,
                site.name,
                f"train-step first arg {first!r} is rebuilt every "
                "call: donate it to halve peak optimizer memory",
                module.snippet(lineno)))


# ---------------------------------------------------------------------------
# scalar signature drift


class _JitCallScan(ast.NodeVisitor):
    def __init__(self, module: Module,
                 bindings: Dict[Tuple[str, str], JitSite],
                 out: List[Finding]):
        self.m = module
        self.bindings = bindings
        self.out = out

    @staticmethod
    def _drifting(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Name) \
                and node.func.id == "len":
            return "len(...)"
        if isinstance(node, ast.BinOp):
            return "an arithmetic expression"
        return None

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        site = None
        if isinstance(f, ast.Name):
            site = self.bindings.get(("", f.id))
        elif isinstance(f, ast.Attribute) \
                and isinstance(f.value, ast.Name):
            site = self.bindings.get((f.value.id, f.attr))
        if site is not None and (site.static_nums or site.static_names):
            for i, a in enumerate(node.args):
                what = self._drifting(a)
                if what and i in site.static_nums:
                    self.out.append(Finding(
                        "jax-scalar-signature", self.m.rel, node.lineno,
                        site.name,
                        f"{what} in static position {i} of jit "
                        f"{site.name!r}: one compile per distinct "
                        "value — round to a bucket or pass a traced "
                        "array", self.m.snippet(node.lineno)))
            for kw in node.keywords:
                what = self._drifting(kw.value) if kw.arg else None
                if what and kw.arg in site.static_names:
                    self.out.append(Finding(
                        "jax-scalar-signature", self.m.rel, node.lineno,
                        site.name,
                        f"{what} in static kwarg {kw.arg!r} of jit "
                        f"{site.name!r}: one compile per distinct "
                        "value", self.m.snippet(node.lineno)))
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# unsynced timing

#: module-level sync fences: any of these forces the device to finish
#: (or pulls the result to host) before the timer is read again
_FENCE_DOTS = {"jax.block_until_ready", "jax.device_get",
               "jax.effects_barrier"}


class _UnsyncedTiming(ast.NodeVisitor):
    """time.* delta bracketing a jit dispatch with no sync fence.

    JAX dispatch is asynchronous: ``fn(x)`` returns as soon as the work
    is enqueued, so ``time.perf_counter() - t0`` around an unfenced jit
    call measures trace+enqueue overhead, not device compute.  The scan
    is a per-function, statement-ordered state machine: assigning a
    ``time.<fn>()`` result arms a timer, a call resolving through the
    module's jit bindings marks every armed timer dispatch-pending, a
    sync fence (block_until_ready / device_get / np.asarray / .item())
    clears the pending bit, and an ``a - b`` read of a still-pending
    timer is a finding.  Branches are scanned sequentially (lenient: a
    fence on either arm clears the state).
    """

    def __init__(self, module: Module,
                 bindings: Dict[Tuple[str, str], JitSite],
                 out: List[Finding]):
        self.m = module
        self.bindings = bindings
        self.out = out
        self._ctx: List[str] = []

    # -- scope bookkeeping ---------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._ctx.append(node.name)
        self.generic_visit(node)
        self._ctx.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._ctx.append(node.name)
        timers: Dict[str, bool] = {}    # timer var -> dispatch pending
        for stmt in node.body:
            self._scan_stmt(stmt, timers)
        self.generic_visit(node)        # nested defs get fresh state
        self._ctx.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    # -- statement walk ------------------------------------------------

    def _scan_stmt(self, stmt: ast.stmt,
                   timers: Dict[str, bool]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return                      # separate scope, own timers
        if isinstance(stmt, ast.If):
            self._scan_expr(stmt.test, timers)
            for s in stmt.body:
                self._scan_stmt(s, timers)
            for s in stmt.orelse:
                self._scan_stmt(s, timers)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._scan_expr(stmt.iter, timers)
            for s in stmt.body:
                self._scan_stmt(s, timers)
            for s in stmt.orelse:
                self._scan_stmt(s, timers)
            return
        if isinstance(stmt, ast.While):
            self._scan_expr(stmt.test, timers)
            for s in stmt.body:
                self._scan_stmt(s, timers)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._scan_expr(item.context_expr, timers)
            for s in stmt.body:
                self._scan_stmt(s, timers)
            return
        if isinstance(stmt, ast.Try):
            for s in stmt.body:
                self._scan_stmt(s, timers)
            for h in stmt.handlers:
                for s in h.body:
                    self._scan_stmt(s, timers)
            for s in stmt.orelse:
                self._scan_stmt(s, timers)
            for s in stmt.finalbody:
                self._scan_stmt(s, timers)
            return
        self._scan_expr(stmt, timers)

    def _scan_expr(self, node: ast.AST,
                   timers: Dict[str, bool]) -> None:
        calls = [n for n in ast.walk(node) if isinstance(n, ast.Call)]
        # dispatch BEFORE fence: np.asarray(self._decode(...)) both
        # dispatches and syncs in one statement — the fence wins
        if any(self._is_dispatch(c) for c in calls):
            for k in timers:
                timers[k] = True
        if any(self._is_fence(c) for c in calls):
            for k in timers:
                timers[k] = False
        for n in ast.walk(node):
            if not (isinstance(n, ast.BinOp)
                    and isinstance(n.op, ast.Sub)):
                continue
            for side in (n.left, n.right):
                if isinstance(side, ast.Name) and timers.get(side.id):
                    self.out.append(Finding(
                        "jax-unsynced-timing", self.m.rel, n.lineno,
                        ".".join(self._ctx) or "<module>",
                        f"timing delta reads {side.id!r} across a jit "
                        "dispatch with no block_until_ready fence: "
                        "the call returns when work is ENQUEUED, so "
                        "this measures dispatch overhead, not device "
                        "compute — block_until_ready the result "
                        "before reading the clock",
                        self.m.snippet(n.lineno)))
                    timers.pop(side.id, None)   # one finding per timer
                    break
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call) \
                and self._is_time_call(node.value):
            timers[node.targets[0].id] = False

    # -- classifiers ---------------------------------------------------

    def _is_time_call(self, call: ast.Call) -> bool:
        d = _dotted(call.func)
        return bool(d) and "." in d and d.split(".")[0] == "time" \
            and d.split(".")[-1] in _TIME_FUNCS

    def _is_dispatch(self, call: ast.Call) -> bool:
        f = call.func
        if isinstance(f, ast.Name):
            return ("", f.id) in self.bindings
        if isinstance(f, ast.Attribute) \
                and isinstance(f.value, ast.Name):
            return (f.value.id, f.attr) in self.bindings
        return False

    @staticmethod
    def _is_fence(call: ast.Call) -> bool:
        f = call.func
        d = _dotted(f)
        if d in _FENCE_DOTS:
            return True
        if isinstance(f, ast.Attribute):
            if f.attr in _SYNC_METHODS:
                return True
            if d is not None:
                parts = d.split(".")
                if parts[0] in ("np", "numpy") \
                        and parts[-1] in _PULL_FUNCS:
                    return True
        return False


# ---------------------------------------------------------------------------
# engine step path


def _class_methods(tree: ast.AST, cls_name: str
                   ) -> Dict[str, ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls_name:
            return {n.name: n for n in node.body
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))}
    return {}


def _reachable(methods: Dict[str, ast.FunctionDef],
               entry: str) -> Set[str]:
    seen, todo = set(), [entry]
    while todo:
        name = todo.pop()
        if name in seen or name not in methods:
            continue
        seen.add(name)
        for node in ast.walk(methods[name]):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "self" \
                    and node.func.attr in methods:
                todo.append(node.func.attr)
    return seen


class _HostProven:
    """Order-of-appearance dataflow: which local names are provably
    host-resident numpy (result of an np.* call, or arithmetic over
    such names). Arithmetic with one proven-host operand stays host as
    long as no non-numpy call appears in the expression: numpy ops
    cannot move an array to the device on their own, and jit results
    enter the step path as whole-statement assignments (which reset
    provenance), not as bare sub-expressions."""

    _HOST_ROOTS = ("np", "numpy")

    def __init__(self):
        self.host: Set[str] = set()

    def _no_foreign_calls(self, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                d = _dotted(sub.func) or ""
                root = d.split(".")[0] if d else ""
                if root not in self._HOST_ROOTS \
                        and root not in ("float", "int", "len",
                                        "abs", "min", "max") \
                        and d != "jax.device_get":
                    return False
        return True

    def expr_is_host(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Constant):
            return True
        if isinstance(node, ast.Name):
            return node.id in self.host or \
                node.id.endswith(_HOST_MIRROR_SUFFIXES)
        if isinstance(node, ast.Attribute):
            # naming convention shared with paged-host-gather: a
            # _np/_host suffix declares a host numpy mirror
            return node.attr.endswith(_HOST_MIRROR_SUFFIXES)
        if isinstance(node, ast.Call):
            d = _dotted(node.func) or ""
            root = d.split(".")[0]
            if root in self._HOST_ROOTS or d == "jax.device_get":
                return True         # np.* RESULTS live on host
            return False
        if isinstance(node, ast.Subscript):
            return self.expr_is_host(node.value)
        if isinstance(node, ast.BinOp):
            return ((self.expr_is_host(node.left)
                     or self.expr_is_host(node.right))
                    and self._no_foreign_calls(node))
        if isinstance(node, ast.UnaryOp):
            return self.expr_is_host(node.operand)
        if isinstance(node, ast.IfExp):
            def ok(n):
                return (isinstance(n, ast.Constant)
                        or self.expr_is_host(n))
            return ok(node.body) and ok(node.orelse)
        return False

    def note_assign(self, node: ast.Assign) -> None:
        if len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            if isinstance(node.value, ast.Constant) \
                    and node.value.value is None:
                return              # neutral: None placeholder
            if self.expr_is_host(node.value):
                self.host.add(name)
            else:
                self.host.discard(name)


class _StepPath(ast.NodeVisitor):
    """Flag looped D2H pulls and unproven float()/int() subscripts in
    one step-path method."""

    def __init__(self, module: Module, obj: str, out: List[Finding],
                 proven: _HostProven, loop_depth: int = 0):
        self.m = module
        self.obj = obj
        self.out = out
        self.proven = proven
        self.loop = loop_depth

    def _emit(self, node: ast.AST, msg: str) -> None:
        self.out.append(Finding(
            "step-host-sync", self.m.rel, node.lineno, self.obj,
            msg, self.m.snippet(node.lineno)))

    def _enter_loop(self, node: ast.AST) -> None:
        self.loop += 1
        self.generic_visit(node)
        self.loop -= 1

    visit_For = visit_While = _enter_loop
    visit_ListComp = visit_SetComp = _enter_loop
    visit_DictComp = visit_GeneratorExp = _enter_loop

    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)
        self.proven.note_assign(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # closures inherit the provenance known at their def site (they
        # are called inline in the step loop)
        inner = _StepPath(self.m, f"{self.obj}.{node.name}", self.out,
                          self.proven, self.loop)
        for stmt in node.body:
            inner.visit(stmt)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        dotted = _dotted(f) or ""
        root = dotted.split(".")[0] if dotted else ""
        if isinstance(f, ast.Attribute) and f.attr in _SYNC_METHODS:
            self._emit(node,
                       f".{f.attr}() is a per-element device sync — "
                       "pull the whole array once with np.asarray")
        elif ((root in ("np", "numpy")
               and dotted.split(".")[-1] in _PULL_FUNCS)
              or dotted == "jax.device_get"):
            if self.loop > 0:
                self._emit(node,
                           f"{dotted}() inside a loop on the step "
                           "path: one D2H pull per iteration — hoist "
                           "a single pull above the loop and index in "
                           "numpy")
        elif isinstance(f, ast.Name) and f.id in ("float", "int") \
                and node.args \
                and isinstance(node.args[0], ast.Subscript) \
                and not self.proven.expr_is_host(node.args[0]):
            self._emit(node,
                       f"{f.id}() of a subscript whose base is not "
                       "provably host numpy: if it is a device array "
                       "this is one D2H sync PER TOKEN — np.asarray "
                       "the row once, then index")
        self.generic_visit(node)


class _DispatchLoop(ast.NodeVisitor):
    """Flag jit dispatches issued per loop iteration in one step-path
    method. A call through a name/attribute a jit result was bound to
    is one host->device launch; in a loop that is per-token dispatch
    overhead. Loops INSIDE a traced body (lax.scan bodies, vmap row
    fns) never reach here — bindings only cover module-level jit
    results, and calling a jit from traced code is inlined anyway."""

    def __init__(self, module: Module, obj: str, out: List[Finding],
                 bindings: Dict[Tuple[str, str], JitSite],
                 loop_depth: int = 0):
        self.m = module
        self.obj = obj
        self.out = out
        self.bindings = bindings
        self.loop = loop_depth

    def _enter_loop(self, node: ast.AST) -> None:
        self.loop += 1
        self.generic_visit(node)
        self.loop -= 1

    visit_For = visit_While = _enter_loop
    visit_ListComp = visit_SetComp = _enter_loop
    visit_DictComp = visit_GeneratorExp = _enter_loop

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # closures inherit the loop depth of their def site (they are
        # called inline in the step loop)
        inner = _DispatchLoop(self.m, f"{self.obj}.{node.name}",
                              self.out, self.bindings, self.loop)
        for stmt in node.body:
            inner.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        if self.loop > 0:
            f = node.func
            site = None
            if isinstance(f, ast.Name):
                site = self.bindings.get(("", f.id))
            elif isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Name):
                site = self.bindings.get((f.value.id, f.attr))
            if site is not None:
                self.out.append(Finding(
                    "jax-dispatch-in-decode-loop", self.m.rel,
                    node.lineno, self.obj,
                    f"jit {site.name!r} dispatched inside a loop on "
                    "the step path: one host->device launch per "
                    "iteration — batch the rows into one call or fold "
                    "the loop into the jit (lax.scan / resident step)",
                    self.m.snippet(node.lineno)))
        self.generic_visit(node)


#: attribute / name fragments that denote paged-KV indexing structures;
#: a host-side subscript of one of these on the step path is a page
#: gather outside the traced step (one per token where the paged decode
#: contract is a single block-table H2D per step, with all per-token
#: page indexing inside the jit — the kernel's scalar prefetch).
_PAGED_TABLE_TOKENS = ("arena", "block_table", "page_table", "page_pool")

#: naming convention for intentional host mirrors (the engine keeps an
#: authoritative numpy block table and refreshes the device copy once
#: per dirty step): these suffixes mark host numpy state, never a
#: device array, so subscripting them is free
_HOST_MIRROR_SUFFIXES = ("_np", "_host")


class _PagedHostGather(ast.NodeVisitor):
    """Flag host-side subscripts of paged-KV tables in one step-path
    method (rule ``paged-host-gather``)."""

    def __init__(self, module: Module, obj: str, out: List[Finding]):
        self.m = module
        self.obj = obj
        self.out = out

    def visit_Subscript(self, node: ast.Subscript) -> None:
        base = node.value
        name = None
        if isinstance(base, ast.Name):
            name = base.id
        elif isinstance(base, ast.Attribute):
            name = base.attr
        if name is not None:
            low = name.lower()
            if not low.endswith(_HOST_MIRROR_SUFFIXES) \
                    and any(t in low for t in _PAGED_TABLE_TOKENS):
                self.out.append(Finding(
                    "paged-host-gather", self.m.rel, node.lineno,
                    self.obj,
                    f"subscript of {name!r} on the step path: paged-KV "
                    "tables must be indexed inside the tracked jit "
                    "(ship the block table H2D once per step); a host "
                    "numpy mirror is fine when named with a _np/_host "
                    "suffix",
                    self.m.snippet(node.lineno)))
        self.generic_visit(node)


def _check_step_path(module: Module, cls: str, entry: str,
                     out: List[Finding],
                     bindings: Optional[Dict[Tuple[str, str],
                                             JitSite]] = None) -> None:
    methods = _class_methods(module.tree, cls)
    if entry not in methods:
        return
    for name in sorted(_reachable(methods, entry)):
        fn = methods[name]
        proven = _HostProven()
        # parameters are unknown; np-typed defaults don't help
        walker = _StepPath(module, f"{cls}.{name}", out, proven)
        for stmt in fn.body:
            walker.visit(stmt)
        if bindings:
            disp = _DispatchLoop(module, f"{cls}.{name}", out, bindings)
            for stmt in fn.body:
                disp.visit(stmt)
        gather = _PagedHostGather(module, f"{cls}.{name}", out)
        for stmt in fn.body:
            gather.visit(stmt)


# ---------------------------------------------------------------------------
# entry


def check(modules: Iterable[Module],
          step_entries: Optional[dict] = None) -> List[Finding]:
    out: List[Finding] = []
    entries = DEFAULT_STEP_ENTRIES if step_entries is None \
        else step_entries
    for m in modules:
        scan = _ModuleScan()
        scan.visit(m.tree)

        allowed = any(m.rel.endswith(sfx) for sfx in RAW_JIT_ALLOWLIST)
        if not allowed:
            for call in scan.raw_jit_calls:
                out.append(Finding(
                    "jax-raw-jit", m.rel, call.lineno, "<module>",
                    RAW_JIT_MESSAGE, m.snippet(call.lineno)))

        seen_fns = set()
        for site in scan.sites:
            if site.fn is not None and id(site.fn) not in seen_fns:
                seen_fns.add(id(site.fn))
                _walk_traced(site, m, out)
                _check_donate(site, m, out)
        _JitCallScan(m, scan.bindings, out).visit(m.tree)
        _UnsyncedTiming(m, scan.bindings, out).visit(m.tree)

        for sfx, (cls, entry) in entries.items():
            if m.rel.endswith(sfx):
                _check_step_path(m, cls, entry, out,
                                 bindings=scan.bindings)
    return out
