"""graftlint core: findings, suppressions, the ratcheted baseline.

The analyzer is pure stdlib (ast + json) on purpose: it inspects
source text only and never executes or imports the code it scans — a
module with a broken import or a TPU-only dependency still lints. Rule logic lives in :mod:`.jax_rules` (tracing /
host-sync hazards), :mod:`.locks` (lock discipline), and
:mod:`.metric_rules` (label cardinality); this module
owns what a finding IS, how an inline suppression works, and how the
baseline may evolve (shrink-only).

Suppressions
------------
A line comment ``# graftlint: disable=<rule>[,<rule>...]`` (or
``disable=all``) suppresses findings anchored to that line. Suppressed
findings are counted and reported but never fail the gate — they are
the audited-exception mechanism.

Baseline ratchet
----------------
``tools/graftlint_baseline.json`` stores the findings the repo has
accepted (legacy debt). The gate fails on any finding whose
fingerprint is not covered by the baseline, and the baseline may only
shrink: an update that would RAISE any rule's count is refused.
Fingerprints are line-number-free (rule + file + enclosing object +
normalized source snippet) so ordinary code motion does not churn
them.
"""

from __future__ import annotations

import ast
import collections
import dataclasses
import json
import pathlib
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: rule catalog: name -> one-line description (the README table renders
#: from the same strings)
RULES: Dict[str, str] = {
    "jax-raw-jit":
        "raw jax.jit( call outside the tracked_jit allowlist",
    "jax-host-sync-in-jit":
        "host-device sync (.item()/np.*/float()/device_get) inside a "
        "jit-traced function",
    "jax-nondet-in-jit":
        "wall-clock or Python/numpy RNG call inside a jit-traced "
        "function (baked in at trace time)",
    "jax-missing-donate":
        "jit whose first arg is a KV-cache/params pytree without "
        "donate_argnums covering it",
    "jax-scalar-signature":
        "unbounded Python scalar (len()/arithmetic) in a static jit "
        "position: one compile per distinct value",
    "jax-unsynced-timing":
        "time.* delta bracketing a jit dispatch with no "
        "block_until_ready fence (measures enqueue, not compute)",
    "step-host-sync":
        "per-element or looped host-device pull on the engine step "
        "path (pull once, index in numpy)",
    "jax-dispatch-in-decode-loop":
        "jit dispatched inside a loop on the engine step path (one "
        "launch per token — batch the call or lax.scan inside the jit)",
    "lock-guarded-unlocked":
        "attribute written under a lock accessed without holding it",
    "lock-order-inversion":
        "two locks acquired in opposite nested orders (deadlock risk)",
    "paged-host-gather":
        "host-side subscript of a paged-KV table (arena / block table "
        "/ page table) on the engine step path — page indexing "
        "belongs inside the tracked jit",
    "metric-label-cardinality":
        "unbounded value (f-string/format/str()/concat/request-scoped "
        "identifier) passed to a metric .labels() call",
}

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\- ]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One analyzer hit, anchored to a source line."""

    rule: str
    path: str          # repo-relative posix path
    line: int          # 1-based
    obj: str           # enclosing context, e.g. "LLMEngine._sample_host"
    message: str
    snippet: str       # stripped source line (fingerprint component)

    def fingerprint(self) -> str:
        """Line-number-free identity: survives code motion, dies when
        the offending line itself changes (which is the point — a
        changed line must be re-audited)."""
        snip = " ".join(self.snippet.split())
        return f"{self.rule}::{self.path}::{self.obj}::{snip}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule}: "
                f"{self.message} [{self.obj}]")

    def to_json(self) -> dict:
        return {"rule": self.rule, "path": self.path, "obj": self.obj,
                "snippet": " ".join(self.snippet.split()),
                "message": self.message}


@dataclasses.dataclass
class Module:
    """One parsed source file handed to the rule families."""

    path: pathlib.Path          # absolute
    rel: str                    # repo-relative posix path
    tree: ast.AST
    lines: List[str]
    suppressions: Dict[int, set] = dataclasses.field(default_factory=dict)

    def snippet(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def suppressed(self, rule: str, lineno: int) -> bool:
        rules = self.suppressions.get(lineno)
        return bool(rules) and (rule in rules or "all" in rules)


def parse_suppressions(lines: Sequence[str]) -> Dict[int, set]:
    out: Dict[int, set] = {}
    for i, line in enumerate(lines, 1):
        m = _SUPPRESS_RE.search(line)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",")
                      if r.strip()}
    return out


def load_module(path: pathlib.Path,
                repo_root: Optional[pathlib.Path] = None
                ) -> Optional[Module]:
    """Parse one file; returns None on syntax errors (reported by the
    CLI, not fatal — a broken file fails its own import/tests)."""
    try:
        src = path.read_text(encoding="utf-8")
        tree = ast.parse(src)
    except (OSError, SyntaxError, ValueError):
        return None
    if repo_root is not None:
        try:
            rel = path.resolve().relative_to(
                repo_root.resolve()).as_posix()
        except ValueError:
            rel = path.as_posix()
    else:
        rel = path.as_posix()
    lines = src.splitlines()
    return Module(path=path, rel=rel, tree=tree, lines=lines,
                  suppressions=parse_suppressions(lines))


def iter_package_files(package_dir: pathlib.Path) -> List[pathlib.Path]:
    return sorted(p for p in package_dir.rglob("*.py")
                  if "__pycache__" not in p.parts)


@dataclasses.dataclass
class AnalysisResult:
    findings: List[Finding]
    suppressed: List[Finding]
    parse_failures: List[str]

    def counts(self) -> Dict[str, int]:
        c: Dict[str, int] = {}
        for f in self.findings:
            c[f.rule] = c.get(f.rule, 0) + 1
        return c


def analyze(files: Iterable[pathlib.Path],
            repo_root: Optional[pathlib.Path] = None,
            rules: Optional[Sequence[str]] = None,
            step_entries: Optional[dict] = None) -> AnalysisResult:
    """Run every rule family over ``files``; split findings into live
    vs inline-suppressed. ``rules`` restricts to a subset by name;
    ``step_entries`` overrides the engine-step-path roots (tests point
    it at fixture modules)."""
    from bigdl_tpu.analysis import jax_rules, locks, metric_rules

    modules: List[Module] = []
    failures: List[str] = []
    for p in files:
        m = load_module(pathlib.Path(p), repo_root)
        if m is None:
            failures.append(str(p))
        else:
            modules.append(m)

    raw: List[Finding] = []
    raw += jax_rules.check(modules, step_entries=step_entries)
    raw += locks.check(modules)
    raw += metric_rules.check(modules)
    if rules is not None:
        keep = set(rules)
        raw = [f for f in raw if f.rule in keep]

    by_path = {m.rel: m for m in modules}
    live, suppressed = [], []
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
        m = by_path.get(f.path)
        if m is not None and m.suppressed(f.rule, f.line):
            suppressed.append(f)
        else:
            live.append(f)
    return AnalysisResult(live, suppressed, failures)


# ---------------------------------------------------------------------------
# baseline


def load_baseline(path: pathlib.Path) -> dict:
    """Read the baseline; a missing file is an empty baseline (the
    strictest one)."""
    try:
        doc = json.loads(path.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return {"version": 1, "counts": {}, "findings": []}
    doc.setdefault("counts", {})
    doc.setdefault("findings", [])
    return doc


def baseline_fingerprints(baseline: dict) -> "collections.Counter":
    c: collections.Counter = collections.Counter()
    for e in baseline.get("findings", []):
        snip = " ".join(str(e.get("snippet", "")).split())
        c[f"{e.get('rule')}::{e.get('path')}::{e.get('obj')}::{snip}"] += 1
    return c


def new_findings(findings: Sequence[Finding],
                 baseline: dict) -> List[Finding]:
    """Findings not covered by the baseline. Multiplicity-aware: two
    identical lines need two baseline entries."""
    budget = baseline_fingerprints(baseline)
    out = []
    for f in findings:
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
        else:
            out.append(f)
    return out


def ratchet_violations(old: dict, findings: Sequence[Finding]
                       ) -> List[str]:
    """Per-rule counts may only shrink. Returns human-readable
    violations (empty = update allowed)."""
    new_counts: Dict[str, int] = {}
    for f in findings:
        new_counts[f.rule] = new_counts.get(f.rule, 0) + 1
    old_counts = {k: int(v) for k, v in old.get("counts", {}).items()}
    out = []
    for rule, n in sorted(new_counts.items()):
        if n > old_counts.get(rule, 0):
            out.append(f"{rule}: {old_counts.get(rule, 0)} -> {n} "
                       "(baseline may only shrink; fix the new finding "
                       "or add an audited inline "
                       f"'# graftlint: disable={rule}')")
    return out


def render_baseline(findings: Sequence[Finding]) -> str:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    doc = {
        "version": 1,
        "counts": dict(sorted(counts.items())),
        "findings": [f.to_json() for f in sorted(
            findings, key=lambda f: (f.path, f.line, f.rule))],
    }
    return json.dumps(doc, indent=2, sort_keys=False) + "\n"
