"""Metric-hygiene rules: label cardinality.

Prometheus label values multiply time series: every distinct value of
every label mints a new child series kept resident in the registry (and
in every scraper downstream). A label fed from request-scoped data — a
request id, a tenant string, a formatted message — grows without bound
and eventually OOMs the registry or the TSDB. The fleet postmortem
pattern is always the same innocent-looking line::

    self._c_reqs.labels(f"replica-{r.idx}", request_id).inc()

Rule
----
``metric-label-cardinality``
    Flags ``.labels(...)`` arguments that are *constructed* or
    *identity-shaped* rather than drawn from a closed set:

    - f-strings and ``str.format`` / ``%`` formatting,
    - ``str()`` / ``repr()`` / ``format()`` stringification,
    - string concatenation (``+`` of anything inside the arg),
    - names or attributes whose identifier looks request-scoped
      (``tenant``, ``user``, ``request_id``, ``rid``, ``trace``,
      ``span``, ``session``, ``uuid``, ``url``, ``addr``, ``host``,
      or a ``*_id`` suffix).

    String literals, bare bounded-looking names (``reason``, ``mode``,
    ``phase``), and ``*args``/``**kwargs`` splats of literal tuples
    pass. The identifier heuristic is deliberately name-based — a
    bounded value routed through a variable called ``tenant`` still
    reads as unbounded and needs an audited inline
    ``# graftlint: disable=metric-label-cardinality`` stating WHY the
    set is closed (e.g. replica index bounded by fleet size).

Known limits (documented, deliberate): no dataflow — a tainted value
laundered through an innocently-named temporary is invisible, and only
calls spelled ``<expr>.labels(...)`` are inspected (the codebase's
metric objects are always held in attributes/locals, so this covers
every real site).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from bigdl_tpu.analysis.core import Finding, Module

RULE = "metric-label-cardinality"

#: identifier fragments that read as per-request / per-identity data.
#: Matched against the *terminal* name of a Name/Attribute label arg.
_TAINTED_TOKENS = (
    "tenant", "user", "request", "rid", "trace", "span", "session",
    "uuid", "url", "addr", "host",
)

_STRINGIFIERS = ("str", "repr", "format")


def _terminal_name(node: ast.AST) -> Optional[str]:
    """'tenant' for ``params.tenant`` / ``tenant``; None otherwise."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _tainted_identifier(name: str) -> bool:
    low = name.lower().lstrip("_")
    if low.endswith("_id") or low == "id":
        return True
    return any(tok in low for tok in _TAINTED_TOKENS)


def _diagnose(arg: ast.AST) -> Optional[str]:
    """Why this label arg is unbounded, or None if it looks closed."""
    if isinstance(arg, ast.JoinedStr):
        return "f-string label value (one series per distinct render)"
    if isinstance(arg, ast.Call):
        f = arg.func
        if isinstance(f, ast.Attribute) and f.attr == "format":
            return ("str.format() label value (one series per "
                    "distinct render)")
        if isinstance(f, ast.Name) and f.id in _STRINGIFIERS:
            return (f"{f.id}() label value — stringified data has no "
                    "static cardinality bound")
    if isinstance(arg, ast.BinOp):
        if isinstance(arg.op, ast.Mod):
            return ("%-format label value (one series per distinct "
                    "render)")
        if isinstance(arg.op, ast.Add):
            return ("concatenated label value (one series per "
                    "distinct render)")
    name = _terminal_name(arg)
    if name is not None and _tainted_identifier(name):
        return (f"label fed from {name!r} — request-scoped identity "
                "values are unbounded")
    return None


class _Scan(ast.NodeVisitor):
    def __init__(self, m: Module, out: List[Finding]):
        self.m = m
        self.out = out
        self.stack: List[str] = []

    @property
    def obj(self) -> str:
        return ".".join(self.stack) or "<module>"

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "labels":
            for arg in list(node.args) + [kw.value for kw in
                                          node.keywords
                                          if kw.arg is not None]:
                why = _diagnose(arg)
                if why is not None:
                    self.out.append(Finding(
                        rule=RULE, path=self.m.rel,
                        line=getattr(arg, "lineno", node.lineno),
                        obj=self.obj,
                        message=why,
                        snippet=self.m.snippet(
                            getattr(arg, "lineno", node.lineno))))
        self.generic_visit(node)


def check(modules: Iterable[Module]) -> List[Finding]:
    out: List[Finding] = []
    for m in modules:
        _Scan(m, out).visit(m.tree)
    return out
