"""Lock-discipline rules: guarded-attribute inference + order graph.

The checker is annotation-free: it infers the lock <-> state map from
the code itself, clang-thread-safety style but heuristic.

Inference
---------
1. A *lock attribute* is any ``self.X = threading.Lock()`` /
   ``RLock()`` assignment (collected globally across the scanned
   modules, so nested acquisitions through other objects' locks can be
   keyed too).
2. Inside one class, an attribute ``A`` is *guarded by* lock ``L``
   when ``self.A`` is WRITTEN somewhere in a ``with self.L:`` body
   (writes: assignment, augmented assignment, subscript stores, and
   mutating method calls — ``append``/``pop``/``setdefault``/...).
   Reads under the lock alone do not bind: read-only config assigned
   once in ``__init__`` stays free.
3. ``__init__`` is exempt (construction precedes sharing), and nested
   ``def``s inherit the locks held at their definition site (the
   codebase's closures are called inline under the same lock).

Rules
-----
``lock-guarded-unlocked``
    Any access (read or write) of a guarded attribute outside its
    lock, in any non-exempt method of the owning class. Accesses
    through other receivers (``other.attr``) are invisible — route
    cross-object mutation through a locked method of the owner.
``lock-order-inversion``
    Nested ``with`` acquisitions define order edges keyed by the LOCK
    ATTRIBUTE NAME (``self._a`` nesting ``b._b`` adds ``_a -> _b``).
    Both directions present anywhere in the scanned set is a deadlock
    risk. Name-keying is a heuristic: give locks distinct names.

Known limits (documented, deliberate): no interprocedural lock
tracking (a helper that REQUIRES a held lock reads as unguarded — take
the lock in the public method, or suppress with an audited inline
disable), no ``.acquire()``/``.release()`` pairing (use ``with``), no
aliasing of lock objects.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from bigdl_tpu.analysis.core import Finding, Module

_MUTATORS = {
    "append", "appendleft", "extend", "insert", "remove", "pop",
    "popleft", "popitem", "clear", "update", "setdefault", "add",
    "discard", "sort", "reverse",
}

_EXEMPT_METHODS = {"__init__", "__new__", "__del__"}


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = node.func
    parts = []
    while isinstance(d, ast.Attribute):
        parts.append(d.attr)
        d = d.value
    if isinstance(d, ast.Name):
        parts.append(d.id)
    parts = list(reversed(parts))
    return bool(parts) and parts[-1] in ("Lock", "RLock")


@dataclasses.dataclass
class _Access:
    attr: str
    write: bool
    held: Tuple[str, ...]       # lock attr names held (this class's)
    lineno: int
    method: str


@dataclasses.dataclass
class _ClassInfo:
    name: str
    module: Module
    locks: Set[str] = dataclasses.field(default_factory=set)
    accesses: List[_Access] = dataclasses.field(default_factory=list)
    # lock -> attrs written under it (non-exempt methods)
    guards: Dict[str, Set[str]] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class _OrderEdge:
    outer: str
    inner: str
    module: Module
    lineno: int
    obj: str


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' for ``self.x``, else None."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


class _MethodScan(ast.NodeVisitor):
    """Walk one method body tracking held locks; record every self.*
    access and every nested lock acquisition."""

    def __init__(self, cls: _ClassInfo, method: str,
                 global_locks: Set[str], edges: List[_OrderEdge],
                 held: Tuple[str, ...] = ()):
        self.cls = cls
        self.method = method
        self.global_locks = global_locks
        self.edges = edges
        self.held = list(held)
        # full held stack including OTHER objects' locks (for ordering)
        self.order_stack: List[str] = list(held)

    # -- with ---------------------------------------------------------------

    def _lock_of_item(self, item: ast.withitem) -> Optional[Tuple[str, bool]]:
        """(lock_attr_name, is_self) for ``with <recv>.<lock>:``."""
        ctx = item.context_expr
        attr = _self_attr(ctx)
        if attr is not None and attr in self.cls.locks:
            return attr, True
        # other receivers: any attribute chain ending in a known lock
        if isinstance(ctx, ast.Attribute) \
                and ctx.attr in self.global_locks:
            return ctx.attr, False
        if isinstance(ctx, ast.Name) and ctx.id in self.global_locks:
            return ctx.id, False
        return None

    def visit_With(self, node: ast.With) -> None:
        acquired: List[Tuple[str, bool]] = []
        for item in node.items:
            got = self._lock_of_item(item)
            if got is not None:
                name, is_self = got
                if self.order_stack:
                    self.edges.append(_OrderEdge(
                        self.order_stack[-1], name, self.cls.module,
                        node.lineno,
                        f"{self.cls.name}.{self.method}"))
                self.order_stack.append(name)
                if is_self:
                    self.held.append(name)
                acquired.append(got)
            # the context expr itself may contain accesses
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        for name, is_self in reversed(acquired):
            self.order_stack.pop()
            if is_self:
                self.held.pop()

    visit_AsyncWith = visit_With

    # -- nested defs inherit the held set at their definition site ---------

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        inner = _MethodScan(self.cls, f"{self.method}.{node.name}",
                            self.global_locks, self.edges,
                            tuple(self.held))
        inner.order_stack = list(self.order_stack)
        for stmt in node.body:
            inner.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = lambda self, node: None      # noqa: E731 — opaque

    # -- accesses -----------------------------------------------------------

    def _record(self, attr: str, write: bool, lineno: int) -> None:
        if attr in self.cls.locks:
            return
        self.cls.accesses.append(_Access(
            attr, write, tuple(self.held), lineno, self.method))

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr is not None:
            self._record(attr, isinstance(node.ctx,
                                          (ast.Store, ast.Del)),
                         node.lineno)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        # self.A[k] = v  /  del self.A[k]  — write to A's contents
        attr = _self_attr(node.value)
        if attr is not None and isinstance(node.ctx,
                                           (ast.Store, ast.Del)):
            self._record(attr, True, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        t = node.target
        attr = _self_attr(t)
        if attr is None and isinstance(t, ast.Subscript):
            attr = _self_attr(t.value)
        if attr is not None:
            self._record(attr, True, node.lineno)
        # visit value side only (target Attribute already recorded)
        self.visit(node.value)
        if isinstance(t, ast.Subscript):
            self.visit(t.slice)

    def visit_Call(self, node: ast.Call) -> None:
        # self.A.append(x) and friends mutate A
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
            attr = _self_attr(f.value)
            if attr is not None:
                self._record(attr, True, node.lineno)
        self.generic_visit(node)


def _scan_class(node: ast.ClassDef, module: Module,
                global_locks: Set[str],
                edges: List[_OrderEdge]) -> _ClassInfo:
    cls = _ClassInfo(node.name, module)
    # pass 1: this class's lock attrs (anywhere in its methods)
    for sub in ast.walk(node):
        if isinstance(sub, ast.Assign) and _is_lock_ctor(sub.value):
            for t in sub.targets:
                attr = _self_attr(t)
                if attr is not None:
                    cls.locks.add(attr)
    # pass 2: accesses with held-lock context
    for item in node.body:
        if not isinstance(item, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        if item.name in _EXEMPT_METHODS:
            continue
        scan = _MethodScan(cls, item.name, global_locks, edges)
        for stmt in item.body:
            scan.visit(stmt)
    # dedupe: one access per (attr, line), write wins (a mutator call
    # like self.A.append records both the call-write and the load-read)
    merged: Dict[Tuple[str, int, Tuple[str, ...]], _Access] = {}
    for a in cls.accesses:
        key = (a.attr, a.lineno, a.held)
        prev = merged.get(key)
        if prev is None or (a.write and not prev.write):
            merged[key] = a
    cls.accesses = sorted(merged.values(),
                          key=lambda a: (a.lineno, a.attr))
    # inference: lock -> attrs WRITTEN while held
    for a in cls.accesses:
        if a.write:
            for lock in a.held:
                cls.guards.setdefault(lock, set()).add(a.attr)
    return cls


def check(modules: Iterable[Module]) -> List[Finding]:
    modules = list(modules)
    # global pass: every lock attribute name in the scanned set
    global_locks: Set[str] = set()
    for m in modules:
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Assign) \
                    and _is_lock_ctor(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Attribute):
                        global_locks.add(t.attr)
                    elif isinstance(t, ast.Name):
                        global_locks.add(t.id)

    out: List[Finding] = []
    edges: List[_OrderEdge] = []
    for m in modules:
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            cls = _scan_class(node, m, global_locks, edges)
            if not cls.guards:
                continue
            guarded_by: Dict[str, str] = {}
            for lock, attrs in sorted(cls.guards.items()):
                for a in attrs:
                    guarded_by.setdefault(a, lock)
            for a in cls.accesses:
                lock = guarded_by.get(a.attr)
                if lock is None or lock in a.held:
                    continue
                kind = "write" if a.write else "read"
                out.append(Finding(
                    "lock-guarded-unlocked", m.rel, a.lineno,
                    f"{cls.name}.{a.method}",
                    f"self.{a.attr} is written under self.{lock} "
                    f"elsewhere in {cls.name} but this {kind} does "
                    "not hold it",
                    m.snippet(a.lineno)))

    # order inversions: both directions present anywhere
    seen_pairs: Set[Tuple[str, str]] = set()
    forward: Dict[Tuple[str, str], _OrderEdge] = {}
    for e in edges:
        forward.setdefault((e.outer, e.inner), e)
    for (a, b), e in sorted(forward.items(),
                            key=lambda kv: (kv[1].module.rel,
                                            kv[1].lineno)):
        if a == b or frozenset((a, b)) in {frozenset(p)
                                           for p in seen_pairs}:
            continue
        rev = forward.get((b, a))
        if rev is not None:
            seen_pairs.add((a, b))
            out.append(Finding(
                "lock-order-inversion", rev.module.rel, rev.lineno,
                rev.obj,
                f"acquires {b!r} then {a!r}, but {e.obj} "
                f"({e.module.rel}:{e.lineno}) acquires {a!r} then "
                f"{b!r} — a concurrent pair can deadlock",
                rev.module.snippet(rev.lineno)))
    return out
