"""graftlint CLI: ``python -m bigdl_tpu.analysis [options] [paths]``.

Exit codes (bench_diff-style, usable as a raw CI gate):

* ``0`` — clean: no findings outside the baseline.
* ``1`` — new findings (printed one per line, ``path:line: rule: ...``).
* ``2`` — ratchet violation on ``--update-baseline`` (a per-rule count
  would grow), or unparseable inputs.

``--update-baseline`` rewrites ``tools/graftlint_baseline.json`` from
the current findings but REFUSES to let any rule's count grow —
the baseline only ratchets down. ``--init-baseline`` bypasses the
ratchet once (bootstrapping a new checkout; review the diff).
"""

from __future__ import annotations

import argparse
import pathlib
import sys
from typing import List, Optional

from bigdl_tpu.analysis import core


def _repo_root() -> pathlib.Path:
    # bigdl_tpu/analysis/__main__.py -> repo root two levels up from
    # the package directory
    return pathlib.Path(__file__).resolve().parent.parent.parent


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description="JAX-hazard + lock-discipline static analysis "
                    "with a ratcheted baseline")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to scan (default: the bigdl_tpu "
                         "package)")
    ap.add_argument("--baseline", type=pathlib.Path, default=None,
                    help="baseline JSON (default: "
                         "tools/graftlint_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding (ignore the baseline)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(refused if any rule count would grow)")
    ap.add_argument("--init-baseline", action="store_true",
                    help="write the baseline without the ratchet check")
    ap.add_argument("--rule", action="append", default=None,
                    help="restrict to a rule (repeatable)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for name, desc in core.RULES.items():
            print(f"{name:24s} {desc}")
        return 0

    root = _repo_root()
    baseline_path = args.baseline or root / "tools" / \
        "graftlint_baseline.json"

    if args.paths:
        files: List[pathlib.Path] = []
        for p in args.paths:
            path = pathlib.Path(p)
            if path.is_dir():
                files += core.iter_package_files(path)
            else:
                files.append(path)
    else:
        files = core.iter_package_files(root / "bigdl_tpu")

    result = core.analyze(files, repo_root=root, rules=args.rule)
    for bad in result.parse_failures:
        print(f"graftlint: cannot parse {bad}", file=sys.stderr)

    if args.update_baseline or args.init_baseline:
        old = core.load_baseline(baseline_path)
        if not args.init_baseline:
            violations = core.ratchet_violations(old, result.findings)
            if violations:
                print("graftlint: baseline update REFUSED "
                      "(ratchet: counts may only shrink):")
                for v in violations:
                    print(f"  {v}")
                return 2
        baseline_path.parent.mkdir(parents=True, exist_ok=True)
        baseline_path.write_text(
            core.render_baseline(result.findings), encoding="utf-8")
        print(f"graftlint: baseline written to {baseline_path} "
              f"({len(result.findings)} finding(s))")
        return 0

    if args.no_baseline:
        new = result.findings
    else:
        new = core.new_findings(result.findings,
                                core.load_baseline(baseline_path))
    for f in new:
        print(f.render())

    counts = result.counts()
    summary = ", ".join(f"{r}={n}" for r, n in sorted(counts.items()))
    print(f"graftlint: {len(result.findings)} finding(s) "
          f"({summary or 'none'}); {len(new)} new vs baseline; "
          f"{len(result.suppressed)} inline-suppressed; "
          f"{len(files)} file(s) scanned")
    if new:
        print("graftlint: FAIL — fix the finding, add an audited "
              "'# graftlint: disable=<rule>', or (for legacy debt) "
              "rebaseline with --update-baseline")
        return 1
    if result.parse_failures:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
