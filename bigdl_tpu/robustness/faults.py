"""Deterministic, seedable fault injection for the serving stack.

Chaos testing only exercises real recovery code when the faults land in
the real execution paths: the serving engine calls into this module at
its step / admit / prefill / logits points (serving/engine.py), and the
`Generator` step path exposes the same hook (generation.py). With no
spec configured every hook is a no-op costing one attribute check.

Spec grammar (``$BIGDL_TPU_FAULT_SPEC`` or ``parse_fault_spec()``):

    spec    := clause (';' clause)*
    clause  := kind '@' param (',' param)*
    param   := key '=' value

Kinds and the injection points they attach to:

- ``step_exception``  — raise ``InjectedFault`` from the engine's
  batched decode step (point ``"step"``). The engine's retry /
  quarantine machinery is the recovery path under test.
- ``admit_exception`` — raise from the admission bookkeeping path
  (point ``"admit"``), blaming a single identifiable request.
- ``prefill_exception`` — raise around the chunked prefill call
  (point ``"prefill"``), also request-attributable.
- ``nan_logits``      — poison one slot's logits row with NaN after the
  decode (point ``"logits"``); exercises the per-slot health check and
  quarantine. ``slot=i`` targets a fixed row (default: the lowest
  active slot).
- ``logit_drift``     — add a FINITE constant bias (``bias=``, default
  3.0) to one vocab column of every active logits row from the first
  firing onward (point ``"logits"``). Unlike ``nan_logits`` this is invisible to the
  engine's isfinite health check: the replica keeps serving at full
  speed with every gauge green, but greedy argmax changes — silent
  correctness drift. The detection path under test is the router's
  golden-canary probes (serving/canary.py), which quarantine the
  replica on byte mismatch. Once fired, drift stays on for the life of
  the process (real corruption doesn't heal); ``times=`` caps only the
  number of *onset* firings.
- ``slow_step``       — sleep ``ms=`` milliseconds at the step point;
  exercises deadline enforcement without a slow model.
- ``replica_crash``   — hard-kill THIS PROCESS (``os._exit``, default
  code 137 — indistinguishable from an external ``kill -9``) at the
  step point. The engine only consults this kind on steps with live
  work, so the crash lands MID-REQUEST (``every=N`` counts busy steps).
  The recovery path under test lives one level up: the serving
  router's supervisor, failover, and request replay
  (serving/router.py). ``code=`` overrides the exit code.
- ``replica_hang``    — freeze the engine's step loop at the step
  point (sleep ``ms=`` milliseconds, or forever when unset). The
  process stays alive and its HTTP threads keep answering, so this
  exercises wedge detection (`/health` heartbeat) and the router's
  hang-kill-restart path rather than crash handling.
- ``overload_storm``  — force the engine's brownout pressure signal to
  ``pressure=`` (default 1.0) on steps where the clause fires (point
  ``"storm"``), driving the overload ladder (serving/overload.py)
  deterministically without real traffic: brownout escalation, QoS
  shedding, and hysteresis recovery all become scriptable.
- ``handoff_drop``    — make the prefill replica's KV-handoff POST to a
  decode replica fail as if the wire dropped it (point ``"handoff"``,
  consulted via ``drop_point`` before each transfer attempt; every
  attempt counts as one visit, so ``every=N`` drops every Nth
  attempt). The recovery path under test is the handoff
  retry/backoff ladder and the local-decode fallback
  (serving/api_server.py) — the request must never be lost.
- ``scale_flap``      — force the fleet autoscaler to alternate
  scale-up/scale-down decisions on ticks where the clause fires
  (``flap_direction``), bypassing its dwell/hysteresis gating. The
  invariants under test are the hard guards: never retire the last
  healthy replica, never fight a rolling restart
  (serving/autoscaler.py).
- ``migration_drop``  — make a live-migration transfer attempt fail at
  one of its three gates (``gate=send|recv|commit``; unset = every
  gate): ``send`` fails the POST before any bytes leave the source,
  ``recv`` makes the target reject before staging, ``commit`` stages
  the state on the target but loses the ACK (the crash-after-commit
  matrix row). Consulted via ``drop_point("migrate_<gate>")``. The
  recovery path under test is the source's local-resume fallback and
  the router's journal replay — the request must never be lost.
- ``migration_corrupt`` — flip one bit in a framed internal wire
  payload after checksumming (``corrupt_point``; ``point=`` scopes to
  ``migrate`` or ``handoff``, unset = both). The receiver's CRC32
  check must reject it with a structured 400 counted in
  ``bigdl_tpu_handoff_rejects_total{reason="crc"}``.
- ``net_latency``     — add ``ms=`` milliseconds of latency to
  fleet-internal HTTP client calls (router→replica stats/canary
  probes and admin fan-outs, replica→replica handoff/migrate posts).
  ``point=`` scopes to one path (``handoff``, ``migrate``, ``stats``,
  ``canary``, ``admin``); unset applies to all internal calls.
- ``net_drop``        — fail fleet-internal HTTP client calls as if
  the connection reset (``p=`` per-call probability, or the usual
  every/times triggers). Same ``point=`` scoping as ``net_latency``.
  Together they make migration/handoff timeout+retry paths
  chaos-testable deterministically.

Trigger params (every kind):

- ``p=0.05``        — fire with probability p per visit (seeded; see
  ``seed=``). Deterministic given the seed and visit order.
- ``after_step=N``  — fire at the first visit whose ``step >= N``.
- ``at_step=N``     — fire at visits with ``step == N`` exactly.
- ``every=N``       — fire every Nth visit to the point (1 = always).
- ``times=K``       — total-fire cap (default 1 for ``after_step`` /
  ``at_step``, unlimited otherwise; ``times=0`` means unlimited).
- ``seed=S``        — seed for this clause's RNG (default 0): two runs
  with the same spec inject the identical fault sequence.
- ``ms=M``          — sleep milliseconds (``slow_step``; for
  ``replica_hang`` a bounded freeze instead of forever).
- ``slot=i``        — target row (``nan_logits`` only).
- ``code=C``        — process exit code (``replica_crash`` only).
- ``pressure=P``    — forced brownout pressure in [0, 1]
  (``overload_storm`` only; default 1.0).
- ``bias=B``        — additive logit bias (``logit_drift`` only;
  default 3.0; must be finite and non-zero).
- ``gate=G``        — migration gate to fail (``migration_drop``
  only): ``send``, ``recv``, or ``commit``; unset fires at every gate.
- ``point=P``       — internal-HTTP path scope (``net_latency`` /
  ``net_drop`` / ``migration_corrupt``); unset applies everywhere the
  hook is consulted.

Example: ``step_exception@p=0.05,seed=7;slow_step@ms=500,every=10``.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Dict, List, Optional

import numpy as np

FAULT_SPEC_ENV = "BIGDL_TPU_FAULT_SPEC"

KINDS = ("step_exception", "admit_exception", "prefill_exception",
         "nan_logits", "logit_drift", "slow_step", "replica_crash",
         "replica_hang", "overload_storm", "handoff_drop", "scale_flap",
         "migration_drop", "migration_corrupt", "net_latency",
         "net_drop")

#: live-migration transfer gates migration_drop can target
MIGRATION_GATES = ("send", "recv", "commit")

#: default exit code for replica_crash — what an external ``kill -9``
#: surfaces as through the shell (128 + SIGKILL)
CRASH_EXIT_CODE = 137

# injection point -> exception kinds that fire there
_RAISE_POINTS = {
    "step": "step_exception",
    "admit": "admit_exception",
    "prefill": "prefill_exception",
}

_INT_PARAMS = ("after_step", "at_step", "every", "times", "seed", "slot",
               "code")
_FLOAT_PARAMS = ("p", "ms", "pressure", "bias")
_STR_PARAMS = ("gate", "point")


class InjectedFault(RuntimeError):
    """A fault raised by the injection harness. ``transient`` mirrors
    what the recovery code assumes about real-world analogs (XLA
    transfer hiccups, tunnel resets): retrying may succeed."""

    def __init__(self, kind: str, point: str, step: int):
        super().__init__(f"injected {kind} at {point} (step {step})")
        self.kind = kind
        self.point = point
        self.step = step
        self.transient = True


@dataclasses.dataclass
class FaultClause:
    kind: str
    p: float = 0.0
    after_step: Optional[int] = None
    at_step: Optional[int] = None
    every: int = 0
    times: Optional[int] = None       # None = unlimited
    seed: int = 0
    ms: float = 0.0
    slot: Optional[int] = None
    code: Optional[int] = None        # replica_crash exit code
    pressure: float = 1.0             # overload_storm forced pressure
    bias: float = 3.0                 # logit_drift additive bias
    gate: Optional[str] = None        # migration_drop target gate
    point: Optional[str] = None       # net_* / migration_corrupt scope
    # runtime state
    fired: int = 0
    visits: int = 0
    _rng: Optional[np.random.Generator] = None

    def __post_init__(self):
        if self.times is None and (self.after_step is not None
                                   or self.at_step is not None):
            self.times = 1            # one-shot by default for step pins
        if self.times == 0:
            self.times = None
        self._rng = np.random.default_rng(self.seed)

    def should_fire(self, step: int) -> bool:
        self.visits += 1
        if self.times is not None and self.fired >= self.times:
            return False
        hit = False
        if self.at_step is not None:
            hit = step == self.at_step
        elif self.after_step is not None:
            hit = step >= self.after_step
        elif self.every > 0:
            hit = self.visits % self.every == 0
        elif self.p > 0.0:
            hit = bool(self._rng.random() < self.p)
        if hit:
            self.fired += 1
        return hit


def parse_fault_spec(spec: str) -> List[FaultClause]:
    """Parse a fault spec string; raises ``ValueError`` on malformed
    input (unknown kind, bad param, non-numeric value)."""
    clauses: List[FaultClause] = []
    for raw in (spec or "").split(";"):
        raw = raw.strip()
        if not raw:
            continue
        kind, _, params = raw.partition("@")
        kind = kind.strip()
        if kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} (choices: {', '.join(KINDS)})")
        kw: Dict[str, object] = {}
        for pair in params.split(","):
            pair = pair.strip()
            if not pair:
                continue
            key, sep, val = pair.partition("=")
            key = key.strip()
            if not sep:
                raise ValueError(f"fault param {pair!r} is not key=value")
            try:
                if key in _INT_PARAMS:
                    kw[key] = int(val)
                elif key in _FLOAT_PARAMS:
                    kw[key] = float(val)
                elif key in _STR_PARAMS:
                    kw[key] = val.strip()
                else:
                    raise ValueError(
                        f"unknown fault param {key!r} for {kind!r}")
            except ValueError as e:
                if "unknown fault param" in str(e):
                    raise
                raise ValueError(
                    f"fault param {key!r}={val!r} is not numeric") from None
        if kw.get("p", 0.0) and not (0.0 < kw["p"] <= 1.0):  # type: ignore
            raise ValueError(f"fault probability p={kw['p']} not in (0, 1]")
        pr = kw.get("pressure")
        if pr is not None and not (0.0 <= pr <= 1.0):  # type: ignore
            raise ValueError(
                f"overload_storm pressure={pr} not in [0, 1]")
        b = kw.get("bias")
        if b is not None and (b != b or b in (float("inf"),
                                              float("-inf")) or b == 0.0):
            raise ValueError(
                f"logit_drift bias={b} must be finite and non-zero")
        g = kw.get("gate")
        if g is not None and g not in MIGRATION_GATES:
            raise ValueError(
                f"migration gate {g!r} not one of "
                f"{', '.join(MIGRATION_GATES)}")
        clauses.append(FaultClause(kind=kind, **kw))  # type: ignore[arg-type]
    return clauses


def validate_fault_spec(spec: str) -> dict:
    """env_check report for ``$BIGDL_TPU_FAULT_SPEC``: parsed clause
    kinds, or the parse error."""
    try:
        clauses = parse_fault_spec(spec)
    except ValueError as e:
        return {"value": spec, "valid": False, "error": str(e)}
    return {"value": spec, "valid": True,
            "clauses": [c.kind for c in clauses]}


class FaultInjector:
    """Holds the parsed clauses and answers the engine's hook calls.

    ``NULL`` (the no-clause injector) is what engines get when no spec
    is configured — every hook is a cheap early return. ``on_fire`` is
    an optional callback ``(kind, point, step)`` the engine uses to
    count ``bigdl_tpu_faults_injected_total`` and drop a flight event.
    """

    def __init__(self, clauses: Optional[List[FaultClause]] = None,
                 on_fire=None):
        self.clauses = clauses or []
        self.on_fire = on_fire
        self._by_kind: Dict[str, List[FaultClause]] = {}
        for c in self.clauses:
            self._by_kind.setdefault(c.kind, []).append(c)

    @classmethod
    def from_env(cls, env: Optional[str] = None) -> "FaultInjector":
        spec = env if env is not None else os.environ.get(
            FAULT_SPEC_ENV, "")
        return cls(parse_fault_spec(spec)) if spec else cls()

    @property
    def enabled(self) -> bool:
        return bool(self.clauses)

    def _fired(self, kind: str, point: str, step: int) -> None:
        if self.on_fire is not None:
            try:
                self.on_fire(kind, point, step)
            except Exception:
                pass                  # telemetry must not alter the fault

    def raise_point(self, point: str, step: int) -> None:
        """Raise ``InjectedFault`` when a clause of the point's
        exception kind fires. Engine calls this at step/admit/prefill."""
        if not self.clauses:
            return
        kind = _RAISE_POINTS.get(point)
        if kind is None:
            return
        for c in self._by_kind.get(kind, ()):
            if c.should_fire(step):
                self._fired(kind, point, step)
                raise InjectedFault(kind, point, step)

    def process_point(self, point: str, step: int) -> None:
        """Process-granularity faults for the multi-replica chaos
        harness (serving/router.py). A firing ``replica_crash`` clause
        hard-kills this process with ``os._exit`` (no atexit, no flush
        — the same hole an OOM-kill or ``kill -9`` leaves); a firing
        ``replica_hang`` clause blocks this thread for ``ms``
        milliseconds (forever when unset), freezing the engine's step
        loop while the process stays alive. Engine calls this at the
        step point only."""
        if not self.clauses or point != "step":
            return
        for c in self._by_kind.get("replica_crash", ()):
            if c.should_fire(step):
                self._fired("replica_crash", point, step)
                os._exit(c.code if c.code is not None else CRASH_EXIT_CODE)
        for c in self._by_kind.get("replica_hang", ()):
            if c.should_fire(step):
                self._fired("replica_hang", point, step)
                if c.ms > 0:
                    time.sleep(c.ms / 1000.0)
                else:
                    while True:       # until the supervisor kills us
                        time.sleep(60.0)

    def sleep_ms(self, point: str, step: int) -> float:
        """Milliseconds the caller should sleep at this point (0 when
        no slow_step clause fires). The caller sleeps — the injector
        never blocks on its own."""
        if not self.clauses or point != "step":
            return 0.0
        total = 0.0
        for c in self._by_kind.get("slow_step", ()):
            if c.should_fire(step):
                self._fired("slow_step", point, step)
                total += c.ms
        return total

    def storm_pressure(self, step: int) -> Optional[float]:
        """Forced brownout pressure for this step, or None when no
        ``overload_storm`` clause fires. Multiple firing clauses take
        the max. The engine feeds the result into its overload
        controller IN PLACE OF the measured pressure floor, so a chaos
        test drives the full brownout ladder without real load."""
        if not self.clauses:
            return None
        forced: Optional[float] = None
        for c in self._by_kind.get("overload_storm", ()):
            if c.should_fire(step):
                self._fired("overload_storm", "storm", step)
                forced = c.pressure if forced is None \
                    else max(forced, c.pressure)
        return forced

    def drop_point(self, point: str, step: int) -> bool:
        """True when a drop clause fires at this point — the caller
        must treat the in-flight transfer attempt as lost (no bytes
        delivered) and run its retry/fallback ladder. Each attempt is
        one visit. ``"handoff"`` consults ``handoff_drop``;
        ``"migrate_send"`` / ``"migrate_recv"`` / ``"migrate_commit"``
        consult ``migration_drop`` clauses whose ``gate`` matches the
        suffix (a gate-less clause fires at every migration gate)."""
        if not self.clauses:
            return False
        dropped = False
        if point == "handoff":
            for c in self._by_kind.get("handoff_drop", ()):
                if c.should_fire(step):
                    self._fired("handoff_drop", point, step)
                    dropped = True
        elif point.startswith("migrate_"):
            gate = point[len("migrate_"):]
            for c in self._by_kind.get("migration_drop", ()):
                if c.gate is not None and c.gate != gate:
                    continue
                if c.should_fire(step):
                    self._fired("migration_drop", point, step)
                    dropped = True
        return dropped

    def corrupt_point(self, point: str, step: int) -> bool:
        """True when a ``migration_corrupt`` clause fires: the sender
        must flip a bit in its already-checksummed frame
        (serving/wire.corrupt_frame) before the POST, so the receiver's
        CRC32 rejection path is what gets exercised. ``point`` is
        ``"migrate"`` or ``"handoff"``; a clause's ``point=`` scopes
        it, unset fires at both."""
        if not self.clauses:
            return False
        corrupted = False
        for c in self._by_kind.get("migration_corrupt", ()):
            if c.point is not None and c.point != point:
                continue
            if c.should_fire(step):
                self._fired("migration_corrupt", point, step)
                corrupted = True
        return corrupted

    def net_delay_ms(self, point: str, step: int = 0) -> float:
        """Milliseconds of injected latency for one fleet-internal
        HTTP client call at ``point`` (0 when no scoped ``net_latency``
        clause fires). The caller sleeps before issuing the call."""
        if not self.clauses:
            return 0.0
        total = 0.0
        for c in self._by_kind.get("net_latency", ()):
            if c.point is not None and c.point != point:
                continue
            if c.should_fire(step):
                self._fired("net_latency", point, step)
                total += c.ms
        return total

    def net_dropped(self, point: str, step: int = 0) -> bool:
        """True when a scoped ``net_drop`` clause fires: the caller
        must fail this fleet-internal HTTP call as if the connection
        reset (raise ``OSError`` before any bytes move)."""
        if not self.clauses:
            return False
        dropped = False
        for c in self._by_kind.get("net_drop", ()):
            if c.point is not None and c.point != point:
                continue
            if c.should_fire(step):
                self._fired("net_drop", point, step)
                dropped = True
        return dropped

    def flap_direction(self, step: int) -> Optional[str]:
        """Forced autoscaler decision for this tick: ``"up"``, ``"down"``
        (alternating per firing, starting with "up"), or None when no
        ``scale_flap`` clause fires. The autoscaler applies the forced
        direction INSTEAD OF its dwell/hysteresis-gated decision — its
        hard guards (min/max replica bounds, last-healthy, rolling
        restart exclusion) still apply and are exactly what a flap
        chaos test exercises."""
        if not self.clauses:
            return None
        direction: Optional[str] = None
        for c in self._by_kind.get("scale_flap", ()):
            if c.should_fire(step):
                self._fired("scale_flap", "scale", step)
                # c.fired was just incremented: odd firings go up,
                # even firings go down — a deterministic flap
                direction = "up" if c.fired % 2 == 1 else "down"
        return direction

    def drift_rows(self, step: int, active_rows):
        """``(rows, bias)`` — logits rows to shift by a finite additive
        ``bias`` this step (``([], 0.0)`` when no ``logit_drift``
        clause is live). Drift is STICKY: once a clause fires its bias
        applies to every active row on every later step, modelling
        corruption that doesn't heal. The shifted logits stay finite,
        so the engine's isfinite health check passes and only a golden
        canary replay can notice."""
        if not self.clauses or not active_rows:
            return [], 0.0
        bias = 0.0
        for c in self._by_kind.get("logit_drift", ()):
            if getattr(c, "_drifting", False):
                bias += c.bias
            elif c.should_fire(step):
                self._fired("logit_drift", "logits", step)
                c._drifting = True    # type: ignore[attr-defined]
                bias += c.bias
        if bias == 0.0:
            return [], 0.0
        return list(active_rows), bias

    def poison_rows(self, step: int, active_rows) -> List[int]:
        """Rows of the decode logits to overwrite with NaN this step
        (empty when no nan_logits clause fires). A clause with
        ``slot=i`` targets that row if it is active; otherwise the
        lowest active row is poisoned."""
        if not self.clauses or not active_rows:
            return []
        rows: List[int] = []
        for c in self._by_kind.get("nan_logits", ()):
            if c.should_fire(step):
                row = c.slot if (c.slot is not None
                                 and c.slot in active_rows) \
                    else active_rows[0]
                self._fired("nan_logits", "logits", step)
                rows.append(row)
        return rows


#: shared no-op injector for unconfigured engines
NULL = FaultInjector()
