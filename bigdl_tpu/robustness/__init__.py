"""Fault tolerance for the serving stack.

Three legs (ROADMAP "heavy traffic" north star — the engine must
degrade per-request, never per-process):

- ``faults``: the deterministic, seedable fault-injection harness
  (``$BIGDL_TPU_FAULT_SPEC``) whose hooks live inside the engine's real
  step / admit / prefill / logits paths, so chaos tests exercise the
  same recovery code production failures hit.
- request lifecycle hardening (serving/engine.py): per-request
  deadlines (``$BIGDL_TPU_REQUEST_DEADLINE_MS`` /
  ``SamplingParams.max_time_ms``), client-disconnect cancellation, and
  bounded step retries with exponential backoff.
- blast-radius isolation + graceful drain (serving/engine.py +
  serving/api_server.py): per-slot NaN/Inf health checks, per-slot
  crash counters, quarantine with structured errors, and SIGTERM drain
  (``$BIGDL_TPU_DRAIN_TIMEOUT_SEC``) answering 503/504 at the API.

This module is stdlib+numpy only — it is imported by the engine's hot
step loop.
"""

from __future__ import annotations

import os
from typing import Optional

from bigdl_tpu.robustness.faults import (FAULT_SPEC_ENV, FaultClause,
                                         FaultInjector, InjectedFault,
                                         parse_fault_spec,
                                         validate_fault_spec)

REQUEST_DEADLINE_ENV = "BIGDL_TPU_REQUEST_DEADLINE_MS"
DRAIN_TIMEOUT_ENV = "BIGDL_TPU_DRAIN_TIMEOUT_SEC"

_DEFAULT_DRAIN_TIMEOUT_SEC = 30.0


def resolve_request_deadline_ms(
        value: Optional[str] = None) -> Optional[float]:
    """Default per-request deadline in ms (None = no deadline).
    Raises ``ValueError`` on a non-positive or non-numeric value —
    env_check surfaces it; the engine falls back to no deadline."""
    raw = value if value is not None else os.environ.get(
        REQUEST_DEADLINE_ENV, "")
    if not raw:
        return None
    ms = float(raw)                    # ValueError propagates
    if ms <= 0:
        raise ValueError(
            f"{REQUEST_DEADLINE_ENV} must be positive, got {raw!r}")
    return ms


def resolve_drain_timeout_sec(value: Optional[str] = None) -> float:
    """Drain deadline in seconds (default 30). Raises ``ValueError``
    on a non-positive or non-numeric value."""
    raw = value if value is not None else os.environ.get(
        DRAIN_TIMEOUT_ENV, "")
    if not raw:
        return _DEFAULT_DRAIN_TIMEOUT_SEC
    sec = float(raw)                   # ValueError propagates
    if sec <= 0:
        raise ValueError(
            f"{DRAIN_TIMEOUT_ENV} must be positive, got {raw!r}")
    return sec


__all__ = [
    "FAULT_SPEC_ENV", "REQUEST_DEADLINE_ENV", "DRAIN_TIMEOUT_ENV",
    "FaultClause", "FaultInjector", "InjectedFault",
    "parse_fault_spec", "validate_fault_spec",
    "resolve_request_deadline_ms", "resolve_drain_timeout_sec",
]
