// Host-side native quantization kernels.
//
// TPU-native equivalent of the reference's offline quantizer executables
// (reference setup.py:94-133 ships quantize-llama/gptneox/bloom/starcoder
// binaries driven by ggml/quantize.py:73-128 via subprocess) and of the
// ggml C quantize API (ggml_quantize_tensor, bound at
// ggml/model/llama/llama_cpp.py:946-989). Checkpoint conversion is
// host-bound (the TPU only sees already-packed blocks), so the hot loop is
// plain C++ + threads, bound to Python with ctypes — no pybind11 needed.
//
// Semantics are BIT-IDENTICAL to ops/quant.py's jitted quantizers:
//  - sym scale d = signed-absmax / -(1<<(bits-1)), first-max-index tie rule
//  - codes = clip(nearbyint(x/d) + half, 0, 2*half-1)  (round half-to-even)
//  - split-block nibble packing: byte j of a block holds values j (lo) and
//    j + block/2 (hi)
// Layout: input w is [K, N] f32 contraction-major; data/scales are the
// QTensor field layouts ([K/2, N] u8 + [K/32, N] f32-scale).

#include <cmath>
#include <cstdint>
#include <cfenv>
#include <algorithm>
#include <thread>
#include <vector>

namespace {

constexpr int kBlock = 32;

inline float block_signed_absmax(const float* w, int64_t n_cols,
                                 int64_t col, int64_t row0) {
  float amax = 0.0f, signed_max = 0.0f;
  for (int j = 0; j < kBlock; ++j) {
    const float x = w[(row0 + j) * n_cols + col];
    const float a = std::fabs(x);
    if (a > amax) {          // strict >: first-max tie rule (jnp.argmax)
      amax = a;
      signed_max = x;
    }
  }
  return signed_max;
}

template <typename Fn>
void parallel_cols(int64_t n_cols, Fn&& fn) {
  unsigned hw = std::thread::hardware_concurrency();
  int64_t n_threads = std::max<int64_t>(1, std::min<int64_t>(hw, n_cols));
  if (n_threads == 1) {
    fn(0, n_cols);
    return;
  }
  std::vector<std::thread> ts;
  const int64_t chunk = (n_cols + n_threads - 1) / n_threads;
  for (int64_t t = 0; t < n_threads; ++t) {
    const int64_t lo = t * chunk;
    const int64_t hi = std::min(n_cols, lo + chunk);
    if (lo >= hi) break;
    ts.emplace_back([=, &fn] { fn(lo, hi); });
  }
  for (auto& t : ts) t.join();
}

}  // namespace

extern "C" {

// w [K, N] f32 (K % 32 == 0) -> data [K/2, N] u8, scale [K/32, N] f32
void bigdl_quantize_q4_0(const float* w, int64_t k, int64_t n,
                         uint8_t* data, float* scale) {
  const int64_t n_blk = k / kBlock;
  parallel_cols(n, [&](int64_t lo, int64_t hi) {
    std::fesetround(FE_TONEAREST);
    for (int64_t col = lo; col < hi; ++col) {
      for (int64_t b = 0; b < n_blk; ++b) {
        const int64_t row0 = b * kBlock;
        const float mx = block_signed_absmax(w, n, col, row0);
        const float d = mx / -8.0f;
        const float inv = d != 0.0f ? 1.0f / d : 0.0f;
        scale[b * n + col] = d;
        uint8_t codes[kBlock];
        for (int j = 0; j < kBlock; ++j) {
          const float q =
              std::nearbyintf(w[(row0 + j) * n + col] * inv) + 8.0f;
          codes[j] = (uint8_t)std::clamp(q, 0.0f, 15.0f);
        }
        uint8_t* out = data + (b * (kBlock / 2)) * n + col;
        for (int j = 0; j < kBlock / 2; ++j) {
          out[j * n] = (uint8_t)(codes[j] | (codes[j + kBlock / 2] << 4));
        }
      }
    }
  });
}

// w [K, N] f32 -> data [K, N] i8, scale [K/32, N] f32
void bigdl_quantize_q8_0(const float* w, int64_t k, int64_t n,
                         int8_t* data, float* scale) {
  const int64_t n_blk = k / kBlock;
  parallel_cols(n, [&](int64_t lo, int64_t hi) {
    std::fesetround(FE_TONEAREST);
    for (int64_t col = lo; col < hi; ++col) {
      for (int64_t b = 0; b < n_blk; ++b) {
        const int64_t row0 = b * kBlock;
        const float mx = block_signed_absmax(w, n, col, row0);
        const float d = mx / -128.0f;
        const float inv = d != 0.0f ? 1.0f / d : 0.0f;
        scale[b * n + col] = d;
        for (int j = 0; j < kBlock; ++j) {
          const float q =
              std::nearbyintf(w[(row0 + j) * n + col] * inv) + 128.0f;
          data[(row0 + j) * n + col] =
              (int8_t)((int)std::clamp(q, 0.0f, 255.0f) - 128);
        }
      }
    }
  });
}

// data [K/2, N] u8 + scale [K/32, N] f32 -> out [K, N] f32
void bigdl_dequantize_q4_0(const uint8_t* data, const float* scale,
                           int64_t k, int64_t n, float* out) {
  const int64_t n_blk = k / kBlock;
  parallel_cols(n, [&](int64_t lo, int64_t hi) {
    for (int64_t col = lo; col < hi; ++col) {
      for (int64_t b = 0; b < n_blk; ++b) {
        const float d = scale[b * n + col];
        const uint8_t* in = data + (b * (kBlock / 2)) * n + col;
        float* o = out + (b * kBlock) * n + col;
        for (int j = 0; j < kBlock / 2; ++j) {
          const uint8_t byte = in[j * n];
          o[j * n] = ((int)(byte & 0x0F) - 8) * d;
          o[(j + kBlock / 2) * n] = ((int)(byte >> 4) - 8) * d;
        }
      }
    }
  });
}

// GGUF q4_0 blocks ([n_rows, n_blk, 18] bytes, row-major over K) ->
// QTensor layout: data [K/2, N] u8 + scale [K/32, N] f32. The repack is
// the transpose described in bigdl_tpu/gguf.py, fused into one pass.
void bigdl_repack_gguf_q4_0(const uint8_t* blocks, int64_t n_rows,
                            int64_t k, uint8_t* data, float* scale) {
  const int64_t n_blk = k / kBlock;
  const int64_t bpb = 18;
  parallel_cols(n_rows, [&](int64_t lo, int64_t hi) {
    for (int64_t row = lo; row < hi; ++row) {       // row == output column
      for (int64_t b = 0; b < n_blk; ++b) {
        const uint8_t* blk = blocks + (row * n_blk + b) * bpb;
        uint16_t h;
        __builtin_memcpy(&h, blk, 2);
        // fp16 -> f32 (scalar; scales are 1/576th of the bytes)
        const uint32_t sign = (uint32_t)(h & 0x8000) << 16;
        const uint32_t expo = (h >> 10) & 0x1F;
        const uint32_t mant = h & 0x3FF;
        uint32_t f;
        if (expo == 0) {
          if (mant == 0) {
            f = sign;
          } else {
            int e = -1;
            uint32_t m = mant;
            do { m <<= 1; ++e; } while (!(m & 0x400));
            f = sign | ((127 - 15 - e) << 23) | ((m & 0x3FF) << 13);
          }
        } else if (expo == 31) {
          f = sign | 0x7F800000 | (mant << 13);
        } else {
          f = sign | ((expo - 15 + 127) << 23) | (mant << 13);
        }
        float fd;
        __builtin_memcpy(&fd, &f, 4);
        scale[b * n_rows + row] = fd;
        const uint8_t* qs = blk + 2;
        uint8_t* out = data + (b * (kBlock / 2)) * n_rows + row;
        for (int j = 0; j < kBlock / 2; ++j) out[j * n_rows] = qs[j];
      }
    }
  });
}

}  // extern "C"
