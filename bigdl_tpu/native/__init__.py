"""Native host-runtime loader: compile-on-first-use C++ kernels via ctypes.

The reference ships prebuilt ISA-dispatched binaries downloaded at package
build (setup.py:59-133) and loads them with ctypes
(ggml/model/llama/llama_cpp.py:71-109). Here the source is in-tree
(quant_kernels.cpp), compiled once with the system g++ into a cached .so;
every entry point has a pure-JAX/numpy fallback so the native layer is an
accelerator, never a requirement.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading
from typing import Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "quant_kernels.cpp")
_CACHE_DIR = os.environ.get(
    "BIGDL_TPU_NATIVE_CACHE",
    os.path.join(tempfile.gettempdir(), "bigdl_tpu_native"))
_DISABLE_ENV = "BIGDL_TPU_DISABLE_NATIVE"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> Optional[str]:
    os.makedirs(_CACHE_DIR, exist_ok=True)
    src_mtime = os.path.getmtime(_SRC)
    so_path = os.path.join(_CACHE_DIR, f"quant_kernels_{int(src_mtime)}.so")
    if os.path.exists(so_path):
        return so_path
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
           "-pthread", _SRC, "-o", so_path + ".tmp"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(so_path + ".tmp", so_path)
        return so_path
    except (subprocess.SubprocessError, OSError):
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    """The native library, or None (disabled / no compiler)."""
    global _lib, _tried
    if os.environ.get(_DISABLE_ENV):
        return None
    with _lock:
        if _tried:
            return _lib
        _tried = True
        path = _build()
        if path is None:
            return None
        lib = ctypes.CDLL(path)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i8p = ctypes.POINTER(ctypes.c_int8)
        f32p = ctypes.POINTER(ctypes.c_float)
        i64 = ctypes.c_int64
        lib.bigdl_quantize_q4_0.argtypes = [f32p, i64, i64, u8p, f32p]
        lib.bigdl_quantize_q8_0.argtypes = [f32p, i64, i64, i8p, f32p]
        lib.bigdl_dequantize_q4_0.argtypes = [u8p, f32p, i64, i64, f32p]
        lib.bigdl_repack_gguf_q4_0.argtypes = [u8p, i64, i64, u8p, f32p]
        _lib = lib
        return _lib


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def quantize_native(w_kn: np.ndarray, qtype: str):
    """Quantize [K, N] f32 (K % 32 == 0) natively.

    Returns (data, scale_f32) numpy arrays in QTensor field layout, or None
    when the native path is unavailable/unsupported (caller falls back to
    ops/quant.quantize)."""
    lib = get_lib()
    if lib is None or qtype not in ("sym_int4", "sym_int8"):
        return None
    w = np.ascontiguousarray(w_kn, np.float32)
    k, n = w.shape
    if k % 32:
        return None
    scale = np.empty((k // 32, n), np.float32)
    if qtype == "sym_int4":
        data = np.empty((k // 2, n), np.uint8)
        lib.bigdl_quantize_q4_0(_ptr(w, ctypes.c_float), k, n,
                                _ptr(data, ctypes.c_uint8),
                                _ptr(scale, ctypes.c_float))
    else:
        data = np.empty((k, n), np.int8)
        lib.bigdl_quantize_q8_0(_ptr(w, ctypes.c_float), k, n,
                                _ptr(data, ctypes.c_int8),
                                _ptr(scale, ctypes.c_float))
    return data, scale


def dequantize_q4_0_native(data: np.ndarray, scale_f32: np.ndarray):
    lib = get_lib()
    if lib is None:
        return None
    data = np.ascontiguousarray(data, np.uint8)
    scale = np.ascontiguousarray(scale_f32, np.float32)
    k2, n = data.shape
    out = np.empty((k2 * 2, n), np.float32)
    lib.bigdl_dequantize_q4_0(_ptr(data, ctypes.c_uint8),
                              _ptr(scale, ctypes.c_float), k2 * 2, n,
                              _ptr(out, ctypes.c_float))
    return out


def repack_gguf_q4_0_native(blocks: np.ndarray, n_rows: int, k: int):
    """GGUF q4_0 raw blocks -> (data [K/2, N], scale [K/32, N] f32)."""
    lib = get_lib()
    if lib is None:
        return None
    blocks = np.ascontiguousarray(blocks, np.uint8)
    data = np.empty((k // 2, n_rows), np.uint8)
    scale = np.empty((k // 32, n_rows), np.float32)
    lib.bigdl_repack_gguf_q4_0(_ptr(blocks, ctypes.c_uint8), n_rows, k,
                               _ptr(data, ctypes.c_uint8),
                               _ptr(scale, ctypes.c_float))
    return data, scale
