"""GPTQ / AWQ checkpoint ingestion: repack to asym_int4 QTensors.

Equivalent of the reference's quantized-checkpoint ingestion
(reference transformers/model.py:237-283 detects GPTQ/AWQ configs;
convert.py:122-188 `convert_gptq` repacks `QuantLinearCudaOld`/
`WQLinear_GEMM` modules into ggml asym_int4; awq/linear.py defines the AWQ
packing; gptq/convert/convert_gptq_to_ggml.py is the offline variant).

Both formats store per-group asymmetric 4-bit: w = (code - zero) * scale.
Our asym_int4 is w = code * scale + min with min = -zero * scale, so the
repack is EXACT whenever the group size is a multiple of our block (32):
group scales/zeros are repeated down to block granularity, codes are
re-packed bytes — no dequantize/requantize round trip.

Layouts handled:
- GPTQ (AutoGPTQ): qweight int32 [K/8, N], 8 codes per int32 along K
  (low nibble first); qzeros int32 [K/G, N/8] packed along N; scales f16
  [K/G, N]; g_idx [K] must be the trivial arange//G order (actorder
  checkpoints fall back to an error). v1 checkpoints store zero-1
  (the famous +1); v2 ("checkpoint_format": "gptq_v2") stores zero.
- AWQ (GEMM): qweight int32 [K, N/8] packed along N with the interleaved
  order [0, 2, 4, 6, 1, 3, 5, 7]; qzeros likewise; scales f16 [K/G, N].
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Tuple

import jax.numpy as jnp
import numpy as np

AWQ_ORDER = np.array([0, 2, 4, 6, 1, 3, 5, 7])


def _unpack_int32_nibbles_rows(qw: np.ndarray) -> np.ndarray:
    """GPTQ qweight [K/8, N] int32 -> codes [K, N] uint8 (K-major)."""
    k8, n = qw.shape
    shifts = (4 * np.arange(8, dtype=np.uint32))[None, :, None]
    codes = (qw.astype(np.uint32)[:, None, :] >> shifts) & 0xF
    return codes.reshape(k8 * 8, n).astype(np.uint8)


def _unpack_int32_nibbles_cols(qz: np.ndarray, order=None) -> np.ndarray:
    """[R, C/8] int32 -> [R, C] uint8 (N-major, optional interleave)."""
    r, c8 = qz.shape
    shifts = (4 * np.arange(8, dtype=np.uint32))[None, None, :]
    z = (qz.astype(np.uint32)[:, :, None] >> shifts) & 0xF   # [R, C/8, 8]
    if order is not None:
        inv = np.empty_like(order)
        inv[order] = np.arange(8)
        z = z[:, :, inv]
    return z.reshape(r, c8 * 8).astype(np.uint8)


def _pack4_np(codes: np.ndarray) -> np.ndarray:
    """[K, N] uint8 codes -> our split-block packed [K/2, N] (block 32)."""
    k, n = codes.shape
    blk = codes.reshape(k // 32, 32, n)
    return (blk[:, :16] | (blk[:, 16:] << 4)).reshape(k // 2, n)


def _to_qtensor(codes: np.ndarray, scales: np.ndarray, zeros: np.ndarray,
                group: int):
    """codes [K,N], scales/zeros [K/G, N] -> asym_int4 QTensor [K, N]."""
    from bigdl_tpu.ops.quant import QTensor

    k, n = codes.shape
    if group % 32:
        raise ValueError(f"group_size {group} is not a multiple of 32")
    rep = group // 32
    scale_b = np.repeat(scales.astype(np.float32), rep, axis=0)
    zero_b = -zeros.astype(np.float32) * scales.astype(np.float32)
    zero_b = np.repeat(zero_b, rep, axis=0)
    return QTensor(
        jnp.asarray(_pack4_np(codes)),
        jnp.asarray(scale_b).astype(jnp.bfloat16),
        jnp.asarray(zero_b).astype(jnp.bfloat16),
        "asym_int4", (k, n))


def _build_gptq(buf: Dict[str, np.ndarray], group: int,
                zero_plus_one: bool):
    codes = _unpack_int32_nibbles_rows(buf["qweight"])
    k, n = codes.shape
    g = group if group > 0 else k
    if "g_idx" in buf:
        expect = np.arange(k, dtype=np.int64) // g
        if not np.array_equal(np.asarray(buf["g_idx"], np.int64), expect):
            raise NotImplementedError(
                "GPTQ act-order (non-trivial g_idx) checkpoints are not "
                "supported; re-quantize without desc_act")
    zeros = _unpack_int32_nibbles_cols(buf["qzeros"]).astype(np.int32)
    if zero_plus_one:
        zeros = zeros + 1
    return _to_qtensor(codes, np.asarray(buf["scales"]), zeros, g)


def _build_awq(buf: Dict[str, np.ndarray], group: int):
    codes = _unpack_int32_nibbles_cols(buf["qweight"], AWQ_ORDER)  # [K, N]
    zeros = _unpack_int32_nibbles_cols(buf["qzeros"], AWQ_ORDER)
    return _to_qtensor(codes, np.asarray(buf["scales"]),
                       zeros.astype(np.int32), group)


def detect_quant_config(hf_config: Dict[str, Any]):
    """(method, group_size, zero_plus_one) or None."""
    qc = hf_config.get("quantization_config")
    if not qc:
        return None
    method = qc.get("quant_method")
    if method not in ("gptq", "awq"):
        return None
    if int(qc.get("bits", 4)) != 4:
        raise NotImplementedError(
            f"{method} bits={qc.get('bits')} not supported (4 only)")
    group = int(qc.get("group_size", 128))
    v2 = qc.get("checkpoint_format") == "gptq_v2"
    return method, group, not v2


def repack_stream(
    tensors: Iterator[Tuple[str, np.ndarray]],
    method: str,
    group: int,
    zero_plus_one: bool = True,
) -> Iterator[Tuple[str, Any]]:
    """Transform a GPTQ/AWQ tensor stream into dense-weight-style names.

    (module.qweight, module.qzeros, module.scales[, module.g_idx]) triples
    are buffered and emitted as a single (module.weight, QTensor); all
    other tensors pass through. Feed the result to any family converter —
    the conversion engine passes QTensor leaves through unchanged.
    """
    bufs: Dict[str, Dict[str, np.ndarray]] = {}
    need = {"qweight", "qzeros", "scales"}
    for name, w in tensors:
        base, _, leaf = name.rpartition(".")
        if leaf in ("qweight", "qzeros", "scales", "g_idx"):
            buf = bufs.setdefault(base, {})
            buf[leaf] = np.asarray(w)
            if need.issubset(buf):
                if method == "gptq":
                    # wait one more tensor in case g_idx follows scales
                    if "g_idx" not in buf and "g_idx_pending" not in buf:
                        buf["g_idx_pending"] = True
                        continue
                yield base + ".weight", (
                    _build_gptq(buf, group, zero_plus_one)
                    if method == "gptq" else _build_awq(buf, group))
                del bufs[base]
        else:
            yield name, w
    # modules whose g_idx never arrived
    for base, buf in list(bufs.items()):
        if need.issubset(buf):
            yield base + ".weight", (
                _build_gptq(buf, group, zero_plus_one)
                if method == "gptq" else _build_awq(buf, group))
