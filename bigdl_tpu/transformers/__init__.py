"""User API layer (the reference's `ipex_llm.transformers` equivalent)."""

from bigdl_tpu.transformers.model import (  # noqa: F401
    AutoModel,
    AutoModelForCausalLM,
    TpuCausalLM,
)
from bigdl_tpu.transformers.lowbit_io import (  # noqa: F401
    load_low_bit,
    save_low_bit,
)
from bigdl_tpu.transformers.seq2seq import (  # noqa: F401
    AutoModelForSeq2SeqLM,
    AutoModelForSpeechSeq2Seq,
    TpuSeq2SeqLM,
    TpuSpeechSeq2Seq,
)
from bigdl_tpu.transformers.bert_heads import (  # noqa: F401
    AutoModelForMaskedLM,
    AutoModelForMultipleChoice,
    AutoModelForNextSentencePrediction,
    AutoModelForQuestionAnswering,
    AutoModelForSequenceClassification,
    AutoModelForTokenClassification,
)
from bigdl_tpu.transformers.embedder import BertEmbedder  # noqa: F401
