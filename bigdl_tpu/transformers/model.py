"""User-facing model API: the reference's Auto* façade, TPU-native.

Mirrors `ipex_llm.transformers.AutoModelForCausalLM.from_pretrained(
load_in_4bit=True / load_in_low_bit="nf4")` (reference transformers/
model.py:104-336), `save_low_bit`/`load_low_bit` (model.py:56, 465), and the
`generate()` entry point — except nothing is monkey-patched: from_pretrained
streams HF safetensors straight into a quantized JAX pytree (one tensor on
host at a time) and returns a `TpuCausalLM` owning compiled prefill/decode
executables.
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Dict, Optional

import numpy as np

from bigdl_tpu.generation import GenerationConfig, GenerationStats, Generator
from bigdl_tpu.models.registry import FamilyAdapter, get_family
from bigdl_tpu.ops.quant import FLOAT_QTYPES, get_qtype
from bigdl_tpu.transformers import lowbit_io
from bigdl_tpu.utils.hf import iter_hf_tensors, load_hf_config

_TOKENIZER_FILES = (
    "tokenizer.json", "tokenizer.model", "tokenizer_config.json",
    "special_tokens_map.json", "vocab.json", "merges.txt",
    "generation_config.json",
)


def _resolve_hub_path(path: str, model_hub: str) -> str:
    """`model_hub="modelscope"` resolves a repo id through ModelScope's
    snapshot_download (reference model.py:139-150); "huggingface" (the
    default) passes the path through — HF repo ids resolve inside
    utils/hf.py. Local paths bypass the hub either way."""
    if model_hub not in ("huggingface", "modelscope"):
        raise ValueError(
            "model_hub must be 'huggingface' or 'modelscope', got "
            f"{model_hub!r}")
    if model_hub == "modelscope" and not os.path.exists(path):
        try:
            from modelscope.hub.snapshot_download import snapshot_download
        except ImportError as e:
            raise ImportError(
                "model_hub='modelscope' needs the `modelscope` package "
                "(pip install modelscope), or pass a local path") from e
        return snapshot_download(path)
    return path


def _prepack(params: Any):
    """Load-time weight prepacking (ops/quant.prepack_tree): retile
    QTensor planes into the decode kernels' layout once, at load. The
    decode GEMV then loads int4 natively instead of burning the VPU on
    nibble unpacking (see ops/pallas/dequant_matmul._gemv_kernel_mxu).
    save_low_bit repacks to canonical. Returns (params, report)."""
    from bigdl_tpu.ops.quant import prepack_tree

    return prepack_tree(params)


def _maybe_mxu_layout(params: Any) -> Any:
    """Back-compat shim over `_prepack` (report dropped) — the prepack
    flag subsumes the older mxu_layout knob."""
    return _prepack(params)[0]


def _maybe_merge(params: Any, cfg: Any, family: FamilyAdapter,
                 enable: bool) -> Any:
    """Apply merged-QKV / merged-gate-up weight surgery (the reference's
    `_optimize_pre`, transformers/convert.py:529-640) for generalized-
    decoder families. Exact (block quant is per-column); families with
    custom forwards (rwkv/chatglm-v1/yuan/encoder-decoders) keep their
    own layouts. Load with merge_projections=False for the split layout
    (adapter training targets / explicit-TP sharding need it)."""
    from bigdl_tpu.models import llama as llama_mod

    if family.forward is not llama_mod.forward:
        return params
    if not enable:
        # a low-bit dir saved from a default (merged) load carries the
        # merged layout — merge_projections=False must UNDO it, not just
        # skip merging, or the split-layout consumers (attach_lora,
        # shard_params_tp) dead-end on their own advice
        return llama_mod.unmerge_projections(params, cfg)
    return llama_mod.merge_projections(params, cfg)


class TpuCausalLM:
    """A loaded (possibly quantized) causal LM + compiled generation."""

    def __init__(
        self,
        params: Any,
        cfg: Any,
        family: FamilyAdapter,
        hf_config: Dict[str, Any],
        qtype: Optional[str],
        model_path: Optional[str] = None,
        max_seq: int = 2048,
        kv_quantized: bool = False,
        kv_cache_dtype: Optional[str] = None,
    ):
        from bigdl_tpu.ops.kvcache import resolve_kv_cache_dtype

        self.params, self.prepack_report = _prepack(params)
        self.config = cfg
        self.family = family
        self.hf_config = hf_config
        self.qtype = qtype
        self.model_path = model_path
        self.max_seq = max_seq
        self.kv_cache_dtype = resolve_kv_cache_dtype(
            kv_cache_dtype if kv_cache_dtype is not None else kv_quantized)
        self.kv_quantized = self.kv_cache_dtype != "bf16"
        self.draft_params: Any = None   # set when loaded with speculative=True
        # load-time quantization-error attribution
        # (observability/quality.py AttributionReport): populated by
        # from_pretrained when conversion ran under an attribution
        # collector; None for float loads, load_low_bit (no pre-quant
        # reference weights exist), and GGUF passthrough
        self.quality_report: Any = None
        self._generator: Optional[Generator] = None
        # packed weight bytes into the process memory ledger at build
        # time (postmortems / GET /v1/memory / bench reports read it);
        # best-effort — accounting never gates a load
        try:
            from bigdl_tpu.observability.memory import (default_ledger,
                                                        tree_nbytes)

            default_ledger().register(
                "weights", "causal_lm", tree_nbytes(self.params),
                qtype=qtype, family=getattr(family, "name",
                                            type(family).__name__))
            if self.prepack_report.get("qtensors"):
                default_ledger().register(
                    "weights", "prepack",
                    self.prepack_report.get("bytes_packed", 0),
                    **{k: v for k, v in self.prepack_report.items()
                       if k != "bytes_packed"})
        except Exception:
            pass

    # -- generation ---------------------------------------------------------
    @property
    def generator(self) -> Generator:
        if self._generator is None:
            self._generator = Generator(
                self.params, self.config,
                forward_fn=self.family.forward,
                prefill_fn=self.family.prefill,
                max_seq=self.max_seq,
                kv_cache_dtype=self.kv_cache_dtype,
                new_cache_fn=self.family.new_cache,
                recurrent=self.family.is_recurrent,
            )
        return self._generator

    def generate(
        self,
        input_ids,
        max_new_tokens: int = 32,
        do_sample: bool = False,
        temperature: float = 1.0,
        top_k: int = 0,
        top_p: float = 1.0,
        eos_token_id: Optional[int] = None,
        seed: int = 0,
        stats: Optional[GenerationStats] = None,
        gamma: int = 4,
        th_stop_draft: float = 0.8,
        auto_th_stop_draft: bool = True,
        prompt_lookup: bool = False,
        ngram: int = 2,
        spec_stats=None,
        visual=None,     # (vidx [B,S], vemb [Nv,D]) — multimodal prefill
        num_beams: int = 1,
        length_penalty: float = 1.0,
        **_ignored,
    ) -> np.ndarray:
        """HF-style generate: returns [B, prompt+new] (prompt included).

        When the model was loaded with speculative=True, decoding runs
        draft/verify speculation (bigdl_tpu.speculative) transparently —
        the reference patches GenerationMixin.generate the same way
        (speculative.py:42-103)."""
        ids = np.asarray(input_ids, np.int32)
        if ids.ndim == 1:
            ids = ids[None]
        if eos_token_id is None:
            eos_token_id = self.hf_config.get("eos_token_id")
            if isinstance(eos_token_id, list):
                eos_token_id = eos_token_id[0]
        # prompt-lookup speculation: n-gram drafts from the context, no
        # draft model, exact greedy output (beyond the reference)
        if (prompt_lookup and ids.shape[0] == 1 and visual is None
                and num_beams <= 1 and not do_sample
                and not self.family.is_recurrent):
            from bigdl_tpu.speculative import prompt_lookup_generate

            new = prompt_lookup_generate(
                self.params, self.config, ids,
                family_forward=self.family.forward,
                family_prefill=self.family.prefill,
                new_cache=self.family.new_cache,
                max_new_tokens=max_new_tokens,
                gamma=gamma,
                ngram=ngram,
                eos_token_id=eos_token_id,
                max_seq=self.max_seq,
                kv_cache_dtype=self.kv_cache_dtype,
                stats=spec_stats,
            )
            return np.concatenate([ids, new], axis=1)
        # beam search preempts speculation: beams change WHICH sequence
        # is returned (semantics), speculation only changes latency
        if (self.draft_params is not None and ids.shape[0] == 1
                and visual is None and num_beams <= 1):
            from bigdl_tpu.speculative import speculative_generate

            new = speculative_generate(
                self.params, self.draft_params, self.config, self.config,
                ids,
                family_forward=self.family.forward,
                family_prefill=self.family.prefill,
                new_cache=self.family.new_cache,
                max_new_tokens=max_new_tokens,
                gamma=gamma,
                do_sample=do_sample,
                temperature=temperature,
                top_k=top_k,
                top_p=top_p,
                eos_token_id=eos_token_id,
                max_seq=self.max_seq,
                seed=seed,
                kv_cache_dtype=self.kv_cache_dtype,
                th_stop_draft=th_stop_draft,
                auto_th_stop_draft=auto_th_stop_draft,
                stats=spec_stats,
            )
            return np.concatenate([ids, new], axis=1)
        if num_beams > 1:
            if visual is not None or do_sample:
                raise NotImplementedError(
                    "num_beams > 1 is greedy beam search (no sampling, "
                    "no multimodal prefill yet)")
            from bigdl_tpu.generation import beam_search

            new = beam_search(
                self.params, self.config, self.family.forward, ids,
                self.family.new_cache, num_beams=num_beams,
                max_new_tokens=max_new_tokens, max_seq=self.max_seq,
                length_penalty=length_penalty, eos_token_id=eos_token_id,
                prefill_fn=self.family.prefill)
            return np.concatenate([ids, new], axis=1)
        gen = GenerationConfig(
            max_new_tokens=max_new_tokens, temperature=temperature,
            top_k=top_k, top_p=top_p, do_sample=do_sample,
            eos_token_id=eos_token_id, seed=seed)
        new = self.generator.generate(ids, gen, stats=stats, visual=visual)
        return np.concatenate([ids, new], axis=1)

    def generate_stream(
        self,
        input_ids,
        max_new_tokens: int = 32,
        do_sample: bool = False,
        temperature: float = 1.0,
        top_k: int = 0,
        top_p: float = 1.0,
        eos_token_id: Optional[int] = None,
        seed: int = 0,
        **_ignored,
    ):
        """Streaming generate: yields ONE new token id (int, batch 1) per
        step — the TextIteratorStreamer-equivalent surface the langchain/
        llamaindex/FastChat integrations build their callbacks on."""
        ids = np.asarray(input_ids, np.int32)
        if ids.ndim == 1:
            ids = ids[None]
        if ids.shape[0] != 1:
            raise ValueError("generate_stream is a batch-1 surface")
        if eos_token_id is None:
            eos_token_id = self.hf_config.get("eos_token_id")
            if isinstance(eos_token_id, list):
                eos_token_id = eos_token_id[0]
        gen = GenerationConfig(
            max_new_tokens=max_new_tokens, temperature=temperature,
            top_k=top_k, top_p=top_p, do_sample=do_sample,
            eos_token_id=eos_token_id, seed=seed)
        for tok in self.generator.stream(ids, gen):
            t = int(tok[0])
            yield t
            if eos_token_id is not None and t == eos_token_id:
                return

    # -- persistence --------------------------------------------------------
    def save_low_bit(self, path: str) -> None:
        """Persist quantized weights + config (+tokenizer files if known).
        The canonical split-block packing is the interchange format —
        int4-dtype (MXU layout) weights repack before writing."""
        from bigdl_tpu.ops.quant import tree_from_mxu_layout

        lowbit_io.save_low_bit(
            tree_from_mxu_layout(self.params), path,
            config=self.hf_config,
            family=self.family.name,
            qtype=self.qtype,
            extra={"max_seq": self.max_seq},
        )
        if self.model_path and os.path.isdir(self.model_path):
            for fname in _TOKENIZER_FILES:
                src = os.path.join(self.model_path, fname)
                if os.path.exists(src):
                    shutil.copy(src, os.path.join(path, fname))


class TpuQwenVLCausalLM(TpuCausalLM):
    """Qwen-VL: the qwen1 text decoder + the ViT/resampler vision tower
    (models/qwen_vl.py; reference transformers/models/qwen_vl.py +
    convert.py:696-711). `generate(images=...)` accepts paths / PIL
    images / pixel arrays; with no `images`, in-band image paths in the
    token stream (the Qwen-VL tokenizer protocol) are decoded and loaded.
    """

    visual_cfg = None            # set by _attach_qwen_vl
    _encode_jit = None

    def encode_images(self, images) -> np.ndarray:
        """images -> [N, n_queries, hidden] visual features.

        A float [N, 3, S, S] array is taken as ALREADY CLIP-normalized
        pixels; uint8 / NHWC / list inputs go through preprocess_images
        (resize + /255 + CLIP mean/std)."""
        import functools

        import jax
        import jax.numpy as jnp

        from bigdl_tpu.models import qwen_vl as QV

        arr = np.asarray(images) if not isinstance(images, (list, tuple)) \
            else None
        if (arr is not None and arr.ndim == 4 and
                np.issubdtype(arr.dtype, np.floating)):
            if arr.shape[1] != 3:
                raise ValueError(
                    f"float pixel batches must be [N, 3, S, S] "
                    f"CLIP-normalized (got {arr.shape}); pass uint8 / "
                    "PIL / paths for automatic preprocessing")
            pixels = arr.astype(np.float32)
        elif arr is not None and arr.ndim == 4:
            pixels = QV.preprocess_images(list(arr), self.visual_cfg)
        else:
            pixels = QV.preprocess_images(images, self.visual_cfg)
        if self._encode_jit is None:
            from bigdl_tpu.observability.compile_watch import tracked_jit

            self._encode_jit = tracked_jit(
                "qwen_vl_encode_images", functools.partial(
                    QV.encode_images, vcfg=self.visual_cfg))
        return np.asarray(self._encode_jit(self.params["visual"],
                                           pixels=jnp.asarray(pixels)))

    def generate(self, input_ids, images=None, **kw) -> np.ndarray:
        from bigdl_tpu.models import qwen_vl as QV

        ids = np.asarray(input_ids, np.int32)
        if ids.ndim == 1:
            ids = ids[None]
        vcfg = self.visual_cfg
        if images is not None and (isinstance(images, str)
                                   or not hasattr(images, "__len__")):
            images = [images]        # single path / PIL image
        if images is None and (ids == vcfg.image_start_id).any():
            images = QV.extract_image_paths(ids, vcfg)
            if any(p == "" for p in images):
                raise ValueError(
                    "prompt contains image spans with no in-band paths; "
                    "pass the images via generate(images=...)")
        if images is None or (hasattr(images, "__len__")
                              and len(images) == 0):
            return super().generate(ids, **kw)
        vidx, n_img = QV.visual_token_index(ids, vcfg)
        n_given = len(images) if hasattr(images, "__len__") else None
        if n_given is not None and n_given != n_img:
            raise ValueError(
                f"{n_img} image span(s) in the prompt but {n_given} "
                "image(s) supplied")
        feats = self.encode_images(images)
        if feats.shape[0] != n_img:
            raise ValueError(
                f"{n_img} image span(s) in the prompt but {feats.shape[0]} "
                "image(s) supplied")
        vemb = feats.reshape(-1, feats.shape[-1])
        return super().generate(ids, visual=(vidx, vemb), **kw)


def _attach_qwen_vl(model: TpuCausalLM) -> TpuCausalLM:
    """Upgrade a qwen1 TpuCausalLM to the VL facade when the checkpoint
    carries a vision tower (config['visual'] + params['visual'])."""
    if "visual" not in model.hf_config or "visual" not in model.params:
        return model
    from bigdl_tpu.models.qwen_vl import VisualConfig

    model.__class__ = TpuQwenVLCausalLM
    model.visual_cfg = VisualConfig.from_hf(model.hf_config["visual"])
    model._encode_jit = None
    return model


def _resolve_qtype(load_in_4bit: bool,
                   load_in_low_bit: Optional[str]) -> Optional[str]:
    if load_in_low_bit is not None:
        from bigdl_tpu.ops.quant import is_valid_qtype

        if (load_in_low_bit not in FLOAT_QTYPES
                and not is_valid_qtype(load_in_low_bit)):
            get_qtype(load_in_low_bit)  # raises with the known-qtype list
        return load_in_low_bit
    if load_in_4bit:
        return "sym_int4"
    return None


class _BaseAutoModelClass:
    """from_pretrained / load_low_bit, shared by the Auto* classes."""

    @classmethod
    def from_pretrained(
        cls,
        pretrained_model_name_or_path: str,
        *,
        load_in_4bit: bool = False,
        load_in_low_bit: Optional[str] = None,
        optimize_model: bool = True,   # accepted for API parity
        modules_to_not_convert=(),
        max_seq: Optional[int] = None,
        quantize_kv_cache: Optional[bool] = None,
        kv_cache_dtype: Optional[str] = None,
        speculative: bool = False,
        embedding_qtype: Optional[str] = None,
        imatrix: Optional[Any] = None,
        merge_projections: bool = True,
        model_hub: str = "huggingface",
        **_ignored,
    ) -> TpuCausalLM:
        from bigdl_tpu.config import default_kv_cache_dtype
        from bigdl_tpu.config import flags
        from bigdl_tpu.ops.kvcache import resolve_kv_cache_dtype

        if kv_cache_dtype is None:
            if quantize_kv_cache is None:
                # neither kwarg given: env/flag defaults decide
                kv_cache_dtype = default_kv_cache_dtype()
            else:
                kv_cache_dtype = resolve_kv_cache_dtype(quantize_kv_cache)
        else:
            kv_cache_dtype = resolve_kv_cache_dtype(kv_cache_dtype)
        path = _resolve_hub_path(pretrained_model_name_or_path, model_hub)
        if lowbit_io.is_low_bit_dir(path):
            if speculative:
                raise ValueError(
                    "speculative=True needs an original checkpoint to build "
                    "the low-bit draft (reference model.py:323-331); this "
                    "path is an already-quantized save_low_bit directory")
            if imatrix is not None:
                raise ValueError(
                    "imatrix applies at quantization time; this path is an "
                    "already-quantized save_low_bit directory — re-convert "
                    "from the original checkpoint with the imatrix")
            # max_seq=None lets the manifest's saved value win
            return cls.load_low_bit(path, max_seq=max_seq,
                                    kv_cache_dtype=kv_cache_dtype,
                                    merge_projections=merge_projections)
        if os.path.isfile(path) and path.endswith(".gguf"):
            if speculative:
                raise ValueError(
                    "speculative=True is not supported for GGUF inputs "
                    "(already low-bit); load the original HF checkpoint")
            if imatrix is not None:
                raise ValueError(
                    "imatrix applies at quantization time; GGUF weights "
                    "are already quantized — use the original HF "
                    "checkpoint with load_in_low_bit + imatrix")
            # direct GGUF ingestion (reference gguf/api.py:31)
            from bigdl_tpu.gguf import load_gguf

            params, hf_config, tok_info = load_gguf(path)
            archs = hf_config.get("architectures") or ["?"]
            family = get_family(archs[0], hf_config)
            cfg = family.config_from_hf(hf_config)
            params = _maybe_merge(params, cfg, family, merge_projections)
            model = TpuCausalLM(params, cfg, family, hf_config,
                                qtype="gguf",
                                model_path=os.path.dirname(path),
                                max_seq=max_seq or 2048,
                                kv_cache_dtype=kv_cache_dtype)
            # vocab already parsed once; CLIs reconstruct a tokenizer from
            # this instead of re-reading the file
            model.gguf_tokenizer_info = tok_info
            return model
        max_seq = max_seq or flags().default_max_seq

        qtype = _resolve_qtype(load_in_4bit, load_in_low_bit)
        hf_config = load_hf_config(path)
        archs = hf_config.get("architectures") or ["?"]
        family = get_family(archs[0], hf_config)
        cfg = family.config_from_hf(hf_config)

        tensor_stream = iter_hf_tensors(path)
        # GPTQ/AWQ checkpoints: repack already-quantized modules directly
        # (reference model.py:237-283 + convert.py:122-188 convert_gptq)
        from bigdl_tpu.transformers.gptq_awq import (detect_quant_config,
                                                     repack_stream)

        qc = detect_quant_config(hf_config)
        if qc is not None:
            if qtype not in (None, "sym_int4", "asym_int4"):
                raise ValueError(
                    f"checkpoint is already {qc[0]}-quantized (asym_int4 "
                    f"after repack); conflicting load_in_low_bit={qtype!r}")
            if imatrix is not None:
                raise ValueError(
                    f"imatrix applies at quantization time; this "
                    f"{qc[0]}-quantized checkpoint repacks as-is — use "
                    "the original float checkpoint with load_in_low_bit "
                    "+ imatrix")
            method, group, plus_one = qc
            tensor_stream = repack_stream(tensor_stream, method, group,
                                          plus_one)
            qtype = "asym_int4"   # remaining dense linears match the ckpt

        if isinstance(imatrix, str):
            # llama.cpp imatrix file, importance-weighted quantization
            # (reference imatrix= kwarg, model.py:104 + utils.py:187-323)
            from bigdl_tpu.imatrix import load_imatrix

            imatrix = load_imatrix(imatrix)

        cvt_qtype = None if (qtype in FLOAT_QTYPES) else qtype
        visual_tensors: list = []
        if "visual" in hf_config and archs[0] == "QWenLMHeadModel":
            # tee the vision tensors out of the one disk pass — the
            # decoder conversion skips them, and a second full read of a
            # multi-GB checkpoint just for the tower would double load IO
            def _tee(stream, sink):
                for name, w in stream:
                    if name.startswith("transformer.visual."):
                        sink.append((name, np.asarray(w)))
                    else:
                        yield name, w
            tensor_stream = _tee(tensor_stream, visual_tensors)
        # quantization-error attribution: run the conversion under a
        # collector so every Acc.linear records SNR/max-abs-err/clip
        # saturation vs the pre-quant floats (observability/quality.py).
        # config.quality_enabled() == False skips the collector and the
        # per-tensor dequant round-trip entirely.
        from bigdl_tpu.config import quality_enabled
        from bigdl_tpu.observability.quality import collect_attribution

        quality_report = None
        if quality_enabled() and cvt_qtype is not None:
            with collect_attribution() as quality_report:
                params = family.convert_params(
                    tensor_stream, cfg, qtype=cvt_qtype,
                    modules_to_not_convert=tuple(modules_to_not_convert),
                    imatrix=imatrix)
        else:
            params = family.convert_params(
                tensor_stream, cfg, qtype=cvt_qtype,
                modules_to_not_convert=tuple(modules_to_not_convert),
                imatrix=imatrix)
        if embedding_qtype is not None:
            # LowBitEmbedding equivalent (reference embedding.py:77-114,
            # embedding_qtype kwarg at model.py:104)
            from bigdl_tpu.ops.embedding import quantize_embedding

            params["embed_tokens"] = quantize_embedding(
                params["embed_tokens"], embedding_qtype)
        if "visual" in hf_config and archs[0] == "QWenLMHeadModel":
            # Qwen-VL: the vision tensors were tee'd out of the one
            # conversion stream (reference convert.py:696-711)
            from bigdl_tpu.models.qwen_vl import (VisualConfig,
                                                  convert_visual_params)

            params["visual"] = convert_visual_params(
                iter(visual_tensors),
                VisualConfig.from_hf(hf_config["visual"]))
        params = _maybe_merge(params, cfg, family, merge_projections)
        model = TpuCausalLM(params, cfg, family, hf_config, qtype,
                            model_path=path, max_seq=max_seq,
                            kv_cache_dtype=kv_cache_dtype)
        if quality_report is not None and len(quality_report):
            model.quality_report = quality_report
        model = _attach_qwen_vl(model)
        if speculative:
            # self-speculation: same checkpoint as a sym_int4 draft
            # (reference model.py:323-331)
            if family.is_recurrent:
                raise ValueError(
                    "speculative=True is not supported for recurrent "
                    "(RWKV-style) families: verification rollback rewinds "
                    "a KV cache, and recurrent state cannot be rewound")
            if cvt_qtype == "sym_int4":
                # already low-bit: share the (possibly MXU-relayouted)
                # tree — the draft decode is the latency-critical loop
                model.draft_params = model.params
            else:
                model.draft_params = _maybe_mxu_layout(_maybe_merge(
                    family.convert_params(
                        iter_hf_tensors(path), cfg, qtype="sym_int4",
                        modules_to_not_convert=tuple(
                            modules_to_not_convert)),
                    cfg, family, merge_projections))
        return model

    @classmethod
    def load_low_bit(cls, path: str, max_seq: Optional[int] = None,
                     quantize_kv_cache: bool = False,
                     kv_cache_dtype: Optional[str] = None,
                     merge_projections: bool = True,
                     **_ignored) -> TpuCausalLM:
        params, manifest = lowbit_io.load_low_bit(path)
        hf_config = manifest["config"]
        archs = hf_config.get("architectures") or ["?"]
        family = get_family(archs[0], hf_config)
        cfg = family.config_from_hf(hf_config)
        params = _maybe_merge(params, cfg, family, merge_projections)
        return _attach_qwen_vl(TpuCausalLM(
            params, cfg, family, hf_config,
            qtype=manifest.get(lowbit_io.MARKER),
            model_path=path,
            max_seq=max_seq or manifest.get("extra", {}).get("max_seq", 2048),
            kv_quantized=quantize_kv_cache,
            kv_cache_dtype=kv_cache_dtype,
        ))


class AutoModelForCausalLM(_BaseAutoModelClass):
    pass


class AutoModel(_BaseAutoModelClass):
    pass
