"""Quantized checkpoint serialization: save_low_bit / load_low_bit.

The reference persists quantized state dicts with a
`bigdl_transformers_low_bit` marker in config.json plus a key manifest
(reference transformers/model.py:56-92, 465-685; optimize.py:41-56).
Equivalent here: one directory with

  low_bit_weights.safetensors — every array leaf of the parameter pytree,
      flattened to "path.to.leaf" keys (QTensor fields as <name>#data,
      #scale, #zero, #aux). bfloat16 is stored as a uint16 view (safetensors
      numpy has no bf16) and restored via the manifest dtype.
  low_bit_manifest.json — pytree structure: per-leaf dtype + per-QTensor
      static metadata (qtype, logical shape), config dict, family name,
      the low_bit marker, and framework version.

Loading rebuilds the exact pytree on device with zero re-quantization work,
the fast path matching the reference's `load_low_bit`.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import __version__
from bigdl_tpu.ops.quant import QTensor

_WEIGHTS = "low_bit_weights.safetensors"
_MANIFEST = "low_bit_manifest.json"
MARKER = "bigdl_tpu_low_bit"


def _walk(tree: Any, prefix, arrays, meta):
    if isinstance(tree, dict):
        for k, v in tree.items():
            _walk(v, prefix + (str(k),), arrays, meta)
    elif isinstance(tree, QTensor):
        key = ".".join(prefix)
        meta[key] = {"kind": "qtensor", "qtype": tree.qtype,
                     "shape": list(tree.shape)}
        for field in ("data", "scale", "zero", "aux"):
            val = getattr(tree, field)
            if val is not None:
                arrays[f"{key}#{field}"] = val
    elif tree is None:
        pass
    else:
        key = ".".join(prefix)
        meta[key] = {"kind": "array"}
        arrays[key] = tree


def _to_numpy(x) -> Tuple[np.ndarray, str]:
    """Return (storable ndarray, logical dtype string).

    device_get can hand back a NON-contiguous host array (observed with
    bf16 over the tunneled TPU backend); safetensors serializes the raw
    buffer without honoring strides, so everything is made C-contiguous
    before the dtype reinterpret."""
    arr = np.ascontiguousarray(np.asarray(jax.device_get(x)))
    name = str(arr.dtype)
    if arr.dtype == jnp.bfloat16:
        return arr.view(np.uint16), "bfloat16"
    if arr.dtype in (jnp.float8_e5m2, jnp.float8_e4m3fn):
        return arr.view(np.uint8), name
    return arr, name


def _from_numpy(arr: np.ndarray, dtype: str) -> jax.Array:
    if dtype == "bfloat16":
        return jnp.asarray(arr.view(jnp.bfloat16))
    if dtype in ("float8_e5m2", "float8_e4m3fn"):
        return jnp.asarray(arr.view(jnp.dtype(dtype)))
    return jnp.asarray(arr)


def save_low_bit(
    params: Any,
    path: str,
    config: Optional[Dict[str, Any]] = None,
    family: Optional[str] = None,
    qtype: Optional[str] = None,
    extra: Optional[Dict[str, Any]] = None,
) -> None:
    """Persist a (possibly quantized) parameter pytree to `path`."""
    from safetensors.numpy import save_file

    os.makedirs(path, exist_ok=True)
    arrays: Dict[str, Any] = {}
    meta: Dict[str, Any] = {}
    _walk(params, (), arrays, meta)

    store: Dict[str, np.ndarray] = {}
    dtypes: Dict[str, str] = {}
    for k, v in arrays.items():
        store[k], dtypes[k] = _to_numpy(v)
    save_file(store, os.path.join(path, _WEIGHTS))

    manifest = {
        "format_version": 1,
        "bigdl_tpu_version": __version__,
        MARKER: qtype or "unknown",
        "family": family,
        "config": config or {},
        "leaves": meta,
        "dtypes": dtypes,
        "extra": extra or {},
    }
    with open(os.path.join(path, _MANIFEST), "w") as f:
        json.dump(manifest, f, indent=1)


def is_low_bit_dir(path: str) -> bool:
    return os.path.exists(os.path.join(path, _MANIFEST))


def load_manifest(path: str) -> Dict[str, Any]:
    with open(os.path.join(path, _MANIFEST)) as f:
        return json.load(f)


def load_low_bit_checked(
    path: str,
    accept_archs: Tuple[str, ...],
    class_name: str,
    imatrix: Any = None,
    required_keys: Tuple[str, ...] = (),
) -> Tuple[Any, Dict[str, Any], Dict[str, Any], Optional[str]]:
    """Manifest-first low-bit load for the facade classes: validates the
    saved architecture (and head keys) BEFORE deserializing weights, and
    rejects quantization-time kwargs, so a wrong-family multi-GB
    checkpoint is refused without touching its tensors.

    Returns (params, manifest, hf_config, qtype)."""
    if imatrix is not None:
        raise ValueError(
            "imatrix applies at quantization time; this path is an "
            "already-quantized save_low_bit directory — re-convert from "
            "the original checkpoint with the imatrix")
    manifest = load_manifest(path)
    hf_config = manifest["config"]
    archs = tuple(hf_config.get("architectures") or ("?",))
    if accept_archs and archs[0] not in accept_archs:
        raise ValueError(
            f"low-bit checkpoint at {path} was saved from {archs[0]!r}; "
            f"{class_name} supports {accept_archs}")
    missing = [k for k in required_keys
               if not any(leaf == k or leaf.startswith(f"{k}.")
                          for leaf in manifest["leaves"])]
    if missing:
        raise ValueError(
            f"low-bit checkpoint at {path} has no {missing} — saved from "
            f"a different task head than {class_name}")
    params, manifest = load_low_bit(path)
    return params, manifest, hf_config, manifest.get(MARKER)


def load_low_bit(path: str) -> Tuple[Any, Dict[str, Any]]:
    """Load (params pytree, manifest) saved by save_low_bit."""
    from safetensors.numpy import load_file

    manifest = load_manifest(path)
    store = load_file(os.path.join(path, _WEIGHTS))
    dtypes = manifest["dtypes"]

    def get(key):
        return _from_numpy(store[key], dtypes[key])

    params: Dict[str, Any] = {}
    for key, info in manifest["leaves"].items():
        parts = key.split(".")
        node = params
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        leaf_name = parts[-1]
        if info["kind"] == "qtensor":
            node[leaf_name] = QTensor(
                data=get(f"{key}#data"),
                scale=get(f"{key}#scale"),
                zero=get(f"{key}#zero") if f"{key}#zero" in store else None,
                qtype=info["qtype"],
                shape=tuple(info["shape"]),
                aux=get(f"{key}#aux") if f"{key}#aux" in store else None,
            )
        else:
            node[leaf_name] = get(key)
    return params, manifest
