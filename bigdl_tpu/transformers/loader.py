"""Uniform model loader for serving and benchmarks.

Equivalent of the reference's `transformers/loader.py:43-89` (`load_model`
used by FastChat serving and the benchmark harness; benchmark wrapping
injected via env there, via the `benchmark` flag here).
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple


def get_model_path(repo_id_or_path: str,
                   local_model_hub: Optional[str] = None) -> str:
    """Reference get_model_path (loader.py:89): map a repo id into a local
    hub directory when one is configured."""
    if local_model_hub:
        candidate = os.path.join(local_model_hub,
                                 repo_id_or_path.replace("/", os.sep))
        if os.path.exists(candidate):
            return candidate
        candidate = os.path.join(local_model_hub,
                                 repo_id_or_path.split("/")[-1])
        if os.path.exists(candidate):
            return candidate
    return repo_id_or_path


def load_model(
    model_path: str,
    device: str = "tpu",            # accepted for API parity; JAX decides
    low_bit: str = "sym_int4",
    max_seq: Optional[int] = None,
    benchmark: bool = False,
    **kwargs: Any,
) -> Tuple[Any, Any]:
    """Returns (model, tokenizer). `benchmark=True` wraps the model in
    BenchmarkWrapper (the reference injects it via env, loader.py:43-77)."""
    from bigdl_tpu.transformers.model import AutoModelForCausalLM

    model = AutoModelForCausalLM.from_pretrained(
        model_path, load_in_low_bit=low_bit, max_seq=max_seq, **kwargs)
    tokenizer = None
    try:
        from transformers import AutoTokenizer

        tokenizer = AutoTokenizer.from_pretrained(model_path,
                                                  trust_remote_code=True)
    except Exception:
        pass
    if benchmark:
        from bigdl_tpu.bench import BenchmarkWrapper

        model = BenchmarkWrapper(model)
    return model, tokenizer
