"""AutoModelForSpeechSeq2Seq: the encoder-decoder facade (Whisper).

Reference analog: ipex-llm's `AutoModelForSpeechSeq2Seq`
(transformers/model.py:688-725) — whisper quantized via optimize_model
(optimize.py:196) and driven through HF generate. Here loading streams the
checkpoint into a quantized pytree (models/whisper.py) and generation is a
jit-compiled encode + decode loop.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.models import whisper as W
from bigdl_tpu.observability.compile_watch import tracked_jit
from bigdl_tpu.ops.quant import FLOAT_QTYPES
from bigdl_tpu.utils.hf import iter_hf_tensors, load_hf_config


def _bucket_seq(n: int, cap: int) -> int:
    """Round a decoder-cache length up to a power-of-two bucket (capped
    at the learned position table) so cache init AND decode compile
    once per bucket instead of once per distinct
    ``forced + max_new_tokens`` sum — the length is a static jit arg
    and shapes the cache. Positions past the written prefix are masked
    by write position in attention, so the slack rows are inert."""
    b = 16
    while b < n:
        b *= 2
    return min(b, cap)


def _greedy_decode_loop(decode_fn, params, cfg, ids: np.ndarray,
                        cache, max_new_tokens: int, eos: int) -> np.ndarray:
    """Shared forced-prefix greedy loop (whisper + bart facades):
    prefill the forced ids, then argmax-decode with eos substitution.
    Returns [B, forced + new]."""
    logits, cache = decode_fn(params, cfg, jnp.asarray(ids), cache)
    tok = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
    out = [np.asarray(tok)]
    finished = out[0] == eos
    for _ in range(max_new_tokens - 1):
        if finished.all():
            break
        logits, cache = decode_fn(params, cfg, tok[:, None], cache)
        tok = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
        t = np.where(finished, eos, np.asarray(tok))
        out.append(t)
        finished |= t == eos
    return np.concatenate([ids, np.stack(out, axis=1)], axis=1)


class TpuSpeechSeq2Seq:
    """A loaded (possibly quantized) Whisper + compiled generation."""

    def __init__(self, params: Any, cfg: W.WhisperConfig,
                 hf_config: Dict[str, Any], qtype: Optional[str],
                 model_path: Optional[str] = None):
        self.params = params
        self.config = cfg
        self.hf_config = hf_config
        self.qtype = qtype
        self.model_path = model_path
        self._encode = tracked_jit("whisper_encode", W.encode,
                                   static_argnums=(1,))
        self._decode = tracked_jit("whisper_decode", W.decode_step,
                                   static_argnums=(1,),
                                   donate_argnums=(3,))
        self._init_cache = tracked_jit("whisper_init_cache",
                                       W.init_decoder_cache,
                                       static_argnums=(1, 3))

    def encode(self, input_features) -> jax.Array:
        mel = jnp.asarray(np.asarray(input_features, np.float32))
        if mel.ndim == 2:
            mel = mel[None]
        return self._encode(self.params, self.config, mel)

    def save_low_bit(self, path: str) -> None:
        """Persist the quantized pytree (reference: optimize_model attaches
        save_low_bit to ANY model incl. whisper, optimize.py:41-56)."""
        from bigdl_tpu.transformers import lowbit_io

        lowbit_io.save_low_bit(self.params, path, config=self.hf_config,
                               family="whisper", qtype=self.qtype)

    def generate(
        self,
        input_features,                   # [B, n_mels, T] log-mel
        decoder_input_ids=None,           # forced tokens; default start id
        max_new_tokens: int = 128,
        eos_token_id: Optional[int] = None,
        **_ignored,
    ) -> np.ndarray:
        """Greedy transcription. Returns [B, forced + new] token ids."""
        cfg = self.config
        enc_out = self.encode(input_features)
        b = enc_out.shape[0]
        if decoder_input_ids is None:
            decoder_input_ids = np.full((b, 1), cfg.decoder_start_token_id,
                                        np.int32)
        ids = np.asarray(decoder_input_ids, np.int32)
        if ids.ndim == 1:
            ids = ids[None]
        eos = cfg.eos_token_id if eos_token_id is None else eos_token_id
        if ids.shape[1] + max_new_tokens > cfg.max_target_positions:
            raise ValueError(
                f"forced tokens ({ids.shape[1]}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the decoder's "
                f"max_target_positions ({cfg.max_target_positions})")
        if max_new_tokens <= 0:
            return ids
        max_seq = _bucket_seq(ids.shape[1] + max_new_tokens,
                              cfg.max_target_positions)
        cache = self._init_cache(self.params, cfg, enc_out, max_seq)
        return _greedy_decode_loop(self._decode, self.params, cfg, ids,
                                   cache, max_new_tokens, eos)


class TpuSeq2SeqLM:
    """A loaded (possibly quantized) BART-family text seq2seq model."""

    def __init__(self, params: Any, cfg, hf_config: Dict[str, Any],
                 qtype: Optional[str], model_path: Optional[str] = None):
        from bigdl_tpu.models import bart as Bt

        self.params = params
        self.config = cfg
        self.hf_config = hf_config
        self.qtype = qtype
        self.model_path = model_path
        self._encode = tracked_jit("seq2seq_encode", Bt.encode,
                                   static_argnums=(1,))
        self._decode = tracked_jit("seq2seq_decode", Bt.decode_step,
                                   static_argnums=(1,),
                                   donate_argnums=(3,))
        self._init_cache = tracked_jit("seq2seq_init_cache",
                                       Bt.init_decoder_cache,
                                       static_argnums=(1, 3, 4))

    def save_low_bit(self, path: str) -> None:
        from bigdl_tpu.transformers import lowbit_io

        lowbit_io.save_low_bit(self.params, path, config=self.hf_config,
                               family="bart", qtype=self.qtype)

    def generate(
        self,
        input_ids,                        # [B, S] source tokens
        attention_mask=None,              # [B, S] 1=real (source padding)
        decoder_input_ids=None,
        max_new_tokens: int = 128,
        eos_token_id: Optional[int] = None,
        **_ignored,
    ) -> np.ndarray:
        """Greedy seq2seq generation. Returns [B, forced + new] ids."""
        cfg = self.config
        src = np.asarray(input_ids, np.int32)
        if src.ndim == 1:
            src = src[None]
        mask = (None if attention_mask is None
                else jnp.asarray(np.asarray(attention_mask, np.int32)))
        enc_out = self._encode(self.params, cfg, jnp.asarray(src), mask)
        b = src.shape[0]
        if decoder_input_ids is None:
            decoder_input_ids = np.full((b, 1), cfg.decoder_start_token_id,
                                        np.int32)
        ids = np.asarray(decoder_input_ids, np.int32)
        if ids.ndim == 1:
            ids = ids[None]
        if cfg.forced_bos_token_id is not None and ids.shape[1] == 1:
            # HF's ForcedBOSTokenLogitsProcessor forces bos at sequence
            # length 1 (bart-large-cnn style) whether or not the caller
            # supplied the start token; folding it into the prefix is
            # equivalent and keeps the decode loop force-free
            ids = np.concatenate(
                [ids, np.full((ids.shape[0], 1), cfg.forced_bos_token_id,
                              np.int32)], axis=1)
        eos = cfg.eos_token_id if eos_token_id is None else eos_token_id
        if ids.shape[1] + max_new_tokens > cfg.max_position_embeddings:
            raise ValueError(
                f"forced ({ids.shape[1]}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_position_embeddings "
                f"({cfg.max_position_embeddings})")
        if max_new_tokens <= 0:
            return ids
        max_seq = _bucket_seq(ids.shape[1] + max_new_tokens,
                              cfg.max_position_embeddings)
        cache = self._init_cache(self.params, cfg, enc_out,
                                 max_seq, False, mask)
        return _greedy_decode_loop(self._decode, self.params, cfg, ids,
                                   cache, max_new_tokens, eos)


class AutoModelForSeq2SeqLM:
    """Text encoder-decoder facade (the reference's tenth Auto class,
    transformers/model.py:701). BART-family checkpoints."""

    _ARCHS = ("BartForConditionalGeneration",)

    @classmethod
    def from_pretrained(
        cls,
        pretrained_model_name_or_path: str,
        load_in_4bit: bool = False,
        load_in_low_bit: Optional[str] = None,
        modules_to_not_convert=(),
        imatrix=None,
        model_hub: str = "huggingface",
        **_ignored,
    ) -> TpuSeq2SeqLM:
        from bigdl_tpu.models import bart as Bt
        from bigdl_tpu.transformers import lowbit_io
        from bigdl_tpu.transformers.model import (_resolve_hub_path,
                                                  _resolve_qtype)

        pretrained_model_name_or_path = _resolve_hub_path(
            pretrained_model_name_or_path, model_hub)

        path = pretrained_model_name_or_path
        if lowbit_io.is_low_bit_dir(path):
            params, _, hf_config, qt = lowbit_io.load_low_bit_checked(
                path, cls._ARCHS, "AutoModelForSeq2SeqLM", imatrix=imatrix)
            return TpuSeq2SeqLM(params, Bt.BartConfig.from_hf(hf_config),
                                hf_config, qt, model_path=path)
        hf_config = load_hf_config(path)
        archs = hf_config.get("architectures") or ["?"]
        if archs[0] not in cls._ARCHS:
            raise ValueError(
                f"AutoModelForSeq2SeqLM supports {cls._ARCHS}; got "
                f"{archs[0]!r} (whisper loads via "
                "AutoModelForSpeechSeq2Seq)")
        qtype = _resolve_qtype(load_in_4bit, load_in_low_bit)
        cfg = Bt.BartConfig.from_hf(hf_config)
        if isinstance(imatrix, str):
            from bigdl_tpu.imatrix import load_imatrix

            imatrix = load_imatrix(imatrix)
        cvt_qtype = None if qtype in FLOAT_QTYPES else qtype
        params = Bt.convert_hf_params(
            iter_hf_tensors(path), cfg, qtype=cvt_qtype,
            modules_to_not_convert=tuple(modules_to_not_convert),
            imatrix=imatrix)
        return TpuSeq2SeqLM(params, cfg, hf_config, qtype, model_path=path)


class AutoModelForSpeechSeq2Seq:
    """from_pretrained with the reference's low-bit kwargs (whisper)."""

    @classmethod
    def from_pretrained(
        cls,
        pretrained_model_name_or_path: str,
        load_in_4bit: bool = False,
        load_in_low_bit: Optional[str] = None,
        modules_to_not_convert=(),
        imatrix=None,
        model_hub: str = "huggingface",
        **_ignored,
    ) -> TpuSpeechSeq2Seq:
        from bigdl_tpu.transformers import lowbit_io
        from bigdl_tpu.transformers.model import (_resolve_hub_path,
                                                  _resolve_qtype)

        path = _resolve_hub_path(pretrained_model_name_or_path, model_hub)
        if lowbit_io.is_low_bit_dir(path):
            params, _, hf_config, qt = lowbit_io.load_low_bit_checked(
                path, ("WhisperForConditionalGeneration",),
                "AutoModelForSpeechSeq2Seq", imatrix=imatrix)
            return TpuSpeechSeq2Seq(
                params, W.WhisperConfig.from_hf(hf_config), hf_config, qt,
                model_path=path)
        hf_config = load_hf_config(path)
        archs = hf_config.get("architectures") or ["?"]
        if archs[0] != "WhisperForConditionalGeneration":
            raise ValueError(
                f"AutoModelForSpeechSeq2Seq supports whisper checkpoints; "
                f"got {archs[0]!r}")
        qtype = _resolve_qtype(load_in_4bit, load_in_low_bit)
        cfg = W.WhisperConfig.from_hf(hf_config)
        if isinstance(imatrix, str):
            from bigdl_tpu.imatrix import load_imatrix

            imatrix = load_imatrix(imatrix)
        cvt_qtype = None if qtype in FLOAT_QTYPES else qtype
        params = W.convert_hf_params(
            iter_hf_tensors(path), cfg, qtype=cvt_qtype,
            modules_to_not_convert=tuple(modules_to_not_convert),
            imatrix=imatrix)
        return TpuSpeechSeq2Seq(params, cfg, hf_config, qtype,
                                model_path=path)
