"""Quantized text-embedding facade (BERT-family encoders).

Reference analog: bert served through `optimize_model` +
`TransformersEmbeddings` (reference transformers/models/bert.py:42-147;
langchain/embeddings/bigdlllm.py). `BertEmbedder` shares the bert loader
with the task-head Auto classes (transformers/bert_heads.py) and adds the
`embed_texts` API the langchain/llamaindex integrations build on.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np

from bigdl_tpu.models import bert as B
from bigdl_tpu.transformers.bert_heads import _BertTaskModel


class BertEmbedder(_BertTaskModel):
    """A loaded (possibly quantized) BERT + compiled embedding forward."""

    HEAD_FN = staticmethod(B.forward)     # (last_hidden, pooled)
    ACCEPT_ARCHS = ("BertModel", "BertForMaskedLM",
                    "BertForSequenceClassification", "BertForPreTraining")

    def forward(self, input_ids, attention_mask=None, token_type_ids=None):
        """(last_hidden, pooled) as JAX arrays (unlike the task heads,
        which return numpy — downstream embedding code often keeps
        computing on device)."""
        ids, am, tt = self._ids(input_ids, attention_mask, token_type_ids)
        return self._fwd(self.params, self.config, ids, am, tt)

    __call__ = forward

    def embed(self, input_ids, attention_mask=None,
              pooling: str = "mean") -> np.ndarray:
        """Sentence embeddings [B, D] (pooling: "mean" | "cls")."""
        ids = np.asarray(input_ids, np.int32)
        if ids.ndim == 1:
            ids = ids[None]
        if attention_mask is None:
            attention_mask = np.ones_like(ids)
        ids_j, am, _ = self._ids(ids, attention_mask, None)
        hidden, pooled = self._fwd(self.params, self.config, ids_j, am,
                                   None)
        if pooling == "cls":
            return np.asarray(pooled, np.float32)
        return np.asarray(B.mean_pool(hidden, jnp.asarray(attention_mask)))

    def embed_texts(self, texts: List[str], tokenizer,
                    max_length: int = 512,
                    pooling: str = "mean",
                    with_counts: bool = False):
        """Tokenize + embed a batch of strings (padded to one bucket).

        Truncation runs through the tokenizer (so the trailing [SEP]
        survives) and is capped at the checkpoint's position table —
        beyond it, position lookups would clamp and silently corrupt
        embeddings. with_counts=True also returns the total number of
        tokens actually embedded (serving usage accounting)."""
        limit = min(max_length, self.config.max_position_embeddings)
        encs = [tokenizer(t, truncation=True,
                          max_length=limit)["input_ids"] for t in texts]
        n = max(len(e) for e in encs)
        ids = np.zeros((len(encs), n), np.int32)
        mask = np.zeros((len(encs), n), np.int32)
        for i, e in enumerate(encs):
            ids[i, :len(e)] = e
            mask[i, :len(e)] = 1
        vecs = self.embed(ids, mask, pooling=pooling)
        if with_counts:
            return vecs, int(mask.sum())
        return vecs
