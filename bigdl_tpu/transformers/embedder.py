"""Quantized text-embedding facade (BERT-family encoders).

Reference analog: bert served through `optimize_model` +
`TransformersEmbeddings` (reference transformers/models/bert.py:42-147;
langchain/embeddings/bigdlllm.py). `BertEmbedder` is the loader +
`embed_texts` API the langchain/llamaindex integrations build on.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.models import bert as B
from bigdl_tpu.ops.quant import FLOAT_QTYPES
from bigdl_tpu.utils.hf import iter_hf_tensors, load_hf_config

_BERT_ARCHS = ("BertModel", "BertForMaskedLM",
               "BertForSequenceClassification")


class BertEmbedder:
    """A loaded (possibly quantized) BERT + compiled embedding forward."""

    def __init__(self, params: Any, cfg: B.BertConfig,
                 hf_config: Dict[str, Any], qtype: Optional[str],
                 model_path: Optional[str] = None):
        self.params = params
        self.config = cfg
        self.hf_config = hf_config
        self.qtype = qtype
        self.model_path = model_path
        self._fwd = jax.jit(B.forward, static_argnums=(1,))

    def forward(self, input_ids, attention_mask=None):
        ids = jnp.asarray(np.asarray(input_ids, np.int32))
        if ids.ndim == 1:
            ids = ids[None]
        mask = (jnp.asarray(np.asarray(attention_mask, np.int32))
                if attention_mask is not None else None)
        return self._fwd(self.params, self.config, ids, mask)

    def embed(self, input_ids, attention_mask=None,
              pooling: str = "mean") -> np.ndarray:
        """Sentence embeddings [B, D] (pooling: "mean" | "cls")."""
        ids = np.asarray(input_ids, np.int32)
        if ids.ndim == 1:
            ids = ids[None]
        if attention_mask is None:
            attention_mask = np.ones_like(ids)
        hidden, pooled = self.forward(ids, attention_mask)
        if pooling == "cls":
            return np.asarray(pooled, np.float32)
        return np.asarray(B.mean_pool(hidden, jnp.asarray(attention_mask)))

    def embed_texts(self, texts: List[str], tokenizer,
                    max_length: int = 512,
                    pooling: str = "mean") -> np.ndarray:
        """Tokenize + embed a batch of strings (padded to one bucket)."""
        encs = [tokenizer(t)["input_ids"][:max_length] for t in texts]
        n = max(len(e) for e in encs)
        ids = np.zeros((len(encs), n), np.int32)
        mask = np.zeros((len(encs), n), np.int32)
        for i, e in enumerate(encs):
            ids[i, :len(e)] = e
            mask[i, :len(e)] = 1
        return self.embed(ids, mask, pooling=pooling)

    @classmethod
    def from_pretrained(
        cls,
        pretrained_model_name_or_path: str,
        load_in_4bit: bool = False,
        load_in_low_bit: Optional[str] = None,
        modules_to_not_convert=(),
        **_ignored,
    ) -> "BertEmbedder":
        from bigdl_tpu.transformers.model import _resolve_qtype

        path = pretrained_model_name_or_path
        hf_config = load_hf_config(path)
        archs = hf_config.get("architectures") or ["?"]
        if archs[0] not in _BERT_ARCHS:
            raise ValueError(
                f"BertEmbedder supports {_BERT_ARCHS}; got {archs[0]!r}")
        qtype = _resolve_qtype(load_in_4bit, load_in_low_bit)
        cfg = B.BertConfig.from_hf(hf_config)
        cvt_qtype = None if qtype in FLOAT_QTYPES else qtype
        params = B.convert_hf_params(
            iter_hf_tensors(path), cfg, qtype=cvt_qtype,
            modules_to_not_convert=tuple(modules_to_not_convert))
        return cls(params, cfg, hf_config, qtype, model_path=path)
