"""Bert-head Auto classes: the remaining facades of the reference's
ten-class Auto surface (reference transformers/model.py:704-725 —
SequenceClassification, TokenClassification, QuestionAnswering, MaskedLM,
NextSentencePrediction, MultipleChoice). Each loads a (possibly
quantized) bert encoder + its task head and exposes a jitted forward.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.models import bert as B
from bigdl_tpu.observability.compile_watch import tracked_jit
from bigdl_tpu.ops.quant import FLOAT_QTYPES
from bigdl_tpu.utils.hf import iter_hf_tensors, load_hf_config


class _BertTaskModel:
    """Shared loader + jitted head dispatch."""

    HEAD_FN = None                    # staticmethod in subclasses
    ACCEPT_ARCHS: tuple = ()
    REQUIRED_KEYS: tuple = ()         # head params that must exist at load

    def __init__(self, params: Any, cfg: B.BertConfig,
                 hf_config: Dict[str, Any], qtype: Optional[str]):
        self.params = params
        self.config = cfg
        self.hf_config = hf_config
        self.qtype = qtype
        self._fwd = tracked_jit(
            f"bert_{type(self).__name__}", type(self).HEAD_FN,
            static_argnums=(1,))

    def _ids(self, input_ids, attention_mask, token_type_ids):
        ids = jnp.asarray(np.asarray(input_ids, np.int32))
        if ids.ndim == 1:
            ids = ids[None]
        am = (None if attention_mask is None
              else jnp.asarray(np.asarray(attention_mask, np.int32)))
        tt = (None if token_type_ids is None
              else jnp.asarray(np.asarray(token_type_ids, np.int32)))
        return ids, am, tt

    def forward(self, input_ids, attention_mask=None, token_type_ids=None):
        ids, am, tt = self._ids(input_ids, attention_mask, token_type_ids)
        out = self._fwd(self.params, self.config, ids, am, tt)
        return jax.tree.map(np.asarray, out)

    __call__ = forward

    def save_low_bit(self, path: str) -> None:
        from bigdl_tpu.transformers import lowbit_io

        lowbit_io.save_low_bit(self.params, path, config=self.hf_config,
                               family="bert", qtype=self.qtype)

    @classmethod
    def from_pretrained(
        cls,
        pretrained_model_name_or_path: str,
        load_in_4bit: bool = False,
        load_in_low_bit: Optional[str] = None,
        modules_to_not_convert=(),
        model_hub: str = "huggingface",
        **_ignored,
    ):
        from bigdl_tpu.transformers import lowbit_io
        from bigdl_tpu.transformers.model import (_resolve_hub_path,
                                                  _resolve_qtype)

        path = _resolve_hub_path(pretrained_model_name_or_path, model_hub)
        if lowbit_io.is_low_bit_dir(path):
            # shared REQUIRED_KEYS can't distinguish classifier-style
            # heads (seq/token/choice); the saved architecture can
            params, _, hf_config, qt = lowbit_io.load_low_bit_checked(
                path, cls.ACCEPT_ARCHS, cls.__name__,
                required_keys=cls.REQUIRED_KEYS)
            model = cls(params, B.BertConfig.from_hf(hf_config), hf_config,
                        qt)
            model.model_path = path
            return model
        hf_config = load_hf_config(path)
        archs = tuple(hf_config.get("architectures") or ("?",))
        if cls.ACCEPT_ARCHS and archs[0] not in cls.ACCEPT_ARCHS:
            raise ValueError(
                f"{cls.__name__} supports {cls.ACCEPT_ARCHS}; "
                f"got {archs[0]!r}")
        qtype = _resolve_qtype(load_in_4bit, load_in_low_bit)
        cfg = B.BertConfig.from_hf(hf_config)
        cvt_qtype = None if qtype in FLOAT_QTYPES else qtype
        params = B.convert_hf_params(
            iter_hf_tensors(path), cfg, qtype=cvt_qtype,
            modules_to_not_convert=tuple(modules_to_not_convert))
        missing = [k for k in cls.REQUIRED_KEYS if k not in params]
        if missing:
            raise ValueError(
                f"checkpoint at {path} has no {missing} tensors — "
                f"{cls.__name__} needs a checkpoint saved WITH its task "
                f"head (architectures={archs})")
        model = cls(params, cfg, hf_config, qtype)
        model.model_path = path
        return model


class AutoModelForSequenceClassification(_BertTaskModel):
    HEAD_FN = staticmethod(B.sequence_logits)
    ACCEPT_ARCHS = ("BertForSequenceClassification",)
    REQUIRED_KEYS = ("head_classifier",)


class AutoModelForTokenClassification(_BertTaskModel):
    HEAD_FN = staticmethod(B.token_logits)
    ACCEPT_ARCHS = ("BertForTokenClassification",)
    REQUIRED_KEYS = ("head_classifier",)


class AutoModelForQuestionAnswering(_BertTaskModel):
    HEAD_FN = staticmethod(B.qa_logits)
    ACCEPT_ARCHS = ("BertForQuestionAnswering",)
    REQUIRED_KEYS = ("head_qa",)


class AutoModelForMaskedLM(_BertTaskModel):
    HEAD_FN = staticmethod(B.mlm_logits)
    ACCEPT_ARCHS = ("BertForMaskedLM", "BertForPreTraining")
    REQUIRED_KEYS = ("mlm_transform", "mlm_norm")


class AutoModelForNextSentencePrediction(_BertTaskModel):
    HEAD_FN = staticmethod(B.nsp_logits)
    ACCEPT_ARCHS = ("BertForNextSentencePrediction", "BertForPreTraining")
    REQUIRED_KEYS = ("head_nsp",)


class AutoModelForMultipleChoice(_BertTaskModel):
    """Choices fold into the batch: input [B, C, S] -> logits [B, C]."""

    HEAD_FN = staticmethod(B.sequence_logits)
    ACCEPT_ARCHS = ("BertForMultipleChoice",)
    REQUIRED_KEYS = ("head_classifier",)

    def forward(self, input_ids, attention_mask=None, token_type_ids=None):
        ids = np.asarray(input_ids, np.int32)
        if ids.ndim == 2:
            ids = ids[None]
        b, c, s = ids.shape
        flat = lambda x: (None if x is None
                          else np.asarray(x, np.int32).reshape(b * c, s))
        out = self._fwd(self.params, self.config,
                        jnp.asarray(ids.reshape(b * c, s)),
                        None if attention_mask is None
                        else jnp.asarray(flat(attention_mask)),
                        None if token_type_ids is None
                        else jnp.asarray(flat(token_type_ids)))
        return np.asarray(out).reshape(b, c)

    __call__ = forward
