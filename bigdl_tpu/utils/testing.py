"""Synthetic model builders (benchmarks, compile checks, unit tests).

Weights are generated *on device* with JAX PRNG and quantized tensor by
tensor, so building a 7B-parameter INT4 model for latency benchmarking
never materializes the float model on host (the benchmark analog of the
reference's low_cpu_mem_usage loading; metric defined by BASELINE.md).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.models.llama import LlamaConfig
from bigdl_tpu.ops.quant import FLOAT_QTYPES, quantize


TINY_LLAMA = LlamaConfig(
    vocab_size=256,
    hidden_size=64,
    intermediate_size=128,
    num_hidden_layers=2,
    num_attention_heads=8,
    num_key_value_heads=4,
    max_position_embeddings=256,
)

LLAMA2_7B = LlamaConfig()  # defaults are llama2-7b

MISTRAL_7B = LlamaConfig(
    vocab_size=32000,
    hidden_size=4096,
    intermediate_size=14336,
    num_hidden_layers=32,
    num_attention_heads=32,
    num_key_value_heads=8,
    rope_theta=10000.0,
    max_position_embeddings=8192,
)


def random_llama_params(
    cfg: LlamaConfig,
    qtype: Optional[str] = "sym_int4",
    seed: int = 0,
    compute_dtype=jnp.bfloat16,
) -> Dict[str, Any]:
    """Random llama-family parameter pytree, quantized linears, on device."""
    key = jax.random.PRNGKey(seed)
    do_quant = qtype is not None and qtype not in FLOAT_QTYPES
    d, ff, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    h, hkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.hd

    def nxt():
        nonlocal key
        key, sub = jax.random.split(key)
        return sub

    def randw(k, kdim, ndim):
        # contraction-major [K, N] directly; ~N(0, 0.02)
        return jax.random.normal(k, (kdim, ndim), jnp.float32) * 0.02

    def make_linear(kdim, ndim):
        w = randw(nxt(), kdim, ndim)
        if do_quant:
            return quantize(w, qtype)
        return w.astype(compute_dtype)

    def stack(makers):
        return jax.tree.map(lambda *xs: jnp.stack(xs), *makers)

    layers: Dict[str, Any] = {}
    per = {
        "q_proj": (d, h * hd),
        "k_proj": (d, hkv * hd),
        "v_proj": (d, hkv * hd),
        "o_proj": (h * hd, d),
        "gate_proj": (d, ff),
        "up_proj": (d, ff),
        "down_proj": (ff, d),
    }
    for name, (kdim, ndim) in per.items():
        layers[name] = stack(
            [make_linear(kdim, ndim) for _ in range(cfg.num_hidden_layers)])
    ones = jnp.ones((cfg.num_hidden_layers, d), compute_dtype)
    layers["input_layernorm"] = ones
    layers["post_attention_layernorm"] = ones

    params: Dict[str, Any] = {
        "embed_tokens": (jax.random.normal(nxt(), (v, d), jnp.float32)
                         * 0.02).astype(compute_dtype),
        "layers": layers,
        "norm": jnp.ones((d,), compute_dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = make_linear(d, v)
    return params


class SyntheticCausalLM:
    """Duck-typed stand-in for TpuCausalLM — ``.params`` / ``.config``
    / ``.family`` / ``.hf_config`` is all ``LLMEngine`` needs. Weights
    come from ``random_llama_params`` with an explicit seed, so two
    PROCESSES built with the same seed hold byte-identical weights:
    the serving router's replica-replay guarantees (a replayed greedy
    request must reproduce the dead replica's answer exactly) are
    testable without shipping a checkpoint into every subprocess."""

    def __init__(self, params, cfg):
        from bigdl_tpu.models import llama as llama_mod

        self.params = params
        self.config = cfg
        self.hf_config = {"eos_token_id": None}

        class _Family:
            name = "llama-synthetic"
            forward = staticmethod(llama_mod.forward)
            prefill = staticmethod(llama_mod.forward_last_token)
            new_cache = staticmethod(llama_mod.new_cache)
            forward_paged = staticmethod(llama_mod.forward_paged)
            new_paged_cache = staticmethod(llama_mod.new_paged_cache)
            SUPPORTS_SCALED_KV = llama_mod.SUPPORTS_SCALED_KV
            SUPPORTS_PAGED_KV = llama_mod.SUPPORTS_PAGED_KV

        self.family = _Family()


def tiny_random_model(seed: int = 0, qtype: Optional[str] = "sym_int4",
                      cfg=None) -> SyntheticCausalLM:
    """A tiny random llama ready for ``LLMEngine`` / ``OpenAIServer``
    (the ``api_server --tiny-random`` replica mode and router tests)."""
    cfg = cfg or TINY_LLAMA
    return SyntheticCausalLM(
        random_llama_params(cfg, qtype=qtype, seed=seed), cfg)


def random_mixtral_params(
    cfg,
    qtype: Optional[str] = "sym_int4",
    seed: int = 0,
    compute_dtype=jnp.bfloat16,
) -> Dict[str, Any]:
    """Random mixtral parameter pytree: llama attention + stacked experts."""
    from bigdl_tpu.ops.quant import quantize

    key = jax.random.PRNGKey(seed)
    do_quant = qtype is not None and qtype not in FLOAT_QTYPES
    d, ff, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    h, hkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.hd
    L, E = cfg.num_hidden_layers, cfg.num_local_experts

    def nxt():
        nonlocal key
        key, sub = jax.random.split(key)
        return sub

    def make_linear(kdim, ndim):
        w = jax.random.normal(nxt(), (kdim, ndim), jnp.float32) * 0.02
        if do_quant:
            return quantize(w, qtype)
        return w.astype(compute_dtype)

    def stack(makers):
        return jax.tree.map(lambda *xs: jnp.stack(xs), *makers)

    layers: Dict[str, Any] = {}
    for name, (kdim, ndim) in {
        "q_proj": (d, h * hd), "k_proj": (d, hkv * hd),
        "v_proj": (d, hkv * hd), "o_proj": (h * hd, d),
    }.items():
        layers[name] = stack([make_linear(kdim, ndim) for _ in range(L)])
    for name, (kdim, ndim) in {
        "experts_gate": (d, ff), "experts_up": (d, ff),
        "experts_down": (ff, d),
    }.items():
        layers[name] = stack(
            [stack([make_linear(kdim, ndim) for _ in range(E)])
             for _ in range(L)])
    layers["router"] = (jax.random.normal(nxt(), (L, d, E), jnp.float32)
                        * 0.02).astype(compute_dtype)
    ones = jnp.ones((L, d), compute_dtype)
    layers["input_layernorm"] = ones
    layers["post_attention_layernorm"] = ones

    params: Dict[str, Any] = {
        "embed_tokens": (jax.random.normal(nxt(), (v, d), jnp.float32)
                         * 0.02).astype(compute_dtype),
        "layers": layers,
        "norm": jnp.ones((d,), compute_dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = make_linear(d, v)
    return params
