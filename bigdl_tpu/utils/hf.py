"""HF checkpoint reading: config + state dict, without instantiating torch.

The reference piggybacks on HF `from_pretrained` to materialize nn.Modules
and then walks them (transformers/model.py:435, convert.py:191-387). We load
tensors directly instead: safetensors files are memory-mapped and converted
per-tensor, so peak host memory is one tensor, not one model — the TPU-side
equivalent of the reference's `low_cpu_mem_usage`/lazy-load path
(utils/lazy_load_torch.py).
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import numpy as np


def load_hf_config(model_path: str) -> Dict[str, Any]:
    with open(os.path.join(model_path, "config.json")) as f:
        return json.load(f)


def _safetensors_files(model_path: str):
    idx = os.path.join(model_path, "model.safetensors.index.json")
    if os.path.exists(idx):
        with open(idx) as f:
            index = json.load(f)
        files = sorted(set(index["weight_map"].values()))
        return [os.path.join(model_path, f) for f in files]
    single = os.path.join(model_path, "model.safetensors")
    if os.path.exists(single):
        return [single]
    return []


def _torch_files(model_path: str):
    idx = os.path.join(model_path, "pytorch_model.bin.index.json")
    if os.path.exists(idx):
        with open(idx) as f:
            index = json.load(f)
        files = sorted(set(index["weight_map"].values()))
        return [os.path.join(model_path, f) for f in files]
    single = os.path.join(model_path, "pytorch_model.bin")
    if os.path.exists(single):
        return [single]
    return []


def iter_hf_tensors(model_path: str) -> Iterator[Tuple[str, np.ndarray]]:
    """Yield (name, np.ndarray) for every tensor in the checkpoint."""
    st_files = _safetensors_files(model_path)
    if st_files:
        from safetensors import safe_open

        for path in st_files:
            with safe_open(path, framework="np") as f:
                for name in f.keys():
                    yield name, f.get_tensor(name)
        return

    pt_files = _torch_files(model_path)
    if pt_files:
        import torch

        for path in pt_files:
            sd = torch.load(path, map_location="cpu", weights_only=True)
            for name, t in sd.items():
                yield name, t.float().numpy()
        return

    raise FileNotFoundError(
        f"no model.safetensors[.index.json] or pytorch_model.bin in {model_path}"
    )


def load_hf_state_dict(model_path: str) -> Dict[str, np.ndarray]:
    return dict(iter_hf_tensors(model_path))
