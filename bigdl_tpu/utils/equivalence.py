"""Layer-wise numerical-equivalence harness.

The reference's most interesting test pattern (SURVEY.md §4, reference
test/inference_gpu/test_transformers_api_attention.py:45-100): load a model
optimized and unoptimized, replay identical layer inputs, and compare
per-layer outputs against a mean-absolute-difference bound. Here the
"unoptimized" model is the f32 dense pytree and the "optimized" one is any
quantized variant; the per-layer capture is a scan that stacks each
layer's hidden state.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.models import llama as llama_mod


def layer_hidden_states(
    params: Dict[str, Any],
    cfg,
    tokens: jax.Array,          # [B, S]
    compute_dtype=jnp.float32,
) -> np.ndarray:
    """Hidden state AFTER each decoder layer: [L, B, S, D] (cacheless)."""
    b, s = tokens.shape
    positions = jnp.arange(s, dtype=jnp.int32)
    x = llama_mod.embed_prologue(params, cfg, tokens, positions,
                                 compute_dtype)
    inv_freq, mscale = llama_mod.model_rope_freqs(cfg)
    from bigdl_tpu.ops.rope import rope_cos_sin

    cos, sin = rope_cos_sin(positions[None, :], inv_freq)
    if mscale != 1.0:
        cos, sin = cos * mscale, sin * mscale
    slopes = (jnp.asarray(llama_mod.alibi_slopes(cfg.num_attention_heads))
              if cfg.use_alibi else None)

    def step(x, xs):
        lp, lidx = xs
        out, _ = llama_mod._decoder_layer(x, lp, cfg, cos, sin, slopes,
                                          cache_ctx=None, lidx=lidx)
        return out, out

    lids = jnp.arange(cfg.num_hidden_layers, dtype=jnp.int32)
    _, per_layer = lax.scan(step, x, (params["layers"], lids))
    return np.asarray(per_layer, np.float32)


def layer_equivalence_report(
    params_ref: Dict[str, Any],
    params_opt: Dict[str, Any],
    cfg,
    tokens,
) -> List[Dict[str, float]]:
    """Per-layer MAD + relative error between two parameter variants."""
    toks = jnp.asarray(np.asarray(tokens, np.int32))
    if toks.ndim == 1:
        toks = toks[None]
    ref = layer_hidden_states(params_ref, cfg, toks)
    opt = layer_hidden_states(params_opt, cfg, toks)
    out = []
    for i in range(ref.shape[0]):
        mad = float(np.mean(np.abs(ref[i] - opt[i])))
        scale = float(np.mean(np.abs(ref[i]))) + 1e-9
        out.append({"layer": i, "mad": mad, "relative": mad / scale})
    return out


def assert_equivalent(params_ref, params_opt, cfg, tokens,
                      max_relative: float = 0.1) -> List[Dict[str, float]]:
    """The reference's lower_bound assertion, per layer."""
    report = layer_equivalence_report(params_ref, params_opt, cfg, tokens)
    bad = [r for r in report if r["relative"] > max_relative]
    if bad:
        raise AssertionError(
            f"layer equivalence exceeded {max_relative}: {bad}")
    return report
