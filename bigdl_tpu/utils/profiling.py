"""Profiling helpers: traces + named regions around the hot loops.

The reference has no tracer — its observability is BenchmarkWrapper's
per-token timing (reference dev/benchmark/benchmark_util.py:489-520) and
manual `torch.xpu.synchronize()` wall-clocks. On TPU the native story is
`jax.profiler` (XLA device traces viewable in TensorBoard/Perfetto); this
module makes it a one-liner around our entry points and keeps working on
CPU test runs.

    from bigdl_tpu.utils.profiling import trace, annotate

    with trace("/tmp/tb"):                     # device + host trace
        with annotate("prefill"):
            model.generate(ids, max_new_tokens=64)
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Iterator, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a jax.profiler trace into `log_dir` (TensorBoard format)."""
    jax.profiler.start_trace(log_dir,
                             create_perfetto_link=False,
                             create_perfetto_trace=True)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


# On-demand profiler for the API server (POST /v1/profiler/{start,stop}):
# same jax.profiler trace as `trace()` above but split into explicit
# start/stop calls so a capture can bracket live traffic. One capture at
# a time per process (jax.profiler itself is single-session).
_profiler_lock = threading.Lock()
_profiler_dir: Optional[str] = None


def start_profiler(log_dir: str) -> dict:
    """Start a device trace into `log_dir`; error if one is running."""
    global _profiler_dir
    with _profiler_lock:
        if _profiler_dir is not None:
            raise RuntimeError(
                f"profiler already capturing into {_profiler_dir}")
        jax.profiler.start_trace(log_dir,
                                 create_perfetto_link=False,
                                 create_perfetto_trace=True)
        _profiler_dir = log_dir
        return {"status": "started", "log_dir": log_dir}


def stop_profiler() -> dict:
    """Stop the running capture; error if none is running."""
    global _profiler_dir
    with _profiler_lock:
        if _profiler_dir is None:
            raise RuntimeError("no profiler capture in progress")
        log_dir, _profiler_dir = _profiler_dir, None
        jax.profiler.stop_trace()
        return {"status": "stopped", "log_dir": log_dir}


def profiler_status() -> dict:
    with _profiler_lock:
        return {"capturing": _profiler_dir is not None,
                "log_dir": _profiler_dir}


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region that shows up on the trace timeline (TraceAnnotation)
    AND works as a no-op grouping label outside a trace."""
    with jax.profiler.TraceAnnotation(name):
        yield


class StepTimer:
    """Blocking wall-clock timer for steps (training loops, engine steps).

    The per-phase analog of GenerationStats: `block_until_ready` on the
    step output before reading the clock, so tunnel dispatch latency
    doesn't masquerade as compute time."""

    def __init__(self, metrics_prefix: Optional[str] = None,
                 registry=None):
        """With `metrics_prefix` set, every sample is also observed into a
        `{prefix}_{name}_seconds` histogram in `registry` (the
        observability default registry when None)."""
        self.times: Dict[str, list] = {}
        self._metrics_prefix = metrics_prefix
        self._registry = registry

    def record(self, name: str, seconds: float) -> None:
        """Append one sample; mirror it to the metrics registry when a
        prefix was configured."""
        self.times.setdefault(name, []).append(seconds)
        if self._metrics_prefix is None:
            return
        try:
            if self._registry is None:
                from bigdl_tpu.observability.metrics import default_registry
                self._registry = default_registry()
            self._registry.histogram(
                f"{self._metrics_prefix}_{name}_seconds",
                f"StepTimer samples for {name}.",
            ).observe(seconds)
        except Exception:
            pass  # telemetry must never break the timed code path

    @contextlib.contextmanager
    def measure(self, name: str, result=None) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        except BaseException:
            # the block failed — a sample here would mix error paths into
            # the latency distribution, so drop it
            raise
        else:
            if result is not None:
                jax.block_until_ready(result)
            self.record(name, time.perf_counter() - t0)

    def timed(self, name: str, fn, *args, **kwargs):
        """Run fn, block on its output, record the wall time, return it."""
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        jax.block_until_ready(out)
        self.record(name, time.perf_counter() - t0)
        return out

    def summary(self) -> Dict[str, Dict[str, float]]:
        out = {}
        for name, ts in self.times.items():
            s = sorted(ts)
            out[name] = {
                "count": len(ts),
                "mean_ms": sum(ts) / len(ts) * 1e3,
                "min_ms": s[0] * 1e3,
                "max_ms": s[-1] * 1e3,
                "p50_ms": _percentile(s, 0.50) * 1e3,
                "p90_ms": _percentile(s, 0.90) * 1e3,
                "p99_ms": _percentile(s, 0.99) * 1e3,
                "total_s": sum(ts),
            }
        return out


def _percentile(sorted_samples, q: float) -> float:
    """Linear-interpolation percentile over pre-sorted samples (numpy's
    default method, without numpy). The old `s[len(s) // 2]` median
    picked the UPPER of the two middle samples on even-length inputs,
    biasing p50 high; interpolation returns their midpoint."""
    s = sorted_samples
    if not s:
        return float("nan")
    if len(s) == 1:
        return s[0]
    pos = q * (len(s) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(s) - 1)
    frac = pos - lo
    return s[lo] * (1.0 - frac) + s[hi] * frac
