"""Profiling helpers: traces + named regions around the hot loops.

The reference has no tracer — its observability is BenchmarkWrapper's
per-token timing (reference dev/benchmark/benchmark_util.py:489-520) and
manual `torch.xpu.synchronize()` wall-clocks. On TPU the native story is
`jax.profiler` (XLA device traces viewable in TensorBoard/Perfetto); this
module makes it a one-liner around our entry points and keeps working on
CPU test runs.

    from bigdl_tpu.utils.profiling import trace, annotate

    with trace("/tmp/tb"):                     # device + host trace
        with annotate("prefill"):
            model.generate(ids, max_new_tokens=64)
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator

import jax


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a jax.profiler trace into `log_dir` (TensorBoard format)."""
    jax.profiler.start_trace(log_dir,
                             create_perfetto_link=False,
                             create_perfetto_trace=True)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region that shows up on the trace timeline (TraceAnnotation)
    AND works as a no-op grouping label outside a trace."""
    with jax.profiler.TraceAnnotation(name):
        yield


class StepTimer:
    """Blocking wall-clock timer for steps (training loops, engine steps).

    The per-phase analog of GenerationStats: `block_until_ready` on the
    step output before reading the clock, so tunnel dispatch latency
    doesn't masquerade as compute time."""

    def __init__(self):
        self.times: Dict[str, list] = {}

    @contextlib.contextmanager
    def measure(self, name: str, result=None) -> Iterator[None]:
        t0 = time.perf_counter()
        yield
        if result is not None:
            jax.block_until_ready(result)
        self.times.setdefault(name, []).append(time.perf_counter() - t0)

    def timed(self, name: str, fn, *args, **kwargs):
        """Run fn, block on its output, record the wall time, return it."""
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        jax.block_until_ready(out)
        self.times.setdefault(name, []).append(time.perf_counter() - t0)
        return out

    def summary(self) -> Dict[str, Dict[str, float]]:
        out = {}
        for name, ts in self.times.items():
            out[name] = {
                "count": len(ts),
                "mean_ms": sum(ts) / len(ts) * 1e3,
                "min_ms": min(ts) * 1e3,
                "total_s": sum(ts),
            }
        return out
