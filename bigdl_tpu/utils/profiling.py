"""Profiling helpers: traces + named regions around the hot loops.

The reference has no tracer — its observability is BenchmarkWrapper's
per-token timing (reference dev/benchmark/benchmark_util.py:489-520) and
manual `torch.xpu.synchronize()` wall-clocks. On TPU the native story is
`jax.profiler` (XLA device traces viewable in TensorBoard/Perfetto); this
module makes it a one-liner around our entry points and keeps working on
CPU test runs.

    from bigdl_tpu.utils.profiling import trace, annotate

    with trace("/tmp/tb"):                     # device + host trace
        with annotate("prefill"):
            model.generate(ids, max_new_tokens=64)
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, Iterator, Optional

import jax


def resolve_profiler_max_sec(value=None) -> float:
    """Hard cap on any on-demand profiler capture: explicit value, else
    ``$BIGDL_TPU_PROFILER_MAX_SEC``, else 60 seconds. Every capture —
    operator-started, router fleet fan-out, or sentinel auto-capture —
    is auto-stopped at this deadline so an abandoned capture can never
    run unbounded. ValueError on a non-positive or non-numeric setting
    (utils/env_check.py surfaces this)."""
    if value is None:
        value = os.environ.get("BIGDL_TPU_PROFILER_MAX_SEC")
    if value is None or value == "":
        return 60.0
    try:
        f = float(value)
    except (TypeError, ValueError):
        raise ValueError(
            f"profiler max seconds must be a positive number, got "
            f"{value!r}")
    if f <= 0:
        raise ValueError(
            f"profiler max seconds must be a positive number, got {f}")
    return f


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a jax.profiler trace into `log_dir` (TensorBoard format)."""
    jax.profiler.start_trace(log_dir,
                             create_perfetto_link=False,
                             create_perfetto_trace=True)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


# On-demand profiler for the API server (POST /v1/profiler/{start,stop}):
# same jax.profiler trace as `trace()` above but split into explicit
# start/stop calls so a capture can bracket live traffic. One capture at
# a time per process (jax.profiler itself is single-session). A
# watchdog timer auto-stops every capture at its deadline.
_profiler_lock = threading.Lock()
_profiler_dir: Optional[str] = None
_profiler_started_at: Optional[float] = None
_profiler_deadline: Optional[float] = None
_profiler_capture_id: Optional[str] = None
_profiler_timer: Optional[threading.Timer] = None
_last_capture: Optional[dict] = None

# a runaway capture dir (Perfetto traces of a busy chip are big) stops
# admission of NEW captures past this many bytes; env-overridable for
# tests and small disks
_CAPTURE_DIR_CAP_BYTES = 1 << 30


def _capture_dir_cap() -> int:
    raw = os.environ.get("BIGDL_TPU_PROFILER_DIR_CAP_BYTES")
    if raw:
        try:
            n = int(raw)
            if n > 0:
                return n
        except ValueError:
            pass
    return _CAPTURE_DIR_CAP_BYTES


def _dir_bytes(path: str) -> int:
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total


def start_profiler(log_dir: str, max_sec: Optional[float] = None,
                   capture_id: Optional[str] = None) -> dict:
    """Start a device trace into `log_dir`; error if one is running.

    Hardening (all three bit operators in practice): non-absolute paths
    are rejected (a capture landing in whatever CWD the server happened
    to start from is a lost capture), the directory is created if
    missing, and an already-oversized capture dir refuses new captures.
    A daemon watchdog stops the capture after ``max_sec`` (clamped to
    ``resolve_profiler_max_sec()``) so it can never run unbounded."""
    global _profiler_dir, _profiler_started_at, _profiler_deadline
    global _profiler_capture_id, _profiler_timer
    if not os.path.isabs(log_dir):
        raise ValueError(
            f"profiler log_dir must be an absolute path, got {log_dir!r}")
    cap_sec = resolve_profiler_max_sec()
    if max_sec is not None:
        try:
            max_sec = float(max_sec)
        except (TypeError, ValueError):
            raise ValueError(
                f"profiler duration must be a positive number, got "
                f"{max_sec!r}")
        if max_sec <= 0:
            raise ValueError(
                f"profiler duration must be a positive number, got "
                f"{max_sec}")
        cap_sec = min(cap_sec, max_sec)
    with _profiler_lock:
        if _profiler_dir is not None:
            raise RuntimeError(
                f"profiler already capturing into {_profiler_dir}")
        os.makedirs(log_dir, exist_ok=True)
        used = _dir_bytes(log_dir)
        cap_bytes = _capture_dir_cap()
        if used >= cap_bytes:
            raise RuntimeError(
                f"capture dir {log_dir} already holds {used} bytes "
                f"(cap {cap_bytes}); clean it up before capturing")
        jax.profiler.start_trace(log_dir,
                                 create_perfetto_link=False,
                                 create_perfetto_trace=True)
        now = time.time()
        _profiler_dir = log_dir
        _profiler_started_at = now
        _profiler_deadline = now + cap_sec
        _profiler_capture_id = capture_id
        _profiler_timer = threading.Timer(
            cap_sec, _auto_stop, args=(log_dir,))
        _profiler_timer.daemon = True
        _profiler_timer.start()
        out = {"status": "started", "log_dir": log_dir,
               "max_sec": cap_sec, "deadline": _profiler_deadline}
        if capture_id is not None:
            out["capture_id"] = capture_id
        return out


def _auto_stop(expected_dir: str) -> None:
    """Watchdog body: stop the capture iff it is still the one we armed
    for (an operator stop + fresh start must not be killed by a stale
    timer)."""
    with _profiler_lock:
        if _profiler_dir != expected_dir:
            return
    try:
        stop_profiler(_reason="auto_stop")
    except RuntimeError:
        pass  # lost the race with an operator stop: fine


def stop_profiler(_reason: str = "manual") -> dict:
    """Stop the running capture; error if none is running.

    ``_profiler_dir`` is cleared BEFORE ``stop_trace()`` can raise
    (try/finally): a failed stop used to leave the module convinced a
    capture was live, wedging the profiler until process restart."""
    global _profiler_dir, _profiler_started_at, _profiler_deadline
    global _profiler_capture_id, _profiler_timer, _last_capture
    with _profiler_lock:
        if _profiler_dir is None:
            raise RuntimeError("no profiler capture in progress")
        log_dir, _profiler_dir = _profiler_dir, None
        started_at, _profiler_started_at = _profiler_started_at, None
        capture_id, _profiler_capture_id = _profiler_capture_id, None
        _profiler_deadline = None
        timer, _profiler_timer = _profiler_timer, None
        if timer is not None:
            timer.cancel()
        out = {"status": "stopped", "log_dir": log_dir,
               "stopped_by": _reason}
        if started_at is not None:
            out["duration_s"] = round(time.time() - started_at, 3)
        if capture_id is not None:
            out["capture_id"] = capture_id
        try:
            jax.profiler.stop_trace()
        finally:
            _last_capture = dict(out)
        return out


def profiler_status() -> dict:
    """Structured view of the on-demand profiler: whether a capture is
    live, its dir / start / deadline, the configured cap, and the last
    finished capture (who stopped it, how long it ran)."""
    try:
        max_sec = resolve_profiler_max_sec()
    except ValueError:
        max_sec = 60.0  # status must render even with a bad env knob
    with _profiler_lock:
        out = {"capturing": _profiler_dir is not None,
               "log_dir": _profiler_dir,
               "max_sec": max_sec}
        if _profiler_dir is not None:
            out["started_at"] = _profiler_started_at
            out["deadline"] = _profiler_deadline
            if _profiler_capture_id is not None:
                out["capture_id"] = _profiler_capture_id
        if _last_capture is not None:
            out["last_capture"] = dict(_last_capture)
        return out


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region that shows up on the trace timeline (TraceAnnotation)
    AND works as a no-op grouping label outside a trace."""
    with jax.profiler.TraceAnnotation(name):
        yield


class StepTimer:
    """Blocking wall-clock timer for steps (training loops, engine steps).

    The per-phase analog of GenerationStats: `block_until_ready` on the
    step output before reading the clock, so tunnel dispatch latency
    doesn't masquerade as compute time."""

    def __init__(self, metrics_prefix: Optional[str] = None,
                 registry=None):
        """With `metrics_prefix` set, every sample is also observed into a
        `{prefix}_{name}_seconds` histogram in `registry` (the
        observability default registry when None)."""
        self.times: Dict[str, list] = {}
        self._metrics_prefix = metrics_prefix
        self._registry = registry

    def record(self, name: str, seconds: float) -> None:
        """Append one sample; mirror it to the metrics registry when a
        prefix was configured."""
        self.times.setdefault(name, []).append(seconds)
        if self._metrics_prefix is None:
            return
        try:
            if self._registry is None:
                from bigdl_tpu.observability.metrics import default_registry
                self._registry = default_registry()
            self._registry.histogram(
                f"{self._metrics_prefix}_{name}_seconds",
                f"StepTimer samples for {name}.",
            ).observe(seconds)
        except Exception:
            pass  # telemetry must never break the timed code path

    @contextlib.contextmanager
    def measure(self, name: str, result=None) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        except BaseException:
            # the block failed — a sample here would mix error paths into
            # the latency distribution, so drop it
            raise
        else:
            if result is not None:
                jax.block_until_ready(result)
            self.record(name, time.perf_counter() - t0)

    def timed(self, name: str, fn, *args, **kwargs):
        """Run fn, block on its output, record the wall time, return it."""
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        jax.block_until_ready(out)
        self.record(name, time.perf_counter() - t0)
        return out

    def summary(self) -> Dict[str, Dict[str, float]]:
        from bigdl_tpu.observability.stats import summarize

        out = {}
        for name, ts in self.times.items():
            s = summarize(ts, scale=1e3)
            out[name] = {
                "count": s["count"],
                "mean_ms": s["mean"],
                "min_ms": s["min"],
                "max_ms": s["max"],
                "p50_ms": s["p50"],
                "p90_ms": s["p90"],
                "p99_ms": s["p99"],
                "total_s": s["total"],
            }
        return out


def _percentile(sorted_samples, q: float) -> float:
    """Linear-interpolation percentile over pre-sorted samples; the
    shared implementation lives in observability/stats.py (single
    source for StepTimer, the sentinel baseline, and bench lane
    stats). Kept as a name here for existing callers."""
    from bigdl_tpu.observability.stats import percentile

    return percentile(sorted_samples, q)
