"""Environment sanity check.

Equivalent of the reference's env-check scripts (reference
python/llm/scripts/env-check.sh + check.py and the `ipex-llm-init`
allocator/OMP setup — the TPU analog reports the XLA backend, device
inventory, memory, native-kernel availability, and key env flags).

Run: python -m bigdl_tpu.utils.env_check
"""

from __future__ import annotations

import os
import sys


def collect() -> dict:
    info: dict = {"python": sys.version.split()[0]}
    try:
        import jax

        info["jax"] = jax.__version__
        info["backend"] = jax.default_backend()
        devs = jax.devices()
        info["devices"] = [str(d) for d in devs]
        try:
            stats = devs[0].memory_stats() or {}
            lim = stats.get("bytes_limit")
            if lim:
                info["device_memory_gb"] = round(lim / 2**30, 2)
        except Exception:
            pass
    except Exception as e:  # pragma: no cover
        info["jax_error"] = repr(e)

    try:
        from bigdl_tpu import __version__, native

        info["bigdl_tpu"] = __version__
        info["native_kernels"] = native.get_lib() is not None
    except Exception as e:
        info["bigdl_tpu_error"] = repr(e)

    for mod in ("flax", "optax", "transformers", "safetensors"):
        try:
            info[mod] = __import__(mod).__version__
        except Exception:
            info[mod] = None

    info["env"] = {k: v for k, v in os.environ.items()
                   if k.startswith(("JAX_", "XLA_", "BIGDL_", "LIBTPU"))}

    # observability event log (serving request tracer JSONL sink):
    # report up front whether the configured path is actually writable —
    # the tracer itself degrades silently by design
    ev = os.environ.get("BIGDL_TPU_EVENT_LOG")
    if ev:
        from bigdl_tpu.observability.tracing import validate_event_log_path

        info["event_log"] = validate_event_log_path(ev)

    # KV cache storage dtype: fail loudly here rather than at the first
    # model load (a typo'd dtype name otherwise surfaces deep in
    # init_cache)
    kvd = os.environ.get("BIGDL_TPU_KV_CACHE_DTYPE")
    if kvd:
        from bigdl_tpu.ops.kvcache import (KV_CACHE_DTYPES,
                                           resolve_kv_cache_dtype)

        try:
            info["kv_cache_dtype"] = {
                "value": resolve_kv_cache_dtype(kvd), "valid": True}
        except ValueError:
            info["kv_cache_dtype"] = {
                "value": kvd, "valid": False,
                "choices": sorted(KV_CACHE_DTYPES)}
    return info


def main() -> int:
    info = collect()
    width = max(len(k) for k in info)
    for k, v in info.items():
        if k == "env":
            print("env flags:")
            for ek, ev in sorted(v.items()):
                print(f"  {ek}={ev}")
        else:
            print(f"{k:<{width}} : {v}")
    ok = ("jax_error" not in info and "bigdl_tpu_error" not in info
          and info.get("kv_cache_dtype", {}).get("valid", True))
    print("status :", "OK" if ok else "PROBLEMS FOUND")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
