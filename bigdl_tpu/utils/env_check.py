"""Environment sanity check.

Equivalent of the reference's env-check scripts (reference
python/llm/scripts/env-check.sh + check.py and the `ipex-llm-init`
allocator/OMP setup — the TPU analog reports the XLA backend, device
inventory, memory, native-kernel availability, and key env flags).

Run: python -m bigdl_tpu.utils.env_check
"""

from __future__ import annotations

import difflib
import os
import sys

#: every knob the stack reads — the typo check suggests the nearest of
#: these for any unrecognized BIGDL_TPU_* variable (a misspelled knob
#: is silently ignored everywhere else, which is exactly the failure
#: mode an env check exists to catch)
KNOWN_ENV = (
    "BIGDL_TPU_AOT_TARGET",
    "BIGDL_TPU_ATTENTION_BACKEND",
    "BIGDL_TPU_AUTOSCALE_DWELL_SEC",
    "BIGDL_TPU_AUTOSCALE_MAX",
    "BIGDL_TPU_AUTOSCALE_MIN",
    "BIGDL_TPU_BROWNOUT_HIGH",
    "BIGDL_TPU_BROWNOUT_LOW",
    "BIGDL_TPU_CANARY_NLL_TOL",
    "BIGDL_TPU_CANARY_SEC",
    "BIGDL_TPU_COMPILE_CACHE",
    "BIGDL_TPU_COMPILE_MEMORY",
    "BIGDL_TPU_DECODE_RESIDENT",
    "BIGDL_TPU_DISABLE_NATIVE",
    "BIGDL_TPU_DRAIN_TIMEOUT_SEC",
    "BIGDL_TPU_EVENT_LOG",
    "BIGDL_TPU_EVENT_LOG_KEEP",
    "BIGDL_TPU_EVENT_LOG_MAX_BYTES",
    "BIGDL_TPU_FAULT_SPEC",
    "BIGDL_TPU_HANDOFF_RETRIES",
    "BIGDL_TPU_HANDOFF_TIMEOUT_MS",
    "BIGDL_TPU_HBM_BUDGET_FRACTION",
    "BIGDL_TPU_IQ_GRID_SOURCE",
    "BIGDL_TPU_KV_CACHE_DTYPE",
    "BIGDL_TPU_KV_PAGES",
    "BIGDL_TPU_KV_PAGE_SIZE",
    "BIGDL_TPU_LIVE_MIGRATION",
    "BIGDL_TPU_MATMUL_BACKEND",
    "BIGDL_TPU_MATMUL_GEMV",
    "BIGDL_TPU_MATMUL_PALLAS_MAX_M",
    "BIGDL_TPU_MAX_QUEUE_BYTES",
    "BIGDL_TPU_MAX_QUEUE_DEPTH",
    "BIGDL_TPU_MAX_SEQ",
    "BIGDL_TPU_MEMORY_POLL_SEC",
    "BIGDL_TPU_MIGRATE_MAX_BYTES",
    "BIGDL_TPU_MIGRATE_TARGETS",
    "BIGDL_TPU_MIGRATE_TIMEOUT_MS",
    "BIGDL_TPU_MOE_DISPATCH",
    "BIGDL_TPU_MXU_LAYOUT",
    "BIGDL_TPU_NATIVE_CACHE",
    "BIGDL_TPU_PEAK_BF16_TFLOPS",
    "BIGDL_TPU_PEAK_HBM_GBPS",
    "BIGDL_TPU_PERF_HISTORY",
    "BIGDL_TPU_POSTMORTEM_DIR",
    "BIGDL_TPU_PREFIX_SHARING",
    "BIGDL_TPU_PREPACK",
    "BIGDL_TPU_PROFILER_DIR_CAP_BYTES",
    "BIGDL_TPU_PROFILER_MAX_SEC",
    "BIGDL_TPU_QOS_AGING_SEC",
    "BIGDL_TPU_QOS_DEFAULT",
    "BIGDL_TPU_QUALITY",
    "BIGDL_TPU_QUALITY_HISTORY",
    "BIGDL_TPU_QUALITY_PROBE_STEPS",
    "BIGDL_TPU_QUALITY_RECOVER_STEPS",
    "BIGDL_TPU_QUALITY_THRESHOLD",
    "BIGDL_TPU_QUALITY_TRIP_STEPS",
    "BIGDL_TPU_QUANTIZE_KV_CACHE",
    "BIGDL_TPU_RECOMPILE_WARN",
    "BIGDL_TPU_REPLICA_ROLE",
    "BIGDL_TPU_REQUEST_DEADLINE_MS",
    "BIGDL_TPU_ROUTER_CRASH_BUDGET",
    "BIGDL_TPU_ROUTER_HEALTH_SEC",
    "BIGDL_TPU_ROUTER_HEDGE_MS",
    "BIGDL_TPU_ROUTER_JOURNAL",
    "BIGDL_TPU_ROUTER_REPLICAS",
    "BIGDL_TPU_SENTINEL",
    "BIGDL_TPU_SENTINEL_RECOVER_STEPS",
    "BIGDL_TPU_SENTINEL_THRESHOLD",
    "BIGDL_TPU_SENTINEL_TRIP_STEPS",
    "BIGDL_TPU_SLO_ALERT_LOG",
    "BIGDL_TPU_SLO_SPEC",
    "BIGDL_TPU_TENANT_BURST",
    "BIGDL_TPU_TENANT_RPS",
    "BIGDL_TPU_TENANT_TPS",
    "BIGDL_TPU_TRACE_SAMPLE",
    "BIGDL_TPU_USAGE_LOG",
)


def find_env_typos(environ=None) -> list:
    """Unrecognized ``BIGDL_TPU_*`` variables with a close known knob:
    ``[{"unknown": ..., "did_you_mean": ...}]``. High match cutoff so
    unrelated private variables don't false-positive."""
    env = os.environ if environ is None else environ
    typos = []
    for k in sorted(env):
        if not k.startswith("BIGDL_TPU_") or k in KNOWN_ENV:
            continue
        close = difflib.get_close_matches(k, KNOWN_ENV, n=1, cutoff=0.85)
        if close:
            typos.append({"unknown": k, "did_you_mean": close[0]})
    return typos


def collect() -> dict:
    info: dict = {"python": sys.version.split()[0]}
    try:
        import jax

        info["jax"] = jax.__version__
        info["backend"] = jax.default_backend()
        devs = jax.devices()
        info["devices"] = [str(d) for d in devs]
        try:
            stats = devs[0].memory_stats() or {}
            lim = stats.get("bytes_limit")
            if lim:
                info["device_memory_gb"] = round(lim / 2**30, 2)
        except Exception:
            pass
    except Exception as e:  # pragma: no cover
        info["jax_error"] = repr(e)

    try:
        from bigdl_tpu import __version__, native

        info["bigdl_tpu"] = __version__
        info["native_kernels"] = native.get_lib() is not None
    except Exception as e:
        info["bigdl_tpu_error"] = repr(e)

    for mod in ("flax", "optax", "transformers", "safetensors"):
        try:
            info[mod] = __import__(mod).__version__
        except Exception:
            info[mod] = None

    info["env"] = {k: v for k, v in os.environ.items()
                   if k.startswith(("JAX_", "XLA_", "BIGDL_", "LIBTPU"))}

    # observability event log (serving request tracer JSONL sink):
    # report up front whether the configured path is actually writable —
    # the tracer itself degrades silently by design
    ev = os.environ.get("BIGDL_TPU_EVENT_LOG")
    if ev:
        from bigdl_tpu.observability.tracing import validate_event_log_path

        info["event_log"] = validate_event_log_path(ev)

    # event-log rotation limit: the tracer degrades to unbounded on a
    # bad value, so report it here where an operator will see it
    evmax = os.environ.get("BIGDL_TPU_EVENT_LOG_MAX_BYTES")
    if evmax:
        from bigdl_tpu.observability.tracing import \
            resolve_event_log_max_bytes

        try:
            info["event_log_max_bytes"] = {
                "value": resolve_event_log_max_bytes(evmax), "valid": True}
        except ValueError as e:
            info["event_log_max_bytes"] = {
                "value": evmax, "valid": False, "error": str(e)}

    # rotated-file retention: the tracer and the span sink both degrade
    # to keep=1 on a bad value, so surface it here
    evkeep = os.environ.get("BIGDL_TPU_EVENT_LOG_KEEP")
    if evkeep:
        from bigdl_tpu.observability.tracing import \
            resolve_event_log_keep

        try:
            info["event_log_keep"] = {
                "value": resolve_event_log_keep(evkeep), "valid": True}
        except ValueError as e:
            info["event_log_keep"] = {
                "value": evkeep, "valid": False, "error": str(e)}

    # distributed-trace tail sampling: the span recorder degrades to
    # 1.0 (record everything) on a bad value
    tsample = os.environ.get("BIGDL_TPU_TRACE_SAMPLE")
    if tsample:
        from bigdl_tpu.observability.disttrace import \
            resolve_trace_sample

        try:
            info["trace_sample"] = {
                "value": resolve_trace_sample(tsample), "valid": True}
        except ValueError as e:
            info["trace_sample"] = {
                "value": tsample, "valid": False, "error": str(e)}

    # postmortem dump directory: write_postmortem swallows failures by
    # contract, so an unwritable dir would otherwise only show up as a
    # missing dump after a crash
    pm = os.environ.get("BIGDL_TPU_POSTMORTEM_DIR")
    if pm:
        from bigdl_tpu.observability.flight import validate_postmortem_dir

        info["postmortem_dir"] = validate_postmortem_dir(pm)

    # recompile-storm warning threshold (compile_watch falls back to the
    # default on a bad value; surface it here instead)
    rw = os.environ.get("BIGDL_TPU_RECOMPILE_WARN")
    if rw:
        from bigdl_tpu.observability.compile_watch import \
            resolve_recompile_threshold

        try:
            info["recompile_warn"] = {
                "value": resolve_recompile_threshold(rw), "valid": True}
        except ValueError as e:
            info["recompile_warn"] = {
                "value": rw, "valid": False, "error": str(e)}

    # HBM admission budget fraction (the memory ledger falls back to
    # the default on a bad value; surface it here instead)
    bf = os.environ.get("BIGDL_TPU_HBM_BUDGET_FRACTION")
    if bf:
        from bigdl_tpu.observability.memory import \
            resolve_hbm_budget_fraction

        try:
            info["hbm_budget_fraction"] = {
                "value": resolve_hbm_budget_fraction(bf), "valid": True}
        except ValueError as e:
            info["hbm_budget_fraction"] = {
                "value": bf, "valid": False, "error": str(e)}

    # live memory_stats poll throttle (same fallback contract)
    mp = os.environ.get("BIGDL_TPU_MEMORY_POLL_SEC")
    if mp:
        from bigdl_tpu.observability.memory import resolve_memory_poll_sec

        try:
            info["memory_poll_sec"] = {
                "value": resolve_memory_poll_sec(mp), "valid": True}
        except ValueError as e:
            info["memory_poll_sec"] = {
                "value": mp, "valid": False, "error": str(e)}

    # KV cache storage dtype: fail loudly here rather than at the first
    # model load (a typo'd dtype name otherwise surfaces deep in
    # init_cache)
    kvd = os.environ.get("BIGDL_TPU_KV_CACHE_DTYPE")
    if kvd:
        from bigdl_tpu.ops.kvcache import (KV_CACHE_DTYPES,
                                           resolve_kv_cache_dtype)

        try:
            info["kv_cache_dtype"] = {
                "value": resolve_kv_cache_dtype(kvd), "valid": True}
        except ValueError:
            info["kv_cache_dtype"] = {
                "value": kvd, "valid": False,
                "choices": sorted(KV_CACHE_DTYPES)}

    # decode fast-path tristates (config.py from_env falls back to
    # "auto" on a bad value; surface the typo here instead): resident
    # single-dispatch decode and load-time weight prepack
    tristate_knobs = (
        ("decode_resident", "BIGDL_TPU_DECODE_RESIDENT",
         "resolve_decode_resident"),
        ("prepack", "BIGDL_TPU_PREPACK", "resolve_prepack"),
        ("sentinel", "BIGDL_TPU_SENTINEL", "resolve_sentinel"),
        ("quality", "BIGDL_TPU_QUALITY", "resolve_quality"),
        ("prefix_sharing", "BIGDL_TPU_PREFIX_SHARING",
         "resolve_prefix_sharing"),
        # paged-KV geometry (not tristates, but the same config.py
        # silently-fall-back contract: a typo'd page size means the
        # engine quietly runs the per-slot slab instead)
        ("kv_page_size", "BIGDL_TPU_KV_PAGE_SIZE",
         "resolve_kv_page_size"),
        ("kv_pages", "BIGDL_TPU_KV_PAGES", "resolve_kv_pages"),
    )
    for key, envname, fname in tristate_knobs:
        raw = os.environ.get(envname)
        if not raw:
            continue
        from bigdl_tpu import config as _config

        try:
            info[key] = {"value": getattr(_config, fname)(raw),
                         "valid": True}
        except ValueError as e:
            info[key] = {"value": raw, "valid": False, "error": str(e)}

    # perf-history baseline sink (the sentinel degrades to a live
    # baseline if the file is unwritable — report it up front, same
    # contract as the event log)
    ph = os.environ.get("BIGDL_TPU_PERF_HISTORY")
    if ph:
        from bigdl_tpu.observability.sentinel import \
            validate_perf_history_path

        info["perf_history"] = validate_perf_history_path(ph)

    # perf-regression sentinel tuning (the sentinel falls back to
    # defaults on bad values; surface range errors here instead)
    sentinel_knobs = (
        ("sentinel_threshold", "BIGDL_TPU_SENTINEL_THRESHOLD",
         "resolve_sentinel_threshold"),
        ("sentinel_trip_steps", "BIGDL_TPU_SENTINEL_TRIP_STEPS",
         "resolve_sentinel_trip_steps"),
        ("sentinel_recover_steps", "BIGDL_TPU_SENTINEL_RECOVER_STEPS",
         "resolve_sentinel_recover_steps"),
    )
    for key, envname, fname in sentinel_knobs:
        raw = os.environ.get(envname)
        if not raw:
            continue
        from bigdl_tpu.observability import sentinel as _sentinel

        try:
            info[key] = {"value": getattr(_sentinel, fname)(raw),
                         "valid": True}
        except ValueError as e:
            info[key] = {"value": raw, "valid": False, "error": str(e)}

    # quality-history baseline sink (same degrade-to-live contract as
    # the perf history)
    qh = os.environ.get("BIGDL_TPU_QUALITY_HISTORY")
    if qh:
        from bigdl_tpu.observability.quality import \
            validate_quality_history_path

        info["quality_history"] = validate_quality_history_path(qh)

    # QualitySentinel tuning + the golden-probe period (the sentinel
    # falls back to defaults on bad values; surface range errors here)
    quality_knobs = (
        ("quality_threshold", "BIGDL_TPU_QUALITY_THRESHOLD",
         "resolve_quality_threshold"),
        ("quality_trip_steps", "BIGDL_TPU_QUALITY_TRIP_STEPS",
         "resolve_quality_trip_steps"),
        ("quality_recover_steps", "BIGDL_TPU_QUALITY_RECOVER_STEPS",
         "resolve_quality_recover_steps"),
        ("quality_probe_steps", "BIGDL_TPU_QUALITY_PROBE_STEPS",
         "resolve_quality_probe_steps"),
    )
    for key, envname, fname in quality_knobs:
        raw = os.environ.get(envname)
        if not raw:
            continue
        from bigdl_tpu.observability import quality as _quality

        try:
            info[key] = {"value": getattr(_quality, fname)(raw),
                         "valid": True}
        except ValueError as e:
            info[key] = {"value": raw, "valid": False, "error": str(e)}

    # profiler capture time-box (start_profiler refuses to start on a
    # bad value, but an operator wants to know before the incident)
    pms = os.environ.get("BIGDL_TPU_PROFILER_MAX_SEC")
    if pms:
        from bigdl_tpu.utils.profiling import resolve_profiler_max_sec

        try:
            info["profiler_max_sec"] = {
                "value": resolve_profiler_max_sec(pms), "valid": True}
        except ValueError as e:
            info["profiler_max_sec"] = {
                "value": pms, "valid": False, "error": str(e)}

    # fault-injection spec: a typo'd spec silently injecting nothing
    # would make a chaos run vacuously green — fail the check instead
    fs = os.environ.get("BIGDL_TPU_FAULT_SPEC")
    if fs:
        from bigdl_tpu.robustness.faults import validate_fault_spec

        info["fault_spec"] = validate_fault_spec(fs)

    # default per-request deadline (the engine falls back to NO deadline
    # on a bad value; surface it here instead)
    dl = os.environ.get("BIGDL_TPU_REQUEST_DEADLINE_MS")
    if dl:
        from bigdl_tpu.robustness import resolve_request_deadline_ms

        try:
            info["request_deadline_ms"] = {
                "value": resolve_request_deadline_ms(dl), "valid": True}
        except ValueError as e:
            info["request_deadline_ms"] = {
                "value": dl, "valid": False, "error": str(e)}

    # graceful-drain window (engine falls back to the 30 s default)
    dt = os.environ.get("BIGDL_TPU_DRAIN_TIMEOUT_SEC")
    if dt:
        from bigdl_tpu.robustness import resolve_drain_timeout_sec

        try:
            info["drain_timeout_sec"] = {
                "value": resolve_drain_timeout_sec(dt), "valid": True}
        except ValueError as e:
            info["drain_timeout_sec"] = {
                "value": dt, "valid": False, "error": str(e)}

    # serving-router knobs (the router falls back to defaults on bad
    # values; surface range errors here instead)
    router_knobs = (
        ("router_health_sec", "BIGDL_TPU_ROUTER_HEALTH_SEC",
         "resolve_router_health_sec"),
        ("router_replicas", "BIGDL_TPU_ROUTER_REPLICAS",
         "resolve_router_replicas"),
        ("router_hedge_ms", "BIGDL_TPU_ROUTER_HEDGE_MS",
         "resolve_router_hedge_ms"),
        ("router_crash_budget", "BIGDL_TPU_ROUTER_CRASH_BUDGET",
         "resolve_router_crash_budget"),
    )
    for key, envname, fname in router_knobs:
        raw = os.environ.get(envname)
        if not raw:
            continue
        from bigdl_tpu.serving import router as _router

        try:
            info[key] = {"value": getattr(_router, fname)(raw),
                         "valid": True}
        except ValueError as e:
            info[key] = {"value": raw, "valid": False, "error": str(e)}

    # overload-control knobs (QoS / per-tenant limits / bounded queue /
    # brownout thresholds): the engine falls back to defaults on bad
    # values, so range errors surface here instead
    overload_knobs = (
        ("qos_default", "BIGDL_TPU_QOS_DEFAULT", "resolve_qos_default"),
        ("qos_aging_sec", "BIGDL_TPU_QOS_AGING_SEC",
         "resolve_qos_aging_sec"),
        ("tenant_rps", "BIGDL_TPU_TENANT_RPS", "resolve_tenant_rps"),
        ("tenant_tps", "BIGDL_TPU_TENANT_TPS", "resolve_tenant_tps"),
        ("tenant_burst", "BIGDL_TPU_TENANT_BURST",
         "resolve_tenant_burst"),
        ("brownout_high", "BIGDL_TPU_BROWNOUT_HIGH",
         "resolve_brownout_high"),
        ("brownout_low", "BIGDL_TPU_BROWNOUT_LOW",
         "resolve_brownout_low"),
        ("max_queue_depth", "BIGDL_TPU_MAX_QUEUE_DEPTH",
         "resolve_max_queue_depth"),
        ("max_queue_bytes", "BIGDL_TPU_MAX_QUEUE_BYTES",
         "resolve_max_queue_bytes"),
    )
    for key, envname, fname in overload_knobs:
        raw = os.environ.get(envname)
        if not raw:
            continue
        from bigdl_tpu.serving import overload as _overload

        try:
            info[key] = {"value": getattr(_overload, fname)(raw),
                         "valid": True}
        except ValueError as e:
            info[key] = {"value": raw, "valid": False, "error": str(e)}

    # fleet autoscaler bounds + dwell (the autoscaler falls back to
    # defaults on bad values; surface range errors here instead)
    autoscale_knobs = (
        ("autoscale_min", "BIGDL_TPU_AUTOSCALE_MIN",
         "resolve_autoscale_min"),
        ("autoscale_max", "BIGDL_TPU_AUTOSCALE_MAX",
         "resolve_autoscale_max"),
        ("autoscale_dwell_sec", "BIGDL_TPU_AUTOSCALE_DWELL_SEC",
         "resolve_autoscale_dwell_sec"),
    )
    for key, envname, fname in autoscale_knobs:
        raw = os.environ.get(envname)
        if not raw:
            continue
        from bigdl_tpu.serving import autoscaler as _autoscaler

        try:
            info[key] = {"value": getattr(_autoscaler, fname)(raw),
                         "valid": True}
        except ValueError as e:
            info[key] = {"value": raw, "valid": False, "error": str(e)}

    # KV-handoff transfer knobs + replica role (the api server refuses
    # to start on a bad role, but a typo'd timeout/retry count would
    # silently fall back — report both classes here)
    handoff_knobs = (
        ("replica_role", "BIGDL_TPU_REPLICA_ROLE",
         "resolve_replica_role"),
        ("handoff_timeout_ms", "BIGDL_TPU_HANDOFF_TIMEOUT_MS",
         "resolve_handoff_timeout_ms"),
        ("handoff_retries", "BIGDL_TPU_HANDOFF_RETRIES",
         "resolve_handoff_retries"),
    )
    for key, envname, fname in handoff_knobs:
        raw = os.environ.get(envname)
        if not raw:
            continue
        from bigdl_tpu.serving import api_server as _api_server

        try:
            info[key] = {"value": getattr(_api_server, fname)(raw),
                         "valid": True}
        except ValueError as e:
            info[key] = {"value": raw, "valid": False, "error": str(e)}

    # live-migration knobs (the api server falls back to defaults on a
    # bad timeout/size and refuses to start on a bad mode; the router
    # treats an unusable journal path as journal-off — all four classes
    # of typo get reported here instead of surfacing mid-drain)
    migrate_knobs = (
        ("live_migration", "BIGDL_TPU_LIVE_MIGRATION",
         "resolve_live_migration"),
        ("migrate_timeout_ms", "BIGDL_TPU_MIGRATE_TIMEOUT_MS",
         "resolve_migrate_timeout_ms"),
        ("migrate_max_bytes", "BIGDL_TPU_MIGRATE_MAX_BYTES",
         "resolve_migrate_max_bytes"),
    )
    for key, envname, fname in migrate_knobs:
        raw = os.environ.get(envname)
        if not raw:
            continue
        from bigdl_tpu.serving import api_server as _api_server

        try:
            info[key] = {"value": getattr(_api_server, fname)(raw),
                         "valid": True}
        except ValueError as e:
            info[key] = {"value": raw, "valid": False, "error": str(e)}

    # migrate-out peer list: free-form host:port entries, so just check
    # the shape — a malformed entry silently skips that peer at drain
    # time, which is the worst moment to learn about a typo
    mt = os.environ.get("BIGDL_TPU_MIGRATE_TARGETS")
    if mt:
        bad = []
        for t in (x.strip() for x in mt.split(",")):
            if not t:
                continue
            host, _, port = t.rpartition(":")
            if not host or not port.isdigit():
                bad.append(t)
        info["migrate_targets"] = (
            {"value": mt, "valid": True} if not bad else
            {"value": mt, "valid": False,
             "error": f"malformed host:port entries: {bad}"})

    # durable router journal path (the router degrades to in-memory on
    # a relative path or an unwritable file)
    rj = os.environ.get("BIGDL_TPU_ROUTER_JOURNAL")
    if rj:
        from bigdl_tpu.serving.router import resolve_router_journal

        try:
            resolved = resolve_router_journal(rj)
            writable = True
            err = None
            d = os.path.dirname(resolved) or "/"
            if not os.path.isdir(d):
                writable, err = False, f"directory does not exist: {d}"
            elif not os.access(d, os.W_OK):
                writable, err = False, f"directory not writable: {d}"
            info["router_journal"] = {"value": resolved,
                                      "valid": True, "writable": writable}
            if err:
                info["router_journal"]["error"] = err
        except ValueError as e:
            info["router_journal"] = {"value": rj, "valid": False,
                                      "error": str(e)}

    # fleet SLO engine / usage metering / canary probes: the tracker
    # swallows a bad spec (falls back to defaults) and the prober
    # treats a bad interval as off, so this is where a broken override
    # actually gets reported
    slo_spec = os.environ.get("BIGDL_TPU_SLO_SPEC")
    if slo_spec:
        from bigdl_tpu.observability.slo import resolve_slo_spec

        try:
            info["slo_spec"] = {"value": resolve_slo_spec(slo_spec),
                                "valid": True}
        except ValueError as e:
            info["slo_spec"] = {"value": slo_spec, "valid": False,
                                "error": str(e)}
    slo_log = os.environ.get("BIGDL_TPU_SLO_ALERT_LOG")
    if slo_log:
        from bigdl_tpu.observability.slo import \
            validate_slo_alert_log_path

        info["slo_alert_log"] = validate_slo_alert_log_path(slo_log)
    usage_log = os.environ.get("BIGDL_TPU_USAGE_LOG")
    if usage_log:
        from bigdl_tpu.observability.usage import \
            validate_usage_log_path

        info["usage_log"] = validate_usage_log_path(usage_log)
    canary_sec = os.environ.get("BIGDL_TPU_CANARY_SEC")
    if canary_sec:
        from bigdl_tpu.serving.canary import resolve_canary_sec

        try:
            info["canary_sec"] = {
                "value": resolve_canary_sec(canary_sec), "valid": True}
        except ValueError as e:
            info["canary_sec"] = {"value": canary_sec, "valid": False,
                                  "error": str(e)}

    # canary NLL-tolerance mode (the prober falls back to byte-equality
    # only on a bad value; surface it here instead)
    nll_tol = os.environ.get("BIGDL_TPU_CANARY_NLL_TOL")
    if nll_tol:
        from bigdl_tpu.serving.canary import resolve_canary_nll_tol

        try:
            info["canary_nll_tol"] = {
                "value": resolve_canary_nll_tol(nll_tol), "valid": True}
        except ValueError as e:
            info["canary_nll_tol"] = {"value": nll_tol, "valid": False,
                                      "error": str(e)}

    typos = find_env_typos()
    if typos:
        info["env_typos"] = typos
    return info


def main() -> int:
    info = collect()
    width = max(len(k) for k in info)
    for k, v in info.items():
        if k == "env":
            print("env flags:")
            for ek, ev in sorted(v.items()):
                print(f"  {ek}={ev}")
        else:
            print(f"{k:<{width}} : {v}")
    ok = ("jax_error" not in info and "bigdl_tpu_error" not in info
          and info.get("kv_cache_dtype", {}).get("valid", True)
          and info.get("event_log_max_bytes", {}).get("valid", True)
          and info.get("event_log_keep", {}).get("valid", True)
          and info.get("trace_sample", {}).get("valid", True)
          and info.get("recompile_warn", {}).get("valid", True)
          and info.get("hbm_budget_fraction", {}).get("valid", True)
          and info.get("memory_poll_sec", {}).get("valid", True)
          and info.get("decode_resident", {}).get("valid", True)
          and info.get("prepack", {}).get("valid", True)
          and info.get("sentinel", {}).get("valid", True)
          and info.get("prefix_sharing", {}).get("valid", True)
          and info.get("kv_page_size", {}).get("valid", True)
          and info.get("kv_pages", {}).get("valid", True)
          and info.get("sentinel_threshold", {}).get("valid", True)
          and info.get("sentinel_trip_steps", {}).get("valid", True)
          and info.get("sentinel_recover_steps", {}).get("valid", True)
          and info.get("profiler_max_sec", {}).get("valid", True)
          and info.get("perf_history", {}).get("writable", True)
          and info.get("fault_spec", {}).get("valid", True)
          and info.get("request_deadline_ms", {}).get("valid", True)
          and info.get("drain_timeout_sec", {}).get("valid", True)
          and info.get("router_health_sec", {}).get("valid", True)
          and info.get("router_replicas", {}).get("valid", True)
          and info.get("router_hedge_ms", {}).get("valid", True)
          and info.get("router_crash_budget", {}).get("valid", True)
          and info.get("qos_default", {}).get("valid", True)
          and info.get("qos_aging_sec", {}).get("valid", True)
          and info.get("tenant_rps", {}).get("valid", True)
          and info.get("tenant_tps", {}).get("valid", True)
          and info.get("tenant_burst", {}).get("valid", True)
          and info.get("brownout_high", {}).get("valid", True)
          and info.get("brownout_low", {}).get("valid", True)
          and info.get("max_queue_depth", {}).get("valid", True)
          and info.get("max_queue_bytes", {}).get("valid", True)
          and info.get("autoscale_min", {}).get("valid", True)
          and info.get("autoscale_max", {}).get("valid", True)
          and info.get("autoscale_dwell_sec", {}).get("valid", True)
          and info.get("replica_role", {}).get("valid", True)
          and info.get("handoff_timeout_ms", {}).get("valid", True)
          and info.get("handoff_retries", {}).get("valid", True)
          and info.get("live_migration", {}).get("valid", True)
          and info.get("migrate_timeout_ms", {}).get("valid", True)
          and info.get("migrate_max_bytes", {}).get("valid", True)
          and info.get("migrate_targets", {}).get("valid", True)
          and info.get("router_journal", {}).get("valid", True)
          and info.get("router_journal", {}).get("writable", True)
          and info.get("slo_spec", {}).get("valid", True)
          and info.get("canary_sec", {}).get("valid", True)
          and info.get("canary_nll_tol", {}).get("valid", True)
          and info.get("quality", {}).get("valid", True)
          and info.get("quality_threshold", {}).get("valid", True)
          and info.get("quality_trip_steps", {}).get("valid", True)
          and info.get("quality_recover_steps", {}).get("valid", True)
          and info.get("quality_probe_steps", {}).get("valid", True)
          and info.get("quality_history", {}).get("writable", True)
          and info.get("slo_alert_log", {}).get("writable", True)
          and info.get("usage_log", {}).get("writable", True)
          and not info.get("env_typos")
          and info.get("postmortem_dir", {}).get("writable", True))
    print("status :", "OK" if ok else "PROBLEMS FOUND")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
