"""Self-speculative decoding: low-bit draft proposes, target verifies.

TPU-native re-design of the reference's `speculative_generate` (reference
transformers/speculative.py:443-1022: host-side draft loop with adaptive
early stop, batched verify forward, greedy prefix-match or min(1,q/p)
rejection-sampling accept, and KV-cache rollback done by slicing/copying
cache tensors per architecture, speculative.py:393-439).

Everything that made the reference's version hard on accelerators is
restructured for XLA:

- **One dispatch per round.** Draft loop (`lax.while_loop`, early-exiting
  on draft confidence), target verify (one gamma+1-token forward), accept
  computation, and the cache rollback all run inside ONE jitted function;
  the host reads back one small (tokens, n_accept) tuple per round. The
  reference pays a host round-trip per draft token.
- **Rollback is index bookkeeping, not realloc.** Our KV caches are
  pre-allocated with validity tracked by a scalar `pos` (ops/kvcache.py);
  rejected entries beyond the accepted prefix are simply left in place —
  masked by position until overwritten. The reference copies/extends cache
  tensors (`_check_and_extend_kv_cache`).
- **Bonus token on full accept.** Verify runs over [cur, d_1..d_gamma]
  (gamma+1 positions), so a fully-accepted round emits gamma+1 tokens —
  the reference's bonus token (speculative.py ~:826), kept jit-static by
  one extra draft catch-up step that writes the last proposed token's KV.
- **Adaptive draft stop, compiled.** The draft while_loop exits when the
  draft's own probability of its pick drops below `th_stop_draft`
  (reference th_stop_draft, speculative.py:63) — saving the remaining
  draft forwards; the threshold is a traced scalar, so the host can adapt
  it between rounds (auto_th_stop_draft) with NO recompilation.

The draft is typically the same checkpoint at sym_int4 (self-speculation,
reference model.py:323-331) and the target bf16/fp8 — both share one
tokenizer, so only token ids cross model boundaries.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.observability.compile_watch import tracked_jit
from bigdl_tpu.ops.kvcache import KVCache


@dataclasses.dataclass
class SpecStats:
    """Reference telemetry equivalent (speculative.py:143-151:
    draft_time/verify_time/accept_num + draft_num for the auto
    threshold)."""
    rounds: int = 0
    accepted: List[int] = dataclasses.field(default_factory=list)
    drafted: List[int] = dataclasses.field(default_factory=list)
    round_s: List[float] = dataclasses.field(default_factory=list)
    first_token_s: float = 0.0

    @property
    def mean_accept(self) -> float:
        return float(np.mean(self.accepted)) if self.accepted else 0.0

    @property
    def accept_rate(self) -> float:
        d = float(np.sum(self.drafted))
        return float(np.sum(self.accepted)) / d if d else 0.0

    @property
    def tokens_per_round(self) -> float:
        return self.mean_accept + 1.0


def _spec_observe(mode: str, n_accept: int, n_draft: int,
                  round_s: float) -> None:
    """Publish one verify round to the observability registry
    (bigdl_tpu_spec_accept_ratio / _round_seconds / _tokens_total,
    labeled mode="draft_model"|"prompt_lookup"). Unconditional — unlike
    SpecStats, which only exists when the caller asks for it."""
    try:
        from bigdl_tpu.observability.metrics import (RATIO_BUCKETS,
                                                     default_registry)

        m = default_registry()
        if n_draft > 0:
            m.histogram("bigdl_tpu_spec_accept_ratio",
                        "Speculative decoding acceptance ratio per "
                        "verify round.", labelnames=("mode",),
                        buckets=RATIO_BUCKETS,
                        ).labels(mode).observe(n_accept / n_draft)
        m.histogram("bigdl_tpu_spec_round_seconds",
                    "Wall time of one draft+verify round.",
                    labelnames=("mode",)).labels(mode).observe(round_s)
        tok = m.counter("bigdl_tpu_spec_tokens_total",
                        "Draft tokens proposed / accepted.",
                        labelnames=("mode", "kind"))
        tok.labels(mode, "drafted").inc(n_draft)
        tok.labels(mode, "accepted").inc(n_accept)
    except Exception:
        pass  # telemetry must never break the decode loop


def make_spec_round(
    fwd_target: Callable,
    cfg_target: Any,
    fwd_draft: Callable,
    cfg_draft: Any,
    gamma: int,
    do_sample: bool = False,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
):
    """Build the fused per-round executable.

    round(params_t, params_d, cache_t, cache_d, cur_tok, key, th_stop) ->
        (out_tokens [B, gamma+1], n_accept [B], n_draft scalar,
         cache_t, cache_d, key)

    Emits n_accept+1 valid tokens per round: the accepted drafts plus the
    target's token at the first divergence — or, on a full accept of all
    n_draft proposals, the target's BONUS token after the last draft.
    `th_stop` (f32 scalar, traced) stops drafting early when the draft's
    confidence in its own pick falls below it; 0.0 drafts all gamma.
    """

    sampling = do_sample and temperature > 0.0

    @functools.partial(tracked_jit, "spec_round", donate_argnums=(2, 3))
    def spec_round(params_t, params_d, cache_t: KVCache, cache_d: KVCache,
                   cur_tok: jax.Array, key: jax.Array, th_stop: jax.Array):
        b = cur_tok.shape[0]
        pos0 = cache_t.pos

        # --- draft: up to gamma proposals + ONE catch-up step that only
        # writes the last proposal's KV (so a full accept + bonus leaves
        # the draft cache consistent) ---
        def one_draft(tok, cache, k):
            logits, cache = fwd_draft(params_d, cfg_draft, tok[:, None],
                                      cache)
            lg = logits[:, -1, :].astype(jnp.float32)
            if sampling:
                # identical tempering for the draw and the recorded q —
                # the accept ratio must use the true draft distribution
                tempered = lg / max(temperature, 1e-6)
                k, sk = jax.random.split(k)
                nxt = jax.random.categorical(
                    sk, tempered, axis=-1).astype(jnp.int32)
                q = jax.nn.softmax(tempered, axis=-1)
            else:
                nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                q = jax.nn.softmax(lg, axis=-1)
            conf = jnp.take_along_axis(q, nxt[:, None], axis=-1)[:, 0]
            return nxt, q, conf, cache, k

        # probe vocab once (first step always runs; also j=0 of the loop)
        key, dk = jax.random.split(key)
        d1, q1, conf1, cache_d, dk = one_draft(cur_tok, cache_d, dk)
        vocab = q1.shape[-1]
        buf_toks = jnp.zeros((gamma, b), jnp.int32).at[0].set(d1)
        buf_q = jnp.zeros((gamma, b, vocab), jnp.float32).at[0].set(q1)

        def cond(c):
            j, _, _, _, going = c
            return going & (j < gamma)

        def body(c):
            j, cache, k, bufs, _ = c
            toks, qs = bufs
            tok_j = toks[j - 1]                       # consume d_j
            d, q, cnf, cache, k = one_draft(tok_j, cache, k)
            toks = toks.at[j].set(d)
            qs = qs.at[j].set(q)
            # gate the NEXT iteration on this fresh proposal's confidence
            going = jnp.all(cnf >= th_stop)
            return (j + 1, cache, k, (toks, qs), going)

        going0 = jnp.all(conf1 >= th_stop)
        n_draft, cache_d, dk, (buf_toks, buf_q), _ = lax.while_loop(
            cond, body,
            (jnp.asarray(1, jnp.int32), cache_d, dk, (buf_toks, buf_q),
             going0))
        # catch-up: consume the last proposal so its KV is written;
        # its output token is discarded
        _, _, _, cache_d, _ = one_draft(buf_toks[n_draft - 1], cache_d, dk)

        draft_toks = buf_toks.T                     # [B, gamma]
        draft_q = jnp.moveaxis(buf_q, 0, 1)         # [B, gamma, V]

        # --- verify: ONE target forward over [cur, d_1..d_gamma] ---
        verify_in = jnp.concatenate([cur_tok[:, None], draft_toks], axis=1)
        logits_t, cache_t = fwd_target(params_t, cfg_target, verify_in,
                                       cache_t)     # [B, gamma+1, V]

        valid = jnp.arange(gamma)[None, :] < n_draft  # [1|B, gamma]

        if sampling:
            # min(1, p/q) rejection sampling (the reference's sampling
            # accept, speculative.py ~:775: q>=p accept / rejected resample)
            from bigdl_tpu.generation import filter_logits

            p = jax.nn.softmax(filter_logits(
                logits_t.astype(jnp.float32) / temperature, top_k, top_p),
                axis=-1)                            # [B, gamma+1, V]
            p_tok = jnp.take_along_axis(p[:, :-1], draft_toks[..., None],
                                        axis=-1)[..., 0]     # [B, gamma]
            q_tok = jnp.take_along_axis(draft_q, draft_toks[..., None],
                                        axis=-1)[..., 0]
            key, uk, rk = jax.random.split(key, 3)
            u = jax.random.uniform(uk, p_tok.shape)
            accepted = (u < jnp.minimum(1.0, p_tok /
                                        jnp.maximum(q_tok, 1e-20))) & valid
            n_accept = jnp.sum(
                jnp.cumprod(accepted.astype(jnp.int32), axis=1), axis=1)
            # token at position n: residual (p - q)+ on a true rejection
            # (n < n_draft); the target distribution itself on a full
            # accept (bonus token)
            p_n = jnp.take_along_axis(
                p, n_accept[:, None, None], axis=1)[:, 0]    # [B, V]
            q_pad = jnp.concatenate(
                [draft_q, jnp.zeros_like(draft_q[:, :1])], axis=1)
            q_n = jnp.take_along_axis(
                q_pad, n_accept[:, None, None], axis=1)[:, 0]
            resid = jnp.maximum(p_n - q_n, 0.0)
            resid_sum = jnp.sum(resid, axis=-1, keepdims=True)
            true_reject = n_accept < n_draft
            dist = jnp.where(
                (true_reject & (resid_sum[:, 0] > 1e-9))[:, None],
                resid / jnp.maximum(resid_sum, 1e-20), p_n)
            correction = jax.random.categorical(
                rk, jnp.log(jnp.maximum(dist, 1e-20)), axis=-1
            ).astype(jnp.int32)                     # [B]
            idx = jnp.arange(gamma + 1)[None, :]
            out = jnp.where(
                idx < n_accept[:, None],
                jnp.concatenate([draft_toks, draft_toks[:, -1:]], axis=1),
                correction[:, None])
        else:
            target_pred = jnp.argmax(logits_t, axis=-1).astype(jnp.int32)
            # --- accept: greedy prefix match over the proposed prefix ---
            matches = (draft_toks == target_pred[:, :-1]) & valid
            n_accept = jnp.sum(
                jnp.cumprod(matches.astype(jnp.int32), axis=1), axis=1)
            # out[i] = d_{i+1} for i < n_accept; target's token at
            # position n_accept (divergence fix OR bonus); garbage after
            idx = jnp.arange(gamma + 1)[None, :]
            out = jnp.where(
                idx < n_accept[:, None],
                jnp.concatenate([draft_toks, draft_toks[:, -1:]], axis=1),
                jnp.take_along_axis(target_pred, n_accept[:, None], axis=1))

        # --- rollback: pure index bookkeeping (reset_pos keeps any
        # family-specific cache state, e.g. ChatGLMCache anchors) ---
        new_pos = pos0 + n_accept[0] + 1            # B=1: scalar pos
        return (out, n_accept, n_draft, cache_t.reset_pos(new_pos),
                cache_d.reset_pos(new_pos), key)

    return spec_round


def _update_threshold(th: float, accept_rate: float,
                      target: float = 0.9, step: float = 0.02,
                      lo: float = 0.0, hi: float = 0.95) -> float:
    """auto_th_stop_draft (reference speculative.py:63-64,81): nudge the
    stop threshold toward a target per-round accept rate. Low accept rate
    -> raise the bar (draft fewer, surer tokens); high -> lower it."""
    return float(np.clip(th + (step if accept_rate < target else -step),
                         lo, hi))


def speculative_generate(
    params_target: Any,
    params_draft: Any,
    cfg_target: Any,
    cfg_draft: Any,
    input_ids,                              # [S] or [1, S] ints
    *,
    family_forward: Callable,
    family_prefill: Callable,
    new_cache: Callable,                    # (cfg, batch, max_seq) -> KVCache
    max_new_tokens: int = 128,
    gamma: int = 4,
    do_sample: bool = False,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
    eos_token_id: Optional[int] = None,
    max_seq: int = 2048,
    seed: int = 0,
    kv_quantized=False,
    kv_cache_dtype: Optional[str] = None,
    th_stop_draft: float = 0.8,
    auto_th_stop_draft: bool = True,
    stats: Optional[SpecStats] = None,
) -> np.ndarray:
    """Generate with draft/verify speculation. Returns new tokens [1, <=N].

    `family_forward/prefill` serve both models (self-speculation: same
    architecture, different qtype). `th_stop_draft`/`auto_th_stop_draft`
    mirror the reference's adaptive draft control (speculative.py:63-64);
    set th_stop_draft=0.0 to always draft the full gamma.
    """
    ids = np.asarray(input_ids, np.int32)
    if ids.ndim == 1:
        ids = ids[None]
    if ids.shape[0] != 1:
        raise ValueError("speculative decoding supports batch size 1 "
                         "(as the reference does)")
    s = ids.shape[1]
    if s + max_new_tokens + gamma + 1 > max_seq:
        raise ValueError(f"prompt ({s}) + max_new_tokens ({max_new_tokens}) "
                         f"+ gamma+1 ({gamma + 1}) exceeds max_seq {max_seq}")

    from bigdl_tpu.ops.kvcache import resolve_kv_cache_dtype

    # canonical dtype string rides the legacy positional `quantized` slot
    # of the family new_cache adapters (they resolve bools and names)
    kv_dtype = resolve_kv_cache_dtype(
        kv_cache_dtype if kv_cache_dtype is not None else kv_quantized)
    cache_t = new_cache(cfg_target, 1, max_seq, kv_dtype)
    cache_d = new_cache(cfg_draft, 1, max_seq, kv_dtype)

    prefill = tracked_jit("spec_prefill", family_prefill,
                          static_argnums=1, donate_argnums=3)

    t0 = time.perf_counter()
    toks = jnp.asarray(ids)
    logits_t, cache_t = prefill(params_target, cfg_target, toks, cache_t)
    _, cache_d = prefill(params_draft, cfg_draft, toks, cache_d)
    cur = jnp.argmax(logits_t[:, -1, :], axis=-1).astype(jnp.int32)
    cur_host = int(np.asarray(cur)[0])
    if stats is not None:
        stats.first_token_s = time.perf_counter() - t0

    spec_round = make_spec_round(
        family_forward, cfg_target, family_forward, cfg_draft, gamma,
        do_sample=do_sample, temperature=temperature, top_k=top_k,
        top_p=top_p)

    out: List[int] = [cur_host]
    key = jax.random.PRNGKey(seed)
    th = float(th_stop_draft)
    while len(out) < max_new_tokens:
        if eos_token_id is not None and out and out[-1] == eos_token_id:
            break
        t1 = time.perf_counter()
        toks_r, n_acc, n_drf, cache_t, cache_d, key = spec_round(
            params_target, params_draft, cache_t, cache_d, cur, key,
            jnp.asarray(th, jnp.float32))
        toks_host = np.asarray(toks_r)[0]
        n = int(np.asarray(n_acc)[0])
        nd = int(np.asarray(n_drf))      # scalar loop counter
        round_s = time.perf_counter() - t1
        _spec_observe("draft_model", n, nd, round_s)
        if stats is not None:
            stats.rounds += 1
            stats.accepted.append(n)
            stats.drafted.append(nd)
            stats.round_s.append(round_s)
        if auto_th_stop_draft and th_stop_draft > 0.0:
            th = _update_threshold(th, n / max(nd, 1))
        emitted = list(toks_host[: n + 1])
        if eos_token_id is not None and eos_token_id in emitted:
            emitted = emitted[: emitted.index(eos_token_id) + 1]
        out.extend(int(t) for t in emitted)
        cur = toks_r[:, n]
    return np.asarray(out[:max_new_tokens], np.int32)[None]


# ---------------------------------------------------------------------------
# Prompt-lookup speculation: n-gram drafts from the token HISTORY, no
# draft model at all (beyond the reference, whose only speculation is
# self-speculation with a quantized draft model, speculative.py:443).
# Greedy decoding stays EXACT — the target verifies every proposal —
# while repetitive spans (code, quotes, retrieved context) decode up to
# gamma+1 tokens per target forward.


def make_lookup_round(fwd_target: Callable, cfg_target: Any, gamma: int,
                      ngram: int = 2):
    """Build the fused per-round executable for prompt-lookup.

    round(params_t, cache_t, hist, hist_len, cur_tok) ->
        (out_tokens [1, gamma+1], n_accept [1], found flag, cache_t)

    The driver below intentionally mirrors speculative_generate's loop
    (same validation, prefill timing, eos truncation) with lookup state
    instead of draft-model state — keep edits to either in sync.

    `hist` is the full token sequence so far (prompt + emitted), valid
    up to `hist_len`, with `cur_tok == hist[hist_len-1]`. The draft is
    the gamma tokens FOLLOWING the most recent earlier occurrence of the
    trailing `ngram` tokens; with no match the round degrades to a
    plain (verified) single-token step. All index work happens on
    device — no host sync inside the round.
    """

    @functools.partial(tracked_jit, "lookup_round", donate_argnums=(1,))
    def lookup_round(params_t, cache_t: KVCache, hist: jax.Array,
                     hist_len: jax.Array, cur_tok: jax.Array):
        pos0 = cache_t.pos
        size = hist.shape[0]
        pos_ar = jnp.arange(size, dtype=jnp.int32)

        # positions p whose trailing ngram equals the CURRENT trailing
        # ngram (hist[hist_len-ngram .. hist_len-1]); p itself must be
        # strictly before the current position so the draft is history
        match = (pos_ar >= ngram - 1) & (pos_ar < hist_len - 1)
        for j in range(ngram):
            h_at = hist[jnp.clip(pos_ar - j, 0, size - 1)]
            match &= h_at == hist[jnp.clip(hist_len - 1 - j, 0, size - 1)]
        p_best = jnp.max(jnp.where(match, pos_ar, -1))
        found = p_best >= 0

        draft = jnp.where(
            found,
            hist[jnp.clip(p_best + 1 + jnp.arange(gamma), 0, size - 1)],
            0)[None, :]                                     # [1, gamma]
        # proposals past the end of written history are stale guesses;
        # they simply fail verification

        verify_in = jnp.concatenate([cur_tok[:, None], draft], axis=1)
        logits_t, cache_t = fwd_target(params_t, cfg_target, verify_in,
                                       cache_t)             # [1, g+1, V]
        target_pred = jnp.argmax(logits_t, axis=-1).astype(jnp.int32)

        matches = (draft == target_pred[:, :-1]) & found
        n_accept = jnp.sum(
            jnp.cumprod(matches.astype(jnp.int32), axis=1), axis=1)
        idx = jnp.arange(gamma + 1)[None, :]
        out = jnp.where(
            idx < n_accept[:, None],
            jnp.concatenate([draft, draft[:, -1:]], axis=1),
            jnp.take_along_axis(target_pred, n_accept[:, None], axis=1))

        new_pos = pos0 + n_accept[0] + 1
        return out, n_accept, found, cache_t.reset_pos(new_pos)

    return lookup_round


def prompt_lookup_generate(
    params: Any,
    cfg: Any,
    input_ids,                              # [S] or [1, S] ints
    *,
    family_forward: Callable,
    family_prefill: Callable,
    new_cache: Callable,                    # (cfg, batch, max_seq) -> KVCache
    max_new_tokens: int = 128,
    gamma: int = 8,
    ngram: int = 2,
    eos_token_id: Optional[int] = None,
    max_seq: int = 2048,
    kv_quantized=False,
    kv_cache_dtype: Optional[str] = None,
    stats: Optional[SpecStats] = None,
) -> np.ndarray:
    """Greedy generation with prompt-lookup speculation. Returns new
    tokens [1, <=N], identical to plain greedy decoding."""
    ids = np.asarray(input_ids, np.int32)
    if ids.ndim == 1:
        ids = ids[None]
    if ids.shape[0] != 1:
        raise ValueError("prompt-lookup decoding supports batch size 1")
    s = ids.shape[1]
    if s + max_new_tokens + gamma + 1 > max_seq:
        raise ValueError(f"prompt ({s}) + max_new_tokens "
                         f"({max_new_tokens}) + gamma+1 ({gamma + 1}) "
                         f"exceeds max_seq {max_seq}")

    from bigdl_tpu.ops.kvcache import resolve_kv_cache_dtype

    cache = new_cache(cfg, 1, max_seq, resolve_kv_cache_dtype(
        kv_cache_dtype if kv_cache_dtype is not None else kv_quantized))
    prefill = tracked_jit("lookup_prefill", family_prefill,
                          static_argnums=1, donate_argnums=3)

    t0 = time.perf_counter()
    logits, cache = prefill(params, cfg, jnp.asarray(ids), cache)
    cur = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
    cur_host = int(np.asarray(cur)[0])
    if stats is not None:
        stats.first_token_s = time.perf_counter() - t0

    lookup_round = make_lookup_round(family_forward, cfg, gamma, ngram)

    hist = np.zeros((max_seq,), np.int32)
    hist[:s] = ids[0]
    hist_len = s + 1
    hist[s] = cur_host

    out: List[int] = [cur_host]
    while len(out) < max_new_tokens:
        if eos_token_id is not None and out[-1] == eos_token_id:
            break
        t1 = time.perf_counter()
        toks_r, n_acc, found, cache = lookup_round(
            params, cache, jnp.asarray(hist),
            jnp.asarray(hist_len, jnp.int32), cur)
        toks_host = np.asarray(toks_r)[0]
        n = int(np.asarray(n_acc)[0])
        round_s = time.perf_counter() - t1
        # a no-match round proposed NOTHING — recording gamma would
        # deflate accept_rate vs draft-model speculation, whose
        # driver records the true n_draft
        nd = gamma if bool(np.asarray(found)) else 0
        _spec_observe("prompt_lookup", n, nd, round_s)
        if stats is not None:
            stats.rounds += 1
            stats.accepted.append(n)
            stats.drafted.append(nd)
            stats.round_s.append(round_s)
        emitted = list(toks_host[: n + 1])
        if eos_token_id is not None and eos_token_id in emitted:
            emitted = emitted[: emitted.index(eos_token_id) + 1]
        out.extend(int(t) for t in emitted)
        k = len(emitted)
        hist[hist_len: hist_len + k] = emitted[:max(0, max_seq - hist_len)]
        hist_len = min(hist_len + k, max_seq)
        cur = toks_r[:, min(n, gamma)]
    return np.asarray(out[:max_new_tokens], np.int32)[None]
