"""Self-speculative decoding: low-bit draft proposes, target verifies.

TPU-native re-design of the reference's `speculative_generate` (reference
transformers/speculative.py:443-1022: host-side draft loop with adaptive
early stop, batched verify forward, greedy prefix-match or min(1,q/p)
rejection-sampling accept, and KV-cache rollback done by slicing/copying
cache tensors per architecture, speculative.py:393-439).

Everything that made the reference's version hard on accelerators is
restructured for XLA:

- **One dispatch per round.** Draft loop (fixed gamma steps, `lax.scan`),
  target verify (one gamma-token forward), accept computation, and the cache
  rollback all run inside ONE jitted function; the host reads back one small
  (tokens, n_accept) tuple per round. The reference pays a host round-trip
  per draft token.
- **Rollback is index bookkeeping, not realloc.** Our KV caches are
  pre-allocated with validity tracked by a scalar `pos` (ops/kvcache.py);
  rejected entries beyond the accepted prefix are simply left in place —
  masked by position until overwritten. The reference copies/extends cache
  tensors (`_check_and_extend_kv_cache`).
- **Static accept bound.** At most gamma-1 drafts are accepted per round
  (full-accept forfeits the reference's "bonus token"), which keeps both
  caches exactly consistent with no variable-length catch-up forward.

The draft is typically the same checkpoint at sym_int4 (self-speculation,
reference model.py:323-331) and the target bf16/fp8 — both share one
tokenizer, so only token ids cross model boundaries.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.ops.kvcache import KVCache


@dataclasses.dataclass
class SpecStats:
    """Reference telemetry equivalent (speculative.py:143-151:
    draft_time/verify_time/accept_num)."""
    rounds: int = 0
    accepted: List[int] = dataclasses.field(default_factory=list)
    round_s: List[float] = dataclasses.field(default_factory=list)
    first_token_s: float = 0.0

    @property
    def mean_accept(self) -> float:
        return float(np.mean(self.accepted)) if self.accepted else 0.0

    @property
    def tokens_per_round(self) -> float:
        return self.mean_accept + 1.0


def make_spec_round(
    fwd_target: Callable,
    cfg_target: Any,
    fwd_draft: Callable,
    cfg_draft: Any,
    gamma: int,
    do_sample: bool = False,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
):
    """Build the fused per-round executable.

    round(params_t, params_d, cache_t, cache_d, cur_tok, key) ->
        (out_tokens [B, gamma], n_accept [B], cache_t, cache_d, key)

    Emits n_accept+1 valid tokens per round (accepted drafts + the target's
    next token at the first divergence).
    """

    sampling = do_sample and temperature > 0.0

    @functools.partial(jax.jit, donate_argnums=(2, 3))
    def spec_round(params_t, params_d, cache_t: KVCache, cache_d: KVCache,
                   cur_tok: jax.Array, key: jax.Array):
        b = cur_tok.shape[0]
        pos0 = cache_t.pos

        # --- draft: gamma steps (greedy, or sampled under the same
        # temperature as the target — required for rejection sampling) ---
        def dstep(carry, _):
            tok, cache, k = carry
            logits, cache = fwd_draft(params_d, cfg_draft, tok[:, None], cache)
            lg = logits[:, -1, :].astype(jnp.float32)
            if sampling:
                # identical tempering for the draw and the recorded q —
                # the accept ratio must use the true draft distribution
                tempered = lg / max(temperature, 1e-6)
                k, sk = jax.random.split(k)
                nxt = jax.random.categorical(
                    sk, tempered, axis=-1).astype(jnp.int32)
                q = jax.nn.softmax(tempered, axis=-1)
            else:
                nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                q = jax.nn.softmax(lg, axis=-1)
            return (nxt, cache, k), (nxt, q)

        key, dk = jax.random.split(key)
        (_, cache_d, _), (draft_toks, draft_q) = lax.scan(
            dstep, (cur_tok, cache_d, dk), None, length=gamma)
        draft_toks = draft_toks.T                   # [B, gamma]
        draft_q = jnp.moveaxis(draft_q, 0, 1)       # [B, gamma, V]

        # --- verify: ONE target forward over [cur_tok, d_1..d_{gamma-1}] ---
        verify_in = jnp.concatenate([cur_tok[:, None], draft_toks[:, :-1]],
                                    axis=1)  # [B, gamma]
        logits_t, cache_t = fwd_target(params_t, cfg_target, verify_in, cache_t)

        if sampling:
            # min(1, p/q) rejection sampling (the reference's sampling
            # accept, speculative.py ~:775: q>=p accept / rejected resample)
            from bigdl_tpu.generation import filter_logits

            p = jax.nn.softmax(filter_logits(
                logits_t.astype(jnp.float32) / temperature, top_k, top_p),
                axis=-1)
            p_tok = jnp.take_along_axis(p, draft_toks[..., None],
                                        axis=-1)[..., 0]     # [B, gamma]
            q_tok = jnp.take_along_axis(draft_q, draft_toks[..., None],
                                        axis=-1)[..., 0]
            key, uk, rk = jax.random.split(key, 3)
            u = jax.random.uniform(uk, p_tok.shape)
            accepted = u < jnp.minimum(1.0, p_tok / jnp.maximum(q_tok, 1e-20))
            n_accept = jnp.minimum(
                jnp.sum(jnp.cumprod(accepted.astype(jnp.int32), axis=1),
                        axis=1),
                gamma - 1)                          # [B]
            # correction at position n: sample from (p - q)+ if n was a
            # true rejection, else (cap hit) from p directly
            p_n = jnp.take_along_axis(
                p, n_accept[:, None, None], axis=1)[:, 0]    # [B, V]
            q_n = jnp.take_along_axis(
                draft_q, n_accept[:, None, None], axis=1)[:, 0]
            resid = jnp.maximum(p_n - q_n, 0.0)
            resid_sum = jnp.sum(resid, axis=-1, keepdims=True)
            was_rejected = jnp.take_along_axis(
                ~accepted, n_accept[:, None], axis=1)[:, 0]
            dist = jnp.where((was_rejected & (resid_sum[:, 0] > 1e-9))[:, None],
                             resid / jnp.maximum(resid_sum, 1e-20), p_n)
            correction = jax.random.categorical(
                rk, jnp.log(jnp.maximum(dist, 1e-20)), axis=-1
            ).astype(jnp.int32)                     # [B]
            idx = jnp.arange(gamma)[None, :]
            out = jnp.where(idx < n_accept[:, None], draft_toks,
                            correction[:, None])
        else:
            target_pred = jnp.argmax(logits_t, axis=-1).astype(jnp.int32)
            # --- accept: greedy prefix match, capped at gamma-1 ---
            matches = (draft_toks == target_pred)   # [B, gamma]
            n_accept = jnp.minimum(
                jnp.sum(jnp.cumprod(matches.astype(jnp.int32), axis=1),
                        axis=1),
                gamma - 1)                          # [B]
            # out[i] = d_{i+1} for i < n_accept, target_pred[n_accept] at
            # i==n, garbage after (host slices by n_accept+1)
            idx = jnp.arange(gamma)[None, :]
            out = jnp.where(idx < n_accept[:, None], draft_toks,
                            jnp.take_along_axis(
                                target_pred, n_accept[:, None], axis=1))

        # --- rollback: pure index bookkeeping ---
        new_pos = pos0 + n_accept[0] + 1            # B=1: scalar pos
        cache_t = KVCache(cache_t.k, cache_t.v, new_pos)
        cache_d = KVCache(cache_d.k, cache_d.v, new_pos)
        return out, n_accept, cache_t, cache_d, key

    return spec_round


def speculative_generate(
    params_target: Any,
    params_draft: Any,
    cfg_target: Any,
    cfg_draft: Any,
    input_ids,                              # [S] or [1, S] ints
    *,
    family_forward: Callable,
    family_prefill: Callable,
    new_cache: Callable,                    # (cfg, batch, max_seq) -> KVCache
    max_new_tokens: int = 128,
    gamma: int = 4,
    do_sample: bool = False,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
    eos_token_id: Optional[int] = None,
    max_seq: int = 2048,
    seed: int = 0,
    kv_quantized: bool = False,
    stats: Optional[SpecStats] = None,
) -> np.ndarray:
    """Generate with draft/verify speculation. Returns new tokens [1, <=N].

    `family_forward/prefill` serve both models (self-speculation: same
    architecture, different qtype).
    """
    ids = np.asarray(input_ids, np.int32)
    if ids.ndim == 1:
        ids = ids[None]
    if ids.shape[0] != 1:
        raise ValueError("speculative decoding supports batch size 1 "
                         "(as the reference does)")
    s = ids.shape[1]
    if s + max_new_tokens + gamma > max_seq:
        raise ValueError(f"prompt ({s}) + max_new_tokens ({max_new_tokens}) "
                         f"+ gamma ({gamma}) exceeds max_seq {max_seq}")

    cache_t = new_cache(cfg_target, 1, max_seq, kv_quantized)
    cache_d = new_cache(cfg_draft, 1, max_seq, kv_quantized)

    prefill = jax.jit(family_prefill, static_argnums=1, donate_argnums=3)

    t0 = time.perf_counter()
    toks = jnp.asarray(ids)
    logits_t, cache_t = prefill(params_target, cfg_target, toks, cache_t)
    _, cache_d = prefill(params_draft, cfg_draft, toks, cache_d)
    cur = jnp.argmax(logits_t[:, -1, :], axis=-1).astype(jnp.int32)
    cur_host = int(np.asarray(cur)[0])
    if stats is not None:
        stats.first_token_s = time.perf_counter() - t0

    spec_round = make_spec_round(
        family_forward, cfg_target, family_forward, cfg_draft, gamma,
        do_sample=do_sample, temperature=temperature, top_k=top_k,
        top_p=top_p)

    out: List[int] = [cur_host]
    key = jax.random.PRNGKey(seed)
    while len(out) < max_new_tokens:
        if eos_token_id is not None and out and out[-1] == eos_token_id:
            break
        t1 = time.perf_counter()
        toks_r, n_acc, cache_t, cache_d, key = spec_round(
            params_target, params_draft, cache_t, cache_d, cur, key)
        toks_host = np.asarray(toks_r)[0]
        n = int(np.asarray(n_acc)[0])
        if stats is not None:
            stats.rounds += 1
            stats.accepted.append(n)
            stats.round_s.append(time.perf_counter() - t1)
        emitted = list(toks_host[: n + 1])
        if eos_token_id is not None and eos_token_id in emitted:
            emitted = emitted[: emitted.index(eos_token_id) + 1]
        out.extend(int(t) for t in emitted)
        cur = toks_r[:, n]
    return np.asarray(out[:max_new_tokens], np.int32)[None]
