"""RWKV v4/v5 family: recurrent (attention-free) language models.

TPU-native re-design of the reference's RWKV support
(reference transformers/models/rwkv4.py and rwkv5.py, whose hot loops call
the native SYCL ops `rwkv_linear_attention_v4`, `rwkv_linear_attention_v5`
and `rwkv_time_shift` — SURVEY.md §2.3-C). Here the same computation is
expressed the XLA way:

- All projections (key/value/receptance/gate/output, and the channel-mix
  MLP) are hoisted OUT of the recurrence and run as big [B*T, D] x [D, N]
  matmuls — quantizable QTensors on the MXU, exactly like the transformer
  families.
- Only the tiny elementwise state recurrence (the WKV scan) runs under
  `lax.scan` over time; its carry is the recurrent state, so prefill and
  decode are the same code at different T. Decode cost is O(state), with
  no KV cache at all — RWKV's selling point survives intact.
- State is a first-class pytree (`RwkvState`), donated between decode
  steps like the transformer KV cache.

v4 ("RwkvForCausalLM", HF transformers modeling_rwkv semantics): scalar
channel state (aa, bb, pp) with the exp-max stabilization trick.
v5.2 ("Rwkv5ForCausalLM", BlinkDL Eagle): per-head matrix state
S[H, hd, hd], decayed by exp(-exp(w)) with bonus u (time_faaaa), grouped
LayerNorm over heads, silu gate.

Numerics: the recurrence and norms run in f32; projections run in the
compute dtype (bf16 by default) so quantized weights hit the fused
dequant-matmul path. The reference's fp16 `rescale_every` weight-halving
exists only to dodge fp16 overflow and has no bf16/f32 analog here.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.ops.embedding import embedding_lookup
from bigdl_tpu.ops.matmul import linear
from bigdl_tpu.ops.norms import layer_norm

_NEG_INF = -1e38


@dataclasses.dataclass(frozen=True)
class RwkvConfig:
    vocab_size: int = 50277
    hidden_size: int = 768
    num_hidden_layers: int = 12
    intermediate_size: int = 3072
    attention_hidden_size: int = 768
    layer_norm_eps: float = 1e-5
    head_size: int = 64            # v5
    version: int = 4               # 4 | 5
    tie_word_embeddings: bool = False
    # BlinkDL group_norm eps: 64e-5 (= 1e-5 * head_size_divisor**2, 8**2)
    ln_x_eps: float = 64e-5

    @property
    def num_heads(self) -> int:
        return self.attention_hidden_size // self.head_size

    @classmethod
    def from_hf(cls, hf: Dict[str, Any], version: int) -> "RwkvConfig":
        d = hf["hidden_size"]
        inter = hf.get("intermediate_size")
        if inter is None:
            # HF defaults: v4 = 4*D; v5 world = round(3.5*D) down to /32
            inter = 4 * d if version == 4 else int(d * 3.5) // 32 * 32
        return cls(
            vocab_size=hf["vocab_size"],
            hidden_size=d,
            num_hidden_layers=hf["num_hidden_layers"],
            intermediate_size=inter,
            attention_hidden_size=hf.get("attention_hidden_size", d),
            layer_norm_eps=hf.get("layer_norm_epsilon", 1e-5),
            head_size=hf.get("head_size", 64),
            version=version,
            tie_word_embeddings=hf.get("tie_word_embeddings", False),
        )


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class RwkvState:
    """Recurrent state. v4: (aa, bb, pp) per channel; v5: matrix state s.

    att_x / ffn_x are the previous token's normed activations (the
    reference's `rwkv_time_shift` native op is this one-element history).
    `max_seq` is nominal — RWKV state is O(1) in sequence length; it only
    satisfies the generation API's capacity check.
    """

    att_x: jax.Array                 # [L, B, D]
    ffn_x: jax.Array                 # [L, B, D]
    aa: Optional[jax.Array]          # v4 [L, B, Da]
    bb: Optional[jax.Array]          # v4 [L, B, Da]
    pp: Optional[jax.Array]          # v4 [L, B, Da]
    s: Optional[jax.Array]           # v5 [L, B, H, hd, hd]
    pos: jax.Array                   # scalar int32
    _max_seq: int = 1 << 30

    def tree_flatten(self):
        return ((self.att_x, self.ffn_x, self.aa, self.bb, self.pp,
                 self.s, self.pos), (self._max_seq,))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, _max_seq=aux[0])

    @property
    def max_seq(self) -> int:
        return self._max_seq


def new_cache(cfg: RwkvConfig, batch: int, max_seq: int,
              quantized: bool = False) -> RwkvState:
    """Fresh zero state (the `new_cache` adapter hook; `quantized` is
    accepted for interface parity — state is tiny, nothing to quantize)."""
    L, B, D = cfg.num_hidden_layers, batch, cfg.hidden_size
    Da = cfg.attention_hidden_size
    zeros = lambda *shape: jnp.zeros(shape, jnp.float32)
    if cfg.version == 4:
        return RwkvState(
            att_x=zeros(L, B, D), ffn_x=zeros(L, B, D),
            aa=zeros(L, B, Da), bb=zeros(L, B, Da),
            pp=jnp.full((L, B, Da), _NEG_INF, jnp.float32),
            s=None, pos=jnp.zeros((), jnp.int32), _max_seq=max_seq)
    H, hd = cfg.num_heads, cfg.head_size
    return RwkvState(
        att_x=zeros(L, B, D), ffn_x=zeros(L, B, D),
        aa=None, bb=None, pp=None,
        s=zeros(L, B, H, hd, hd),
        pos=jnp.zeros((), jnp.int32), _max_seq=max_seq)


def _token_shift(xn: jax.Array, prev_x: jax.Array) -> jax.Array:
    """[B, T, D] -> previous-token view: [prev_x, xn[:, :-1]]."""
    return jnp.concatenate([prev_x[:, None, :], xn[:, :-1, :]], axis=1)


def _lerp(xn, prev, mix):
    """RWKV time-mix interpolation x*mu + x_prev*(1-mu), f32."""
    m = mix.astype(jnp.float32)
    return xn * m + prev * (1.0 - m)


def _wkv_v4(k, v, w, u, aa, bb, pp):
    """v4 WKV recurrence with exp-max stabilization.

    k, v: [B, T, Da] f32; w (= -exp(time_decay)), u: [Da];
    state aa/bb/pp: [B, Da]. Returns (out [B, T, Da], new state).
    """
    kT = k.transpose(1, 0, 2)
    vT = v.transpose(1, 0, 2)

    def step(carry, kv):
        aa, bb, pp = carry
        kt, vt = kv
        ww = u + kt
        qq = jnp.maximum(pp, ww)
        e1 = jnp.exp(pp - qq)
        e2 = jnp.exp(ww - qq)
        out = (e1 * aa + e2 * vt) / (e1 * bb + e2)
        ww = pp + w
        qq = jnp.maximum(ww, kt)
        e1 = jnp.exp(ww - qq)
        e2 = jnp.exp(kt - qq)
        return (e1 * aa + e2 * vt, e1 * bb + e2, qq), out

    (aa, bb, pp), outT = lax.scan(step, (aa, bb, pp), (kT, vT))
    return outT.transpose(1, 0, 2), (aa, bb, pp)


def _wkv_v5(r, k, v, w, u, s):
    """v5 matrix-state recurrence.

    r, k, v: [B, T, H, hd] f32; w (= exp(-exp(time_decay))), u: [H, hd];
    s: [B, H, hd, hd] (k-index first). Returns (out [B, T, H, hd], s).
    """
    rT = r.transpose(1, 0, 2, 3)
    kT = k.transpose(1, 0, 2, 3)
    vT = v.transpose(1, 0, 2, 3)

    def step(s, rkv):
        rt, kt, vt = rkv
        at = jnp.einsum("bhi,bhj->bhij", kt, vt)
        yt = jnp.einsum("bhi,bhij->bhj", rt,
                        u[None, :, :, None] * at + s)
        s = at + w[None, :, :, None] * s
        return s, yt

    s, yT = lax.scan(step, s, (rT, kT, vT))
    return yT.transpose(1, 0, 2, 3), s


def _group_norm(x, weight, bias, num_groups: int, eps: float):
    """GroupNorm over the channel dim of [B, T, D] (v5 ln_x)."""
    b, t, d = x.shape
    xg = x.reshape(b, t, num_groups, d // num_groups).astype(jnp.float32)
    mu = jnp.mean(xg, axis=-1, keepdims=True)
    var = jnp.var(xg, axis=-1, keepdims=True)
    y = ((xg - mu) * lax.rsqrt(var + eps)).reshape(b, t, d)
    return y * weight.astype(jnp.float32) + bias.astype(jnp.float32)


def _time_mix(x, lp, cfg: RwkvConfig, st, compute_dtype):
    """Attention-analog block. x [B,T,D] f32. Returns (out, new state)."""
    xn = layer_norm(x, lp["ln1"], lp["ln1_bias"], cfg.layer_norm_eps)
    prev = _token_shift(xn, st["att_x"])
    new_att_x = xn[:, -1, :]

    proj = lambda y, wkey, bkey=None: linear(
        y.astype(compute_dtype), lp[wkey]).astype(jnp.float32)

    k = proj(_lerp(xn, prev, lp["att_mix_k"]), "att_key")
    v = proj(_lerp(xn, prev, lp["att_mix_v"]), "att_value")
    r = proj(_lerp(xn, prev, lp["att_mix_r"]), "att_receptance")

    if cfg.version == 4:
        w = -jnp.exp(lp["att_decay"].astype(jnp.float32))
        u = lp["att_first"].astype(jnp.float32)
        wkv, (aa, bb, pp) = _wkv_v4(k, v, w, u, st["aa"], st["bb"], st["pp"])
        out = jax.nn.sigmoid(r) * wkv
        out = linear(out.astype(compute_dtype), lp["att_output"])
        return out.astype(jnp.float32), dict(
            att_x=new_att_x, aa=aa, bb=bb, pp=pp)

    b, t, _ = x.shape
    H, hd = cfg.num_heads, cfg.head_size
    g = proj(_lerp(xn, prev, lp["att_mix_g"]), "att_gate")
    w = jnp.exp(-jnp.exp(lp["att_decay"].astype(jnp.float32))).reshape(H, hd)
    u = lp["att_first"].astype(jnp.float32).reshape(H, hd)
    y, s = _wkv_v5(r.reshape(b, t, H, hd), k.reshape(b, t, H, hd),
                   v.reshape(b, t, H, hd), w, u, st["s"])
    y = _group_norm(y.reshape(b, t, H * hd), lp["ln_x"], lp["ln_x_bias"],
                    H, cfg.ln_x_eps)
    y = y * jax.nn.silu(g)
    out = linear(y.astype(compute_dtype), lp["att_output"])
    return out.astype(jnp.float32), dict(att_x=new_att_x, s=s)


def _channel_mix(x, lp, cfg: RwkvConfig, prev_ffn_x, compute_dtype):
    """MLP-analog block: r ⊙ Wv(relu(Wk(x̃))²). Returns (out, new ffn_x)."""
    xn = layer_norm(x, lp["ln2"], lp["ln2_bias"], cfg.layer_norm_eps)
    prev = _token_shift(xn, prev_ffn_x)
    proj = lambda y, wkey: linear(
        y.astype(compute_dtype), lp[wkey]).astype(jnp.float32)
    k = proj(_lerp(xn, prev, lp["ffn_mix_k"]), "ffn_key")
    r = proj(_lerp(xn, prev, lp["ffn_mix_r"]), "ffn_receptance")
    inner = jnp.square(jax.nn.relu(k))
    out = jax.nn.sigmoid(r) * proj(inner, "ffn_value")
    return out, xn[:, -1, :]


def forward(
    params: Dict[str, Any],
    cfg: RwkvConfig,
    tokens: jax.Array,        # [B, T] int32
    state: RwkvState,
    compute_dtype=jnp.bfloat16,
    last_only: bool = False,
) -> Tuple[jax.Array, RwkvState]:
    """Run T tokens through the recurrence; returns (logits f32, state).

    Prefill and decode are the same function (T = prompt length vs 1);
    the state carry replaces the transformer KV cache.
    """
    x = embedding_lookup(params["embed_tokens"], tokens, jnp.float32)
    x = layer_norm(x, params["pre_ln"], params["pre_ln_bias"],
                   cfg.layer_norm_eps)

    if cfg.version == 4:
        st_slices = dict(att_x=state.att_x, ffn_x=state.ffn_x,
                         aa=state.aa, bb=state.bb, pp=state.pp)
    else:
        st_slices = dict(att_x=state.att_x, ffn_x=state.ffn_x, s=state.s)

    def step(x, xs):
        lp, st = xs
        att, new_att = _time_mix(x, lp, cfg, st, compute_dtype)
        x = x + att
        ffn, new_ffn_x = _channel_mix(x, lp, cfg, st["ffn_x"], compute_dtype)
        x = x + ffn
        new_att["ffn_x"] = new_ffn_x
        return x, new_att

    x, new_st = lax.scan(step, x, (params["layers"], st_slices))

    if last_only:
        x = x[:, -1:, :]
    x = layer_norm(x, params["norm"], params["norm_bias"],
                   cfg.layer_norm_eps)
    logits = linear(x.astype(compute_dtype), params["lm_head"])
    logits = logits.astype(jnp.float32)

    if cfg.version == 4:
        out_state = RwkvState(
            att_x=new_st["att_x"], ffn_x=new_st["ffn_x"], aa=new_st["aa"],
            bb=new_st["bb"], pp=new_st["pp"], s=None,
            pos=state.pos + tokens.shape[1], _max_seq=state._max_seq)
    else:
        out_state = RwkvState(
            att_x=new_st["att_x"], ffn_x=new_st["ffn_x"],
            aa=None, bb=None, pp=None, s=new_st["s"],
            pos=state.pos + tokens.shape[1], _max_seq=state._max_seq)
    return logits, out_state


def forward_last_token(params, cfg, tokens, state, compute_dtype=jnp.bfloat16):
    return forward(params, cfg, tokens, state, compute_dtype=compute_dtype,
                   last_only=True)


def forward_train(params, cfg, tokens, compute_dtype=jnp.bfloat16,
                  attn_fn=None, pos_offset=0):
    """Cacheless training forward (fresh zero state). Sequence-parallel
    attn_fn does not apply to a recurrence; train long contexts with
    BPTT-style chunking instead."""
    if attn_fn is not None:
        raise NotImplementedError(
            "RWKV is recurrent; ring-attention sequence parallelism does "
            "not apply (chunk the sequence and carry state instead)")
    b = tokens.shape[0]
    logits, _ = forward(params, cfg, tokens,
                        new_cache(cfg, b, int(tokens.shape[1])),
                        compute_dtype=compute_dtype)
    return logits


# ---------------------------------------------------------------------------
# HF checkpoint conversion (reference analog: convert.py routes rwkv
# architectures to models/rwkv4.py / rwkv5.py forwards)
# ---------------------------------------------------------------------------

_ATT_LINEARS = {
    "attention.key.weight": "att_key",
    "attention.value.weight": "att_value",
    "attention.receptance.weight": "att_receptance",
    "attention.gate.weight": "att_gate",
    "attention.output.weight": "att_output",
    "feed_forward.key.weight": "ffn_key",
    "feed_forward.receptance.weight": "ffn_receptance",
    "feed_forward.value.weight": "ffn_value",
}

_MIX_PARAMS = {
    "attention.time_mix_key": "att_mix_k",
    "attention.time_mix_value": "att_mix_v",
    "attention.time_mix_receptance": "att_mix_r",
    "attention.time_mix_gate": "att_mix_g",
    # v6-style names map to the same slots when encountered
    "attention.time_decay": "att_decay",
    "attention.time_first": "att_first",
    "attention.time_faaaa": "att_first",
    "feed_forward.time_mix_key": "ffn_mix_k",
    "feed_forward.time_mix_receptance": "ffn_mix_r",
}

_NORMS = {
    "ln1.weight": "ln1", "ln1.bias": "ln1_bias",
    "ln2.weight": "ln2", "ln2.bias": "ln2_bias",
    "attention.ln_x.weight": "ln_x", "attention.ln_x.bias": "ln_x_bias",
}


def _rwkv_map(acc, name: str, w) -> None:
    from bigdl_tpu.models.convert_base import layer_idx

    name_ = name[len("rwkv."):] if name.startswith("rwkv.") else name
    f32 = lambda a: jnp.asarray(np.asarray(a), jnp.float32)
    if name_ == "embeddings.weight":
        acc.top["embed_tokens"] = acc.dense(w)
    elif name_ == "blocks.0.pre_ln.weight":
        acc.top["pre_ln"] = f32(w)
    elif name_ == "blocks.0.pre_ln.bias":
        acc.top["pre_ln_bias"] = f32(w)
    elif name_ == "ln_out.weight":
        acc.top["norm"] = f32(w)
    elif name_ == "ln_out.bias":
        acc.top["norm_bias"] = f32(w)
    elif name_ == "head.weight":
        acc.top["lm_head"] = acc.linear(name, w)
    else:
        hit = layer_idx(name_, "blocks.")
        if hit is None:
            return
        idx, sub = hit
        if sub in _ATT_LINEARS:
            acc.put(_ATT_LINEARS[sub], idx, acc.linear(name, w))
        elif sub in _MIX_PARAMS:
            # recurrence parameters stay f32: decay enters a double exp,
            # where bf16 rounding visibly shifts the state trajectory
            acc.put(_MIX_PARAMS[sub], idx, f32(w).reshape(-1))
        elif sub in _NORMS:
            acc.put(_NORMS[sub], idx, f32(w))


def convert_hf_params(tensors, cfg: RwkvConfig, qtype="sym_int4",
                      compute_dtype=jnp.bfloat16,
                      modules_to_not_convert: Tuple[str, ...] = (),
                      imatrix=None):
    from bigdl_tpu.models.convert_base import make_convert

    return make_convert(_rwkv_map)(
        tensors, cfg, qtype=qtype, compute_dtype=compute_dtype,
        modules_to_not_convert=modules_to_not_convert, imatrix=imatrix)
