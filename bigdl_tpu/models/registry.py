"""Architecture registry: HF `architectures[0]` -> family adapter.

The reference's conversion engine special-cases 30 model families via
monkey-patched forwards chosen in `_optimize_post` (reference
transformers/convert.py:785-1357). Here each family is an adapter bundling
config parsing, checkpoint conversion, and forward functions; families that
are llama-shaped (mistral, qwen2, ...) reuse the llama module with config
deltas instead of carrying 400-line forks.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional


@dataclasses.dataclass(frozen=True)
class FamilyAdapter:
    name: str
    config_from_hf: Callable[[Dict[str, Any]], Any]
    convert_params: Callable[..., Any]     # (tensors, cfg, qtype, ...) -> pytree
    forward: Callable                       # (params, cfg, tokens, cache)
    prefill: Callable                       # last-token-only variant
    forward_train: Optional[Callable]
    new_cache: Callable                     # (cfg, batch, max_seq, quantized)
    # Recurrent families (RWKV/mamba-style): the "cache" is absorbed state,
    # not a KV cache. Gates (a) speculative decoding (no rollback) and
    # (b) prompt padding in the Generator (state cannot mask pads).
    is_recurrent: bool = False


_REGISTRY: Dict[str, Any] = {}


def register_family(arch_names, adapter) -> None:
    """adapter: a FamilyAdapter, or a callable dispatcher
    `(hf_config | None) -> FamilyAdapter` for arch names shared by
    structurally different versions (chatglm v1 vs v2/3)."""
    for a in arch_names:
        _REGISTRY[a] = adapter


def get_family(arch: str,
               hf_config: Optional[Dict[str, Any]] = None) -> FamilyAdapter:
    try:
        entry = _REGISTRY[arch]
    except KeyError:
        raise ValueError(
            f"unsupported architecture {arch!r}; supported: "
            f"{sorted(_REGISTRY)}") from None
    if isinstance(entry, FamilyAdapter):
        return entry
    return entry(hf_config)


def supported_architectures():
    return sorted(_REGISTRY)


def _register_builtin() -> None:
    from bigdl_tpu.models import llama as llama_mod

    def llama_adapter(config_tweak=None):
        def cfg_from_hf(hf):
            cfg = llama_mod.LlamaConfig.from_hf(hf)
            return config_tweak(cfg, hf) if config_tweak else cfg
        return FamilyAdapter(
            name="llama",
            config_from_hf=cfg_from_hf,
            convert_params=llama_mod.convert_hf_params,
            forward=llama_mod.forward,
            prefill=llama_mod.forward_last_token,
            forward_train=llama_mod.forward_train,
            new_cache=llama_mod.new_cache,
        )

    register_family(
        ["LlamaForCausalLM", "MistralForCausalLM", "CodeLlamaForCausalLM",
         # llama-shaped aliases (the reference also routes these through
         # its llama forwards, convert.py:785-1357)
         "AquilaForCausalLM", "InternLMForCausalLM", "YiForCausalLM",
         "DeciLMForCausalLM"],
        llama_adapter())

    def qwen2_tweak(cfg, hf):
        # HF Qwen2 has QKV bias but no attention_bias flag in config.json
        return dataclasses.replace(cfg, attention_bias=True)

    register_family(["Qwen2ForCausalLM"], llama_adapter(qwen2_tweak))

    from bigdl_tpu.models import families

    families.register_all()

    from bigdl_tpu.models import mixtral as mixtral_mod

    register_family(
        ["MixtralForCausalLM"],
        FamilyAdapter(
            name="mixtral",
            config_from_hf=mixtral_mod.MixtralConfig.from_hf,
            convert_params=mixtral_mod.convert_hf_params,
            forward=mixtral_mod.forward,
            prefill=mixtral_mod.forward_last_token,
            forward_train=mixtral_mod.forward_train,
            new_cache=mixtral_mod.new_cache,
        ))

    from bigdl_tpu.models import rwkv as rwkv_mod

    def rwkv_adapter(version: int) -> FamilyAdapter:
        return FamilyAdapter(
            name=f"rwkv{version}",
            config_from_hf=lambda hf: rwkv_mod.RwkvConfig.from_hf(
                hf, version),
            convert_params=rwkv_mod.convert_hf_params,
            forward=rwkv_mod.forward,
            prefill=rwkv_mod.forward_last_token,
            forward_train=rwkv_mod.forward_train,
            new_cache=rwkv_mod.new_cache,
            is_recurrent=True,
        )

    register_family(["RwkvForCausalLM"], rwkv_adapter(4))
    register_family(["Rwkv5ForCausalLM", "RwkvWorldForCausalLM"],
                    rwkv_adapter(5))

    from bigdl_tpu.models import yuan as yuan_mod

    register_family(["YuanForCausalLM"], FamilyAdapter(
        name="yuan",
        config_from_hf=yuan_mod.config_from_hf,
        convert_params=yuan_mod.convert_hf_params,
        forward=yuan_mod.forward,
        prefill=yuan_mod.forward_last_token,
        forward_train=yuan_mod.forward_train,
        new_cache=yuan_mod.new_cache,
        # the LFA conv history cannot mask pad tokens or rewind
        is_recurrent=True,
    ))


_register_builtin()
