"""Yuan 2.0: llama-style decoder with Localized Filtering Attention (LFA).

TPU-native re-design of the reference's yuan path (reference
transformers/models/yuan.py: `yuan_localized_filtering_forward` at :56-93,
`yuan_attention_forward_origin` at :318 — Q and K are projected from a
causally-filtered view of the normed hidden states; V from the raw normed
hidden; the filter is two cross-channel 2-tap convolutions + LayerNorm with
a residual).

The reference carries the last-2 hidden states inside its KV tuple and
runs cuDNN-style Conv2d per token. Here:
- Prefill computes the filter as two shifted MATMUL pairs
  (c1_t = x_{t-1} W1a + x_t W1b; lf_t = c1_{t-1} W2a + c1_t W2b), which is
  exactly the (2,1)-kernel Conv2d unrolled — MXU-batched over [B*S, D],
  no conv primitive needed.
- Decode carries a [L, B, 2, D] history of the last two normed hiddens in
  `YuanCache` next to the static KV cache (the analog of the reference's
  `past_key_value[2]`). Like RWKV, the family is flagged recurrent: pad
  tokens would pollute the history, so prefill runs at exact prompt
  length and speculative rollback is rejected.

Yuan's MLP applies the activation to up_proj (reference yuan.py:141:
`down(act(up(x)) * gate(x))`) — the checkpoint's up/gate are SWAPPED into
our gated-MLP slots at conversion so the one decoder body serves it.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.models import llama as M
from bigdl_tpu.models.llama import LlamaConfig
from bigdl_tpu.ops.attention import sdp_attention
from bigdl_tpu.ops.kvcache import KVCache, init_cache as init_kv, \
    reject_scaled_kv, \
    read_layer, update_layer
from bigdl_tpu.ops.matmul import linear
from bigdl_tpu.ops.norms import layer_norm, rms_norm
from bigdl_tpu.ops.rope import apply_rope, rope_cos_sin


def config_from_hf(hf: Dict[str, Any]) -> LlamaConfig:
    return LlamaConfig.from_hf(hf)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class YuanCache:
    """KV cache + per-layer last-2 normed-hidden history (LFA state)."""

    kv: KVCache
    hist: jax.Array            # [L, B, 2, D] f32

    def tree_flatten(self):
        return (self.kv, self.hist), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def pos(self):
        return self.kv.pos

    @property
    def max_seq(self) -> int:
        return self.kv.max_seq


def new_cache(cfg: LlamaConfig, batch: int, max_seq: int,
              quantized=False) -> YuanCache:
    reject_scaled_kv(quantized, "yuan")
    return YuanCache(
        kv=init_kv(cfg.num_hidden_layers, batch, max_seq,
                   cfg.num_key_value_heads, cfg.hd, quantized=quantized),
        hist=jnp.zeros((cfg.num_hidden_layers, batch, 2, cfg.hidden_size),
                       jnp.float32))


def _conv_tap(prev, cur, w, b):
    """One (2,1)-kernel cross-channel conv tap: prev @ Wa + cur @ Wb + b.

    w: [D_out, D_in, 2, 1] (HF Conv2d layout, f32)."""
    wa = w[:, :, 0, 0]
    wb = w[:, :, 1, 0]
    out = (jnp.dot(prev, wa.T, preferred_element_type=jnp.float32)
           + jnp.dot(cur, wb.T, preferred_element_type=jnp.float32))
    return out + b.astype(jnp.float32)


def _lfa_prefill(xn, lp, eps):
    """Localized filtering over a full sequence. xn [B, S, D] f32."""
    shift = lambda a: jnp.concatenate(
        [jnp.zeros_like(a[:, :1]), a[:, :-1]], axis=1)
    c1 = _conv_tap(shift(xn), xn, lp["lf_conv1"], lp["lf_conv1_bias"])
    out = _conv_tap(shift(c1), c1, lp["lf_conv2"], lp["lf_conv2_bias"])
    return layer_norm(out + xn, lp["lf_norm"], lp["lf_norm_bias"], eps)


def _lfa_decode(x1, hist, lp, eps, pos):
    """One-token filter from the [B, 2, D] history. x1 [B, 1, D] f32.

    `pos` = tokens already consumed. The prefill path's shifted sequence
    has an exact ZERO for c1_{-1} (no conv bias); with an empty history
    the naive conv of zeros would inject the bias, so c1_prev is masked
    out until a real t-1 exists (pos >= 1)."""
    h0, h1 = hist[:, 0], hist[:, 1]
    x = x1[:, 0]
    c1_prev = _conv_tap(h0, h1, lp["lf_conv1"], lp["lf_conv1_bias"])
    c1_prev = jnp.where(pos >= 1, c1_prev, 0.0)
    c1_cur = _conv_tap(h1, x, lp["lf_conv1"], lp["lf_conv1_bias"])
    out = _conv_tap(c1_prev, c1_cur, lp["lf_conv2"], lp["lf_conv2_bias"])
    lf = layer_norm((out + x)[:, None, :], lp["lf_norm"],
                    lp["lf_norm_bias"], eps)
    return lf


def _layer(x, lp, cfg, cos, sin, ck, cv, lidx, pos, hist):
    b, sq, d = x.shape
    h, hkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.hd
    eps = cfg.rms_norm_eps

    hidden = rms_norm(x, lp["input_layernorm"], eps).astype(jnp.float32)
    if sq == 1:
        lf = _lfa_decode(hidden, hist, lp, eps, pos)
        new_hist = jnp.concatenate([hist[:, 1:], hidden], axis=1)
    else:
        lf = _lfa_prefill(hidden, lp, eps)
        new_hist = hidden[:, -2:, :]

    cdt = x.dtype
    q = linear(lf.astype(cdt), lp["q_proj"]).reshape(b, sq, h, hd)
    k = linear(lf.astype(cdt), lp["k_proj"]).reshape(b, sq, hkv, hd)
    v = linear(hidden.astype(cdt), lp["v_proj"]).reshape(b, sq, hkv, hd)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    ck, cv = update_layer(ck, cv, lidx, k, v, pos)
    kf, vf = read_layer(ck, cv, lidx)
    attn = sdp_attention(q, kf, vf, pos).reshape(b, sq, h * hd)
    x = x + linear(attn, lp["o_proj"])

    hidden2 = rms_norm(x, lp["post_attention_layernorm"], eps)
    x = x + M._mlp(hidden2, lp, cfg)
    return x, ck, cv, new_hist


def forward(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    tokens: jax.Array,
    cache: YuanCache,
    compute_dtype=jnp.bfloat16,
    last_only: bool = False,
) -> Tuple[jax.Array, YuanCache]:
    b, sq = tokens.shape
    pos = cache.pos
    x = M.embedding_lookup(params["embed_tokens"], tokens, compute_dtype)
    inv_freq, _ = M.model_rope_freqs(cfg)
    positions = pos + jnp.arange(sq, dtype=jnp.int32)
    cos, sin = rope_cos_sin(positions[None, :], inv_freq)

    lidx = jnp.arange(cfg.num_hidden_layers, dtype=jnp.int32)

    def step(carry, xs):
        x, ck, cv = carry
        lp, li, hist = xs
        x, ck, cv, new_hist = _layer(x, lp, cfg, cos, sin, ck, cv, li, pos,
                                     hist)
        return (x, ck, cv), new_hist

    (x, ck, cv), new_hist = lax.scan(
        step, (x, cache.kv.k, cache.kv.v),
        (params["layers"], lidx, cache.hist))

    if last_only:
        x = x[:, -1:, :]
    x = rms_norm(x, params["norm"], cfg.rms_norm_eps)
    logits = M._lm_head(x, params, cfg)
    return logits, YuanCache(kv=KVCache(ck, cv, pos + sq), hist=new_hist)


def forward_last_token(params, cfg, tokens, cache, compute_dtype=jnp.bfloat16):
    return forward(params, cfg, tokens, cache, compute_dtype=compute_dtype,
                   last_only=True)


def forward_train(params, cfg, tokens, compute_dtype=jnp.bfloat16,
                  attn_fn=None, pos_offset=0):
    """Cacheless forward (fresh state; LFA prefill path throughout)."""
    if attn_fn is not None:
        raise NotImplementedError(
            "yuan's localized filtering is stateful along the sequence; "
            "ring-attention sequence parallelism is not supported")
    b = tokens.shape[0]
    logits, _ = forward(params, cfg, tokens,
                        new_cache(cfg, b, int(tokens.shape[1])),
                        compute_dtype=compute_dtype)
    return logits


# -- conversion ---------------------------------------------------------------


def _yuan_map(acc, name: str, w) -> None:
    from bigdl_tpu.models.convert_base import layer_idx

    f32 = lambda a: jnp.asarray(np.asarray(a), jnp.float32)
    if name == "model.embed_tokens.weight":
        acc.top["embed_tokens"] = acc.dense(w)
    elif name == "model.norm.weight":
        acc.top["norm"] = acc.dense(w)
    elif name == "lm_head.weight":
        acc.top["lm_head"] = acc.linear(name, w)
    else:
        hit = layer_idx(name, "model.layers.")
        if hit is None:
            return
        idx, sub = hit
        m = {
            "self_attn.q_proj.weight": ("q_proj", "linear"),
            "self_attn.k_proj.weight": ("k_proj", "linear"),
            "self_attn.v_proj.weight": ("v_proj", "linear"),
            "self_attn.o_proj.weight": ("o_proj", "linear"),
            # activation sits on yuan's up_proj -> our gate slot
            "mlp.up_proj.weight": ("gate_proj", "linear"),
            "mlp.gate_proj.weight": ("up_proj", "linear"),
            "mlp.down_proj.weight": ("down_proj", "linear"),
            "input_layernorm.weight": ("input_layernorm", "dense"),
            "post_attention_layernorm.weight":
                ("post_attention_layernorm", "dense"),
            "self_attn.lf_gate.conv1.weight": ("lf_conv1", "f32"),
            "self_attn.lf_gate.conv1.bias": ("lf_conv1_bias", "f32"),
            "self_attn.lf_gate.conv2.weight": ("lf_conv2", "f32"),
            "self_attn.lf_gate.conv2.bias": ("lf_conv2_bias", "f32"),
            "self_attn.lf_gate.output_layernorm.weight":
                ("lf_norm", "f32"),
            "self_attn.lf_gate.output_layernorm.bias":
                ("lf_norm_bias", "f32"),
        }.get(sub)
        if m:
            key, kind = m
            if kind == "linear":
                acc.put(key, idx, acc.linear(name, w))
            elif kind == "f32":
                acc.put(key, idx, f32(w))
            else:
                acc.put(key, idx, acc.dense(w))


def convert_hf_params(tensors, cfg, qtype="sym_int4",
                      compute_dtype=jnp.bfloat16,
                      modules_to_not_convert: Tuple[str, ...] = (),
                      imatrix=None):
    from bigdl_tpu.models.convert_base import make_convert

    return make_convert(_yuan_map)(
        tensors, cfg, qtype=qtype, compute_dtype=compute_dtype,
        modules_to_not_convert=modules_to_not_convert, imatrix=imatrix)
