"""Mixtral (sparse MoE) model: functional, static-shape, expert-sharded.

TPU-native re-design of the reference's Mixtral path (reference
transformers/models/mixtral.py: `mixtral_moeblock_forward` at :79-138 — a
Python loop over experts with a `.cpu().tolist()` host sync to pick the
top-k on decode, which is unacceptable on TPU). Here expert dispatch is a
one-hot einsum combine with NO host sync and no data-dependent shapes:

- All experts are evaluated and combined with routing weights
  (`combine[n,e]`), the standard dense-MoE formulation that XLA maps onto
  batched MXU matmuls. With int4-packed experts the full-expert weight read
  is the same byte count as reading 2 bf16 experts, so even decode stays
  HBM-reasonable; a top-k-gathering Pallas kernel is the planned upgrade.
- Expert weights are stacked [L, E, K, N] (layer, expert leading axes on
  every QTensor leaf), so the `ep` mesh axis shards axis E and `tp` shards
  N — XLA inserts the all-to-all/psum (SURVEY.md §2.2: the reference has NO
  cross-device expert parallelism at all).

Attention/embeddings/lm_head reuse the llama module's layout exactly.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.models import llama as llama_mod
from bigdl_tpu.models.llama import LlamaConfig
from bigdl_tpu.ops.attention import sdp_attention
from bigdl_tpu.ops.kvcache import (KVCache, read_layer,
                                   read_layer_quantized, update_layer)
from bigdl_tpu.ops.matmul import linear, q_matmul
from bigdl_tpu.ops.norms import rms_norm
from bigdl_tpu.ops.quant import QTensor
from bigdl_tpu.ops.rope import apply_rope, rope_cos_sin


@dataclasses.dataclass(frozen=True)
class MixtralConfig(LlamaConfig):
    num_local_experts: int = 8
    num_experts_per_tok: int = 2

    @classmethod
    def from_hf(cls, hf: Dict[str, Any]) -> "MixtralConfig":
        kw = dataclasses.asdict(LlamaConfig.from_hf(hf))
        kw.pop("num_local_experts", None)   # now also LlamaConfig fields
        kw.pop("num_experts_per_tok", None)
        return cls(
            **kw,
            num_local_experts=hf.get("num_local_experts", 8),
            num_experts_per_tok=hf.get("num_experts_per_tok", 2),
        )


# Parameter pytree layout: llama's, with the mlp keys replaced by
# {
#   "router":       [L, D, E] dense (small; kept full precision, as the
#                   reference excludes the gate from quantization),
#   "experts_gate": QTensor/dense stacked [L, E, D, F],   (HF w1)
#   "experts_up":   QTensor/dense stacked [L, E, D, F],   (HF w3)
#   "experts_down": QTensor/dense stacked [L, E, F, D],   (HF w2)
# }


def moe_block(x: jax.Array, lp: Dict[str, Any], cfg: MixtralConfig) -> jax.Array:
    """Sparse-MoE MLP: route, evaluate experts, one-hot combine. [B,T,D].

    One implementation serves every MoE family: the generalized decoder's
    `_moe_mlp` (models/llama.py) handles mixtral's gated expert layout
    (cfg.mlp_gated=True) and phixtral's dense fc1/fc2 experts."""
    return llama_mod._moe_mlp(x, lp, cfg)


def _layer_step(cfg: MixtralConfig, carry, xs):
    x, ck, cv, cks, cvs, pos, cos, sin = carry
    lp, lidx = xs
    b, sq, d = x.shape
    h, hkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.hd

    hidden = rms_norm(x, lp["input_layernorm"], cfg.rms_norm_eps)
    q = linear(hidden, lp["q_proj"]).reshape(b, sq, h, hd)
    k = linear(hidden, lp["k_proj"]).reshape(b, sq, hkv, hd)
    v = linear(hidden, lp["v_proj"]).reshape(b, sq, hkv, hd)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    if cks is not None:   # block-scaled int8/int4 storage (see llama)
        ck, cv, cks, cvs = update_layer(ck, cv, lidx, k, v, pos, cks, cvs)
        kq, vq, ksc, vsc = read_layer_quantized(ck, cv, cks, cvs, lidx)
        attn = sdp_attention(q, kq, vq, pos,
                             sliding_window=cfg.sliding_window,
                             k_scale=ksc, v_scale=vsc)
    else:
        ck, cv = update_layer(ck, cv, lidx, k, v, pos)
        kf, vf = read_layer(ck, cv, lidx)
        attn = sdp_attention(q, kf, vf, pos,
                             sliding_window=cfg.sliding_window)
    x = x + linear(attn.reshape(b, sq, h * hd), lp["o_proj"])

    hidden = rms_norm(x, lp["post_attention_layernorm"], cfg.rms_norm_eps)
    x = x + moe_block(hidden, lp, cfg)
    return (x, ck, cv, cks, cvs, pos, cos, sin), None


def forward(
    params: Dict[str, Any],
    cfg: MixtralConfig,
    tokens: jax.Array,
    cache: KVCache,
    compute_dtype=jnp.bfloat16,
    last_only: bool = False,
) -> Tuple[jax.Array, KVCache]:
    b, sq = tokens.shape
    pos = cache.pos
    x = llama_mod.embedding_lookup(params["embed_tokens"], tokens,
                                   compute_dtype)
    inv_freq, rope_mscale = llama_mod.model_rope_freqs(cfg)
    if getattr(pos, "ndim", 0) == 1:   # per-slot positions (serving)
        positions = pos[:, None] + jnp.arange(sq, dtype=jnp.int32)[None, :]
        cos, sin = rope_cos_sin(positions, inv_freq)
    else:
        positions = pos + jnp.arange(sq, dtype=jnp.int32)
        cos, sin = rope_cos_sin(positions[None, :], inv_freq)
    if rope_mscale != 1.0:
        cos, sin = cos * rope_mscale, sin * rope_mscale

    lidx = jnp.arange(cfg.num_hidden_layers, dtype=jnp.int32)
    (x, ck, cv, cks, cvs, _, _, _), _ = lax.scan(
        lambda c, xs: _layer_step(cfg, c, xs),
        (x, cache.k, cache.v, cache.k_scale, cache.v_scale, pos, cos, sin),
        (params["layers"], lidx),
    )

    if last_only:
        x = x[:, -1:, :]
    x = rms_norm(x, params["norm"], cfg.rms_norm_eps)
    lm_head = params.get("lm_head")
    if lm_head is None:
        logits = jnp.dot(x, params["embed_tokens"].T.astype(x.dtype),
                         preferred_element_type=jnp.float32)
    else:
        logits = linear(x, lm_head)
    return logits.astype(jnp.float32), KVCache(ck, cv, pos + sq, cks, cvs)


def forward_last_token(params, cfg, tokens, cache, compute_dtype=jnp.bfloat16):
    return forward(params, cfg, tokens, cache, compute_dtype=compute_dtype,
                   last_only=True)


def forward_train(
    params: Dict[str, Any],
    cfg: MixtralConfig,
    tokens: jax.Array,
    compute_dtype=jnp.bfloat16,
) -> jax.Array:
    """Cacheless causal forward (QLoRA finetuning of MoE models)."""
    b, s = tokens.shape
    x = llama_mod.embedding_lookup(params["embed_tokens"], tokens,
                                   compute_dtype)
    inv_freq, rope_mscale = llama_mod.model_rope_freqs(cfg)
    cos, sin = rope_cos_sin(jnp.arange(s, dtype=jnp.int32)[None, :], inv_freq)
    if rope_mscale != 1.0:
        cos, sin = cos * rope_mscale, sin * rope_mscale
    h, hkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.hd

    @jax.checkpoint
    def layer(x, lp):
        hidden = rms_norm(x, lp["input_layernorm"], cfg.rms_norm_eps)
        q = apply_rope(linear(hidden, lp["q_proj"]).reshape(b, s, h, hd),
                       cos, sin)
        k = apply_rope(linear(hidden, lp["k_proj"]).reshape(b, s, hkv, hd),
                       cos, sin)
        v = linear(hidden, lp["v_proj"]).reshape(b, s, hkv, hd)
        attn = sdp_attention(q, k, v, jnp.zeros((), jnp.int32),
                             sliding_window=cfg.sliding_window)
        x = x + linear(attn.reshape(b, s, h * hd), lp["o_proj"])
        hidden = rms_norm(x, lp["post_attention_layernorm"], cfg.rms_norm_eps)
        return x + moe_block(hidden, lp, cfg)

    x, _ = lax.scan(lambda c, lp: (layer(c, lp), None), x, params["layers"])
    x = rms_norm(x, params["norm"], cfg.rms_norm_eps)
    lm_head = params.get("lm_head")
    if lm_head is None:
        logits = jnp.dot(x, params["embed_tokens"].T.astype(x.dtype),
                         preferred_element_type=jnp.float32)
    else:
        logits = linear(x, lm_head)
    return logits.astype(jnp.float32)


SUPPORTS_SCALED_KV = True   # scale planes threaded through _layer_step


def new_cache(cfg: MixtralConfig, batch: int, max_seq: int,
              quantized=False) -> KVCache:
    return llama_mod.new_cache(cfg, batch, max_seq, quantized)


def convert_hf_params(
    tensors,
    cfg: MixtralConfig,
    qtype: Optional[str] = "sym_int4",
    compute_dtype=jnp.bfloat16,
    modules_to_not_convert: Tuple[str, ...] = (),
    imatrix=None,     # {hf_name: importance[K]} (bigdl_tpu.imatrix)
) -> Dict[str, Any]:
    """HF MixtralForCausalLM tensors -> stacked [L, E, ...] pytree.

    HF names: model.layers.N.block_sparse_moe.gate.weight [E, D];
    experts.M.{w1,w3} [F, D] (gate/up), w2 [D, F] (down). The router stays
    dense (the reference also leaves the tiny gate unquantized in practice
    via modules_to_not_convert). Like the Acc-based families, an imatrix
    weights the quantization and ultra-low-bit loads apply the per-tensor
    protection policy (bigdl_tpu.imatrix.low_bit_policy) — MoE is the
    main consumer of those formats (the reference's "Mixtral on 16 GB"
    IQ2 claim, README.md:16).
    """
    from bigdl_tpu.imatrix import imatrix_lookup, low_bit_policy
    from bigdl_tpu.ops.quant import FLOAT_QTYPES, quantize_linear

    L, E = cfg.num_hidden_layers, cfg.num_local_experts
    do_quant = qtype is not None and qtype not in FLOAT_QTYPES

    def cvt_linear(name, w):
        w = jnp.asarray(np.asarray(w))
        if do_quant and not any(m in name for m in modules_to_not_convert):
            qw = imatrix_lookup(imatrix, name)
            if qw is not None and len(qw) != w.shape[1]:
                qw = None
            return quantize_linear(w, low_bit_policy(qtype, name), qw=qw)
        return w.T.astype(compute_dtype)

    attn_keys = {"self_attn.q_proj": "q_proj", "self_attn.k_proj": "k_proj",
                 "self_attn.v_proj": "v_proj", "self_attn.o_proj": "o_proj"}
    expert_keys = {"w1": "experts_gate", "w3": "experts_up",
                   "w2": "experts_down"}

    layer_acc: Dict[str, list] = {}
    params: Dict[str, Any] = {}

    def put(key, idx, val):
        layer_acc.setdefault(key, [None] * L)[idx] = val

    def put_expert(key, lidx, eidx, val):
        slot = layer_acc.setdefault(key, [None] * L)
        if slot[lidx] is None:
            slot[lidx] = [None] * E
        slot[lidx][eidx] = val

    for name, w in tensors:
        if name == "model.embed_tokens.weight":
            params["embed_tokens"] = jnp.asarray(np.asarray(w)).astype(
                compute_dtype)
        elif name == "model.norm.weight":
            params["norm"] = jnp.asarray(np.asarray(w)).astype(compute_dtype)
        elif name == "lm_head.weight":
            params["lm_head"] = cvt_linear(name, w)
        elif name.startswith("model.layers."):
            parts = name.split(".")
            idx = int(parts[2])
            sub = ".".join(parts[3:-1])
            if sub in attn_keys:
                put(attn_keys[sub], idx, cvt_linear(name, w))
            elif sub in ("input_layernorm", "post_attention_layernorm"):
                put(sub, idx,
                    jnp.asarray(np.asarray(w)).astype(compute_dtype))
            elif sub == "block_sparse_moe.gate":
                put("router", idx,
                    jnp.asarray(np.asarray(w)).T.astype(compute_dtype))
            elif sub.startswith("block_sparse_moe.experts."):
                eidx = int(sub.split(".")[2])
                wname = sub.split(".")[3]
                put_expert(expert_keys[wname], idx, eidx,
                           cvt_linear(name, w))

    missing = [k for k, v in layer_acc.items()
               if any(x is None for x in v)
               or (k.startswith("experts_")
                   and any(e is None for x in v for e in x))]
    if missing:
        raise ValueError(f"checkpoint missing layer tensors for: {missing}")

    layers: Dict[str, Any] = {}
    for key, per_layer in layer_acc.items():
        if key.startswith("experts_"):
            stacked_e = [jax.tree.map(lambda *xs: jnp.stack(xs), *experts)
                         for experts in per_layer]
            layers[key] = jax.tree.map(lambda *xs: jnp.stack(xs), *stacked_e)
        else:
            layers[key] = jax.tree.map(lambda *xs: jnp.stack(xs), *per_layer)
    params["layers"] = layers

    if cfg.tie_word_embeddings:
        params.pop("lm_head", None)
    elif "lm_head" not in params:
        raise ValueError("checkpoint has no lm_head.weight")
    return params
