"""Shared checkpoint-conversion scaffolding.

One conversion engine for every family (used by models/llama.py and
models/families.py): per-layer accumulation + leading-L stacking, linear
quantization gating, missing-tensor validation, tied-embedding handling,
and fused-QKV de-interleave helpers.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class Acc:
    """Accumulates per-layer leaves and stacks them along L."""

    def __init__(self, cfg, qtype, compute_dtype, modules_to_not_convert,
                 imatrix: Optional[Dict[str, np.ndarray]] = None):
        from bigdl_tpu.ops.quant import FLOAT_QTYPES, quantize_linear

        self.cfg = cfg
        self.L = cfg.num_hidden_layers
        self.compute_dtype = compute_dtype
        self.do_quant = qtype is not None and qtype not in FLOAT_QTYPES
        self.qtype = qtype
        self.skip = modules_to_not_convert
        self.imatrix = imatrix
        if imatrix is not None and qtype in (
                "iq2_xxs", "iq2_xs", "iq1_s",
                "gguf_iq2_xxs", "gguf_iq2_xs", "gguf_iq1_s"):
            import logging

            # never SILENTLY degrade (r5): on both in-repo testbeds,
            # imatrix-weighted encodes of these formats measured WORSE
            # held-out ppl than unweighted — even after matching
            # llama.cpp's magnitude-modulated objective (ACCURACY_
            # MEDIUM.md "imatrix investigation"). Real-model evidence
            # in the llama.cpp ecosystem says the opposite, so the
            # imatrix is still applied — but validate with
            # bench/perplexity.py rather than assuming it helps.
            logging.getLogger(__name__).warning(
                "imatrix-weighted %s quantization measured WORSE "
                "held-out perplexity than unweighted on the in-repo "
                "testbeds (see ACCURACY_MEDIUM.md); applying it anyway "
                "(reference behavior) — validate with "
                "bigdl_tpu.bench.perplexity on your model", qtype)
        self._quantize_linear = quantize_linear
        self.layers: Dict[str, list] = {}
        self.top: Dict[str, Any] = {}
        # mixed_* policies: the scan-stacked layer layout needs ONE
        # concrete qtype per logical key (stacking heterogeneous
        # QTensors is a pytree-structure mismatch), so the per-tensor
        # MSE pick (reference low_bit_linear.py:302-335 picks per
        # module) is made on the first layer seen and reused for the
        # rest of that key
        self._mixed_picks: Dict[str, str] = {}

    def linear(self, name: str, w: np.ndarray):
        """HF [out, in] -> contraction-major leaf (QTensor or dense).

        Quantization prefers the native C++ kernels (bigdl_tpu.native, the
        quantize-llama-binary equivalent) — bit-identical to the JAX path,
        which remains the fallback. Already-quantized leaves (GPTQ/AWQ
        repack, transformers/gptq_awq.py) pass through unchanged. An
        imatrix makes quantization importance-weighted; independent of
        that, ultra-low-bit qtypes ALWAYS apply the per-tensor protection
        policy (bigdl_tpu.imatrix.low_bit_policy — part of those formats'
        semantics, as in the reference's transformers/utils.py:187-323)."""
        from bigdl_tpu.ops.quant import QTensor as _QT

        if isinstance(w, _QT):
            return w
        if self.do_quant and not any(m in name for m in self.skip):
            from bigdl_tpu.imatrix import imatrix_lookup, low_bit_policy
            from bigdl_tpu.native import quantize_native
            from bigdl_tpu.ops.quant import QTensor

            qtype = low_bit_policy(self.qtype, name)
            from bigdl_tpu.ops.quant import MIXED_QTYPES

            mixed_key = None
            if qtype in MIXED_QTYPES:
                import re as _re

                mixed_key = _re.sub(r"\.\d+\.", ".N.", name)
                qtype = self._mixed_picks.get(mixed_key, qtype)
            qw = imatrix_lookup(self.imatrix, name)
            if qw is not None and len(qw) != np.asarray(w).shape[1]:
                qw = None     # wrong orientation (e.g. embedding row)
            if qw is None:
                wt = np.ascontiguousarray(np.asarray(w).T, np.float32)
                native = quantize_native(wt, qtype)
                if native is not None:
                    data, scale = native
                    qt = QTensor(jnp.asarray(data),
                                 jnp.asarray(scale).astype(jnp.bfloat16),
                                 None, qtype, wt.shape)
                    self._attribute(name, w, qt)
                    return qt
            out = self._quantize_linear(jnp.asarray(np.asarray(w)),
                                        qtype, qw=qw)
            if mixed_key is not None and mixed_key not in self._mixed_picks:
                self._mixed_picks[mixed_key] = out.qtype
            self._attribute(name, w, out)
            return out
        return jnp.asarray(np.asarray(w)).T.astype(self.compute_dtype)

    def _attribute(self, name: str, w, qt) -> None:
        """Quantization-error attribution (observability/quality.py):
        when a collector is installed (model.from_pretrained under
        config.quality_enabled()), record this tensor's SNR /
        max-abs-err / clip-saturation vs the pre-quant floats via a
        dequantize round-trip. No collector -> no round-trip, zero
        load-time cost. Telemetry only: never load-bearing."""
        from bigdl_tpu.observability.quality import current_attribution

        report = current_attribution()
        if report is None:
            return
        try:
            from bigdl_tpu.observability.quality import weight_error_stats
            from bigdl_tpu.ops.quant import dequantize_linear

            # dequantize_linear returns HF layout [out, in] — the same
            # orientation the pre-quant weight arrived in
            deq = np.asarray(dequantize_linear(qt, jnp.float32))
            ref = np.asarray(w, np.float32)
            if deq.shape != ref.shape:
                return
            report.add(name, qt.qtype, weight_error_stats(ref, deq))
        except Exception:
            pass

    def dense(self, w) -> jax.Array:
        return jnp.asarray(np.asarray(w)).astype(self.compute_dtype)

    def put(self, key: str, idx: int, val):
        self.layers.setdefault(key, [None] * self.L)[idx] = val

    @classmethod
    def for_layer_count(cls, num_layers: int, qtype, compute_dtype,
                        modules_to_not_convert, imatrix=None) -> "Acc":
        """Accumulator for a bare layer stack (encoder-decoder models
        build one per stack; whisper/bart conversions)."""
        import types

        return cls(types.SimpleNamespace(num_hidden_layers=num_layers),
                   qtype, compute_dtype, modules_to_not_convert,
                   imatrix=imatrix)

    def finish(self, tie: bool, lm_head_required: bool = True,
               what: str = "checkpoint") -> Dict[str, Any]:
        missing = [k for k, v in self.layers.items()
                   if any(x is None for x in v)]
        if missing:
            raise ValueError(f"{what} missing layer tensors: {missing}")
        params = dict(self.top)
        params["layers"] = {
            k: jax.tree.map(lambda *xs: jnp.stack(xs), *v)
            for k, v in self.layers.items()
        }
        if tie:
            params.pop("lm_head", None)
        elif lm_head_required and "lm_head" not in params:
            raise ValueError("checkpoint has no lm_head and embeddings are "
                             "not tied")
        return params


def make_convert(map_tensor: Callable,
                 lm_head_required: bool = True) -> Callable:
    """Build a convert_hf_params from a per-tensor mapping callback.

    map_tensor(acc, name, w) handles one HF tensor (calls acc.put /
    acc.top). Unknown tensors are ignored (rotary inv_freq etc.).
    lm_head_required=False serves headless encoders (bert)."""

    def convert(tensors, cfg, qtype="sym_int4", compute_dtype=jnp.bfloat16,
                modules_to_not_convert: Tuple[str, ...] = (),
                imatrix: Optional[Dict[str, np.ndarray]] = None):
        from bigdl_tpu.ops.quant import QTensor

        acc = Acc(cfg, qtype, compute_dtype, modules_to_not_convert,
                  imatrix=imatrix)
        for name, w in tensors:
            map_tensor(acc, name,
                       w if isinstance(w, QTensor) else np.asarray(w))
        return acc.finish(getattr(cfg, "tie_word_embeddings", False),
                          lm_head_required=lm_head_required)

    return convert


def split_rows(w: np.ndarray, sizes) -> list:
    """Split an HF [out, in] fused weight along out into len(sizes) parts."""
    out = []
    off = 0
    for s in sizes:
        out.append(w[off:off + s])
        off += s
    return out


def deinterleave_qkv(w: np.ndarray, heads: int, hd: int):
    """gptneox/bloom fused qkv [(H*3*hd), in] with per-head (h, 3, hd)
    layout -> (q, k, v) each [H*hd, in]. Works for bias ([H*3*hd])."""
    lead = w.shape[1:] if w.ndim > 1 else ()
    w = w.reshape(heads, 3, hd, *lead)
    q, k, v = w[:, 0], w[:, 1], w[:, 2]
    flat = lambda x: x.reshape(heads * hd, *lead)
    return flat(q), flat(k), flat(v)


def layer_idx(name: str, prefix: str) -> Optional[Tuple[int, str]]:
    if not name.startswith(prefix):
        return None
    rest = name[len(prefix):]
    idx_s, _, sub = rest.partition(".")
    return int(idx_s), sub


# HF encoder-decoder layer key map shared by whisper and bart (both use
# the self_attn/encoder_attn/fc naming); value = (our key, is_linear)
ENC_DEC_LAYER_MAP: Dict[str, Tuple[str, bool]] = {
    "self_attn.q_proj": ("q_proj", True),
    "self_attn.k_proj": ("k_proj", True),
    "self_attn.v_proj": ("v_proj", True),
    "self_attn.out_proj": ("o_proj", True),
    "encoder_attn.q_proj": ("cross_q_proj", True),
    "encoder_attn.k_proj": ("cross_k_proj", True),
    "encoder_attn.v_proj": ("cross_v_proj", True),
    "encoder_attn.out_proj": ("cross_o_proj", True),
    "fc1": ("fc1", True), "fc2": ("fc2", True),
    "self_attn_layer_norm": ("ln1", False),
    "encoder_attn_layer_norm": ("ln_cross", False),
    "final_layer_norm": ("ln2", False),
}


def map_encdec_layer_tensor(accs: Dict[bool, "Acc"], name: str,
                            w) -> bool:
    """Route one 'model.{encoder,decoder}.layers.N.*' tensor into the
    encoder (accs[True]) or decoder (accs[False]) accumulator. Returns
    True when the tensor was a layer tensor (handled or skipped)."""
    if not name.startswith(("model.encoder.layers.",
                            "model.decoder.layers.")):
        return False
    acc = accs[name.startswith("model.encoder.")]
    parts = name.split(".")
    idx = int(parts[3])
    sub = ".".join(parts[4:-1])
    leaf = parts[-1]
    hit = ENC_DEC_LAYER_MAP.get(sub)
    if hit is None:
        return True
    key, is_lin = hit
    if is_lin and leaf == "weight":
        acc.put(key, idx, acc.linear(name, w))
    elif is_lin:
        acc.put(f"{key}_bias", idx, acc.dense(w))
    else:
        acc.put(key if leaf == "weight" else f"{key}_bias", idx,
                acc.dense(w))
    return True
