"""Whisper: encoder-decoder (speech-to-text) family.

The reference quantizes Whisper through its generic `optimize_model` API and
ships an `AutoModelForSpeechSeq2Seq` facade (reference optimize.py:196 —
"quantize ANY nn.Module (Whisper, LLaVA...)"; transformers/model.py:688-725
Auto classes; test/inference/test_optimize_model_api.py exercises whisper).
This is the TPU-native counterpart: a functional encoder-decoder built from
the same ops as the decoder-only families.

Design notes:
- The audio encoder (2x conv + bidirectional transformer) runs ONCE per
  utterance as a single jit; its output feeds a per-layer cross K/V cache
  computed once (`init_cache`) so the decode loop never re-projects
  encoder states — the encoder-decoder analog of prefill.
- The decoder is the same scan-over-layers + static KV cache pattern as
  models/llama.py, with a second (static) cross-attention read per layer.
  Bidirectional/cross attention reuses `sdp_attention` with q_pos = S_kv
  (every key visible), so there is exactly one attention op in the
  framework.
- Whisper uses learned absolute positions (no RoPE) and pre-LN blocks;
  k_proj carries no bias (HF WhisperAttention convention).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.ops.attention import sdp_attention
from bigdl_tpu.ops.kvcache import KVCache, init_cache as init_kv, \
    reject_scaled_kv, \
    read_layer, update_layer
from bigdl_tpu.ops.matmul import linear
from bigdl_tpu.ops.norms import layer_norm


@dataclasses.dataclass(frozen=True)
class WhisperConfig:
    vocab_size: int = 51865
    num_mel_bins: int = 80
    d_model: int = 384
    encoder_layers: int = 4
    encoder_attention_heads: int = 6
    decoder_layers: int = 4
    decoder_attention_heads: int = 6
    encoder_ffn_dim: int = 1536
    decoder_ffn_dim: int = 1536
    max_source_positions: int = 1500
    max_target_positions: int = 448
    layer_norm_eps: float = 1e-5
    decoder_start_token_id: int = 50257
    eos_token_id: int = 50256

    @property
    def hd(self) -> int:
        return self.d_model // self.decoder_attention_heads

    @classmethod
    def from_hf(cls, hf: Dict[str, Any]) -> "WhisperConfig":
        return cls(
            vocab_size=hf["vocab_size"],
            num_mel_bins=hf.get("num_mel_bins", 80),
            d_model=hf["d_model"],
            encoder_layers=hf["encoder_layers"],
            encoder_attention_heads=hf["encoder_attention_heads"],
            decoder_layers=hf["decoder_layers"],
            decoder_attention_heads=hf["decoder_attention_heads"],
            encoder_ffn_dim=hf["encoder_ffn_dim"],
            decoder_ffn_dim=hf["decoder_ffn_dim"],
            max_source_positions=hf.get("max_source_positions", 1500),
            max_target_positions=hf.get("max_target_positions", 448),
            decoder_start_token_id=hf.get("decoder_start_token_id", 50257),
            eos_token_id=hf.get("eos_token_id", 50256),
        )


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class WhisperCache:
    """Decoder self-attention KV cache + per-layer cross K/V (static)."""

    self_kv: KVCache                  # [Ld, B, Tmax, H, hd]
    cross_k: jax.Array                # [Ld, B, S_enc, H, hd]
    cross_v: jax.Array

    def tree_flatten(self):
        return (self.self_kv, self.cross_k, self.cross_v), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def pos(self):
        return self.self_kv.pos

    @property
    def max_seq(self) -> int:
        return self.self_kv.max_seq


# -- encoder -----------------------------------------------------------------


def _conv1d(x: jax.Array, w: jax.Array, b: jax.Array,
            stride: int) -> jax.Array:
    """x [B, C, T], w [O, C, 3] -> [B, O, T//stride] (SAME-ish pad=1)."""
    y = lax.conv_general_dilated(
        x.astype(jnp.float32), w.astype(jnp.float32),
        window_strides=(stride,), padding=((1, 1),),
        dimension_numbers=("NCH", "OIH", "NCH"))
    return y + b.astype(jnp.float32)[None, :, None]


def _enc_layer(x, lp, cfg: WhisperConfig):
    h, hd = cfg.encoder_attention_heads, cfg.d_model // \
        cfg.encoder_attention_heads
    b, s, _ = x.shape
    hidden = layer_norm(x, lp["ln1"], lp["ln1_bias"], cfg.layer_norm_eps)
    q = linear(hidden, lp["q_proj"], lp.get("q_proj_bias")).reshape(
        b, s, h, hd)
    k = linear(hidden, lp["k_proj"]).reshape(b, s, h, hd)
    v = linear(hidden, lp["v_proj"], lp.get("v_proj_bias")).reshape(
        b, s, h, hd)
    # q_pos = S -> every key visible (bidirectional)
    attn = sdp_attention(q, k, v, jnp.asarray(s, jnp.int32)).reshape(
        b, s, h * hd)
    x = x + linear(attn, lp["o_proj"], lp.get("o_proj_bias"))
    hidden = layer_norm(x, lp["ln2"], lp["ln2_bias"], cfg.layer_norm_eps)
    inner = jax.nn.gelu(linear(hidden, lp["fc1"], lp.get("fc1_bias")),
                        approximate=False)
    return x + linear(inner, lp["fc2"], lp.get("fc2_bias"))


def encode(params: Dict[str, Any], cfg: WhisperConfig,
           input_features: jax.Array,     # [B, n_mels, T]
           compute_dtype=jnp.bfloat16) -> jax.Array:
    """Audio features -> encoder states [B, T//2, D]."""
    x = jax.nn.gelu(_conv1d(input_features, params["enc_conv1_w"],
                            params["enc_conv1_b"], 1), approximate=False)
    x = jax.nn.gelu(_conv1d(x, params["enc_conv2_w"],
                            params["enc_conv2_b"], 2), approximate=False)
    x = x.transpose(0, 2, 1).astype(compute_dtype)        # [B, S, D]
    s = x.shape[1]
    x = x + params["enc_pos"][:s].astype(compute_dtype)[None]
    x, _ = lax.scan(lambda c, lp: (_enc_layer(c, lp, cfg), None), x,
                    params["enc_layers"])
    return layer_norm(x, params["enc_norm"], params["enc_norm_bias"],
                      cfg.layer_norm_eps)


# -- decoder -----------------------------------------------------------------


def init_decoder_cache(params: Dict[str, Any], cfg: WhisperConfig,
                       enc_out: jax.Array, max_seq: Optional[int] = None,
                       quantized=False) -> WhisperCache:
    """Allocate the self KV cache and precompute cross K/V per layer."""
    reject_scaled_kv(quantized, "whisper")
    b, s_enc, _ = enc_out.shape
    h, hd = cfg.decoder_attention_heads, cfg.hd
    max_seq = max_seq or cfg.max_target_positions
    if max_seq > cfg.max_target_positions:
        # decode_step gathers dec_pos[pos] under jit, where an
        # out-of-range row would clamp silently; refuse while static
        raise ValueError(
            f"max_seq={max_seq} exceeds max_target_positions="
            f"{cfg.max_target_positions}: decoder positions past the "
            "learned table would silently clamp under jit")

    def proj(carry, lp):
        k = linear(enc_out, lp["cross_k_proj"]).reshape(b, s_enc, h, hd)
        v = linear(enc_out, lp["cross_v_proj"],
                   lp.get("cross_v_proj_bias")).reshape(b, s_enc, h, hd)
        return carry, (k, v)

    _, (ck, cv) = lax.scan(proj, 0, params["dec_layers"])
    return WhisperCache(
        self_kv=init_kv(cfg.decoder_layers, b, max_seq, h, hd,
                        quantized=quantized),
        cross_k=ck, cross_v=cv)


def _dec_layer(x, lp, cfg: WhisperConfig, ck, cv, cross_k, cross_v,
               lidx, pos):
    h, hd = cfg.decoder_attention_heads, cfg.hd
    b, sq, _ = x.shape
    s_enc = cross_k.shape[1]

    hidden = layer_norm(x, lp["ln1"], lp["ln1_bias"], cfg.layer_norm_eps)
    q = linear(hidden, lp["q_proj"], lp.get("q_proj_bias")).reshape(
        b, sq, h, hd)
    k = linear(hidden, lp["k_proj"]).reshape(b, sq, h, hd)
    v = linear(hidden, lp["v_proj"], lp.get("v_proj_bias")).reshape(
        b, sq, h, hd)
    ck, cv = update_layer(ck, cv, lidx, k, v, pos)
    kf, vf = read_layer(ck, cv, lidx)
    attn = sdp_attention(q, kf, vf, pos).reshape(b, sq, h * hd)
    x = x + linear(attn, lp["o_proj"], lp.get("o_proj_bias"))

    hidden = layer_norm(x, lp["ln_cross"], lp["ln_cross_bias"],
                        cfg.layer_norm_eps)
    q = linear(hidden, lp["cross_q_proj"],
               lp.get("cross_q_proj_bias")).reshape(b, sq, h, hd)
    attn = sdp_attention(q, cross_k, cross_v,
                         jnp.asarray(s_enc, jnp.int32)).reshape(b, sq, h * hd)
    x = x + linear(attn, lp["cross_o_proj"], lp.get("cross_o_proj_bias"))

    hidden = layer_norm(x, lp["ln2"], lp["ln2_bias"], cfg.layer_norm_eps)
    inner = jax.nn.gelu(linear(hidden, lp["fc1"], lp.get("fc1_bias")),
                        approximate=False)
    return x + linear(inner, lp["fc2"], lp.get("fc2_bias")), (ck, cv)


def decode_step(
    params: Dict[str, Any],
    cfg: WhisperConfig,
    tokens: jax.Array,        # [B, Sq] int32
    cache: WhisperCache,
    compute_dtype=jnp.bfloat16,
) -> Tuple[jax.Array, WhisperCache]:
    """Decoder forward (prefill Sq = forced tokens, decode Sq = 1)."""
    b, sq = tokens.shape
    pos = cache.self_kv.pos
    emb = params["dec_embed"]
    x = emb[tokens].astype(compute_dtype)
    positions = pos + jnp.arange(sq, dtype=jnp.int32)
    x = x + params["dec_pos"][positions].astype(compute_dtype)[None]

    lidx = jnp.arange(cfg.decoder_layers, dtype=jnp.int32)

    def step(carry, xs):
        x, ck, cv = carry
        lp, li, crk, crv = xs
        x, (ck, cv) = _dec_layer(x, lp, cfg, ck, cv, crk, crv, li, pos)
        return (x, ck, cv), None

    (x, ck, cv), _ = lax.scan(
        step, (x, cache.self_kv.k, cache.self_kv.v),
        (params["dec_layers"], lidx, cache.cross_k, cache.cross_v))

    x = layer_norm(x, params["dec_norm"], params["dec_norm_bias"],
                   cfg.layer_norm_eps)
    logits = jnp.dot(x, emb.T.astype(x.dtype),
                     preferred_element_type=jnp.float32).astype(jnp.float32)
    return logits, WhisperCache(
        self_kv=KVCache(ck, cv, pos + sq),
        cross_k=cache.cross_k, cross_v=cache.cross_v)


# -- conversion ---------------------------------------------------------------


def convert_hf_params(
    tensors,
    cfg: WhisperConfig,
    qtype: Optional[str] = "sym_int4",
    compute_dtype=jnp.bfloat16,
    modules_to_not_convert: Tuple[str, ...] = (),
    imatrix=None,
) -> Dict[str, Any]:
    """HF WhisperForConditionalGeneration tensors -> pytree.

    Linears quantize (imatrix-weighted when given); convs, embeddings and
    norms stay dense. Two Acc accumulators (encoder / decoder stacks)
    share the standard conversion leaf helpers (models/convert_base.py:
    native-kernel preference, imatrix weighting, protection policy) —
    same structure as models/bart.py.
    """
    from bigdl_tpu.models.convert_base import (Acc,
                                               map_encdec_layer_tensor)

    accs = {
        True: Acc.for_layer_count(cfg.encoder_layers, qtype, compute_dtype,
                                  modules_to_not_convert, imatrix=imatrix),
        False: Acc.for_layer_count(cfg.decoder_layers, qtype, compute_dtype,
                                   modules_to_not_convert, imatrix=imatrix),
    }
    dense = accs[True].dense
    f32 = lambda w: jnp.asarray(np.asarray(w), jnp.float32)

    top: Dict[str, Any] = {}

    for name, w in tensors:
        w = np.asarray(w)
        if map_encdec_layer_tensor(accs, name, w):
            pass
        elif name == "model.encoder.conv1.weight":
            top["enc_conv1_w"] = f32(w)
        elif name == "model.encoder.conv1.bias":
            top["enc_conv1_b"] = f32(w)
        elif name == "model.encoder.conv2.weight":
            top["enc_conv2_w"] = f32(w)
        elif name == "model.encoder.conv2.bias":
            top["enc_conv2_b"] = f32(w)
        elif name == "model.encoder.embed_positions.weight":
            top["enc_pos"] = dense(w)
        elif name == "model.encoder.layer_norm.weight":
            top["enc_norm"] = dense(w)
        elif name == "model.encoder.layer_norm.bias":
            top["enc_norm_bias"] = dense(w)
        elif name in ("model.decoder.embed_tokens.weight",
                      "proj_out.weight"):
            top["dec_embed"] = dense(w)
        elif name == "model.decoder.embed_positions.weight":
            top["dec_pos"] = dense(w)
        elif name == "model.decoder.layer_norm.weight":
            top["dec_norm"] = dense(w)
        elif name == "model.decoder.layer_norm.bias":
            top["dec_norm_bias"] = dense(w)

    top["enc_layers"] = accs[True].finish(
        tie=False, lm_head_required=False,
        what="whisper encoder")["layers"]
    top["dec_layers"] = accs[False].finish(
        tie=False, lm_head_required=False,
        what="whisper decoder")["layers"]
    return top
