"""BART: text encoder-decoder (summarization / translation).

Backs the reference's `AutoModelForSeq2SeqLM` facade (reference
transformers/model.py:701 — seq2seq checkpoints quantized through the same
low-bit pipeline). Same runtime shape as models/whisper.py — encode once,
precompute per-layer cross K/V, scan-decode against a static KV cache —
but with BART's text specifics:

- POST-layer-norm blocks (norm after the residual add, original
  transformer order; whisper/llama are pre-LN),
- learned positions with the +2 offset quirk of the BART checkpoint
  format, an embedding layernorm, and every attention projection biased,
- tied lm_head = shared embedding + final_logits_bias.

`BartCache` extends the whisper cache shape (self KV + static cross K/V)
with the source padding mask so batched, padded sources cross-attend only
real tokens.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.models.bert import _masked_attention
from bigdl_tpu.ops.attention import sdp_attention
from bigdl_tpu.ops.kvcache import KVCache, init_cache as init_kv, \
    reject_scaled_kv, \
    read_layer, update_layer
from bigdl_tpu.ops.matmul import linear
from bigdl_tpu.ops.norms import layer_norm

_POS_OFFSET = 2      # BartLearnedPositionalEmbedding reserves rows 0/1


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class BartCache:
    """Decoder self KV cache + static cross K/V + source padding mask."""

    self_kv: KVCache
    cross_k: jax.Array            # [Ld, B, S_enc, H, hd]
    cross_v: jax.Array
    src_mask: jax.Array           # [B, S_enc] bool (True = real token)

    def tree_flatten(self):
        return (self.self_kv, self.cross_k, self.cross_v,
                self.src_mask), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def pos(self):
        return self.self_kv.pos

    @property
    def max_seq(self) -> int:
        return self.self_kv.max_seq


@dataclasses.dataclass(frozen=True)
class BartConfig:
    vocab_size: int = 50265
    d_model: int = 768
    encoder_layers: int = 6
    decoder_layers: int = 6
    encoder_attention_heads: int = 12
    decoder_attention_heads: int = 12
    encoder_ffn_dim: int = 3072
    decoder_ffn_dim: int = 3072
    max_position_embeddings: int = 1024
    activation_function: str = "gelu"
    scale_embedding: bool = False
    layer_norm_eps: float = 1e-5
    decoder_start_token_id: int = 2
    eos_token_id: int = 2
    pad_token_id: int = 1
    forced_bos_token_id: Optional[int] = None   # bart-large-cnn style

    @property
    def hd(self) -> int:
        return self.d_model // self.decoder_attention_heads

    @classmethod
    def from_hf(cls, hf: Dict[str, Any]) -> "BartConfig":
        return cls(
            vocab_size=hf["vocab_size"],
            d_model=hf["d_model"],
            encoder_layers=hf["encoder_layers"],
            decoder_layers=hf["decoder_layers"],
            encoder_attention_heads=hf["encoder_attention_heads"],
            decoder_attention_heads=hf["decoder_attention_heads"],
            encoder_ffn_dim=hf["encoder_ffn_dim"],
            decoder_ffn_dim=hf["decoder_ffn_dim"],
            max_position_embeddings=hf.get("max_position_embeddings", 1024),
            activation_function=hf.get("activation_function", "gelu"),
            scale_embedding=hf.get("scale_embedding", False),
            decoder_start_token_id=hf.get("decoder_start_token_id", 2),
            eos_token_id=hf.get("eos_token_id", 2),
            pad_token_id=hf.get("pad_token_id", 1),
            forced_bos_token_id=hf.get("forced_bos_token_id"),
        )


def _act(cfg: BartConfig):
    import functools

    return {
        "gelu": functools.partial(jax.nn.gelu, approximate=False),
        "gelu_new": functools.partial(jax.nn.gelu, approximate=True),
        "relu": jax.nn.relu,
        "silu": jax.nn.silu,
    }[cfg.activation_function]


def _enc_attn(x, lp, h, hd, key_mask):
    """Bidirectional encoder self-attention with a key-padding mask."""
    b, s, _ = x.shape
    q = linear(x, lp["q_proj"], lp.get("q_proj_bias")).reshape(b, s, h, hd)
    k = linear(x, lp["k_proj"], lp.get("k_proj_bias")).reshape(b, s, h, hd)
    v = linear(x, lp["v_proj"], lp.get("v_proj_bias")).reshape(b, s, h, hd)
    attn = _masked_attention(q, k, v, key_mask, hd ** -0.5)
    return linear(attn.reshape(b, s, h * hd), lp["o_proj"],
                  lp.get("o_proj_bias"))


def _embed(params, cfg: BartConfig, tokens, pos_start, compute_dtype):
    x = params["shared"][tokens].astype(compute_dtype)
    if cfg.scale_embedding:
        x = x * jnp.asarray(cfg.d_model ** 0.5, compute_dtype)
    s = tokens.shape[1]
    positions = pos_start + jnp.arange(s, dtype=jnp.int32) + _POS_OFFSET
    return x, positions


def encode(params: Dict[str, Any], cfg: BartConfig,
           input_ids: jax.Array,          # [B, S] int32
           attention_mask: Optional[jax.Array] = None,   # [B, S] 1=real
           compute_dtype=jnp.bfloat16) -> jax.Array:
    """Token encoder -> [B, S, D] (bidirectional, post-LN)."""
    b, s = input_ids.shape
    if s > cfg.max_position_embeddings:
        raise ValueError(
            f"source length {s} exceeds max_position_embeddings "
            f"{cfg.max_position_embeddings} (position rows would clamp "
            "silently under jit)")
    h, hd = cfg.encoder_attention_heads, cfg.d_model // \
        cfg.encoder_attention_heads
    key_mask = (jnp.ones((b, s), bool) if attention_mask is None
                else attention_mask.astype(bool))
    x, positions = _embed(params, cfg, input_ids, 0, compute_dtype)
    x = x + params["enc_pos"][positions].astype(compute_dtype)[None]
    x = layer_norm(x, params["enc_embed_norm"],
                   params["enc_embed_norm_bias"], cfg.layer_norm_eps)

    eps = cfg.layer_norm_eps
    act = _act(cfg)

    def enc_layer(x, lp):
        a = _enc_attn(x, lp, h, hd, key_mask)
        x = layer_norm(x + a, lp["ln1"], lp["ln1_bias"], eps)
        inner = act(linear(x, lp["fc1"], lp.get("fc1_bias")))
        out = linear(inner, lp["fc2"], lp.get("fc2_bias"))
        return layer_norm(x + out, lp["ln2"], lp["ln2_bias"], eps)

    x, _ = lax.scan(lambda c, lp: (enc_layer(c, lp), None), x,
                    params["enc_layers"])
    return x


def init_decoder_cache(params: Dict[str, Any], cfg: BartConfig,
                       enc_out: jax.Array, max_seq: Optional[int] = None,
                       quantized=False,
                       src_mask: Optional[jax.Array] = None) -> BartCache:
    reject_scaled_kv(quantized, "bart")
    b, s_enc, _ = enc_out.shape
    h, hd = cfg.decoder_attention_heads, cfg.hd
    max_seq = max_seq or cfg.max_position_embeddings
    if max_seq > cfg.max_position_embeddings:
        # decode_step gathers dec_pos[pos] under jit, where an
        # out-of-range row would clamp silently; refuse here, where
        # max_seq is still static (mirrors encode()'s length check)
        raise ValueError(
            f"max_seq={max_seq} exceeds max_position_embeddings="
            f"{cfg.max_position_embeddings}: decoder positions past the "
            "learned table would silently clamp under jit")

    def proj(carry, lp):
        k = linear(enc_out, lp["cross_k_proj"],
                   lp.get("cross_k_proj_bias")).reshape(b, s_enc, h, hd)
        v = linear(enc_out, lp["cross_v_proj"],
                   lp.get("cross_v_proj_bias")).reshape(b, s_enc, h, hd)
        return carry, (k, v)

    _, (ck, cv) = lax.scan(proj, 0, params["dec_layers"])
    return BartCache(
        self_kv=init_kv(cfg.decoder_layers, b, max_seq, h, hd,
                        quantized=quantized),
        cross_k=ck, cross_v=cv,
        src_mask=(jnp.ones((b, s_enc), bool) if src_mask is None
                  else src_mask.astype(bool)))


def decode_step(
    params: Dict[str, Any],
    cfg: BartConfig,
    tokens: jax.Array,
    cache: BartCache,
    compute_dtype=jnp.bfloat16,
) -> Tuple[jax.Array, BartCache]:
    b, sq = tokens.shape
    pos = cache.self_kv.pos
    h, hd = cfg.decoder_attention_heads, cfg.hd
    eps = cfg.layer_norm_eps
    act = _act(cfg)

    x, positions = _embed(params, cfg, tokens, pos, compute_dtype)
    x = x + params["dec_pos"][positions].astype(compute_dtype)[None]
    x = layer_norm(x, params["dec_embed_norm"],
                   params["dec_embed_norm_bias"], eps)

    lidx = jnp.arange(cfg.decoder_layers, dtype=jnp.int32)

    def step(carry, xs):
        x, ck, cv = carry
        lp, li, crk, crv = xs
        q = linear(x, lp["q_proj"], lp.get("q_proj_bias")).reshape(
            b, sq, h, hd)
        k = linear(x, lp["k_proj"], lp.get("k_proj_bias")).reshape(
            b, sq, h, hd)
        v = linear(x, lp["v_proj"], lp.get("v_proj_bias")).reshape(
            b, sq, h, hd)
        ck, cv = update_layer(ck, cv, li, k, v, pos)
        kf, vf = read_layer(ck, cv, li)
        a = sdp_attention(q, kf, vf, pos).reshape(b, sq, h * hd)
        a = linear(a, lp["o_proj"], lp.get("o_proj_bias"))
        x = layer_norm(x + a, lp["ln1"], lp["ln1_bias"], eps)

        q2 = linear(x, lp["cross_q_proj"],
                    lp.get("cross_q_proj_bias")).reshape(b, sq, h, hd)
        a2 = _masked_attention(q2, crk, crv, cache.src_mask,
                               hd ** -0.5).reshape(b, sq, h * hd)
        a2 = linear(a2, lp["cross_o_proj"], lp.get("cross_o_proj_bias"))
        x = layer_norm(x + a2, lp["ln_cross"], lp["ln_cross_bias"], eps)

        inner = act(linear(x, lp["fc1"], lp.get("fc1_bias")))
        out = linear(inner, lp["fc2"], lp.get("fc2_bias"))
        x = layer_norm(x + out, lp["ln2"], lp["ln2_bias"], eps)
        return (x, ck, cv), None

    (x, ck, cv), _ = lax.scan(
        step, (x, cache.self_kv.k, cache.self_kv.v),
        (params["dec_layers"], lidx, cache.cross_k, cache.cross_v))

    logits = jnp.dot(x, params["shared"].T.astype(x.dtype),
                     preferred_element_type=jnp.float32).astype(jnp.float32)
    if "final_logits_bias" in params:
        logits = logits + params["final_logits_bias"].astype(jnp.float32)
    return logits, BartCache(
        self_kv=KVCache(ck, cv, pos + sq),
        cross_k=cache.cross_k, cross_v=cache.cross_v,
        src_mask=cache.src_mask)


# -- conversion ---------------------------------------------------------------

def convert_hf_params(
    tensors,
    cfg: BartConfig,
    qtype: Optional[str] = "sym_int4",
    compute_dtype=jnp.bfloat16,
    modules_to_not_convert: Tuple[str, ...] = (),
    imatrix=None,
) -> Dict[str, Any]:
    """Two Acc accumulators (encoder / decoder stacks) share the standard
    conversion leaf helpers (models/convert_base.py: native-kernel
    quantization preference, imatrix weighting, protection policy)."""
    from bigdl_tpu.models.convert_base import (Acc,
                                               map_encdec_layer_tensor)

    accs = {
        True: Acc.for_layer_count(cfg.encoder_layers, qtype, compute_dtype,
                                  modules_to_not_convert, imatrix=imatrix),
        False: Acc.for_layer_count(cfg.decoder_layers, qtype, compute_dtype,
                                   modules_to_not_convert, imatrix=imatrix),
    }
    top: Dict[str, Any] = {}
    dense = accs[True].dense

    for name, w in tensors:
        w = np.asarray(w)
        if map_encdec_layer_tensor(accs, name, w):
            pass
        elif name in ("model.shared.weight", "shared.weight"):
            top["shared"] = dense(w)
        elif name in ("model.encoder.embed_tokens.weight",
                      "model.decoder.embed_tokens.weight", "lm_head.weight"):
            if "shared" not in top:                # tied duplicates: skip
                top["shared"] = dense(w)           # re-uploading [V, D]
        elif name == "model.encoder.embed_positions.weight":
            top["enc_pos"] = dense(w)
        elif name == "model.decoder.embed_positions.weight":
            top["dec_pos"] = dense(w)
        elif name == "model.encoder.layernorm_embedding.weight":
            top["enc_embed_norm"] = dense(w)
        elif name == "model.encoder.layernorm_embedding.bias":
            top["enc_embed_norm_bias"] = dense(w)
        elif name == "model.decoder.layernorm_embedding.weight":
            top["dec_embed_norm"] = dense(w)
        elif name == "model.decoder.layernorm_embedding.bias":
            top["dec_embed_norm_bias"] = dense(w)
        elif name == "final_logits_bias":
            top["final_logits_bias"] = jnp.asarray(w, jnp.float32).reshape(-1)

    top["enc_layers"] = accs[True].finish(
        tie=False, lm_head_required=False, what="bart encoder")["layers"]
    top["dec_layers"] = accs[False].finish(
        tie=False, lm_head_required=False, what="bart decoder")["layers"]
    return top
