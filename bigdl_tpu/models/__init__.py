from bigdl_tpu.models import llama  # noqa: F401
