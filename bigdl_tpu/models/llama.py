"""Llama-family model: functional, static-shape, scan-over-layers.

TPU-native re-design of the reference's optimized llama path
(reference transformers/models/llama.py: llama_model_forward_4_36 at :103,
llama_attention_forward_4_36 at :875, llama_mlp_forward at :150,
llama_rms_norm_forward at :134). Where the reference monkey-patches HF
nn.Modules and dispatches per-shape to SYCL kernels, this is a from-scratch
functional model over a parameter pytree:

- All linear weights are contraction-major leaves ([K, N] dense or QTensor),
  so every projection is one `linear()` call that hits the fused Pallas
  dequant-matmul on TPU.
- Per-layer parameters are STACKED along a leading L axis and the layer loop
  is `lax.scan` — one layer gets traced/compiled once, not 32 times.
- The KV cache is pre-allocated static-shape (ops/kvcache.py) and carried
  through the scan; decode never re-allocates or re-compiles.
- The same `forward()` serves prefill (Sq = prompt length) and decode
  (Sq = 1): query positions make causal + cache-tail masking uniform.

Covers the llama architecture family as the reference does (llama/llama2/
codellama/vicuna and, via configs, mistral-style GQA models).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.ops.attention import sdp_attention
from bigdl_tpu.ops.kvcache import KVCache, init_cache, read_layer, update_layer
from bigdl_tpu.ops.matmul import linear
from bigdl_tpu.ops.norms import rms_norm
from bigdl_tpu.ops.rope import apply_rope, rope_cos_sin, rope_freqs


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    head_dim: Optional[int] = None
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    rope_scaling_factor: float = 1.0
    max_position_embeddings: int = 4096
    tie_word_embeddings: bool = False
    attention_bias: bool = False
    mlp_bias: bool = False
    sliding_window: Optional[int] = None

    @property
    def hd(self) -> int:
        return self.head_dim or self.hidden_size // self.num_attention_heads

    @classmethod
    def from_hf(cls, hf: Dict[str, Any]) -> "LlamaConfig":
        """Build from an HF config dict (config.json of llama/mistral...)."""
        rs = hf.get("rope_scaling") or {}
        factor = 1.0
        if rs:
            rtype = rs.get("rope_type", rs.get("type", "linear"))
            if rtype == "linear":
                factor = float(rs.get("factor", 1.0))
            elif rtype != "default":
                raise NotImplementedError(
                    f"rope_scaling type {rtype!r} not supported yet "
                    "(supported: linear)")
        return cls(
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            intermediate_size=hf["intermediate_size"],
            num_hidden_layers=hf["num_hidden_layers"],
            num_attention_heads=hf["num_attention_heads"],
            num_key_value_heads=hf.get(
                "num_key_value_heads", hf["num_attention_heads"]),
            head_dim=hf.get("head_dim"),
            rms_norm_eps=hf.get("rms_norm_eps", 1e-5),
            rope_theta=hf.get("rope_theta", 10000.0),
            rope_scaling_factor=factor,
            max_position_embeddings=hf.get("max_position_embeddings", 4096),
            tie_word_embeddings=hf.get("tie_word_embeddings", False),
            attention_bias=hf.get("attention_bias", False),
            mlp_bias=hf.get("mlp_bias", False),
            sliding_window=hf.get("sliding_window"),
        )


# Parameter pytree layout (all linear leaves contraction-major [K, N]):
# {
#   "embed_tokens": [V, D],
#   "layers": {
#     "input_layernorm":          [L, D],
#     "post_attention_layernorm": [L, D],
#     "q_proj" | "k_proj" | "v_proj" | "o_proj":       stacked QTensor/dense,
#     "gate_proj" | "up_proj" | "down_proj":           stacked QTensor/dense,
#     (+ "<name>_bias": [L, N] when attention_bias/mlp_bias)
#   },
#   "norm": [D],
#   "lm_head": QTensor/dense [D, V] (absent when tied),
# }


def _layer_step(cfg: LlamaConfig, carry, xs):
    x, ck, cv, pos, cos, sin = carry
    lp, lidx = xs
    b, sq, d = x.shape
    h, hkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.hd

    # --- attention block ---
    hidden = rms_norm(x, lp["input_layernorm"], cfg.rms_norm_eps)
    q = linear(hidden, lp["q_proj"], lp.get("q_proj_bias"))
    k = linear(hidden, lp["k_proj"], lp.get("k_proj_bias"))
    v = linear(hidden, lp["v_proj"], lp.get("v_proj_bias"))
    q = q.reshape(b, sq, h, hd)
    k = k.reshape(b, sq, hkv, hd)
    v = v.reshape(b, sq, hkv, hd)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    ck, cv = update_layer(ck, cv, lidx, k, v, pos)
    kf, vf = read_layer(ck, cv, lidx)
    attn = sdp_attention(q, kf, vf, pos, sliding_window=cfg.sliding_window)
    attn = attn.reshape(b, sq, h * hd)
    x = x + linear(attn, lp["o_proj"], lp.get("o_proj_bias"))

    # --- mlp block (fused gate/up + SiLU, the reference's mlp_forward_xpu) ---
    hidden = rms_norm(x, lp["post_attention_layernorm"], cfg.rms_norm_eps)
    gate = linear(hidden, lp["gate_proj"], lp.get("gate_proj_bias"))
    up = linear(hidden, lp["up_proj"], lp.get("up_proj_bias"))
    mlp = linear(jax.nn.silu(gate) * up, lp["down_proj"],
                 lp.get("down_proj_bias"))
    x = x + mlp

    return (x, ck, cv, pos, cos, sin), None


def forward(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    tokens: jax.Array,       # [B, Sq] int32
    cache: KVCache,
    compute_dtype=jnp.bfloat16,
    last_only: bool = False,
) -> Tuple[jax.Array, KVCache]:
    """Run the model; returns (logits [B, Sq, V], updated cache).

    `cache.pos` is the write offset: 0 for prefill, prompt_len + n for the
    n-th decode step. One function, both phases (static Sq distinguishes
    the compiled executables). last_only=True computes lm_head for the
    final position only — the reference's `optimize_lm_head` trick
    (low_bit_linear.py:251-258), which matters when V=32k+ and Sq is long.
    """
    b, sq = tokens.shape
    pos = cache.pos

    x = params["embed_tokens"][tokens].astype(compute_dtype)

    inv_freq = rope_freqs(cfg.hd, cfg.rope_theta,
                          scaling_factor=cfg.rope_scaling_factor)
    positions = pos + jnp.arange(sq, dtype=jnp.int32)
    cos, sin = rope_cos_sin(positions[None, :], inv_freq)  # [1, Sq, hd/2]

    lidx = jnp.arange(cfg.num_hidden_layers, dtype=jnp.int32)
    (x, ck, cv, _, _, _), _ = lax.scan(
        lambda c, xs: _layer_step(cfg, c, xs),
        (x, cache.k, cache.v, pos, cos, sin),
        (params["layers"], lidx),
    )

    if last_only:
        x = x[:, -1:, :]
    x = rms_norm(x, params["norm"], cfg.rms_norm_eps)
    lm_head = params.get("lm_head")
    if lm_head is None:
        logits = jnp.dot(x, params["embed_tokens"].T.astype(x.dtype),
                         preferred_element_type=jnp.float32)
    else:
        logits = linear(x, lm_head)
    logits = logits.astype(jnp.float32)

    return logits, KVCache(ck, cv, pos + sq)


def forward_last_token(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    tokens: jax.Array,
    cache: KVCache,
    compute_dtype=jnp.bfloat16,
) -> Tuple[jax.Array, KVCache]:
    """Prefill variant of `forward` with lm_head on the final position only."""
    return forward(params, cfg, tokens, cache, compute_dtype=compute_dtype,
                   last_only=True)


def forward_train(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    tokens: jax.Array,       # [B, S] int32
    compute_dtype=jnp.bfloat16,
    attn_fn=None,            # (q, k, v) -> out; default causal sdp
    pos_offset=0,            # global position of tokens[:, 0] (seq parallel)
) -> jax.Array:
    """Cacheless causal forward for training: returns logits [B, S, V].

    The finetuning path (QLoRA stack, reference transformers/qlora.py) runs
    through this; no KV cache is materialized, attention is causal over the
    in-flight sequence, and `jax.checkpoint` on the layer body trades FLOPs
    for HBM during backward (the scan carries only layer inputs).

    `attn_fn`/`pos_offset` let sequence parallelism swap in ring attention
    over the sp mesh axis (bigdl_tpu.parallel.sp) with per-shard RoPE
    offsets — the model body is otherwise unchanged.
    """
    b, s = tokens.shape
    x = params["embed_tokens"][tokens].astype(compute_dtype)
    inv_freq = rope_freqs(cfg.hd, cfg.rope_theta,
                          scaling_factor=cfg.rope_scaling_factor)
    positions = pos_offset + jnp.arange(s, dtype=jnp.int32)
    cos, sin = rope_cos_sin(positions[None, :], inv_freq)

    h, hkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.hd

    if attn_fn is None:
        def attn_fn(q, k, v):
            return sdp_attention(q, k, v, jnp.zeros((), jnp.int32),
                                 sliding_window=cfg.sliding_window)

    @jax.checkpoint
    def layer(x, lp):
        hidden = rms_norm(x, lp["input_layernorm"], cfg.rms_norm_eps)
        q = linear(hidden, lp["q_proj"], lp.get("q_proj_bias"))
        k = linear(hidden, lp["k_proj"], lp.get("k_proj_bias"))
        v = linear(hidden, lp["v_proj"], lp.get("v_proj_bias"))
        q = apply_rope(q.reshape(b, s, h, hd), cos, sin)
        k = apply_rope(k.reshape(b, s, hkv, hd), cos, sin)
        v = v.reshape(b, s, hkv, hd)
        attn = attn_fn(q, k, v)
        x = x + linear(attn.reshape(b, s, h * hd), lp["o_proj"],
                       lp.get("o_proj_bias"))
        hidden = rms_norm(x, lp["post_attention_layernorm"], cfg.rms_norm_eps)
        gate = linear(hidden, lp["gate_proj"], lp.get("gate_proj_bias"))
        up = linear(hidden, lp["up_proj"], lp.get("up_proj_bias"))
        x = x + linear(jax.nn.silu(gate) * up, lp["down_proj"],
                       lp.get("down_proj_bias"))
        return x

    x, _ = lax.scan(lambda c, lp: (layer(c, lp), None), x, params["layers"])
    x = rms_norm(x, params["norm"], cfg.rms_norm_eps)
    lm_head = params.get("lm_head")
    if lm_head is None:
        logits = jnp.dot(x, params["embed_tokens"].T.astype(x.dtype),
                         preferred_element_type=jnp.float32)
    else:
        logits = linear(x, lm_head)
    return logits.astype(jnp.float32)


def new_cache(cfg: LlamaConfig, batch: int, max_seq: int,
              quantized: bool = False) -> KVCache:
    return init_cache(cfg.num_hidden_layers, batch, max_seq,
                      cfg.num_key_value_heads, cfg.hd,
                      quantized=quantized)


# ---------------------------------------------------------------------------
# HF checkpoint -> parameter pytree (the conversion engine for this family;
# reference analog: ggml_convert_low_bit walking nn.Modules, convert.py:643)
# ---------------------------------------------------------------------------

_LAYER_LINEARS = {
    "self_attn.q_proj": "q_proj",
    "self_attn.k_proj": "k_proj",
    "self_attn.v_proj": "v_proj",
    "self_attn.o_proj": "o_proj",
    "mlp.gate_proj": "gate_proj",
    "mlp.up_proj": "up_proj",
    "mlp.down_proj": "down_proj",
}


def convert_hf_params(
    tensors,                      # iterable of (name, np.ndarray)
    cfg: LlamaConfig,
    qtype: Optional[str] = "sym_int4",
    compute_dtype=jnp.bfloat16,
    modules_to_not_convert: Tuple[str, ...] = (),
) -> Dict[str, Any]:
    """Build the parameter pytree from HF-named tensors, quantizing linears.

    qtype=None (or a FLOAT_QTYPE) keeps dense weights in compute_dtype —
    the reference's optimize_model(low_bit=False) / BF16Linear path.
    Weights are converted tensor-by-tensor (host holds one at a time) and
    per-layer results are stacked along a leading L axis for lax.scan.
    """
    from bigdl_tpu.ops.quant import FLOAT_QTYPES, quantize_linear

    L = cfg.num_hidden_layers
    do_quant = qtype is not None and qtype not in FLOAT_QTYPES

    def cvt_linear(name: str, w) -> Any:
        w = jnp.asarray(np.asarray(w))
        if do_quant and not any(m in name for m in modules_to_not_convert):
            return quantize_linear(w, qtype)
        return w.T.astype(compute_dtype)  # contraction-major dense

    layer_acc: Dict[str, list] = {}
    params: Dict[str, Any] = {}

    def put_layer(key: str, idx: int, val):
        slot = layer_acc.setdefault(key, [None] * L)
        slot[idx] = val

    for name, w in tensors:
        if name in ("model.embed_tokens.weight", "transformer.wte.weight"):
            params["embed_tokens"] = jnp.asarray(np.asarray(w)).astype(
                compute_dtype)
        elif name == "model.norm.weight":
            params["norm"] = jnp.asarray(np.asarray(w)).astype(compute_dtype)
        elif name == "lm_head.weight":
            params["lm_head"] = cvt_linear(name, w)
        elif name.startswith("model.layers."):
            parts = name.split(".")
            idx = int(parts[2])
            sub = ".".join(parts[3:-1])   # e.g. self_attn.q_proj
            leaf = parts[-1]              # weight | bias
            if sub in _LAYER_LINEARS:
                key = _LAYER_LINEARS[sub]
                if leaf == "weight":
                    put_layer(key, idx, cvt_linear(name, w))
                else:
                    put_layer(f"{key}_bias", idx,
                              jnp.asarray(np.asarray(w)).astype(compute_dtype))
            elif sub in ("input_layernorm", "post_attention_layernorm"):
                put_layer(sub, idx,
                          jnp.asarray(np.asarray(w)).astype(compute_dtype))
            # rotary_emb.inv_freq etc. are derived, skip
        # else: ignore non-model tensors

    missing = [k for k, v in layer_acc.items() if any(x is None for x in v)]
    if missing:
        raise ValueError(f"checkpoint missing layer tensors for: {missing}")

    layers = {}
    for key, per_layer in layer_acc.items():
        layers[key] = jax.tree.map(lambda *xs: jnp.stack(xs), *per_layer)
    params["layers"] = layers

    if cfg.tie_word_embeddings:
        params.pop("lm_head", None)
    elif "lm_head" not in params:
        raise ValueError("checkpoint has no lm_head.weight and config does "
                         "not tie word embeddings")
    return params
