"""Llama-family model: functional, static-shape, scan-over-layers.

TPU-native re-design of the reference's optimized llama path
(reference transformers/models/llama.py: llama_model_forward_4_36 at :103,
llama_attention_forward_4_36 at :875, llama_mlp_forward at :150,
llama_rms_norm_forward at :134). Where the reference monkey-patches HF
nn.Modules and dispatches per-shape to SYCL kernels, this is a from-scratch
functional model over a parameter pytree:

- All linear weights are contraction-major leaves ([K, N] dense or QTensor),
  so every projection is one `linear()` call that hits the fused Pallas
  dequant-matmul on TPU.
- Per-layer parameters are STACKED along a leading L axis and the layer loop
  is `lax.scan` — one layer gets traced/compiled once, not 32 times.
- The KV cache is pre-allocated static-shape (ops/kvcache.py) and carried
  through the scan; decode never re-allocates or re-compiles.
- The same `forward()` serves prefill (Sq = prompt length) and decode
  (Sq = 1): query positions make causal + cache-tail masking uniform.

Covers the llama architecture family as the reference does (llama/llama2/
codellama/vicuna and, via configs, mistral-style GQA models).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

import functools

from bigdl_tpu.ops.attention import sdp_attention, sdp_attention_paged
from bigdl_tpu.ops.kvcache import (KVCache, init_cache, read_layer,
                                   read_layer_quantized, update_layer)
from bigdl_tpu.ops.paged import (PagedKVCache, init_paged_cache,
                                 paged_update_layer)
from bigdl_tpu.ops.matmul import linear
from bigdl_tpu.ops.embedding import embedding_lookup
from bigdl_tpu.ops.norms import layer_norm, rms_norm
from bigdl_tpu.ops.rope import (apply_rope, rope_cos_sin, rope_freqs,
                                scaled_rope_freqs)


def _lm_head(x, params, cfg):
    """Final projection (tied or separate), f32 logits, optional softcap."""
    from bigdl_tpu.ops.quant import QTensor

    lm_head = params.get("lm_head")
    if lm_head is None:
        emb = params["embed_tokens"]
        if isinstance(emb, QTensor):      # quantized table is [D, V]
            logits = linear(x, emb)
        else:
            logits = jnp.dot(x, emb.T.astype(x.dtype),
                             preferred_element_type=jnp.float32)
    else:
        logits = linear(x, lm_head, params.get("lm_head_bias"))
    logits = logits.astype(jnp.float32)
    if cfg.logits_soft_cap is not None:
        logits = jnp.tanh(logits / cfg.logits_soft_cap) * cfg.logits_soft_cap
    return logits


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    """Config for the generalized decoder module.

    The base fields describe llama; the knobs below let one scan-based code
    path serve the reference's other monkey-patched families (SURVEY.md §2:
    transformers/models/{gptneox,bloom,falcon,phi,gemma,starcoder2,...}.py)
    as config deltas instead of 400-line forks.
    """
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    head_dim: Optional[int] = None
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    rope_scaling_factor: float = 1.0
    max_position_embeddings: int = 4096
    tie_word_embeddings: bool = False
    attention_bias: bool = False
    mlp_bias: bool = False
    sliding_window: Optional[int] = None
    # --- family knobs ---
    norm_type: str = "rmsnorm"          # "rmsnorm" | "layernorm"
    rms_weight_offset: float = 0.0      # gemma: y * (offset + w)
    hidden_act: str = "silu"            # "silu" | "gelu" | "gelu_tanh"
    mlp_gated: bool = True              # False: dense 2-proj (up/down) MLP
    rope_interleaved: bool = False      # gptj/chatglm rotation convention
    rotary_dim: Optional[int] = None    # partial rotary (gptneox/phi)
    use_rope: bool = True               # False for alibi families
    learned_positions: bool = False     # gptbigcode/gpt2: wpe table added
    parallel_residual: bool = False     # x + attn(n1(x)) + mlp(n2(x))
    shared_input_norm: bool = False     # phi/falcon-7b: mlp reuses n1(x)
    use_alibi: bool = False             # bloom/baichuan-13b
    # explicit TP (parallel/tp.py) traces the decoder with LOCAL head
    # counts; ALiBi slopes are a function of the FULL head count, so the
    # local trace slices alibi_slopes(alibi_total_heads) at
    # axis_index(tp_axis) * local_heads instead of regenerating a
    # (different) schedule for the local count
    alibi_total_heads: Optional[int] = None
    tp_axis: str = "tp"
    embed_scale: float = 1.0            # gemma: sqrt(hidden_size)
    embed_norm: bool = False            # bloom: LN right after embedding
    logits_soft_cap: Optional[float] = None   # gemma2 final logits
    attn_soft_cap: Optional[float] = None     # gemma2 attention scores
    lm_head_bias: bool = False          # phi
    # non-linear rope scaling (yarn/dynamic/llama3) as a hashable
    # sorted-items tuple; linear scaling uses rope_scaling_factor
    rope_scaling: Optional[Tuple[Tuple[str, Any], ...]] = None
    # gemma2 block shape: norms AFTER attn/mlp outputs too, scaled queries,
    # sliding window on even layers only
    sandwich_norms: bool = False
    query_pre_attn_scalar: Optional[float] = None
    alt_sliding_window: bool = False
    # sparse-MoE MLP (phixtral-style; layer params carry "router" +
    # "experts_*" stacks instead of the dense mlp keys)
    num_local_experts: int = 0
    num_experts_per_tok: int = 2

    @property
    def hd(self) -> int:
        return self.head_dim or self.hidden_size // self.num_attention_heads

    @classmethod
    def from_hf(cls, hf: Dict[str, Any]) -> "LlamaConfig":
        """Build from an HF config dict (config.json of llama/mistral...)."""
        rs = hf.get("rope_scaling") or {}
        factor = 1.0
        rs_tuple = None
        if rs:
            rtype = rs.get("rope_type", rs.get("type", "linear"))
            if rtype == "linear":
                factor = float(rs.get("factor", 1.0))
            elif rtype in ("default", "none"):
                pass
            else:
                # yarn / dynamic / llama3: handled by scaled_rope_freqs;
                # stored as a hashable tuple (config is a jit static arg)
                rs_tuple = tuple(sorted(
                    (k, v) for k, v in rs.items()
                    if isinstance(v, (int, float, str))))
        return cls(
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            intermediate_size=hf["intermediate_size"],
            num_hidden_layers=hf["num_hidden_layers"],
            num_attention_heads=hf["num_attention_heads"],
            num_key_value_heads=hf.get(
                "num_key_value_heads", hf["num_attention_heads"]),
            head_dim=hf.get("head_dim"),
            rms_norm_eps=hf.get("rms_norm_eps", 1e-5),
            rope_theta=hf.get("rope_theta", 10000.0),
            rope_scaling_factor=factor,
            rope_scaling=rs_tuple,
            max_position_embeddings=hf.get("max_position_embeddings", 4096),
            tie_word_embeddings=hf.get("tie_word_embeddings", False),
            attention_bias=hf.get("attention_bias", False),
            mlp_bias=hf.get("mlp_bias", False),
            sliding_window=hf.get("sliding_window"),
        )


# Parameter pytree layout (all linear leaves contraction-major [K, N]):
# {
#   "embed_tokens": [V, D],
#   "layers": {
#     "input_layernorm":          [L, D],
#     "post_attention_layernorm": [L, D],
#     "q_proj" | "k_proj" | "v_proj" | "o_proj":       stacked QTensor/dense,
#     "gate_proj" | "up_proj" | "down_proj":           stacked QTensor/dense,
#     (+ "<name>_bias": [L, N] when attention_bias/mlp_bias)
#   },
#   "norm": [D],
#   "lm_head": QTensor/dense [D, V] (absent when tied),
# }


def merge_projections(params: Dict[str, Any], cfg: "LlamaConfig"
                      ) -> Dict[str, Any]:
    """Fuse q/k/v into one [D, (H+2Hkv)*hd] weight and gate/up into one
    [D, 2F] — the reference's `_optimize_pre` weight surgery + fused
    `forward_qkv`/`mlp_forward_xpu` kernels (reference transformers/
    convert.py:529-640, models/llama.py:362-373, 162-166), done here as
    a pure param transform: one matmul instead of three (two) per block
    raises prefill MFU and cuts decode kernel dispatches; block
    quantization is per-column so the merge is BIT-exact.

    Skips (returns inputs unchanged) whenever the merge would not be
    exact or the layout does not apply: mixed qtypes across the
    projections, partial biases, MoE layers, non-gated MLPs. The layer
    body (`_attn_block`/`_mlp`) accepts both layouts; use
    `unmerge_projections` to restore the split layout (adapters and
    explicit TP sharding need it)."""
    from bigdl_tpu.ops.quant import QTensor, concat_qtensors_n

    layers = params.get("layers")
    if not isinstance(layers, dict):
        return params

    def bundle(names):
        ws = [layers.get(nm) for nm in names]
        if any(w is None for w in ws):
            return None, None
        if all(isinstance(w, QTensor) for w in ws):
            if len({w.qtype for w in ws}) != 1 \
                    or len({w.shape[0] for w in ws}) != 1:
                return None, None
        elif any(isinstance(w, QTensor) for w in ws):
            return None, None
        elif len({w.dtype for w in ws}) != 1 \
                or len({w.shape[-2] for w in ws}) != 1:
            return None, None
        bs = [layers.get(f"{nm}_bias") for nm in names]
        if any(b is not None for b in bs) and not all(
                b is not None for b in bs):
            return None, None            # partial biases: keep split
        return ws, (bs if bs[0] is not None else None)

    def concat(ws):
        if isinstance(ws[0], QTensor):
            return concat_qtensors_n(ws)
        return jnp.concatenate(ws, axis=-1)

    new = dict(layers)
    changed = False
    qkv, qkv_b = bundle(("q_proj", "k_proj", "v_proj"))
    if qkv is not None:
        new["qkv_proj"] = concat(qkv)
        if qkv_b is not None:
            new["qkv_proj_bias"] = jnp.concatenate(qkv_b, axis=-1)
        for nm in ("q_proj", "k_proj", "v_proj"):
            new.pop(nm)
            new.pop(f"{nm}_bias", None)
        changed = True
    gu, gu_b = bundle(("gate_proj", "up_proj"))
    if gu is not None:
        new["gate_up_proj"] = concat(gu)
        if gu_b is not None:
            new["gate_up_proj_bias"] = jnp.concatenate(gu_b, axis=-1)
        for nm in ("gate_proj", "up_proj"):
            new.pop(nm)
            new.pop(f"{nm}_bias", None)
        changed = True
    if not changed:
        return params
    return {**params, "layers": new}


def unmerge_projections(params: Dict[str, Any], cfg: "LlamaConfig"
                        ) -> Dict[str, Any]:
    """Inverse of `merge_projections` (exact slicing)."""
    from bigdl_tpu.ops.quant import QTensor, split_qtensor_n

    layers = params.get("layers")
    if not isinstance(layers, dict):
        return params

    def split(w, sizes):
        if isinstance(w, QTensor):
            return split_qtensor_n(w, sizes)
        off, outs = 0, []
        for s in sizes:
            outs.append(w[..., off:off + s])
            off += s
        return outs

    new = dict(layers)
    changed = False
    if "qkv_proj" in new:
        h, hkv, hd = (cfg.num_attention_heads, cfg.num_key_value_heads,
                      cfg.hd)
        sizes = (h * hd, hkv * hd, hkv * hd)
        for nm, w in zip(("q_proj", "k_proj", "v_proj"),
                         split(new.pop("qkv_proj"), sizes)):
            new[nm] = w
        if "qkv_proj_bias" in new:
            for nm, b in zip(("q_proj", "k_proj", "v_proj"),
                             split(new.pop("qkv_proj_bias"), sizes)):
                new[f"{nm}_bias"] = b
        changed = True
    if "gate_up_proj" in new:
        gu = new.pop("gate_up_proj")
        f = (gu.shape[1] if isinstance(gu, QTensor)
             else gu.shape[-1]) // 2
        for nm, w in zip(("gate_proj", "up_proj"), split(gu, (f, f))):
            new[nm] = w
        if "gate_up_proj_bias" in new:
            for nm, b in zip(("gate_proj", "up_proj"),
                             split(new.pop("gate_up_proj_bias"), (f, f))):
                new[f"{nm}_bias"] = b
        changed = True
    if not changed:
        return params
    return {**params, "layers": new}


def model_rope_freqs(cfg: "LlamaConfig"):
    """(inv_freq, attention_factor) honoring cfg.rope_scaling."""
    if cfg.rope_scaling is not None:
        return scaled_rope_freqs(
            cfg.hd, cfg.rope_theta, dict(cfg.rope_scaling),
            rotary_dim=cfg.rotary_dim,
            max_position_embeddings=cfg.max_position_embeddings)
    return rope_freqs(cfg.hd, cfg.rope_theta, rotary_dim=cfg.rotary_dim,
                      scaling_factor=cfg.rope_scaling_factor), 1.0


def alibi_slopes(n_heads: int) -> np.ndarray:
    """Standard ALiBi slope schedule (bloom/baichuan-13b families)."""
    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(np.log2(n) - 3)))
        return start * (start ** np.arange(n))

    if np.log2(n_heads).is_integer():
        return pow2_slopes(n_heads).astype(np.float32)
    closest = 2 ** int(np.floor(np.log2(n_heads)))
    base = pow2_slopes(closest)
    extra = pow2_slopes(2 * closest)[0::2][: n_heads - closest]
    return np.concatenate([base, extra]).astype(np.float32)


def _model_slopes(cfg: "LlamaConfig") -> Optional[jax.Array]:
    """Per-head ALiBi slopes for THIS trace's head count.

    Single device: the full schedule. Under explicit TP (parallel/tp.py)
    cfg carries local head counts but slopes are a function of the FULL
    count — slice the full schedule at this device's head offset."""
    if not cfg.use_alibi:
        return None
    total = cfg.alibi_total_heads or cfg.num_attention_heads
    full = jnp.asarray(alibi_slopes(total))
    if total == cfg.num_attention_heads:
        return full
    idx = lax.axis_index(cfg.tp_axis)
    return lax.dynamic_slice(full, (idx * cfg.num_attention_heads,),
                             (cfg.num_attention_heads,))


def _norm(x, w, b, cfg: LlamaConfig):
    if cfg.norm_type == "layernorm":
        return layer_norm(x, w, b, cfg.rms_norm_eps)
    if cfg.rms_weight_offset:
        w = w.astype(jnp.float32) + cfg.rms_weight_offset
    return rms_norm(x, w, cfg.rms_norm_eps)


def embed_prologue(params, cfg: LlamaConfig, tokens, positions,
                   compute_dtype):
    """Token embedding + scale + embedding norm + learned positions.

    THE one copy of the embed stage — forward/forward_train here,
    the pipeline schedule (parallel/pp.py) and imatrix calibration all
    call it, so a new config knob lands everywhere at once. `positions`
    is [Sq] (shared) or [B, Sq] (per-slot serving)."""
    x = embedding_lookup(params["embed_tokens"], tokens, compute_dtype)
    if cfg.embed_scale != 1.0:
        x = x * jnp.asarray(cfg.embed_scale, compute_dtype)
    if cfg.embed_norm:
        x = _norm(x, params["embed_norm"], params.get("embed_norm_bias"),
                  cfg)
    if cfg.learned_positions:
        pe = params["embed_positions"][positions].astype(x.dtype)
        if pe.ndim == 2:                  # positions [Sq]: add batch axis
            pe = pe[None]
        x = x + pe
    return x


_ACTS = {
    "silu": jax.nn.silu,
    "gelu": functools.partial(jax.nn.gelu, approximate=False),
    "gelu_tanh": functools.partial(jax.nn.gelu, approximate=True),
    "gelu_new": functools.partial(jax.nn.gelu, approximate=True),
    "gelu_pytorch_tanh": functools.partial(jax.nn.gelu, approximate=True),
    "relu": jax.nn.relu,
}


def _moe_mlp(hidden, lp, cfg: LlamaConfig):
    """Sparse-MoE MLP for generalized-decoder families (phixtral: phi body
    with a mixture of dense fc1/fc2 experts, reference transformers/models/
    phixtral.py:73-138 — there a Python loop with host syncs; here two
    host-sync-free strategies chosen by token count, like the reference's
    prefill/decode split in mixtral_moeblock_forward:

    - prefill (many tokens): dense one-hot einsum combine — every expert
      runs on every token; with enough tokens per expert the full-expert
      weight read amortizes and everything is big MXU matmuls.
    - decode (few tokens): per-token expert GATHER — only the top-k
      experts' weights leave HBM (dynamic-index on the stacked [E, ...]
      leaves), cutting MoE decode HBM traffic by E/k (4x for Mixtral
      8x top-2), which is the whole cost of a memory-bound decode step."""
    b, t, d = hidden.shape
    act = _ACTS[cfg.hidden_act]
    xf = hidden.reshape(-1, d)
    n = xf.shape[0]
    router_logits = jnp.dot(xf, lp["router"].astype(hidden.dtype),
                            preferred_element_type=jnp.float32)
    topv, topi = lax.top_k(router_logits, cfg.num_experts_per_tok)
    w = jax.nn.softmax(topv, axis=-1)                         # [N, k]

    gated = cfg.mlp_gated
    biased = (not gated) and ("experts_up_bias" in lp)
    # explicit TP wraps experts_down in a collective-injecting wrapper
    # (parallel/tp.AllReduceLinear); paths that consume the raw stack
    # (qtype probes, the ragged kernel) unwrap it and apply the reduce
    # to their partial output themselves
    dleaf = lp["experts_down"]
    post_reduce = getattr(dleaf, "post_reduce", None)
    dstack = dleaf.base if post_reduce is not None else dleaf

    def one_expert(x_row, gw, uw, dw, ub, db, backend=None):
        """x [1, D] through ONE expert's projections."""
        if gated:
            return linear(act(linear(x_row, gw, backend=backend))
                          * linear(x_row, uw, backend=backend), dw,
                          backend=backend)
        return linear(act(linear(x_row, uw, ub, backend=backend)), dw, db,
                      backend=backend)

    # gather path pays k weight-gathers per token; dense pays E expert
    # matmuls over all N tokens — switch where gathered bytes win
    if n * cfg.num_experts_per_tok <= cfg.num_local_experts:
        from bigdl_tpu.ops.matmul import vmapped_pallas_ok

        # fused kernels under vmap are gated by eager probes covering
        # EVERY (qtype, geometry) the gather actually runs — mixed_*
        # policies can land different qtypes per projection — (compile
        # failures degrade to the XLA matmul, never crash a jit); dense
        # expert stacks never hit pallas
        ff = cfg.intermediate_size
        probes = []
        for leaf, kk, nn in ((lp.get("experts_gate"), d, ff),
                             (lp.get("experts_up"), d, ff),
                             (dstack, ff, d)):
            if leaf is not None and hasattr(leaf, "qtype"):
                probes.append((leaf.qtype, kk, nn))
        gather_backend = (
            None if probes and all(vmapped_pallas_ok(*p) for p in probes)
            else "xla")

        def per_token(x_row, idxs, wts):
            def per_choice(i):
                gw = (jax.tree.map(lambda a: a[i], lp["experts_gate"])
                      if gated else None)
                uw = jax.tree.map(lambda a: a[i], lp["experts_up"])
                dw = jax.tree.map(lambda a: a[i], lp["experts_down"])
                ub = lp["experts_up_bias"][i] if biased else None
                db = lp["experts_down_bias"][i] if biased else None
                return one_expert(x_row[None], gw, uw, dw, ub, db,
                                  backend=gather_backend)[0]

            outs = jnp.stack([per_choice(idxs[j])
                              for j in range(cfg.num_experts_per_tok)])
            return jnp.sum(outs * wts[:, None].astype(outs.dtype), axis=0)

        y = jax.vmap(per_token)(xf, topi, w)
        return y.reshape(b, t, d)

    # prefill: sorted ragged dispatch runs only the CHOSEN experts'
    # FLOPs (E/k cut vs the dense combine below); requires the Pallas
    # kernel, probed per geometry. Quantized-with-bias stacks (none of
    # the served families) would fall through to dense.
    from bigdl_tpu.config import flags, target_is_tpu, under_spmd

    if (not biased and flags().moe_dispatch != "dense"
            and not under_spmd(xf, *jax.tree_util.tree_leaves(
                lp["experts_up"]))
            and (target_is_tpu()
                 or flags().moe_dispatch == "ragged")):
        from bigdl_tpu.ops.pallas.moe_dispatch import (
            moe_mlp_ragged, ragged_kernel_compiles)

        interp = not target_is_tpu()
        forced = flags().moe_dispatch == "ragged"
        # forced mode bypasses the probes so compile errors SURFACE
        # (A/B runs must never silently measure the dense path); auto
        # probes every (qtype, geometry) pair the dispatch runs
        ff = cfg.intermediate_size
        pairs = []
        for leaf, kk, nn in ((lp.get("experts_gate"), d, ff),
                             (lp.get("experts_up"), d, ff),
                             (dstack, ff, d)):
            if leaf is not None:
                pairs.append((leaf.qtype if hasattr(leaf, "qtype")
                              else None, kk, nn))
        if interp or forced or all(
                ragged_kernel_compiles(*p) for p in pairs):
            y = moe_mlp_ragged(
                xf, topi, w,
                lp["experts_gate"] if gated else None,
                lp["experts_up"], dstack, act,
                cfg.num_local_experts, interpret=interp)
            if post_reduce is not None:
                # ragged ran on the local ff shard: reduce the partial
                y = post_reduce(y)
            return y.reshape(b, t, d)

    combine = jnp.sum(
        jax.nn.one_hot(topi, cfg.num_local_experts, dtype=w.dtype)
        * w[..., None], axis=1)                               # [N, E]

    if gated:
        all_out = jax.vmap(lambda gw, uw, dw: one_expert(
            xf, gw, uw, dw, None, None))(
            lp["experts_gate"], lp["experts_up"], lp["experts_down"])
    elif biased:
        all_out = jax.vmap(lambda uw, ub, dw, db: one_expert(
            xf, None, uw, dw, ub, db))(
            lp["experts_up"], lp["experts_up_bias"],
            lp["experts_down"], lp["experts_down_bias"])
    else:
        all_out = jax.vmap(lambda uw, dw: one_expert(
            xf, None, uw, dw, None, None))(
            lp["experts_up"], lp["experts_down"])
    y = jnp.einsum("ne,end->nd", combine.astype(hidden.dtype), all_out)
    return y.reshape(b, t, d)


def _mlp(hidden, lp, cfg: LlamaConfig, record=None):
    if "router" in lp:
        if record is not None:
            # silent no-stats would quietly degrade every expert weight
            # to unweighted quantization — the bulk of an MoE model
            raise NotImplementedError(
                "imatrix collection over MoE expert MLPs is not supported "
                "yet; quantize MoE models without an imatrix (attention "
                "projections would be the only weighted tensors)")
        return _moe_mlp(hidden, lp, cfg)
    act = _ACTS[cfg.hidden_act]
    if "gate_up_proj" in lp:
        if record is not None:
            record("gate_up_proj", hidden)
        gu = linear(hidden, lp["gate_up_proj"], lp.get("gate_up_proj_bias"))
        f = gu.shape[-1] // 2
        inner = act(gu[..., :f]) * gu[..., f:]
        if record is not None:
            record("down_proj", inner)
        return linear(inner, lp["down_proj"], lp.get("down_proj_bias"))
    if record is not None:
        record("gate_proj" if cfg.mlp_gated else "up_proj", hidden)
        if cfg.mlp_gated:
            record("up_proj", hidden)
    if cfg.mlp_gated:
        gate = linear(hidden, lp["gate_proj"], lp.get("gate_proj_bias"))
        up = linear(hidden, lp["up_proj"], lp.get("up_proj_bias"))
        inner = act(gate) * up
    else:
        inner = act(linear(hidden, lp["up_proj"], lp.get("up_proj_bias")))
    if record is not None:
        record("down_proj", inner)
    return linear(inner, lp["down_proj"], lp.get("down_proj_bias"))


def _split_qkv(qkv, b, sq, h, hkv, hd):
    """Merged-projection output [B, Sq, (H+2Hkv)*hd] -> q/k/v heads."""
    q = qkv[..., :h * hd].reshape(b, sq, h, hd)
    k = qkv[..., h * hd:(h + hkv) * hd].reshape(b, sq, hkv, hd)
    v = qkv[..., (h + hkv) * hd:].reshape(b, sq, hkv, hd)
    return q, k, v


def _attn_block(hidden, lp, cfg: LlamaConfig, cos, sin, slopes,
                cache_ctx=None, lidx=None, record=None,
                block_tables=None):
    """QKV + rope + (cached) attention + output projection.

    With ``block_tables`` the cache planes in ``cache_ctx`` are page
    ARENAS (``[L, P, ps, Hkv, D]``): appends scatter through the table
    and attention reads via `sdp_attention_paged` (fused gather on TPU,
    XLA take fallback elsewhere)."""
    b, sq, _ = hidden.shape
    h, hkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.hd
    scale = (cfg.query_pre_attn_scalar ** -0.5
             if cfg.query_pre_attn_scalar is not None else None)
    sw = cfg.sliding_window
    if cfg.alt_sliding_window and sw is not None and lidx is not None:
        # gemma2: sliding attention on even layers, global on odd
        sw = jnp.where(lidx % 2 == 0, sw, jnp.int32(1 << 30))
    if "qkv_proj" in lp:
        if record is not None:
            record("qkv_proj", hidden)
        q, k, v = _split_qkv(
            linear(hidden, lp["qkv_proj"], lp.get("qkv_proj_bias")),
            b, sq, h, hkv, hd)
    else:
        if record is not None:
            record("q_proj", hidden)
            record("k_proj", hidden)
            record("v_proj", hidden)
        q = linear(hidden, lp["q_proj"], lp.get("q_proj_bias")).reshape(
            b, sq, h, hd)
        k = linear(hidden, lp["k_proj"], lp.get("k_proj_bias")).reshape(
            b, sq, hkv, hd)
        v = linear(hidden, lp["v_proj"], lp.get("v_proj_bias")).reshape(
            b, sq, hkv, hd)
    if cfg.use_rope:
        q = apply_rope(q, cos, sin, interleaved=cfg.rope_interleaved)
        k = apply_rope(k, cos, sin, interleaved=cfg.rope_interleaved)

    if cache_ctx is not None and block_tables is not None:
        ck, cv, cks, cvs, clidx, pos = cache_ctx
        if cks is not None:
            ck, cv, cks, cvs = paged_update_layer(
                ck, cv, clidx, k, v, pos, block_tables, cks, cvs)
            kq = lax.dynamic_index_in_dim(ck, clidx, 0, keepdims=False)
            vq = lax.dynamic_index_in_dim(cv, clidx, 0, keepdims=False)
            ksc = lax.dynamic_index_in_dim(cks, clidx, 0, keepdims=False)
            vsc = lax.dynamic_index_in_dim(cvs, clidx, 0, keepdims=False)
            attn = sdp_attention_paged(q, kq, vq, block_tables, pos,
                                       scale=scale, sliding_window=sw,
                                       logits_soft_cap=cfg.attn_soft_cap,
                                       alibi_slopes=slopes,
                                       k_scale=ksc, v_scale=vsc)
        else:
            ck, cv = paged_update_layer(ck, cv, clidx, k, v, pos,
                                        block_tables)
            kf = lax.dynamic_index_in_dim(ck, clidx, 0, keepdims=False)
            vf = lax.dynamic_index_in_dim(cv, clidx, 0, keepdims=False)
            attn = sdp_attention_paged(q, kf, vf, block_tables, pos,
                                       scale=scale, sliding_window=sw,
                                       logits_soft_cap=cfg.attn_soft_cap,
                                       alibi_slopes=slopes)
        out = (ck, cv, cks, cvs)
    elif cache_ctx is not None:
        ck, cv, cks, cvs, clidx, pos = cache_ctx
        if cks is not None:
            # block-scaled storage: quantize-on-append, then hand raw
            # codes + scale planes to the attention dispatch so the
            # dequant fuses into the kernels
            ck, cv, cks, cvs = update_layer(ck, cv, clidx, k, v, pos,
                                            cks, cvs)
            kq, vq, ksc, vsc = read_layer_quantized(ck, cv, cks, cvs, clidx)
            attn = sdp_attention(q, kq, vq, pos, scale=scale,
                                 sliding_window=sw,
                                 logits_soft_cap=cfg.attn_soft_cap,
                                 alibi_slopes=slopes,
                                 k_scale=ksc, v_scale=vsc)
        else:
            ck, cv = update_layer(ck, cv, clidx, k, v, pos)
            kf, vf = read_layer(ck, cv, clidx)
            attn = sdp_attention(q, kf, vf, pos, scale=scale,
                                 sliding_window=sw,
                                 logits_soft_cap=cfg.attn_soft_cap,
                                 alibi_slopes=slopes)
        out = (ck, cv, cks, cvs)
    else:
        attn = sdp_attention(q, k, v, jnp.zeros((), jnp.int32), scale=scale,
                             sliding_window=sw,
                             logits_soft_cap=cfg.attn_soft_cap,
                             alibi_slopes=slopes)
        out = None
    attn = attn.reshape(b, sq, h * hd)
    if record is not None:
        record("o_proj", attn)
    return linear(attn, lp["o_proj"], lp.get("o_proj_bias")), out


def _decoder_layer(x, lp, cfg: LlamaConfig, cos, sin, slopes,
                   cache_ctx=None, lidx=None, record=None,
                   block_tables=None):
    """One transformer block, sequential/parallel/sandwich residual.

    `record(key, activation)` (optional, trace-time) observes the input of
    every linear — the imatrix collection hook (bigdl_tpu.imatrix), kept
    here so statistics always match the real forward."""
    hidden = _norm(x, lp["input_layernorm"],
                   lp.get("input_layernorm_bias"), cfg)
    attn_out, cache_out = _attn_block(hidden, lp, cfg, cos, sin, slopes,
                                      cache_ctx, lidx=lidx, record=record,
                                      block_tables=block_tables)
    if cfg.sandwich_norms:
        # gemma2: x += postnorm(attn(prenorm(x))); same sandwich for mlp
        attn_out = _norm(attn_out, lp["post_attention_layernorm"],
                         lp.get("post_attention_layernorm_bias"), cfg)
        x = x + attn_out
        mlp_in = _norm(x, lp["pre_feedforward_layernorm"],
                       lp.get("pre_feedforward_layernorm_bias"), cfg)
        mlp_out = _mlp(mlp_in, lp, cfg, record=record)
        mlp_out = _norm(mlp_out, lp["post_feedforward_layernorm"],
                        lp.get("post_feedforward_layernorm_bias"), cfg)
        return x + mlp_out, cache_out
    if cfg.parallel_residual:
        if cfg.shared_input_norm:
            mlp_in = hidden
        else:
            mlp_in = _norm(x, lp["post_attention_layernorm"],
                           lp.get("post_attention_layernorm_bias"), cfg)
        x = x + attn_out + _mlp(mlp_in, lp, cfg, record=record)
    else:
        x = x + attn_out
        hidden2 = _norm(x, lp["post_attention_layernorm"],
                        lp.get("post_attention_layernorm_bias"), cfg)
        x = x + _mlp(hidden2, lp, cfg, record=record)
    return x, cache_out


def _layer_step(cfg: LlamaConfig, slopes, carry, xs):
    x, ck, cv, cks, cvs, pos, cos, sin = carry
    lp, lidx = xs
    x, (ck, cv, cks, cvs) = _decoder_layer(
        x, lp, cfg, cos, sin, slopes,
        cache_ctx=(ck, cv, cks, cvs, lidx, pos), lidx=lidx)
    return (x, ck, cv, cks, cvs, pos, cos, sin), None


def forward(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    tokens: jax.Array,       # [B, Sq] int32
    cache: KVCache,
    compute_dtype=jnp.bfloat16,
    last_only: bool = False,
    visual: Optional[Tuple[jax.Array, jax.Array]] = None,
) -> Tuple[jax.Array, KVCache]:
    """Run the model; returns (logits [B, Sq, V], updated cache).

    `cache.pos` is the write offset: 0 for prefill, prompt_len + n for the
    n-th decode step. One function, both phases (static Sq distinguishes
    the compiled executables). last_only=True computes lm_head for the
    final position only — the reference's `optimize_lm_head` trick
    (low_bit_linear.py:251-258), which matters when V=32k+ and Sq is long.

    `visual=(vidx [B, Sq] int32, vemb [Nv, D])` splices multimodal
    embeddings over the token embeddings: rows where vidx > 0 take
    vemb[vidx-1] (Qwen-VL image spans, models/qwen_vl.py; the reference
    mutates hidden_states in place in qwen_vl's QWenModel.forward). One
    gather + select — shapes stay static, positions/RoPE unchanged.
    """
    b, sq = tokens.shape
    pos = cache.pos

    inv_freq, rope_mscale = model_rope_freqs(cfg)
    if getattr(pos, "ndim", 0) == 1:   # per-slot positions (serving)
        positions = pos[:, None] + jnp.arange(sq, dtype=jnp.int32)[None, :]
        cos, sin = rope_cos_sin(positions, inv_freq)       # [B, Sq, hd/2]
    else:
        positions = pos + jnp.arange(sq, dtype=jnp.int32)
        cos, sin = rope_cos_sin(positions[None, :], inv_freq)  # [1, Sq, hd/2]
    x = embed_prologue(params, cfg, tokens, positions, compute_dtype)
    if visual is not None:
        vidx, vemb = visual
        x = jnp.where((vidx > 0)[..., None],
                      vemb[jnp.clip(vidx - 1, 0)].astype(x.dtype), x)
    if rope_mscale != 1.0:             # yarn attention temperature
        cos, sin = cos * rope_mscale, sin * rope_mscale
    slopes = _model_slopes(cfg)

    lidx = jnp.arange(cfg.num_hidden_layers, dtype=jnp.int32)
    # scale planes are None for bf16/fp8 storage — None is an empty
    # pytree, so the scan carry structure stays consistent either way
    (x, ck, cv, cks, cvs, _, _, _), _ = lax.scan(
        lambda c, xs: _layer_step(cfg, slopes, c, xs),
        (x, cache.k, cache.v, cache.k_scale, cache.v_scale, pos, cos, sin),
        (params["layers"], lidx),
    )

    if last_only:
        x = x[:, -1:, :]
    x = _norm(x, params["norm"], params.get("norm_bias"), cfg)
    logits = _lm_head(x, params, cfg)
    return logits, KVCache(ck, cv, pos + sq, cks, cvs)


def forward_last_token(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    tokens: jax.Array,
    cache: KVCache,
    compute_dtype=jnp.bfloat16,
    visual: Optional[Tuple[jax.Array, jax.Array]] = None,
) -> Tuple[jax.Array, KVCache]:
    """Prefill variant of `forward` with lm_head on the final position only."""
    return forward(params, cfg, tokens, cache, compute_dtype=compute_dtype,
                   last_only=True, visual=visual)


def _paged_layer_step(cfg: LlamaConfig, slopes, block_tables, carry, xs):
    x, ck, cv, cks, cvs, pos, cos, sin = carry
    lp, lidx = xs
    x, (ck, cv, cks, cvs) = _decoder_layer(
        x, lp, cfg, cos, sin, slopes,
        cache_ctx=(ck, cv, cks, cvs, lidx, pos), lidx=lidx,
        block_tables=block_tables)
    return (x, ck, cv, cks, cvs, pos, cos, sin), None


def forward_paged(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    tokens: jax.Array,        # [B, Sq] int32
    cache: PagedKVCache,
    block_tables: jax.Array,  # [B, NP] int32
    compute_dtype=jnp.bfloat16,
    last_only: bool = False,
) -> Tuple[jax.Array, PagedKVCache]:
    """`forward` over a paged KV arena: appends scatter through the
    block table, attention gathers through it (fused on TPU). Positions
    are always per-slot ([B] `cache.pos`) — the paged layout exists for
    continuous batching. With ``NP * page_size == max_seq`` the logits
    are byte-identical to the slab `forward` at equal positions (tests
    pin this for bf16/int8/int4 storage)."""
    b, sq = tokens.shape
    pos = cache.pos

    inv_freq, rope_mscale = model_rope_freqs(cfg)
    positions = pos[:, None] + jnp.arange(sq, dtype=jnp.int32)[None, :]
    cos, sin = rope_cos_sin(positions, inv_freq)           # [B, Sq, hd/2]
    x = embed_prologue(params, cfg, tokens, positions, compute_dtype)
    if rope_mscale != 1.0:             # yarn attention temperature
        cos, sin = cos * rope_mscale, sin * rope_mscale
    slopes = _model_slopes(cfg)

    lidx = jnp.arange(cfg.num_hidden_layers, dtype=jnp.int32)
    (x, ck, cv, cks, cvs, _, _, _), _ = lax.scan(
        lambda c, xs: _paged_layer_step(cfg, slopes, block_tables, c, xs),
        (x, cache.k, cache.v, cache.k_scale, cache.v_scale, pos, cos, sin),
        (params["layers"], lidx),
    )

    if last_only:
        x = x[:, -1:, :]
    x = _norm(x, params["norm"], params.get("norm_bias"), cfg)
    logits = _lm_head(x, params, cfg)
    return logits, PagedKVCache(ck, cv, pos + sq, cks, cvs)


def ext_attn_layer(x, lp, cfg: LlamaConfig, cos, sin, attn_fn):
    """One transformer block with an EXTERNAL attention function —
    THE shared layer body of every parallel attention scheme
    (forward_train's ring-attention branch, parallel/cp.py's context-
    parallel prefill/decode). attn_fn(q, k, v) -> attention output;
    returns (x_out, (k, v)) so callers that keep a KV cache can collect
    the projections. Families outside the standard residual path are
    rejected by the callers' guards."""
    b, s, _ = x.shape
    h, hkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.hd
    hidden = _norm(x, lp["input_layernorm"],
                   lp.get("input_layernorm_bias"), cfg)
    if "qkv_proj" in lp:
        q, k, v = _split_qkv(
            linear(hidden, lp["qkv_proj"], lp.get("qkv_proj_bias")),
            b, s, h, hkv, hd)
    else:
        q = linear(hidden, lp["q_proj"], lp.get("q_proj_bias")).reshape(
            b, s, h, hd)
        k = linear(hidden, lp["k_proj"], lp.get("k_proj_bias")).reshape(
            b, s, hkv, hd)
        v = linear(hidden, lp["v_proj"], lp.get("v_proj_bias")).reshape(
            b, s, hkv, hd)
    if cfg.use_rope:
        q = apply_rope(q, cos, sin, interleaved=cfg.rope_interleaved)
        k = apply_rope(k, cos, sin, interleaved=cfg.rope_interleaved)
    attn_out = linear(attn_fn(q, k, v).reshape(b, s, h * hd),
                      lp["o_proj"], lp.get("o_proj_bias"))
    if cfg.parallel_residual:
        mlp_in = hidden if cfg.shared_input_norm else _norm(
            x, lp["post_attention_layernorm"],
            lp.get("post_attention_layernorm_bias"), cfg)
        return x + attn_out + _mlp(mlp_in, lp, cfg), (k, v)
    x2 = x + attn_out
    hidden2 = _norm(x2, lp["post_attention_layernorm"],
                    lp.get("post_attention_layernorm_bias"), cfg)
    return x2 + _mlp(hidden2, lp, cfg), (k, v)


def forward_train(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    tokens: jax.Array,       # [B, S] int32
    compute_dtype=jnp.bfloat16,
    attn_fn=None,            # (q, k, v) -> out; default causal sdp
    pos_offset=0,            # global position of tokens[:, 0] (seq parallel)
    return_hidden: bool = False,   # post-norm hidden states instead of logits
) -> jax.Array:
    """Cacheless causal forward for training: returns logits [B, S, V]
    (or the post-final-norm hidden states [B, S, D] with
    `return_hidden=True` — the embeddings path, reference
    langchain/embeddings pooled model outputs).

    The finetuning path (QLoRA stack, reference transformers/qlora.py) runs
    through this; no KV cache is materialized, attention is causal over the
    in-flight sequence, and `jax.checkpoint` on the layer body trades FLOPs
    for HBM during backward (the scan carries only layer inputs).

    `attn_fn`/`pos_offset` let sequence parallelism swap in ring attention
    over the sp mesh axis (bigdl_tpu.parallel.sp) with per-shard RoPE
    offsets — the model body is otherwise unchanged.
    """
    b, s = tokens.shape
    inv_freq, rope_mscale = model_rope_freqs(cfg)
    positions = pos_offset + jnp.arange(s, dtype=jnp.int32)
    x = embed_prologue(params, cfg, tokens, positions, compute_dtype)
    cos, sin = rope_cos_sin(positions[None, :], inv_freq)
    if rope_mscale != 1.0:             # yarn attention temperature
        cos, sin = cos * rope_mscale, sin * rope_mscale

    h, hkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.hd

    slopes = _model_slopes(cfg)

    if attn_fn is not None:
        if (cfg.use_alibi or cfg.attn_soft_cap is not None
                or cfg.sandwich_norms or cfg.alt_sliding_window
                or cfg.query_pre_attn_scalar is not None):
            raise NotImplementedError(
                "external attn_fn (sequence-parallel ring attention) does "
                "not support ALiBi/soft-cap/gemma2-style families yet; "
                "train these single-device or extend ops/ring.py")
        ext_attn = attn_fn

        @jax.checkpoint
        def layer(x, lp):
            out, _ = ext_attn_layer(x, lp, cfg, cos, sin, ext_attn)
            return out
    else:
        @jax.checkpoint
        def layer(x, lp, lidx):
            out, _ = _decoder_layer(x, lp, cfg, cos, sin, slopes,
                                    cache_ctx=None, lidx=lidx)
            return out

    if attn_fn is not None:
        x, _ = lax.scan(lambda c, lp: (layer(c, lp), None), x,
                        params["layers"])
    else:
        lids = jnp.arange(cfg.num_hidden_layers, dtype=jnp.int32)
        x, _ = lax.scan(lambda c, xs: (layer(c, xs[0], xs[1]), None), x,
                        (params["layers"], lids))
    x = _norm(x, params["norm"], params.get("norm_bias"), cfg)
    if return_hidden:
        return x
    return _lm_head(x, params, cfg)


# this family threads int8/int4 scale planes through its forward scan;
# serving consults the attribute before enabling block-scaled storage
SUPPORTS_SCALED_KV = True

# this family's forward_paged threads block tables through its scan;
# serving consults the attribute before enabling the paged KV arena
SUPPORTS_PAGED_KV = True


def new_cache(cfg: LlamaConfig, batch: int, max_seq: int,
              quantized=False) -> KVCache:
    """`quantized` accepts the legacy bool (True -> fp8_e5m2, deprecated)
    or a kv_cache_dtype name ("bf16"|"fp8_e5m2"|"int8"|"int4")."""
    return init_cache(cfg.num_hidden_layers, batch, max_seq,
                      cfg.num_key_value_heads, cfg.hd,
                      quantized=quantized)


def new_paged_cache(cfg: LlamaConfig, num_pages: int, page_size: int,
                    batch: int, kv_cache_dtype=None) -> PagedKVCache:
    """Allocate this family's page arena (`ops/paged.py` layout)."""
    return init_paged_cache(cfg.num_hidden_layers, num_pages, page_size,
                            cfg.num_key_value_heads, cfg.hd, batch,
                            kv_cache_dtype=kv_cache_dtype)


# ---------------------------------------------------------------------------
# HF checkpoint -> parameter pytree (the conversion engine for this family;
# reference analog: ggml_convert_low_bit walking nn.Modules, convert.py:643)
# ---------------------------------------------------------------------------

_LAYER_LINEARS = {
    "self_attn.q_proj": "q_proj",
    "self_attn.k_proj": "k_proj",
    "self_attn.v_proj": "v_proj",
    "self_attn.o_proj": "o_proj",
    "mlp.gate_proj": "gate_proj",
    "mlp.up_proj": "up_proj",
    "mlp.down_proj": "down_proj",
}


def _llama_map(acc, name: str, w) -> None:
    """HF llama/mistral/qwen2-style tensor names -> pytree keys."""
    if name in ("model.embed_tokens.weight", "transformer.wte.weight"):
        acc.top["embed_tokens"] = acc.dense(w)
    elif name == "model.norm.weight":
        acc.top["norm"] = acc.dense(w)
    elif name == "model.norm.bias":
        acc.top["norm_bias"] = acc.dense(w)
    elif name == "lm_head.weight":
        acc.top["lm_head"] = acc.linear(name, w)
    elif name == "lm_head.bias":
        acc.top["lm_head_bias"] = acc.dense(w)
    elif name.startswith("model.layers."):
        parts = name.split(".")
        idx = int(parts[2])
        sub = ".".join(parts[3:-1])   # e.g. self_attn.q_proj
        leaf = parts[-1]              # weight | bias
        if sub in _LAYER_LINEARS:
            key = _LAYER_LINEARS[sub]
            if leaf == "weight":
                acc.put(key, idx, acc.linear(name, w))
            else:
                acc.put(f"{key}_bias", idx, acc.dense(w))
        elif sub in ("input_layernorm", "post_attention_layernorm",
                     "pre_feedforward_layernorm",
                     "post_feedforward_layernorm"):
            # biased LayerNorm families (stablelm) route .bias separately
            acc.put(sub if leaf == "weight" else f"{sub}_bias", idx,
                    acc.dense(w))
        # rotary_emb.inv_freq etc. are derived, skip


def convert_hf_params(
    tensors,                      # iterable of (name, np.ndarray)
    cfg: LlamaConfig,
    qtype: Optional[str] = "sym_int4",
    compute_dtype=jnp.bfloat16,
    modules_to_not_convert: Tuple[str, ...] = (),
    imatrix=None,                 # {hf_name: importance[K]} (bigdl_tpu.imatrix)
) -> Dict[str, Any]:
    """Build the parameter pytree from HF-named tensors, quantizing linears.

    qtype=None (or a FLOAT_QTYPE) keeps dense weights in compute_dtype —
    the reference's optimize_model(low_bit=False) / BF16Linear path.
    Weights are converted tensor-by-tensor (host holds one at a time) and
    per-layer results are stacked along a leading L axis for lax.scan.
    Shares the conversion engine in models/convert_base.py with every
    other family (models/families.py).
    """
    from bigdl_tpu.models.convert_base import make_convert

    return make_convert(_llama_map)(
        tensors, cfg, qtype=qtype, compute_dtype=compute_dtype,
        modules_to_not_convert=modules_to_not_convert, imatrix=imatrix)
