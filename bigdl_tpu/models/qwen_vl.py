"""Qwen-VL vision tower: ViT encoder + cross-attention resampler.

TPU-native equivalent of the reference's Qwen-VL support (reference
transformers/models/qwen_vl.py:251-289 `qwen_vl_vision_transformer_forward` /
`qwen_vl_resampler_forward`, and the visual-module conversion hooks at
transformers/convert.py:696-711). The LLM side of Qwen-VL is the qwen1
family adapter (models/families.py) — this module adds the image leg:

- `VisualConfig`: the `config.visual` dict of Qwen-VL-Chat checkpoints.
- `convert_visual_params`: streams `transformer.visual.*` tensors into a
  stacked pytree (resblocks [L, ...] for `lax.scan`). The tower stays
  unquantized (the reference also leaves the ViT out of low-bit
  conversion, convert.py:1071-1080) — it runs once per image, so weight
  bandwidth is irrelevant next to the 48-layer decode loop.
- `encode_images`: jittable pixels -> [N, n_queries, output_dim]
  features. The patch "conv" (stride == kernel) is an unfold + ONE
  [N*grid^2, 3p^2] x [3p^2, width] matmul — MXU-shaped, no conv op.
- `visual_token_index` / `extract_image_paths` / `preprocess_images`:
  the host-side protocol legs. Qwen-VL embeds each image as
  `<img> ...path bytes... <imgpad>*k </img>` spanning exactly n_queries
  tokens between the markers; injection replaces those rows of the
  token-embedding output (reference qwen_vl's QWenModel.forward does
  `hidden_states[i][a+1:b] = images[idx]`).

Injection itself happens inside the jitted prefill: `llama.forward(...,
visual=(vidx, vemb))` does one gather + select after the embed prologue —
data-dependent *values*, static shapes, so the executable is shared with
the text-only path per prompt bucket.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.ops.norms import layer_norm

# CLIP normalization constants (Qwen-VL visual.py image_transform)
CLIP_MEAN = (0.48145466, 0.4578275, 0.40821073)
CLIP_STD = (0.26862954, 0.26130258, 0.27577711)


@dataclasses.dataclass(frozen=True)
class VisualConfig:
    image_size: int = 448
    patch_size: int = 14
    width: int = 1664
    layers: int = 48
    heads: int = 16
    mlp_ratio: float = 4.9231
    output_dim: int = 4096
    n_queries: int = 256
    image_start_id: int = 151857

    @property
    def grid(self) -> int:
        return self.image_size // self.patch_size

    @property
    def mlp_width(self) -> int:
        return int(self.width * self.mlp_ratio)

    @property
    def pool_heads(self) -> int:
        # Resampler(num_heads=output_dim // 128) in Qwen-VL visual.py;
        # floor of 1 keeps tiny test configs valid
        return max(1, self.output_dim // 128)

    @property
    def image_end_id(self) -> int:
        return self.image_start_id + 1

    @property
    def image_pad_id(self) -> int:
        return self.image_start_id + 2

    @classmethod
    def from_hf(cls, visual: Dict[str, Any]) -> "VisualConfig":
        return cls(
            image_size=visual.get("image_size", 448),
            patch_size=visual.get("patch_size", 14),
            width=visual.get("width", 1664),
            layers=visual.get("layers", 48),
            heads=visual.get("heads", 16),
            mlp_ratio=visual.get("mlp_ratio", 4.9231),
            output_dim=visual.get("output_dim", 4096),
            n_queries=visual.get("n_queries", 256),
            image_start_id=visual.get("image_start_id", 151857),
        )


# -- conversion ---------------------------------------------------------------

_BLOCK_KEYS = (
    "ln_1.weight", "ln_1.bias", "ln_2.weight", "ln_2.bias",
    "attn.in_proj.weight", "attn.in_proj.bias",
    "attn.out_proj.weight", "attn.out_proj.bias",
    "mlp.c_fc.weight", "mlp.c_fc.bias",
    "mlp.c_proj.weight", "mlp.c_proj.bias",
)


def convert_visual_params(tensors, vcfg: VisualConfig,
                          compute_dtype=jnp.bfloat16) -> Dict[str, Any]:
    """`transformer.visual.*` tensors -> pytree (resblocks stacked [L, ...]).

    Linear weights are stored transposed ([in, out]) so every matmul is a
    plain `x @ w`. Accepts the full checkpoint stream; non-visual names
    are ignored.
    """
    L = vcfg.layers
    blocks: Dict[str, List[Optional[np.ndarray]]] = {
        k: [None] * L for k in _BLOCK_KEYS}
    top: Dict[str, Any] = {}

    def dense(w, transpose=False):
        a = np.asarray(w, np.float32)
        if transpose:
            a = a.T
        return jnp.asarray(a).astype(compute_dtype)

    for name, w in tensors:
        if not name.startswith("transformer.visual."):
            continue
        sub = name[len("transformer.visual."):]
        if sub == "conv1.weight":
            # [width, 3, p, p] -> [3*p*p, width] unfold-matmul operand
            a = np.asarray(w, np.float32)
            top["patch_proj"] = jnp.asarray(
                a.reshape(a.shape[0], -1).T).astype(compute_dtype)
        elif sub == "positional_embedding":
            top["pos_embed"] = dense(w)
        elif sub == "proj":
            top["proj"] = dense(w)          # [D2, D2], applied as x @ proj
        elif sub.startswith(("ln_pre.", "ln_post.")):
            top[sub.replace(".", "_")] = dense(w)
        elif sub.startswith("attn_pool."):
            k = sub[len("attn_pool."):]
            if k in ("kv_proj.weight", "attn.in_proj_weight",
                     "attn.out_proj.weight"):
                top["pool_" + k.replace(".", "_")] = dense(w, transpose=True)
            else:   # query, pos_embed, ln_q/ln_kv, biases
                top["pool_" + k.replace(".", "_")] = dense(w)
        elif sub.startswith("transformer.resblocks."):
            rest = sub[len("transformer.resblocks."):]
            idx_s, key = rest.split(".", 1)
            if key in blocks:
                transpose = key.endswith("weight") and (
                    "in_proj" in key or "out_proj" in key
                    or "c_fc" in key or "c_proj" in key)
                blocks[key][int(idx_s)] = np.asarray(w, np.float32).T \
                    if transpose else np.asarray(w, np.float32)

    missing = [k for k, v in blocks.items() if any(x is None for x in v)]
    if missing or "patch_proj" not in top:
        raise ValueError(
            f"incomplete Qwen-VL visual tower in checkpoint: missing "
            f"{missing or ['conv1.weight']}")
    top["resblocks"] = {
        k.replace(".", "_"): jnp.asarray(np.stack(v)).astype(compute_dtype)
        for k, v in blocks.items()}
    return top


# -- forward ------------------------------------------------------------------


def _ln(x, w, b):
    # norm_layer = partial(nn.LayerNorm, eps=1e-6) in Qwen-VL visual.py
    return layer_norm(x, w, b, eps=1e-6)


def _interp_pos(table: jax.Array, tgt_len: int) -> jax.Array:
    """get_abs_pos (reference qwen_vl.py:51-69): bicubic-resize a square
    [S*S, C] position table to [T*T, C] when the grids differ."""
    src = int(round(float(np.sqrt(table.shape[0]))))
    tgt = int(round(float(np.sqrt(tgt_len))))
    if src == tgt:
        return table
    grid = table.reshape(src, src, -1).astype(jnp.float32)
    out = jax.image.resize(grid, (tgt, tgt, grid.shape[-1]),
                           method="bicubic")
    return out.reshape(tgt * tgt, -1).astype(table.dtype)


def _mha(q, k, v, heads: int):
    """Bidirectional multi-head attention. q [B,Lq,D], k/v [B,Lk,D]."""
    b, lq, d = q.shape
    lk = k.shape[1]
    hd = d // heads
    qh = q.reshape(b, lq, heads, hd).astype(jnp.bfloat16)
    kh = k.reshape(b, lk, heads, hd).astype(jnp.bfloat16)
    vh = v.reshape(b, lk, heads, hd).astype(jnp.bfloat16)
    scores = jnp.einsum("bqhd,bkhd->bhqk", qh, kh,
                        preferred_element_type=jnp.float32) * (hd ** -0.5)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(jnp.bfloat16), vh,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, lq, d).astype(q.dtype)


def _resblock(x, lp, heads: int):
    """Pre-LN ViT block (Qwen-VL visual.py VisualAttentionBlock).

    The fused in_proj uses the Megatron-style PER-HEAD layout: output
    viewed as [..., heads, 3*hd] and split into q/k/v within each head's
    block — not [q_all; k_all; v_all]."""
    b, l, d = x.shape
    hd = d // heads
    h = _ln(x, lp["ln_1_weight"], lp["ln_1_bias"])
    qkv = h @ lp["attn_in_proj_weight"] + lp["attn_in_proj_bias"]
    qkv = qkv.reshape(b, l, heads, 3 * hd)
    q = qkv[..., :hd].reshape(b, l, d)
    k = qkv[..., hd:2 * hd].reshape(b, l, d)
    v = qkv[..., 2 * hd:].reshape(b, l, d)
    a = _mha(q, k, v, heads)
    x = x + (a @ lp["attn_out_proj_weight"] + lp["attn_out_proj_bias"])
    h = _ln(x, lp["ln_2_weight"], lp["ln_2_bias"])
    h = jax.nn.gelu(h @ lp["mlp_c_fc_weight"] + lp["mlp_c_fc_bias"],
                    approximate=False)
    return x + (h @ lp["mlp_c_proj_weight"] + lp["mlp_c_proj_bias"])


def encode_images(vparams: Dict[str, Any], vcfg: VisualConfig,
                  pixels: jax.Array,            # [N, 3, H, W] f32 normalized
                  compute_dtype=jnp.bfloat16) -> jax.Array:
    """Pixels -> [N, n_queries, output_dim] visual features (jittable).

    Mirrors the reference vision forward (qwen_vl.py:268-289): patchify,
    +abs pos, ln_pre, 48 resblocks, resampler attn_pool, ln_post, proj.
    """
    n, c, hh, ww = pixels.shape
    p = vcfg.patch_size
    gh, gw = hh // p, ww // p
    # unfold: [N, 3, gh, p, gw, p] -> [N, gh*gw, 3*p*p]; channel-major
    # patch layout matches conv1.weight.reshape(width, -1)
    patches = pixels.reshape(n, c, gh, p, gw, p)
    patches = patches.transpose(0, 2, 4, 1, 3, 5).reshape(n, gh * gw,
                                                          c * p * p)
    x = patches.astype(compute_dtype) @ vparams["patch_proj"]

    x = x + _interp_pos(vparams["pos_embed"], x.shape[1]).astype(x.dtype)
    x = _ln(x, vparams["ln_pre_weight"], vparams["ln_pre_bias"])

    x, _ = lax.scan(
        lambda h, lp: (_resblock(h, lp, vcfg.heads), None),
        x, vparams["resblocks"])

    # resampler (qwen_vl.py:251-266): n_queries learned queries
    # cross-attend the patch sequence; both sides carry sincos positions
    kv = x @ vparams["pool_kv_proj_weight"]                  # [N, L, D2]
    kv = _ln(kv, vparams["pool_ln_kv_weight"], vparams["pool_ln_kv_bias"])
    q = _ln(vparams["pool_query"], vparams["pool_ln_q_weight"],
            vparams["pool_ln_q_bias"])                       # [nq, D2]
    pos_q = vparams["pool_pos_embed"]                        # [nq, D2]
    pos_k = _interp_pos(vparams["pool_pos_embed"], kv.shape[1])

    d2 = q.shape[-1]
    w_q, w_k, w_v = jnp.split(vparams["pool_attn_in_proj_weight"], 3,
                              axis=1)                        # [D2, D2] each
    b_q, b_k, b_v = jnp.split(vparams["pool_attn_in_proj_bias"], 3)
    qq = (q + pos_q)[None].astype(compute_dtype) @ w_q + b_q  # [1, nq, D2]
    kk = (kv + pos_k[None].astype(kv.dtype)) @ w_k + b_k
    vv = kv @ w_v + b_v
    out = _mha(jnp.broadcast_to(qq, (n,) + qq.shape[1:]), kk, vv,
               vcfg.pool_heads)
    out = out @ vparams["pool_attn_out_proj_weight"] \
        + vparams["pool_attn_out_proj_bias"]

    out = _ln(out, vparams["ln_post_weight"], vparams["ln_post_bias"])
    return out @ vparams["proj"]


# -- host-side protocol -------------------------------------------------------


def visual_token_index(input_ids: np.ndarray,
                       vcfg: VisualConfig) -> Tuple[np.ndarray, int]:
    """[B, S] ids -> (vidx [B, S] int32, n_images).

    vidx is 0 on text rows; row j of image i carries i*n_queries + j + 1.
    Image i is the i-th `<img>...</img>` span in batch-major order, the
    order `extract_image_paths` / caller-supplied image lists use.
    """
    ids = np.asarray(input_ids)
    vidx = np.zeros(ids.shape, np.int32)
    count = 0
    nq = vcfg.n_queries
    for b in range(ids.shape[0]):
        starts = np.where(ids[b] == vcfg.image_start_id)[0]
        ends = np.where(ids[b] == vcfg.image_end_id)[0]
        if len(starts) != len(ends):
            raise ValueError(
                f"unbalanced image markers in row {b}: {len(starts)} "
                f"<img> vs {len(ends)} </img>")
        for a, e in zip(starts, ends):
            if e - a - 1 != nq:
                raise ValueError(
                    f"image span at row {b} pos {a} holds {e - a - 1} "
                    f"tokens; expected n_queries={nq}")
            vidx[b, a + 1:e] = count * nq + np.arange(nq) + 1
            count += 1
    return vidx, count


def extract_image_paths(input_ids: np.ndarray,
                        vcfg: VisualConfig) -> List[str]:
    """Decode the in-band image paths/URLs the Qwen-VL tokenizer embeds
    between the markers (reference qwen_vl's QWenModel.forward: bytes up
    to the first <imgpad> token)."""
    ids = np.asarray(input_ids)
    out: List[str] = []
    for b in range(ids.shape[0]):
        starts = np.where(ids[b] == vcfg.image_start_id)[0]
        ends = np.where(ids[b] == vcfg.image_end_id)[0]
        for a, e in zip(starts, ends):
            span = ids[b, a + 1:e].tolist()
            if vcfg.image_pad_id in span:
                span = span[:span.index(vcfg.image_pad_id)]
            out.append(bytes(span).decode("utf-8"))
    return out


def preprocess_images(images: Sequence[Any],
                      vcfg: VisualConfig) -> np.ndarray:
    """paths / PIL images / [H,W,3] uint8 arrays -> [N,3,S,S] f32 CLIP-
    normalized pixels (Qwen-VL visual.py image_transform)."""
    from PIL import Image

    s = vcfg.image_size
    mean = np.asarray(CLIP_MEAN, np.float32).reshape(3, 1, 1)
    std = np.asarray(CLIP_STD, np.float32).reshape(3, 1, 1)
    out = []
    for im in images:
        if isinstance(im, str):
            im = Image.open(im)
        if isinstance(im, Image.Image):
            im = np.asarray(
                im.convert("RGB").resize((s, s), Image.BICUBIC))
        arr = np.asarray(im)
        if arr.ndim == 3 and arr.shape[-1] == 3:    # HWC -> CHW
            arr = arr.transpose(2, 0, 1)
        if arr.shape[1] != s or arr.shape[2] != s:
            raise ValueError(
                f"image array must be {s}x{s} (got {arr.shape}); pass a "
                "path or PIL image for automatic resizing")
        arr = arr.astype(np.float32)
        if arr.max() > 1.5:                         # uint8 range
            arr = arr / 255.0
        out.append((arr - mean) / std)
    return np.stack(out)
