"""BERT encoder: quantized sentence/token embeddings.

The reference optimizes bert through merged-QKV + SDP forwards
(reference transformers/models/bert.py:42-147) and exposes it to users as
the embedding backend of its langchain integration
(`TransformersEmbeddings`, langchain/embeddings/bigdlllm.py). TPU-native
counterpart: a functional post-LN encoder over stacked layer params —
bidirectional attention with a key-padding mask (sdp_attention is causal
by construction, so bert computes its masked attention inline), quantized
linears everywhere, mean/CLS pooling for sentence embeddings.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.ops.matmul import linear
from bigdl_tpu.ops.norms import layer_norm


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12

    @property
    def hd(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def from_hf(cls, hf: Dict[str, Any]) -> "BertConfig":
        return cls(
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            num_hidden_layers=hf["num_hidden_layers"],
            num_attention_heads=hf["num_attention_heads"],
            intermediate_size=hf["intermediate_size"],
            max_position_embeddings=hf.get("max_position_embeddings", 512),
            type_vocab_size=hf.get("type_vocab_size", 2),
            layer_norm_eps=hf.get("layer_norm_eps", 1e-12),
        )


def _masked_attention(q, k, v, key_mask, scale):
    """Bidirectional SDP with a key-padding mask. q/k/v [B, S, H, hd]."""
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.bfloat16),
                        k.astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32) * scale
    # finite mask value: an all-pad row (every key False, seen in ragged
    # batches) must soften to uniform probs, not NaN through -inf - -inf
    scores = jnp.where(key_mask[:, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(jnp.bfloat16),
                     v.astype(jnp.bfloat16),
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def _encoder_layer(x, lp, cfg: BertConfig, key_mask):
    """Post-LN block (original-BERT residual order)."""
    b, s, _ = x.shape
    h, hd = cfg.num_attention_heads, cfg.hd
    q = linear(x, lp["q_proj"], lp["q_proj_bias"]).reshape(b, s, h, hd)
    k = linear(x, lp["k_proj"], lp["k_proj_bias"]).reshape(b, s, h, hd)
    v = linear(x, lp["v_proj"], lp["v_proj_bias"]).reshape(b, s, h, hd)
    attn = _masked_attention(q, k, v, key_mask, hd ** -0.5)
    attn = linear(attn.reshape(b, s, h * hd), lp["o_proj"],
                  lp["o_proj_bias"])
    x = layer_norm(x + attn, lp["attn_norm"], lp["attn_norm_bias"],
                   cfg.layer_norm_eps)
    inner = jax.nn.gelu(linear(x, lp["fc1"], lp["fc1_bias"]),
                        approximate=False)
    out = linear(inner, lp["fc2"], lp["fc2_bias"])
    return layer_norm(x + out, lp["out_norm"], lp["out_norm_bias"],
                      cfg.layer_norm_eps)


def forward(
    params: Dict[str, Any],
    cfg: BertConfig,
    input_ids: jax.Array,                 # [B, S] int32
    attention_mask: Optional[jax.Array] = None,   # [B, S] 1=real
    token_type_ids: Optional[jax.Array] = None,
    compute_dtype=jnp.bfloat16,
) -> Tuple[jax.Array, jax.Array]:
    """Returns (last_hidden [B, S, D], pooled CLS [B, D])."""
    b, s = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((b, s), jnp.int32)
    if token_type_ids is None:
        token_type_ids = jnp.zeros((b, s), jnp.int32)
    key_mask = attention_mask.astype(bool)

    emb = params["word_embeddings"][input_ids]
    emb = emb + params["position_embeddings"][jnp.arange(s)][None]
    emb = emb + params["token_type_embeddings"][token_type_ids]
    x = layer_norm(emb.astype(compute_dtype), params["embed_norm"],
                   params["embed_norm_bias"], cfg.layer_norm_eps)

    x, _ = lax.scan(
        lambda c, lp: (_encoder_layer(c, lp, cfg, key_mask), None),
        x, params["layers"])

    pooled = x[:, 0, :]
    if "pooler" in params:
        pooled = jnp.tanh(linear(pooled, params["pooler"],
                                 params["pooler_bias"]))
    return x, pooled


def mean_pool(last_hidden: jax.Array, attention_mask: jax.Array) -> jax.Array:
    """Masked mean over tokens — the standard sentence-embedding pool."""
    m = attention_mask.astype(jnp.float32)[..., None]
    return (jnp.sum(last_hidden.astype(jnp.float32) * m, axis=1)
            / jnp.maximum(jnp.sum(m, axis=1), 1e-9))


# -- task heads (the bert-based Auto classes, reference transformers/
#    model.py:704-725: SequenceClassification / TokenClassification /
#    QuestionAnswering / MaskedLM / NextSentencePrediction / MultipleChoice)


def sequence_logits(params, cfg, input_ids, attention_mask=None,
                    token_type_ids=None, compute_dtype=jnp.bfloat16):
    """[B, num_labels] classification logits (pooled CLS -> classifier)."""
    _, pooled = forward(params, cfg, input_ids, attention_mask,
                        token_type_ids, compute_dtype)
    return linear(pooled, params["head_classifier"],
                  params.get("head_classifier_bias")).astype(jnp.float32)


def token_logits(params, cfg, input_ids, attention_mask=None,
                 token_type_ids=None, compute_dtype=jnp.bfloat16):
    """[B, S, num_labels] per-token classification logits."""
    hidden, _ = forward(params, cfg, input_ids, attention_mask,
                        token_type_ids, compute_dtype)
    return linear(hidden, params["head_classifier"],
                  params.get("head_classifier_bias")).astype(jnp.float32)


def qa_logits(params, cfg, input_ids, attention_mask=None,
              token_type_ids=None, compute_dtype=jnp.bfloat16):
    """(start_logits [B, S], end_logits [B, S])."""
    hidden, _ = forward(params, cfg, input_ids, attention_mask,
                        token_type_ids, compute_dtype)
    se = linear(hidden, params["head_qa"],
                params.get("head_qa_bias")).astype(jnp.float32)
    return se[..., 0], se[..., 1]


def mlm_logits(params, cfg, input_ids, attention_mask=None,
               token_type_ids=None, compute_dtype=jnp.bfloat16):
    """[B, S, V] masked-LM logits (transform + LN + tied decoder)."""
    hidden, _ = forward(params, cfg, input_ids, attention_mask,
                        token_type_ids, compute_dtype)
    h = jax.nn.gelu(linear(hidden, params["mlm_transform"],
                           params.get("mlm_transform_bias")),
                    approximate=False)
    h = layer_norm(h, params["mlm_norm"], params.get("mlm_norm_bias"),
                   cfg.layer_norm_eps)
    dec = params.get("mlm_decoder")
    if dec is None:                          # tied to word embeddings
        logits = jnp.dot(h, params["word_embeddings"].T.astype(h.dtype),
                         preferred_element_type=jnp.float32)
    else:
        logits = linear(h, dec)
    logits = logits.astype(jnp.float32)
    if "mlm_decoder_bias" in params:
        logits = logits + params["mlm_decoder_bias"].astype(jnp.float32)
    return logits


def nsp_logits(params, cfg, input_ids, attention_mask=None,
               token_type_ids=None, compute_dtype=jnp.bfloat16):
    """[B, 2] next-sentence-prediction logits."""
    _, pooled = forward(params, cfg, input_ids, attention_mask,
                        token_type_ids, compute_dtype)
    return linear(pooled, params["head_nsp"],
                  params.get("head_nsp_bias")).astype(jnp.float32)


# -- conversion (shared Acc engine, models/convert_base.py) ------------------

_LAYER_MAP = {
    "attention.self.query": ("q_proj", True),
    "attention.self.key": ("k_proj", True),
    "attention.self.value": ("v_proj", True),
    "attention.output.dense": ("o_proj", True),
    "attention.output.LayerNorm": ("attn_norm", False),
    "intermediate.dense": ("fc1", True),
    "output.dense": ("fc2", True),
    "output.LayerNorm": ("out_norm", False),
}

# embeddings/norm-like tensors stored as-is in the top-level tree
_TOP_DENSE = {
    "embeddings.word_embeddings.weight": "word_embeddings",
    "embeddings.position_embeddings.weight": "position_embeddings",
    "embeddings.token_type_embeddings.weight": "token_type_embeddings",
    "embeddings.LayerNorm.weight": "embed_norm",
    "embeddings.LayerNorm.bias": "embed_norm_bias",
    "pooler.dense.bias": "pooler_bias",
    "classifier.bias": "head_classifier_bias",
    "qa_outputs.bias": "head_qa_bias",
    "cls.predictions.transform.dense.bias": "mlm_transform_bias",
    "cls.predictions.transform.LayerNorm.weight": "mlm_norm",
    "cls.predictions.transform.LayerNorm.bias": "mlm_norm_bias",
    "cls.predictions.bias": "mlm_decoder_bias",
    "cls.predictions.decoder.bias": "mlm_decoder_bias",
    "cls.seq_relationship.bias": "head_nsp_bias",
}

# task heads kept dense-transposed (tiny, accuracy-critical); quantizable
# projections go through acc.linear
_TOP_LINEAR = {
    "pooler.dense.weight": ("pooler", True),
    "cls.predictions.transform.dense.weight": ("mlm_transform", True),
    "cls.predictions.decoder.weight": ("mlm_decoder", True),
    "classifier.weight": ("head_classifier", False),
    "qa_outputs.weight": ("head_qa", False),
    "cls.seq_relationship.weight": ("head_nsp", False),
}


def _bert_map(acc, name: str, w) -> None:
    n = name[len("bert."):] if name.startswith("bert.") else name
    if n in _TOP_DENSE:
        acc.top[_TOP_DENSE[n]] = acc.dense(w)
    elif n in _TOP_LINEAR:
        key, quantize = _TOP_LINEAR[n]
        acc.top[key] = (acc.linear(name, w) if quantize
                        else jnp.asarray(np.asarray(w)).T.astype(
                            acc.compute_dtype))
    elif n.startswith("encoder.layer."):
        parts = n.split(".")
        idx = int(parts[2])
        sub = ".".join(parts[3:-1])
        leaf = parts[-1]
        hit = _LAYER_MAP.get(sub)
        if hit is None:
            return
        key, is_lin = hit
        if is_lin and leaf == "weight":
            acc.put(key, idx, acc.linear(name, w))
        elif is_lin:
            acc.put(f"{key}_bias", idx, acc.dense(w))
        else:
            acc.put(key if leaf == "weight" else f"{key}_bias", idx,
                    acc.dense(w))


def convert_hf_params(
    tensors,
    cfg: BertConfig,
    qtype: Optional[str] = "sym_int4",
    compute_dtype=jnp.bfloat16,
    modules_to_not_convert: Tuple[str, ...] = (),
    imatrix=None,
) -> Dict[str, Any]:
    from bigdl_tpu.models.convert_base import make_convert

    return make_convert(_bert_map, lm_head_required=False)(
        tensors, cfg, qtype=qtype, compute_dtype=compute_dtype,
        modules_to_not_convert=modules_to_not_convert, imatrix=imatrix)
