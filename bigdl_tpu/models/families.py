"""Family adapters: config deltas + checkpoint converters over the
generalized decoder (models/llama.py).

The reference ships a 400-line monkey-patched forward per family
(transformers/models/{gemma,phi,gptneox,bloom,falcon,starcoder2,baichuan,
chatglm2}.py — SURVEY.md §2, 30 files / 12.4k LoC). Here each family is a
LlamaConfig delta plus an HF-tensor-name mapping; the model body is the one
scan-based decoder. Fused QKV layouts (gptneox/bloom per-head interleave,
falcon MQA block, baichuan W_pack, chatglm2 grouped) are de-interleaved at
conversion time so the runtime never special-cases them.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.models.llama import LlamaConfig
from bigdl_tpu.models import llama as llama_mod
# NOTE: bigdl_tpu.models.registry is imported lazily inside register_all()
# to keep `import bigdl_tpu.models.families` free of an import cycle
# (registry's builtin registration imports this module).
from bigdl_tpu.models.convert_base import (Acc as _Acc, make_convert as
    _make_convert, split_rows as _split_rows, deinterleave_qkv as
    _deinterleave_qkv, layer_idx as _layer_idx)


# ---------------------------------------------------------------------------
# Gemma — llama-shaped with scaled embeddings and (1+w) RMSNorm
# (reference transformers/models/gemma.py)
# ---------------------------------------------------------------------------

def _gemma_cfg(hf: Dict[str, Any]) -> LlamaConfig:
    import dataclasses

    base = LlamaConfig.from_hf(hf)
    return dataclasses.replace(
        base,
        head_dim=hf.get("head_dim", 256),
        rms_weight_offset=1.0,
        hidden_act="gelu_tanh",
        embed_scale=math.sqrt(hf["hidden_size"]),
        tie_word_embeddings=hf.get("tie_word_embeddings", True),
    )


def _gemma2_cfg(hf: Dict[str, Any]) -> LlamaConfig:
    """Gemma2 (reference transformers/models/gemma2 path): gemma plus
    sandwich norms, attention/final soft caps, scaled queries, and a
    sliding window on even layers."""
    import dataclasses

    return dataclasses.replace(
        _gemma_cfg(hf),
        sandwich_norms=True,
        attn_soft_cap=hf.get("attn_logit_softcapping", 50.0),
        logits_soft_cap=hf.get("final_logit_softcapping", 30.0),
        query_pre_attn_scalar=float(hf.get("query_pre_attn_scalar", 256)),
        sliding_window=hf.get("sliding_window", 4096),
        alt_sliding_window=True,
    )


# ---------------------------------------------------------------------------
# Phi (phi-1/1.5/2) — parallel residual, shared LN, dense gelu MLP,
# partial rotary, biases everywhere (reference models/phixtral.py kin)
# ---------------------------------------------------------------------------

def _phi_cfg(hf: Dict[str, Any]) -> LlamaConfig:
    hd = hf["hidden_size"] // hf["num_attention_heads"]
    return LlamaConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_hidden_layers=hf["num_hidden_layers"],
        num_attention_heads=hf["num_attention_heads"],
        num_key_value_heads=hf.get("num_key_value_heads") or
        hf["num_attention_heads"],
        rms_norm_eps=hf.get("layer_norm_eps", 1e-5),
        rope_theta=hf.get("rope_theta", 10000.0),
        max_position_embeddings=hf.get("max_position_embeddings", 2048),
        tie_word_embeddings=hf.get("tie_word_embeddings", False),
        attention_bias=True,
        norm_type="layernorm",
        parallel_residual=True,
        shared_input_norm=True,
        mlp_gated=False,
        hidden_act="gelu_tanh",
        rotary_dim=int(hf.get("partial_rotary_factor", 0.5) * hd),
        lm_head_bias=True,
    )


def _phi_map(acc: _Acc, name: str, w) -> None:
    if name == "model.embed_tokens.weight":
        acc.top["embed_tokens"] = acc.dense(w)
    elif name == "model.final_layernorm.weight":
        acc.top["norm"] = acc.dense(w)
    elif name == "model.final_layernorm.bias":
        acc.top["norm_bias"] = acc.dense(w)
    elif name == "lm_head.weight":
        acc.top["lm_head"] = acc.linear(name, w)
    elif name == "lm_head.bias":
        acc.top["lm_head_bias"] = acc.dense(w)
    else:
        hit = _layer_idx(name, "model.layers.")
        if hit is None:
            return
        idx, sub = hit
        m = {
            "self_attn.q_proj.weight": ("q_proj", "linear"),
            "self_attn.k_proj.weight": ("k_proj", "linear"),
            "self_attn.v_proj.weight": ("v_proj", "linear"),
            "self_attn.dense.weight": ("o_proj", "linear"),
            "mlp.fc1.weight": ("up_proj", "linear"),
            "mlp.fc2.weight": ("down_proj", "linear"),
            "self_attn.q_proj.bias": ("q_proj_bias", "dense"),
            "self_attn.k_proj.bias": ("k_proj_bias", "dense"),
            "self_attn.v_proj.bias": ("v_proj_bias", "dense"),
            "self_attn.dense.bias": ("o_proj_bias", "dense"),
            "mlp.fc1.bias": ("up_proj_bias", "dense"),
            "mlp.fc2.bias": ("down_proj_bias", "dense"),
            "input_layernorm.weight": ("input_layernorm", "dense"),
            "input_layernorm.bias": ("input_layernorm_bias", "dense"),
        }.get(sub)
        if m:
            key, kind = m
            acc.put(key, idx,
                    acc.linear(name, w) if kind == "linear" else acc.dense(w))


# ---------------------------------------------------------------------------
# GPT-NeoX — parallel residual (two LNs), fused per-head QKV, partial rotary
# (reference transformers/models/gptneox.py)
# ---------------------------------------------------------------------------

def _gptneox_cfg(hf: Dict[str, Any]) -> LlamaConfig:
    hd = hf["hidden_size"] // hf["num_attention_heads"]
    return LlamaConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_hidden_layers=hf["num_hidden_layers"],
        num_attention_heads=hf["num_attention_heads"],
        num_key_value_heads=hf["num_attention_heads"],
        rms_norm_eps=hf.get("layer_norm_eps", 1e-5),
        rope_theta=hf.get("rotary_emb_base", hf.get("rope_theta", 10000.0)),
        max_position_embeddings=hf.get("max_position_embeddings", 2048),
        tie_word_embeddings=hf.get("tie_word_embeddings", False),
        attention_bias=True,
        norm_type="layernorm",
        parallel_residual=hf.get("use_parallel_residual", True),
        mlp_gated=False,
        hidden_act="gelu",
        rotary_dim=int(hf.get("rotary_pct", 0.25) * hd),
    )


def _gptneox_map(acc: _Acc, name: str, w) -> None:
    cfg = acc.cfg
    h, hd = cfg.num_attention_heads, cfg.hd
    if name == "gpt_neox.embed_in.weight":
        acc.top["embed_tokens"] = acc.dense(w)
    elif name == "gpt_neox.final_layer_norm.weight":
        acc.top["norm"] = acc.dense(w)
    elif name == "gpt_neox.final_layer_norm.bias":
        acc.top["norm_bias"] = acc.dense(w)
    elif name == "embed_out.weight":
        acc.top["lm_head"] = acc.linear(name, w)
    else:
        hit = _layer_idx(name, "gpt_neox.layers.")
        if hit is None:
            return
        idx, sub = hit
        if sub == "attention.query_key_value.weight":
            q, k, v = _deinterleave_qkv(w, h, hd)
            # "#<slot>" marks the logical projection inside a fused tensor
            # (drives low_bit_policy and imatrix_lookup fallback)
            acc.put("q_proj", idx, acc.linear(name + "#q_proj", q))
            acc.put("k_proj", idx, acc.linear(name + "#k_proj", k))
            acc.put("v_proj", idx, acc.linear(name + "#v_proj", v))
        elif sub == "attention.query_key_value.bias":
            q, k, v = _deinterleave_qkv(w, h, hd)
            acc.put("q_proj_bias", idx, acc.dense(q))
            acc.put("k_proj_bias", idx, acc.dense(k))
            acc.put("v_proj_bias", idx, acc.dense(v))
        else:
            m = {
                "attention.dense.weight": ("o_proj", "linear"),
                "attention.dense.bias": ("o_proj_bias", "dense"),
                "mlp.dense_h_to_4h.weight": ("up_proj", "linear"),
                "mlp.dense_h_to_4h.bias": ("up_proj_bias", "dense"),
                "mlp.dense_4h_to_h.weight": ("down_proj", "linear"),
                "mlp.dense_4h_to_h.bias": ("down_proj_bias", "dense"),
                "input_layernorm.weight": ("input_layernorm", "dense"),
                "input_layernorm.bias": ("input_layernorm_bias", "dense"),
                "post_attention_layernorm.weight":
                    ("post_attention_layernorm", "dense"),
                "post_attention_layernorm.bias":
                    ("post_attention_layernorm_bias", "dense"),
            }.get(sub)
            if m:
                key, kind = m
                acc.put(key, idx, acc.linear(name, w) if kind == "linear"
                        else acc.dense(w))


# ---------------------------------------------------------------------------
# Bloom — ALiBi, embedding LN, fused per-head QKV, dense gelu MLP
# (reference transformers/models/bloom.py + ggml/model/bloom native engine)
# ---------------------------------------------------------------------------

def _bloom_cfg(hf: Dict[str, Any]) -> LlamaConfig:
    h = hf.get("n_head", hf.get("num_attention_heads"))
    d = hf.get("hidden_size", hf.get("n_embed"))
    return LlamaConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=d,
        # HF bloom is always 4d; GGUF metadata may spell it explicitly
        intermediate_size=hf.get("intermediate_size",
                                 hf.get("n_inner") or 4 * d),
        num_hidden_layers=hf.get("n_layer", hf.get("num_hidden_layers")),
        num_attention_heads=h,
        num_key_value_heads=h,
        rms_norm_eps=hf.get("layer_norm_epsilon", 1e-5),
        tie_word_embeddings=True,
        attention_bias=True,
        norm_type="layernorm",
        mlp_gated=False,
        hidden_act="gelu_tanh",
        use_rope=False,
        use_alibi=True,
        embed_norm=True,
    )


def _bloom_map(acc: _Acc, name: str, w) -> None:
    cfg = acc.cfg
    h, hd = cfg.num_attention_heads, cfg.hd
    if name.startswith("transformer."):
        name_ = name[len("transformer."):]
    else:
        name_ = name
    if name_ == "word_embeddings.weight":
        acc.top["embed_tokens"] = acc.dense(w)
    elif name_ == "word_embeddings_layernorm.weight":
        acc.top["embed_norm"] = acc.dense(w)
    elif name_ == "word_embeddings_layernorm.bias":
        acc.top["embed_norm_bias"] = acc.dense(w)
    elif name_ == "ln_f.weight":
        acc.top["norm"] = acc.dense(w)
    elif name_ == "ln_f.bias":
        acc.top["norm_bias"] = acc.dense(w)
    else:
        hit = _layer_idx(name_, "h.")
        if hit is None:
            return
        idx, sub = hit
        if sub == "self_attention.query_key_value.weight":
            q, k, v = _deinterleave_qkv(w, h, hd)
            # "#<slot>" marks the logical projection inside a fused tensor
            # (drives low_bit_policy and imatrix_lookup fallback)
            acc.put("q_proj", idx, acc.linear(name + "#q_proj", q))
            acc.put("k_proj", idx, acc.linear(name + "#k_proj", k))
            acc.put("v_proj", idx, acc.linear(name + "#v_proj", v))
        elif sub == "self_attention.query_key_value.bias":
            q, k, v = _deinterleave_qkv(w, h, hd)
            acc.put("q_proj_bias", idx, acc.dense(q))
            acc.put("k_proj_bias", idx, acc.dense(k))
            acc.put("v_proj_bias", idx, acc.dense(v))
        else:
            m = {
                "self_attention.dense.weight": ("o_proj", "linear"),
                "self_attention.dense.bias": ("o_proj_bias", "dense"),
                "mlp.dense_h_to_4h.weight": ("up_proj", "linear"),
                "mlp.dense_h_to_4h.bias": ("up_proj_bias", "dense"),
                "mlp.dense_4h_to_h.weight": ("down_proj", "linear"),
                "mlp.dense_4h_to_h.bias": ("down_proj_bias", "dense"),
                "input_layernorm.weight": ("input_layernorm", "dense"),
                "input_layernorm.bias": ("input_layernorm_bias", "dense"),
                "post_attention_layernorm.weight":
                    ("post_attention_layernorm", "dense"),
                "post_attention_layernorm.bias":
                    ("post_attention_layernorm_bias", "dense"),
            }.get(sub)
            if m:
                key, kind = m
                acc.put(key, idx, acc.linear(name, w) if kind == "linear"
                        else acc.dense(w))


# ---------------------------------------------------------------------------
# Falcon (7b-style: multi_query + parallel_attn + single LN)
# (reference transformers/models/falcon.py)
# ---------------------------------------------------------------------------

def _falcon_cfg(hf: Dict[str, Any]) -> LlamaConfig:
    h = hf.get("num_attention_heads", hf.get("n_head"))
    d = hf["hidden_size"]
    if hf.get("new_decoder_architecture"):
        raise NotImplementedError(
            "falcon new_decoder_architecture (40b/180b) conversion not "
            "supported yet; falcon-7b-style checkpoints only")
    hkv = 1 if hf.get("multi_query", True) else h
    return LlamaConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=d,
        intermediate_size=hf.get("intermediate_size",
                                 hf.get("ffn_hidden_size") or 4 * d),
        num_hidden_layers=hf.get("num_hidden_layers", hf.get("n_layer")),
        num_attention_heads=h,
        num_key_value_heads=hkv,
        rms_norm_eps=hf.get("layer_norm_epsilon", 1e-5),
        rope_theta=hf.get("rope_theta", 10000.0),
        max_position_embeddings=hf.get("max_position_embeddings", 2048),
        tie_word_embeddings=hf.get("tie_word_embeddings", True),
        attention_bias=bool(hf.get("bias", False)),
        norm_type="layernorm",
        parallel_residual=bool(hf.get("parallel_attn", True)),
        shared_input_norm=True,
        mlp_gated=False,
        hidden_act="gelu",
    )


def _falcon_map(acc: _Acc, name: str, w) -> None:
    cfg = acc.cfg
    h, hkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.hd
    name_ = name[len("transformer."):] if name.startswith("transformer.") \
        else name
    if name_ == "word_embeddings.weight":
        acc.top["embed_tokens"] = acc.dense(w)
    elif name_ == "ln_f.weight":
        acc.top["norm"] = acc.dense(w)
    elif name_ == "ln_f.bias":
        acc.top["norm_bias"] = acc.dense(w)
    elif name_ == "lm_head.weight":
        acc.top["lm_head"] = acc.linear(name, w)
    else:
        hit = _layer_idx(name_, "h.")
        if hit is None:
            return
        idx, sub = hit
        if sub == "self_attention.query_key_value.weight":
            q, k, v = _split_rows(w, [h * hd, hkv * hd, hkv * hd])
            # "#<slot>" marks the logical projection inside a fused tensor
            # (drives low_bit_policy and imatrix_lookup fallback)
            acc.put("q_proj", idx, acc.linear(name + "#q_proj", q))
            acc.put("k_proj", idx, acc.linear(name + "#k_proj", k))
            acc.put("v_proj", idx, acc.linear(name + "#v_proj", v))
        else:
            m = {
                "self_attention.dense.weight": ("o_proj", "linear"),
                "mlp.dense_h_to_4h.weight": ("up_proj", "linear"),
                "mlp.dense_4h_to_h.weight": ("down_proj", "linear"),
                "input_layernorm.weight": ("input_layernorm", "dense"),
                "input_layernorm.bias": ("input_layernorm_bias", "dense"),
            }.get(sub)
            if m:
                key, kind = m
                acc.put(key, idx, acc.linear(name, w) if kind == "linear"
                        else acc.dense(w))


# ---------------------------------------------------------------------------
# Starcoder2 — LN + dense gelu MLP + GQA + rope
# (reference transformers/models/starcoder2.py)
# ---------------------------------------------------------------------------

def _starcoder2_cfg(hf: Dict[str, Any]) -> LlamaConfig:
    return LlamaConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_hidden_layers=hf["num_hidden_layers"],
        num_attention_heads=hf["num_attention_heads"],
        num_key_value_heads=hf.get("num_key_value_heads", 4),
        rms_norm_eps=hf.get("norm_epsilon", 1e-5),
        rope_theta=hf.get("rope_theta", 100000.0),
        max_position_embeddings=hf.get("max_position_embeddings", 4096),
        tie_word_embeddings=hf.get("tie_word_embeddings", True),
        attention_bias=bool(hf.get("use_bias", True)),
        mlp_bias=bool(hf.get("use_bias", True)),
        sliding_window=hf.get("sliding_window"),
        norm_type="layernorm",
        mlp_gated=False,
        hidden_act="gelu_tanh",
    )


def _starcoder2_map(acc: _Acc, name: str, w) -> None:
    if name == "model.embed_tokens.weight":
        acc.top["embed_tokens"] = acc.dense(w)
    elif name == "model.norm.weight":
        acc.top["norm"] = acc.dense(w)
    elif name == "model.norm.bias":
        acc.top["norm_bias"] = acc.dense(w)
    elif name == "lm_head.weight":
        acc.top["lm_head"] = acc.linear(name, w)
    else:
        hit = _layer_idx(name, "model.layers.")
        if hit is None:
            return
        idx, sub = hit
        table = {
            "self_attn.q_proj": "q_proj", "self_attn.k_proj": "k_proj",
            "self_attn.v_proj": "v_proj", "self_attn.o_proj": "o_proj",
            "mlp.c_fc": "up_proj", "mlp.c_proj": "down_proj",
        }
        base, _, leaf = sub.rpartition(".")
        if base in table:
            key = table[base]
            if leaf == "weight":
                acc.put(key, idx, acc.linear(name, w))
            else:
                acc.put(f"{key}_bias", idx, acc.dense(w))
        elif sub in ("input_layernorm.weight",
                     "post_attention_layernorm.weight"):
            acc.put(sub[:-len(".weight")], idx, acc.dense(w))
        elif sub in ("input_layernorm.bias",
                     "post_attention_layernorm.bias"):
            acc.put(sub.replace(".bias", "_bias"), idx, acc.dense(w))


# ---------------------------------------------------------------------------
# Baichuan (7B rope / 13B alibi, W_pack fused QKV, baichuan2 NormHead)
# (reference transformers/models/baichuan.py + baichuan2)
# ---------------------------------------------------------------------------

def _baichuan_cfg(hf: Dict[str, Any]) -> LlamaConfig:
    import dataclasses

    base = LlamaConfig.from_hf(hf)
    # 13B has no rope: HF config carries no explicit flag; the 13B shape
    # (40 heads / hidden 5120) is the discriminator the reference also
    # keys on (convert.py picks baichuan_13b forwards by hidden size)
    if hf["hidden_size"] >= 5120:
        base = dataclasses.replace(base, use_rope=False, use_alibi=True)
    return base


def _baichuan_map(acc: _Acc, name: str, w) -> None:
    d = acc.cfg.hidden_size
    if name == "model.embed_tokens.weight":
        acc.top["embed_tokens"] = acc.dense(w)
    elif name == "model.norm.weight":
        acc.top["norm"] = acc.dense(w)
    elif name == "lm_head.weight":
        if acc.cfg.vocab_size > 100000:   # baichuan2 NormHead
            wn = np.asarray(w, np.float32)
            wn = wn / (np.linalg.norm(wn, axis=-1, keepdims=True) + 1e-12)
            w = wn
        acc.top["lm_head"] = acc.linear(name, w)
    else:
        hit = _layer_idx(name, "model.layers.")
        if hit is None:
            return
        idx, sub = hit
        if sub == "self_attn.W_pack.weight":
            q, k, v = _split_rows(w, [d, d, d])
            # "#<slot>" marks the logical projection inside a fused tensor
            # (drives low_bit_policy and imatrix_lookup fallback)
            acc.put("q_proj", idx, acc.linear(name + "#q_proj", q))
            acc.put("k_proj", idx, acc.linear(name + "#k_proj", k))
            acc.put("v_proj", idx, acc.linear(name + "#v_proj", v))
        else:
            m = {
                "self_attn.o_proj.weight": "o_proj",
                "mlp.gate_proj.weight": "gate_proj",
                "mlp.up_proj.weight": "up_proj",
                "mlp.down_proj.weight": "down_proj",
                "input_layernorm.weight": "input_layernorm",
                "post_attention_layernorm.weight": "post_attention_layernorm",
            }.get(sub)
            if m:
                is_lin = m.endswith("_proj")
                acc.put(m, idx,
                        acc.linear(name, w) if is_lin else acc.dense(w))


# ---------------------------------------------------------------------------
# ChatGLM2/3 — RMSNorm, grouped fused QKV+bias, swiglu fused gate|up,
# interleaved half-dim rotary (reference transformers/models/chatglm2.py)
# ---------------------------------------------------------------------------

def _chatglm2_cfg(hf: Dict[str, Any]) -> LlamaConfig:
    h = hf["num_attention_heads"]
    d = hf["hidden_size"]
    hkv = (hf.get("multi_query_group_num", h)
           if hf.get("multi_query_attention") else h)
    return LlamaConfig(
        vocab_size=hf.get("padded_vocab_size", hf.get("vocab_size", 65024)),
        hidden_size=d,
        intermediate_size=hf["ffn_hidden_size"],
        num_hidden_layers=hf["num_layers"],
        num_attention_heads=h,
        num_key_value_heads=hkv,
        rms_norm_eps=hf.get("layernorm_epsilon", 1e-5),
        rope_theta=10000.0 * hf.get("rope_ratio", 1.0),
        max_position_embeddings=hf.get("seq_length", 32768),
        tie_word_embeddings=False,
        attention_bias=bool(hf.get("add_qkv_bias", True)),
        norm_type="rmsnorm" if hf.get("rmsnorm", True) else "layernorm",
        hidden_act="silu",
        mlp_gated=True,
        rope_interleaved=True,
        rotary_dim=(d // h) // 2,
    )


def _chatglm2_map(acc: _Acc, name: str, w) -> None:
    cfg = acc.cfg
    h, hkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.hd
    ff = cfg.intermediate_size
    if name == "transformer.embedding.word_embeddings.weight":
        acc.top["embed_tokens"] = acc.dense(w)
    elif name == "transformer.encoder.final_layernorm.weight":
        acc.top["norm"] = acc.dense(w)
    elif name == "transformer.output_layer.weight":
        acc.top["lm_head"] = acc.linear(name, w)
    else:
        hit = _layer_idx(name, "transformer.encoder.layers.")
        if hit is None:
            return
        idx, sub = hit
        if sub == "self_attention.query_key_value.weight":
            q, k, v = _split_rows(w, [h * hd, hkv * hd, hkv * hd])
            # "#<slot>" marks the logical projection inside a fused tensor
            # (drives low_bit_policy and imatrix_lookup fallback)
            acc.put("q_proj", idx, acc.linear(name + "#q_proj", q))
            acc.put("k_proj", idx, acc.linear(name + "#k_proj", k))
            acc.put("v_proj", idx, acc.linear(name + "#v_proj", v))
        elif sub == "self_attention.query_key_value.bias":
            q, k, v = _split_rows(w, [h * hd, hkv * hd, hkv * hd])
            acc.put("q_proj_bias", idx, acc.dense(q))
            acc.put("k_proj_bias", idx, acc.dense(k))
            acc.put("v_proj_bias", idx, acc.dense(v))
        elif sub == "mlp.dense_h_to_4h.weight":
            gate, up = _split_rows(w, [ff, ff])
            acc.put("gate_proj", idx, acc.linear(name + "#gate_proj", gate))
            acc.put("up_proj", idx, acc.linear(name + "#up_proj", up))
        else:
            m = {
                "self_attention.dense.weight": "o_proj",
                "mlp.dense_4h_to_h.weight": "down_proj",
                "input_layernorm.weight": "input_layernorm",
                "post_attention_layernorm.weight": "post_attention_layernorm",
            }.get(sub)
            if m:
                is_lin = m in ("o_proj", "down_proj")
                acc.put(m, idx,
                        acc.linear(name, w) if is_lin else acc.dense(w))


# ---------------------------------------------------------------------------
# MPT — ALiBi, LayerNorm (usually bias-free), fused plain-thirds Wqkv
# (reference transformers/models/mpt.py)
# ---------------------------------------------------------------------------

def _mpt_cfg(hf: Dict[str, Any]) -> LlamaConfig:
    d = hf["d_model"]
    return LlamaConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=d,
        intermediate_size=hf.get("expansion_ratio", 4) * d,
        num_hidden_layers=hf["n_layers"],
        num_attention_heads=hf["n_heads"],
        num_key_value_heads=hf["n_heads"],
        rms_norm_eps=1e-5,
        tie_word_embeddings=True,
        norm_type="layernorm",
        mlp_gated=False,
        hidden_act="gelu",
        use_rope=False,
        use_alibi=True,
        max_position_embeddings=hf.get("max_seq_len", 2048),
    )


def _mpt_map(acc: _Acc, name: str, w) -> None:
    d = acc.cfg.hidden_size
    name_ = name[len("transformer."):] if name.startswith("transformer.") \
        else name
    if name_ == "wte.weight":
        acc.top["embed_tokens"] = acc.dense(w)
    elif name_ == "norm_f.weight":
        acc.top["norm"] = acc.dense(w)
    elif name_ == "norm_f.bias":
        acc.top["norm_bias"] = acc.dense(w)
    else:
        hit = _layer_idx(name_, "blocks.")
        if hit is None:
            return
        idx, sub = hit
        if sub == "attn.Wqkv.weight":
            q, k, v = _split_rows(w, [d, d, d])
            # "#<slot>" marks the logical projection inside a fused tensor
            # (drives low_bit_policy and imatrix_lookup fallback)
            acc.put("q_proj", idx, acc.linear(name + "#q_proj", q))
            acc.put("k_proj", idx, acc.linear(name + "#k_proj", k))
            acc.put("v_proj", idx, acc.linear(name + "#v_proj", v))
        else:
            m = {
                "attn.out_proj.weight": ("o_proj", "linear"),
                "ffn.up_proj.weight": ("up_proj", "linear"),
                "ffn.down_proj.weight": ("down_proj", "linear"),
                "norm_1.weight": ("input_layernorm", "dense"),
                "norm_1.bias": ("input_layernorm_bias", "dense"),
                "norm_2.weight": ("post_attention_layernorm", "dense"),
                "norm_2.bias": ("post_attention_layernorm_bias", "dense"),
            }.get(sub)
            if m:
                key, kind = m
                acc.put(key, idx, acc.linear(name, w) if kind == "linear"
                        else acc.dense(w))


# ---------------------------------------------------------------------------
# GPT-J — parallel residual with ONE shared LN, interleaved partial rotary,
# dense gelu MLP with biases (reference transformers/models/gptj.py)
# ---------------------------------------------------------------------------

def _gptj_cfg(hf: Dict[str, Any]) -> LlamaConfig:
    return LlamaConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["n_embd"],
        intermediate_size=hf.get("n_inner") or 4 * hf["n_embd"],
        num_hidden_layers=hf["n_layer"],
        num_attention_heads=hf["n_head"],
        num_key_value_heads=hf["n_head"],
        rms_norm_eps=hf.get("layer_norm_epsilon", 1e-5),
        max_position_embeddings=hf.get("n_positions", 2048),
        tie_word_embeddings=hf.get("tie_word_embeddings", False),
        norm_type="layernorm",
        parallel_residual=True,
        shared_input_norm=True,
        mlp_gated=False,
        hidden_act="gelu_tanh",
        rope_interleaved=True,
        rotary_dim=hf.get("rotary_dim", 64),
        lm_head_bias=True,
    )


def _gptj_map(acc: _Acc, name: str, w) -> None:
    name_ = name[len("transformer."):] if name.startswith("transformer.") \
        else name
    if name_ == "wte.weight":
        acc.top["embed_tokens"] = acc.dense(w)
    elif name_ == "ln_f.weight":
        acc.top["norm"] = acc.dense(w)
    elif name_ == "ln_f.bias":
        acc.top["norm_bias"] = acc.dense(w)
    elif name_ == "lm_head.weight":
        acc.top["lm_head"] = acc.linear(name, w)
    elif name_ == "lm_head.bias":
        acc.top["lm_head_bias"] = acc.dense(w)
    else:
        hit = _layer_idx(name_, "h.")
        if hit is None:
            return
        idx, sub = hit
        m = {
            "attn.q_proj.weight": ("q_proj", "linear"),
            "attn.k_proj.weight": ("k_proj", "linear"),
            "attn.v_proj.weight": ("v_proj", "linear"),
            "attn.out_proj.weight": ("o_proj", "linear"),
            "mlp.fc_in.weight": ("up_proj", "linear"),
            "mlp.fc_in.bias": ("up_proj_bias", "dense"),
            "mlp.fc_out.weight": ("down_proj", "linear"),
            "mlp.fc_out.bias": ("down_proj_bias", "dense"),
            "ln_1.weight": ("input_layernorm", "dense"),
            "ln_1.bias": ("input_layernorm_bias", "dense"),
        }.get(sub)
        if m:
            key, kind = m
            acc.put(key, idx, acc.linear(name, w) if kind == "linear"
                    else acc.dense(w))


# ---------------------------------------------------------------------------
# InternLM2 — grouped fused wqkv, llama-style otherwise
# (reference transformers/models/internlm.py)
# ---------------------------------------------------------------------------

def _internlm2_cfg(hf: Dict[str, Any]) -> LlamaConfig:
    return LlamaConfig.from_hf(hf)


def _internlm2_map(acc: _Acc, name: str, w) -> None:
    cfg = acc.cfg
    h, hkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.hd
    g = h // hkv
    if name == "model.tok_embeddings.weight":
        acc.top["embed_tokens"] = acc.dense(w)
    elif name == "model.norm.weight":
        acc.top["norm"] = acc.dense(w)
    elif name == "output.weight":
        acc.top["lm_head"] = acc.linear(name, w)
    else:
        hit = _layer_idx(name, "model.layers.")
        if hit is None:
            return
        idx, sub = hit
        if sub == "attention.wqkv.weight":
            # grouped layout: per kv head, (g q heads, 1 k, 1 v)
            wg = w.reshape(hkv, g + 2, hd, -1)
            q = wg[:, :g].reshape(h * hd, -1)
            k = wg[:, g].reshape(hkv * hd, -1)
            v = wg[:, g + 1].reshape(hkv * hd, -1)
            # "#<slot>" marks the logical projection inside a fused tensor
            # (drives low_bit_policy and imatrix_lookup fallback)
            acc.put("q_proj", idx, acc.linear(name + "#q_proj", q))
            acc.put("k_proj", idx, acc.linear(name + "#k_proj", k))
            acc.put("v_proj", idx, acc.linear(name + "#v_proj", v))
        else:
            m = {
                "attention.wo.weight": "o_proj",
                "feed_forward.w1.weight": "gate_proj",
                "feed_forward.w3.weight": "up_proj",
                "feed_forward.w2.weight": "down_proj",
                "attention_norm.weight": "input_layernorm",
                "ffn_norm.weight": "post_attention_layernorm",
            }.get(sub)
            if m:
                is_lin = "norm" not in m
                acc.put(m, idx, acc.linear(name, w) if is_lin
                        else acc.dense(w))


# ---------------------------------------------------------------------------
# Qwen (v1, incl. the text decoder of Qwen-VL) — fused c_attn with bias,
# RMSNorm, silu-gated MLP with HALF intermediate width (w1/w2 each
# intermediate_size//2), llama rope
# (reference transformers/models/qwen.py + qwen_vl.py)
# ---------------------------------------------------------------------------

def _qwen1_cfg(hf: Dict[str, Any]) -> LlamaConfig:
    return LlamaConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        # Qwen1 splits config intermediate_size across w1/w2
        intermediate_size=hf["intermediate_size"] // 2,
        num_hidden_layers=hf["num_hidden_layers"],
        num_attention_heads=hf["num_attention_heads"],
        num_key_value_heads=hf["num_attention_heads"],
        head_dim=hf.get("kv_channels"),
        rms_norm_eps=hf.get("layer_norm_epsilon", 1e-6),
        rope_theta=hf.get("rotary_emb_base", 10000.0),
        max_position_embeddings=hf.get("seq_length", 8192),
        tie_word_embeddings=hf.get("tie_word_embeddings", False),
        attention_bias=True,
        hidden_act="silu",
        mlp_gated=True,
    )


def _qwen1_map(acc: _Acc, name: str, w) -> None:
    d = acc.cfg.num_attention_heads * acc.cfg.hd
    name_ = name[len("transformer."):] if name.startswith("transformer.") \
        else name
    if name_ == "wte.weight":
        acc.top["embed_tokens"] = acc.dense(w)
    elif name_ == "ln_f.weight":
        acc.top["norm"] = acc.dense(w)
    elif name_ == "lm_head.weight":
        acc.top["lm_head"] = acc.linear(name, w)
    else:
        hit = _layer_idx(name_, "h.")
        if hit is None:
            return
        idx, sub = hit
        if sub == "attn.c_attn.weight":
            q, k, v = _split_rows(w, [d, d, d])
            acc.put("q_proj", idx, acc.linear(name + "#q_proj", q))
            acc.put("k_proj", idx, acc.linear(name + "#k_proj", k))
            acc.put("v_proj", idx, acc.linear(name + "#v_proj", v))
        elif sub == "attn.c_attn.bias":
            q, k, v = _split_rows(w, [d, d, d])
            acc.put("q_proj_bias", idx, acc.dense(q))
            acc.put("k_proj_bias", idx, acc.dense(k))
            acc.put("v_proj_bias", idx, acc.dense(v))
        else:
            m = {
                "attn.c_proj.weight": "o_proj",
                # Qwen1 MLP: c_proj(silu(w2(x)) * w1(x)) — w2 is the
                # activated branch, i.e. our gate slot
                "mlp.w2.weight": "gate_proj",
                "mlp.w1.weight": "up_proj",
                "mlp.c_proj.weight": "down_proj",
                "ln_1.weight": "input_layernorm",
                "ln_2.weight": "post_attention_layernorm",
            }.get(sub)
            if m:
                is_lin = "norm" not in m
                acc.put(m, idx, acc.linear(name, w) if is_lin
                        else acc.dense(w))


# ---------------------------------------------------------------------------
# StableLM — LN with bias, partial rotary, gated silu MLP
# (reference transformers/models/stablelm.py)
# ---------------------------------------------------------------------------

def _stablelm_cfg(hf: Dict[str, Any]) -> LlamaConfig:
    import dataclasses

    base = LlamaConfig.from_hf(hf)
    hd = base.hd
    return dataclasses.replace(
        base,
        norm_type="layernorm",
        rms_norm_eps=hf.get("layer_norm_eps", 1e-5),
        rotary_dim=int(hf.get("partial_rotary_factor",
                               hf.get("rope_pct", 0.25)) * hd),
        attention_bias=bool(hf.get("use_qkv_bias", False)),
    )


# ---------------------------------------------------------------------------
# GPT-BigCode (starcoder v1) — LEARNED positions, MQA (1 kv head), LN,
# dense gelu MLP, fused c_attn = [q(D) | k(hd) | v(hd)]
# (reference transformers/models/gptbigcode.py — forward_qk fused kernel)
# ---------------------------------------------------------------------------

def _gptbigcode_cfg(hf: Dict[str, Any]) -> LlamaConfig:
    d = hf["n_embd"]
    h = hf["n_head"]
    act_map = {"gelu": "gelu", "relu": "relu"}   # tanh approximants below
    return LlamaConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=d,
        intermediate_size=hf.get("n_inner") or 4 * d,
        num_hidden_layers=hf["n_layer"],
        num_attention_heads=h,
        num_key_value_heads=1 if hf.get("multi_query", True) else h,
        rms_norm_eps=hf.get("layer_norm_epsilon", 1e-5),
        max_position_embeddings=hf.get("n_positions", 8192),
        tie_word_embeddings=hf.get("tie_word_embeddings", True),
        attention_bias=True,
        mlp_bias=True,
        norm_type="layernorm",
        mlp_gated=False,
        hidden_act=act_map.get(
            hf.get("activation_function", "gelu_pytorch_tanh"),
            "gelu_tanh"),
        use_rope=False,
        learned_positions=True,
    )


def _gptbigcode_split_qkv(w, cfg):
    """c_attn rows: MQA = [q(D) | k(hd) | v(hd)] block layout; MHA = the
    gpt2 per-head interleave [q_h | k_h | v_h] x H (HF reshapes to
    (H, 3*hd) and splits per head)."""
    d, hd = cfg.hidden_size, cfg.hd
    if cfg.num_key_value_heads == 1:
        return _split_rows(w, [d, hd, hd])
    return _deinterleave_qkv(w, cfg.num_attention_heads, hd)


def _gptbigcode_map(acc: _Acc, name: str, w) -> None:
    cfg = acc.cfg
    name_ = name[len("transformer."):] if name.startswith("transformer.") \
        else name
    if name_ == "wte.weight":
        acc.top["embed_tokens"] = acc.dense(w)
    elif name_ == "wpe.weight":
        acc.top["embed_positions"] = acc.dense(w)
    elif name_ == "ln_f.weight":
        acc.top["norm"] = acc.dense(w)
    elif name_ == "ln_f.bias":
        acc.top["norm_bias"] = acc.dense(w)
    elif name_ == "lm_head.weight":      # untied checkpoints
        acc.top["lm_head"] = acc.linear(name, w)
    else:
        hit = _layer_idx(name_, "h.")
        if hit is None:
            return
        idx, sub = hit
        if sub == "attn.c_attn.weight":
            q, k, v = _gptbigcode_split_qkv(w, cfg)
            acc.put("q_proj", idx, acc.linear(name + "#q_proj", q))
            acc.put("k_proj", idx, acc.linear(name + "#k_proj", k))
            acc.put("v_proj", idx, acc.linear(name + "#v_proj", v))
        elif sub == "attn.c_attn.bias":
            q, k, v = _gptbigcode_split_qkv(w, cfg)
            acc.put("q_proj_bias", idx, acc.dense(q))
            acc.put("k_proj_bias", idx, acc.dense(k))
            acc.put("v_proj_bias", idx, acc.dense(v))
        else:
            m = {
                "attn.c_proj.weight": ("o_proj", "linear"),
                "attn.c_proj.bias": ("o_proj_bias", "dense"),
                "mlp.c_fc.weight": ("up_proj", "linear"),
                "mlp.c_fc.bias": ("up_proj_bias", "dense"),
                "mlp.c_proj.weight": ("down_proj", "linear"),
                "mlp.c_proj.bias": ("down_proj_bias", "dense"),
                "ln_1.weight": ("input_layernorm", "dense"),
                "ln_1.bias": ("input_layernorm_bias", "dense"),
                "ln_2.weight": ("post_attention_layernorm", "dense"),
                "ln_2.bias": ("post_attention_layernorm_bias", "dense"),
            }.get(sub)
            if m:
                key, kind = m
                acc.put(key, idx, acc.linear(name, w) if kind == "linear"
                        else acc.dense(w))


# ---------------------------------------------------------------------------
# Phixtral — phi-2 body (parallel residual, ONE shared LN, biases, partial
# rotary, gelu) with a mixture of dense fc1/fc2 experts
# (reference transformers/models/phixtral.py:73-138)
# ---------------------------------------------------------------------------

def _phixtral_cfg(hf: Dict[str, Any]) -> LlamaConfig:
    d = hf["n_embd"]
    return LlamaConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=d,
        intermediate_size=hf.get("n_inner") or 4 * d,
        num_hidden_layers=hf["n_layer"],
        num_attention_heads=hf["n_head"],
        num_key_value_heads=hf.get("n_head_kv") or hf["n_head"],
        rms_norm_eps=hf.get("layer_norm_epsilon", 1e-5),
        max_position_embeddings=hf.get("n_positions", 2048),
        tie_word_embeddings=False,
        attention_bias=True,
        norm_type="layernorm",
        parallel_residual=True,
        shared_input_norm=True,
        mlp_gated=False,
        hidden_act="gelu_tanh",
        rotary_dim=hf.get("rotary_dim", 32),
        lm_head_bias=True,
        num_local_experts=hf.get("num_local_experts", 4),
        num_experts_per_tok=hf.get("num_experts_per_tok", 2),
    )


def _phixtral_map(acc: _Acc, name: str, w) -> None:
    d = acc.cfg.hidden_size
    name_ = name[len("transformer."):] if name.startswith("transformer.") \
        else name
    if name_ == "embd.wte.weight":
        acc.top["embed_tokens"] = acc.dense(w)
    elif name_ == "lm_head.ln.weight":
        acc.top["norm"] = acc.dense(w)
    elif name_ == "lm_head.ln.bias":
        acc.top["norm_bias"] = acc.dense(w)
    elif name_ == "lm_head.linear.weight":
        acc.top["lm_head"] = acc.linear(name, w)
    elif name_ == "lm_head.linear.bias":
        acc.top["lm_head_bias"] = acc.dense(w)
    else:
        hit = _layer_idx(name_, "h.")
        if hit is None:
            return
        idx, sub = hit
        if sub == "mixer.Wqkv.weight":
            q, k, v = _split_rows(w, [d, d, d])
            acc.put("q_proj", idx, acc.linear(name + "#q_proj", q))
            acc.put("k_proj", idx, acc.linear(name + "#k_proj", k))
            acc.put("v_proj", idx, acc.linear(name + "#v_proj", v))
        elif sub == "mixer.Wqkv.bias":
            q, k, v = _split_rows(w, [d, d, d])
            acc.put("q_proj_bias", idx, acc.dense(q))
            acc.put("k_proj_bias", idx, acc.dense(k))
            acc.put("v_proj_bias", idx, acc.dense(v))
        elif sub == "mixer.out_proj.weight":
            acc.put("o_proj", idx, acc.linear(name, w))
        elif sub == "mixer.out_proj.bias":
            acc.put("o_proj_bias", idx, acc.dense(w))
        elif sub == "ln.weight":
            acc.put("input_layernorm", idx, acc.dense(w))
        elif sub == "ln.bias":
            acc.put("input_layernorm_bias", idx, acc.dense(w))
        elif sub == "moe.gate.weight":
            # router kept dense [D, E] (the reference also leaves the tiny
            # gate unquantized)
            acc.put("router", idx,
                    jnp.asarray(np.asarray(w)).T.astype(acc.compute_dtype))
        elif sub.startswith("moe.mlp."):
            parts = sub.split(".")
            e, proj, leaf = int(parts[2]), parts[3], parts[4]
            key = {"fc1": "experts_up", "fc2": "experts_down"}[proj]
            if leaf == "weight":
                acc.put(f"{key}__{e}", idx, acc.linear(name, w))
            else:
                acc.put(f"{key}_bias__{e}", idx, acc.dense(w))


def _phixtral_convert(tensors, cfg, qtype="sym_int4",
                      compute_dtype=jnp.bfloat16,
                      modules_to_not_convert=(), imatrix=None):
    """Per-expert keys are accumulated flat, then re-stacked to the
    [L, E, ...] expert layout _moe_mlp vmaps over."""
    params = _make_convert(_phixtral_map)(
        tensors, cfg, qtype=qtype, compute_dtype=compute_dtype,
        modules_to_not_convert=modules_to_not_convert, imatrix=imatrix)
    layers = params["layers"]
    E = cfg.num_local_experts
    for base in ("experts_up", "experts_down",
                 "experts_up_bias", "experts_down_bias"):
        parts = [layers.pop(f"{base}__{e}") for e in range(E)]
        layers[base] = jax.tree.map(
            lambda *xs: jnp.stack(xs, axis=1), *parts)
    return params


# ---------------------------------------------------------------------------
# Registration
# ---------------------------------------------------------------------------

def _adapter(name: str, cfg_fn, map_fn):
    from bigdl_tpu.models.registry import FamilyAdapter

    return FamilyAdapter(
        name=name,
        config_from_hf=cfg_fn,
        convert_params=_make_convert(map_fn),
        forward=llama_mod.forward,
        prefill=llama_mod.forward_last_token,
        forward_train=llama_mod.forward_train,
        new_cache=llama_mod.new_cache,
    )


def register_all() -> None:
    from bigdl_tpu.models.llama import convert_hf_params as llama_convert
    from bigdl_tpu.models.registry import FamilyAdapter, register_family

    register_family(["GemmaForCausalLM"], FamilyAdapter(
        name="gemma",
        config_from_hf=_gemma_cfg,
        convert_params=llama_convert,     # same tensor names as llama
        forward=llama_mod.forward,
        prefill=llama_mod.forward_last_token,
        forward_train=llama_mod.forward_train,
        new_cache=llama_mod.new_cache,
    ))
    register_family(["Gemma2ForCausalLM"], FamilyAdapter(
        name="gemma2",
        config_from_hf=_gemma2_cfg,
        convert_params=llama_convert,
        forward=llama_mod.forward,
        prefill=llama_mod.forward_last_token,
        forward_train=llama_mod.forward_train,
        new_cache=llama_mod.new_cache,
    ))
    register_family(["PhiForCausalLM"], _adapter("phi", _phi_cfg, _phi_map))
    register_family(["GPTNeoXForCausalLM"],
                    _adapter("gptneox", _gptneox_cfg, _gptneox_map))
    register_family(["BloomForCausalLM", "BloomModel"],
                    _adapter("bloom", _bloom_cfg, _bloom_map))
    register_family(["FalconForCausalLM", "RWForCausalLM"],
                    _adapter("falcon", _falcon_cfg, _falcon_map))
    register_family(["Starcoder2ForCausalLM"],
                    _adapter("starcoder2", _starcoder2_cfg, _starcoder2_map))
    register_family(["BaichuanForCausalLM", "BaiChuanForCausalLM"],
                    _adapter("baichuan", _baichuan_cfg, _baichuan_map))
    # chatglm arch names are shared across structurally different
    # versions: v1 (2D rope, prefix-bidirectional, deepnorm — its own
    # module) vs v2/3 (llama-shaped config delta). Dispatch on config.
    _chatglm2_adapter = _adapter("chatglm", _chatglm2_cfg, _chatglm2_map)

    def _chatglm_dispatch(hf):
        from bigdl_tpu.models import chatglm as glm1

        if hf is not None and glm1.is_v1_config(hf):
            return FamilyAdapter(
                name="chatglm1",
                config_from_hf=glm1.config_from_hf,
                convert_params=glm1.convert_hf_params,
                forward=glm1.forward,
                prefill=glm1.forward_last_token,
                forward_train=glm1.forward_train,
                new_cache=glm1.new_cache,
            )
        return _chatglm2_adapter

    register_family(["ChatGLMModel", "ChatGLMForConditionalGeneration"],
                    _chatglm_dispatch)
    # HF transformers writes "MptForCausalLM"; community ckpts "MPT..."
    register_family(["MPTForCausalLM", "MptForCausalLM"],
                    _adapter("mpt", _mpt_cfg, _mpt_map))
    register_family(["GPTJForCausalLM"],
                    _adapter("gptj", _gptj_cfg, _gptj_map))
    register_family(["InternLM2ForCausalLM"],
                    _adapter("internlm2", _internlm2_cfg, _internlm2_map))
    # Qwen v1; QWenLMHeadModel is also the text decoder of Qwen-VL
    # (the reference routes qwen_vl's LLM through the same qwen forwards,
    # transformers/models/qwen_vl.py — the ViT tower stays unquantized)
    register_family(["QWenLMHeadModel"],
                    _adapter("qwen", _qwen1_cfg, _qwen1_map))
    register_family(["GPTBigCodeForCausalLM"],
                    _adapter("gptbigcode", _gptbigcode_cfg,
                             _gptbigcode_map))
    register_family(["PhixtralForCausalLM"], FamilyAdapter(
        name="phixtral",
        config_from_hf=_phixtral_cfg,
        convert_params=_phixtral_convert,
        forward=llama_mod.forward,
        prefill=llama_mod.forward_last_token,
        forward_train=llama_mod.forward_train,
        new_cache=llama_mod.new_cache,
    ))
    register_family(["StableLmForCausalLM", "StableLMEpochForCausalLM"],
                    FamilyAdapter(
                        name="stablelm",
                        config_from_hf=_stablelm_cfg,
                        convert_params=llama_convert,
                        forward=llama_mod.forward,
                        prefill=llama_mod.forward_last_token,
                        forward_train=llama_mod.forward_train,
                        new_cache=llama_mod.new_cache,
                    ))
